"""Multiscale deformable-attention sampling — gather-free Pallas MXU kernel,
XLA row-gather path, and an experimental Pallas lane-gather kernel.

This is the one custom op of the RT-DETR family (the torch lineage ships a
CUDA kernel for it; HF's port falls back to `grid_sample` per level —
modeling_rt_detr_v2's multi_scale_deformable_attention_v2). On TPU the op
dominates the whole model when expressed as gathers — measured on v5e,
R101 batch 8: the six decoder layers' sampling costs ~69 of the 78 ms
forward, and scales super-linearly with batch (11.5 -> 73 ms per layer from
batch 8 to 16) because XLA's gather lowering falls off a vectorized path.
Every gather formulation (2 batch dims, flattened batch, global-row take,
folded corners) hits the same wall.

The production Pallas kernel ("pallas", auto-selected on TPU) therefore
eliminates the gather entirely — TPU-first thinking: turn irregular memory
access into regular compute on the MXU/VPU:

    out(q, hd) = OneHot(q, s) @ V(s, hd)

where OneHot folds ALL of a query's sample weights — L*P points x 4
bilinear corners x attention weight x in-bounds validity — into one row:
OneHot[q, s] = sum_{point, corner} w[point, corner, q] * (idx[point,
corner, q] == s). The kernel builds OneHot *tiles* in VMEM from iota
comparisons (pure VPU, no scatter/gather) and contracts them against value
tiles on the MXU, accumulating over source tiles via output revisiting.
The full one-hot matrix never exists: a (Q, S_TILE) tile lives per grid
step. The comparisons are the cost: 48*Q*S per (batch, head) on the VPU —
regular, vectorizable work instead of 48*Q irregular row fetches.

Two more backends:
- "xla": row gathers along S of (S, head_dim) value rows — the fastest
  *gather-based* XLA formulation (minor-axis gathers are ~40x worse:
  2650 ms/call measured). CPU/GPU default, and the VJP reference.
- "pallas_gather": fused lane-dimension `take_along_axis` kernel. Blocked
  today by Mosaic's single-vreg gather limit ("Not implemented: Multiple
  source vregs along gather dimension" for S > 128); kept for when Mosaic
  grows multi-vreg gathers, correct under interpret mode and on
  single-vreg sources (pinned by tests/test_msda.py).

Differentiation: both Pallas kernels carry a custom VJP whose backward
recomputes through the pure-jnp XLA reference — exactly differentiable, so
the train step works with kernels enabled.

Two sparsity layers cut the compare cost:

- Level-split: the kernel runs once per feature level — a sample only ever
  lands inside its own level's span of the flat source, so comparing it
  against other levels' positions is pure waste (the stride-8 level holds
  ~76% of positions but only 1/3 of samples; ~3x fewer compares).
- Block-sparse: queries are sorted by quantized mean sample location
  (y-major, matching the row-major source so source tiles are horizontal
  bands), and a per-(query-tile, source-tile) hit table — scalar-prefetched
  into SMEM — lets the kernel skip pairs no sample touches. Sampling
  offsets cluster around each query's reference box, so sorted neighbors
  touch few bands. The sort/unsort are two tiny Q-row permutes in XLA; the
  mask provably never suppresses a hit (built from idx where w > 0).

Measured on v5e (R101, 640x640, clean chip, full model forward, batch
8 / 16): XLA row-gathers 77.7 / 500.6 ms (the gather lowering collapses
above batch*heads ~96); dense one-hot 109.9 / 228.9; level-split 71.2 /
145.2; level-split + block-sparse (production) 63.2 / 137.9 — every
formulation parity-tested against the gather reference.

Backend policy: `SPOTTER_TPU_MSDA` = auto (pallas on TPU, xla elsewhere) |
xla | pallas | pallas_gather.
"""

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MSDA_ENV = "SPOTTER_TPU_MSDA"
LANE = 128


def msda_backend(override: str | None = None, batch_heads: int | None = None) -> str:
    """`batch_heads` is accepted for callers that want to specialize the
    policy by problem size; with the level-split kernel the measured answer
    is uniform, so it is currently unused."""
    del batch_heads
    name = (override or os.environ.get(MSDA_ENV, "auto")).strip().lower()
    if name not in ("auto", "xla", "pallas", "pallas_gather"):
        raise ValueError(
            f"{MSDA_ENV} must be auto|xla|pallas|pallas_gather, got {name!r}"
        )
    if name == "auto":
        # TPU: the level-split one-hot kernel wins at every measured size
        # (R101 full model, v5e: batch 8 71.2 ms vs 77.7 XLA; batch 16
        # 145.2 ms vs 500.6 — XLA's gather lowering collapses above
        # batch*heads ~96). CPU/GPU: always XLA (interpret-mode pallas
        # would be pointlessly slow there).
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return name


def _level_offsets(spatial_shapes: tuple[tuple[int, int], ...]) -> np.ndarray:
    sizes = [h * w for h, w in spatial_shapes]
    return np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(np.int32)


def prepare_msda_gather(
    loc: jnp.ndarray,  # (B, H, LP, Q, 2) normalized [0,1] sample points
    attn: jnp.ndarray,  # (B, H, LP, Q) softmaxed attention weights
    spatial_shapes: tuple[tuple[int, int], ...],
    num_points: int,
    method: str = "default",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Corner indices + folded weights for the gather kernel.

    Returns idx (B, H, 4, LP*Q) int32 into the padded flat space and
    w (B, H, 4, LP*Q) fp32. For method="discrete" only corner 0 is active
    (nearest-neighbor, border-clamped — RT-DETRv2 discrete sampling
    semantics); for "default" the four bilinear corners carry
    align_corners=False, zeros-padding semantics.
    """
    b, h_axis, lp, q, _ = loc.shape
    levels = len(spatial_shapes)
    offs = _level_offsets(spatial_shapes)
    # per-sample level id: sample axis is level-major (L blocks of P points)
    lvl_h = np.repeat([hh for hh, _ in spatial_shapes], num_points).astype(np.float32)
    lvl_w = np.repeat([ww for _, ww in spatial_shapes], num_points).astype(np.float32)
    lvl_off = np.repeat(offs, num_points).astype(np.int32)
    assert lvl_h.shape[0] == lp, (lp, levels, num_points)
    shp = (1, 1, lp, 1)
    lvl_h = lvl_h.reshape(shp)
    lvl_w = lvl_w.reshape(shp)
    lvl_off = lvl_off.reshape(shp)

    gx = loc[..., 0] * lvl_w  # pixel coords, align_corners=False
    gy = loc[..., 1] * lvl_h
    attn = attn.astype(jnp.float32)

    if method == "discrete":
        cx = jnp.clip(jnp.floor(gx + 0.5).astype(jnp.int32), 0, lvl_w.astype(np.int32) - 1)
        cy = jnp.clip(jnp.floor(gy + 0.5).astype(jnp.int32), 0, lvl_h.astype(np.int32) - 1)
        idx0 = lvl_off + cy * lvl_w.astype(np.int32) + cx
        zeros_i = jnp.zeros_like(idx0)
        zeros_w = jnp.zeros_like(attn)
        idx = jnp.stack([idx0, zeros_i, zeros_i, zeros_i], axis=2)
        w = jnp.stack([attn, zeros_w, zeros_w, zeros_w], axis=2)
    else:
        gx = gx - 0.5
        gy = gy - 0.5
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        fx = (gx - x0).astype(jnp.float32)
        fy = (gy - y0).astype(jnp.float32)

        wi = lvl_w.astype(np.int32)
        hi = lvl_h.astype(np.int32)

        def corner(xc, yc, cw):
            valid = (xc >= 0) & (xc <= wi - 1) & (yc >= 0) & (yc <= hi - 1)
            xcc = jnp.clip(xc, 0, wi - 1).astype(jnp.int32)
            ycc = jnp.clip(yc, 0, hi - 1).astype(jnp.int32)
            return lvl_off + ycc * wi + xcc, cw * valid.astype(jnp.float32) * attn

        i00, w00 = corner(x0, y0, (1 - fx) * (1 - fy))
        i01, w01 = corner(x0 + 1, y0, fx * (1 - fy))
        i10, w10 = corner(x0, y0 + 1, (1 - fx) * fy)
        i11, w11 = corner(x0 + 1, y0 + 1, fx * fy)
        idx = jnp.stack([i00, i01, i10, i11], axis=2)
        w = jnp.stack([w00, w01, w10, w11], axis=2)

    # (B, H, 4, LP, Q) -> (B, H, 4, LP*Q): sample-major flat layout so the
    # kernel's group-sum is LP contiguous static slices of Q lanes.
    idx = idx.reshape(b, h_axis, 4, lp * q)
    w = w.reshape(b, h_axis, 4, lp * q)
    return idx, w


def _gather_weighted_sum(vt, idx, w, lp: int, q: int):
    """Reference math shared by the XLA path and the kernel's VJP.

    vt: (B, H, hd, S); idx/w: (B, H, 4, LP*Q). Returns (B, H, hd, Q).

    Gather-axis choice is the whole performance story here, and it differs
    per backend: XLA lowers *row* gathers (major axis, contiguous minor dim)
    to fast vector loads but per-element minor-axis gathers to a ~40x-slower
    generic path, while Mosaic's DynamicGather vectorizes only along lanes
    (the minor axis). So this XLA-side reference works row-major — value
    rows (S, hd) gathered along S — on the transpose of the kernel's
    (hd, S) lane layout.
    """
    rows = vt.transpose(0, 1, 3, 2)  # (B, H, S, hd): gather rows along S
    return _row_gather_weighted_sum(rows, idx, w, lp, q).transpose(0, 1, 3, 2)


def _row_gather_weighted_sum(rows, idx, w, lp: int, q: int):
    """Row-major core: rows (B, H, S, hd), idx/w (B, H, 4, LP*Q) ->
    (B, H, Q, hd)."""
    hd = rows.shape[-1]
    acc = None
    for c in range(4):  # corner loop: never broadcast the value maps 4x
        g = jnp.take_along_axis(rows, idx[:, :, c, :, None], axis=2)
        term = g * w[:, :, c, :, None].astype(rows.dtype)  # (B, H, N, hd)
        acc = term if acc is None else acc + term
    return acc.reshape(*acc.shape[:2], lp, q, hd).sum(axis=2)


def xla_deformable_sampling(vt, idx, w, lp: int, q: int):
    """Pure-XLA fallback with identical semantics to the Pallas kernel."""
    return _gather_weighted_sum(vt, idx, w, lp, q)


def _msda_kernel(vt_ref, idx_ref, w_ref, out_ref, *, lp: int, q: int):
    # vt, idx, w all share the lane extent G = max(S, LP*Q) rounded up to a
    # lane multiple: Mosaic's vectorized gather requires indices broadcast
    # to exactly the input shape (dynamic_gather is an elementwise lookup).
    vt = vt_ref[0, 0]  # (hd, G)
    hd, g_lanes = vt.shape
    acc = jnp.zeros((hd, g_lanes), vt.dtype)
    for c in range(4):
        ids = jnp.broadcast_to(idx_ref[0, 0, c][None, :], (hd, g_lanes))
        g = jnp.take_along_axis(vt, ids, axis=1)
        acc = acc + g * w_ref[0, 0, c][None, :].astype(vt.dtype)
    out = jnp.zeros((hd, q), vt.dtype)
    for j in range(lp):  # static contiguous slices: sample-major layout
        out = out + acc[:, j * q : (j + 1) * q]
    out_ref[0, 0] = out


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_deformable_sampling(vt, idx, w, lp: int, q: int, interpret: bool = False):
    """Fused gather + weighted group-sum on TPU.

    vt: (B, H, hd, S) value maps (S padded to a lane multiple);
    idx/w: (B, H, 4, LP*Q) from `prepare_msda_gather`. Returns (B, H, hd, Q).
    """
    b, h_axis, hd, s = vt.shape
    n = idx.shape[-1]
    # Common lane extent: Mosaic's gather needs source and (broadcast)
    # indices to share a shape. Pad source and samples to G lanes; padded
    # sample slots carry idx 0 / weight 0 and never enter the group-sum.
    g_lanes = max(-(-s // LANE) * LANE, -(-n // LANE) * LANE)
    if g_lanes != s:
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, 0), (0, g_lanes - s)))
    if g_lanes != n:
        idx = jnp.pad(idx, ((0, 0), (0, 0), (0, 0), (0, g_lanes - n)))
        w = jnp.pad(w, ((0, 0), (0, 0), (0, 0), (0, g_lanes - n)))
    kernel = partial(_msda_kernel, lp=lp, q=q)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h_axis, hd, q), vt.dtype),
        grid=(b, h_axis),
        in_specs=[
            pl.BlockSpec(
                (1, 1, hd, g_lanes), lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, 4, g_lanes), lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, 4, g_lanes), lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, hd, q), lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(vt, idx, w)


def _msda_fwd(vt, idx, w, lp, q, interpret):
    return pallas_deformable_sampling(vt, idx, w, lp, q, interpret), (vt, idx, w)


def _msda_bwd(lp, q, interpret, res, g):
    # Backward through the pure-jnp reference: exactly the same math, so the
    # kernel stays a drop-in under jax.grad (train step with pallas on).
    vt, idx, w = res
    _, vjp = jax.vjp(lambda v, ww: _gather_weighted_sum(v, idx, ww, lp, q), vt, w)
    dvt, dw = vjp(g)
    return dvt, None, dw


pallas_deformable_sampling.defvjp(_msda_fwd, _msda_bwd)


# --- gather-free one-hot MXU kernel (the production TPU backend) ---

# Five 128-lane vregs per one-hot tile column block. Swept on v5e (R101
# batch 8, mixed policy): S_TILE 256/384/512/640/768 -> 64.0/58.5/54.4/
# 52.1/54.9 ms end-to-end. 640 wins on tile-count alignment: the stride-8
# level's 80x80=6400 positions split into exactly 10 tiles (512 pads 12.5
# ->13) while staying small enough that the hit table still prunes.
# Q_TILE 128 and finer S tiles both lose (more revisits / more grid steps).
S_TILE = 640


def _onehot_ref_math(rows, idx, w):
    """jnp reference for the one-hot kernel (VJP + interpret parity).

    rows: (BH, S, hd); idx/w: (BH, Qp, JC). Returns (BH, Qp, hd) fp32 —
    the kernel accumulates and emits fp32 regardless of the rows dtype.
    """
    bh, qp, jc = idx.shape
    hd = rows.shape[-1]
    flat = idx.reshape(bh, qp * jc, 1)
    g = jnp.take_along_axis(rows, flat, axis=1).reshape(bh, qp, jc, hd)
    return (g.astype(jnp.float32) * w[..., None].astype(jnp.float32)).sum(axis=2)


# --- block-sparse kernel: skip (query-tile, source-tile) pairs no sample
# hits. Queries are pre-sorted by spatial locality (dispatcher), so a tile
# of neighboring queries samples a narrow band of each level's source and
# most pairs are misses — the compare cost drops by the miss rate.

Q_TILE = 64


def _mxu_precision() -> jax.lax.Precision:
    """MXU pass count for the one-hot contraction (SPOTTER_TPU_MSDA_PRECISION).

    "highest" (default): 6-pass fp32 — bit-faithful to the gather reference
    (kernel parity tests pin this). "default": single bf16 pass — the one-hot
    weights are bilinear coefficients in [0,1] and values are activations, so
    bf16 rounding costs ~1e-3 relative on sampled values; opt in when that
    drift is acceptable for the deployment.

    Read ONCE at import (module constant below) like the other env knobs:
    the value is baked into jit-compiled programs and is not part of any jit
    cache key, so changing the env after first trace could never take effect.
    """
    name = os.environ.get("SPOTTER_TPU_MSDA_PRECISION", "highest").strip().lower()
    table = {
        "highest": jax.lax.Precision.HIGHEST,
        "default": jax.lax.Precision.DEFAULT,
    }
    if name not in table:
        raise ValueError(
            f"Unsupported SPOTTER_TPU_MSDA_PRECISION={name!r}; "
            f"expected one of {sorted(table)}"
        )
    return table[name]


# process-start-only knob (see _mxu_precision docstring)
MSDA_MXU_PRECISION = _mxu_precision()


def _onehot_sparse_kernel(
    mask_ref, idx_ref, w_ref, v_ref, out_ref, *, s_tile: int, precision
):
    # mask_ref is the scalar-prefetch (SMEM) hit table, indexed by grid ids
    qt, jc = idx_ref.shape[1], idx_ref.shape[2]
    i, nq, ns = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(ns == 0)
    def _():
        out_ref[0] = jnp.zeros_like(out_ref[0])

    @pl.when(mask_ref[i, nq, ns] != 0)
    def _():
        s_off = ns * s_tile
        col = jax.lax.broadcasted_iota(jnp.int32, (qt, s_tile), 1) + s_off
        oh = jnp.zeros((qt, s_tile), jnp.float32)
        idx = idx_ref[0]
        w = w_ref[0]
        for j in range(jc):
            oh = oh + jnp.where(
                col == idx[:, j : j + 1], w[:, j : j + 1].astype(jnp.float32), 0.0
            )
        acc = jnp.dot(
            oh,
            v_ref[0].astype(jnp.float32),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        out_ref[0] = out_ref[0] + acc.astype(out_ref.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def pallas_onehot_sampling_sparse(rows, idx, w, mask, interpret: bool = False):
    """Block-sparse one-hot sampling.

    rows: (BH, S_pad, hd); idx/w: (BH, Qp, JC) with Qp a multiple of
    Q_TILE; mask: (BH, Qp // Q_TILE, S_pad // S_TILE) int32 — nonzero where
    any sample of the query tile lands in the source tile (must never
    suppress a real hit; the dispatcher derives it from idx where w > 0).
    Returns (BH, Qp, hd) fp32.
    """
    bh, s_pad, hd = rows.shape
    _, qp, jc = idx.shape
    n_s = s_pad // S_TILE
    n_qt = qp // Q_TILE
    # env parsed here (dispatch), not in the kernel body: typos fail fast
    # with a readable error instead of mid-trace, and the environment isn't
    # re-read per kernel trace
    kernel = partial(_onehot_sparse_kernel, s_tile=S_TILE, precision=MSDA_MXU_PRECISION)
    # upper bound: the mask is runtime data, so masked-off tiles can't be
    # subtracted statically; the true cost is this times the hit fraction
    flops = 2 * bh * n_s * (qp * S_TILE * hd + jc * qp * S_TILE)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # the hit table rides in SMEM
        grid=(bh, n_qt, n_s),
        in_specs=[
            pl.BlockSpec(
                (1, Q_TILE, jc), lambda i, nq, s, *_: (i, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, Q_TILE, jc), lambda i, nq, s, *_: (i, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, S_TILE, hd), lambda i, nq, s, *_: (i, s, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, Q_TILE, hd), lambda i, nq, s, *_: (i, nq, 0),
            memory_space=pltpu.VMEM,
        ),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, qp, hd), jnp.float32),
        grid_spec=grid_spec,
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=rows.size * 4 + 2 * idx.size * 4 + mask.size * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(mask, idx, w, rows)


def _onehot_sparse_fwd(rows, idx, w, mask, interpret):
    return (
        pallas_onehot_sampling_sparse(rows, idx, w, mask, interpret),
        (rows, idx, w),
    )


def _onehot_sparse_bwd(interpret, res, g):
    # the mask never suppresses a real hit, so the dense reference computes
    # the identical primal — its VJP is exact for the sparse kernel too
    rows, idx, w = res
    _, vjp = jax.vjp(lambda r, ww: _onehot_ref_math(r, idx, ww), rows, w)
    d_rows, d_w = vjp(g)
    return d_rows, None, d_w, None


pallas_onehot_sampling_sparse.defvjp(_onehot_sparse_fwd, _onehot_sparse_bwd)


def deformable_sampling(
    value: jnp.ndarray,  # (B, S, H, hd)
    loc: jnp.ndarray,  # (B, Q, H, LP, 2) in [0, 1]
    attn: jnp.ndarray,  # (B, Q, H, LP)
    spatial_shapes: tuple[tuple[int, int], ...],
    num_points: int,
    method: str = "default",
    backend: str | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Full MSDA core: returns (B, Q, H*hd) aggregated values.

    Backends (module docstring): "pallas" = gather-free one-hot MXU kernel
    (auto on TPU), "xla" = row-gather math (auto elsewhere, VJP reference),
    "pallas_gather" = experimental lane-gather kernel. `interpret=True`
    forces kernel interpret mode (CPU tests).
    """
    b, s, h_axis, hd = value.shape
    q = loc.shape[1]
    lp = loc.shape[3]

    # (B, Q, H, LP, ...) -> (B, H, LP, Q, ...): head-major for per-(b,h) cells
    loc_t = loc.transpose(0, 2, 3, 1, 4)
    attn_t = attn.transpose(0, 2, 3, 1)
    idx, w = prepare_msda_gather(loc_t, attn_t, spatial_shapes, num_points, method)

    chosen = msda_backend(backend, batch_heads=b * h_axis)
    interp = bool(interpret) if interpret is not None else False
    if chosen == "pallas":
        # Level-split: a sample only ever lands inside its own level's span
        # of the flat source (block-diagonal one-hot), so each per-level
        # kernel call compares its 4*P sample columns against that level's
        # positions only — a ~3x compare reduction vs one dense call (the
        # stride-8 level holds ~76% of positions but only 1/3 of samples).
        # Block-sparsity on top: queries sorted by spatial locality so a
        # Q_TILE of neighbors samples a narrow band of each level, and the
        # kernel skips (query-tile, source-tile) pairs with no hit.
        jc = 4 * lp
        qp = -(-q // Q_TILE) * Q_TILE

        # locality sort key: quantized mean sample position, y-major (the
        # flat source is row-major, so source tiles are horizontal bands)
        mean_xy = loc.mean(axis=(2, 3))  # (B, Q, 2) in [0, 1]
        key = (
            jnp.clip((mean_xy[..., 1] * 64).astype(jnp.int32), 0, 63) * 64
            + jnp.clip((mean_xy[..., 0] * 64).astype(jnp.int32), 0, 63)
        )
        perm = jnp.argsort(key, axis=1)  # (B, Q)
        inv_perm = jnp.argsort(perm, axis=1)

        idx_q = idx.reshape(b, h_axis, 4, lp, q).transpose(0, 1, 4, 2, 3)
        w_q = w.reshape(b, h_axis, 4, lp, q).transpose(0, 1, 4, 2, 3)
        psel = perm[:, None, :, None, None]
        idx_q = jnp.take_along_axis(idx_q, psel, axis=2).reshape(
            b * h_axis, q, jc
        )
        w_q = jnp.take_along_axis(w_q, psel, axis=2).reshape(b * h_axis, q, jc)
        if qp != q:  # padded queries: idx 0, weight 0 -> zero rows, no hits
            idx_q = jnp.pad(idx_q, ((0, 0), (0, qp - q), (0, 0)))
            w_q = jnp.pad(w_q, ((0, 0), (0, qp - q), (0, 0)))

        rows_all = value.transpose(0, 2, 1, 3).reshape(b * h_axis, s, hd)
        offs = _level_offsets(spatial_shapes)
        points = lp // len(spatial_shapes)
        n_qt = qp // Q_TILE
        out = None
        for lvl, (lh, lw) in enumerate(spatial_shapes):
            s_l = lh * lw
            rows_l = rows_all[:, offs[lvl] : offs[lvl] + s_l]
            s_pad = -(-s_l // S_TILE) * S_TILE
            if s_pad != s_l:
                rows_l = jnp.pad(rows_l, ((0, 0), (0, s_pad - s_l), (0, 0)))
            cols = [
                c * lp + lvl * points + p for c in range(4) for p in range(points)
            ]
            # level-local indices; padded/invalid slots (global idx 0, w 0)
            # may go negative here — they simply never match a column
            idx_l = idx_q[:, :, cols] - np.int32(offs[lvl])
            w_l = w_q[:, :, cols]
            # hit mask: which source tiles does each query tile touch?
            n_s = s_pad // S_TILE
            tile_of = jnp.where(w_l > 0, idx_l // S_TILE, -1)  # (BH, Qp, JCl)
            hits = tile_of[..., None] == jnp.arange(n_s, dtype=jnp.int32)
            mask = (
                hits.reshape(b * h_axis, n_qt, Q_TILE, len(cols), n_s)
                .any(axis=(2, 3))
                .astype(jnp.int32)
            )
            part = pallas_onehot_sampling_sparse(rows_l, idx_l, w_l, mask, interp)
            out = part if out is None else out + part
        out = out[:, :q].reshape(b, h_axis, q, hd)
        out = jnp.take_along_axis(out, inv_perm[:, None, :, None], axis=2)
        return out.transpose(0, 2, 1, 3).reshape(b, q, h_axis * hd)
    if chosen == "pallas_gather":
        vt = value.transpose(0, 2, 3, 1)  # (B, H, hd, S): spatial on lanes
        out = pallas_deformable_sampling(vt, idx, w, lp, q, interp)
        # (B, H, hd, Q) -> (B, Q, H*hd)
        return out.transpose(0, 3, 1, 2).reshape(b, q, h_axis * hd)
    rows = value.transpose(0, 2, 1, 3)  # (B, H, S, hd): row gathers for XLA
    out = _row_gather_weighted_sum(rows, idx, w, lp, q)  # (B, H, Q, hd)
    return out.transpose(0, 2, 1, 3).reshape(b, q, h_axis * hd)
