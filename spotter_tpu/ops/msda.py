"""Multiscale deformable-attention sampling — gather-free Pallas MXU kernel,
XLA row-gather path, and an experimental Pallas lane-gather kernel.

This is the one custom op of the RT-DETR family (the torch lineage ships a
CUDA kernel for it; HF's port falls back to `grid_sample` per level —
modeling_rt_detr_v2's multi_scale_deformable_attention_v2). On TPU the op
dominates the whole model when expressed as gathers — measured on v5e,
R101 batch 8: the six decoder layers' sampling costs ~69 of the 78 ms
forward, and scales super-linearly with batch (11.5 -> 73 ms per layer from
batch 8 to 16) because XLA's gather lowering falls off a vectorized path.
Every gather formulation (2 batch dims, flattened batch, global-row take,
folded corners) hits the same wall.

The production Pallas kernel ("pallas", auto-selected on TPU) therefore
eliminates the gather entirely — TPU-first thinking: turn irregular memory
access into regular compute on the MXU/VPU:

    out(q, hd) = OneHot(q, s) @ V(s, hd)

where OneHot folds ALL of a query's sample weights — L*P points x 4
bilinear corners x attention weight x in-bounds validity — into one row:
OneHot[q, s] = sum_{point, corner} w[point, corner, q] * (idx[point,
corner, q] == s). The kernel builds OneHot *tiles* in VMEM from iota
comparisons (pure VPU, no scatter/gather) and contracts them against value
tiles on the MXU, accumulating over source tiles via output revisiting.
The full one-hot matrix never exists: a (Q, S_TILE) tile lives per grid
step. The comparisons are the cost: 48*Q*S per (batch, head) on the VPU —
regular, vectorizable work instead of 48*Q irregular row fetches.

Two more backends:
- "xla": row gathers along S of (S, head_dim) value rows — the fastest
  *gather-based* XLA formulation (minor-axis gathers are ~40x worse:
  2650 ms/call measured). CPU/GPU default, and the VJP reference.
- "pallas_gather": fused lane-dimension `take_along_axis` kernel. Blocked
  today by Mosaic's single-vreg gather limit ("Not implemented: Multiple
  source vregs along gather dimension" for S > 128); kept for when Mosaic
  grows multi-vreg gathers, correct under interpret mode and on
  single-vreg sources (pinned by tests/test_msda.py).

Differentiation: both Pallas kernels carry a custom VJP whose backward
recomputes through the pure-jnp XLA reference — exactly differentiable, so
the train step works with kernels enabled.

Two sparsity layers cut the compare cost:

- Level-split: the kernel runs once per feature level — a sample only ever
  lands inside its own level's span of the flat source, so comparing it
  against other levels' positions is pure waste (the stride-8 level holds
  ~76% of positions but only 1/3 of samples; ~3x fewer compares).
- Block-sparse: queries are sorted by quantized mean sample location
  (y-major, matching the row-major source so source tiles are horizontal
  bands), and a per-(query-tile, source-tile) hit table — scalar-prefetched
  into SMEM — lets the kernel skip pairs no sample touches. Sampling
  offsets cluster around each query's reference box, so sorted neighbors
  touch few bands. The sort/unsort are two tiny Q-row permutes in XLA; the
  mask provably never suppresses a hit (built from idx where w > 0).

Measured on v5e (R101, 640x640, clean chip, full model forward, batch
8 / 16): XLA row-gathers 77.7 / 500.6 ms (the gather lowering collapses
above batch*heads ~96); dense one-hot 109.9 / 228.9; level-split 71.2 /
145.2; level-split + block-sparse (production) 63.2 / 137.9 — every
formulation parity-tested against the gather reference.

Backend policy: `SPOTTER_TPU_MSDA` = auto (pallas on TPU, xla elsewhere) |
xla | pallas | pallas_sep | pallas_gather.
"""

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MSDA_ENV = "SPOTTER_TPU_MSDA"
LANE = 128


def locality_sort_key(xy: jnp.ndarray) -> jnp.ndarray:
    """(…, 2) normalized xy -> (…,) int32 quantized y-major sort key.

    Shared by the in-op locality sort below and model-level presorting
    (models/rtdetr.py): y-major matches the row-major source layout, so
    neighboring sorted queries sample the same horizontal bands and the
    kernels' block-sparse hit tables prune."""
    return (
        jnp.clip((xy[..., 1] * 64).astype(jnp.int32), 0, 63) * 64
        + jnp.clip((xy[..., 0] * 64).astype(jnp.int32), 0, 63)
    )


def locality_presort(xy: jnp.ndarray):
    """(B, Q, 2) normalized centers -> (sort, unsort) callables that
    permute / un-permute (B, Q, ...) tensors along axis 1 by
    `locality_sort_key` order. The single implementation of the model-level
    presort contract (rtdetr.py / deformable_detr.py decoders): both
    decoders and the kernels' tiling assumption stay in lockstep by
    construction."""
    perm = jnp.argsort(locality_sort_key(xy), axis=1)
    inv_perm = jnp.argsort(perm, axis=1)

    def sort(a: jnp.ndarray) -> jnp.ndarray:
        return jnp.take_along_axis(a, perm[:, :, None], axis=1)

    def unsort(a: jnp.ndarray) -> jnp.ndarray:
        return jnp.take_along_axis(a, inv_perm[:, :, None], axis=1)

    return sort, unsort


def encoder_presorted() -> bool:
    """Whether MSDA *encoder* self-attention may claim its queries are
    already locality-ordered. Encoder tokens arrive level-major row-major —
    exactly the y-major band order the hit tables want — so the in-op
    argsort + two q-row permutes over the full token set (10k+ at 800x1333)
    are pure waste and default off. SPOTTER_TPU_MSDA_ENC_PRESORTED=0
    restores the in-op mean-sample-location sort for checkpoints whose
    encoder offsets reach far enough that sample-location order beats
    token order (ADVICE r3: the knob must exist or such checkpoints have
    no way back to the sorted path)."""
    return os.environ.get("SPOTTER_TPU_MSDA_ENC_PRESORTED", "1") != "0"


def presort_wanted() -> bool:
    """True when a caller that can order its queries by spatial locality
    ONCE (e.g. the RT-DETR decoder stack, whose six layers share one
    ordering) should do so and pass `presorted=True` per op, instead of
    paying the sort + two q-row permutes inside every sampling op
    (measured 3.34 -> 2.97 ms per R101 layer cell, v5e). False when the
    active backend ignores ordering (XLA gathers) or the sort is disabled."""
    return MSDA_SORT and msda_backend(None) in ("pallas", "pallas_sep")


def msda_backend(override: str | None = None, batch_heads: int | None = None) -> str:
    """`batch_heads` is accepted for callers that want to specialize the
    policy by problem size; with the level-split kernel the measured answer
    is uniform, so it is currently unused."""
    del batch_heads
    name = (override or os.environ.get(MSDA_ENV, "auto")).strip().lower()
    if name not in ("auto", "xla", "pallas", "pallas_sep", "pallas_gather"):
        raise ValueError(
            f"{MSDA_ENV} must be auto|xla|pallas|pallas_sep|pallas_gather, "
            f"got {name!r}"
        )
    if name == "auto":
        # TPU: the merged-level one-hot kernel wins at every measured size
        # (R101 decoder stack, v5e, 1-pass precision: 24 ms vs 36 ms for the
        # separable-dot kernel and 205 ms for XLA row-gathers, whose
        # lowering collapses above batch*heads ~96). CPU/GPU: always XLA
        # (interpret-mode pallas would be pointlessly slow there).
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return name


def _level_offsets(spatial_shapes: tuple[tuple[int, int], ...]) -> np.ndarray:
    sizes = [h * w for h, w in spatial_shapes]
    return np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(np.int32)


def _corner_terms(xs, ys, at, w_const, h_const, method):
    """Shared corner math of the loc-prep kernel and its jnp reference.

    xs/ys/at: (..., LP) normalized sample coords + attention weights;
    w_const/h_const: (1, LP) (or broadcastable) per-lane level dims.
    Returns [(idx_level_local, weight)] per active corner, each (..., LP).
    """
    if method == "discrete":
        cx = jnp.clip(jnp.floor(xs * w_const + 0.5), 0, w_const - 1)
        cy = jnp.clip(jnp.floor(ys * h_const + 0.5), 0, h_const - 1)
        idx0 = (cy * w_const + cx).astype(jnp.int32)
        return [(idx0, at.astype(jnp.float32))]
    gx = xs * w_const - 0.5
    gy = ys * h_const - 0.5
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    fx = (gx - x0).astype(jnp.float32)
    fy = (gy - y0).astype(jnp.float32)
    out = []
    for dy in (0, 1):
        for dx in (0, 1):
            xc = x0 + dx
            yc = y0 + dy
            valid = (xc >= 0) & (xc <= w_const - 1) & (yc >= 0) & (yc <= h_const - 1)
            wx = fx if dx else 1.0 - fx
            wy = fy if dy else 1.0 - fy
            wgt = jnp.where(valid, wx * wy * at.astype(jnp.float32), 0.0)
            idxc = (
                jnp.clip(yc, 0, h_const - 1) * w_const + jnp.clip(xc, 0, w_const - 1)
            ).astype(jnp.int32)
            out.append((idxc, wgt))
    return out


def prepare_msda_gather(
    loc: jnp.ndarray,  # (B, H, LP, Q, 2) normalized [0,1] sample points
    attn: jnp.ndarray,  # (B, H, LP, Q) softmaxed attention weights
    spatial_shapes: tuple[tuple[int, int], ...],
    num_points: int,
    method: str = "default",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Corner indices + folded weights for the gather kernel.

    Returns idx (B, H, 4, LP*Q) int32 into the padded flat space and
    w (B, H, 4, LP*Q) fp32. For method="discrete" only corner 0 is active
    (nearest-neighbor, border-clamped — RT-DETRv2 discrete sampling
    semantics); for "default" the four bilinear corners carry
    align_corners=False, zeros-padding semantics.
    """
    b, h_axis, lp, q, _ = loc.shape
    levels = len(spatial_shapes)
    offs = _level_offsets(spatial_shapes)
    # per-sample level id: sample axis is level-major (L blocks of P points)
    lvl_h = np.repeat([hh for hh, _ in spatial_shapes], num_points).astype(np.float32)
    lvl_w = np.repeat([ww for _, ww in spatial_shapes], num_points).astype(np.float32)
    lvl_off = np.repeat(offs, num_points).astype(np.int32)
    assert lvl_h.shape[0] == lp, (lp, levels, num_points)
    shp = (1, 1, lp, 1)
    lvl_h = lvl_h.reshape(shp)
    lvl_w = lvl_w.reshape(shp)
    lvl_off = lvl_off.reshape(shp)

    # Corner decomposition shared with the in-kernel prep path
    # (_corner_terms is THE single implementation of the discrete/bilinear
    # corner semantics); this wrapper adds the global level offsets and the
    # fixed 4-slot corner axis the gather consumers index.
    corners = _corner_terms(loc[..., 0], loc[..., 1], attn, lvl_w, lvl_h, method)
    while len(corners) < 4:  # discrete: one active corner + zero slots
        corners.append(
            (jnp.zeros_like(corners[0][0]), jnp.zeros_like(corners[0][1]))
        )
    idx = jnp.stack([lvl_off + c for c, _ in corners], axis=2)
    w = jnp.stack([cw for _, cw in corners], axis=2)

    # (B, H, 4, LP, Q) -> (B, H, 4, LP*Q): sample-major flat layout so the
    # kernel's group-sum is LP contiguous static slices of Q lanes.
    idx = idx.reshape(b, h_axis, 4, lp * q)
    w = w.reshape(b, h_axis, 4, lp * q)
    return idx, w


def _gather_weighted_sum(vt, idx, w, lp: int, q: int):
    """Reference math shared by the XLA path and the kernel's VJP.

    vt: (B, H, hd, S); idx/w: (B, H, 4, LP*Q). Returns (B, H, hd, Q).

    Gather-axis choice is the whole performance story here, and it differs
    per backend: XLA lowers *row* gathers (major axis, contiguous minor dim)
    to fast vector loads but per-element minor-axis gathers to a ~40x-slower
    generic path, while Mosaic's DynamicGather vectorizes only along lanes
    (the minor axis). So this XLA-side reference works row-major — value
    rows (S, hd) gathered along S — on the transpose of the kernel's
    (hd, S) lane layout.
    """
    rows = vt.transpose(0, 1, 3, 2)  # (B, H, S, hd): gather rows along S
    return _row_gather_weighted_sum(rows, idx, w, lp, q).transpose(0, 1, 3, 2)


def _row_gather_weighted_sum(rows, idx, w, lp: int, q: int):
    """Row-major core: rows (B, H, S, hd), idx/w (B, H, 4, LP*Q) ->
    (B, H, Q, hd)."""
    hd = rows.shape[-1]
    acc = None
    for c in range(4):  # corner loop: never broadcast the value maps 4x
        g = jnp.take_along_axis(rows, idx[:, :, c, :, None], axis=2)
        term = g * w[:, :, c, :, None].astype(rows.dtype)  # (B, H, N, hd)
        acc = term if acc is None else acc + term
    return acc.reshape(*acc.shape[:2], lp, q, hd).sum(axis=2)


def xla_deformable_sampling(vt, idx, w, lp: int, q: int):
    """Pure-XLA fallback with identical semantics to the Pallas kernel."""
    return _gather_weighted_sum(vt, idx, w, lp, q)


def _msda_kernel(vt_ref, idx_ref, w_ref, out_ref, *, lp: int, q: int):
    # vt, idx, w all share the lane extent G = max(S, LP*Q) rounded up to a
    # lane multiple: Mosaic's vectorized gather requires indices broadcast
    # to exactly the input shape (dynamic_gather is an elementwise lookup).
    vt = vt_ref[0, 0]  # (hd, G)
    hd, g_lanes = vt.shape
    acc = jnp.zeros((hd, g_lanes), vt.dtype)
    for c in range(4):
        ids = jnp.broadcast_to(idx_ref[0, 0, c][None, :], (hd, g_lanes))
        g = jnp.take_along_axis(vt, ids, axis=1)
        acc = acc + g * w_ref[0, 0, c][None, :].astype(vt.dtype)
    out = jnp.zeros((hd, q), vt.dtype)
    for j in range(lp):  # static contiguous slices: sample-major layout
        out = out + acc[:, j * q : (j + 1) * q]
    out_ref[0, 0] = out


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_deformable_sampling(vt, idx, w, lp: int, q: int, interpret: bool = False):
    """Fused gather + weighted group-sum on TPU.

    vt: (B, H, hd, S) value maps (S padded to a lane multiple);
    idx/w: (B, H, 4, LP*Q) from `prepare_msda_gather`. Returns (B, H, hd, Q).
    """
    b, h_axis, hd, s = vt.shape
    n = idx.shape[-1]
    # Common lane extent: Mosaic's gather needs source and (broadcast)
    # indices to share a shape. Pad source and samples to G lanes; padded
    # sample slots carry idx 0 / weight 0 and never enter the group-sum.
    g_lanes = max(-(-s // LANE) * LANE, -(-n // LANE) * LANE)
    if g_lanes != s:
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, 0), (0, g_lanes - s)))
    if g_lanes != n:
        idx = jnp.pad(idx, ((0, 0), (0, 0), (0, 0), (0, g_lanes - n)))
        w = jnp.pad(w, ((0, 0), (0, 0), (0, 0), (0, g_lanes - n)))
    kernel = partial(_msda_kernel, lp=lp, q=q)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h_axis, hd, q), vt.dtype),
        grid=(b, h_axis),
        in_specs=[
            pl.BlockSpec(
                (1, 1, hd, g_lanes), lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, 4, g_lanes), lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, 4, g_lanes), lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, hd, q), lambda i, j: (i, j, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(vt, idx, w)


def _msda_fwd(vt, idx, w, lp, q, interpret):
    return pallas_deformable_sampling(vt, idx, w, lp, q, interpret), (vt, idx, w)


def _msda_bwd(lp, q, interpret, res, g):
    # Backward through the pure-jnp reference: exactly the same math, so the
    # kernel stays a drop-in under jax.grad (train step with pallas on).
    vt, idx, w = res
    _, vjp = jax.vjp(lambda v, ww: _gather_weighted_sum(v, idx, ww, lp, q), vt, w)
    dvt, dw = vjp(g)
    return dvt, None, dw


pallas_deformable_sampling.defvjp(_msda_fwd, _msda_bwd)


# --- gather-free one-hot MXU kernel (the production TPU backend) ---

# Five 128-lane vregs per one-hot tile column block. Swept on v5e (R101
# batch 8, mixed policy): S_TILE 256/384/512/640/768 -> 64.0/58.5/54.4/
# 52.1/54.9 ms end-to-end. 640 wins on tile-count alignment: the stride-8
# level's 80x80=6400 positions split into exactly 10 tiles (512 pads 12.5
# ->13) while staying small enough that the hit table still prunes.
# Process-start-only env overrides (like SPOTTER_TPU_MSDA_PRECISION) for
# hardware tile sweeps; values are baked into compiled programs.
S_TILE = int(os.environ.get("SPOTTER_TPU_MSDA_STILE", "640"))

# Optional finer tile for the FIRST (stride-8, densest) level only: its
# 80x80 span holds ~76% of positions, so a hit there compares a whole
# S_TILE (8 rows at 640) even when the query tile's samples span fewer
# rows. 0 = use S_TILE (default; the round-3 uniform sweep showed smaller
# GLOBAL tiles lose — this knob changes level 0 alone).
S_TILE0 = int(os.environ.get("SPOTTER_TPU_MSDA_STILE0", "0"))

# Locality sort ON by default: sorting queries by quantized mean sample
# position makes the block-sparse hit table prune (neighbor queries share
# source bands). SPOTTER_TPU_MSDA_SORT=0 uses the identity permutation —
# for hardware where the argsort + q-row permutes cost more than the
# sparsity saves (process-start-only knob like the tile sizes).
MSDA_SORT = os.environ.get("SPOTTER_TPU_MSDA_SORT", "1") != "0"


def _onehot_ref_math(rows, idx, w):
    """jnp reference for the one-hot kernel (VJP + interpret parity).

    rows: (BH, S, hd); idx/w: (BH, Qp, JC). Returns (BH, Qp, hd) fp32 —
    the kernel accumulates and emits fp32 regardless of the rows dtype.
    """
    bh, qp, jc = idx.shape
    hd = rows.shape[-1]
    flat = idx.reshape(bh, qp * jc, 1)
    g = jnp.take_along_axis(rows, flat, axis=1).reshape(bh, qp, jc, hd)
    return (g.astype(jnp.float32) * w[..., None].astype(jnp.float32)).sum(axis=2)


# --- block-sparse kernel: skip (query-tile, source-tile) pairs no sample
# hits. Queries are pre-sorted by spatial locality (dispatcher), so a tile
# of neighboring queries samples a narrow band of each level's source and
# most pairs are misses — the compare cost drops by the miss rate.

Q_TILE = int(os.environ.get("SPOTTER_TPU_MSDA_QTILE", "64"))

# Sub-query-tile sparsity (SPOTTER_TPU_MSDA_SG): the hit table says "some
# query in this 64-row tile touches source tile k", but a SINGLE query's
# 16 corners only span 1-2 source tiles — the sorted 64-query tile's span
# (~6 tiles on the stride-8 level; reference points, not offsets, dominate
# it) is what forces every hit tile to pay all 64 rows of compares. With
# SG=8 the one-hot build runs per 8-query sublane group, each predicated on
# its OWN hit bit (the mask becomes a bitfield over groups), writing its
# slice of a shared VMEM scratch tile; the MXU contraction still happens
# ONCE per source tile over the full 64-row tile, so dot count is
# unchanged while compare elements drop by the per-group miss rate
# (measured span statistics: ~2.5x fewer on the stride-8 level). 0 = off.
# Nested-select one-hot build (SPOTTER_TPU_MSDA_NEST=1): the 4 bilinear
# corners of ONE sample point are always 4 distinct cells, so their four
# (compare, select, add) chains can fold into a first-match select tree —
# 4 cmp + 4 sel + 1 add per point instead of 4x(cmp+sel+add), ~25% off
# the kernel's dominant op count. Exactness needs collision-free indices:
# a clamped out-of-bounds corner (weight 0) can alias an in-bounds
# neighbor's cell and would shadow its weight in first-match order, so
# the dispatcher rewrites every weight<=0 corner's index to a unique
# negative sentinel (never matches a column). Sum semantics are then
# identical; the VJP reference is unchanged.
MSDA_NEST = os.environ.get("SPOTTER_TPU_MSDA_NEST", "0") != "0"

MSDA_SG = int(os.environ.get("SPOTTER_TPU_MSDA_SG", "0"))
if MSDA_SG and (
    Q_TILE % MSDA_SG or MSDA_SG % 8 or Q_TILE // MSDA_SG > 32
):
    # <= 32 groups: the per-group hit bits live in ONE int32 mask entry
    raise ValueError(
        f"SPOTTER_TPU_MSDA_SG must be 0 or a multiple of 8 dividing "
        f"Q_TILE={Q_TILE} into at most 32 groups, got {MSDA_SG}"
    )
if (MSDA_SG or MSDA_NEST) and os.environ.get(
    MSDA_ENV, "auto"
).strip().lower() not in ("auto", "pallas"):
    # only the merged one-hot kernel on the XLA-prep path implements
    # subgroup masks / nested corner selects; silently no-op'ing a knob
    # would record a wrong A/B conclusion — exactly what the flags exist
    # to measure. (The PREP=kernel conflicts are checked below, after
    # MSDA_PREP is parsed.)
    raise ValueError(
        "SPOTTER_TPU_MSDA_SG/NEST require the merged one-hot backend "
        "(SPOTTER_TPU_MSDA=auto|pallas); other backends ignore them"
    )
if (MSDA_SG or MSDA_NEST) and os.environ.get(
    MSDA_ENV, "auto"
).strip().lower() == "auto":
    # ADVICE r5 #3: under `auto`, CPU/GPU hosts resolve to the XLA backend
    # and the knobs would be silently ignored — or, worse, abort every
    # forward if checked per call. Fail fast HERE, at import, where the
    # operator set the env; the call-time check below is reserved for
    # explicit per-call `backend=` overrides. (Exported knobs on a TPU host
    # still work: auto resolves to pallas there.)
    if jax.default_backend() != "tpu":
        raise ValueError(
            f"SPOTTER_TPU_MSDA_SG/NEST require the pallas backend, but "
            f"SPOTTER_TPU_MSDA=auto resolves to 'xla' on this "
            f"{jax.default_backend()!r} host — unset the knobs or run on TPU"
        )


def _mxu_precision() -> jax.lax.Precision:
    """MXU pass count for the one-hot contraction (SPOTTER_TPU_MSDA_PRECISION).

    "highest" (default): 6-pass fp32 — bit-faithful to the gather reference
    (kernel parity tests pin this). "default": single bf16 pass — the one-hot
    weights are bilinear coefficients in [0,1] and values are activations, so
    bf16 rounding costs ~1e-3 relative on sampled values; opt in when that
    drift is acceptable for the deployment.

    Read ONCE at import (module constant below) like the other env knobs:
    the value is baked into jit-compiled programs and is not part of any jit
    cache key, so changing the env after first trace could never take effect.

    Default follows the serving precision policy: SPOTTER_TPU_DTYPE of
    "mixed"/"bfloat16" already accepts bf16 rounding in the model, so the
    sampling contraction defaults to the 1-pass MXU there; fp32 policies
    keep the bit-faithful 6-pass default.
    """
    from spotter_tpu.utils.precision import DTYPE_ENV  # no heavy imports

    policy = os.environ.get(DTYPE_ENV, "").strip().lower()
    policy_default = (
        "default" if policy in ("mixed", "bfloat16", "bf16") else "highest"
    )
    name = (
        os.environ.get("SPOTTER_TPU_MSDA_PRECISION", policy_default)
        .strip()
        .lower()
    )
    table = {
        "highest": jax.lax.Precision.HIGHEST,
        "default": jax.lax.Precision.DEFAULT,
    }
    if name not in table:
        raise ValueError(
            f"Unsupported SPOTTER_TPU_MSDA_PRECISION={name!r}; "
            f"expected one of {sorted(table)}"
        )
    return table[name]


# process-start-only knob (see _mxu_precision docstring)
MSDA_MXU_PRECISION = _mxu_precision()




# --- separable bilinear kernel ("pallas_sep"): MXU work instead of compares.
#
# The one-hot kernel's cost is the tile BUILD: 4 corners x P points x
# (compare+select+add) over every (query, source) element — ~48 VPU ops per
# element, measured ~80% of the op's time (the MXU contraction is a minority).
# Bilinear weights are separable: w_corner = attn*(wy0|wy1)*(wx0|wx1), so per
# point the whole (Q, S) one-hot block factors into wy(Q, rows) (x) wx(Q, W).
# This kernel never builds the (Q, S) block at all:
#
#     g_p(q, r*hd)   = Wx_p(q, W) @ V_band(W, R*hd)          [MXU dot 1]
#     m_p            = g_p * WyExpand_p(q, R*hd)             [VPU, 2 compares]
#     out_p(q, hd)   = m_p @ SumBlock(R*hd, hd)              [MXU dot 2, 0/1]
#
# where V_band is the source band transposed to (W, R*hd) lanes r-major and
# SumBlock is the constant 0/1 matrix summing each row group. Compares drop
# from 16 full-width columns to 2 narrow + 2 full-width per point.
# Out-of-band rows and out-of-bounds corners match nothing (unclamped
# indices never equal an in-range lane id), so band masking and the
# zeros-padding sampling semantics fall out of the compares for free.
#
# Status: measured SLOWER than the merged one-hot kernel on v5e at R101
# decoder shapes (36 vs 24 ms per 6-layer stack at 1-pass precision — the
# per-cell dot issues, not the compares, dominate there), so `auto` never
# picks it; it stays as an explicit `SPOTTER_TPU_MSDA=pallas_sep` backend
# for re-evaluation on hardware where the trade flips.

SEP_R_BAND = 8  # rows per band when W <= 128; wider maps halve it


def _sep_band_kernel(
    mask_ref, xi_ref, xw0_ref, xw1_ref, yi_ref, yw0_ref, yw1_ref, v_ref,
    out_ref, *, w_level: int, r_band: int, n_points: int, precision,
):
    # All query-side blocks are point-STACKED columns (1, P*Q_TILE, 1) with
    # point p owning sublane rows [p*Q_TILE, (p+1)*Q_TILE). The whole cell
    # issues TWO dots — one (P*QT, W) x-contraction and one group-sum —
    # instead of 2*P small ones; matmul issue latency was the measured
    # bottleneck of the per-point variant.
    pqt = xi_ref.shape[1]
    qt = pqt // n_points
    hd = out_ref.shape[-1]
    i, nq, ns = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(ns == 0)
    def _():
        out_ref[0] = jnp.zeros_like(out_ref[0])

    @pl.when(mask_ref[i, nq, ns] != 0)
    def _():
        r0 = ns * r_band
        cx = jax.lax.broadcasted_iota(jnp.int32, (pqt, w_level), 1)
        x0 = xi_ref[0]  # (P*QT, 1) column, broadcast along lanes
        wx = jnp.where(cx == x0, xw0_ref[0], 0.0) + jnp.where(
            cx == x0 + 1, xw1_ref[0], 0.0
        )
        g = jnp.dot(
            wx, v_ref[0, 0], preferred_element_type=jnp.float32, precision=precision
        )  # (P*QT, R*hd)
        # lane r-id of the dot-1 output: lane = r*hd + hd_i
        lane_r = jax.lax.broadcasted_iota(jnp.int32, (pqt, r_band * hd), 1) // hd
        y0 = yi_ref[0] - r0
        wy = jnp.where(lane_r == y0, yw0_ref[0], 0.0) + jnp.where(
            lane_r == y0 + 1, yw1_ref[0], 0.0
        )
        m = g * wy
        acc = m[:qt]
        for p in range(1, n_points):  # static sublane slices: point group-sum
            acc = acc + m[p * qt : (p + 1) * qt]
        # constant 0/1 group-sum matrix (R*hd, hd): lane l feeds column l%hd
        sum_block = (
            jax.lax.broadcasted_iota(jnp.int32, (r_band * hd, hd), 0) % hd
            == jax.lax.broadcasted_iota(jnp.int32, (r_band * hd, hd), 1)
        ).astype(jnp.float32)
        out = jnp.dot(
            acc, sum_block, preferred_element_type=jnp.float32, precision=precision
        )
        out_ref[0] = out_ref[0] + out.astype(out_ref.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11))
def pallas_sep_sampling(
    rows, xi, xw0, xw1, yi, yw0, yw1, mask,
    w_level: int, r_band: int, n_points: int, interpret: bool = False,
):
    """Separable bilinear sampling over one level (point-stacked layout).

    rows: (BH, n_bands, W, R*hd) band-transposed values; xi/yi:
    (BH, n_qt*P*Q_TILE, 1) int32 column-vector UNCLAMPED level-local x0/y0,
    point-major within each query tile; xw0/xw1/yw0/yw1: same-shape f32
    corner-pair weights (attn folded into the x pair, validity folded by
    zeroing); mask: (BH, n_qt, n_bands) int32 hit table. Returns
    (BH, n_qt*Q_TILE, hd) f32.
    """
    bh, n_bands, w_lvl, rhd = rows.shape
    hd = rhd // r_band
    n_qt = mask.shape[1]
    pqt = xi.shape[1] // n_qt
    qp = n_qt * (pqt // n_points)
    kernel = partial(
        _sep_band_kernel,
        w_level=w_level,
        r_band=r_band,
        n_points=n_points,
        precision=MSDA_MXU_PRECISION,
    )
    flops = 2 * bh * n_bands * (n_qt * pqt * w_lvl * rhd + qp * rhd * hd)
    _note_flops("msda_sep_band", flops)
    qblock = [
        pl.BlockSpec(
            (1, pqt, 1), lambda i, nq, s, *_: (i, nq, 0), memory_space=pltpu.VMEM
        )
        for _ in range(6)
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n_qt, n_bands),
        in_specs=qblock
        + [
            pl.BlockSpec(
                (1, 1, w_lvl, rhd), lambda i, nq, s, *_: (i, s, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, Q_TILE, hd), lambda i, nq, s, *_: (i, nq, 0),
            memory_space=pltpu.VMEM,
        ),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, qp, hd), jnp.float32),
        grid_spec=grid_spec,
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=rows.size * 4 * n_qt
            + (xi.size + yi.size) * 4
            + 4 * xw0.size * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(mask, xi, xw0, xw1, yi, yw0, yw1, rows)


def _sep_ref_math(rows, xi, xw0, xw1, yi, yw0, yw1, r_band, n_points):
    """jnp reference of the separable kernel (VJP + parity tests).

    Same contraction order (x-dot, y-weight, point sum, group sum in fp32),
    so under HIGHEST precision it matches the kernel bit-for-bit-ish.
    """
    bh, n_bands, w_lvl, rhd = rows.shape
    hd = rhd // r_band
    qt = Q_TILE
    n_qt = xi.shape[1] // (n_points * qt)
    cx = jnp.arange(w_lvl, dtype=jnp.int32)
    rr = jnp.arange(n_bands * r_band, dtype=jnp.int32)
    # rows (BH, bands, W, R, hd) -> (BH, bands*R rows, W, hd)
    v = rows.reshape(bh, n_bands, w_lvl, r_band, hd).transpose(0, 1, 3, 2, 4)
    v = v.reshape(bh, n_bands * r_band, w_lvl, hd)

    def unstack(a):  # (BH, n_qt*P*QT, 1) -> (BH, P, n_qt*QT)
        return a.reshape(bh, n_qt, n_points, qt).transpose(0, 2, 1, 3).reshape(
            bh, n_points, n_qt * qt
        )

    xi_u, yi_u = unstack(xi), unstack(yi)
    xw0_u, xw1_u = unstack(xw0), unstack(xw1)
    yw0_u, yw1_u = unstack(yw0), unstack(yw1)
    out = jnp.zeros((bh, n_qt * qt, hd), jnp.float32)
    for p in range(n_points):
        wx = (
            (cx[None, None, :] == xi_u[:, p, :, None]) * xw0_u[:, p, :, None]
            + (cx[None, None, :] == xi_u[:, p, :, None] + 1) * xw1_u[:, p, :, None]
        ).astype(jnp.float32)
        wy = (
            (rr[None, None, :] == yi_u[:, p, :, None]) * yw0_u[:, p, :, None]
            + (rr[None, None, :] == yi_u[:, p, :, None] + 1) * yw1_u[:, p, :, None]
        ).astype(jnp.float32)
        g = jnp.einsum("bqw,brwd->bqrd", wx, v)  # (BH, Qp, rows, hd)
        out = out + (g * wy[..., None]).sum(axis=2)
    return out


def _sep_fwd(rows, xi, xw0, xw1, yi, yw0, yw1, mask, w_level, r_band, n_points, interpret):
    return (
        pallas_sep_sampling(
            rows, xi, xw0, xw1, yi, yw0, yw1, mask, w_level, r_band, n_points, interpret
        ),
        (rows, xi, xw0, xw1, yi, yw0, yw1),
    )


def _sep_bwd(w_level, r_band, n_points, interpret, res, g):
    rows, xi, xw0, xw1, yi, yw0, yw1 = res
    _, vjp = jax.vjp(
        lambda r, a0, a1, b0, b1: _sep_ref_math(
            r, xi, a0, a1, yi, b0, b1, r_band, n_points
        ),
        rows, xw0, xw1, yw0, yw1,
    )
    d_rows, d_xw0, d_xw1, d_yw0, d_yw1 = vjp(g)
    return d_rows, None, d_xw0, d_xw1, None, d_yw0, d_yw1, None


pallas_sep_sampling.defvjp(_sep_fwd, _sep_bwd)


def _sep_level_dispatch(
    value_l,  # (BH, S_l, hd) this level's rows (unpadded)
    loc_l,  # (B, Q, H, P, 2) this level's sample points in [0, 1]
    attn_l,  # (B, Q, H, P)
    lh: int,
    lw: int,
    method: str,
    interpret: bool,
) -> jnp.ndarray:
    """Prepare separable operands for one level and run the kernel."""
    b, q, h_axis, pts, _ = loc_l.shape
    bh = b * h_axis
    hd = value_l.shape[-1]
    qp = -(-q // Q_TILE) * Q_TILE

    # band geometry: R_BAND rows per grid step, W on the dot's K axis
    r_band = SEP_R_BAND if lw <= 128 else max(1, SEP_R_BAND // 2)
    n_bands = -(-lh // r_band)

    attn_f = attn_l.astype(jnp.float32)
    if method == "discrete":
        # nearest-integer, border-clamped (RT-DETRv2 discrete semantics):
        # single active corner, always valid after the clamp
        x0 = jnp.clip(jnp.floor(loc_l[..., 0] * lw + 0.5), 0, lw - 1)
        y0 = jnp.clip(jnp.floor(loc_l[..., 1] * lh + 0.5), 0, lh - 1)
        xw0, xw1 = attn_f, jnp.zeros_like(attn_f)
        yw0, yw1 = jnp.ones_like(attn_f), jnp.zeros_like(attn_f)
    else:
        gx = loc_l[..., 0] * lw - 0.5
        gy = loc_l[..., 1] * lh - 0.5
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        fx = (gx - x0).astype(jnp.float32)
        fy = (gy - y0).astype(jnp.float32)
        # validity folds into the weights; indices stay UNCLAMPED so an
        # out-of-bounds corner can never equal an in-range lane/row id
        vx0 = ((x0 >= 0) & (x0 <= lw - 1)).astype(jnp.float32)
        vx1 = (x0 + 1 <= lw - 1).astype(jnp.float32) * (x0 + 1 >= 0)
        vy0 = ((y0 >= 0) & (y0 <= lh - 1)).astype(jnp.float32)
        vy1 = (y0 + 1 <= lh - 1).astype(jnp.float32) * (y0 + 1 >= 0)
        xw0 = (1.0 - fx) * vx0 * attn_f  # attn folded into the x pair
        xw1 = fx * vx1 * attn_f
        yw0 = (1.0 - fy) * vy0
        yw1 = fy * vy1

    n_qt = qp // Q_TILE

    def stack(a, pad_value=0):  # (B, Q, H, P) -> (BH, n_qt, P*Q_TILE)
        a = a.transpose(0, 2, 1, 3).reshape(bh, q, pts)
        if qp != q:
            a = jnp.pad(
                a, ((0, 0), (0, qp - q), (0, 0)), constant_values=pad_value
            )
        # point-major within each query tile, as a column vector (the
        # kernel's (P*QT, 1) sublane layout)
        return a.reshape(bh, n_qt, Q_TILE, pts).transpose(0, 1, 3, 2).reshape(
            bh, n_qt * pts * Q_TILE, 1
        )

    xi = stack(x0.astype(jnp.int32), pad_value=-7)
    yi = stack(y0.astype(jnp.int32), pad_value=-7)
    xw0_s, xw1_s = stack(xw0), stack(xw1)
    yw0_s, yw1_s = stack(yw0), stack(yw1)

    # band-transposed values: (BH, S_l, hd) -> (BH, n_bands, W, R*hd) r-major
    h_pad = n_bands * r_band
    v = value_l.reshape(bh, lh, lw, hd)
    if h_pad != lh:
        v = jnp.pad(v, ((0, 0), (0, h_pad - lh), (0, 0), (0, 0)))
    v = v.reshape(bh, n_bands, r_band, lw, hd).transpose(0, 1, 3, 2, 4)
    rows = v.reshape(bh, n_bands, lw, r_band * hd)

    # hit table: which row bands does each query tile touch? (from y0/y0+1
    # where the corner weight can be nonzero — never suppresses a real hit).
    # A corner's y-weight gates BOTH its row candidates (wy0 -> y0 row,
    # wy1 -> y0+1 row); x-weights don't matter, the band spans the width.
    band_ids = jnp.arange(n_bands, dtype=jnp.int32)
    y_hits = [
        jnp.where(yw0_s > 0, yi // r_band, -1),
        jnp.where(yw1_s > 0, (yi + 1) // r_band, -1),
    ]
    # columns (BH, n_qt*P*QT, 1) -> per-query-tile rows (BH, n_qt, 2*P*QT)
    bands = jnp.concatenate(y_hits, axis=-1).reshape(bh, n_qt, -1)
    mask = (bands[..., None] == band_ids).any(axis=2).astype(jnp.int32)
    return pallas_sep_sampling(
        rows, xi, xw0_s, xw1_s, yi, yw0_s, yw1_s, mask, lw, r_band, pts, interpret
    )[:, :q]


# --- merged-level one-hot kernel: ONE pallas_call per MSDA op.
#
# Measured on v5e (R101 decoder shapes): each pallas_call costs ~0.9 ms of
# launch overhead and each grid step ~0.5 us even when the hit mask skips
# the body — with 3 per-level calls x 6 decoder layers, launches alone were
# ~16 ms of the ~30 ms sampling stack. This kernel runs every level's source
# tiles in one grid: the s axis walks the CONCATENATED per-level padded
# spans, index maps route the per-level (Q_TILE, jc) idx/w blocks by which
# level the s-step belongs to (static thresholds -> plain id arithmetic),
# and the output accumulates across all levels' steps, so the per-level
# partial sums come free.


def _onehot_merged_kernel(
    mask_ref, idx_ref, w_ref, v_ref, out_ref, *scratch,
    level_tiles: tuple, precision, subgroup: int = 0, nested: bool = False,
):
    # Grid is (bh, n_qt) ONLY: the s-walk over every level's tiles is a
    # static Python unroll over slices of the fully-fetched value block.
    # Measured on v5e, each pipelined grid step costs ~0.7 us of machinery
    # even when the hit mask skips the body — a (bh, n_qt, n_s) grid spent
    # ~3 ms/layer on machinery alone at R101 decoder shapes (4480 steps);
    # this layout pays it for 320. The s-loop being in-kernel also means the
    # value block is fetched once per (bh, nq), and each unrolled step knows
    # its level (and its level's tile size) STATICALLY. `level_tiles` is a
    # per-level (tile_size, span_count) tuple: finer tiles on the dense
    # stride-8 level shrink each hit's compare footprint without touching
    # the coarser levels (SPOTTER_TPU_MSDA_STILE0).
    #
    # `subgroup` (MSDA_SG): build the one-hot per SG-query sublane group,
    # each predicated on its own bit of the (bitfield) hit mask, into a
    # shared VMEM scratch tile; contract ONCE per source tile. Compare work
    # drops by the per-group miss rate; dot count is unchanged.
    qt, jc = idx_ref.shape[2], idx_ref.shape[3]
    i, nq = pl.program_id(0), pl.program_id(1)

    out_ref[0] = jnp.zeros_like(out_ref[0])
    step0 = 0
    v_off = 0
    for lvl, (ts, span) in enumerate(level_tiles):
        idx = idx_ref[0, lvl]
        w = w_ref[0, lvl]
        for k in range(span):
            ns = step0 + k

            @pl.when(mask_ref[i, nq, ns] != 0)
            def _(k=k, idx=idx, w=w, ts=ts, lo=v_off):
                def oh_chain(rows_sl):
                    """The one one-hot build over (rows, ts) at tile k —
                    shared verbatim by the full-tile and per-subgroup paths
                    so the two can never drift. `nested` folds each point's
                    4 corner chains into a first-match select tree (exact
                    under the dispatcher's sentinel-index rewrite — see
                    MSDA_NEST)."""
                    n_rows = idx[rows_sl].shape[0]
                    col = jax.lax.broadcasted_iota(
                        jnp.int32, (n_rows, ts), 1
                    ) + (k * ts)
                    oh = jnp.zeros((n_rows, ts), jnp.float32)
                    if nested:
                        points = jc // 4
                        for p in range(points):
                            sel = jnp.zeros((n_rows, ts), jnp.float32)
                            for c in reversed(range(4)):
                                j = c * points + p
                                sel = jnp.where(
                                    col == idx[rows_sl, j : j + 1],
                                    w[rows_sl, j : j + 1].astype(jnp.float32),
                                    sel,
                                )
                            oh = oh + sel
                        return oh
                    for j in range(jc):
                        oh = oh + jnp.where(
                            col == idx[rows_sl, j : j + 1],
                            w[rows_sl, j : j + 1].astype(jnp.float32),
                            0.0,
                        )
                    return oh

                if subgroup:
                    oh_ref = scratch[0]
                    oh_ref[:, :ts] = jnp.zeros((qt, ts), jnp.float32)
                    for g in range(qt // subgroup):

                        @pl.when(((mask_ref[i, nq, ns] >> g) & 1) != 0)
                        def _(g=g, ts=ts):
                            sl = slice(g * subgroup, (g + 1) * subgroup)
                            oh_ref[sl, :ts] = oh_chain(sl)

                    oh = oh_ref[:, :ts]
                else:
                    oh = oh_chain(slice(None))
                acc = jnp.dot(
                    oh,
                    v_ref[0, lo + k * ts : lo + (k + 1) * ts].astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                    precision=precision,
                )
                out_ref[0] = out_ref[0] + acc.astype(out_ref.dtype)

        step0 += span
        v_off += ts * span


@partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def pallas_onehot_sampling_merged(
    rows, idx, w, mask, level_tiles: tuple, interpret: bool = False
):
    """Block-sparse one-hot sampling over ALL levels in one pallas_call.

    rows: (BH, s_cat, hd) — per-level spans each padded to their own tile
    multiple and concatenated; idx/w: (BH, L, Qp, jc) level-LOCAL corner
    indices/weights (invalid slots negative/zero); mask: (BH, Qp//Q_TILE,
    n_s_total) hit table over the concatenated s-steps; level_tiles: static
    per-level (tile_size, span_count) pairs (sum of tile*span = s_cat).
    Returns (BH, Qp, hd) fp32.
    """
    bh, s_cat, hd = rows.shape
    _, n_levels, qp, jc = idx.shape
    level_tiles = tuple((int(t), int(s)) for t, s in level_tiles)
    n_s = sum(span for _, span in level_tiles)
    n_qt = qp // Q_TILE
    assert sum(t * s for t, s in level_tiles) == s_cat, (level_tiles, s_cat)
    assert mask.shape[2] == n_s, (mask.shape, level_tiles)
    kernel = partial(
        _onehot_merged_kernel,
        level_tiles=level_tiles,
        precision=MSDA_MXU_PRECISION,
        subgroup=MSDA_SG,
        nested=MSDA_NEST,
    )
    scratch_shapes = (
        [pltpu.VMEM((Q_TILE, max(t for t, _ in level_tiles)), jnp.float32)]
        if MSDA_SG
        else []
    )
    flops = sum(
        2 * bh * span * (qp * ts * hd + jc * qp * ts) for ts, span in level_tiles
    )
    _note_flops("msda_onehot_merged", flops)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n_qt),
        in_specs=[
            pl.BlockSpec(
                (1, n_levels, Q_TILE, jc),
                lambda i, nq, *_: (i, 0, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, n_levels, Q_TILE, jc),
                lambda i, nq, *_: (i, 0, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            # the whole concatenated value block rides along per bh; the
            # index map ignores nq, so the pipeline fetches it once per i
            pl.BlockSpec(
                (1, s_cat, hd), lambda i, nq, *_: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, Q_TILE, hd), lambda i, nq, *_: (i, nq, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=scratch_shapes,
    )
    if MSDA_NEST:
        # unique negative sentinels for match-incapable corners so a
        # clamped OOB corner can never shadow a sibling's cell in the
        # first-match select tree. Applied HERE (kernel-facing primal
        # only): the custom-VJP residuals keep the caller's true indices,
        # whose gather-backward needs the real corner cells even for
        # exactly-zero-weight corners (their d_w drives the loc gradient).
        sent = -1 - jnp.arange(jc, dtype=jnp.int32)
        idx = jnp.where(w > 0, idx, sent)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, qp, hd), jnp.float32),
        grid_spec=grid_spec,
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=rows.size * 4 + 2 * idx.size * 4 + mask.size * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(mask, idx, w, rows)


def _onehot_merged_ref(rows, idx, w, level_tiles):
    """Dense reference for the merged kernel (identical primal -> exact VJP)."""
    bh, _, hd = rows.shape
    out = None
    off = 0
    for lvl, (ts, span) in enumerate(level_tiles):
        rows_l = rows[:, off : off + ts * span]
        off += ts * span
        part = _onehot_ref_math(rows_l, idx[:, lvl], w[:, lvl])
        out = part if out is None else out + part
    return out


def _onehot_merged_fwd(rows, idx, w, mask, level_tiles, interpret):
    return (
        pallas_onehot_sampling_merged(rows, idx, w, mask, level_tiles, interpret),
        (rows, idx, w),
    )


def _onehot_merged_bwd(level_tiles, interpret, res, g):
    rows, idx, w = res
    _, vjp = jax.vjp(
        lambda r, ww: _onehot_merged_ref(r, idx, ww, level_tiles), rows, w
    )
    d_rows, d_w = vjp(g)
    return d_rows, None, d_w, None


pallas_onehot_sampling_merged.defvjp(_onehot_merged_fwd, _onehot_merged_bwd)


# --- in-kernel-prep variant (SPOTTER_TPU_MSDA_PREP=kernel): the corner
# decomposition (floor, bilinear weights, validity, level-local indices)
# moves INSIDE the kernel as ~45 VPU ops on one (Q_TILE, LP) lane group per
# grid cell, replacing the XLA-side prep passes over (B, H, Q, 4, LP)
# idx/w tensors (~0.3 ms/layer measured after the presort change). The hit
# table is built outside from the y coordinates alone — exact for every
# in-bounds corner when each level tile spans whole rows (ts % W == 0:
# tile_of(y0*W + x0) == y0 // rows_per_tile for any x0 < W), a superset
# otherwise only for out-of-bounds corners whose weight the kernel zeroes.
# Default stays "xla" until the on-chip A/B records a win (BASELINE.md).
#
# TRAINING caveat (ADVICE r3): this path's custom VJP backward runs the
# jnp gather reference (_loc_ref) plus a forward recompute, so under
# PREP=kernel the kernel's benefit exists in the FORWARD only — a training
# A/B that reads end-to-end step time would misattribute the gather-cost
# backward to the kernel. Serving (forward-only) is the intended consumer.

MSDA_PREP = os.environ.get("SPOTTER_TPU_MSDA_PREP", "xla").strip().lower()
if MSDA_PREP not in ("xla", "kernel", "fused"):
    raise ValueError(
        f"SPOTTER_TPU_MSDA_PREP must be xla|kernel|fused, got {MSDA_PREP!r}"
    )
if MSDA_SG and MSDA_PREP != "xla":
    # the loc-prep / fused-prologue kernels build their own hit logic (see
    # the SG guard at the MSDA_SG definition for why silent no-ops are
    # rejected)
    raise ValueError(
        "SPOTTER_TPU_MSDA_SG requires SPOTTER_TPU_MSDA_PREP=xla "
        "(the loc-prep/fused kernels do not implement subgroup hit bits)"
    )
if MSDA_NEST and MSDA_PREP != "xla":
    raise ValueError(
        "SPOTTER_TPU_MSDA_NEST requires SPOTTER_TPU_MSDA_PREP=xla "
        "(the loc-prep/fused kernels build their own corner chains)"
    )


def msda_prep_fused() -> bool:
    """True when the model layer should route deformable cross-attention
    through `deformable_sampling_fused` (SPOTTER_TPU_MSDA_PREP=fused): the
    sampling-offset / attention-weight projections + softmax + location
    arithmetic fold into the Pallas kernel's prologue, so the gather-heavy
    one-hot core runs as fewer, fatter dispatches (ISSUE 18 tentpole).
    Checked at trace time like the other knobs."""
    return MSDA_PREP == "fused"


def _note_flops(name: str, flops) -> None:
    """Report this dispatch's analytic FLOPs (the same formula handed to
    pl.CostEstimate) to the perf ledger's trace-time collector — XLA's
    cost_analysis counts pallas custom-calls as 0 FLOPs, so without this
    the MFU attribution under-reports every kernel-path program (ISSUE 18
    FLOPs honesty). Lazy import: obs must stay importable without jax."""
    from spotter_tpu.obs.perf import note_kernel_flops

    note_kernel_flops(name, flops)


def _onehot_merged_loc_kernel(
    mask_ref, xy_ref, attn_ref, v_ref, out_ref,
    *, level_tiles: tuple, level_dims: tuple, n_points: int, method: str, precision,
):
    qt, lp2 = xy_ref.shape[1], xy_ref.shape[2]
    lp = lp2 // 2
    i, nq = pl.program_id(0), pl.program_id(1)
    out_ref[0] = jnp.zeros_like(out_ref[0])

    step0 = 0
    v_off = 0
    for lvl, (ts, span) in enumerate(level_tiles):
        # per-level corner build with PYTHON-scalar dims (pallas kernels may
        # not capture trace-time array constants): ~45 VPU ops on a
        # (Q_TILE, P) block, once per grid cell per level
        lh, lw = level_dims[lvl]
        sl = slice(lvl * n_points, (lvl + 1) * n_points)
        corners = _corner_terms(
            xy_ref[0, :, sl],
            xy_ref[0, :, lp + lvl * n_points : lp + (lvl + 1) * n_points],
            attn_ref[0, :, sl],
            float(lw), float(lh), method,
        )
        for k in range(span):
            ns = step0 + k

            @pl.when(mask_ref[i, nq, ns] != 0)
            def _(k=k, ts=ts, lo=v_off, corners=corners):
                col = jax.lax.broadcasted_iota(jnp.int32, (qt, ts), 1) + (k * ts)
                oh = jnp.zeros((qt, ts), jnp.float32)
                for idxc, wgt in corners:
                    for p_ in range(idxc.shape[1]):
                        oh = oh + jnp.where(
                            col == idxc[:, p_ : p_ + 1], wgt[:, p_ : p_ + 1], 0.0
                        )
                acc = jnp.dot(
                    oh,
                    v_ref[0, lo + k * ts : lo + (k + 1) * ts].astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                    precision=precision,
                )
                out_ref[0] = out_ref[0] + acc.astype(out_ref.dtype)

        step0 += span
        v_off += ts * span


def _loc_ref(rows, xy, attn_cols, level_tiles, level_dims, n_points, method):
    """jnp reference of the loc-prep kernel (VJP + interpret parity):
    rows (BH, s_cat, hd), xy (BH, Qp, 2*LP), attn_cols (BH, Qp, LP) ->
    (BH, Qp, hd) fp32."""
    lp = attn_cols.shape[-1]
    w_const = jnp.asarray(
        np.repeat([float(w) for (_, w) in level_dims], n_points)[None, None, :],
        jnp.float32,
    )
    h_const = jnp.asarray(
        np.repeat([float(h) for (h, _) in level_dims], n_points)[None, None, :],
        jnp.float32,
    )
    corners = _corner_terms(
        xy[..., :lp], xy[..., lp:], attn_cols, w_const, h_const, method
    )
    offs_cat = np.concatenate(
        [[0], np.cumsum([ts * span for ts, span in level_tiles])[:-1]]
    ).astype(np.int32)
    lane_off = jnp.asarray(
        np.repeat(offs_cat, n_points)[None, None, :], jnp.int32
    )
    out = None
    for idxc, wgt in corners:
        g = jnp.take_along_axis(
            rows.astype(jnp.float32),
            (idxc + lane_off).reshape(rows.shape[0], -1, 1),
            axis=1,
        ).reshape(*idxc.shape, rows.shape[-1])
        term = (g * wgt[..., None]).sum(axis=2)
        out = term if out is None else out + term
    return out


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def pallas_onehot_sampling_merged_loc(
    rows, xy, attn_cols, mask,
    level_tiles: tuple, level_dims: tuple, n_points: int, method: str,
    interpret: bool = False,
):
    """Loc-prep merged kernel: corner decomposition happens in-kernel.

    rows: (BH, s_cat, hd) as in `pallas_onehot_sampling_merged`; xy:
    (BH, Qp, 2*LP) normalized sample coords, x lanes then y lanes, level-
    major points within each half; attn_cols: (BH, Qp, LP); mask as before.
    Padded query rows must carry zero attention (their corner weights then
    vanish regardless of where their zero coords land).
    """
    bh, s_cat, hd = rows.shape
    qp = xy.shape[1]
    level_tiles = tuple((int(t), int(s)) for t, s in level_tiles)
    level_dims = tuple((int(h), int(w)) for h, w in level_dims)
    n_s = sum(span for _, span in level_tiles)
    n_qt = qp // Q_TILE
    lp = attn_cols.shape[-1]
    assert sum(t * s for t, s in level_tiles) == s_cat, (level_tiles, s_cat)
    assert mask.shape[2] == n_s, (mask.shape, level_tiles)
    kernel = partial(
        _onehot_merged_loc_kernel,
        level_tiles=level_tiles,
        level_dims=level_dims,
        n_points=n_points,
        method=method,
        precision=MSDA_MXU_PRECISION,
    )
    jc = (1 if method == "discrete" else 4) * n_points
    flops = sum(
        2 * bh * span * (qp * ts * hd + jc * qp * ts) for ts, span in level_tiles
    )
    _note_flops("msda_onehot_merged_loc", flops)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n_qt),
        in_specs=[
            pl.BlockSpec(
                (1, Q_TILE, 2 * lp),
                lambda i, nq, *_: (i, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, Q_TILE, lp),
                lambda i, nq, *_: (i, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, s_cat, hd), lambda i, nq, *_: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, Q_TILE, hd), lambda i, nq, *_: (i, nq, 0),
            memory_space=pltpu.VMEM,
        ),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, qp, hd), jnp.float32),
        grid_spec=grid_spec,
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=rows.size * 4 + xy.size * 4 + attn_cols.size * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(mask, xy, attn_cols, rows)


def _loc_fwd(rows, xy, attn_cols, mask, level_tiles, level_dims, n_points, method, interpret):
    out = pallas_onehot_sampling_merged_loc(
        rows, xy, attn_cols, mask, level_tiles, level_dims, n_points, method, interpret
    )
    return out, (rows, xy, attn_cols)


def _loc_bwd(level_tiles, level_dims, n_points, method, interpret, res, g):
    rows, xy, attn_cols = res
    _, vjp = jax.vjp(
        lambda r, x, a: _loc_ref(r, x, a, level_tiles, level_dims, n_points, method),
        rows, xy, attn_cols,
    )
    d_rows, d_xy, d_attn = vjp(g)
    return d_rows.astype(rows.dtype), d_xy, d_attn, None


pallas_onehot_sampling_merged_loc.defvjp(_loc_fwd, _loc_bwd)


def _onehot_merged_fused_kernel(
    hs_ref, woff_ref, boff_ref, watt_ref, batt_ref, base_ref, scale_ref,
    v_ref, out_ref,
    *, level_tiles: tuple, level_dims: tuple, n_points: int, method: str,
    precision,
):
    """Fused-prologue variant of `_onehot_merged_loc_kernel`: the sampling-
    offset and attention-weight projections, the per-head softmax, and the
    location arithmetic all run in the kernel's prologue, so the op consumes
    raw decoder hidden states instead of precomputed coords.

    Per grid cell (bh, nq): two small MXU dots against this head's weight
    slices (hs_tile @ w_off -> offsets, hs_tile @ w_att -> logits), a
    row-softmax over the LP lanes, xy = base + offs * scale, then the same
    corner build + one-hot MXU walk as the loc kernel. The per-head split
    does no redundant projection work — the unfused Dense computes all H
    heads at once; here each grid cell computes exactly its own head's
    slice. The hit test is DYNAMIC (computed from the in-kernel corner
    indices) because sample locations do not exist outside the kernel.
    """
    qt = hs_ref.shape[1]
    lp = watt_ref.shape[2]
    out_ref[0] = jnp.zeros_like(out_ref[0])

    hs = hs_ref[0].astype(jnp.float32)  # (Q_TILE, D)
    offs = (
        jnp.dot(
            hs, woff_ref[0].astype(jnp.float32),
            preferred_element_type=jnp.float32, precision=precision,
        )
        + boff_ref[0].astype(jnp.float32)
    )
    xy = base_ref[0].astype(jnp.float32) + offs * scale_ref[0].astype(jnp.float32)
    logits = (
        jnp.dot(
            hs, watt_ref[0].astype(jnp.float32),
            preferred_element_type=jnp.float32, precision=precision,
        )
        + batt_ref[0].astype(jnp.float32)
    )
    logits = logits - jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits)
    at = e / jnp.sum(e, axis=-1, keepdims=True)  # (Q_TILE, LP)

    v_off = 0
    for lvl, (ts, span) in enumerate(level_tiles):
        lh, lw = level_dims[lvl]
        sl = slice(lvl * n_points, (lvl + 1) * n_points)
        corners = _corner_terms(
            xy[:, sl],
            xy[:, lp + lvl * n_points : lp + (lvl + 1) * n_points],
            at[:, sl],
            float(lw), float(lh), method,
        )
        # dynamic block-sparsity: a source tile is visited only if some
        # corner of some query in this Q_TILE lands in it (zero-weight
        # corners excluded — skipping them changes nothing)
        tiles_of = [jnp.where(wgt > 0, idxc // ts, -1) for idxc, wgt in corners]
        for k in range(span):
            hit = tiles_of[0] == k
            for t in tiles_of[1:]:
                hit = hit | (t == k)

            @pl.when(jnp.any(hit))
            def _(k=k, ts=ts, lo=v_off, corners=corners):
                col = jax.lax.broadcasted_iota(jnp.int32, (qt, ts), 1) + (k * ts)
                oh = jnp.zeros((qt, ts), jnp.float32)
                for idxc, wgt in corners:
                    for p_ in range(idxc.shape[1]):
                        oh = oh + jnp.where(
                            col == idxc[:, p_ : p_ + 1], wgt[:, p_ : p_ + 1], 0.0
                        )
                acc = jnp.dot(
                    oh,
                    v_ref[0, lo + k * ts : lo + (k + 1) * ts].astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                    precision=precision,
                )
                out_ref[0] = out_ref[0] + acc.astype(out_ref.dtype)

        v_off += ts * span


def _fused_ref(
    rows, hs, w_off, b_off, w_att, b_att, base, scale,
    level_tiles, level_dims, n_points, method,
):
    """jnp reference of the fused-prologue kernel (VJP + interpret parity):
    prologue in einsum form, core through `_loc_ref`. rows (BH, s_cat, hd),
    hs (B, Qp, D), w_off (H, D, 2*LP), b_off (H, 1, 2*LP), w_att (H, D, LP),
    b_att (H, 1, LP), base/scale (B, Qp, 2*LP) -> (BH, Qp, hd) fp32."""
    h_axis = w_off.shape[0]
    b, qp, _ = hs.shape
    lp = w_att.shape[-1]
    hs32 = hs.astype(jnp.float32)
    offs = (
        jnp.einsum("bqd,hdl->bhql", hs32, w_off.astype(jnp.float32))
        + b_off.astype(jnp.float32)[None]
    )
    xy = base[:, None] + offs * scale[:, None]  # (B, H, Qp, 2*LP)
    logits = (
        jnp.einsum("bqd,hdl->bhql", hs32, w_att.astype(jnp.float32))
        + b_att.astype(jnp.float32)[None]
    )
    at = jax.nn.softmax(logits, axis=-1)
    return _loc_ref(
        rows,
        xy.reshape(b * h_axis, qp, 2 * lp),
        at.reshape(b * h_axis, qp, lp),
        level_tiles, level_dims, n_points, method,
    )


@partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11, 12))
def pallas_msda_fused(
    rows, hs, w_off, b_off, w_att, b_att, base, scale,
    level_tiles: tuple, level_dims: tuple, n_points: int, method: str,
    interpret: bool = False,
):
    """Fused-prologue merged kernel (SPOTTER_TPU_MSDA_PREP=fused).

    rows: (BH, s_cat, hd) per-level-padded concatenation as in the other
    merged kernels; hs: (B, Qp, D) decoder hidden states (query + pos),
    zero-padded rows beyond the real query count; w_off/b_off, w_att/b_att:
    per-head weight slices pre-permuted by `deformable_sampling_fused` into
    the kernel's x-lanes-then-y-lanes layout; base/scale: (B, Qp, 2*LP)
    reference-point anchors so xy = base + (hs @ w_off + b_off) * scale.
    Padded query rows carry zero hs/base/scale: their coords collapse to 0
    (in-bounds, garbage-but-finite) and their output rows are discarded by
    the caller's [:, :q] slice; the VJP sees zero cotangent for them.
    """
    bh, s_cat, hd = rows.shape
    b, qp, d = hs.shape
    h_axis = w_off.shape[0]
    lp = w_att.shape[-1]
    level_tiles = tuple((int(t), int(s)) for t, s in level_tiles)
    level_dims = tuple((int(h), int(w)) for h, w in level_dims)
    n_qt = qp // Q_TILE
    assert bh == b * h_axis, (rows.shape, hs.shape, w_off.shape)
    assert sum(t * s for t, s in level_tiles) == s_cat, (level_tiles, s_cat)
    kernel = partial(
        _onehot_merged_fused_kernel,
        level_tiles=level_tiles,
        level_dims=level_dims,
        n_points=n_points,
        method=method,
        precision=MSDA_MXU_PRECISION,
    )
    jc = (1 if method == "discrete" else 4) * n_points
    flops = 2 * bh * qp * d * 3 * lp + sum(  # prologue dots + one-hot core
        2 * bh * span * (qp * ts * hd + jc * qp * ts) for ts, span in level_tiles
    )
    _note_flops("msda_fused", flops)
    h = h_axis  # python int, closed over by the index maps
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, qp, hd), jnp.float32),
        grid=(bh, n_qt),
        in_specs=[
            pl.BlockSpec(
                (1, Q_TILE, d), lambda i, nq: (i // h, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, d, 2 * lp), lambda i, nq: (i % h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, 2 * lp), lambda i, nq: (i % h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, d, lp), lambda i, nq: (i % h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, lp), lambda i, nq: (i % h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, Q_TILE, 2 * lp), lambda i, nq: (i // h, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, Q_TILE, 2 * lp), lambda i, nq: (i // h, nq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, s_cat, hd), lambda i, nq: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, Q_TILE, hd), lambda i, nq: (i, nq, 0),
            memory_space=pltpu.VMEM,
        ),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=(
                rows.size * 4
                + hs.size * 4 * h_axis  # each head re-reads the hs tile
                + (w_off.size + w_att.size) * 4 * n_qt
                + (base.size + scale.size) * 4 * h_axis
            ),
            transcendentals=bh * qp * lp,
        ),
        interpret=interpret,
    )(hs, w_off, b_off, w_att, b_att, base, scale, rows)


def _fused_fwd(
    rows, hs, w_off, b_off, w_att, b_att, base, scale,
    level_tiles, level_dims, n_points, method, interpret,
):
    out = pallas_msda_fused(
        rows, hs, w_off, b_off, w_att, b_att, base, scale,
        level_tiles, level_dims, n_points, method, interpret,
    )
    return out, (rows, hs, w_off, b_off, w_att, b_att, base, scale)


def _fused_bwd(level_tiles, level_dims, n_points, method, interpret, res, g):
    rows, hs, w_off, b_off, w_att, b_att, base, scale = res
    _, vjp = jax.vjp(
        lambda r, q_, wo, bo, wa, ba, bs, sc: _fused_ref(
            r, q_, wo, bo, wa, ba, bs, sc,
            level_tiles, level_dims, n_points, method,
        ),
        rows, hs, w_off, b_off, w_att, b_att, base, scale,
    )
    d_rows, d_hs, d_wo, d_bo, d_wa, d_ba, d_base, d_scale = vjp(g)
    return (
        d_rows.astype(rows.dtype), d_hs.astype(hs.dtype),
        d_wo.astype(w_off.dtype), d_bo.astype(b_off.dtype),
        d_wa.astype(w_att.dtype), d_ba.astype(b_att.dtype),
        d_base, d_scale,
    )


pallas_msda_fused.defvjp(_fused_fwd, _fused_bwd)


def deformable_sampling(
    value: jnp.ndarray,  # (B, S, H, hd)
    loc: jnp.ndarray,  # (B, Q, H, LP, 2) in [0, 1]
    attn: jnp.ndarray,  # (B, Q, H, LP)
    spatial_shapes: tuple[tuple[int, int], ...],
    num_points: int,
    method: str = "default",
    backend: str | None = None,
    interpret: bool | None = None,
    presorted: bool = False,
) -> jnp.ndarray:
    """Full MSDA core: returns (B, Q, H*hd) aggregated values.

    Backends (module docstring): "pallas" = gather-free one-hot MXU kernel
    (auto on TPU), "xla" = row-gather math (auto elsewhere, VJP reference),
    "pallas_gather" = experimental lane-gather kernel. `interpret=True`
    forces kernel interpret mode (CPU tests). `presorted=True` promises the
    queries already arrive ordered by `locality_sort_key` (see
    `presort_wanted`), so the kernel branches skip the in-op sort and the
    two q-row permutes; hit tables are still built from the actual indices,
    so a broken promise only costs sparsity, never correctness.
    """
    b, s, h_axis, hd = value.shape
    q = loc.shape[1]
    lp = loc.shape[3]

    chosen = msda_backend(backend, batch_heads=b * h_axis)
    if (MSDA_SG or MSDA_NEST) and backend is not None and chosen != "pallas":
        # Same contract as the import-time env guards (above, after the
        # MSDA_SG parse) but scoped to EXPLICIT per-call `backend=`
        # overrides, so e.g. bench_msda with SPOTTER_TPU_MSDA_SG=8
        # --backends pallas,pallas_sep cannot silently no-op the knobs and
        # record a wrong A/B conclusion. Auto resolution is NOT re-checked
        # here: the import-time guard already rejected hosts where auto
        # cannot mean pallas (ADVICE r5 #3 — the old resolved-backend check
        # aborted every CPU/GPU forward under exported knobs).
        raise ValueError(
            f"SPOTTER_TPU_MSDA_SG/NEST apply only to the merged one-hot "
            f"backend; this call's explicit backend={chosen!r} override "
            f"would silently ignore them"
        )
    interp = bool(interpret) if interpret is not None else False

    def locality_perm():
        """Quantized mean-sample-position sort key, y-major (source tiles
        are horizontal bands of each level's row-major span). Shared by both
        kernel backends so their tiling behavior can't desynchronize.
        (None, None) when MSDA_SORT is off or the caller presorted —
        callers skip the permutes entirely (the sort is a sparsity
        heuristic, never a correctness requirement)."""
        if presorted or not MSDA_SORT:
            return None, None
        mean_xy = loc.mean(axis=(2, 3))  # (B, Q, 2) in [0, 1]
        key = locality_sort_key(mean_xy)
        p = jnp.argsort(key, axis=1)  # (B, Q)
        return p, jnp.argsort(p, axis=1)

    def corner_idx_w():
        """Lazy XLA-side corner prep — (B, H, LP, Q) head-major layout.
        Skipped entirely by the backends that do their own decomposition
        (pallas_sep; pallas under MSDA_PREP=kernel)."""
        loc_t = loc.transpose(0, 2, 3, 1, 4)
        attn_t = attn.transpose(0, 2, 3, 1)
        return prepare_msda_gather(loc_t, attn_t, spatial_shapes, num_points, method)

    if chosen == "pallas_sep":
        # Separable bilinear kernel, one call per level (level-split as in
        # the one-hot kernel). Sorted queries make a Q_TILE of neighbors
        # touch few row bands, so the hit table prunes; the sort/unsort are
        # two Q-row permutes.
        perm, inv_perm = locality_perm()
        loc_s, attn_s = loc, attn
        if perm is not None:
            loc_s = jnp.take_along_axis(loc, perm[:, :, None, None, None], axis=1)
            attn_s = jnp.take_along_axis(attn, perm[:, :, None, None], axis=1)

        rows_all = value.transpose(0, 2, 1, 3).reshape(b * h_axis, s, hd)
        offs = _level_offsets(spatial_shapes)
        out = None
        for lvl, (lh, lw) in enumerate(spatial_shapes):
            part = _sep_level_dispatch(
                rows_all[:, offs[lvl] : offs[lvl] + lh * lw],
                loc_s[:, :, :, lvl * num_points : (lvl + 1) * num_points, :],
                attn_s[:, :, :, lvl * num_points : (lvl + 1) * num_points],
                lh,
                lw,
                method,
                interp,
            )
            out = part if out is None else out + part
        out = out.reshape(b, h_axis, q, hd)
        if inv_perm is not None:
            out = jnp.take_along_axis(out, inv_perm[:, None, :, None], axis=2)
        return out.transpose(0, 2, 1, 3).reshape(b, q, h_axis * hd)
    if chosen == "pallas":
        # Level-split: a sample only ever lands inside its own level's span
        # of the flat source (block-diagonal one-hot), so each per-level
        # kernel call compares its 4*P sample columns against that level's
        # positions only — a ~3x compare reduction vs one dense call (the
        # stride-8 level holds ~76% of positions but only 1/3 of samples).
        # Block-sparsity on top: queries sorted by spatial locality so a
        # Q_TILE of neighbors samples a narrow band of each level, and the
        # kernel skips (query-tile, source-tile) pairs with no hit.
        jc = 4 * lp
        qp = -(-q // Q_TILE) * Q_TILE
        perm, inv_perm = locality_perm()

        if MSDA_PREP == "kernel" and all(
            ((S_TILE0 if (lvl == 0 and S_TILE0) else S_TILE) % lw) == 0
            for lvl, (lh, lw) in enumerate(spatial_shapes)
        ):
            # In-kernel corner prep (module comment at MSDA_PREP): ship raw
            # coords + attention; the y-only hit table is exact for every
            # in-bounds corner because each level tile spans whole rows.
            loc_s, attn_s = loc, attn
            if perm is not None:
                loc_s = jnp.take_along_axis(loc, perm[:, :, None, None, None], axis=1)
                attn_s = jnp.take_along_axis(attn, perm[:, :, None, None], axis=1)
            loc_bh = loc_s.transpose(0, 2, 1, 3, 4).reshape(b * h_axis, q, lp, 2)
            xy = jnp.concatenate(
                [loc_bh[..., 0], loc_bh[..., 1]], axis=-1
            ).astype(jnp.float32)
            at_bh = (
                attn_s.transpose(0, 2, 1, 3)
                .reshape(b * h_axis, q, lp)
                .astype(jnp.float32)
            )
            if qp != q:  # padded queries: zero attention -> zero weights
                xy = jnp.pad(xy, ((0, 0), (0, qp - q), (0, 0)))
                at_bh = jnp.pad(at_bh, ((0, 0), (0, qp - q), (0, 0)))

            rows_all = value.transpose(0, 2, 1, 3).reshape(b * h_axis, s, hd)
            offs = _level_offsets(spatial_shapes)
            points = num_points
            n_qt = qp // Q_TILE
            ys_cols = xy[:, :, lp:]
            rows_cat, masks, tiles = [], [], []
            for lvl, (lh, lw) in enumerate(spatial_shapes):
                ts = S_TILE0 if (lvl == 0 and S_TILE0) else S_TILE
                s_l = lh * lw
                rows_l = rows_all[:, offs[lvl] : offs[lvl] + s_l]
                s_pad = -(-s_l // ts) * ts
                if s_pad != s_l:
                    rows_l = jnp.pad(rows_l, ((0, 0), (0, s_pad - s_l), (0, 0)))
                n_s = s_pad // ts
                rpt = ts // lw  # rows per tile (whole rows by the guard)
                y_l = ys_cols[:, :, lvl * points : (lvl + 1) * points]
                if method == "discrete":
                    cy = jnp.clip(
                        jnp.floor(y_l * lh + 0.5).astype(jnp.int32), 0, lh - 1
                    )
                    cand = [cy // rpt]
                else:
                    y0 = jnp.floor(y_l * lh - 0.5).astype(jnp.int32)
                    cand = [
                        jnp.where((y0 >= 0) & (y0 <= lh - 1), y0 // rpt, -1),
                        jnp.where(
                            (y0 + 1 >= 0) & (y0 + 1 <= lh - 1), (y0 + 1) // rpt, -1
                        ),
                    ]
                bands = jnp.concatenate(cand, axis=-1).reshape(
                    b * h_axis, n_qt, -1
                )
                mask = (
                    (bands[..., None] == jnp.arange(n_s, dtype=jnp.int32))
                    .any(axis=2)
                    .astype(jnp.int32)
                )
                rows_cat.append(rows_l)
                masks.append(mask)
                tiles.append((ts, n_s))
            out = pallas_onehot_sampling_merged_loc(
                jnp.concatenate(rows_cat, axis=1),
                xy,
                at_bh,
                jnp.concatenate(masks, axis=2),
                tuple(tiles),
                tuple(spatial_shapes),
                points,
                method,
                interp,
            )
            out = out[:, :q].reshape(b, h_axis, q, hd)
            if inv_perm is not None:
                out = jnp.take_along_axis(out, inv_perm[:, None, :, None], axis=2)
            return out.transpose(0, 2, 1, 3).reshape(b, q, h_axis * hd)

        idx, w = corner_idx_w()
        idx_q = idx.reshape(b, h_axis, 4, lp, q).transpose(0, 1, 4, 2, 3)
        w_q = w.reshape(b, h_axis, 4, lp, q).transpose(0, 1, 4, 2, 3)
        if perm is not None:
            psel = perm[:, None, :, None, None]
            idx_q = jnp.take_along_axis(idx_q, psel, axis=2)
            w_q = jnp.take_along_axis(w_q, psel, axis=2)
        idx_q = idx_q.reshape(b * h_axis, q, jc)
        w_q = w_q.reshape(b * h_axis, q, jc)
        if qp != q:  # padded queries: idx 0, weight 0 -> zero rows, no hits
            idx_q = jnp.pad(idx_q, ((0, 0), (0, qp - q), (0, 0)))
            w_q = jnp.pad(w_q, ((0, 0), (0, qp - q), (0, 0)))

        rows_all = value.transpose(0, 2, 1, 3).reshape(b * h_axis, s, hd)
        offs = _level_offsets(spatial_shapes)
        points = lp // len(spatial_shapes)
        n_qt = qp // Q_TILE
        # Per-level blocks, all feeding ONE merged pallas_call (launch
        # overhead per call is ~0.9 ms on v5e — one call per op, not per
        # level): each level's span padded to its OWN tile multiple and
        # concatenated, per-level idx/w stacked, hit masks concatenated
        # along the s-step axis. The first (densest, stride-8) level may
        # take a finer tile via SPOTTER_TPU_MSDA_STILE0: its rows-per-tile
        # footprint shrinks, cutting each hit's compare cost without
        # touching the coarser levels.
        rows_cat, idx_levels, w_levels, masks, tiles = [], [], [], [], []
        for lvl, (lh, lw) in enumerate(spatial_shapes):
            ts = S_TILE0 if (lvl == 0 and S_TILE0) else S_TILE
            s_l = lh * lw
            rows_l = rows_all[:, offs[lvl] : offs[lvl] + s_l]
            s_pad = -(-s_l // ts) * ts
            if s_pad != s_l:
                rows_l = jnp.pad(rows_l, ((0, 0), (0, s_pad - s_l), (0, 0)))
            cols = [
                c * lp + lvl * points + p for c in range(4) for p in range(points)
            ]
            # level-local indices; padded/invalid slots (global idx 0, w 0)
            # may go negative here — they simply never match a column.
            # (MSDA_NEST's sentinel rewrite happens INSIDE the kernel
            # wrapper's primal so the VJP residuals keep the true indices —
            # the gather-based backward must read the real corner cells
            # even for exactly-zero-weight corners, whose d_w feeds the
            # location gradient.)
            idx_l = idx_q[:, :, cols] - np.int32(offs[lvl])
            w_l = w_q[:, :, cols]
            # hit mask: which source tiles does each query tile touch?
            # Under MSDA_SG the mask is a BITFIELD: bit g set iff sublane
            # group g (queries [g*SG, (g+1)*SG)) has a corner in the tile;
            # "any bit set" keeps the same outer skip condition.
            n_s = s_pad // ts
            tile_of = jnp.where(w_l > 0, idx_l // ts, -1)  # (BH, Qp, JCl)
            hits = tile_of[..., None] == jnp.arange(n_s, dtype=jnp.int32)
            if MSDA_SG:
                n_g = Q_TILE // MSDA_SG
                hits_g = hits.reshape(
                    b * h_axis, n_qt, n_g, MSDA_SG, len(cols), n_s
                ).any(axis=(3, 4))
                bits = jnp.left_shift(
                    hits_g.astype(jnp.int32),
                    jnp.arange(n_g, dtype=jnp.int32)[None, None, :, None],
                )
                mask = bits.sum(axis=2)
            else:
                mask = (
                    hits.reshape(b * h_axis, n_qt, Q_TILE, len(cols), n_s)
                    .any(axis=(2, 3))
                    .astype(jnp.int32)
                )
            rows_cat.append(rows_l)
            idx_levels.append(idx_l)
            w_levels.append(w_l)
            masks.append(mask)
            tiles.append((ts, n_s))
        out = pallas_onehot_sampling_merged(
            jnp.concatenate(rows_cat, axis=1),
            jnp.stack(idx_levels, axis=1),
            jnp.stack(w_levels, axis=1),
            jnp.concatenate(masks, axis=2),
            tuple(tiles),
            interp,
        )
        out = out[:, :q].reshape(b, h_axis, q, hd)
        if inv_perm is not None:
            out = jnp.take_along_axis(out, inv_perm[:, None, :, None], axis=2)
        return out.transpose(0, 2, 1, 3).reshape(b, q, h_axis * hd)
    if chosen == "pallas_gather":
        idx, w = corner_idx_w()
        vt = value.transpose(0, 2, 3, 1)  # (B, H, hd, S): spatial on lanes
        out = pallas_deformable_sampling(vt, idx, w, lp, q, interp)
        # (B, H, hd, Q) -> (B, Q, H*hd)
        return out.transpose(0, 3, 1, 2).reshape(b, q, h_axis * hd)
    idx, w = corner_idx_w()
    rows = value.transpose(0, 2, 1, 3)  # (B, H, S, hd): row gathers for XLA
    out = _row_gather_weighted_sum(rows, idx, w, lp, q)  # (B, H, Q, hd)
    return out.transpose(0, 2, 1, 3).reshape(b, q, h_axis * hd)


def deformable_sampling_fused(
    value: jnp.ndarray,  # (B, S, H, hd)
    hs: jnp.ndarray,  # (B, Q, D) decoder hidden states (query + pos embed)
    reference_points: jnp.ndarray,  # (B, Q, 4) normalized cxcywh
    w_off: jnp.ndarray,  # (D, H*LP*2) sampling_offsets Dense kernel
    b_off: jnp.ndarray,  # (H*LP*2,)
    w_att: jnp.ndarray,  # (D, H*LP) attention_weights Dense kernel
    b_att: jnp.ndarray,  # (H*LP,)
    spatial_shapes: tuple[tuple[int, int], ...],
    num_points: int,
    offset_scale: float = 0.5,
    method: str = "default",
    backend: str | None = None,
    interpret: bool | None = None,
    presorted: bool = False,
) -> jnp.ndarray:
    """MSDA with the projection/softmax/location prologue fused into the
    kernel (SPOTTER_TPU_MSDA_PREP=fused): the model layer hands over raw
    hidden states + the offset/attention Dense params instead of computing
    offsets and attention weights in XLA. Returns (B, Q, H*hd).

    Weight layout contract: w_off/b_off and w_att/b_att arrive in the plain
    `nn.Dense` layout (the model declares them via `DenseParams` at the
    same param paths, so checkpoints are interchangeable with the unfused
    path); this wrapper pre-permutes them into per-head x-lanes-then-y-lanes
    slices once per trace — a cheap (D, H*LP*2) shuffle that XLA folds into
    the weight constant.

    There is no in-op locality sort on this path (sample locations do not
    exist before the kernel runs): callers that want sorted queries must
    presort (`presorted=True`, see `presort_wanted`). Non-pallas backends
    and CPU hosts fall back to the einsum prologue + `deformable_sampling`,
    which is also the VJP reference — so the fused path keeps the xla
    bit-parity contract of the other kernel backends.
    """
    b, s, h_axis, hd = value.shape
    q = hs.shape[1]
    d = hs.shape[2]
    lp = len(spatial_shapes) * num_points

    # nn.Dense layout -> per-head kernel layout (x lanes then y lanes,
    # level-major points within each half, matching the loc kernel's xy)
    w_off_h = (
        w_off.reshape(d, h_axis, lp, 2)
        .transpose(1, 0, 3, 2)
        .reshape(h_axis, d, 2 * lp)
    )
    b_off_h = b_off.reshape(h_axis, lp, 2).transpose(0, 2, 1).reshape(h_axis, 1, 2 * lp)
    w_att_h = w_att.reshape(d, h_axis, lp).transpose(1, 0, 2)
    b_att_h = b_att.reshape(h_axis, lp)[:, None, :]

    # reference-point anchors: xy = base + offs * scale, per lane
    ref_xy = reference_points[..., :2].astype(jnp.float32)
    ref_wh = reference_points[..., 2:].astype(jnp.float32)
    ps = np.float32(offset_scale / num_points)
    base = jnp.concatenate(
        [
            jnp.broadcast_to(ref_xy[..., 0:1], (b, q, lp)),
            jnp.broadcast_to(ref_xy[..., 1:2], (b, q, lp)),
        ],
        axis=-1,
    )
    scale = jnp.concatenate(
        [
            jnp.broadcast_to(ref_wh[..., 0:1] * ps, (b, q, lp)),
            jnp.broadcast_to(ref_wh[..., 1:2] * ps, (b, q, lp)),
        ],
        axis=-1,
    )

    chosen = msda_backend(backend, batch_heads=b * h_axis)
    if chosen != "pallas":
        # XLA prologue + whatever core `chosen` names. This branch IS the
        # reference numerics (`_fused_ref` computes the same einsums).
        hs32 = hs.astype(jnp.float32)
        offs = (
            jnp.einsum("bqd,hdl->bqhl", hs32, w_off_h.astype(jnp.float32))
            + b_off_h[:, 0][None, None]
        )
        xy = base[:, :, None, :] + offs * scale[:, :, None, :]
        logits = (
            jnp.einsum("bqd,hdl->bqhl", hs32, w_att_h.astype(jnp.float32))
            + b_att_h[:, 0][None, None]
        )
        attn = jax.nn.softmax(logits, axis=-1)
        loc = jnp.stack([xy[..., :lp], xy[..., lp:]], axis=-1)
        return deformable_sampling(
            value, loc, attn.astype(value.dtype), spatial_shapes, num_points,
            method=method, backend=backend, interpret=interpret,
            presorted=presorted,
        )

    interp = bool(interpret) if interpret is not None else False
    qp = -(-q // Q_TILE) * Q_TILE
    hs_p, base_p, scale_p = hs, base, scale
    if qp != q:  # padded queries: zero hs/base/scale -> discarded rows
        hs_p = jnp.pad(hs, ((0, 0), (0, qp - q), (0, 0)))
        base_p = jnp.pad(base, ((0, 0), (0, qp - q), (0, 0)))
        scale_p = jnp.pad(scale, ((0, 0), (0, qp - q), (0, 0)))

    rows_all = value.transpose(0, 2, 1, 3).reshape(b * h_axis, s, hd)
    offs_l = _level_offsets(spatial_shapes)
    rows_cat, tiles = [], []
    for lvl, (lh, lw) in enumerate(spatial_shapes):
        ts = S_TILE0 if (lvl == 0 and S_TILE0) else S_TILE
        s_l = lh * lw
        rows_l = rows_all[:, offs_l[lvl] : offs_l[lvl] + s_l]
        s_pad = -(-s_l // ts) * ts
        if s_pad != s_l:
            rows_l = jnp.pad(rows_l, ((0, 0), (0, s_pad - s_l), (0, 0)))
        rows_cat.append(rows_l)
        tiles.append((ts, s_pad // ts))
    out = pallas_msda_fused(
        jnp.concatenate(rows_cat, axis=1),
        hs_p, w_off_h, b_off_h, w_att_h, b_att_h, base_p, scale_p,
        tuple(tiles), tuple(spatial_shapes), num_points, method, interp,
    )
    out = out[:, :q].reshape(b, h_axis, q, hd)
    return out.transpose(0, 2, 1, 3).reshape(b, q, h_axis * hd)
