"""Fused open-vocab logit head for the OWL-ViT path (ISSUE 18 tentpole).

The unfused `OwlViTClassHead` tail is four elementwise/matmul HLOs with the
(B, P, Q) logits tensor materialized between them: per-patch L2 normalize,
cosine matmul against the text-query bank, learned per-patch (shift,
elu-scale) affine, and the NEG_INF padded-query mask. This module fuses all
four into one Pallas kernel so the logits tensor is produced exactly once,
already masked — the natural fused shape named by ROADMAP item 1.

Knob: `SPOTTER_TPU_OWL_FUSED` = auto|1|0 (default auto = on for TPU, off
elsewhere; `1` forces the kernel everywhere, auto-resolving interpret mode
off-TPU so CPU tests exercise the same code path). The dense0 / logit_shift
/ logit_scale projections stay in XLA — they are plain GEMMs XLA already
fuses well; the win is the (B, P, Q)-shaped tail.

Sharding: under the PR 13 tp partition rules the OWL-ViT heads are
replicated (their params are omitted from TRANSFORMER_TP_RULES), so every
input to this kernel arrives replicated and the pallas_call needs no
sharding annotations of its own.

Padded-query contract: query slots beyond the real count (lane padding to
128) get mask 0 and therefore NEG_INF logits — same value the reference
writes for caller-masked queries — so a padded slot can never win an
argmax over any real query (test-asserted).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(np.finfo(np.float32).min)
LANE = 128
P_TILE = 128  # patch rows per grid cell

OWL_FUSED = os.environ.get("SPOTTER_TPU_OWL_FUSED", "auto").strip().lower()
if OWL_FUSED not in ("auto", "1", "0"):
    raise ValueError(f"SPOTTER_TPU_OWL_FUSED must be auto|1|0, got {OWL_FUSED!r}")


def owl_fused_wanted() -> bool:
    """True when OwlViTClassHead should route through the fused kernel.
    Checked at trace time (module constant + backend), monkeypatchable in
    tests like the MSDA knobs."""
    if OWL_FUSED == "1":
        return True
    if OWL_FUSED == "0":
        return False
    return jax.default_backend() == "tpu"


def _class_logits_kernel(img_ref, qt_ref, ss_ref, qmask_ref, out_ref):
    x = img_ref[0].astype(jnp.float32)  # (P_TILE, Dt)
    n = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True)) + 1e-6
    xn = x / n
    logits = jnp.dot(
        xn, qt_ref[...].astype(jnp.float32), preferred_element_type=jnp.float32
    )  # (P_TILE, Qp)
    sh = ss_ref[0][:, 0:1].astype(jnp.float32)
    sc_raw = ss_ref[0][:, 1:2].astype(jnp.float32)
    # jax.nn.elu(x) + 1 == where(x > 0, x, expm1(x)) + 1, bit-for-bit
    sc = jnp.where(sc_raw > 0, sc_raw, jnp.expm1(sc_raw)) + 1.0
    out = (logits + sh) * sc
    out_ref[0] = jnp.where(qmask_ref[...] == 0.0, NEG_INF, out)


def _class_logits_ref(img, qt, ss, qmask):
    """jnp reference (VJP + interpret parity): same math as the kernel.
    img (B, Pp, Dt), qt (Dt, Qp) pre-normalized queries, ss (B, Pp, 2)
    raw (shift, scale) lanes, qmask (1, Qp) float 1=valid -> (B, Pp, Qp)."""
    x = img.astype(jnp.float32)
    xn = x / (jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True)) + 1e-6)
    logits = jnp.einsum("bpd,dq->bpq", xn, qt.astype(jnp.float32))
    sh = ss[..., 0:1].astype(jnp.float32)
    sc = jax.nn.elu(ss[..., 1:2].astype(jnp.float32)) + 1.0
    out = (logits + sh) * sc
    return jnp.where(qmask[:, None, :] == 0.0, NEG_INF, out)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def pallas_class_logits(img, qt, ss, qmask, interpret: bool = False):
    """Fused normalize + cosine-logit + affine + mask kernel.

    img: (B, Pp, Dt) raw dense0 output, patch rows padded to P_TILE (zero
    rows normalize to zero and their output is sliced off by the caller);
    qt: (Dt, Qp) pre-L2-normalized query bank, transposed, lane-padded with
    zero columns; ss: (B, Pp, 2) raw logit_shift/logit_scale lanes (elu
    applied in-kernel); qmask: (1, Qp) float, 0 for caller-masked AND
    lane-padded query slots -> those columns come out NEG_INF.
    """
    b, pp, dt = img.shape
    qp = qt.shape[1]
    n_pt = pp // P_TILE
    assert ss.shape == (b, pp, 2), (ss.shape, img.shape)
    assert qmask.shape == (1, qp), (qmask.shape, qt.shape)
    flops = 2 * b * pp * dt * qp + 5 * b * pp * (dt + qp)
    # XLA costs pallas custom-calls as 0 FLOPs; self-report for MFU honesty
    from spotter_tpu.obs.perf import note_kernel_flops

    note_kernel_flops("owl_class_logits", flops)
    return pl.pallas_call(
        _class_logits_kernel,
        out_shape=jax.ShapeDtypeStruct((b, pp, qp), jnp.float32),
        grid=(b, n_pt),
        in_specs=[
            pl.BlockSpec(
                (1, P_TILE, dt), lambda i, pt: (i, pt, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (dt, qp), lambda i, pt: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, P_TILE, 2), lambda i, pt: (i, pt, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, qp), lambda i, pt: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, P_TILE, qp), lambda i, pt: (i, pt, 0), memory_space=pltpu.VMEM
        ),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=img.size * 4 + qt.size * 4 * b + b * pp * qp * 4,
            transcendentals=2 * b * pp,  # rsqrt + expm1 per patch row
        ),
        interpret=interpret,
    )(img, qt, ss, qmask)


def _cl_fwd(img, qt, ss, qmask, interpret):
    out = pallas_class_logits(img, qt, ss, qmask, interpret)
    return out, (img, qt, ss, qmask)


def _cl_bwd(interpret, res, g):
    img, qt, ss, qmask = res
    # NEG_INF columns carry zero cotangent in any sane loss; the reference
    # where() kills their gradient regardless.
    _, vjp = jax.vjp(_class_logits_ref, img, qt, ss, qmask)
    d_img, d_qt, d_ss, d_qmask = vjp(g)
    return d_img.astype(img.dtype), d_qt.astype(qt.dtype), d_ss.astype(ss.dtype), d_qmask


pallas_class_logits.defvjp(_cl_fwd, _cl_bwd)


def fused_class_logits(
    img_cls: jnp.ndarray,  # (B, P, Dt) raw dense0 output (unnormalized)
    query_embeds: jnp.ndarray,  # (Q, Dt) pre-L2-normalized text queries
    shift: jnp.ndarray,  # (B, P) raw logit_shift
    scale_raw: jnp.ndarray,  # (B, P) raw logit_scale (pre-elu)
    query_mask: jnp.ndarray | None,  # (Q,) 1=valid, or None
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Pad/transpose prep + fused kernel; returns (B, P, Q) fp32 logits.

    `interpret=None` auto-resolves to interpret mode off-TPU, so forcing
    `SPOTTER_TPU_OWL_FUSED=1` on a CPU box runs the same kernel code path
    tier-1 certifies (matching the MSDA interpret convention).
    """
    b, p, dt = img_cls.shape
    q = query_embeds.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qp = -(-q // LANE) * LANE
    pp = -(-p // P_TILE) * P_TILE
    qt = query_embeds.astype(jnp.float32).T  # (Dt, Q)
    if qp != q:
        qt = jnp.pad(qt, ((0, 0), (0, qp - q)))
    mask = (
        jnp.ones((q,), jnp.float32)
        if query_mask is None
        else (query_mask != 0).astype(jnp.float32)
    )
    mask = jnp.pad(mask, (0, qp - q))[None] if qp != q else mask[None]
    ss = jnp.stack([shift, scale_raw], axis=-1)  # (B, P, 2)
    img = img_cls
    if pp != p:
        img = jnp.pad(img, ((0, 0), (0, pp - p), (0, 0)))
        ss = jnp.pad(ss, ((0, 0), (0, pp - p), (0, 0)))
    out = pallas_class_logits(img, qt, ss, mask, bool(interpret))
    return out[:, :p, :q]
