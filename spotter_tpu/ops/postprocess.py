"""Detection postprocess in jnp — fixed output shapes, jit/TPU friendly.

Replaces the reference's torch `post_process_object_detection(threshold=0.5, ...)`
call (apps/spotter/src/spotter/serve.py:102-109). On TPU, thresholding would make
output shapes data-dependent, so the device side always returns fixed-k
(scores, labels, boxes) tensors; the host converts to thresholded Python lists
(`to_detections`), preserving the reference's observable behavior.

Three device-side variants cover the model families in scope:
- sigmoid top-k over (query, class)   — RT-DETR / RT-DETRv2 (focal-loss heads)
- softmax per query, no-object drop   — DETR, YOLOS
- sigmoid max over text queries       — OWL-ViT (open-vocabulary)
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spotter_tpu.ops.boxes import center_to_corners, scale_boxes
from spotter_tpu.ops.topk import top_k as fast_top_k


@partial(jax.jit, static_argnames=("k",))
def sigmoid_topk_postprocess(
    logits: jnp.ndarray,
    pred_boxes: jnp.ndarray,
    target_sizes: jnp.ndarray,
    k: int = 300,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """RT-DETR-style postprocess.

    logits: (B, Q, C) raw class logits; pred_boxes: (B, Q, 4) normalized cxcywh;
    target_sizes: (B, 2) [h, w]. Returns scores (B, k), labels (B, k), boxes
    (B, k, 4) xyxy pixels — top-k over the flattened (query, class) axis, the
    NMS-free selection RT-DETR uses.
    """
    b, q, c = logits.shape
    scores = jax.nn.sigmoid(logits).reshape(b, q * c)
    # ops/topk.py: lax.top_k by default, SPOTTER_TPU_TOPK=bisect opt-in
    top_scores, top_idx = fast_top_k(scores, k)
    labels = top_idx % c
    query_idx = top_idx // c
    boxes = jnp.take_along_axis(pred_boxes, query_idx[..., None], axis=1)
    boxes = center_to_corners(boxes)
    boxes = scale_boxes(boxes, target_sizes.astype(boxes.dtype))
    return top_scores, labels, boxes


@jax.jit
def softmax_postprocess(
    logits: jnp.ndarray,
    pred_boxes: jnp.ndarray,
    target_sizes: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """DETR/YOLOS-style postprocess.

    The final class is "no object" and is dropped before the per-query argmax.
    Returns scores (B, Q), labels (B, Q), boxes (B, Q, 4) xyxy pixels.
    """
    probs = jax.nn.softmax(logits, axis=-1)[..., :-1]
    scores = probs.max(axis=-1)
    labels = probs.argmax(axis=-1)
    boxes = center_to_corners(pred_boxes)
    boxes = scale_boxes(boxes, target_sizes.astype(boxes.dtype))
    return scores, labels, boxes


@jax.jit
def sigmoid_max_postprocess(
    logits: jnp.ndarray,
    pred_boxes: jnp.ndarray,
    target_sizes: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """OWL-ViT-style postprocess: per-query sigmoid max over text-query classes."""
    probs = jax.nn.sigmoid(logits)
    scores = probs.max(axis=-1)
    labels = probs.argmax(axis=-1)
    boxes = center_to_corners(pred_boxes)
    boxes = scale_boxes(boxes, target_sizes.astype(boxes.dtype))
    return scores, labels, boxes


def to_detections(
    scores: np.ndarray | jnp.ndarray,
    labels: np.ndarray | jnp.ndarray,
    boxes: np.ndarray | jnp.ndarray,
    id2label: dict[int, str],
    threshold: float = 0.5,
) -> list[dict]:
    """Host-side: one image's fixed-k device output -> thresholded detections.

    Matches the observable result of the reference's threshold=0.5 filter + id2label
    lookup (serve.py:102-114): a list of {"label": str, "score": float,
    "box": [xmin, ymin, xmax, ymax]} dicts.
    """
    scores = np.asarray(scores)
    labels = np.asarray(labels)
    boxes = np.asarray(boxes)
    keep = scores > threshold
    return [
        {
            "label": id2label[int(lbl)],
            "score": float(s),
            "box": [float(v) for v in box],
        }
        for s, lbl, box in zip(scores[keep], labels[keep], boxes[keep])
    ]
