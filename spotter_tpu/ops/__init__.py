from spotter_tpu.ops import boxes, postprocess, preprocess  # noqa: F401
