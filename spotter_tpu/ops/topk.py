"""Exact top-k without the big sort — TPU radix-bisect selection.

Computes the IDENTICAL result to `jax.lax.top_k` (values sorted descending,
ties by lower index — the documented lax.top_k contract) from three pieces:

1. radix bisection of the k-th largest value: 32 monotone-key threshold
   counts (compare + row-sum over (B, S), one per bit) instead of a sort —
   the float-to-ordered-uint trick makes bitwise binary search exact;
2. mask compaction: the selected positions' indices scatter into k slots by
   their prefix-sum rank (index order == lax.top_k's tie order);
3. a final k-element lax.top_k to produce score-descending order — tiny
   (k x k) compared to the S-wide sort it replaces.

NaN caveat: the monotone key orders NaN above +inf (sign-magnitude view)
instead of lax.top_k's NaN semantics; detection scores are finite logits.

Measured (v5e via tunnel, loop-in-jit, (8, 8400) k=300): lax.top_k
0.51 ms/iter vs bisect 0.94 ms/iter — the compaction scatter + cumsums cost
more than XLA's sort at these shapes, so `auto` keeps lax everywhere and
bisect stays an opt-in for re-evaluation at wider S or larger batch
(threshold search alone is 0.52 ms and scales O(S) vs the sort's
O(S log S)).

`SPOTTER_TPU_TOPK` = auto (currently always lax) | lax | bisect.
"""

import os

import jax
import jax.numpy as jnp

TOPK_ENV = "SPOTTER_TPU_TOPK"


def _mode() -> str:
    name = os.environ.get(TOPK_ENV, "auto").strip().lower()
    if name not in ("auto", "lax", "bisect"):
        raise ValueError(f"{TOPK_ENV} must be auto|lax|bisect, got {name!r}")
    return name


def _ordered_key(x: jnp.ndarray) -> jnp.ndarray:
    """Monotone float32 -> uint32 map: a > b  <=>  key(a) > key(b)."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    neg = bits >= jnp.uint32(0x80000000)
    return jnp.where(neg, ~bits, bits | jnp.uint32(0x80000000))


def bisect_top_k(scores: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(B, S) scores -> (values (B, k) desc, indices (B, k) int32); exact
    lax.top_k semantics (see module docstring for the NaN caveat)."""
    b, s = scores.shape
    if k >= s:
        return jax.lax.top_k(scores, k)
    scores_f = scores.astype(jnp.float32)
    key = _ordered_key(scores_f)

    # radix-select the k-th largest key: build the threshold MSB-first
    def body(i, t):
        cand = t | (jnp.uint32(1) << (31 - i))
        cnt = (key >= cand[:, None]).sum(axis=1)
        return jnp.where(cnt >= k, cand, t)

    kth = jax.lax.fori_loop(0, 32, body, jnp.zeros((b,), jnp.uint32))

    gt = key > kth[:, None]
    eq = key == kth[:, None]
    need = k - gt.sum(axis=1, keepdims=True)
    sel = gt | (eq & (jnp.cumsum(eq, axis=1) <= need))

    # compact selected indices into k slots in ascending-index order
    rank = jnp.cumsum(sel, axis=1)  # 1-based among selected
    pos = jnp.where(sel, rank - 1, k)  # unselected -> trash slot k
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, s))
    sidx = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    idx_by_index = (
        jnp.zeros((b, k + 1), jnp.int32).at[bidx, pos].set(sidx, mode="drop")[:, :k]
    )

    # order the k winners by score; the stable small sort keeps lower-index
    # ties first because idx_by_index is ascending
    vals = jnp.take_along_axis(scores_f, idx_by_index, axis=1)
    vals_sorted, order = jax.lax.top_k(vals, k)
    idx_sorted = jnp.take_along_axis(idx_by_index, order, axis=1)
    return vals_sorted.astype(scores.dtype), idx_sorted


def top_k(scores: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in lax.top_k for 2-D (B, S); SPOTTER_TPU_TOPK=bisect opts into
    the radix path (measured slower at R101 shapes — module docstring)."""
    if _mode() == "bisect":
        return bisect_top_k(scores, k)
    return jax.lax.top_k(scores, k)
