"""Text-embedding cache for open-vocabulary detection (ISSUE 13).

OWL-ViT's text tower is the expensive half of an open-vocab request that the
closed-set serving path never pays: at ViT-L scale one vocabulary encode is
tens of milliseconds of device time. Vocabularies repeat heavily (a tenant
reuses its label set on every image), so the resolver memoizes encoded query
sets keyed `model|sha256(sorted queries)` (caching/keys.py) — a repeated
vocabulary costs one dict lookup, and the bench's text-cache hit p50 vs miss
p50 is the measured proof.

The cached value is a `QuerySet`: labels in canonical (sorted) order, the
normalized (Q_pad, proj) embedding matrix PADDED to a bucketed query count
(`SPOTTER_TPU_QUERY_PAD`, default 8) with a validity mask, so the number of
compiled engine programs is bounded by distinct PAD MULTIPLES, not distinct
vocabulary sizes. `QuerySet.key` doubles as the scheduler's batch-group id:
the engine forward is shape- and constant-specialized per query set, so the
batcher must never mix two vocabularies into one dispatch.

Thread-safe like ResultCache (resolve runs in an executor off the event
loop); entry count is bounded (`SPOTTER_TPU_TEXT_CACHE_ENTRIES`, LRU).
"""

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from spotter_tpu.caching.keys import queries_digest, queries_key

QUERY_PAD_ENV = "SPOTTER_TPU_QUERY_PAD"
DEFAULT_QUERY_PAD = 8
TEXT_CACHE_ENTRIES_ENV = "SPOTTER_TPU_TEXT_CACHE_ENTRIES"
DEFAULT_TEXT_CACHE_ENTRIES = 256


@dataclass(frozen=True)
class QuerySet:
    """One resolved open-vocabulary query set, engine-ready.

    `embeds` is (Q_pad, proj) float32 with rows past `len(labels)` zeroed;
    `mask` is (Q_pad,) int32 1=real query. Padded slots carry NEG_INF logits
    through the class head, so they can never win the per-patch argmax.
    """

    key: str  # queries_key(model, queries) — also the scheduler group id
    digest: str  # sha256 over the sorted queries (result-cache key suffix)
    labels: tuple  # canonical sorted query strings, index == label id
    embeds: np.ndarray
    mask: np.ndarray

    @property
    def id2label(self) -> dict[int, str]:
        return dict(enumerate(self.labels))


def query_pad() -> int:
    raw = os.environ.get(QUERY_PAD_ENV, "").strip()
    try:
        pad = int(raw) if raw else DEFAULT_QUERY_PAD
    except ValueError:
        raise ValueError(f"{QUERY_PAD_ENV} must be an integer, got {raw!r}")
    return max(1, pad)


class TextQueryResolver:
    """queries -> QuerySet through the memoized text encoder.

    `encoder` is `BuiltDetector.text_encoder` (list[str] -> (Q, proj)
    float32). `metrics` (engine Metrics) gets hit/miss counts and encode
    wall times so the cache's win is observable in /metrics and the bench.
    """

    def __init__(
        self,
        model_name: str,
        encoder: Callable,
        metrics=None,
        max_entries: Optional[int] = None,
        pad: Optional[int] = None,
    ) -> None:
        self.model_name = model_name
        self.encoder = encoder
        self.metrics = metrics
        if max_entries is None:
            raw = os.environ.get(TEXT_CACHE_ENTRIES_ENV, "").strip()
            max_entries = int(raw) if raw else DEFAULT_TEXT_CACHE_ENTRIES
        self.max_entries = max(1, max_entries)
        self.pad = pad if pad is not None else query_pad()
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, QuerySet] = OrderedDict()

    def resolve(self, queries) -> QuerySet:
        """The memoized encode. Raises ValueError on an empty query set.

        Holding the lock across the encode serializes concurrent misses for
        DIFFERENT keys too — deliberate: the encoder runs the model's text
        tower, and two towers racing on one device buys nothing. A hit
        never waits on an in-flight miss's device time beyond the lock.
        """
        t0 = time.monotonic()
        labels = tuple(sorted(str(q).strip() for q in queries if str(q).strip()))
        if not labels:
            raise ValueError("queries must contain at least one non-empty string")
        key = queries_key(self.model_name, labels)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self._record(True, (time.monotonic() - t0) * 1000.0)
                return entry
            embeds = np.asarray(self.encoder(list(labels)), np.float32)
            q, d = embeds.shape
            q_pad = -(-q // self.pad) * self.pad
            padded = np.zeros((q_pad, d), np.float32)
            padded[:q] = embeds
            mask = np.zeros((q_pad,), np.int32)
            mask[:q] = 1
            entry = QuerySet(
                key=key,
                digest=queries_digest(labels),
                labels=labels,
                embeds=padded,
                mask=mask,
            )
            self._entries[key] = entry
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            self._record(False, (time.monotonic() - t0) * 1000.0)
            return entry

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                "query_pad": self.pad,
            }

    def _record(self, hit: bool, encode_ms: Optional[float]) -> None:
        if self.metrics is not None:
            try:
                self.metrics.record_text_cache(hit, encode_ms)
            except Exception:
                pass
