"""Content-addressed result cache + single-flight coalescing (ISSUE 5).

Real spotter traffic (amenity detection over listing-photo URLs) is heavily
duplicated, and the RT-DETR serving path is deterministic per
(model, image bytes, threshold) — so memoization in front of the engine is
exact, not approximate (DeepServe makes the same argument for serverless
LLM serving, PAPERS.md). Two cooperating pieces:

- `singleflight.SingleFlight` — async in-flight coalescing: N concurrent
  calls for the same key share ONE underlying flight, with per-waiter
  deadline/cancellation semantics (one waiter's expiry never fails the
  shared flight).
- `result_cache.ResultCache` — content-addressed LRU over post-processed
  detections (tiny — never tensors), keyed on
  (model, sha256(image bytes), threshold bucket), with TTL + byte budget
  and a short-TTL negative cache for deterministic failures.

The whole tier is opt-in: `SPOTTER_TPU_CACHE_MAX_MB=0` (the default)
disables it entirely and the serving path is bit-identical to a build
without this package.

Import-light on purpose (lazy, PEP 562): nothing here pulls in jax, so the
supervisor/router processes can keep importing serving modules cheaply.
"""

_EXPORTS = {
    "SingleFlight": "spotter_tpu.caching.singleflight",
    "ResultCache": "spotter_tpu.caching.result_cache",
    "CACHE_MAX_MB_ENV": "spotter_tpu.caching.result_cache",
    "CACHE_TTL_ENV": "spotter_tpu.caching.result_cache",
    "CACHE_NEGATIVE_TTL_ENV": "spotter_tpu.caching.result_cache",
    "CACHE_ANNOTATED_ENV": "spotter_tpu.caching.result_cache",
    # the ONE key-normalization module (ISSUE 11): edge affinity keys and
    # replica cache keys both come from here so they can never drift
    "content_key": "spotter_tpu.caching.keys",
    "url_key": "spotter_tpu.caching.keys",
    "affinity_key": "spotter_tpu.caching.keys",
    "normalize_url": "spotter_tpu.caching.keys",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
