"""The ONE key-normalization module for the data plane (ISSUE 11).

Three layers hash the same identifiers and must never drift:

- the replica's content-addressed result cache keys detections on
  `(model, sha256(bytes), threshold bucket)` and its negative cache keys
  deterministic fetch failures on the URL (`url|<url>`);
- the edge router's rendezvous ring hashes the URL to pick the replica
  whose cache already holds that URL's result;
- the edge's negative verdict table is keyed by the same URL string the
  replica used when it recorded the verdict.

If the edge normalized a URL differently from the replica — trailing
whitespace handled on one side only, say — affinity would silently route
same-key requests to different owners and the fleet hit rate would decay
back toward 1/N, which is exactly the failure mode this PR exists to kill.
So every key derivation lives here, the result cache and the router both
import it, and tests/test_ring.py pins `url_key == "url|" + affinity_key`.

Normalization is deliberately conservative: the replica caches under the
URL string it was asked to fetch, so the edge must hash the SAME string —
anything cleverer (case-folding hosts, dropping default ports) would make
the edge's notion of "same URL" broader than the replica's and break the
affinity == cache-key invariant this module pins.
"""

import hashlib


def normalize_url(url: str) -> str:
    """Canonical URL string for keying: whitespace-stripped, otherwise the
    exact string the replica will fetch (see module docstring for why no
    deeper canonicalization)."""
    return url.strip()


def affinity_key(url: str) -> str:
    """The edge router's rendezvous-hash key for a URL. By construction the
    replica's negative-cache key for the same URL is `"url|" + this`."""
    return normalize_url(url)


def url_key(url: str) -> str:
    """Negative-cache key for a deterministic fetch failure (content
    unknown — the URL is the only identity we have)."""
    return f"url|{normalize_url(url)}"


def queries_digest(queries) -> str:
    """Order-insensitive digest of an open-vocabulary query set: the text
    cache and the result-cache key suffix both key on sha256 over the SORTED
    queries, so ["dog", "couch"] and ["couch", "dog"] are one vocabulary."""
    joined = "\x1f".join(sorted(str(q) for q in queries))
    return hashlib.sha256(joined.encode("utf-8")).hexdigest()


def queries_key(model_name: str, queries) -> str:
    """Text-embedding cache key: `model|sha256(sorted queries)` (ISSUE 13)."""
    return f"{model_name}|{queries_digest(queries)}"


def content_key(model_name: str, image_bytes: bytes, threshold: float) -> str:
    """The content-addressed key: model + sha256(bytes) + threshold bucket.

    The threshold is bucketed to 2 decimals so float formatting noise can't
    split otherwise-identical deployments into disjoint key spaces.
    """
    digest = hashlib.sha256(image_bytes).hexdigest()
    return f"{model_name}|{digest}|t{threshold:.2f}"
