"""Content-addressed LRU result cache with TTL, byte budget, negative cache.

Stores POST-PROCESSED detections (a handful of label/score/box dicts —
tens of bytes) keyed on `(model_name, sha256(image bytes), threshold
bucket)`; never tensors, so a generous entry count fits in a few MB and a
hit costs a dict lookup, not an engine pass. Deterministic failures
(non-retryable 4xx `FetchError`, `PoisonImageError`) go to a short-TTL
negative cache so a repeat poison skips the fetch/bisect machinery instead
of re-poisoning a batch.

What is NEVER cached (enforced by the fill sites — the detector's fetch
flight and the batcher's keyed-completion callback — which only ever pass
the classes below in):
- 5xx / 429 / timeouts / connect errors — retryable, the next attempt may
  succeed;
- admission sheds (queue full, breaker open, draining) — load state, not a
  property of the image;
- fatal/transient engine errors (device lost, OOM) — the degraded-dp
  rebuild must retry them, not serve a stale verdict.

Knobs: `SPOTTER_TPU_CACHE_MAX_MB` (byte budget; 0 — the default — disables
the whole tier), `SPOTTER_TPU_CACHE_TTL_S`, `SPOTTER_TPU_CACHE_NEGATIVE_TTL_S`.

Thread-safe (a lock around the OrderedDicts): lookups and fills happen on
the event loop, but /metrics snapshots and tests touch it from other
threads. Cache faults injected via `testing.faults` (`cache_error=N`) are
CONTAINED here — a broken cache degrades to a miss / skipped fill, never to
a failed request.
"""

import hashlib
import json
import logging
import threading
import time
from collections import OrderedDict
from typing import Optional

from spotter_tpu.serving.resilience import _env_float
from spotter_tpu.testing import faults

logger = logging.getLogger(__name__)

CACHE_MAX_MB_ENV = "SPOTTER_TPU_CACHE_MAX_MB"
CACHE_TTL_ENV = "SPOTTER_TPU_CACHE_TTL_S"
CACHE_NEGATIVE_TTL_ENV = "SPOTTER_TPU_CACHE_NEGATIVE_TTL_S"

DEFAULT_CACHE_MAX_MB = 0.0  # disabled: caching is an explicit deployment opt-in
DEFAULT_CACHE_TTL_S = 600.0
DEFAULT_CACHE_NEGATIVE_TTL_S = 30.0
# negative entries are bounded by count (they carry an exception, not
# detections, so the byte budget is the wrong ruler)
MAX_NEGATIVE_ENTRIES = 4096


def content_key(model_name: str, image_bytes: bytes, threshold: float) -> str:
    """The content-addressed key: model + sha256(bytes) + threshold bucket.

    The threshold is bucketed to 2 decimals so float formatting noise can't
    split otherwise-identical deployments into disjoint key spaces.
    """
    digest = hashlib.sha256(image_bytes).hexdigest()
    return f"{model_name}|{digest}|t{threshold:.2f}"


def url_key(url: str) -> str:
    """Negative-cache key for a deterministic fetch failure (content unknown)."""
    return f"url|{url}"


class ResultCache:
    """LRU + TTL + byte budget over tiny detection lists, with a sidecar
    negative cache for deterministic failures."""

    def __init__(
        self,
        max_bytes: int,
        ttl_s: float = DEFAULT_CACHE_TTL_S,
        negative_ttl_s: float = DEFAULT_CACHE_NEGATIVE_TTL_S,
        metrics=None,
        clock=time.monotonic,
    ) -> None:
        self.max_bytes = int(max_bytes)
        self.ttl_s = ttl_s
        self.negative_ttl_s = negative_ttl_s
        self.metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        # key -> (detections, nbytes, expires_at)
        self._entries: OrderedDict[str, tuple[list, int, float]] = OrderedDict()
        # key -> (exception, expires_at)
        self._negative: OrderedDict[str, tuple[BaseException, float]] = OrderedDict()
        self._bytes = 0

    @classmethod
    def from_env(cls, metrics=None, max_mb: Optional[float] = None) -> Optional["ResultCache"]:
        """The serving wiring: an armed cache, or None when the tier is off
        (`SPOTTER_TPU_CACHE_MAX_MB` unset or <= 0) — None means every caller
        takes the exact pre-cache code path, bit-identical to today.
        `max_mb` (the `--cache-mb` flag) overrides the env budget; the TTL
        knobs are read from the env either way."""
        if max_mb is None:
            max_mb = _env_float(CACHE_MAX_MB_ENV, DEFAULT_CACHE_MAX_MB)
        if max_mb <= 0:
            return None
        return cls(
            max_bytes=int(max_mb * 1024 * 1024),
            ttl_s=_env_float(CACHE_TTL_ENV, DEFAULT_CACHE_TTL_S),
            negative_ttl_s=_env_float(
                CACHE_NEGATIVE_TTL_ENV, DEFAULT_CACHE_NEGATIVE_TTL_S
            ),
            metrics=metrics,
        )

    # -- positive entries ----------------------------------------------------

    def get(self, key: str) -> Optional[list]:
        """Detections for `key`, or None. Counts a hit/miss; returns a COPY
        of the stored list so no two requests share mutable state."""
        return self.get_entry(key)[0]

    def get_entry(self, key: str, stale_ok: bool = False) -> tuple[Optional[list], bool]:
        """(detections, is_stale) for `key`, or (None, False).

        `stale_ok=True` (the brownout serve-stale rung, ISSUE 8) makes an
        expired-TTL entry acceptable: it is returned with `is_stale=True`
        and KEPT (the brownout may clear before the next request; the LRU/
        byte budget still bounds it) instead of dropped. The fresh path is
        unchanged: expired entries are dropped and miss.
        """
        try:
            faults.on_cache("get", key)
            with self._lock:
                entry = self._entries.get(key)
                stale = entry is not None and entry[2] <= self._clock()
                if stale and not stale_ok:
                    self._drop(key)
                    entry = None
                    stale = False
                if entry is None:
                    self._record("record_cache_miss")
                    return None, False
                self._entries.move_to_end(key)
                self._record("record_cache_hit")
                if stale:
                    self._record("record_stale_served")
                return [dict(d) for d in entry[0]], stale
        except Exception:
            logger.exception("result cache get(%s) failed; treating as miss", key)
            self._record("record_cache_miss")
            return None, False

    def put(self, key: str, detections: list) -> None:
        """Fill (idempotent; last writer wins). Oversized values — bigger
        than the whole budget — are not stored."""
        try:
            faults.on_cache("put", key)
            nbytes = self._estimate_nbytes(key, detections)
            if nbytes > self.max_bytes:
                return
            value = [dict(d) for d in detections]
            with self._lock:
                if key in self._entries:
                    self._drop(key)
                self._entries[key] = (value, nbytes, self._clock() + self.ttl_s)
                self._bytes += nbytes
                evicted = 0
                while self._bytes > self.max_bytes and self._entries:
                    oldest = next(iter(self._entries))
                    self._drop(oldest)
                    evicted += 1
                if evicted and self.metrics is not None:
                    self.metrics.record_cache_eviction(evicted)
                self._publish_size()
        except Exception:
            logger.exception("result cache put(%s) failed; skipping fill", key)

    # -- negative entries ----------------------------------------------------

    def get_negative(self, key: str) -> Optional[BaseException]:
        """The cached deterministic failure for `key`, or None. The caller
        re-raises it; expiry means the next attempt really retries."""
        try:
            faults.on_cache("get_negative", key)
            with self._lock:
                entry = self._negative.get(key)
                if entry is None:
                    return None
                if entry[1] <= self._clock():
                    del self._negative[key]
                    return None
                self._negative.move_to_end(key)
                self._record("record_cache_negative_hit")
                return entry[0]
        except Exception:
            logger.exception(
                "result cache get_negative(%s) failed; treating as miss", key
            )
            return None

    def put_negative(self, key: str, exc: BaseException) -> None:
        try:
            faults.on_cache("put_negative", key)
            with self._lock:
                self._negative[key] = (exc, self._clock() + self.negative_ttl_s)
                self._negative.move_to_end(key)
                while len(self._negative) > MAX_NEGATIVE_ENTRIES:
                    self._negative.popitem(last=False)
        except Exception:
            logger.exception("result cache put_negative(%s) failed; skipping", key)

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """/healthz-shaped snapshot of the cache's size state."""
        with self._lock:
            return {
                "enabled": True,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "negative_entries": len(self._negative),
                "ttl_s": self.ttl_s,
                "negative_ttl_s": self.negative_ttl_s,
            }

    # -- internals (callers hold the lock where noted) -----------------------

    def _drop(self, key: str) -> None:
        # caller holds the lock
        value = self._entries.pop(key, None)
        if value is not None:
            self._bytes -= value[1]

    def _publish_size(self) -> None:
        # caller holds the lock
        if self.metrics is not None:
            self.metrics.set_cache_size(len(self._entries), self._bytes)

    def _record(self, method: str) -> None:
        if self.metrics is not None:
            getattr(self.metrics, method)()

    @staticmethod
    def _estimate_nbytes(key: str, detections: list) -> int:
        # detections are tiny JSON-shaped dicts (label/score/box); the JSON
        # encoding is an honest, deterministic size proxy for the budget
        try:
            payload = len(json.dumps(detections))
        except (TypeError, ValueError):
            payload = len(repr(detections))
        return len(key) + payload + 96  # + OrderedDict/tuple overhead
