"""Content-addressed LRU result cache with TTL, byte budget, negative cache.

Stores POST-PROCESSED detections (a handful of label/score/box dicts —
tens of bytes) keyed on `(model_name, sha256(image bytes), threshold
bucket)`; never tensors, so a generous entry count fits in a few MB and a
hit costs a dict lookup, not an engine pass. Deterministic failures
(non-retryable 4xx `FetchError`, `PoisonImageError`) go to a short-TTL
negative cache so a repeat poison skips the fetch/bisect machinery instead
of re-poisoning a batch.

What is NEVER cached (enforced by the fill sites — the detector's fetch
flight and the batcher's keyed-completion callback — which only ever pass
the classes below in):
- 5xx / 429 / timeouts / connect errors — retryable, the next attempt may
  succeed;
- admission sheds (queue full, breaker open, draining) — load state, not a
  property of the image;
- fatal/transient engine errors (device lost, OOM) — the degraded-dp
  rebuild must retry them, not serve a stale verdict.

Knobs: `SPOTTER_TPU_CACHE_MAX_MB` (byte budget; 0 — the default — disables
the whole tier), `SPOTTER_TPU_CACHE_TTL_S`, `SPOTTER_TPU_CACHE_NEGATIVE_TTL_S`.

Thread-safe (a lock around the OrderedDicts): lookups and fills happen on
the event loop, but /metrics snapshots and tests touch it from other
threads. Cache faults injected via `testing.faults` (`cache_error=N`) are
CONTAINED here — a broken cache degrades to a miss / skipped fill, never to
a failed request.
"""

import json
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

# key derivation lives in caching/keys.py (ISSUE 11): the edge router's
# affinity key and these cache keys must come from ONE module so they can
# never drift. Re-exported here for existing importers.
from spotter_tpu.caching.keys import content_key, url_key  # noqa: F401
from spotter_tpu.serving.resilience import _env_float
from spotter_tpu.testing import faults

logger = logging.getLogger(__name__)

CACHE_MAX_MB_ENV = "SPOTTER_TPU_CACHE_MAX_MB"
CACHE_TTL_ENV = "SPOTTER_TPU_CACHE_TTL_S"
CACHE_NEGATIVE_TTL_ENV = "SPOTTER_TPU_CACHE_NEGATIVE_TTL_S"
CACHE_ANNOTATED_ENV = "SPOTTER_TPU_CACHE_ANNOTATED"

DEFAULT_CACHE_MAX_MB = 0.0  # disabled: caching is an explicit deployment opt-in
DEFAULT_CACHE_TTL_S = 600.0
DEFAULT_CACHE_NEGATIVE_TTL_S = 30.0
# negative entries are bounded by count (they carry an exception, not
# detections, so the byte budget is the wrong ruler)
MAX_NEGATIVE_ENTRIES = 4096


class ResultCache:
    """LRU + TTL + byte budget over tiny detection lists, with a sidecar
    negative cache for deterministic failures."""

    def __init__(
        self,
        max_bytes: int,
        ttl_s: float = DEFAULT_CACHE_TTL_S,
        negative_ttl_s: float = DEFAULT_CACHE_NEGATIVE_TTL_S,
        metrics=None,
        clock=time.monotonic,
        annotated: Optional[bool] = None,
    ) -> None:
        self.max_bytes = int(max_bytes)
        self.ttl_s = ttl_s
        self.negative_ttl_s = negative_ttl_s
        self.metrics = metrics
        self._clock = clock
        # annotated-JPEG sidecar (ISSUE 11 satellite): hits can skip the
        # redundant decode+draw+re-encode when the entry also carries the
        # finished JPEG; default on, SPOTTER_TPU_CACHE_ANNOTATED=0 keeps
        # detections-only entries (PR 5 behavior)
        if annotated is None:
            annotated = os.environ.get(CACHE_ANNOTATED_ENV, "1").strip() not in (
                "", "0",
            )
        self.annotated = bool(annotated)
        self._lock = threading.Lock()
        # key -> [detections, nbytes, expires_at, annotated]; `annotated`
        # is None or {"jpeg": bytes, "detections": [{"label","box"}]} —
        # one entry, one eviction unit, one byte budget
        self._entries: OrderedDict[str, list] = OrderedDict()
        # key -> (exception, expires_at)
        self._negative: OrderedDict[str, tuple[BaseException, float]] = OrderedDict()
        self._bytes = 0

    @classmethod
    def from_env(cls, metrics=None, max_mb: Optional[float] = None) -> Optional["ResultCache"]:
        """The serving wiring: an armed cache, or None when the tier is off
        (`SPOTTER_TPU_CACHE_MAX_MB` unset or <= 0) — None means every caller
        takes the exact pre-cache code path, bit-identical to today.
        `max_mb` (the `--cache-mb` flag) overrides the env budget; the TTL
        knobs are read from the env either way."""
        if max_mb is None:
            max_mb = _env_float(CACHE_MAX_MB_ENV, DEFAULT_CACHE_MAX_MB)
        if max_mb <= 0:
            return None
        return cls(
            max_bytes=int(max_mb * 1024 * 1024),
            ttl_s=_env_float(CACHE_TTL_ENV, DEFAULT_CACHE_TTL_S),
            negative_ttl_s=_env_float(
                CACHE_NEGATIVE_TTL_ENV, DEFAULT_CACHE_NEGATIVE_TTL_S
            ),
            metrics=metrics,
        )

    # -- positive entries ----------------------------------------------------

    def get(self, key: str) -> Optional[list]:
        """Detections for `key`, or None. Counts a hit/miss; returns a COPY
        of the stored list so no two requests share mutable state."""
        return self.get_entry(key)[0]

    def get_entry(self, key: str, stale_ok: bool = False) -> tuple[Optional[list], bool]:
        """(detections, is_stale) for `key`, or (None, False).

        `stale_ok=True` (the brownout serve-stale rung, ISSUE 8) makes an
        expired-TTL entry acceptable: it is returned with `is_stale=True`
        and KEPT (the brownout may clear before the next request; the LRU/
        byte budget still bounds it) instead of dropped. The fresh path is
        unchanged: expired entries are dropped and miss.
        """
        detections, stale, _ = self.get_entry_full(key, stale_ok=stale_ok)
        return detections, stale

    def get_entry_full(
        self, key: str, stale_ok: bool = False
    ) -> tuple[Optional[list], bool, Optional[dict]]:
        """(detections, is_stale, annotated) — `annotated` is the sidecar
        {"jpeg": bytes, "detections": [...]} when a previous hit/miss
        attached the finished draw output (ISSUE 11 satellite), else None.
        Same hit/miss/stale accounting as `get_entry`."""
        try:
            faults.on_cache("get", key)
            with self._lock:
                entry = self._entries.get(key)
                stale = entry is not None and entry[2] <= self._clock()
                if stale and not stale_ok:
                    self._drop(key)
                    entry = None
                    stale = False
                if entry is None:
                    self._record("record_cache_miss")
                    return None, False, None
                self._entries.move_to_end(key)
                self._record("record_cache_hit")
                if stale:
                    self._record("record_stale_served")
                annotated = entry[3]
                if annotated is not None:
                    annotated = {
                        "jpeg": annotated["jpeg"],
                        "detections": [dict(d) for d in annotated["detections"]],
                    }
                return [dict(d) for d in entry[0]], stale, annotated
        except Exception:
            logger.exception("result cache get(%s) failed; treating as miss", key)
            self._record("record_cache_miss")
            return None, False, None

    def put(self, key: str, detections: list) -> None:
        """Fill (idempotent; last writer wins). Oversized values — bigger
        than the whole budget — are not stored."""
        try:
            faults.on_cache("put", key)
            nbytes = self._estimate_nbytes(key, detections)
            if nbytes > self.max_bytes:
                return
            value = [dict(d) for d in detections]
            with self._lock:
                if key in self._entries:
                    self._drop(key)
                self._entries[key] = [value, nbytes, self._clock() + self.ttl_s, None]
                self._bytes += nbytes
                self._evict_over_budget()
                self._publish_size()
        except Exception:
            logger.exception("result cache put(%s) failed; skipping fill", key)

    def attach_annotated(
        self, key: str, jpeg: bytes, detections: list[dict]
    ) -> None:
        """Attach the finished draw output (annotated JPEG + the amenity-
        filtered label/box list) to an existing fresh entry so the next hit
        skips decode+draw+re-encode entirely. The sidecar lives and dies
        with the entry — one eviction unit — and its bytes count against
        the same budget; a JPEG that would blow the whole budget is simply
        not attached (the detections-only entry keeps serving)."""
        if not self.annotated:
            return
        try:
            faults.on_cache("put", key)
            extra = len(jpeg) + self._estimate_nbytes("", detections)
            with self._lock:
                entry = self._entries.get(key)
                if (
                    entry is None
                    or entry[3] is not None
                    or entry[2] <= self._clock()
                ):
                    return
                if entry[1] + extra > self.max_bytes:
                    return
                entry[3] = {
                    "jpeg": jpeg,
                    "detections": [dict(d) for d in detections],
                }
                entry[1] += extra
                self._bytes += extra
                # freshly useful: don't let the attach itself evict the key
                self._entries.move_to_end(key)
                self._evict_over_budget()
                self._publish_size()
        except Exception:
            logger.exception(
                "result cache attach_annotated(%s) failed; skipping", key
            )

    def _evict_over_budget(self) -> None:
        # caller holds the lock
        evicted = 0
        while self._bytes > self.max_bytes and self._entries:
            oldest = next(iter(self._entries))
            self._drop(oldest)
            evicted += 1
        if evicted and self.metrics is not None:
            self.metrics.record_cache_eviction(evicted)

    # -- negative entries ----------------------------------------------------

    def get_negative(self, key: str) -> Optional[BaseException]:
        """The cached deterministic failure for `key`, or None. The caller
        re-raises it; expiry means the next attempt really retries."""
        try:
            faults.on_cache("get_negative", key)
            with self._lock:
                entry = self._negative.get(key)
                if entry is None:
                    return None
                if entry[1] <= self._clock():
                    del self._negative[key]
                    return None
                self._negative.move_to_end(key)
                self._record("record_cache_negative_hit")
                return entry[0]
        except Exception:
            logger.exception(
                "result cache get_negative(%s) failed; treating as miss", key
            )
            return None

    def peek_negative(self, key: str) -> Optional[tuple[BaseException, float]]:
        """(exception, remaining_ttl_s) for a live verdict, else None —
        WITHOUT counting a negative hit or touching LRU order. The replica
        HTTP layer uses this to surface verdicts in `X-Spotter-Negative`
        response headers (ISSUE 11): observation, not consumption."""
        try:
            with self._lock:
                entry = self._negative.get(key)
                if entry is None:
                    return None
                remaining = entry[1] - self._clock()
                if remaining <= 0:
                    del self._negative[key]
                    return None
                return entry[0], remaining
        except Exception:
            logger.exception("result cache peek_negative(%s) failed", key)
            return None

    def put_negative(self, key: str, exc: BaseException) -> None:
        try:
            faults.on_cache("put_negative", key)
            with self._lock:
                self._negative[key] = (exc, self._clock() + self.negative_ttl_s)
                self._negative.move_to_end(key)
                while len(self._negative) > MAX_NEGATIVE_ENTRIES:
                    self._negative.popitem(last=False)
        except Exception:
            logger.exception("result cache put_negative(%s) failed; skipping", key)

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """/healthz-shaped snapshot of the cache's size state."""
        with self._lock:
            return {
                "enabled": True,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "negative_entries": len(self._negative),
                "annotated_entries": sum(
                    1 for e in self._entries.values() if e[3] is not None
                ),
                "ttl_s": self.ttl_s,
                "negative_ttl_s": self.negative_ttl_s,
            }

    # -- internals (callers hold the lock where noted) -----------------------

    def _drop(self, key: str) -> None:
        # caller holds the lock
        value = self._entries.pop(key, None)
        if value is not None:
            self._bytes -= value[1]

    def _publish_size(self) -> None:
        # caller holds the lock
        if self.metrics is not None:
            self.metrics.set_cache_size(len(self._entries), self._bytes)

    def _record(self, method: str) -> None:
        if self.metrics is not None:
            getattr(self.metrics, method)()

    @staticmethod
    def _estimate_nbytes(key: str, detections: list) -> int:
        # detections are tiny JSON-shaped dicts (label/score/box); the JSON
        # encoding is an honest, deterministic size proxy for the budget
        try:
            payload = len(json.dumps(detections))
        except (TypeError, ValueError):
            payload = len(repr(detections))
        return len(key) + payload + 96  # + OrderedDict/tuple overhead
