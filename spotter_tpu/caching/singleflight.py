"""Async single-flight coalescing: N concurrent calls, one underlying flight.

The serving stack uses this at two layers (ISSUE 5): URL-level in the
detector (N concurrent requests for the same URL perform ONE fetch) and —
via the MicroBatcher's keyed-submit machinery, which implements the same
fan-out contract over its future plumbing — content-hash-level at batch
admission (same decoded bytes already heading to the engine attach to the
existing call instead of re-running it).

Contract, in the presence of every failure mode the serving stack knows:

- the flight runs in its OWN task, never under a waiter: one waiter's
  expired `Deadline` or client disconnect (task cancellation) detaches that
  waiter only — the flight keeps running for everyone else, and its result
  still fills the cache;
- a failed flight fans its exception to every attached waiter exactly once
  (each waiter observes the same exception instance);
- flights are keyed per-instance, not globally, so two detectors (tests,
  replicas in one process) never share state.
"""

import asyncio
from typing import Awaitable, Callable, Optional


class SingleFlight:
    """In-flight call coalescing keyed by string.

    `on_coalesced` (optional) is called once per waiter that attached to an
    existing flight instead of starting its own — the metrics hook.
    """

    def __init__(self, on_coalesced: Optional[Callable[[], None]] = None) -> None:
        self._flights: dict[str, asyncio.Task] = {}
        self._on_coalesced = on_coalesced

    def in_flight(self, key: str) -> bool:
        task = self._flights.get(key)
        return task is not None and not task.done()

    def __len__(self) -> int:
        return len(self._flights)

    async def run(
        self,
        key: str,
        factory: Callable[[], Awaitable],
        deadline=None,
        what: str = "shared flight",
    ):
        """Await the (possibly shared) flight for `key`.

        `factory` is only invoked when no flight for `key` is in progress.
        It must NOT bake any one waiter's deadline into the flight — the
        per-waiter `deadline` is applied here, around a shield, so expiry
        cancels only this waiter's wait (`DeadlineExceededError`), never the
        flight itself.
        """
        task = self._flights.get(key)
        if task is None or task.done():
            task = asyncio.create_task(factory())
            # consume the exception even if every waiter detached before the
            # flight failed — an unobserved-exception warning is not an
            # acceptable failure mode for a cache tier
            task.add_done_callback(self._reap(key))
            self._flights[key] = task
        elif self._on_coalesced is not None:
            self._on_coalesced()
        if deadline is None:
            return await asyncio.shield(task)
        return await deadline.wait_for(asyncio.shield(task), what)

    def _reap(self, key: str):
        def done(task: asyncio.Task) -> None:
            if self._flights.get(key) is task:
                del self._flights[key]
            if not task.cancelled():
                task.exception()  # mark retrieved; waiters re-raise their own

        return done
