"""Flax ResNet backbones: "d" (RT-DETR presnet) and "v1" (classic / DETR).

style "d" matches HF's RTDetrResNetBackbone (modeling_rt_detr_resnet.py): deep
3-conv stem, max-pool, and — the "D" trick — 2x2 ceil-mode average pooling in
front of 1x1 projection shortcuts when downsampling. style "v1" matches HF's
ResNetBackbone / timm resnet (modeling_resnet.py): single 7x7 stride-2 stem and
strided 1x1 projection shortcuts — the backbone of facebook/detr-resnet-*.
NHWC layout, frozen BN.
"""

import os
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from spotter_tpu.models.configs import ResNetConfig
from spotter_tpu.models.layers import (
    ConvKernel,
    ConvNorm,
    FrozenBatchNorm,
    get_activation,
)

# Space-to-depth first stem conv (process-start knob, default off until the
# measured win is recorded in BASELINE.md): the deep stem's 3x3 stride-2
# conv on (H, W, 3) runs at a few percent of MXU peak on v5e (3 input
# channels). With SPOTTER_TPU_S2D_STEM=1 the same conv executes as
# space-to-depth(2) + a 2x2 stride-1 conv over 12 channels — an EXACT
# weight rearrangement of the checkpoint's (3, 3, 3, C) kernel done at
# trace time, so the param tree, converter, and numerics (up to float
# reassociation) are unchanged. Requires even H and W (every serving
# bucket; odd inputs fall back to the plain conv).
S2D_STEM = os.environ.get("SPOTTER_TPU_S2D_STEM", "0") != "0"


class DeepStemS2DConv(nn.Module):
    """stem0 (ConvNorm 3x3 s2 pad 1) as space-to-depth + 2x2 s1 conv.

    Derivation: out(i,j) = sum_{d in {0,1,2}^2} x[2i+di-1, 2j+dj-1] w[di,dj].
    Packing 2x2 input blocks as channels (a = row-in-block, b = col), the
    receptive rows {2i-1, 2i, 2i+1} live in blocks {i-1, i}: kernel index
    ki = (di+1)//2, in-block row a = (di+1)%2 (slot (ki=0, a=0) = row 2i-2
    is never read -> zero weight), with one zero block padded in front —
    identical zeros to the plain conv's pad-by-1.
    """

    features: int
    activation: Optional[str] = None
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, h, w, c = x.shape
        kern = ConvKernel((3, 3, c, self.features), name="conv")()
        w2 = jnp.zeros((2, 2, 4 * c, self.features), kern.dtype)
        for di in range(3):
            ki, a = (di + 1) // 2, (di + 1) % 2
            for dj in range(3):
                kj, bb = (dj + 1) // 2, (dj + 1) % 2
                lo = a * 2 * c + bb * c
                w2 = w2.at[ki, kj, lo : lo + c].set(kern[di, dj])
        blocks = x.reshape(b, h // 2, 2, w // 2, 2, c)
        blocks = blocks.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
        y = jax.lax.conv_general_dilated(
            blocks.astype(self.dtype),
            w2.astype(self.dtype),
            window_strides=(1, 1),
            padding=((1, 0), (1, 0)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = FrozenBatchNorm(self.features, eps=self.eps, dtype=self.dtype, name="bn")(y)
        return get_activation(self.activation)(y)


def avg_pool_2x2_ceil(x: jnp.ndarray) -> jnp.ndarray:
    """torch AvgPool2d(2, 2, ceil_mode=True): clipped edge windows divide by
    their actual element count."""
    b, h, w, c = x.shape
    ph, pw = h % 2, w % 2
    summed = nn.avg_pool(
        x, (2, 2), strides=(2, 2), padding=((0, ph), (0, pw)), count_include_pad=False
    )
    return summed


class BasicBlock(nn.Module):
    """Two 3x3 convs + residual (resnet-18/34)."""

    out_channels: int
    stride: int = 1
    shortcut: str = "none"  # "none" | "proj" | "avgpool_proj"
    hidden_act: str = "relu"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = ConvNorm(
            self.out_channels, 3, self.stride, activation=self.hidden_act,
            dtype=self.dtype, name="conv0",
        )(x)
        y = ConvNorm(self.out_channels, 3, 1, activation=None, dtype=self.dtype, name="conv1")(y)
        if self.shortcut == "proj":
            residual = ConvNorm(
                self.out_channels, 1, self.stride, activation=None,
                dtype=self.dtype, name="shortcut",
            )(x)
        elif self.shortcut == "avgpool_proj":
            residual = avg_pool_2x2_ceil(x)
            residual = ConvNorm(
                self.out_channels, 1, 1, activation=None, dtype=self.dtype, name="shortcut"
            )(residual)
        return get_activation(self.hidden_act)(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 reduce -> 3x3 -> 1x1 expand + residual (resnet-50/101)."""

    out_channels: int
    stride: int = 1
    shortcut: str = "none"
    downsample_in_bottleneck: bool = False
    hidden_act: str = "relu"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        reduced = self.out_channels // 4
        s1 = self.stride if self.downsample_in_bottleneck else 1
        s2 = self.stride if not self.downsample_in_bottleneck else 1
        y = ConvNorm(reduced, 1, s1, activation=self.hidden_act, dtype=self.dtype, name="conv0")(x)
        y = ConvNorm(reduced, 3, s2, activation=self.hidden_act, dtype=self.dtype, name="conv1")(y)
        y = ConvNorm(self.out_channels, 1, 1, activation=None, dtype=self.dtype, name="conv2")(y)
        residual = x
        if self.shortcut == "proj":
            residual = ConvNorm(
                self.out_channels, 1, self.stride, activation=None,
                dtype=self.dtype, name="shortcut",
            )(x)
        elif self.shortcut == "avgpool_proj":
            residual = avg_pool_2x2_ceil(x)
            residual = ConvNorm(
                self.out_channels, 1, 1, activation=None, dtype=self.dtype, name="shortcut"
            )(residual)
        elif self.shortcut == "avgpool":
            residual = avg_pool_2x2_ceil(x)
        return get_activation(self.hidden_act)(y + residual)


def _basic_shortcut(in_ch: int, out_ch: int, stride: int, apply: bool) -> str:
    # modeling_rt_detr_resnet.py RTDetrResNetBasicLayer.__init__ semantics
    if in_ch != out_ch:
        return "avgpool_proj" if apply else "none"
    return "proj" if apply else "none"


def _v1_shortcut(in_ch: int, out_ch: int, stride: int) -> str:
    # modeling_resnet.py ResNet{Basic,BottleNeck}Layer: strided 1x1 projection
    # whenever shape or stride changes, no avg-pool trick
    return "proj" if (in_ch != out_ch or stride != 1) else "none"


def _bottleneck_shortcut(in_ch: int, out_ch: int, stride: int) -> str:
    # RTDetrResNetBottleNeckLayer.__init__: stride==2 always takes the avg-pool
    # path (projection only when shapes change); stride==1 projects iff needed.
    should_project = in_ch != out_ch or stride != 1
    if stride == 2:
        return "avgpool_proj" if should_project else "avgpool"
    return "proj" if should_project else "none"


class ResNetBackbone(nn.Module):
    """Returns feature maps at `config.out_indices` of
    (stem_out, stage1, stage2, stage3, stage4)."""

    config: ResNetConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixel_values: jnp.ndarray) -> list[jnp.ndarray]:
        cfg = self.config
        act = cfg.hidden_act
        x = pixel_values.astype(self.dtype)
        if cfg.style == "v1":
            # Classic stem: single 7x7 s2 conv, then 3x3 s2 max pool.
            x = ConvNorm(cfg.embedding_size, 7, 2, activation=act, dtype=self.dtype, name="stem0")(x)
        elif S2D_STEM and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            # Deep stem, first conv via space-to-depth (exact rearrangement).
            x = DeepStemS2DConv(
                cfg.embedding_size // 2, activation=act, dtype=self.dtype, name="stem0"
            )(x)
            x = ConvNorm(cfg.embedding_size // 2, 3, 1, activation=act, dtype=self.dtype, name="stem1")(x)
            x = ConvNorm(cfg.embedding_size, 3, 1, activation=act, dtype=self.dtype, name="stem2")(x)
        else:
            # Deep stem: 3x3 s2 -> 3x3 -> 3x3.
            x = ConvNorm(cfg.embedding_size // 2, 3, 2, activation=act, dtype=self.dtype, name="stem0")(x)
            x = ConvNorm(cfg.embedding_size // 2, 3, 1, activation=act, dtype=self.dtype, name="stem1")(x)
            x = ConvNorm(cfg.embedding_size, 3, 1, activation=act, dtype=self.dtype, name="stem2")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        hidden_states = [x]
        in_ch = cfg.embedding_size
        for stage_idx, (out_ch, depth) in enumerate(zip(cfg.hidden_sizes, cfg.depths)):
            stride = 2 if (stage_idx > 0 or cfg.downsample_in_first_stage) else 1
            for block_idx in range(depth):
                block_stride = stride if block_idx == 0 else 1
                block_in = in_ch if block_idx == 0 else out_ch
                name = f"stage{stage_idx}_block{block_idx}"
                if cfg.layer_type == "bottleneck":
                    if block_idx != 0:
                        shortcut = "none"
                    elif cfg.style == "v1":
                        shortcut = _v1_shortcut(block_in, out_ch, block_stride)
                    else:
                        shortcut = _bottleneck_shortcut(block_in, out_ch, block_stride)
                    x = BottleneckBlock(
                        out_ch, block_stride, shortcut, cfg.downsample_in_bottleneck,
                        act, self.dtype, name=name,
                    )(x)
                else:
                    if cfg.style == "v1":
                        shortcut = (
                            _v1_shortcut(block_in, out_ch, block_stride)
                            if block_idx == 0
                            else "none"
                        )
                    else:
                        shortcut = _basic_shortcut(block_in, out_ch, block_stride, block_idx == 0)
                    x = BasicBlock(out_ch, block_stride, shortcut, act, self.dtype, name=name)(x)
            hidden_states.append(x)
            in_ch = out_ch

        return [hidden_states[i] for i in cfg.out_indices]
