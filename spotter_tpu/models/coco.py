"""COCO label tables for the offline/tiny model paths.

Real checkpoints carry id2label in their HF config (that is what the engine
uses — serve.py:111-114 semantics). These tables back the no-network tiny
models and synthetic benchmarks.
"""

COCO_LABELS_80: tuple[str, ...] = (
    "person", "bicycle", "car", "motorcycle", "airplane", "bus", "train", "truck",
    "boat", "traffic light", "fire hydrant", "stop sign", "parking meter", "bench",
    "bird", "cat", "dog", "horse", "sheep", "cow", "elephant", "bear", "zebra",
    "giraffe", "backpack", "umbrella", "handbag", "tie", "suitcase", "frisbee",
    "skis", "snowboard", "sports ball", "kite", "baseball bat", "baseball glove",
    "skateboard", "surfboard", "tennis racket", "bottle", "wine glass", "cup",
    "fork", "knife", "spoon", "bowl", "banana", "apple", "sandwich", "orange",
    "broccoli", "carrot", "hot dog", "pizza", "donut", "cake", "chair", "couch",
    "potted plant", "bed", "dining table", "toilet", "tv", "laptop", "mouse",
    "remote", "keyboard", "cell phone", "microwave", "oven", "toaster", "sink",
    "refrigerator", "book", "clock", "vase", "scissors", "teddy bear",
    "hair drier", "toothbrush",
)

# COCO's original 91-id space (DETR/YOLOS head size); gaps are "N/A".
_GAPS = {0, 12, 26, 29, 30, 45, 66, 68, 69, 71, 83}


def coco_id2label_80() -> dict[int, str]:
    return dict(enumerate(COCO_LABELS_80))


def coco_id2label_91() -> dict[int, str]:
    out: dict[int, str] = {}
    it = iter(COCO_LABELS_80)
    for i in range(91):
        out[i] = "N/A" if i in _GAPS else next(it)
    return out
