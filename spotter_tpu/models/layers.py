"""Shared Flax building blocks for the detection model families.

Design notes (TPU-first):
- NHWC layout everywhere; conv kernels HWIO (XLA's native TPU layout).
- BatchNorms are "frozen": affine + running stats folded into 4 per-channel
  params. This matches detection-serving practice (the torch lineage freezes
  backbone BN: RTDetrV2FrozenBatchNorm2d / DetrFrozenBatchNorm2d) and keeps the
  param tree a single pure-functional collection.
- `dtype` on each module is the compute dtype (bf16 on TPU for the MXU);
  params stay fp32.
- Position tables, anchors, and sampling grids are computed with numpy at
  trace time from static shapes, so XLA constant-folds them.
"""

import math
import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from spotter_tpu.utils.quant import (
    int8_attn_wanted,
    int8_av,
    int8_conv,
    int8_dense,
    int8_dense_wanted,
    int8_qk,
    int8_wanted,
)

# GELU policy: torch's default nn.GELU / HF ACT2FN["gelu"] is the exact erf
# form, which costs ~14 VPU transcendental-class ops per element — measured
# 1.13 vs 0.08 ms against the tanh form at one yolos MLP activation
# (8, 4300, 3072) bf16 on v5e, ~1 ms x 12 layers of pure erf. On bf16
# tensors the tanh approximation's error (<~1e-3 absolute) sits below the
# bf16 rounding already accepted for that tensor, so "auto" (default) uses
# tanh there and exact erf on fp32 — the parity-pinned fp32 policy is
# unchanged. SPOTTER_TPU_GELU=exact|tanh overrides both ways.
_GELU_MODE = os.environ.get("SPOTTER_TPU_GELU", "auto").strip().lower()
if _GELU_MODE not in ("auto", "exact", "tanh"):
    raise ValueError(
        f"SPOTTER_TPU_GELU must be auto|exact|tanh, got {_GELU_MODE!r}"
    )


def _gelu(x: jnp.ndarray) -> jnp.ndarray:
    if _GELU_MODE == "tanh" or (_GELU_MODE == "auto" and x.dtype == jnp.bfloat16):
        return nn.gelu(x, approximate=True)
    return nn.gelu(x, approximate=False)


ACTIVATIONS: dict[str, Callable] = {
    "relu": nn.relu,
    "gelu": _gelu,
    "silu": nn.silu,
    "swish": nn.silu,
    "tanh": jnp.tanh,
    "sigmoid": nn.sigmoid,
    "quick_gelu": lambda x: x * nn.sigmoid(1.702 * x),
}


def get_activation(name: Optional[str]) -> Callable:
    if name is None:
        return lambda x: x
    return ACTIVATIONS[name]


# Flash attention cutover: unmasked self-attention at or above this many
# tokens runs the Pallas TPU flash kernel instead of materializing the
# (B, H, S, S) score matrix. ViT-detector sequences make naive attention
# HBM-catastrophic — yolos-base at 800x1344 is 4300 tokens, i.e. ~7 GB of
# fp32 scores per batch-8 forward (measured 7.6 img/s naive). Short
# sequences (AIFI's 400, decoder's 300) stay on the fused-XLA path, which
# wins there and is the torch-parity-pinned reference. Process-start knob:
# SPOTTER_TPU_FLASH_ATTN=0 disables.
FLASH_ATTN_MIN_SEQ = 1024
_FLASH_ATTN_ENABLED = os.environ.get("SPOTTER_TPU_FLASH_ATTN", "1") != "0"
_FLASH_BLOCK = 512

# Which Pallas attention kernel backs the cutover. "splash" is the newer
# TPU kernel and measured faster at ViT-detector shapes — yolos-base
# (8, 12, 4608, 64): 11.8 vs 13.9 ms/layer raw against flash_attention with
# its best swept blocks (same session, segment ids in both). "auto"
# (default) follows the repo's numerics-default convention (GELU policy,
# RepVGG fusion, MSDA precision): the faster-but-different kernel only
# where bf16 rounding is already accepted — bf16 tensors take splash, fp32
# keeps the established flash kernel. Process-start knob like the others.
_FLASH_IMPL = os.environ.get("SPOTTER_TPU_FLASH_IMPL", "auto").strip().lower()
if _FLASH_IMPL not in ("auto", "splash", "flash"):
    raise ValueError(
        f"SPOTTER_TPU_FLASH_IMPL must be auto|splash|flash, got {_FLASH_IMPL!r}"
    )
# splash block sizes swept on v5e at (8, 12, 4608, 64): bq/bkv 384/2304
# (compute 768) beat 512/512, 768/768, 1536/1536, 256/2304, */4608.
# Round-5 bq re-sweep at the same shape: bq 512 and 768 tie at 12.0
# ms/layer vs 384's 13.6 (-12%); 512 is kept (768's full-kv variants hit
# compile-helper OOMs) and scoped to s_pad >= 4608 where it was measured —
# _splash_block_q below. The ADVICE-r4 3072 interpolation is now measured,
# not extrapolated: full-row 3072 at 6.93 ms vs 1536 at 9.04 / 1024 at
# 9.12 / 768 at 9.59 (s=3000).
_SPLASH_BQ = 384
_SPLASH_BQ_WIDE = 512
_SPLASH_BKV = 2304
_SPLASH_BKV_COMPUTE = 768


def _splash_block_q(s_pad: int) -> int:
    """block_q policy: 512 at the measured >=4608 wide shapes it divides
    (yolos 4608: 12.0 vs 13.6 ms/layer), else the 384 default; both pinned
    by tests/test_flash_attention.py."""
    if s_pad >= 4608 and s_pad % _SPLASH_BQ_WIDE == 0:
        return _SPLASH_BQ_WIDE
    return min(_SPLASH_BQ, s_pad)


def flash_attention_enabled() -> bool:
    """True when the flash path may be taken on this backend (shared by
    every attention implementation in the model zoo)."""
    return _FLASH_ATTN_ENABLED and jax.default_backend() == "tpu"


def flash_self_attention(q, k, v):
    """(B, S, H, hd) pre-scaled q/k/v -> (B, S, H, hd) via a Pallas TPU
    attention kernel (splash on bf16 tensors / flash on fp32 under the
    default "auto" policy — see _FLASH_IMPL). Pads S to the kernel block
    size; padded tokens live in a
    different segment id, so they can never attend to or be attended by real
    tokens (exact zeros-free equivalence with the naive path)."""
    if _FLASH_IMPL == "splash" or (
        _FLASH_IMPL == "auto" and q.dtype == jnp.bfloat16
    ):
        return _splash_self_attention(q, k, v)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        SegmentIds,
        flash_attention,
    )

    b, s, h, hd = q.shape
    s_pad = -(-s // _FLASH_BLOCK) * _FLASH_BLOCK

    def prep(x):
        x = x.transpose(0, 2, 1, 3)  # (B, H, S, hd)
        if s_pad != s:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
        return x

    seg = jnp.broadcast_to(
        (jnp.arange(s_pad) >= s).astype(jnp.int32)[None], (b, s_pad)
    )
    # Explicit uniform block sizes: the kernel's defaults picked a
    # pathological schedule on v5e (64.6 ms vs 3.3 ms at yolos-base shapes,
    # (8, 12, 4608, 64)); s_pad is a _FLASH_BLOCK multiple by construction.
    blk = min(_FLASH_BLOCK, s_pad)
    bs = BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=blk, block_k_major_dkv=blk,
        block_q_dkv=blk, block_k_dkv=blk,
        block_q_dq=blk, block_k_dq=blk, block_k_major_dq=blk,
    )
    out = flash_attention(
        prep(q), prep(k), prep(v),
        segment_ids=SegmentIds(q=seg, kv=seg),
        sm_scale=1.0,  # q arrives pre-scaled by head_dim**-0.5
        block_sizes=bs,
    )
    return out[:, :, :s].transpose(0, 2, 1, 3)


def _splash_block_kv(s_pad: int) -> int:
    """block_kv for a 768-padded sequence (see _splash_self_attention's
    block-size policy notes; swept on v5e round 3 at 4608 and round 4 at
    3840 — tests/test_flash_attention.py pins the chosen ladder)."""
    if s_pad % _SPLASH_BKV == 0:
        return _SPLASH_BKV
    if s_pad <= 3840:
        return s_pad
    return next(c for c in (1536, 768) if s_pad % c == 0)


def _splash_self_attention(q, k, v, interpret: bool = False):
    """Splash-kernel backend of `flash_self_attention` (same contract:
    (B, S, H, hd) pre-scaled inputs, padded tokens isolated by segment ids).

    Block-size policy: pad S to a multiple of 768 so block_q=384 and a
    768-multiple block_kv always divide it; block_kv prefers the swept-best
    2304 (yolos 4608: 11.53 vs 12.49 ms/layer full-kv), else FULL-row kv
    up to 3840 (owlv2's 3601->3840: full-kv 10.18 vs 12.67 at the old
    768 fallback, round-4 sweep), else the largest 768-multiple divisor.
    Splash has no sm_scale — q arrives pre-scaled, matching the flash
    path's sm_scale=1.
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as _sk,
    )
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_mask as _sm,
    )

    b, s, h, hd = q.shape
    s_pad = -(-s // 768) * 768
    bkv = _splash_block_kv(s_pad)
    bq = _splash_block_q(s_pad)
    bs = _sk.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=min(_SPLASH_BKV_COMPUTE, bkv),
        block_q_dkv=bq, block_kv_dkv=bkv,
        block_kv_dkv_compute=min(_SPLASH_BKV_COMPUTE, bkv),
        block_q_dq=bq, block_kv_dq=bkv,
    )
    kernel = _sk.make_splash_mha(
        mask=_sm.MultiHeadMask([_sm.FullMask((s_pad, s_pad))] * h),
        head_shards=1,
        q_seq_shards=1,
        block_sizes=bs,
        interpret=interpret,
    )

    def prep(x):
        x = x.transpose(0, 2, 1, 3)  # (B, H, S, hd)
        if s_pad != s:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
        return x

    seg = (jnp.arange(s_pad) >= s).astype(jnp.int32)
    segs = _sk.SegmentIds(q=seg, kv=seg)
    out = jax.vmap(kernel, in_axes=(0, 0, 0, None))(prep(q), prep(k), prep(v), segs)
    return out[:, :, :s].transpose(0, 2, 1, 3)


def inverse_sigmoid(x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    x = jnp.clip(x, 0.0, 1.0)
    x1 = jnp.clip(x, eps, None)
    x2 = jnp.clip(1.0 - x, eps, None)
    return jnp.log(x1 / x2)


def fold_bn(
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    mean: jnp.ndarray,
    var: jnp.ndarray,
    eps: float,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Frozen-BN stats folded to one (mul, add) pair — the single source of
    the fold arithmetic, shared by FrozenBatchNorm and the fused RepVgg path
    (models/rtdetr.py) so the two can never diverge numerically."""
    mul = scale * jax.lax.rsqrt(var + eps)
    return mul, bias - mean * mul


class FrozenBatchNorm(nn.Module):
    """Inference-mode batch norm: y = (x - mean) / sqrt(var + eps) * scale + bias.

    Converted from torch BatchNorm2d running stats. Kept frozen during
    fine-tuning (the DETR-family convention).
    """

    features: int
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param("scale", nn.initializers.ones, (self.features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (self.features,), jnp.float32)
        var = self.param("var", nn.initializers.ones, (self.features,), jnp.float32)
        # Fold into a single multiply-add (XLA fuses this into the preceding conv).
        mul, add = fold_bn(scale, bias, mean, var, self.eps)
        return (x * mul.astype(self.dtype) + add.astype(self.dtype)).astype(self.dtype)


class ConvNorm(nn.Module):
    """Conv (no bias) + frozen BN + optional activation.

    Equivalent of the torch ConvNormLayer used across the RT-DETR lineage
    (conv k, stride s, padding (k-1)//2, bias=False, then BN, then act).
    """

    features: int
    kernel_size: int = 3
    stride: int = 1
    padding: Optional[int] = None
    activation: Optional[str] = None
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        pad = (self.kernel_size - 1) // 2 if self.padding is None else self.padding
        # batch-aware: the small-batch guard (utils/quant.py INT8_MIN_BATCH)
        # keeps the latency-SLO buckets bf16 — batch is static under jit
        if int8_wanted(x.shape[-1], batch=x.shape[0]):
            # Quantized path (SPOTTER_TPU_INT8=1, utils/quant.py): int8 MXU
            # conv with the dequant feeding the same frozen-BN chain. The
            # kernel param is declared at nn.Conv's exact path/shape/init so
            # checkpoints and converters are unaffected.
            kernel = ConvKernel(
                (self.kernel_size, self.kernel_size, x.shape[-1], self.features),
                name="conv",
            )()
            x = int8_conv(
                x,
                kernel,
                (self.stride, self.stride),
                [(pad, pad), (pad, pad)],
                self.dtype,
            )
        else:
            x = nn.Conv(
                self.features,
                (self.kernel_size, self.kernel_size),
                strides=(self.stride, self.stride),
                padding=[(pad, pad), (pad, pad)],
                use_bias=False,
                dtype=self.dtype,
                name="conv",
            )(x)
        x = FrozenBatchNorm(self.features, eps=self.eps, dtype=self.dtype, name="bn")(x)
        return get_activation(self.activation)(x)


class ConvNormParams(nn.Module):
    """The exact param tree of ConvNorm (conv/kernel + bn stats) WITHOUT the
    computation, returned as a BN-folded (kernel*mul, add) pair.

    Lives here, directly below the two modules whose param contract it
    shadows (nn.Conv-in-ConvNorm and FrozenBatchNorm): any change to their
    param names/shapes/initializers must be mirrored in the declarations
    below, and tests/test_rep_fuse.py pins the two trees identical. Used by
    the fused RepVgg path (models/rtdetr.py REP_FUSE).
    """

    features: int
    kernel_size: int
    in_features: int
    eps: float = 1e-5

    @nn.compact
    def __call__(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        k = self.kernel_size
        kernel = ConvKernel((k, k, self.in_features, self.features), name="conv")()
        mul, add = _BNStats(self.features, self.eps, name="bn")()
        return kernel * mul, add


class PatchEmbed(nn.Module):
    """ViT patch embedding: Conv(P, stride P) rewritten as P row-dots.

    Exact algebraic rewrite of the non-overlapping patchify conv that
    avoids both XLA's small-channel conv lowering and the patch transpose:
    each `pixels[:, ry::P]` slice strides over CONTIGUOUS (gw*P*C)-element
    blocks (XLA copies those well — unlike the per-element minor-dim
    strides that make 3-channel convs slow, BASELINE.md round 4), and each
    slice feeds one (B*gh*gw, P*C) @ (P*C, D) dot, accumulated in fp32.
    Measured on v5e bf16 at OWL-ViT patchify shapes ((8, 768^2, 3), P=32):
    2.89 ms vs 5.76 for the conv (the transpose-based reshape+matmul TIES
    the conv at 5.06 — the transpose is the cost, not the contraction).

    Param tree is identical to nn.Conv(features, (P, P), strides=(P, P),
    name=...): "kernel" (P, P, C, D) lecun-normal + optional "bias" zeros,
    so converters and checkpoints are unaffected.
    """

    features: int
    patch_size: int
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels: jnp.ndarray) -> jnp.ndarray:
        p = self.patch_size
        b, h, w, c = pixels.shape
        assert h % p == 0 and w % p == 0, (h, w, p)
        gh, gw = h // p, w // p
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (p, p, c, self.features),
            jnp.float32,
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
            if self.use_bias
            else None
        )
        x4 = pixels.reshape(b, h, gw, p * c)  # minor merge (rx, c): trivial
        wr = kernel.reshape(p, p * c, self.features).astype(self.dtype)
        out = None
        for ry in range(p):
            t = jax.lax.dot_general(
                x4[:, ry::p].astype(self.dtype),
                wr[ry],
                (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            out = t if out is None else out + t
        if bias is not None:
            out = out + bias
        return out.astype(self.dtype).reshape(b, gh * gw, self.features)


class QuantDense(nn.Module):
    """nn.Dense-compatible projection (identical param tree: `kernel`
    lecun-normal (in, out) + optional `bias` zeros) that takes the int8 MXU
    path (utils/quant.py int8_dense, STE backward) when SPOTTER_TPU_INT8
    enables it for this width. With the knob off the float path reproduces
    nn.Dense exactly, so the torch-parity tests pin the default numerics.

    Used by the ViT-family projections (yolos, OWL-ViT): their qkv/out/
    fc1/fc2 matmuls carry most of each layer's non-attention FLOPs."""

    features: int
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (x.shape[-1], self.features),
            jnp.float32,
        )
        # batch is static under jit, so the small-batch guard (int8 regresses
        # under-filled MXU batches — utils/quant.py INT8_MIN_BATCH) resolves
        # per compiled bucket with no runtime branch
        if int8_dense_wanted(x.shape[-1], batch=x.shape[0]):
            y = int8_dense(x, kernel, self.dtype)
        else:
            y = jnp.matmul(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y + bias.astype(self.dtype)
        return y


class ConvKernel(nn.Module):
    """`kernel` at the path/shape/init nn.Conv(name=...) declares it."""

    shape: tuple

    @nn.compact
    def __call__(self) -> jnp.ndarray:
        return self.param(
            "kernel", nn.initializers.lecun_normal(), self.shape, jnp.float32
        )


class DenseParams(nn.Module):
    """The exact param tree of nn.Dense(features, name=...) — `kernel`
    lecun-normal (in, out) + `bias` zeros — WITHOUT the matmul, returned
    raw. The ConvNormParams pattern for dense layers: the fused MSDA
    prologue kernel (models/rtdetr.py / ops/msda.py) consumes the
    sampling_offsets / attention_weights projection weights directly, and
    declaring them at nn.Dense's paths keeps checkpoints and converters
    unaffected."""

    features: int
    in_features: int

    @nn.compact
    def __call__(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (self.in_features, self.features),
            jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32
        )
        return kernel, bias


class _BNStats(nn.Module):
    """The four FrozenBatchNorm params at its exact paths, returned folded
    as (mul, add)."""

    features: int
    eps: float = 1e-5

    @nn.compact
    def __call__(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        scale = self.param("scale", nn.initializers.ones, (self.features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (self.features,), jnp.float32)
        var = self.param("var", nn.initializers.ones, (self.features,), jnp.float32)
        return fold_bn(scale, bias, mean, var, self.eps)


class PReLU(nn.Module):
    """torch nn.PReLU with num_parameters=1: max(0,x) + a*min(0,x), learned a.

    DAB-DETR's FFN activation (ACT2FN["prelu"]) — the one activation in the
    zoo that carries a weight, so it can't go through get_activation."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        a = self.param("weight", nn.initializers.constant(0.25), (1,), jnp.float32)
        return jnp.maximum(x, 0) + a.astype(x.dtype) * jnp.minimum(x, 0)


class MLPHead(nn.Module):
    """DETR-style MLP prediction head: Linear stack with ReLU between layers."""

    hidden_dim: int
    out_dim: int
    num_layers: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i in range(self.num_layers):
            out = self.out_dim if i == self.num_layers - 1 else self.hidden_dim
            x = nn.Dense(out, dtype=self.dtype, name=f"layer{i}")(x)
            if i < self.num_layers - 1:
                x = nn.relu(x)
        return x


class MultiHeadAttention(nn.Module):
    """Standard MHA with separate q/k/v/out projections (torch-convertible).

    DETR-lineage peculiarity: position embeddings are added to queries and keys
    only — values come from the un-positioned hidden states.
    """

    embed_dim: int
    num_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        hidden_states: jnp.ndarray,
        position_embeddings: Optional[jnp.ndarray] = None,
        key_value_states: Optional[jnp.ndarray] = None,
        attention_mask: Optional[jnp.ndarray] = None,
        key_position_embeddings: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        head_dim = self.embed_dim // self.num_heads
        q_in = hidden_states
        if position_embeddings is not None:
            q_in = hidden_states + position_embeddings
        if key_value_states is None:  # self-attention
            k_in, v_in = q_in, hidden_states
        else:  # cross-attention
            k_in = key_value_states
            if key_position_embeddings is not None:
                k_in = key_value_states + key_position_embeddings
            v_in = key_value_states

        def proj(x, name):
            return QuantDense(self.embed_dim, dtype=self.dtype, name=name)(x)

        def split(x):
            return x.reshape(*x.shape[:-1], self.num_heads, head_dim)

        q = split(proj(q_in, "q_proj")) * (head_dim**-0.5)
        k = split(proj(k_in, "k_proj"))
        v = split(proj(v_in, "v_proj"))

        if (
            flash_attention_enabled()
            and attention_mask is None
            and key_value_states is None
            and q.shape[1] >= FLASH_ATTN_MIN_SEQ
        ):
            out = flash_self_attention(q, k, v)
            out = out.reshape(*out.shape[:-2], self.embed_dim)
            return proj(out, "out_proj")

        # int8 attention matmuls (SPOTTER_TPU_INT8_ATTN, utils/quant.py):
        # QK^T and attn·V on the int8 MXU with per-(sample, head) dynamic
        # scales. batch is static under jit, so the INT8_MIN_BATCH guard
        # resolves per compiled bucket — the latency-SLO bucket stays bf16.
        # With the knob unset this branch is never taken and the forward is
        # bit-identical to the plain einsum path below (test-asserted).
        quantized = int8_attn_wanted(head_dim, batch=q.shape[0])

        # (B, H, Tq, Tk)
        if quantized:
            logits = int8_qk(q, k)
        else:
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        if attention_mask is not None:
            logits = logits + attention_mask.astype(logits.dtype)
        weights = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(self.dtype)
        if quantized:
            out = int8_av(weights, v, self.dtype)
        else:
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        out = out.reshape(*out.shape[:-2], self.embed_dim)
        return proj(out, "out_proj")


def sincos_2d_position_embedding(
    width: int, height: int, embed_dim: int, temperature: float = 10000.0
) -> np.ndarray:
    """AIFI 2D sin-cos table, (1, W*H, D) — computed in numpy from static shapes.

    Grid is built with 'ij' indexing over (w, h), matching the RT-DETR hybrid
    encoder's layout (tokens enumerate width-major after the flatten-permute).
    """
    if embed_dim % 4 != 0:
        raise ValueError("embed_dim must be divisible by 4 for 2D sin-cos embeddings")
    grid_w, grid_h = np.meshgrid(
        np.arange(width, dtype=np.float32),
        np.arange(height, dtype=np.float32),
        indexing="ij",
    )
    pos_dim = embed_dim // 4
    omega = 1.0 / (temperature ** (np.arange(pos_dim, dtype=np.float32) / pos_dim))
    out_w = grid_w.reshape(-1)[:, None] * omega[None]
    out_h = grid_h.reshape(-1)[:, None] * omega[None]
    table = np.concatenate(
        [np.sin(out_w), np.cos(out_w), np.sin(out_h), np.cos(out_h)], axis=1
    )
    return table[None].astype(np.float32)


def sine_position_embedding_nhwc(
    height: int,
    width: int,
    embed_dim: int,
    temperature: float = 10000.0,
    normalize: bool = True,
    scale: float = 2.0 * math.pi,
    eps: float = 1e-6,
) -> np.ndarray:
    """DETR-style interleaved sine position embedding, (1, H, W, D) numpy.

    Matches DetrSinePositionEmbedding on an all-ones pixel mask: cumulative row
    and column indices (1-based), optionally normalized to [0, scale].
    """
    half = embed_dim // 2
    y = np.arange(1, height + 1, dtype=np.float32)[:, None].repeat(width, 1)
    x = np.arange(1, width + 1, dtype=np.float32)[None, :].repeat(height, 0)
    if normalize:
        y = y / (y[-1:, :] + eps) * scale
        x = x / (x[:, -1:] + eps) * scale
    dim_t = temperature ** (2 * (np.arange(half, dtype=np.float32) // 2) / half)
    pos_x = x[..., None] / dim_t
    pos_y = y[..., None] / dim_t
    pos_x = np.stack([np.sin(pos_x[..., 0::2]), np.cos(pos_x[..., 1::2])], axis=-1)
    pos_y = np.stack([np.sin(pos_y[..., 0::2]), np.cos(pos_y[..., 1::2])], axis=-1)
    pos_x = pos_x.reshape(height, width, half)
    pos_y = pos_y.reshape(height, width, half)
    return np.concatenate([pos_y, pos_x], axis=-1)[None].astype(np.float32)


def grid_sample_bilinear_nhwc(value: jnp.ndarray, grid: jnp.ndarray) -> jnp.ndarray:
    """Bilinear grid sample, align_corners=False, zeros padding — jnp/gather based.

    value: (B, H, W, C); grid: (B, N, P, 2) in [-1, 1] with (x, y) order.
    Returns (B, N, P, C). Semantics match torch.nn.functional.grid_sample so the
    deformable-attention parity holds; implemented as 4 gathers + lerp, which XLA
    lowers to efficient dynamic-gathers on TPU.
    """
    _, h, w, _ = value.shape
    gx = (grid[..., 0] + 1.0) * w / 2.0 - 0.5
    gy = (grid[..., 1] + 1.0) * h / 2.0 - 0.5

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        valid = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        flat = value.reshape(value.shape[0], h * w, value.shape[-1])
        idx = yc * w + xc  # (B, N, P)
        out = jnp.take_along_axis(
            flat, idx.reshape(idx.shape[0], -1, 1), axis=1
        ).reshape(*idx.shape, value.shape[-1])
        return out * valid[..., None].astype(value.dtype)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx = wx[..., None].astype(value.dtype)
    wy = wy[..., None].astype(value.dtype)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy
