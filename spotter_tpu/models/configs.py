"""Config dataclasses for the detection model families.

Mirrors the semantic content of the HF configs (RTDetrV2Config etc.) so that a
checkpoint's config.json can be adapted 1:1 (`from_hf`), while staying plain
frozen dataclasses — hashable, so they can be static args under jax.jit.
"""

from dataclasses import dataclass, field, replace


@dataclass(frozen=True)
class ResNetConfig:
    """ResNet backbone in two flavors.

    style "d": RT-DETR's "presnet" (deep 3-conv stem, avg-pool downsample
    shortcuts — HF RTDetrResNetBackbone). style "v1": the classic
    torchvision-style ResNet (single 7x7 stem, strided 1x1 projection
    shortcuts — HF ResNetBackbone / timm resnet, the DETR backbone).
    """

    num_channels: int = 3
    embedding_size: int = 64
    hidden_sizes: tuple[int, ...] = (256, 512, 1024, 2048)
    depths: tuple[int, ...] = (3, 4, 6, 3)
    layer_type: str = "bottleneck"  # "basic" | "bottleneck"
    hidden_act: str = "relu"
    downsample_in_first_stage: bool = False
    downsample_in_bottleneck: bool = False
    style: str = "d"  # "d" (RT-DETR ResNet-D) | "v1" (classic / DETR)
    # indices into (stem, stage1, ..., stage4); RT-DETR taps strides 8/16/32
    out_indices: tuple[int, ...] = (2, 3, 4)

    @classmethod
    def from_hf(cls, hf) -> "ResNetConfig":
        return cls(
            num_channels=hf.num_channels,
            embedding_size=hf.embedding_size,
            hidden_sizes=tuple(hf.hidden_sizes),
            depths=tuple(hf.depths),
            layer_type=hf.layer_type,
            hidden_act=hf.hidden_act,
            downsample_in_first_stage=hf.downsample_in_first_stage,
            downsample_in_bottleneck=hf.downsample_in_bottleneck,
            style="v1" if hf.model_type == "resnet" else "d",
            out_indices=tuple(hf.out_indices),
        )


@dataclass(frozen=True)
class RTDetrConfig:
    """RT-DETR / RT-DETRv2 detector (hybrid encoder + deformable decoder)."""

    backbone: ResNetConfig = field(default_factory=ResNetConfig)
    num_labels: int = 80
    d_model: int = 256
    num_queries: int = 300
    # hybrid encoder
    encoder_hidden_dim: int = 256
    encoder_in_channels: tuple[int, ...] = (512, 1024, 2048)
    feat_strides: tuple[int, ...] = (8, 16, 32)
    encoder_ffn_dim: int = 1024
    encode_proj_layers: tuple[int, ...] = (2,)
    encoder_layers: int = 1
    encoder_attention_heads: int = 8
    encoder_activation_function: str = "gelu"
    activation_function: str = "silu"
    hidden_expansion: float = 1.0
    positional_encoding_temperature: float = 10000.0
    csp_num_blocks: int = 3
    # decoder
    decoder_ffn_dim: int = 1024
    num_feature_levels: int = 3
    decoder_n_points: int = 4
    decoder_layers: int = 6
    decoder_attention_heads: int = 8
    decoder_activation_function: str = "relu"
    learn_initial_query: bool = False
    anchor_grid_size: float = 0.05
    # v2-specific deformable-attention semantics (configuration_rt_detr_v2.py)
    decoder_offset_scale: float = 0.5
    decoder_method: str = "default"  # "default" (bilinear) | "discrete"
    version: int = 2
    layer_norm_eps: float = 1e-5
    batch_norm_eps: float = 1e-5
    id2label: tuple[tuple[int, str], ...] = ()

    @property
    def id2label_dict(self) -> dict[int, str]:
        return dict(self.id2label)

    @classmethod
    def from_hf(cls, hf) -> "RTDetrConfig":
        version = 2 if hf.model_type == "rt_detr_v2" else 1
        return cls(
            backbone=ResNetConfig.from_hf(hf.backbone_config),
            num_labels=hf.num_labels,
            d_model=hf.d_model,
            num_queries=hf.num_queries,
            encoder_hidden_dim=hf.encoder_hidden_dim,
            encoder_in_channels=tuple(hf.encoder_in_channels),
            feat_strides=tuple(hf.feat_strides),
            encoder_ffn_dim=hf.encoder_ffn_dim,
            encode_proj_layers=tuple(hf.encode_proj_layers),
            encoder_layers=hf.encoder_layers,
            encoder_attention_heads=hf.encoder_attention_heads,
            encoder_activation_function=hf.encoder_activation_function,
            activation_function=hf.activation_function,
            hidden_expansion=hf.hidden_expansion,
            positional_encoding_temperature=float(hf.positional_encoding_temperature),
            decoder_ffn_dim=hf.decoder_ffn_dim,
            num_feature_levels=hf.num_feature_levels,
            decoder_n_points=hf.decoder_n_points,
            decoder_layers=hf.decoder_layers,
            decoder_attention_heads=hf.decoder_attention_heads,
            decoder_activation_function=hf.decoder_activation_function,
            learn_initial_query=hf.learn_initial_query,
            decoder_offset_scale=getattr(hf, "decoder_offset_scale", 0.5),
            decoder_method=getattr(hf, "decoder_method", "default"),
            version=version,
            layer_norm_eps=hf.layer_norm_eps,
            batch_norm_eps=hf.batch_norm_eps,
            id2label=tuple(sorted((int(k), v) for k, v in hf.id2label.items())),
        )


@dataclass(frozen=True)
class DetrConfig:
    """DETR (facebook/detr-resnet-*) — CNN backbone + vanilla enc-dec transformer.

    Mirrors HF DetrConfig (configuration_detr.py); the reference serves this
    family through the same AutoModel boundary (serve.py:199-205).
    """

    backbone: "ResNetConfig" = field(
        default_factory=lambda: ResNetConfig(style="v1", out_indices=(4,))
    )
    num_labels: int = 91
    d_model: int = 256
    num_queries: int = 100
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 8
    decoder_attention_heads: int = 8
    encoder_ffn_dim: int = 2048
    decoder_ffn_dim: int = 2048
    activation_function: str = "relu"
    positional_encoding_temperature: float = 10000.0
    layer_norm_eps: float = 1e-5  # torch nn.LayerNorm default (DETR never overrides)
    # Table-Transformer (microsoft/table-transformer-*) is DETR with pre-norm
    # layers and a final encoder LayerNorm (modeling_table_transformer.py
    # normalizes before attention/FFN; DETR normalizes after)
    pre_norm: bool = False
    id2label: tuple[tuple[int, str], ...] = ()

    @property
    def id2label_dict(self) -> dict[int, str]:
        return dict(self.id2label)

    @classmethod
    def from_hf(cls, hf) -> "DetrConfig":
        check_no_dilation(hf)
        if hf.use_timm_backbone:
            backbone = timm_resnet_backbone(hf.backbone)
        else:
            backbone = replace(
                ResNetConfig.from_hf(hf.backbone_config),
                out_indices=(len(hf.backbone_config.depths),),
            )
        return cls(
            backbone=backbone,
            num_labels=hf.num_labels,
            d_model=hf.d_model,
            num_queries=hf.num_queries,
            encoder_layers=hf.encoder_layers,
            decoder_layers=hf.decoder_layers,
            encoder_attention_heads=hf.encoder_attention_heads,
            decoder_attention_heads=hf.decoder_attention_heads,
            encoder_ffn_dim=hf.encoder_ffn_dim,
            decoder_ffn_dim=hf.decoder_ffn_dim,
            activation_function=hf.activation_function,
            pre_norm=hf.model_type == "table-transformer",
            id2label=tuple(sorted((int(k), v) for k, v in hf.id2label.items())),
        )


@dataclass(frozen=True)
class ConditionalDetrConfig:
    """Conditional DETR (microsoft/conditional-detr-resnet-*).

    DETR-shaped encoder plus the conditional decoder (content/spatial
    decoupled cross-attention, reference-point box regression, focal
    classification without a "no-object" class). Mirrors HF
    ConditionalDetrConfig (configuration_conditional_detr.py).
    """

    backbone: "ResNetConfig" = field(
        default_factory=lambda: ResNetConfig(style="v1", out_indices=(4,))
    )
    num_labels: int = 91
    d_model: int = 256
    num_queries: int = 300
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 8
    decoder_attention_heads: int = 8
    encoder_ffn_dim: int = 2048
    decoder_ffn_dim: int = 2048
    activation_function: str = "relu"
    positional_encoding_temperature: float = 10000.0
    layer_norm_eps: float = 1e-5
    pre_norm: bool = False  # encoder layers are shared with DETR's post-norm
    id2label: tuple[tuple[int, str], ...] = ()

    @property
    def id2label_dict(self) -> dict[int, str]:
        return dict(self.id2label)

    @classmethod
    def from_hf(cls, hf) -> "ConditionalDetrConfig":
        check_no_dilation(hf)
        if hf.use_timm_backbone:
            backbone = timm_resnet_backbone(hf.backbone)
        else:
            backbone = replace(
                ResNetConfig.from_hf(hf.backbone_config),
                out_indices=(len(hf.backbone_config.depths),),
            )
        return cls(
            backbone=backbone,
            num_labels=hf.num_labels,
            d_model=hf.d_model,
            num_queries=hf.num_queries,
            encoder_layers=hf.encoder_layers,
            decoder_layers=hf.decoder_layers,
            encoder_attention_heads=hf.encoder_attention_heads,
            decoder_attention_heads=hf.decoder_attention_heads,
            encoder_ffn_dim=hf.encoder_ffn_dim,
            decoder_ffn_dim=hf.decoder_ffn_dim,
            activation_function=hf.activation_function,
            id2label=tuple(sorted((int(k), v) for k, v in hf.id2label.items())),
        )


def check_no_dilation(hf) -> None:
    """Reject dc5 checkpoints (timm `dilation=True` turns stage-4 stride into
    dilation-2 convs, which our ResNet doesn't model — converting anyway would
    produce a half-resolution final feature map and silently-garbage boxes)."""
    if getattr(hf, "dilation", False):
        raise ValueError(
            "dilated (dc5) backbones are not supported; use the non-dc5 checkpoint"
        )


# timm checkpoints name their backbone: facebook/detr-resnet-50/101 and
# microsoft/conditional-detr-resnet-* (bottleneck), microsoft/
# table-transformer-* (resnet18, basic blocks). One table shared by every
# DETR-lineage from_hf so new backbones are added in one place.
_TIMM_RESNET_PRESETS = {
    "resnet18": dict(
        layer_type="basic", depths=(2, 2, 2, 2), hidden_sizes=(64, 128, 256, 512)
    ),
    "resnet34": dict(
        layer_type="basic", depths=(3, 4, 6, 3), hidden_sizes=(64, 128, 256, 512)
    ),
    "resnet50": dict(depths=(3, 4, 6, 3)),
    "resnet101": dict(depths=(3, 4, 23, 3)),
}


def timm_resnet_backbone(name: str) -> ResNetConfig:
    if name not in _TIMM_RESNET_PRESETS:
        raise ValueError(
            f"Unsupported timm backbone {name!r}; known: {sorted(_TIMM_RESNET_PRESETS)}"
        )
    return ResNetConfig(style="v1", out_indices=(4,), **_TIMM_RESNET_PRESETS[name])


@dataclass(frozen=True)
class DabDetrConfig:
    """DAB-DETR (IDEA-Research/dab-detr-resnet-*) — DETR with 4D dynamic
    anchor-box queries: each query is a learned (x, y, w, h) anchor whose sine
    embedding conditions both self- and cross-attention, refined per decoder
    layer through a shared box head. Mirrors HF DabDetrConfig
    (configuration_dab_detr.py).
    """

    backbone: "ResNetConfig" = field(
        default_factory=lambda: ResNetConfig(style="v1", out_indices=(4,))
    )
    num_labels: int = 91
    d_model: int = 256  # hf "hidden_size"
    num_queries: int = 300
    query_dim: int = 4
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 8
    decoder_attention_heads: int = 8
    encoder_ffn_dim: int = 2048
    decoder_ffn_dim: int = 2048
    activation_function: str = "prelu"
    temperature_height: float = 20.0
    temperature_width: float = 20.0
    keep_query_pos: bool = False
    layer_norm_eps: float = 1e-5
    id2label: tuple[tuple[int, str], ...] = ()

    @property
    def id2label_dict(self) -> dict[int, str]:
        return dict(self.id2label)

    @classmethod
    def from_hf(cls, hf) -> "DabDetrConfig":
        check_no_dilation(hf)
        if hf.query_dim != 4:
            raise ValueError(f"Only query_dim=4 is supported, got {hf.query_dim}")
        if getattr(hf, "num_patterns", 0):
            raise ValueError("num_patterns > 0 is not supported")
        if getattr(hf, "normalize_before", False):
            raise ValueError("normalize_before (pre-norm) DAB-DETR is not supported")
        if hf.activation_function != "prelu":
            # the Flax model hardcodes the learned-PReLU FFN of the published
            # checkpoints; other activations carry no activation_fn.weight
            raise ValueError(
                f"Only activation_function='prelu' is supported, got "
                f"{hf.activation_function!r}"
            )
        if hf.use_timm_backbone:
            backbone = timm_resnet_backbone(hf.backbone)
        else:
            backbone = replace(
                ResNetConfig.from_hf(hf.backbone_config),
                out_indices=(len(hf.backbone_config.depths),),
            )
        return cls(
            backbone=backbone,
            num_labels=hf.num_labels,
            d_model=hf.hidden_size,
            num_queries=hf.num_queries,
            query_dim=hf.query_dim,
            encoder_layers=hf.encoder_layers,
            decoder_layers=hf.decoder_layers,
            encoder_attention_heads=hf.encoder_attention_heads,
            decoder_attention_heads=hf.decoder_attention_heads,
            encoder_ffn_dim=hf.encoder_ffn_dim,
            decoder_ffn_dim=hf.decoder_ffn_dim,
            activation_function=hf.activation_function,
            temperature_height=float(hf.temperature_height),
            temperature_width=float(hf.temperature_width),
            keep_query_pos=hf.keep_query_pos,
            id2label=tuple(sorted((int(k), v) for k, v in hf.id2label.items())),
        )


@dataclass(frozen=True)
class DeformableDetrConfig:
    """Deformable DETR (SenseTime/deformable-detr*) — multiscale deformable
    attention in BOTH encoder and decoder, with the plain / with-box-refine /
    two-stage variants. Mirrors HF DeformableDetrConfig
    (configuration_deformable_detr.py); the reference serves this family
    through the same AutoModel boundary (serve.py:199-205).
    """

    backbone: "ResNetConfig" = field(
        default_factory=lambda: ResNetConfig(style="v1", out_indices=(2, 3, 4))
    )
    num_labels: int = 91
    d_model: int = 256
    num_queries: int = 300
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 8
    decoder_attention_heads: int = 8
    encoder_ffn_dim: int = 1024
    decoder_ffn_dim: int = 1024
    activation_function: str = "relu"
    num_feature_levels: int = 4
    encoder_n_points: int = 4
    decoder_n_points: int = 4
    with_box_refine: bool = False
    two_stage: bool = False
    two_stage_num_proposals: int = 300
    positional_encoding_temperature: float = 10000.0
    layer_norm_eps: float = 1e-5  # torch nn.LayerNorm/GroupNorm default
    id2label: tuple[tuple[int, str], ...] = ()

    @property
    def id2label_dict(self) -> dict[int, str]:
        return dict(self.id2label)

    @property
    def num_pred_heads(self) -> int:
        # two-stage keeps one extra head pair for scoring encoder proposals
        return self.decoder_layers + (1 if self.two_stage else 0)

    @classmethod
    def from_hf(cls, hf) -> "DeformableDetrConfig":
        if hf.position_embedding_type != "sine":
            raise ValueError(
                f"Unsupported position_embedding_type {hf.position_embedding_type!r}"
            )
        check_no_dilation(hf)
        if hf.use_timm_backbone:
            out_indices = (2, 3, 4) if hf.num_feature_levels > 1 else (4,)
            backbone = replace(timm_resnet_backbone(hf.backbone), out_indices=out_indices)
        else:
            # the AutoBackbone path taps backbone_config.out_features as-is
            backbone = ResNetConfig.from_hf(hf.backbone_config)
        return cls(
            backbone=backbone,
            num_labels=hf.num_labels,
            d_model=hf.d_model,
            num_queries=hf.num_queries,
            encoder_layers=hf.encoder_layers,
            decoder_layers=hf.decoder_layers,
            encoder_attention_heads=hf.encoder_attention_heads,
            decoder_attention_heads=hf.decoder_attention_heads,
            encoder_ffn_dim=hf.encoder_ffn_dim,
            decoder_ffn_dim=hf.decoder_ffn_dim,
            activation_function=hf.activation_function,
            num_feature_levels=hf.num_feature_levels,
            encoder_n_points=hf.encoder_n_points,
            decoder_n_points=hf.decoder_n_points,
            with_box_refine=hf.with_box_refine,
            two_stage=hf.two_stage,
            two_stage_num_proposals=hf.two_stage_num_proposals,
            id2label=tuple(sorted((int(k), v) for k, v in hf.id2label.items())),
        )


@dataclass(frozen=True)
class YolosConfig:
    """YOLOS (hustvl/yolos-*) — plain ViT with appended detection tokens."""

    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    image_size: tuple[int, int] = (800, 1344)
    patch_size: int = 16
    num_channels: int = 3
    num_detection_tokens: int = 100
    use_mid_position_embeddings: bool = True
    qkv_bias: bool = True
    layer_norm_eps: float = 1e-12
    num_labels: int = 91
    id2label: tuple[tuple[int, str], ...] = ()

    @property
    def id2label_dict(self) -> dict[int, str]:
        return dict(self.id2label)

    @property
    def grid_hw(self) -> tuple[int, int]:
        return self.image_size[0] // self.patch_size, self.image_size[1] // self.patch_size

    @classmethod
    def from_hf(cls, hf) -> "YolosConfig":
        return cls(
            hidden_size=hf.hidden_size,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            intermediate_size=hf.intermediate_size,
            hidden_act=hf.hidden_act,
            image_size=tuple(hf.image_size),
            patch_size=hf.patch_size,
            num_channels=hf.num_channels,
            num_detection_tokens=hf.num_detection_tokens,
            use_mid_position_embeddings=hf.use_mid_position_embeddings,
            qkv_bias=hf.qkv_bias,
            layer_norm_eps=hf.layer_norm_eps,
            num_labels=hf.num_labels,
            id2label=tuple(sorted((int(k), v) for k, v in hf.id2label.items())),
        )


@dataclass(frozen=True)
class OwlViTTextConfig:
    """CLIP-style text tower of OWL-ViT."""

    vocab_size: int = 49408
    hidden_size: int = 512
    intermediate_size: int = 2048
    num_hidden_layers: int = 12
    num_attention_heads: int = 8
    max_position_embeddings: int = 16
    hidden_act: str = "quick_gelu"
    layer_norm_eps: float = 1e-5

    @classmethod
    def from_hf(cls, hf) -> "OwlViTTextConfig":
        return cls(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            max_position_embeddings=hf.max_position_embeddings,
            hidden_act=hf.hidden_act,
            layer_norm_eps=hf.layer_norm_eps,
        )


@dataclass(frozen=True)
class OwlViTVisionConfig:
    """CLIP-style vision tower of OWL-ViT."""

    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    image_size: int = 768
    patch_size: int = 32
    num_channels: int = 3
    hidden_act: str = "quick_gelu"
    layer_norm_eps: float = 1e-5

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @classmethod
    def from_hf(cls, hf) -> "OwlViTVisionConfig":
        return cls(
            hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            image_size=hf.image_size,
            patch_size=hf.patch_size,
            num_channels=hf.num_channels,
            hidden_act=hf.hidden_act,
            layer_norm_eps=hf.layer_norm_eps,
        )


@dataclass(frozen=True)
class OwlViTConfig:
    """OWL-ViT / OWLv2 open-vocabulary detector (google/owlvit-*, google/owlv2-*).

    OWLv2 is architecturally OWL-ViT plus an objectness head (and a
    pad-to-square preprocess handled by the serving spec); `objectness` is
    therefore the one config switch between the two families.
    """

    text: OwlViTTextConfig = field(default_factory=OwlViTTextConfig)
    vision: OwlViTVisionConfig = field(default_factory=OwlViTVisionConfig)
    projection_dim: int = 512
    objectness: bool = False  # True = OWLv2

    @classmethod
    def from_hf(cls, hf) -> "OwlViTConfig":
        return cls(
            text=OwlViTTextConfig.from_hf(hf.text_config),
            vision=OwlViTVisionConfig.from_hf(hf.vision_config),
            projection_dim=hf.projection_dim,
            objectness=hf.model_type == "owlv2",
        )


RESNET_PRESETS = {
    "r18": ResNetConfig(
        embedding_size=64, hidden_sizes=(64, 128, 256, 512), depths=(2, 2, 2, 2),
        layer_type="basic",
    ),
    "r34": ResNetConfig(
        embedding_size=64, hidden_sizes=(64, 128, 256, 512), depths=(3, 4, 6, 3),
        layer_type="basic",
    ),
    "r50": ResNetConfig(),
    "r101": ResNetConfig(depths=(3, 4, 23, 3)),
}

# Published RT-DETRv2 variants (PekingU/rtdetr_v2_*). When loading a checkpoint,
# `from_hf` of the checkpoint's own config takes precedence; presets exist for
# offline/synthetic use.
RTDETR_PRESETS = {
    "rtdetr_v2_r18vd": RTDetrConfig(
        backbone=RESNET_PRESETS["r18"],
        encoder_in_channels=(128, 256, 512),
        decoder_layers=3,
        hidden_expansion=0.5,
    ),
    "rtdetr_v2_r34vd": RTDetrConfig(
        backbone=RESNET_PRESETS["r34"],
        encoder_in_channels=(128, 256, 512),
        decoder_layers=4,
        hidden_expansion=0.5,
    ),
    "rtdetr_v2_r50vd": RTDetrConfig(),
    "rtdetr_v2_r101vd": RTDetrConfig(
        backbone=RESNET_PRESETS["r101"],
        encoder_hidden_dim=384,
        encoder_ffn_dim=2048,
    ),
}
