"""Config dataclasses for the detection model families.

Mirrors the semantic content of the HF configs (RTDetrV2Config etc.) so that a
checkpoint's config.json can be adapted 1:1 (`from_hf`), while staying plain
frozen dataclasses — hashable, so they can be static args under jax.jit.
"""

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ResNetConfig:
    """RT-DETR's ResNet-D backbone (deep 3-conv stem, avg-pool downsample shortcuts)."""

    num_channels: int = 3
    embedding_size: int = 64
    hidden_sizes: tuple[int, ...] = (256, 512, 1024, 2048)
    depths: tuple[int, ...] = (3, 4, 6, 3)
    layer_type: str = "bottleneck"  # "basic" | "bottleneck"
    hidden_act: str = "relu"
    downsample_in_first_stage: bool = False
    downsample_in_bottleneck: bool = False
    # indices into (stem, stage1, ..., stage4); RT-DETR taps strides 8/16/32
    out_indices: tuple[int, ...] = (2, 3, 4)

    @classmethod
    def from_hf(cls, hf) -> "ResNetConfig":
        return cls(
            num_channels=hf.num_channels,
            embedding_size=hf.embedding_size,
            hidden_sizes=tuple(hf.hidden_sizes),
            depths=tuple(hf.depths),
            layer_type=hf.layer_type,
            hidden_act=hf.hidden_act,
            downsample_in_first_stage=hf.downsample_in_first_stage,
            downsample_in_bottleneck=hf.downsample_in_bottleneck,
            out_indices=tuple(hf.out_indices),
        )


@dataclass(frozen=True)
class RTDetrConfig:
    """RT-DETR / RT-DETRv2 detector (hybrid encoder + deformable decoder)."""

    backbone: ResNetConfig = field(default_factory=ResNetConfig)
    num_labels: int = 80
    d_model: int = 256
    num_queries: int = 300
    # hybrid encoder
    encoder_hidden_dim: int = 256
    encoder_in_channels: tuple[int, ...] = (512, 1024, 2048)
    feat_strides: tuple[int, ...] = (8, 16, 32)
    encoder_ffn_dim: int = 1024
    encode_proj_layers: tuple[int, ...] = (2,)
    encoder_layers: int = 1
    encoder_attention_heads: int = 8
    encoder_activation_function: str = "gelu"
    activation_function: str = "silu"
    hidden_expansion: float = 1.0
    positional_encoding_temperature: float = 10000.0
    csp_num_blocks: int = 3
    # decoder
    decoder_ffn_dim: int = 1024
    num_feature_levels: int = 3
    decoder_n_points: int = 4
    decoder_layers: int = 6
    decoder_attention_heads: int = 8
    decoder_activation_function: str = "relu"
    learn_initial_query: bool = False
    anchor_grid_size: float = 0.05
    # v2-specific deformable-attention semantics (configuration_rt_detr_v2.py)
    decoder_offset_scale: float = 0.5
    decoder_method: str = "default"  # "default" (bilinear) | "discrete"
    version: int = 2
    layer_norm_eps: float = 1e-5
    batch_norm_eps: float = 1e-5
    id2label: tuple[tuple[int, str], ...] = ()

    @property
    def id2label_dict(self) -> dict[int, str]:
        return dict(self.id2label)

    @classmethod
    def from_hf(cls, hf) -> "RTDetrConfig":
        version = 2 if hf.model_type == "rt_detr_v2" else 1
        return cls(
            backbone=ResNetConfig.from_hf(hf.backbone_config),
            num_labels=hf.num_labels,
            d_model=hf.d_model,
            num_queries=hf.num_queries,
            encoder_hidden_dim=hf.encoder_hidden_dim,
            encoder_in_channels=tuple(hf.encoder_in_channels),
            feat_strides=tuple(hf.feat_strides),
            encoder_ffn_dim=hf.encoder_ffn_dim,
            encode_proj_layers=tuple(hf.encode_proj_layers),
            encoder_layers=hf.encoder_layers,
            encoder_attention_heads=hf.encoder_attention_heads,
            encoder_activation_function=hf.encoder_activation_function,
            activation_function=hf.activation_function,
            hidden_expansion=hf.hidden_expansion,
            positional_encoding_temperature=float(hf.positional_encoding_temperature),
            decoder_ffn_dim=hf.decoder_ffn_dim,
            num_feature_levels=hf.num_feature_levels,
            decoder_n_points=hf.decoder_n_points,
            decoder_layers=hf.decoder_layers,
            decoder_attention_heads=hf.decoder_attention_heads,
            decoder_activation_function=hf.decoder_activation_function,
            learn_initial_query=hf.learn_initial_query,
            decoder_offset_scale=getattr(hf, "decoder_offset_scale", 0.5),
            decoder_method=getattr(hf, "decoder_method", "default"),
            version=version,
            layer_norm_eps=hf.layer_norm_eps,
            batch_norm_eps=hf.batch_norm_eps,
            id2label=tuple(sorted((int(k), v) for k, v in hf.id2label.items())),
        )


RESNET_PRESETS = {
    "r18": ResNetConfig(
        embedding_size=64, hidden_sizes=(64, 128, 256, 512), depths=(2, 2, 2, 2),
        layer_type="basic",
    ),
    "r34": ResNetConfig(
        embedding_size=64, hidden_sizes=(64, 128, 256, 512), depths=(3, 4, 6, 3),
        layer_type="basic",
    ),
    "r50": ResNetConfig(),
    "r101": ResNetConfig(depths=(3, 4, 23, 3)),
}

# Published RT-DETRv2 variants (PekingU/rtdetr_v2_*). When loading a checkpoint,
# `from_hf` of the checkpoint's own config takes precedence; presets exist for
# offline/synthetic use.
RTDETR_PRESETS = {
    "rtdetr_v2_r18vd": RTDetrConfig(
        backbone=RESNET_PRESETS["r18"],
        encoder_in_channels=(128, 256, 512),
        decoder_layers=3,
        hidden_expansion=0.5,
    ),
    "rtdetr_v2_r34vd": RTDetrConfig(
        backbone=RESNET_PRESETS["r34"],
        encoder_in_channels=(128, 256, 512),
        decoder_layers=4,
        hidden_expansion=0.5,
    ),
    "rtdetr_v2_r50vd": RTDetrConfig(),
    "rtdetr_v2_r101vd": RTDetrConfig(
        backbone=RESNET_PRESETS["r101"],
        encoder_hidden_dim=384,
        encoder_ffn_dim=2048,
    ),
}
