"""Flax model implementations for the spotter-tpu detection families.

The reference serves arbitrary HF object-detection checkpoints via
`AutoModelForObjectDetection` selected by env MODEL_NAME
(apps/spotter/src/spotter/serve.py:199-205). Here each supported family is a
TPU-first Flax implementation plus a torch->JAX weight converter; the registry
in `spotter_tpu.models.registry` plays the AutoModel role.
"""

from spotter_tpu.models.registry import build_detector, MODEL_REGISTRY  # noqa: F401
