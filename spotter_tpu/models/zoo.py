"""Model zoo: loaders that turn a MODEL_NAME into a BuiltDetector.

The loading boundary mirrors the reference's
`AutoModelForObjectDetection.from_pretrained(MODEL_NAME)` (serve.py:203):
torch weights come from the local HF cache (baked into the serving image the
way the reference bakes them — Dockerfile:17, download.py), get converted to
Flax params once, and are cached as an Orbax checkpoint keyed by MODEL_NAME
so later pod starts skip torch entirely.

Offline/test path: SPOTTER_TPU_TINY=1 builds a tiny random-init model (no
network, no torch) — the serving stack's equivalent of the reference tests'
MagicMock model (test_serve.py:24-28), but running the real engine.
"""

import logging
import os

import jax
import numpy as np

from spotter_tpu.engine.engine import BuiltDetector
from spotter_tpu.models.coco import coco_id2label_80
from spotter_tpu.models.configs import (
    RESNET_PRESETS,
    DetrConfig,
    ResNetConfig,
    RTDetrConfig,
    YolosConfig,
)
from spotter_tpu.models.detr import DetrDetector
from spotter_tpu.models.yolos import YolosDetector
from spotter_tpu.models.registry import ModelFamily, register
from spotter_tpu.models.rtdetr import RTDetrDetector
from spotter_tpu.ops.preprocess import (
    DETR_SPEC,
    IMAGENET_MEAN,
    IMAGENET_STD,
    RTDETR_SPEC,
    PreprocessSpec,
)

logger = logging.getLogger(__name__)

TINY_ENV = "SPOTTER_TPU_TINY"


def tiny_rtdetr_config(num_labels: int = 80) -> RTDetrConfig:
    return RTDetrConfig(
        backbone=ResNetConfig(
            embedding_size=16, hidden_sizes=(16, 24, 32, 48), depths=(1, 1, 1, 1),
            layer_type="basic",
        ),
        num_labels=num_labels,
        d_model=32,
        num_queries=30,
        encoder_hidden_dim=32,
        encoder_in_channels=(24, 32, 48),
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        decoder_layers=2,
        decoder_n_points=2,
        id2label=tuple(coco_id2label_80().items()),
    )


def _init_random(module, input_hw: tuple[int, int]) -> dict:
    h, w = input_hw
    variables = module.init(jax.random.PRNGKey(0), np.zeros((1, h, w, 3), np.float32))
    return variables["params"]


def _build_rtdetr(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_rtdetr_config()
        spec = PreprocessSpec(mode="fixed", size=(64, 64))
        module = RTDetrDetector(cfg)
        params = _init_random(module, spec.input_hw)
        logger.info("Built tiny random RT-DETR for %s (%s)", model_name, TINY_ENV)
    else:
        from spotter_tpu.convert.loader import load_rtdetr_from_hf  # lazy: needs torch

        cfg, params = load_rtdetr_from_hf(model_name)
        spec = RTDETR_SPEC
        module = RTDetrDetector(cfg)
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_topk",
        id2label=cfg.id2label_dict,
        num_top_queries=min(300, cfg.num_queries),
    )


def tiny_detr_config(num_labels: int = 80) -> DetrConfig:
    return DetrConfig(
        backbone=ResNetConfig(
            embedding_size=8, hidden_sizes=(8, 12, 16, 24), depths=(1, 1, 1, 1),
            layer_type="basic", style="v1", out_indices=(4,),
        ),
        num_labels=num_labels,
        d_model=32,
        num_queries=9,
        encoder_layers=1,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        id2label=tuple(coco_id2label_80().items()),
    )


def _build_detr(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_detr_config()
        spec = PreprocessSpec(
            mode="shortest_edge", size=(48, 64), mean=IMAGENET_MEAN, std=IMAGENET_STD,
            pad_to=(64, 64),
        )
        module = DetrDetector(cfg)
        params = _init_random(module, spec.input_hw)
        logger.info("Built tiny random DETR for %s (%s)", model_name, TINY_ENV)
    else:
        from spotter_tpu.convert.loader import load_detr_from_hf  # lazy: needs torch

        cfg, params = load_detr_from_hf(model_name)
        spec = DETR_SPEC
        module = DetrDetector(cfg)
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="softmax",
        id2label=cfg.id2label_dict,
        num_top_queries=cfg.num_queries,
        needs_mask=True,
    )


def tiny_yolos_config(num_labels: int = 80) -> YolosConfig:
    return YolosConfig(
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=48,
        image_size=(32, 48),
        patch_size=8,
        num_detection_tokens=5,
        num_labels=num_labels,
        id2label=tuple(coco_id2label_80().items()),
    )


def _build_yolos(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_yolos_config()
        module = YolosDetector(cfg)
        spec = PreprocessSpec(
            mode="fixed", size=cfg.image_size, mean=IMAGENET_MEAN, std=IMAGENET_STD
        )
        params = _init_random(module, spec.input_hw)
        logger.info("Built tiny random YOLOS for %s (%s)", model_name, TINY_ENV)
    else:
        from spotter_tpu.convert.loader import load_yolos_from_hf  # lazy: needs torch

        cfg, params = load_yolos_from_hf(model_name)
        module = YolosDetector(cfg)
        # Warp-resize to the trained image size: position tables apply exactly
        # and every shape is static. (The torch processor instead pads to the
        # batch max and interpolates position tables per size — a recompile
        # per shape under XLA.)
        spec = PreprocessSpec(
            mode="fixed", size=cfg.image_size, mean=IMAGENET_MEAN, std=IMAGENET_STD
        )
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="softmax",
        id2label=cfg.id2label_dict,
        num_top_queries=cfg.num_detection_tokens,
    )


register(
    ModelFamily(name="rtdetr", matches=("rtdetr", "rt_detr", "rt-detr"), build=_build_rtdetr)
)
register(ModelFamily(name="yolos", matches=("yolos",), build=_build_yolos))
register(
    # plain DETR; matched AFTER rtdetr so "rtdetr*" names never land here
    ModelFamily(name="detr", matches=("detr-resnet", "detr_resnet"), build=_build_detr)
)
