"""Model zoo: loaders that turn a MODEL_NAME into a BuiltDetector.

The loading boundary mirrors the reference's
`AutoModelForObjectDetection.from_pretrained(MODEL_NAME)` (serve.py:203):
torch weights come from the local HF cache (baked into the serving image the
way the reference bakes them — Dockerfile:17, download.py), get converted to
Flax params once, and are cached as an Orbax checkpoint keyed by MODEL_NAME
so later pod starts skip torch entirely.

Offline/test path: SPOTTER_TPU_TINY=1 builds a tiny random-init model (no
network, no torch) — the serving stack's equivalent of the reference tests'
MagicMock model (test_serve.py:24-28), but running the real engine.
"""

import logging
import os

import jax
import numpy as np

from spotter_tpu.engine.engine import BuiltDetector
from spotter_tpu.models.coco import coco_id2label_80
from spotter_tpu.models.configs import (
    ConditionalDetrConfig,
    RESNET_PRESETS,
    DabDetrConfig,
    DeformableDetrConfig,
    DetrConfig,
    OwlViTConfig,
    OwlViTTextConfig,
    OwlViTVisionConfig,
    ResNetConfig,
    RTDetrConfig,
    YolosConfig,
)
from spotter_tpu.models.conditional_detr import ConditionalDetrDetector
from spotter_tpu.models.dab_detr import DabDetrDetector
from spotter_tpu.models.deformable_detr import DeformableDetrDetector
from spotter_tpu.models.detr import DetrDetector
from spotter_tpu.models.owlvit import OwlViTDetector
from spotter_tpu.models.yolos import YolosDetector
from spotter_tpu.models.registry import ModelFamily, register
from spotter_tpu.models.rtdetr import RTDetrDetector
from spotter_tpu.utils.precision import backbone_dtype, compute_dtype
from spotter_tpu.ops.preprocess import (
    CLIP_MEAN,
    CLIP_STD,
    DETR_SPEC,
    IMAGENET_MEAN,
    IMAGENET_STD,
    OWLV2_SPEC,
    OWLVIT_SPEC,
    RTDETR_SPEC,
    PreprocessSpec,
)

logger = logging.getLogger(__name__)

TINY_ENV = "SPOTTER_TPU_TINY"


def tiny_rtdetr_config(num_labels: int = 80) -> RTDetrConfig:
    return RTDetrConfig(
        backbone=ResNetConfig(
            embedding_size=16, hidden_sizes=(16, 24, 32, 48), depths=(1, 1, 1, 1),
            layer_type="basic",
        ),
        num_labels=num_labels,
        d_model=32,
        num_queries=30,
        encoder_hidden_dim=32,
        encoder_in_channels=(24, 32, 48),
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        decoder_layers=2,
        decoder_n_points=2,
        id2label=tuple(coco_id2label_80().items()),
    )


def _init_random(module, input_hw: tuple[int, int]) -> dict:
    h, w = input_hw
    variables = module.init(jax.random.PRNGKey(0), np.zeros((1, h, w, 3), np.float32))
    return variables["params"]


def _build_rtdetr(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_rtdetr_config()
        spec = PreprocessSpec(mode="fixed", size=(64, 64))
        module = RTDetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
        params = _init_random(module, spec.input_hw)
        logger.info("Built tiny random RT-DETR for %s (%s)", model_name, TINY_ENV)
    else:
        from spotter_tpu.convert.loader import load_rtdetr_from_hf  # lazy: needs torch

        cfg, params = load_rtdetr_from_hf(model_name)
        spec = RTDETR_SPEC
        module = RTDetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_topk",
        id2label=cfg.id2label_dict,
        num_top_queries=min(300, cfg.num_queries),
    )


def tiny_detr_config(num_labels: int = 80) -> DetrConfig:
    return DetrConfig(
        backbone=ResNetConfig(
            embedding_size=8, hidden_sizes=(8, 12, 16, 24), depths=(1, 1, 1, 1),
            layer_type="basic", style="v1", out_indices=(4,),
        ),
        num_labels=num_labels,
        d_model=32,
        num_queries=9,
        encoder_layers=1,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        id2label=tuple(coco_id2label_80().items()),
    )


def _build_detr(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_detr_config()
        spec = PreprocessSpec(
            mode="shortest_edge", size=(48, 64), mean=IMAGENET_MEAN, std=IMAGENET_STD,
            pad_to=(64, 64),
        )
        module = DetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
        params = _init_random(module, spec.input_hw)
        logger.info("Built tiny random DETR for %s (%s)", model_name, TINY_ENV)
    else:
        from spotter_tpu.convert.loader import load_detr_from_hf  # lazy: needs torch

        cfg, params = load_detr_from_hf(model_name)
        spec = DETR_SPEC
        module = DetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="softmax",
        id2label=cfg.id2label_dict,
        num_top_queries=cfg.num_queries,
        needs_mask=True,
    )


def tiny_yolos_config(num_labels: int = 80) -> YolosConfig:
    return YolosConfig(
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=48,
        image_size=(32, 48),
        patch_size=8,
        num_detection_tokens=5,
        num_labels=num_labels,
        id2label=tuple(coco_id2label_80().items()),
    )


def _build_yolos(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_yolos_config()
        # The ViT body IS the HBM-bound half of this model (there is no CNN
        # backbone), so it follows the backbone dtype: bf16 under "mixed"
        # (measured v5e: the fp32 body is bandwidth-bound at 4300 tokens).
        # Heads/logits/boxes stay fp32 inside the module.
        module = YolosDetector(cfg, dtype=backbone_dtype())
        spec = PreprocessSpec(
            mode="fixed", size=cfg.image_size, mean=IMAGENET_MEAN, std=IMAGENET_STD
        )
        params = _init_random(module, spec.input_hw)
        logger.info("Built tiny random YOLOS for %s (%s)", model_name, TINY_ENV)
    else:
        from spotter_tpu.convert.loader import load_yolos_from_hf  # lazy: needs torch

        cfg, params = load_yolos_from_hf(model_name)
        module = YolosDetector(cfg, dtype=backbone_dtype())  # see tiny note
        # Warp-resize to the trained image size: position tables apply exactly
        # and every shape is static. (The torch processor instead pads to the
        # batch max and interpolates position tables per size — a recompile
        # per shape under XLA.)
        spec = PreprocessSpec(
            mode="fixed", size=cfg.image_size, mean=IMAGENET_MEAN, std=IMAGENET_STD
        )
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="softmax",
        id2label=cfg.id2label_dict,
        num_top_queries=cfg.num_detection_tokens,
    )


def tiny_owlvit_config() -> OwlViTConfig:
    return OwlViTConfig(
        text=OwlViTTextConfig(
            vocab_size=99, hidden_size=16, intermediate_size=24,
            num_hidden_layers=2, num_attention_heads=2, max_position_embeddings=8,
        ),
        vision=OwlViTVisionConfig(
            hidden_size=20, intermediate_size=28, num_hidden_layers=2,
            num_attention_heads=2, image_size=32, patch_size=8,
        ),
        projection_dim=16,
    )


QUERIES_ENV = "SPOTTER_TPU_TEXT_QUERIES"


def owlvit_query_labels() -> list[str]:
    """Deploy-time label set for open-vocab detection.

    Defaults to the amenity taxonomy's COCO labels (so the downstream
    AMENITIES_MAPPING filter behaves exactly as with closed-set detectors);
    operators override with a comma-separated SPOTTER_TPU_TEXT_QUERIES — the
    capability the reference's fixed-vocab models cannot offer.
    """
    env = os.environ.get(QUERIES_ENV, "")
    if env.strip():
        labels = [s.strip() for s in env.split(",") if s.strip()]
        if not labels:
            raise ValueError(
                f"{QUERIES_ENV} is set but contains no labels: {env!r}"
            )
        return labels
    from spotter_tpu.taxonomy import AMENITIES_MAPPING

    return list(AMENITIES_MAPPING)


def _tiny_tokenize(prompts: list[str], vocab_size: int, t: int):
    """Deterministic pseudo-tokenizer for the tiny (no-torch) OWL-ViT: each
    prompt hashes to a stable token sequence, so runtime `encode_text` of the
    same query string is reproducible across processes (the text-embedding
    cache key contract) without an HF tokenizer in the image."""
    import hashlib

    rows = []
    for p in prompts:
        seed = int.from_bytes(hashlib.sha256(p.encode()).digest()[:8], "little")
        rng = np.random.default_rng(seed)
        rows.append(rng.integers(1, vocab_size, (t,)))
    ids = np.stack(rows).astype(np.int32)
    return ids, np.ones_like(ids)


def _build_owlvit(model_name: str) -> BuiltDetector:
    labels = owlvit_query_labels()
    prompts = [f"a photo of a {label}" for label in labels]
    tiny = bool(os.environ.get(TINY_ENV))
    if tiny:
        cfg = tiny_owlvit_config()
        module = OwlViTDetector(
            cfg, dtype=compute_dtype(), vision_dtype=backbone_dtype()
        )
        spec = PreprocessSpec(mode="fixed", size=(32, 32), mean=CLIP_MEAN, std=CLIP_STD)
        ids, mask = _tiny_tokenize(
            prompts, cfg.text.vocab_size, cfg.text.max_position_embeddings
        )
        params = module.init(
            jax.random.PRNGKey(0),
            np.zeros((1, 32, 32, 3), np.float32),
            ids,
            mask,
            method=OwlViTDetector.detect_with_text,
        )["params"]
        logger.info("Built tiny random OWL-ViT for %s (%s)", model_name, TINY_ENV)
    else:
        from spotter_tpu.convert.loader import (  # lazy: needs torch first time
            load_owlvit_from_hf,
            owlvit_tokenize,
        )

        cfg, params = load_owlvit_from_hf(model_name)
        module = OwlViTDetector(
            cfg, dtype=compute_dtype(), vision_dtype=backbone_dtype()
        )
        spec = OWLV2_SPEC if cfg.objectness else OWLVIT_SPEC
        ids, mask = owlvit_tokenize(model_name, prompts, cfg.text.max_position_embeddings)
    # TPU-first split: the text tower runs ONCE here; the serving hot path is
    # vision-only with the (Q, proj) query matrix riding as a jit constant.
    query_embeds = np.asarray(
        module.apply({"params": params}, ids, mask, method=OwlViTDetector.encode_text)
    )

    def encode_text(queries: list[str]) -> np.ndarray:
        """Runtime text encoder for the open-vocabulary /detect path: query
        strings -> normalized (Q, proj) embeddings, same prompt template and
        text tower as the build-time vocabulary. Callers cache the result
        (caching/text_cache.py) so a repeated vocabulary costs one encode."""
        q_prompts = [f"a photo of a {q}" for q in queries]
        if tiny:
            q_ids, q_mask = _tiny_tokenize(
                q_prompts, cfg.text.vocab_size, cfg.text.max_position_embeddings
            )
        else:
            from spotter_tpu.convert.loader import owlvit_tokenize  # lazy

            q_ids, q_mask = owlvit_tokenize(
                model_name, q_prompts, cfg.text.max_position_embeddings
            )
        return np.asarray(
            module.apply(
                {"params": params}, q_ids, q_mask,
                method=OwlViTDetector.encode_text,
            ),
            np.float32,
        )

    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_max",
        id2label=dict(enumerate(labels)),
        num_top_queries=len(labels),
        apply_kwargs={"query_embeds": query_embeds},
        text_encoder=encode_text,
    )



def tiny_conditional_detr_config(num_labels: int = 80) -> ConditionalDetrConfig:
    return ConditionalDetrConfig(
        backbone=ResNetConfig(
            embedding_size=8, hidden_sizes=(8, 12, 16, 24), depths=(1, 1, 1, 1),
            layer_type="basic", style="v1", out_indices=(4,),
        ),
        num_labels=num_labels,
        d_model=32,
        num_queries=9,
        encoder_layers=1,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        id2label=tuple(coco_id2label_80().items()),
    )


def _build_conditional_detr(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_conditional_detr_config()
        spec = PreprocessSpec(
            mode="shortest_edge", size=(48, 64), mean=IMAGENET_MEAN, std=IMAGENET_STD,
            pad_to=(64, 64),
        )
        module = ConditionalDetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
        params = _init_random(module, spec.input_hw)
        logger.info(
            "Built tiny random Conditional-DETR for %s (%s)", model_name, TINY_ENV
        )
    else:
        from spotter_tpu.convert.loader import (  # lazy: needs torch
            load_conditional_detr_from_hf,
        )

        cfg, params = load_conditional_detr_from_hf(model_name)
        spec = DETR_SPEC
        module = ConditionalDetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_topk",  # focal head, NMS-free top-k like RT-DETR
        id2label=cfg.id2label_dict,
        # ConditionalDetrImageProcessor.post_process_object_detection defaults
        # to top_k=100; matching it keeps the serve contract identical
        num_top_queries=min(100, cfg.num_queries),
        needs_mask=True,
    )


def tiny_deformable_detr_config(num_labels: int = 80) -> DeformableDetrConfig:
    return DeformableDetrConfig(
        backbone=ResNetConfig(
            embedding_size=8, hidden_sizes=(8, 12, 16, 24), depths=(1, 1, 1, 1),
            layer_type="basic", style="v1", out_indices=(2, 3, 4),
        ),
        num_labels=num_labels,
        d_model=32,
        num_queries=9,
        encoder_layers=1,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        encoder_n_points=2,
        decoder_n_points=2,
        with_box_refine=True,
        id2label=tuple(coco_id2label_80().items()),
    )


def _build_deformable_detr(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_deformable_detr_config()
        spec = PreprocessSpec(
            mode="shortest_edge", size=(48, 64), mean=IMAGENET_MEAN, std=IMAGENET_STD,
            pad_to=(64, 64),
        )
        module = DeformableDetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
        params = _init_random(module, spec.input_hw)
        logger.info(
            "Built tiny random Deformable-DETR for %s (%s)", model_name, TINY_ENV
        )
    else:
        from spotter_tpu.convert.loader import (  # lazy: needs torch
            load_deformable_detr_from_hf,
        )

        cfg, params = load_deformable_detr_from_hf(model_name)
        spec = DETR_SPEC
        module = DeformableDetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_topk",  # focal head, NMS-free top-k (HF top_k=100)
        id2label=cfg.id2label_dict,
        num_top_queries=min(100, cfg.num_queries),
        needs_mask=True,
    )


def tiny_dab_detr_config(num_labels: int = 80) -> DabDetrConfig:
    return DabDetrConfig(
        backbone=ResNetConfig(
            embedding_size=8, hidden_sizes=(8, 12, 16, 24), depths=(1, 1, 1, 1),
            layer_type="basic", style="v1", out_indices=(4,),
        ),
        num_labels=num_labels,
        d_model=32,
        num_queries=9,
        encoder_layers=1,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        id2label=tuple(coco_id2label_80().items()),
    )


def _build_dab_detr(model_name: str) -> BuiltDetector:
    if os.environ.get(TINY_ENV):
        cfg = tiny_dab_detr_config()
        spec = PreprocessSpec(
            mode="shortest_edge", size=(48, 64), mean=IMAGENET_MEAN, std=IMAGENET_STD,
            pad_to=(64, 64),
        )
        module = DabDetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
        params = _init_random(module, spec.input_hw)
        logger.info("Built tiny random DAB-DETR for %s (%s)", model_name, TINY_ENV)
    else:
        from spotter_tpu.convert.loader import load_dab_detr_from_hf  # lazy: needs torch

        cfg, params = load_dab_detr_from_hf(model_name)
        spec = DETR_SPEC
        module = DabDetrDetector(
            cfg, dtype=compute_dtype(), backbone_dtype=backbone_dtype()
        )
    return BuiltDetector(
        model_name=model_name,
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_topk",  # focal head, NMS-free top-k
        id2label=cfg.id2label_dict,
        # HF DAB-DETR has no processor of its own; its checkpoints pair with
        # ConditionalDetrImageProcessor, whose post_process_object_detection
        # defaults to top_k=100 — detections ranked 101+ would never be
        # returned by the reference serve path
        num_top_queries=min(100, cfg.num_queries),
        needs_mask=True,
    )


# Per-family TP rule sets (ISSUE 13): the registry is where the serving
# bootstrap looks them up, so tp>1 shards the weights of the family actually
# being served. All current families speak the shared layers.py transformer
# vocabulary (fc1/fc2, q/k/v/out_proj); OWL-ViT keeps its own name for the
# towers-specific documentation in sharding.py.
from spotter_tpu.parallel.sharding import (  # noqa: E402  (after model imports)
    OWLVIT_TP_RULES,
    RTDETR_TP_RULES,
    TRANSFORMER_TP_RULES,
    VIT_TP_RULES,
)

# Registration order carries no precedence: family_for resolves ambiguous
# names ("dab-detr-resnet-50" contains both "dab-detr" and "detr-resnet")
# by earliest-start-then-longest match, so the specific family always wins.
register(
    ModelFamily(
        name="conditional_detr",
        matches=("conditional-detr", "conditional_detr"),
        build=_build_conditional_detr,
        tp_rules=tuple(TRANSFORMER_TP_RULES),
    )
)
register(
    ModelFamily(
        name="dab_detr", matches=("dab-detr", "dab_detr"), build=_build_dab_detr,
        tp_rules=tuple(TRANSFORMER_TP_RULES),
    )
)
register(
    ModelFamily(
        name="deformable_detr",
        matches=("deformable-detr", "deformable_detr"),
        build=_build_deformable_detr,
        tp_rules=tuple(TRANSFORMER_TP_RULES),
    )
)
register(
    ModelFamily(
        name="rtdetr", matches=("rtdetr", "rt_detr", "rt-detr"),
        build=_build_rtdetr, tp_rules=tuple(RTDETR_TP_RULES),
    )
)
register(
    ModelFamily(
        name="owlvit",  # OWL-ViT and OWLv2 (same architecture + objectness head)
        matches=("owlvit", "owl-vit", "owl_vit", "owlv2", "owl-v2", "owl_v2"),
        build=_build_owlvit,
        tp_rules=tuple(OWLVIT_TP_RULES),
    )
)
register(ModelFamily(
    name="yolos", matches=("yolos",), build=_build_yolos,
    tp_rules=tuple(VIT_TP_RULES),
))
register(
    # plain DETR (+ Table-Transformer, a pre-norm DETR with identical keys)
    ModelFamily(
        name="detr",
        matches=("detr-resnet", "detr_resnet", "table-transformer", "table_transformer"),
        build=_build_detr,
        tp_rules=tuple(TRANSFORMER_TP_RULES),
    )
)
