"""Flax Deformable DETR (SenseTime/deformable-detr*) — TPU-first implementation.

Replaces the reference's torch `AutoModelForObjectDetection` forward
(apps/spotter/src/spotter/serve.py:99-100) for MODEL_NAME values in the
SenseTime/deformable-detr family. Architecture semantics follow HF's
modeling_deformable_detr.py: multiscale deformable attention in BOTH the
encoder (self-attention over the flattened multi-level feature map) and the
decoder (cross-attention from object queries), with the three published
variants — plain, `with_box_refine` (per-layer box heads iteratively refining
reference boxes), and `two_stage` (encoder proposals seed the object queries).

TPU-first notes:
- all sampling grids, per-level position tables, and level spans come from
  static spatial shapes (numpy at trace time) so XLA constant-folds them; the
  only data-dependent values are pixel-mask contents (valid ratios, cumsum
  position embeddings) — shapes never change and jit compiles one program
  per input bucket;
- both encoder and decoder deformable attention run through the shared
  sampling core (spotter_tpu/ops/msda.py): the gather-free level-split
  one-hot Pallas kernel on TPU (the encoder's Q == S self-attention is
  exactly the regime where XLA's gather lowering collapses), XLA row-gathers
  elsewhere;
- box-refinement arithmetic and head outputs stay fp32 under bf16 compute,
  matching the repo-wide ±1 px golden-box policy.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from spotter_tpu.models.configs import DeformableDetrConfig
from spotter_tpu.models.detr import nearest_downsample_mask
from spotter_tpu.models.layers import (
    MLPHead,
    MultiHeadAttention,
    get_activation,
    inverse_sigmoid,
)
from spotter_tpu.models.resnet import ResNetBackbone
from spotter_tpu.ops.msda import (
    deformable_sampling,
    encoder_presorted,
    locality_presort,
    presort_wanted,
)
from spotter_tpu.ops.topk import top_k as fast_top_k


def sine_position_from_mask_offset(
    mask: jnp.ndarray, embed_dim: int, temperature: float = 10000.0
) -> jnp.ndarray:
    """DeformableDetrSinePositionEmbedding(normalize=True): (B, h, w) -> (B, h, w, 2*half).

    Like DETR's mask sine embedding but with the deformable lineage's half-cell
    shift: coords are (cumsum - 0.5) / total * 2*pi (modeling_deformable_detr.py
    normalizes `y_embed - 0.5`; DETR does not subtract).
    """
    half = embed_dim
    scale = 2.0 * math.pi
    y = jnp.cumsum(mask, axis=1)
    x = jnp.cumsum(mask, axis=2)
    y = (y - 0.5) / (y[:, -1:, :] + 1e-6) * scale
    x = (x - 0.5) / (x[:, :, -1:] + 1e-6) * scale
    dim_t = temperature ** (2.0 * (np.arange(half, dtype=np.float32) // 2) / half)
    pos_x = x[..., None] / dim_t
    pos_y = y[..., None] / dim_t

    def interleave(p):
        return jnp.stack([jnp.sin(p[..., 0::2]), jnp.cos(p[..., 1::2])], axis=-1).reshape(
            *p.shape[:-1], -1
        )

    return jnp.concatenate([interleave(pos_y), interleave(pos_x)], axis=-1)


def encoder_reference_base(
    spatial_shapes: tuple[tuple[int, int], ...],
) -> np.ndarray:
    """Static (S, 2) xy cell centers, each normalized by its own level's dims."""
    out = []
    for h, w in spatial_shapes:
        gy, gx = np.meshgrid(
            np.linspace(0.5, h - 0.5, h, dtype=np.float32),
            np.linspace(0.5, w - 0.5, w, dtype=np.float32),
            indexing="ij",
        )
        out.append(np.stack([gx / w, gy / h], axis=-1).reshape(h * w, 2))
    return np.concatenate(out, axis=0)


def proposal_position_embedding(
    coord_logits: jnp.ndarray, d_model: int, temperature: float = 10000.0
) -> jnp.ndarray:
    """get_proposal_pos_embed: (B, K, 4) box logits -> (B, K, 2*d_model) sines."""
    num_pos_feats = d_model // 2
    dim_t = temperature ** (
        2.0 * (np.arange(num_pos_feats, dtype=np.float32) // 2) / num_pos_feats
    )
    proposals = nn.sigmoid(coord_logits) * (2.0 * math.pi)
    pos = proposals[..., None] / dim_t  # (B, K, 4, num_pos_feats)
    pos = jnp.stack([jnp.sin(pos[..., 0::2]), jnp.cos(pos[..., 1::2])], axis=-1)
    return pos.reshape(*coord_logits.shape[:2], -1)


class MsdaAttention(nn.Module):
    """Multiscale deformable attention (Deformable-DETR semantics).

    Handles both reference-point layouts of the lineage: 2-coordinate points
    (offsets normalized by each level's (w, h)) and 4-coordinate boxes
    (offsets scaled by box size / n_points * 0.5). `reference_points` arrives
    per level, already valid-ratio scaled: (B, Q, L, 2 or 4).
    """

    d_model: int
    num_heads: int
    num_levels: int
    num_points: int
    dtype: jnp.dtype = jnp.float32
    presorted: bool = False

    @nn.compact
    def __call__(
        self,
        hidden_states: jnp.ndarray,  # (B, Q, D)
        position_embeddings: Optional[jnp.ndarray],
        encoder_hidden_states: jnp.ndarray,  # (B, S, D)
        reference_points: jnp.ndarray,  # (B, Q, L, 2|4)
        spatial_shapes: tuple[tuple[int, int], ...],
        value_mask: Optional[jnp.ndarray] = None,  # (B, S) 1=valid
    ) -> jnp.ndarray:
        b, q, _ = hidden_states.shape
        heads, levels, points = self.num_heads, self.num_levels, self.num_points
        head_dim = self.d_model // heads
        hs = hidden_states
        if position_embeddings is not None:
            hs = hs + position_embeddings

        value = nn.Dense(self.d_model, dtype=self.dtype, name="value_proj")(
            encoder_hidden_states
        )
        if value_mask is not None:
            value = value * value_mask[..., None].astype(value.dtype)
        s = value.shape[1]
        value = value.reshape(b, s, heads, head_dim)

        offsets = nn.Dense(
            heads * levels * points * 2, dtype=self.dtype, name="sampling_offsets"
        )(hs).reshape(b, q, heads, levels, points, 2)
        attn = nn.Dense(heads * levels * points, dtype=self.dtype, name="attention_weights")(
            hs
        ).reshape(b, q, heads, levels * points)
        attn = nn.softmax(attn.astype(jnp.float32), axis=-1).astype(self.dtype)

        if reference_points.shape[-1] == 2:
            # (L, 2) as (w, h) — offsets are in source cells of each level
            normalizer = np.asarray(
                [[w, h] for (h, w) in spatial_shapes], np.float32
            )[None, None, None, :, None, :]
            loc = (
                reference_points[:, :, None, :, None, :]
                + offsets / jnp.asarray(normalizer, offsets.dtype)
            )
        else:
            ref_xy = reference_points[:, :, None, :, None, :2]
            ref_wh = reference_points[:, :, None, :, None, 2:]
            loc = ref_xy + offsets / points * ref_wh * 0.5
        loc = loc.reshape(b, q, heads, levels * points, 2)

        out = deformable_sampling(
            value, loc, attn, spatial_shapes, points, presorted=self.presorted
        )
        return nn.Dense(self.d_model, dtype=self.dtype, name="output_proj")(out)


class DeformableEncoderLayer(nn.Module):
    """Post-norm encoder layer: MSDA self-attention + FFN."""

    config: DeformableDetrConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        hidden: jnp.ndarray,
        pos: jnp.ndarray,
        reference_points: jnp.ndarray,
        spatial_shapes: tuple[tuple[int, int], ...],
        value_mask: Optional[jnp.ndarray],
    ) -> jnp.ndarray:
        cfg = self.config
        # Encoder self-attention queries ARE the grid tokens, which arrive
        # level-major row-major — already ordered by spatial locality — so
        # the in-op argsort + two q-row permutes over the full token set
        # (10k+ at 800x1333) are skipped by default; wide-offset checkpoints
        # can restore the in-op sort via SPOTTER_TPU_MSDA_ENC_PRESORTED=0
        # (ops/msda.py presorted contract / encoder_presorted).
        attn_out = MsdaAttention(
            cfg.d_model,
            cfg.encoder_attention_heads,
            cfg.num_feature_levels,
            cfg.encoder_n_points,
            dtype=self.dtype,
            presorted=encoder_presorted(),
            name="self_attn",
        )(hidden, pos, hidden, reference_points, spatial_shapes, value_mask)
        hidden = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="self_attn_layer_norm"
        )(hidden + attn_out)
        y = nn.Dense(cfg.encoder_ffn_dim, dtype=self.dtype, name="fc1")(hidden)
        y = get_activation(cfg.activation_function)(y)
        y = nn.Dense(cfg.d_model, dtype=self.dtype, name="fc2")(y)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm"
        )(hidden + y)


class DeformableDecoderLayer(nn.Module):
    """Post-norm decoder layer: query self-attention + MSDA cross-attention + FFN."""

    config: DeformableDetrConfig
    dtype: jnp.dtype = jnp.float32
    presorted: bool = False

    @nn.compact
    def __call__(
        self,
        hidden: jnp.ndarray,
        query_pos: jnp.ndarray,
        memory: jnp.ndarray,
        reference_points: jnp.ndarray,
        spatial_shapes: tuple[tuple[int, int], ...],
        value_mask: Optional[jnp.ndarray],
    ) -> jnp.ndarray:
        cfg = self.config
        eps = cfg.layer_norm_eps
        attn_out = MultiHeadAttention(
            cfg.d_model, cfg.decoder_attention_heads, dtype=self.dtype, name="self_attn"
        )(hidden, position_embeddings=query_pos)
        hidden = nn.LayerNorm(epsilon=eps, dtype=self.dtype, name="self_attn_layer_norm")(
            hidden + attn_out
        )
        cross = MsdaAttention(
            cfg.d_model,
            cfg.decoder_attention_heads,
            cfg.num_feature_levels,
            cfg.decoder_n_points,
            dtype=self.dtype,
            presorted=self.presorted,
            name="encoder_attn",
        )(hidden, query_pos, memory, reference_points, spatial_shapes, value_mask)
        hidden = nn.LayerNorm(epsilon=eps, dtype=self.dtype, name="encoder_attn_layer_norm")(
            hidden + cross
        )
        y = nn.Dense(cfg.decoder_ffn_dim, dtype=self.dtype, name="fc1")(hidden)
        y = get_activation(cfg.activation_function)(y)
        y = nn.Dense(cfg.d_model, dtype=self.dtype, name="fc2")(y)
        return nn.LayerNorm(epsilon=eps, dtype=self.dtype, name="final_layer_norm")(hidden + y)


class DeformableDetrDetector(nn.Module):
    """Full Deformable-DETR detector: pixels (B, H, W, 3) -> logits + boxes.

    Returns {"logits": (B, Q, C), "pred_boxes": (B, Q, 4) normalized cxcywh,
    "aux_logits"/"aux_boxes" stacked over decoder layers, and (two-stage)
    "enc_outputs_class"/"enc_outputs_coord_logits" for the proposal loss}.
    """

    config: DeformableDetrConfig
    dtype: jnp.dtype = jnp.float32
    # "mixed" policy: bf16 backbone convs, compute dtype for the transformer
    backbone_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(
        self, pixel_values: jnp.ndarray, pixel_mask: Optional[jnp.ndarray] = None
    ) -> dict[str, jnp.ndarray]:
        cfg = self.config
        b, h, w, _ = pixel_values.shape
        full_mask = pixel_mask is None
        if full_mask:
            pixel_mask = jnp.ones((b, h, w), dtype=jnp.float32)

        features = ResNetBackbone(
            cfg.backbone, dtype=self.backbone_dtype or self.dtype, name="backbone"
        )(pixel_values)
        features = [f.astype(self.dtype) for f in features]

        # --- input projection to d_model: 1x1 conv + GroupNorm(32) per level,
        # extra pyramid levels via 3x3 stride-2 convs on the LAST RAW backbone
        # feature (then on previous extra levels) ---
        sources = []
        for i, f in enumerate(features):
            src = nn.Conv(
                cfg.d_model, (1, 1), use_bias=True, dtype=self.dtype,
                name=f"input_proj{i}_conv",
            )(f)
            sources.append(
                nn.GroupNorm(
                    num_groups=32, epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                    name=f"input_proj{i}_norm",
                )(src)
            )
        for i in range(len(features), cfg.num_feature_levels):
            prev = features[-1] if i == len(features) else sources[-1]
            src = nn.Conv(
                cfg.d_model, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)],
                use_bias=True, dtype=self.dtype, name=f"input_proj{i}_conv",
            )(prev)
            sources.append(
                nn.GroupNorm(
                    num_groups=32, epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                    name=f"input_proj{i}_norm",
                )(src)
            )

        spatial_shapes = tuple((s.shape[1], s.shape[2]) for s in sources)
        level_embed = self.param(
            "level_embed",
            nn.initializers.normal(1.0),
            (cfg.num_feature_levels, cfg.d_model),
            jnp.float32,
        )

        masks = [nearest_downsample_mask(pixel_mask, (sh, sw)) for sh, sw in spatial_shapes]
        pos_list, src_list, mask_list = [], [], []
        for lvl, (source, mask) in enumerate(zip(sources, masks)):
            sh, sw = spatial_shapes[lvl]
            pos = sine_position_from_mask_offset(
                mask, cfg.d_model // 2, cfg.positional_encoding_temperature
            ).astype(self.dtype)
            pos_list.append(
                pos.reshape(b, sh * sw, cfg.d_model) + level_embed[lvl].astype(self.dtype)
            )
            src_list.append(source.reshape(b, sh * sw, cfg.d_model))
            mask_list.append(mask.reshape(b, sh * sw))
        source_flatten = jnp.concatenate(src_list, axis=1)
        pos_flatten = jnp.concatenate(pos_list, axis=1)
        mask_flatten = jnp.concatenate(mask_list, axis=1)
        value_mask = None if full_mask else mask_flatten

        # valid_ratios: (B, L, 2) as (w_ratio, h_ratio) per level
        valid_ratios = jnp.stack(
            [
                jnp.stack(
                    [m[:, 0, :].sum(axis=1) / sw, m[:, :, 0].sum(axis=1) / sh], axis=-1
                )
                for m, (sh, sw) in zip(masks, spatial_shapes)
            ],
            axis=1,
        )

        # --- encoder: MSDA self-attention; reference points are per-position
        # cell centers, normalized by own-level valid extent, projected into
        # every level's valid extent ---
        base = encoder_reference_base(spatial_shapes)  # (S, 2) static
        level_of = np.repeat(
            np.arange(len(spatial_shapes)), [sh * sw for sh, sw in spatial_shapes]
        )
        own_vr = valid_ratios[:, level_of, :]  # (B, S, 2), static gather
        enc_ref = (jnp.asarray(base)[None] / own_vr)[:, :, None, :] * valid_ratios[:, None]

        hidden = source_flatten
        for i in range(cfg.encoder_layers):
            hidden = DeformableEncoderLayer(cfg, dtype=self.dtype, name=f"encoder_layer{i}")(
                hidden, pos_flatten, enc_ref, spatial_shapes, value_mask
            )
        memory = hidden

        # --- prediction heads: shared instances across layers (plain) or
        # per-layer clones (box refine); two-stage adds one extra pair for
        # proposals (index decoder_layers) ---
        n_heads = cfg.decoder_layers + 1  # last slot used only when two_stage
        if cfg.with_box_refine:
            class_heads = [
                nn.Dense(cfg.num_labels, dtype=self.dtype, name=f"class_head{i}")
                for i in range(cfg.num_pred_heads)
            ]
            bbox_heads = [
                MLPHead(cfg.d_model, 4, 3, dtype=self.dtype, name=f"bbox_head{i}")
                for i in range(cfg.num_pred_heads)
            ]
        else:
            shared_class = nn.Dense(cfg.num_labels, dtype=self.dtype, name="class_head")
            shared_bbox = MLPHead(cfg.d_model, 4, 3, dtype=self.dtype, name="bbox_head")
            class_heads = [shared_class] * n_heads
            bbox_heads = [shared_bbox] * n_heads
        class_head = class_heads.__getitem__
        bbox_head = bbox_heads.__getitem__

        outputs: dict[str, jnp.ndarray] = {}

        # --- decoder inputs ---
        if cfg.two_stage:
            target, query_pos, ref, enc_class, enc_coord_logits = self._two_stage_queries(
                memory, mask_flatten, spatial_shapes, class_head, bbox_head
            )
            outputs["enc_outputs_class"] = enc_class.astype(jnp.float32)
            outputs["enc_outputs_coord_logits"] = enc_coord_logits.astype(jnp.float32)
        else:
            query_embeddings = self.param(
                "query_embeddings",
                nn.initializers.normal(1.0),
                (cfg.num_queries, cfg.d_model * 2),
                jnp.float32,
            )
            query_pos = jnp.broadcast_to(
                query_embeddings[None, :, : cfg.d_model],
                (b, cfg.num_queries, cfg.d_model),
            ).astype(self.dtype)
            target = jnp.broadcast_to(
                query_embeddings[None, :, cfg.d_model :],
                (b, cfg.num_queries, cfg.d_model),
            ).astype(self.dtype)
            ref = nn.sigmoid(
                nn.Dense(2, dtype=jnp.float32, name="reference_points_proj")(
                    query_pos.astype(jnp.float32)
                )
            )

        # --- decoder: fp32 reference iteration (repo box-precision policy) ---
        # Model-level locality presort (see models/rtdetr.py + ops/msda.py):
        # all decoder layers share one spatial ordering of the queries, so
        # sort once by the initial reference centers instead of per op.
        # Exact: pure permutation through permutation-equivariant layers,
        # un-permuted at the outputs.
        presort = presort_wanted()
        if presort:
            sort_q, unsort_q = locality_presort(ref[..., :2])
            target, query_pos, ref = sort_q(target), sort_q(query_pos), sort_q(ref)
        hq = target
        aux_logits, aux_boxes = [], []
        for i in range(cfg.decoder_layers):
            if ref.shape[-1] == 4:
                ref_input = ref[:, :, None] * jnp.concatenate(
                    [valid_ratios, valid_ratios], axis=-1
                )[:, None]
            else:
                ref_input = ref[:, :, None] * valid_ratios[:, None]
            hq = DeformableDecoderLayer(
                cfg, dtype=self.dtype, presorted=presort, name=f"decoder_layer{i}"
            )(
                hq, query_pos, memory, ref_input.astype(self.dtype), spatial_shapes,
                value_mask,
            )
            delta = bbox_head(i)(hq).astype(jnp.float32)
            if cfg.with_box_refine:
                if ref.shape[-1] == 4:
                    new_ref = nn.sigmoid(delta + inverse_sigmoid(ref))
                else:
                    # first refinement promotes 2-coordinate refs to full boxes
                    new_ref = nn.sigmoid(
                        jnp.concatenate(
                            [delta[..., :2] + inverse_sigmoid(ref), delta[..., 2:]],
                            axis=-1,
                        )
                    )
                aux_boxes.append(new_ref)
                ref = jax.lax.stop_gradient(new_ref)
            else:
                box_logits = jnp.concatenate(
                    [delta[..., :2] + inverse_sigmoid(ref), delta[..., 2:]], axis=-1
                )
                aux_boxes.append(nn.sigmoid(box_logits))
            aux_logits.append(class_head(i)(hq).astype(jnp.float32))

        if presort:
            aux_logits = [unsort_q(a) for a in aux_logits]
            aux_boxes = [unsort_q(a) for a in aux_boxes]

        outputs.update(
            logits=aux_logits[-1],
            pred_boxes=aux_boxes[-1],
            aux_logits=jnp.stack(aux_logits, axis=1),
            aux_boxes=jnp.stack(aux_boxes, axis=1),
        )
        return outputs

    def _two_stage_queries(self, memory, mask_flatten, spatial_shapes, class_head, bbox_head):
        """Encoder proposals -> top-k object queries (two-stage variant).

        gen_encoder_output_proposals + the proposal heads: every source
        position proposes an anchor box (cell center, wh = 0.05 * 2^level in
        VALID-cell units); border/padded positions are pushed to +inf logits
        exactly as the torch lineage does, the extra head pair scores them,
        and the top `two_stage_num_proposals` seed the decoder.
        """
        cfg = self.config
        b, s, _ = memory.shape

        proposals = []
        start = 0
        for level, (sh, sw) in enumerate(spatial_shapes):
            mask_l = mask_flatten[:, start : start + sh * sw].reshape(b, sh, sw)
            valid_h = mask_l[:, :, 0].sum(axis=1)  # (B,)
            valid_w = mask_l[:, 0, :].sum(axis=1)
            gy, gx = np.meshgrid(
                np.arange(sh, dtype=np.float32),
                np.arange(sw, dtype=np.float32),
                indexing="ij",
            )
            grid = np.stack([gx, gy], axis=-1) + 0.5  # (sh, sw, 2) static
            scale = jnp.stack([valid_w, valid_h], axis=-1)[:, None, None, :]
            grid_n = jnp.asarray(grid)[None] / scale
            wh = jnp.full_like(grid_n, 0.05 * (2.0**level))
            proposals.append(jnp.concatenate([grid_n, wh], axis=-1).reshape(b, -1, 4))
            start += sh * sw
        output_proposals = jnp.concatenate(proposals, axis=1).astype(jnp.float32)
        proposals_valid = jnp.all(
            (output_proposals > 0.01) & (output_proposals < 0.99), axis=-1, keepdims=True
        )
        output_proposals = jnp.log(output_proposals / (1.0 - output_proposals))
        keep = proposals_valid & (mask_flatten[..., None] > 0)
        output_proposals = jnp.where(keep, output_proposals, jnp.inf)

        object_query = memory * keep.astype(memory.dtype)
        object_query = nn.Dense(cfg.d_model, dtype=self.dtype, name="enc_output")(
            object_query
        )
        object_query = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="enc_output_norm"
        )(object_query)

        # the extra (index decoder_layers) head pair scores the proposals
        enc_class = class_head(cfg.decoder_layers)(object_query)
        delta = bbox_head(cfg.decoder_layers)(object_query).astype(jnp.float32)
        enc_coord_logits = delta + output_proposals

        k = cfg.two_stage_num_proposals
        # ops/topk.py: lax.top_k by default, SPOTTER_TPU_TOPK=bisect opt-in
        _, topk_ind = fast_top_k(enc_class[..., 0].astype(jnp.float32), k)
        topk_coords_logits = jnp.take_along_axis(
            enc_coord_logits, topk_ind[..., None], axis=1
        )
        topk_coords_logits = jax.lax.stop_gradient(topk_coords_logits)
        ref = nn.sigmoid(topk_coords_logits)

        pos_embed = proposal_position_embedding(
            topk_coords_logits, cfg.d_model, cfg.positional_encoding_temperature
        ).astype(self.dtype)
        pos_trans = nn.Dense(cfg.d_model * 2, dtype=self.dtype, name="pos_trans")(pos_embed)
        pos_trans = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="pos_trans_norm"
        )(pos_trans)
        query_pos = pos_trans[..., : cfg.d_model]
        target = pos_trans[..., cfg.d_model :]
        return target, query_pos, ref, enc_class, enc_coord_logits
