"""Model registry: MODEL_NAME -> (config, Flax module, converter, pre/postprocess).

Plays the role of `AutoModelForObjectDetection.from_pretrained(MODEL_NAME)` in
the reference (serve.py:203-204). Families register themselves here; lookup is
by HF repo-name substring so the same MODEL_NAME env values keep working.
"""

from dataclasses import dataclass
from typing import Callable

MODEL_REGISTRY: dict[str, "ModelFamily"] = {}


@dataclass(frozen=True)
class ModelFamily:
    """Everything the engine needs to serve one architecture family."""

    name: str
    matches: tuple[str, ...]  # substrings of MODEL_NAME that select this family
    build: Callable  # (model_name) -> BuiltDetector


def register(family: ModelFamily) -> None:
    MODEL_REGISTRY[family.name] = family


def build_detector(model_name: str):
    """Resolve MODEL_NAME to a built detector (module, params, specs)."""
    # Lazy: zoo pulls in the engine (jax/PIL); config-only consumers of
    # spotter_tpu.models must not pay that import.
    from spotter_tpu.models import zoo  # noqa: F401  (self-registers families)

    key = model_name.lower()
    for family in MODEL_REGISTRY.values():
        if any(m in key for m in family.matches):
            return family.build(model_name)
    raise ValueError(
        f"MODEL_NAME '{model_name}' does not match any registered family: "
        f"{[f.matches for f in MODEL_REGISTRY.values()]}"
    )
