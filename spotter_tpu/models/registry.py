"""Model registry: MODEL_NAME -> (config, Flax module, converter, pre/postprocess).

Plays the role of `AutoModelForObjectDetection.from_pretrained(MODEL_NAME)` in
the reference (serve.py:203-204). Families register themselves here; lookup is
by HF repo-name substring so the same MODEL_NAME env values keep working.

Each family also carries its tensor-parallel rule set (`tp_rules`, a
parallel/sharding.py Rules tuple): the regexes that split THIS family's
attention/MLP weights over the "tp" mesh axis. The serving bootstrap reads
the rules from here instead of assuming one architecture, so `tp=2` on an
OWL-ViT deployment shards the CLIP towers, not a hand-written RT-DETR list.
"""

from dataclasses import dataclass, field
from typing import Callable

MODEL_REGISTRY: dict[str, "ModelFamily"] = {}


@dataclass(frozen=True)
class ModelFamily:
    """Everything the engine needs to serve one architecture family."""

    name: str
    matches: tuple[str, ...]  # substrings of MODEL_NAME that select this family
    build: Callable  # (model_name) -> BuiltDetector
    # (regex, PartitionSpec) pairs splitting this family's weights over the
    # "tp" mesh axis (parallel/sharding.py); empty = the family serves
    # replicated-only (tp>1 buys nothing but costs nothing either)
    tp_rules: tuple = field(default=())


def register(family: ModelFamily) -> None:
    MODEL_REGISTRY[family.name] = family


def match_score(key: str, matches: tuple[str, ...]):
    """Best (start, -length) score of any pattern inside `key`, or None.

    Lower is better: the pattern that begins earliest in the name wins, and
    among patterns starting at the same offset the longest wins. This makes
    resolution order-independent — "dab-detr-resnet-50" contains both
    "dab-detr" (at 0) and the plain-detr pattern "detr-resnet" (at 4), and
    the earliest-start rule picks the specific family no matter which
    registered first. Pure longest-substring would misroute that name
    (len("detr-resnet") > len("dab-detr")); earliest-start-then-longest
    resolves every zoo family correctly with no ordering contract.
    """
    best = None
    for m in matches:
        i = key.find(m)
        if i < 0:
            continue
        score = (i, -len(m))
        if best is None or score < best:
            best = score
    return best


def family_for(model_name: str) -> ModelFamily:
    """Resolve MODEL_NAME to its registered family.

    Substring match scored by `match_score`: most-specific wins
    (earliest match start, then longest pattern), independent of
    registration order. Ties on identical scores keep the first
    registered family, so resolution is fully deterministic.
    """
    # Lazy: zoo pulls in the engine (jax/PIL); config-only consumers of
    # spotter_tpu.models must not pay that import.
    from spotter_tpu.models import zoo  # noqa: F401  (self-registers families)

    key = model_name.lower()
    best_family = None
    best_score = None
    for family in MODEL_REGISTRY.values():
        score = match_score(key, family.matches)
        if score is not None and (best_score is None or score < best_score):
            best_family, best_score = family, score
    if best_family is not None:
        return best_family
    raise ValueError(
        f"MODEL_NAME '{model_name}' does not match any registered family: "
        f"{[f.matches for f in MODEL_REGISTRY.values()]}"
    )


def build_detector(model_name: str):
    """Resolve MODEL_NAME to a built detector (module, params, specs)."""
    return family_for(model_name).build(model_name)
