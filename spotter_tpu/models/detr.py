"""Flax DETR (facebook/detr-resnet-*): CNN backbone + vanilla encoder-decoder.

Semantics match HF's DetrForObjectDetection (modeling_detr.py): frozen-BN
ResNet backbone, mask-aware sine position embeddings (cumsum over the pixel
mask, DetrSinePositionEmbedding), post-norm transformer layers where position
embeddings are added to queries/keys only, zero-initialized object queries
with learned query position embeddings, final decoder layernorm, linear class
head (num_labels + 1 with "no object") and a 3-layer MLP box head with sigmoid.

TPU-first notes: NHWC throughout; the pixel mask arrives as a static-shape
(B, H, W) float array from the preprocess bucket (SURVEY.md §5.7), so the only
data-dependent values are mask contents — shapes never change and XLA compiles
one program per bucket. The reference serves this family through the same
`AutoModelForObjectDetection` boundary (serve.py:199-205).
"""

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from spotter_tpu.models.configs import DetrConfig
from spotter_tpu.models.layers import MLPHead, MultiHeadAttention, get_activation
from spotter_tpu.models.resnet import ResNetBackbone


def nearest_downsample_mask(mask: jnp.ndarray, out_hw: tuple[int, int]) -> jnp.ndarray:
    """torch F.interpolate(mode="nearest") on a (B, H, W) mask — static indices.

    torch's legacy nearest uses src = floor(dst * in/out); the index tables are
    computed in numpy from static shapes so XLA sees constant gathers.
    """
    _, h_in, w_in = mask.shape
    h_out, w_out = out_hw
    idx_h = np.floor(np.arange(h_out) * (h_in / h_out)).astype(np.int32)
    idx_w = np.floor(np.arange(w_out) * (w_in / w_out)).astype(np.int32)
    return mask[:, idx_h][:, :, idx_w]


def sine_position_from_mask(
    mask: jnp.ndarray,
    embed_dim: int,
    temperature: float | tuple[float, float] = 10000.0,
) -> jnp.ndarray:
    """DetrSinePositionEmbedding(normalize=True): (B, h, w) mask -> (B, h, w, 2*half).

    Cumulative (1-based) row/col coordinates over valid pixels, normalized to
    [0, 2*pi], interleaved sin/cos per coordinate; y-half then x-half.
    `temperature` may be a (height, width) pair — DAB-DETR uses 20/20
    (DabDetrSinePositionEmbedding); the DETR lineage uses a single 10000.
    """
    half = embed_dim
    scale = 2.0 * math.pi
    temp_y, temp_x = (
        temperature if isinstance(temperature, tuple) else (temperature, temperature)
    )
    y = jnp.cumsum(mask, axis=1)
    x = jnp.cumsum(mask, axis=2)
    y = y / (y[:, -1:, :] + 1e-6) * scale
    x = x / (x[:, :, -1:] + 1e-6) * scale
    rng = 2.0 * (np.arange(half, dtype=np.float32) // 2) / half
    pos_x = x[..., None] / (temp_x**rng)
    pos_y = y[..., None] / (temp_y**rng)

    def interleave(p):
        return jnp.stack([jnp.sin(p[..., 0::2]), jnp.cos(p[..., 1::2])], axis=-1).reshape(
            *p.shape[:-1], -1
        )

    return jnp.concatenate([interleave(pos_y), interleave(pos_x)], axis=-1)


class DetrEncoderLayer(nn.Module):
    """Encoder layer: self-attn + FFN. Post-norm (DETR) or pre-norm
    (Table-Transformer) per config.pre_norm."""

    config: DetrConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, hidden: jnp.ndarray, pos: jnp.ndarray, attn_mask: Optional[jnp.ndarray]
    ) -> jnp.ndarray:
        cfg = self.config
        norm1 = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="self_attn_layer_norm"
        )
        norm2 = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm"
        )
        mha = MultiHeadAttention(
            cfg.d_model, cfg.encoder_attention_heads, dtype=self.dtype, name="self_attn"
        )

        def ffn_block(x):
            y = nn.Dense(cfg.encoder_ffn_dim, dtype=self.dtype, name="fc1")(x)
            y = get_activation(cfg.activation_function)(y)
            return nn.Dense(cfg.d_model, dtype=self.dtype, name="fc2")(y)

        if cfg.pre_norm:
            hidden = hidden + mha(
                norm1(hidden), position_embeddings=pos, attention_mask=attn_mask
            )
            return hidden + ffn_block(norm2(hidden))
        attn = mha(hidden, position_embeddings=pos, attention_mask=attn_mask)
        hidden = norm1(hidden + attn)
        return norm2(hidden + ffn_block(hidden))


class DetrDecoderLayer(nn.Module):
    """Decoder layer: self-attn over queries + cross-attn to memory.
    Post-norm (DETR) or pre-norm (Table-Transformer) per config.pre_norm."""

    config: DetrConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        queries: jnp.ndarray,
        query_pos: jnp.ndarray,
        memory: jnp.ndarray,
        memory_pos: jnp.ndarray,
        memory_mask: Optional[jnp.ndarray],
    ) -> jnp.ndarray:
        cfg = self.config
        norm1 = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="self_attn_layer_norm"
        )
        norm2 = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="encoder_attn_layer_norm"
        )
        norm3 = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm"
        )
        self_attn = MultiHeadAttention(
            cfg.d_model, cfg.decoder_attention_heads, dtype=self.dtype, name="self_attn"
        )
        cross_attn = MultiHeadAttention(
            cfg.d_model, cfg.decoder_attention_heads, dtype=self.dtype, name="encoder_attn"
        )

        def cross(x):
            return cross_attn(
                x,
                position_embeddings=query_pos,
                key_value_states=memory,
                key_position_embeddings=memory_pos,
                attention_mask=memory_mask,
            )

        def ffn_block(x):
            y = nn.Dense(cfg.decoder_ffn_dim, dtype=self.dtype, name="fc1")(x)
            y = get_activation(cfg.activation_function)(y)
            return nn.Dense(cfg.d_model, dtype=self.dtype, name="fc2")(y)

        if cfg.pre_norm:
            queries = queries + self_attn(norm1(queries), position_embeddings=query_pos)
            queries = queries + cross(norm2(queries))
            return queries + ffn_block(norm3(queries))
        queries = norm1(
            queries + self_attn(queries, position_embeddings=query_pos)
        )
        queries = norm2(queries + cross(queries))
        return norm3(queries + ffn_block(queries))


class DetrDetector(nn.Module):
    """DETR object detector: returns {"logits": (B, Q, C+1), "pred_boxes": (B, Q, 4)}."""

    config: DetrConfig
    dtype: jnp.dtype = jnp.float32
    # "mixed" policy: bf16 for the HBM-bound backbone convs, compute dtype
    # (fp32 by default) for the transformer — cast at the feature boundary
    backbone_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(
        self, pixel_values: jnp.ndarray, pixel_mask: Optional[jnp.ndarray] = None
    ) -> dict[str, jnp.ndarray]:
        cfg = self.config
        b, h, w, _ = pixel_values.shape
        if pixel_mask is None:
            pixel_mask = jnp.ones((b, h, w), dtype=jnp.float32)

        features = ResNetBackbone(
            cfg.backbone, dtype=self.backbone_dtype or self.dtype, name="backbone"
        )(pixel_values)
        feat = features[-1].astype(self.dtype)
        _, fh, fw, _ = feat.shape
        mask = nearest_downsample_mask(pixel_mask, (fh, fw))

        pos = sine_position_from_mask(
            mask, cfg.d_model // 2, cfg.positional_encoding_temperature
        ).astype(self.dtype)

        proj = nn.Conv(
            cfg.d_model, (1, 1), use_bias=True, dtype=self.dtype, name="input_projection"
        )(feat)

        src = proj.reshape(b, fh * fw, cfg.d_model)
        pos = pos.reshape(b, fh * fw, cfg.d_model)
        mask_flat = mask.reshape(b, fh * fw)
        # additive mask, (B, 1, 1, S): valid -> 0, pad -> dtype-min (HF
        # _prepare_4d_attention_mask semantics)
        attn_mask = jnp.where(
            mask_flat[:, None, None, :] > 0, 0.0, jnp.finfo(jnp.float32).min
        )

        for i in range(cfg.encoder_layers):
            src = DetrEncoderLayer(cfg, dtype=self.dtype, name=f"encoder_layer{i}")(
                src, pos, attn_mask
            )
        if cfg.pre_norm:  # Table-Transformer closes the pre-norm encoder
            src = nn.LayerNorm(
                epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="encoder_layernorm"
            )(src)

        query_pos = self.param(
            "query_pos",
            nn.initializers.normal(1.0),
            (cfg.num_queries, cfg.d_model),
            jnp.float32,
        )
        query_pos = jnp.broadcast_to(
            query_pos[None].astype(self.dtype), (b, cfg.num_queries, cfg.d_model)
        )
        queries = jnp.zeros_like(query_pos)
        for i in range(cfg.decoder_layers):
            queries = DetrDecoderLayer(cfg, dtype=self.dtype, name=f"decoder_layer{i}")(
                queries, query_pos, src, pos, attn_mask
            )
        queries = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="decoder_layernorm"
        )(queries)

        # Heads return fp32 even under bf16 compute: box sigmoid and softmax
        # scores need the extra mantissa to keep the ±1 px golden contract.
        logits = nn.Dense(
            cfg.num_labels + 1, dtype=self.dtype, name="class_labels_classifier"
        )(queries)
        boxes = nn.sigmoid(
            MLPHead(cfg.d_model, 4, 3, dtype=self.dtype, name="bbox_predictor")(
                queries
            ).astype(jnp.float32)
        )
        return {"logits": logits.astype(jnp.float32), "pred_boxes": boxes}
