"""Flax Conditional-DETR detector (microsoft/conditional-detr-resnet-*).

Served through the reference's `MODEL_NAME` AutoModel boundary
(serve.py:199-205) like the other families. Architecture follows HF
modeling_conditional_detr.py: a DETR encoder over backbone features plus a
decoder whose cross-attention decouples *content* from *spatial* matching —
each query carries a sine embedding of its predicted reference point,
concatenated per-head with the content features, so q/k live in 2*d_model
while values stay d_model. Boxes are regressed relative to the reference
points (inverse-sigmoid add), and classification is focal-style (no
"no-object" class) — postprocess is the same sigmoid top-k as RT-DETR.

TPU-first notes: static shapes throughout; the per-layer `is_first`
branching of the torch code (ca_qpos_proj exists only on layer 0) becomes a
static Python conditional at trace time; all sine tables are computed in jnp
from traced reference points (they depend on data, unlike DETR's static
grid).
"""

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from spotter_tpu.models.configs import ConditionalDetrConfig
from spotter_tpu.models.detr import (
    DetrEncoderLayer,
    nearest_downsample_mask,
    sine_position_from_mask,
)
from spotter_tpu.models.layers import MLPHead, get_activation, inverse_sigmoid
from spotter_tpu.models.resnet import ResNetBackbone


def query_sine_embedding(pos: jnp.ndarray, d_model: int) -> jnp.ndarray:
    """Sine embedding of normalized (x, y) points, (B, Q, d_model).

    Matches gen_sine_position_embeddings (modeling_conditional_detr.py:422):
    scale 2*pi, half the channels for y then x, interleaved sin/cos.
    """
    dim = d_model // 2
    dim_t = 10000.0 ** (2 * (np.arange(dim, dtype=np.float32) // 2) / dim)
    x = pos[..., 0:1] * (2 * math.pi) / dim_t
    y = pos[..., 1:2] * (2 * math.pi) / dim_t

    def interleave(p):
        return jnp.stack([jnp.sin(p[..., 0::2]), jnp.cos(p[..., 1::2])], axis=-1).reshape(
            *p.shape[:-1], -1
        )

    return jnp.concatenate([interleave(y), interleave(x)], axis=-1)


def _attend(q, k, v, num_heads, attn_mask, dtype):
    """Scaled-dot attention over pre-projected q/k/v with per-head split.

    q/k may be wider than v (Conditional-DETR's concatenated cross-attn);
    the softmax runs fp32 like the rest of the stack.
    """
    b, tq, qk_dim = q.shape
    head = qk_dim // num_heads
    v_head = v.shape[-1] // num_heads
    qh = q.reshape(b, tq, num_heads, head) * (head**-0.5)
    kh = k.reshape(b, -1, num_heads, head)
    vh = v.reshape(b, -1, num_heads, v_head)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh)
    if attn_mask is not None:
        logits = logits + attn_mask.astype(logits.dtype)
    weights = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights, vh)
    return out.reshape(b, tq, num_heads * v_head)


class ConditionalDecoderLayer(nn.Module):
    config: ConditionalDetrConfig
    is_first: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        hidden: jnp.ndarray,  # (B, Q, D)
        query_pos: jnp.ndarray,  # (B, Q, D)
        query_sine: jnp.ndarray,  # (B, Q, D) transformed sine embedding
        memory: jnp.ndarray,  # (B, S, D)
        memory_pos: jnp.ndarray,  # (B, S, D)
        memory_mask: Optional[jnp.ndarray],
    ) -> jnp.ndarray:
        cfg = self.config
        d, heads = cfg.d_model, cfg.decoder_attention_heads
        dense = lambda name: nn.Dense(d, dtype=self.dtype, name=name)

        # self-attention: decoupled content/position projections
        q = dense("sa_qcontent_proj")(hidden) + dense("sa_qpos_proj")(query_pos)
        k = dense("sa_kcontent_proj")(hidden) + dense("sa_kpos_proj")(query_pos)
        v = dense("sa_v_proj")(hidden)
        attn = _attend(q, k, v, heads, None, self.dtype)
        attn = dense("self_attn_out_proj")(attn)
        hidden = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="self_attn_layer_norm"
        )(hidden + attn)

        # cross-attention: per-head concat of content and spatial halves
        qc = dense("ca_qcontent_proj")(hidden)
        kc = dense("ca_kcontent_proj")(memory)
        v = dense("ca_v_proj")(memory)
        kpos = dense("ca_kpos_proj")(memory_pos)
        if self.is_first:  # ca_qpos_proj exists only on the first layer
            qc = qc + dense("ca_qpos_proj")(query_pos)
            kc = kc + kpos
        qsine = dense("ca_qpos_sine_proj")(query_sine)

        b, nq, _ = qc.shape
        s = kc.shape[1]
        head = d // heads
        q2 = jnp.concatenate(
            [qc.reshape(b, nq, heads, head), qsine.reshape(b, nq, heads, head)], axis=-1
        ).reshape(b, nq, 2 * d)
        k2 = jnp.concatenate(
            [kc.reshape(b, s, heads, head), kpos.reshape(b, s, heads, head)], axis=-1
        ).reshape(b, s, 2 * d)
        cross = _attend(q2, k2, v, heads, memory_mask, self.dtype)
        cross = dense("encoder_attn_out_proj")(cross)
        hidden = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="encoder_attn_layer_norm"
        )(hidden + cross)

        ffn = nn.Dense(cfg.decoder_ffn_dim, dtype=self.dtype, name="fc1")(hidden)
        ffn = get_activation(cfg.activation_function)(ffn)
        ffn = nn.Dense(d, dtype=self.dtype, name="fc2")(ffn)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm"
        )(hidden + ffn)


class ConditionalDetrDetector(nn.Module):
    """Conditional DETR: pixels (+mask) -> {"logits" (B,Q,C), "pred_boxes"}."""

    config: ConditionalDetrConfig
    dtype: jnp.dtype = jnp.float32
    # "mixed" policy: bf16 backbone convs, compute dtype for the transformer
    backbone_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(
        self, pixel_values: jnp.ndarray, pixel_mask: Optional[jnp.ndarray] = None
    ) -> dict:
        cfg = self.config
        b, h, w, _ = pixel_values.shape
        if pixel_mask is None:
            pixel_mask = jnp.ones((b, h, w), dtype=jnp.float32)

        features = ResNetBackbone(
            cfg.backbone, dtype=self.backbone_dtype or self.dtype, name="backbone"
        )(pixel_values)
        feat = features[-1].astype(self.dtype)
        _, fh, fw, _ = feat.shape
        mask = nearest_downsample_mask(pixel_mask, (fh, fw))

        pos = sine_position_from_mask(
            mask, cfg.d_model // 2, cfg.positional_encoding_temperature
        ).astype(self.dtype)
        src = nn.Conv(
            cfg.d_model, (1, 1), use_bias=True, dtype=self.dtype, name="input_projection"
        )(feat)
        src = src.reshape(b, fh * fw, cfg.d_model)
        pos = pos.reshape(b, fh * fw, cfg.d_model)
        mask_flat = mask.reshape(b, fh * fw)
        attn_mask = jnp.where(
            mask_flat[:, None, None, :] > 0, 0.0, jnp.finfo(jnp.float32).min
        )

        for i in range(cfg.encoder_layers):
            src = DetrEncoderLayer(cfg, dtype=self.dtype, name=f"encoder_layer{i}")(
                src, pos, attn_mask
            )

        query_pos = self.param(
            "query_pos",
            nn.initializers.normal(1.0),
            (cfg.num_queries, cfg.d_model),
            jnp.float32,
        )
        query_pos = jnp.broadcast_to(
            query_pos[None].astype(self.dtype), (b, cfg.num_queries, cfg.d_model)
        )

        # reference points from the query embeddings (shared by all layers)
        ref_logits = MLPHead(cfg.d_model, 2, 2, dtype=self.dtype, name="ref_point_head")(
            query_pos
        ).astype(jnp.float32)
        ref = nn.sigmoid(ref_logits)  # (B, Q, 2) normalized centers
        sine_base = query_sine_embedding(ref, cfg.d_model).astype(self.dtype)

        query_scale = MLPHead(
            cfg.d_model, cfg.d_model, 2, dtype=self.dtype, name="query_scale"
        )
        hidden = jnp.zeros_like(query_pos)
        for i in range(cfg.decoder_layers):
            scale = 1.0 if i == 0 else query_scale(hidden)
            hidden = ConditionalDecoderLayer(
                cfg, is_first=(i == 0), dtype=self.dtype, name=f"decoder_layer{i}"
            )(hidden, query_pos, sine_base * scale, src, pos, attn_mask)
        hidden = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="decoder_layernorm"
        )(hidden)

        logits = nn.Dense(
            cfg.num_labels, dtype=self.dtype, name="class_labels_classifier"
        )(hidden)
        # box regression relative to the reference point (x, y only)
        delta = MLPHead(cfg.d_model, 4, 3, dtype=self.dtype, name="bbox_predictor")(
            hidden
        ).astype(jnp.float32)
        delta = delta.at[..., :2].add(inverse_sigmoid(ref))
        boxes = nn.sigmoid(delta)
        return {"logits": logits.astype(jnp.float32), "pred_boxes": boxes}
