"""Flax OWL-ViT / OWLv2 (google/owlvit-*, google/owlv2-*): open-vocabulary
detection, text-conditioned.

Semantics match HF's OwlViTForObjectDetection (modeling_owlvit.py): CLIP-style
vision and text towers, class-token merge over patch features, a text-query
class head (normalized dot product with learned per-patch logit shift/scale)
and a box MLP head biased toward each patch's grid position. OWLv2
(modeling_owlv2.py) shares the architecture and adds an objectness head over
detached patch features (config.objectness); its pad-to-square preprocess
lives in the serving spec (ops/preprocess.py "pad_square").

TPU-first split (SURVEY.md §7): the queries a deployment serves are static
(the amenity taxonomy, or an operator-supplied list), so `encode_text` runs
ONCE at model-build time and its output rides along as a small constant —
the serving hot path is vision-only, keeping the per-request program a pure
(B, H, W, 3) -> fixed-shape detection map that XLA tiles onto the MXU. The
reference serves detection through the same `MODEL_NAME` boundary
(serve.py:199-205); open-vocab is the one family where the label set itself
is a deploy-time input rather than checkpoint metadata.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from spotter_tpu.models.configs import (
    OwlViTConfig,
    OwlViTTextConfig,
    OwlViTVisionConfig,
)
from spotter_tpu.models.layers import (
    MultiHeadAttention,
    PatchEmbed,
    QuantDense,
    get_activation,
)
from spotter_tpu.ops.openvocab import fused_class_logits, owl_fused_wanted

NEG_INF = float(np.finfo(np.float32).min)


def owlvit_box_bias(grid_h: int, grid_w: int) -> np.ndarray:
    """Per-patch box prior, (grid_h*grid_w, 4) numpy — constant under jit.

    Centers biased to the patch's normalized grid position, sizes to one patch
    (both through an inverse sigmoid with the 1e-4 eps the checkpoints were
    trained with). Row-major over (h, w), matching the patch-embedding flatten.
    """
    x = np.arange(1, grid_w + 1, dtype=np.float32) / grid_w
    y = np.arange(1, grid_h + 1, dtype=np.float32) / grid_h
    xx, yy = np.meshgrid(x, y)  # (grid_h, grid_w)
    coords = np.stack([xx, yy], axis=-1).reshape(-1, 2)
    coord_bias = np.log(coords + 1e-4) - np.log1p(-coords + 1e-4)
    size = np.empty_like(coords)
    size[:, 0] = 1.0 / grid_w
    size[:, 1] = 1.0 / grid_h
    size_bias = np.log(size + 1e-4) - np.log1p(-size + 1e-4)
    return np.concatenate([coord_bias, size_bias], axis=-1).astype(np.float32)


class OwlViTLayer(nn.Module):
    """Pre-norm CLIP transformer block (ln1 -> attn -> res, ln2 -> mlp -> res)."""

    hidden_size: int
    num_heads: int
    intermediate_size: int
    hidden_act: str
    layer_norm_eps: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, attention_mask: Optional[jnp.ndarray] = None
    ) -> jnp.ndarray:
        h = nn.LayerNorm(
            epsilon=self.layer_norm_eps, dtype=self.dtype, name="layer_norm1"
        )(x)
        x = x + MultiHeadAttention(
            self.hidden_size, self.num_heads, dtype=self.dtype, name="self_attn"
        )(h, attention_mask=attention_mask)
        h = nn.LayerNorm(
            epsilon=self.layer_norm_eps, dtype=self.dtype, name="layer_norm2"
        )(x)
        h = QuantDense(self.intermediate_size, dtype=self.dtype, name="fc1")(h)
        h = get_activation(self.hidden_act)(h)
        return x + QuantDense(self.hidden_size, dtype=self.dtype, name="fc2")(h)


class OwlViTTextTower(nn.Module):
    """CLIP text transformer -> pooled EOT-token features, (Q, D_text).

    Causal attention plus the padding mask; pooling picks the position of the
    highest token id (CLIP's end-of-text token) per query.
    """

    config: OwlViTTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, input_ids: jnp.ndarray, attention_mask: Optional[jnp.ndarray] = None
    ) -> jnp.ndarray:
        cfg = self.config
        q, t = input_ids.shape
        tok_table = self.param(
            "token_embedding",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        pos_table = self.param(
            "position_embedding",
            nn.initializers.normal(0.02),
            (cfg.max_position_embeddings, cfg.hidden_size),
            jnp.float32,
        )
        x = jnp.take(tok_table, input_ids, axis=0).astype(self.dtype)
        x = x + pos_table[:t].astype(self.dtype)

        causal = jnp.triu(jnp.full((t, t), NEG_INF, jnp.float32), k=1)
        mask = causal[None, None]  # (1, 1, T, T)
        if attention_mask is not None:
            pad = jnp.where(attention_mask == 0, NEG_INF, 0.0).astype(jnp.float32)
            mask = mask + pad[:, None, None, :]  # (Q, 1, T, T)

        for i in range(cfg.num_hidden_layers):
            x = OwlViTLayer(
                cfg.hidden_size,
                cfg.num_attention_heads,
                cfg.intermediate_size,
                cfg.hidden_act,
                cfg.layer_norm_eps,
                dtype=self.dtype,
                name=f"layer{i}",
            )(x, attention_mask=mask)
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm"
        )(x)

        eot = jnp.argmax(input_ids, axis=-1)  # first occurrence of the max id
        return jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]


class OwlViTVisionTower(nn.Module):
    """CLIP vision transformer -> post-LN token sequence, (B, 1 + P, D_vision)."""

    config: OwlViTVisionConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixel_values: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        p = cfg.patch_size
        b, h, w, _ = pixel_values.shape
        if h % p or w % p:
            raise ValueError(f"input {h}x{w} not divisible by patch size {p}")
        gh, gw = h // p, w // p

        # row-dot patchify (layers.PatchEmbed): exact conv rewrite, ~2x on
        # v5e — the patchify conv measured 38% of this tower's time
        x = PatchEmbed(
            cfg.hidden_size,
            p,
            use_bias=False,
            dtype=self.dtype,
            name="patch_embedding",
        )(pixel_values)

        cls = self.param(
            "class_embedding",
            nn.initializers.normal(0.02),
            (cfg.hidden_size,),
            jnp.float32,
        )
        pos = self.param(
            "position_embedding",
            nn.initializers.normal(0.02),
            (cfg.grid * cfg.grid + 1, cfg.hidden_size),
            jnp.float32,
        )
        patch_pos = pos[1:]
        if (gh, gw) != (cfg.grid, cfg.grid):
            # off-native static size: bicubic table interpolation at trace time
            grid_tab = patch_pos.reshape(1, cfg.grid, cfg.grid, cfg.hidden_size)
            grid_tab = jax.image.resize(
                grid_tab, (1, gh, gw, cfg.hidden_size), method="bicubic"
            )
            patch_pos = grid_tab.reshape(gh * gw, cfg.hidden_size)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype), (b, 1, cfg.hidden_size)), x],
            axis=1,
        )
        x = x + jnp.concatenate([pos[:1], patch_pos], axis=0).astype(self.dtype)

        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="pre_layernorm"
        )(x)
        for i in range(cfg.num_hidden_layers):
            x = OwlViTLayer(
                cfg.hidden_size,
                cfg.num_attention_heads,
                cfg.intermediate_size,
                cfg.hidden_act,
                cfg.layer_norm_eps,
                dtype=self.dtype,
                name=f"layer{i}",
            )(x)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="post_layernorm"
        )(x)


class OwlViTClassHead(nn.Module):
    """Text-query classifier: cosine logits with learned per-patch shift/scale."""

    config: OwlViTConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        image_feats: jnp.ndarray,  # (B, P, D_vision)
        query_embeds: jnp.ndarray,  # (Q, D_text) — precomputed at build time
        query_mask: Optional[jnp.ndarray] = None,  # (Q,) 1=valid
    ) -> jnp.ndarray:
        cfg = self.config
        img_cls = nn.Dense(cfg.text.hidden_size, dtype=self.dtype, name="dense0")(
            image_feats
        )
        q = query_embeds / (jnp.linalg.norm(query_embeds, axis=-1, keepdims=True) + 1e-6)
        shift = nn.Dense(1, dtype=self.dtype, name="logit_shift")(image_feats)
        scale = nn.Dense(1, dtype=self.dtype, name="logit_scale")(image_feats)

        if owl_fused_wanted():
            # SPOTTER_TPU_OWL_FUSED: patch-normalize + cosine matmul +
            # shift/elu-scale + NEG_INF query masking in one Pallas kernel
            # (spotter_tpu/ops/openvocab.py). The three Denses above stay in
            # XLA; param tree and masking semantics are identical to the
            # unfused tail below.
            return fused_class_logits(
                img_cls, q.astype(jnp.float32), shift[..., 0], scale[..., 0],
                query_mask,
            )

        img_cls = img_cls / (jnp.linalg.norm(img_cls, axis=-1, keepdims=True) + 1e-6)
        logits = jnp.einsum("bpd,qd->bpq", img_cls, q.astype(img_cls.dtype))
        scale = jax.nn.elu(scale) + 1.0
        logits = (logits + shift) * scale
        if query_mask is not None:
            logits = jnp.where(query_mask[None, None, :] == 0, NEG_INF, logits)
        return logits


class OwlViTBoxHead(nn.Module):
    """Box MLP (dense-gelu-dense-gelu-dense) + static grid bias + sigmoid."""

    config: OwlViTVisionConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, image_feats: jnp.ndarray, grid_hw: tuple[int, int]
    ) -> jnp.ndarray:
        d = self.config.hidden_size
        x = nn.Dense(d, dtype=self.dtype, name="dense0")(image_feats)
        x = get_activation("gelu")(x)
        x = nn.Dense(d, dtype=self.dtype, name="dense1")(x)
        x = get_activation("gelu")(x)
        x = nn.Dense(4, dtype=self.dtype, name="dense2")(x)
        bias = owlvit_box_bias(*grid_hw)  # numpy: XLA constant-folds it
        # fp32 sigmoid under bf16 compute (box precision at full-image scale)
        return nn.sigmoid(x.astype(jnp.float32) + jnp.asarray(bias, jnp.float32))


class ObjectnessHead(nn.Module):
    """OWLv2 objectness predictor: box-head-shaped MLP -> (B, P) logits.

    HF Owlv2ForObjectDetection.objectness_predictor: a BoxPredictionHead with
    out_dim=1 applied to DETACHED image features (the head trains without
    shaping the backbone)."""

    hidden_size: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, image_feats: jnp.ndarray) -> jnp.ndarray:
        x = jax.lax.stop_gradient(image_feats)
        x = nn.Dense(self.hidden_size, dtype=self.dtype, name="dense0")(x)
        x = get_activation("gelu")(x)
        x = nn.Dense(self.hidden_size, dtype=self.dtype, name="dense1")(x)
        x = get_activation("gelu")(x)
        return nn.Dense(1, dtype=self.dtype, name="dense2")(x)[..., 0]


class OwlViTDetector(nn.Module):
    """OWL-ViT / OWLv2 detector.

    `__call__(pixels, query_embeds)` is the serving forward:
    {"logits": (B, P, Q), "pred_boxes": (B, P, 4) normalized cxcywh, plus
    "objectness" (B, P) for OWLv2}. `encode_text(input_ids, attention_mask)`
    -> normalized (Q, proj) query embeddings, run once at build time.
    `detect_with_text` chains both (used for init and parity testing).
    """

    config: OwlViTConfig
    dtype: jnp.dtype = jnp.float32
    # "mixed" policy: the vision tower is the HBM-bound ViT half (owlv2:
    # 3600 patch tokens) and follows the backbone dtype like yolos' body;
    # text tower + heads keep the compute dtype (fp32 by default).
    vision_dtype: Optional[jnp.dtype] = None

    def setup(self) -> None:
        cfg = self.config
        self.vision = OwlViTVisionTower(
            cfg.vision, dtype=self.vision_dtype or self.dtype
        )
        self.text = OwlViTTextTower(cfg.text, dtype=self.dtype)
        self.text_projection = nn.Dense(
            cfg.projection_dim, use_bias=False, dtype=self.dtype
        )
        # the detection head's merge LayerNorm (HF: OwlViTForObjectDetection.layer_norm)
        self.merge_layer_norm = nn.LayerNorm(
            epsilon=cfg.vision.layer_norm_eps, dtype=self.dtype
        )
        self.class_head = OwlViTClassHead(cfg, dtype=self.dtype)
        self.box_head = OwlViTBoxHead(cfg.vision, dtype=self.dtype)
        if cfg.objectness:
            self.objectness_head = ObjectnessHead(cfg.vision.hidden_size, dtype=self.dtype)

    def encode_text(
        self, input_ids: jnp.ndarray, attention_mask: Optional[jnp.ndarray] = None
    ) -> jnp.ndarray:
        pooled = self.text(input_ids, attention_mask)
        q = self.text_projection(pooled)
        return q / jnp.linalg.norm(q, axis=-1, keepdims=True)

    def __call__(
        self,
        pixel_values: jnp.ndarray,
        query_embeds: jnp.ndarray,
        query_mask: Optional[jnp.ndarray] = None,
    ) -> dict[str, jnp.ndarray]:
        feats = self.vision(pixel_values)  # (B, 1+P, D)
        image_feats = feats[:, 1:, :] * feats[:, :1, :]  # class-token merge
        image_feats = self.merge_layer_norm(image_feats)
        logits = self.class_head(image_feats, query_embeds, query_mask)
        gh = pixel_values.shape[1] // self.config.vision.patch_size
        gw = pixel_values.shape[2] // self.config.vision.patch_size
        boxes = self.box_head(image_feats, (gh, gw))
        out = {"logits": logits.astype(jnp.float32), "pred_boxes": boxes}
        if self.config.objectness:
            out["objectness"] = self.objectness_head(image_feats).astype(jnp.float32)
        return out

    def detect_with_text(
        self,
        pixel_values: jnp.ndarray,
        input_ids: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray] = None,
    ) -> dict[str, jnp.ndarray]:
        return self(pixel_values, self.encode_text(input_ids, attention_mask))
