"""Flax DAB-DETR detector (IDEA-Research/dab-detr-resnet-*).

Served through the reference's `MODEL_NAME` AutoModel boundary
(serve.py:199-205) like the other families. Architecture follows HF
modeling_dab_detr.py: each object query is a learned 4D anchor box
(x, y, w, h); its sine embedding drives the decoder's query positions
(`ref_point_head`), its conditional cross-attention spatial half is scaled by
a content-dependent transform (`query_scale`) and modulated by predicted
anchor aspect (`ref_anchor_head`), and a shared 3-layer box head iteratively
refines the anchors layer by layer. The encoder is a DETR encoder whose sine
position map (temperature 20) is rescaled per layer by its own MLP. FFNs use
a learned PReLU. Classification is focal-style — postprocess is the same
sigmoid top-k as Conditional-DETR/RT-DETR.

TPU-first notes: static shapes throughout; the shared-vs-per-layer head
tying and the first-layer-only `ca_qpos_proj` are static Python branches at
trace time; anchor refinement runs fp32 (repo box-precision policy) while
the heavy matmuls run the compute dtype.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from spotter_tpu.models.conditional_detr import _attend
from spotter_tpu.models.configs import DabDetrConfig
from spotter_tpu.models.detr import nearest_downsample_mask, sine_position_from_mask
from spotter_tpu.models.layers import (
    MLPHead,
    MultiHeadAttention,
    PReLU,
    inverse_sigmoid,
)
from spotter_tpu.models.resnet import ResNetBackbone


def anchor_sine_embedding(boxes: jnp.ndarray, d_model: int) -> jnp.ndarray:
    """Sine embedding of normalized (x, y, w, h) anchors, (B, Q, 2*d_model).

    Matches gen_sine_position_embeddings (modeling_dab_detr.py): scale 2*pi,
    d_model/2 channels per coordinate, concatenated [y, x, w, h].
    """
    dim = d_model // 2
    dim_t = 10000.0 ** (2 * (np.arange(dim, dtype=np.float32) // 2) / dim)

    def interleave(p):
        return jnp.stack([jnp.sin(p[..., 0::2]), jnp.cos(p[..., 1::2])], axis=-1).reshape(
            *p.shape[:-1], -1
        )

    def emb(coord):
        return interleave(coord[..., None] * (2 * math.pi) / dim_t)

    return jnp.concatenate(
        [emb(boxes[..., 1]), emb(boxes[..., 0]), emb(boxes[..., 2]), emb(boxes[..., 3])],
        axis=-1,
    )


class DabEncoderLayer(nn.Module):
    """DETR-style post-norm encoder layer with a learned PReLU FFN."""

    config: DabDetrConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, hidden: jnp.ndarray, pos: jnp.ndarray, attn_mask: Optional[jnp.ndarray]
    ) -> jnp.ndarray:
        cfg = self.config
        attn = MultiHeadAttention(
            cfg.d_model, cfg.encoder_attention_heads, dtype=self.dtype, name="self_attn"
        )(hidden, position_embeddings=pos, attention_mask=attn_mask)
        hidden = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="self_attn_layer_norm"
        )(hidden + attn)
        y = nn.Dense(cfg.encoder_ffn_dim, dtype=self.dtype, name="fc1")(hidden)
        y = PReLU(dtype=self.dtype, name="activation")(y)
        y = nn.Dense(cfg.d_model, dtype=self.dtype, name="fc2")(y)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm"
        )(hidden + y)


class DabDecoderLayer(nn.Module):
    """Conditional-style decoder layer with DAB's sine-conditioned cross-attn."""

    config: DabDetrConfig
    is_first: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        hidden: jnp.ndarray,  # (B, Q, D)
        query_pos: jnp.ndarray,  # (B, Q, D) from ref_point_head
        query_sine: jnp.ndarray,  # (B, Q, D) scaled+modulated anchor sine
        memory: jnp.ndarray,  # (B, S, D)
        memory_pos: jnp.ndarray,  # (B, S, D)
        memory_mask: Optional[jnp.ndarray],
    ) -> jnp.ndarray:
        cfg = self.config
        d, heads = cfg.d_model, cfg.decoder_attention_heads
        dense = lambda name: nn.Dense(d, dtype=self.dtype, name=name)

        # self-attention: decoupled content/position projections
        q = dense("sa_qcontent_proj")(hidden) + dense("sa_qpos_proj")(query_pos)
        k = dense("sa_kcontent_proj")(hidden) + dense("sa_kpos_proj")(query_pos)
        v = dense("sa_v_proj")(hidden)
        attn = _attend(q, k, v, heads, None, self.dtype)
        attn = dense("self_attn_out_proj")(attn)
        hidden = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="self_attn_layer_norm"
        )(hidden + attn)

        # cross-attention: per-head concat of content and spatial halves
        qc = dense("ca_qcontent_proj")(hidden)
        kc = dense("ca_kcontent_proj")(memory)
        v = dense("ca_v_proj")(memory)
        kpos = dense("ca_kpos_proj")(memory_pos)
        if self.is_first or cfg.keep_query_pos:
            qc = qc + dense("ca_qpos_proj")(query_pos)
            kc = kc + kpos
        qsine = dense("ca_qpos_sine_proj")(query_sine)

        b, nq, _ = qc.shape
        s = kc.shape[1]
        head = d // heads
        q2 = jnp.concatenate(
            [qc.reshape(b, nq, heads, head), qsine.reshape(b, nq, heads, head)], axis=-1
        ).reshape(b, nq, 2 * d)
        k2 = jnp.concatenate(
            [kc.reshape(b, s, heads, head), kpos.reshape(b, s, heads, head)], axis=-1
        ).reshape(b, s, 2 * d)
        cross = _attend(q2, k2, v, heads, memory_mask, self.dtype)
        cross = dense("encoder_attn_out_proj")(cross)
        hidden = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="encoder_attn_layer_norm"
        )(hidden + cross)

        ffn = nn.Dense(cfg.decoder_ffn_dim, dtype=self.dtype, name="fc1")(hidden)
        ffn = PReLU(dtype=self.dtype, name="activation")(ffn)
        ffn = nn.Dense(d, dtype=self.dtype, name="fc2")(ffn)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm"
        )(hidden + ffn)


class DabDetrDetector(nn.Module):
    """DAB-DETR: pixels (+mask) -> {"logits" (B,Q,C), "pred_boxes" cxcywh}."""

    config: DabDetrConfig
    dtype: jnp.dtype = jnp.float32
    # "mixed" policy: bf16 backbone convs, compute dtype for the transformer
    backbone_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(
        self, pixel_values: jnp.ndarray, pixel_mask: Optional[jnp.ndarray] = None
    ) -> dict:
        cfg = self.config
        b, h, w, _ = pixel_values.shape
        if pixel_mask is None:
            pixel_mask = jnp.ones((b, h, w), dtype=jnp.float32)

        features = ResNetBackbone(
            cfg.backbone, dtype=self.backbone_dtype or self.dtype, name="backbone"
        )(pixel_values)
        feat = features[-1].astype(self.dtype)
        _, fh, fw, _ = feat.shape
        mask = nearest_downsample_mask(pixel_mask, (fh, fw))

        pos = sine_position_from_mask(
            mask, cfg.d_model // 2, (cfg.temperature_height, cfg.temperature_width)
        ).astype(self.dtype)
        src = nn.Conv(
            cfg.d_model, (1, 1), use_bias=True, dtype=self.dtype, name="input_projection"
        )(feat)
        src = src.reshape(b, fh * fw, cfg.d_model)
        pos = pos.reshape(b, fh * fw, cfg.d_model)
        mask_flat = mask.reshape(b, fh * fw)
        attn_mask = jnp.where(
            mask_flat[:, None, None, :] > 0, 0.0, jnp.finfo(jnp.float32).min
        )

        # encoder: the sine map is rescaled per layer by a content MLP
        enc_query_scale = MLPHead(
            cfg.d_model, cfg.d_model, 2, dtype=self.dtype, name="encoder_query_scale"
        )
        for i in range(cfg.encoder_layers):
            src = DabEncoderLayer(cfg, dtype=self.dtype, name=f"encoder_layer{i}")(
                src, pos * enc_query_scale(src), attn_mask
            )

        # learned 4D anchor queries
        refpoints = self.param(
            "query_refpoints",
            nn.initializers.normal(1.0),
            (cfg.num_queries, cfg.query_dim),
            jnp.float32,
        )
        ref = jnp.broadcast_to(
            nn.sigmoid(refpoints)[None], (b, cfg.num_queries, cfg.query_dim)
        )

        ref_point_head = MLPHead(
            cfg.d_model, cfg.d_model, 2, dtype=self.dtype, name="ref_point_head"
        )
        query_scale = MLPHead(
            cfg.d_model, cfg.d_model, 2, dtype=self.dtype, name="query_scale"
        )
        ref_anchor_head = MLPHead(cfg.d_model, 2, 2, dtype=self.dtype, name="ref_anchor_head")
        bbox_head = MLPHead(cfg.d_model, 4, 3, dtype=self.dtype, name="bbox_predictor")
        decoder_ln = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="decoder_layernorm"
        )

        half = cfg.d_model // 2
        hidden = jnp.zeros((b, cfg.num_queries, cfg.d_model), self.dtype)
        intermediate = []
        ref_inputs = []  # refs entering each layer (box decode anchor)
        for i in range(cfg.decoder_layers):
            ref_inputs.append(ref)
            sine_full = anchor_sine_embedding(ref, cfg.d_model).astype(self.dtype)
            query_pos = ref_point_head(sine_full)
            scale = 1.0 if i == 0 else query_scale(hidden)
            query_sine = sine_full[..., : cfg.d_model] * scale
            # modulated height/width attention: rescale the x/y sine halves
            # by predicted anchor aspect over the current anchor size
            ref_hw = nn.sigmoid(ref_anchor_head(hidden).astype(jnp.float32))  # (B,Q,2)
            mod_y = (ref_hw[..., 1] / ref[..., 3])[..., None].astype(self.dtype)
            mod_x = (ref_hw[..., 0] / ref[..., 2])[..., None].astype(self.dtype)
            query_sine = jnp.concatenate(
                [query_sine[..., :half] * mod_y, query_sine[..., half:] * mod_x], axis=-1
            )
            hidden = DabDecoderLayer(
                cfg, is_first=(i == 0), dtype=self.dtype, name=f"decoder_layer{i}"
            )(hidden, query_pos, query_sine, src, pos, attn_mask)
            # iterative anchor refinement through the SHARED box head (raw
            # hidden; the output boxes below use the layernormed hidden)
            delta = bbox_head(hidden).astype(jnp.float32)
            ref = jax.lax.stop_gradient(nn.sigmoid(delta + inverse_sigmoid(ref)))
            intermediate.append(decoder_ln(hidden))

        logits = nn.Dense(cfg.num_labels, dtype=self.dtype, name="class_embed")(
            intermediate[-1]
        )
        aux_boxes = []
        for hid, r in zip(intermediate, ref_inputs):
            d = bbox_head(hid).astype(jnp.float32)
            aux_boxes.append(nn.sigmoid(d + inverse_sigmoid(r)))
        return {
            "logits": logits.astype(jnp.float32),
            "pred_boxes": aux_boxes[-1],
            "aux_boxes": jnp.stack(aux_boxes, axis=1),
        }
