"""Flax YOLOS (hustvl/yolos-*): plain ViT with appended detection tokens.

Semantics match HF's YolosForObjectDetection (modeling_yolos.py): patch
embedding conv, [CLS] + patch + detection tokens with a single learned
position table, pre-norm ViT layers, optional per-layer "mid" position
embeddings added after every non-final layer, final layernorm, and two
3-layer MLP heads (class incl. "no object", sigmoid boxes) applied to the
detection-token outputs only.

TPU-first notes: the serving preprocess warp-resizes to the checkpoint's
native `image_size`, so position tables are used exactly as trained and every
shape is static (SURVEY.md §5.7). For other static input sizes the tables are
interpolated bicubically at trace time (jax.image uses the Catmull-Rom kernel
a=-0.5 vs torch bicubic a=-0.75 — trained-size inputs avoid the difference
entirely). The reference serves this family through the same
`AutoModelForObjectDetection` boundary (serve.py:199-205).
"""

import jax
import jax.numpy as jnp
from flax import linen as nn

from spotter_tpu.models.configs import YolosConfig
from spotter_tpu.models.layers import (
    FLASH_ATTN_MIN_SEQ,
    MLPHead,
    PatchEmbed,
    QuantDense,
    flash_self_attention,
    flash_attention_enabled,
    get_activation,
)


def _interpolate_patch_pos(
    table: jnp.ndarray, src_hw: tuple[int, int], dst_hw: tuple[int, int]
) -> jnp.ndarray:
    """(1, src_h*src_w, D) patch position table -> (1, dst_h*dst_w, D)."""
    if src_hw == dst_hw:
        return table
    d = table.shape[-1]
    grid = table.reshape(1, *src_hw, d)
    grid = jax.image.resize(grid, (1, *dst_hw, d), method="bicubic")
    return grid.reshape(1, dst_hw[0] * dst_hw[1], d)


class YolosAttention(nn.Module):
    """ViT-style self-attention (separate query/key/value + output dense)."""

    config: YolosConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        heads = cfg.num_attention_heads
        head_dim = cfg.hidden_size // heads

        def proj(name):
            return QuantDense(
                cfg.hidden_size, use_bias=cfg.qkv_bias, dtype=self.dtype, name=name
            )(x).reshape(*x.shape[:-1], heads, head_dim)

        q = proj("query")
        k = proj("key")
        v = proj("value")
        if flash_attention_enabled() and q.shape[1] >= FLASH_ATTN_MIN_SEQ:
            # ViT-detector sequences (800x1344 -> 4300 tokens) make the
            # naive path HBM-bound on the (B, H, S, S) scores; the flash
            # kernel never materializes them (layers.py cutover notes)
            out = flash_self_attention(q * (head_dim**-0.5), k, v)
        else:
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (head_dim**-0.5)
            weights = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
                self.dtype
            )
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        out = out.reshape(*out.shape[:-2], cfg.hidden_size)
        return QuantDense(cfg.hidden_size, dtype=self.dtype, name="out")(out)


class YolosLayer(nn.Module):
    """Pre-norm ViT block (YolosLayer)."""

    config: YolosConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        normed = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="layernorm_before"
        )(x)
        x = x + YolosAttention(cfg, dtype=self.dtype, name="attention")(normed)
        normed = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="layernorm_after"
        )(x)
        ffn = QuantDense(cfg.intermediate_size, dtype=self.dtype, name="fc1")(normed)
        ffn = get_activation(cfg.hidden_act)(ffn)
        return x + QuantDense(cfg.hidden_size, dtype=self.dtype, name="fc2")(ffn)


class YolosDetector(nn.Module):
    """YOLOS detector: returns {"logits": (B, T, C+1), "pred_boxes": (B, T, 4)}."""

    config: YolosConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixel_values: jnp.ndarray) -> dict[str, jnp.ndarray]:
        cfg = self.config
        b, h, w, _ = pixel_values.shape
        p = cfg.patch_size
        if h % p or w % p:
            raise ValueError(f"input {h}x{w} not divisible by patch size {p}")
        gh, gw = h // p, w // p
        src_hw = cfg.grid_hw
        n_src = src_hw[0] * src_hw[1]
        t = cfg.num_detection_tokens

        # row-dot patchify (layers.PatchEmbed): exact conv rewrite, ~2x on
        # v5e for 3-channel patchify (BASELINE.md round 4)
        x = PatchEmbed(
            cfg.hidden_size, p, dtype=self.dtype, name="patch_projection"
        )(pixel_values)

        cls_token = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, cfg.hidden_size), jnp.float32
        )
        det_tokens = self.param(
            "detection_tokens", nn.initializers.zeros, (1, t, cfg.hidden_size), jnp.float32
        )
        pos_table = self.param(
            "position_embeddings",
            nn.initializers.zeros,
            (1, n_src + t + 1, cfg.hidden_size),
            jnp.float32,
        )
        x = jnp.concatenate(
            [
                jnp.broadcast_to(cls_token.astype(self.dtype), (b, 1, cfg.hidden_size)),
                x,
                jnp.broadcast_to(det_tokens.astype(self.dtype), (b, t, cfg.hidden_size)),
            ],
            axis=1,
        )

        def split_pos(table):
            return (
                table[:, :1],
                _interpolate_patch_pos(table[:, 1 : 1 + n_src], src_hw, (gh, gw)),
                table[:, 1 + n_src :],
            )

        pos = jnp.concatenate(split_pos(pos_table), axis=1)
        x = x + pos.astype(self.dtype)

        if cfg.use_mid_position_embeddings:
            mid_table = self.param(
                "mid_position_embeddings",
                nn.initializers.zeros,
                (cfg.num_hidden_layers - 1, 1, n_src + t + 1, cfg.hidden_size),
                jnp.float32,
            )
        for i in range(cfg.num_hidden_layers):
            x = YolosLayer(cfg, dtype=self.dtype, name=f"layer{i}")(x)
            if cfg.use_mid_position_embeddings and i < cfg.num_hidden_layers - 1:
                mid = jnp.concatenate(split_pos(mid_table[i]), axis=1)
                x = x + mid.astype(self.dtype)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="layernorm")(x)
        det_out = x[:, -t:]

        # fp32 head outputs under bf16 compute (box precision at 640 px scale)
        logits = MLPHead(
            cfg.hidden_size, cfg.num_labels + 1, 3, dtype=self.dtype,
            name="class_labels_classifier",
        )(det_out)
        boxes = nn.sigmoid(
            MLPHead(cfg.hidden_size, 4, 3, dtype=self.dtype, name="bbox_predictor")(
                det_out
            ).astype(jnp.float32)
        )
        return {"logits": logits.astype(jnp.float32), "pred_boxes": boxes}
