"""Flax RT-DETR / RT-DETRv2 detector — TPU-first implementation.

Replaces the reference's torch `AutoModelForObjectDetection` forward
(apps/spotter/src/spotter/serve.py:99-100) for MODEL_NAME values in the
PekingU/rtdetr* family. Architecture semantics follow the published RT-DETRv2
model (hybrid encoder with AIFI + CSP-RepVGG FPN/PAN; NMS-free deformable
decoder with iterative box refinement), implemented in NHWC with static
shapes so jit compiles once per input bucket:

- anchors, sin-cos position tables, and per-level token spans are computed in
  numpy at trace time from static spatial shapes — XLA constant-folds them;
- multiscale deformable attention runs through the shared sampling core
  (spotter_tpu/ops/msda.py): on TPU the gather-free level-split one-hot
  Pallas kernel (one-hot weight tiles contracted on the MXU), XLA
  row-gathers elsewhere; this is the TPU-native replacement for the torch
  lineage's custom CUDA sampler;
- the whole forward is one jit region: backbone -> encoder -> decoder ->
  (logits, boxes); no data-dependent control flow.
"""

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from spotter_tpu.models.configs import RTDetrConfig
from spotter_tpu.models.layers import (
    ConvNorm,
    ConvNormParams,
    DenseParams,
    MLPHead,
    MultiHeadAttention,
    get_activation,
    inverse_sigmoid,
    sincos_2d_position_embedding,
)
from spotter_tpu.models.resnet import ResNetBackbone
from spotter_tpu.ops.msda import (
    deformable_sampling,
    deformable_sampling_fused,
    locality_presort,
    msda_prep_fused,
    presort_wanted,
)
from spotter_tpu.ops.topk import top_k as fast_top_k
from spotter_tpu.utils.precision import compute_dtype
from spotter_tpu.utils.quant import int8_conv, int8_wanted


def generate_anchors(
    spatial_shapes: tuple[tuple[int, int], ...],
    grid_size: float = 0.05,
    eps: float = 1e-2,
) -> tuple[np.ndarray, np.ndarray]:
    """Static anchor logits per multi-level grid cell.

    Returns (anchors_logit (1, S, 4), valid_mask (1, S, 1)) in numpy; invalid
    anchors get float32 max so sigmoid saturates at 1 (matching the torch
    semantics of masking with finfo.max before sigmoid).
    """
    all_anchors = []
    for level, (h, w) in enumerate(spatial_shapes):
        gy, gx = np.meshgrid(
            np.arange(h, dtype=np.float32), np.arange(w, dtype=np.float32), indexing="ij"
        )
        gxy = np.stack([gx, gy], axis=-1) + 0.5
        gxy[..., 0] /= w
        gxy[..., 1] /= h
        wh = np.ones_like(gxy) * grid_size * (2.0**level)
        all_anchors.append(np.concatenate([gxy, wh], -1).reshape(h * w, 4))
    anchors = np.concatenate(all_anchors, 0)[None]
    valid = ((anchors > eps) & (anchors < 1 - eps)).all(-1, keepdims=True)
    anchors_logit = np.log(anchors / (1 - anchors))
    anchors_logit = np.where(valid, anchors_logit, np.finfo(np.float32).max)
    return anchors_logit.astype(np.float32), valid.astype(np.float32)


class EncoderLayer(nn.Module):
    """AIFI transformer encoder layer (post-norm)."""

    embed_dim: int
    num_heads: int
    ffn_dim: int
    activation: str = "gelu"
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, pos: Optional[jnp.ndarray]) -> jnp.ndarray:
        attn_out = MultiHeadAttention(
            self.embed_dim, self.num_heads, dtype=self.dtype, name="self_attn"
        )(x, position_embeddings=pos)
        x = nn.LayerNorm(epsilon=self.eps, dtype=self.dtype, name="self_attn_layer_norm")(
            x + attn_out
        )
        y = nn.Dense(self.ffn_dim, dtype=self.dtype, name="fc1")(x)
        y = get_activation(self.activation)(y)
        y = nn.Dense(self.embed_dim, dtype=self.dtype, name="fc2")(y)
        return nn.LayerNorm(epsilon=self.eps, dtype=self.dtype, name="final_layer_norm")(x + y)


# RepVGG re-parameterization at trace time (the classic inference-time
# identity the torch reference never applies): conv3x3+BN + conv1x1+BN
# summed == ONE 3x3 conv with kernel w3*mul3 + center-pad(w1*mul1) and bias
# add3+add1 — exact up to float reassociation. Saves the 1x1 conv's HBM
# pass + the elementwise add per RepVgg block (30 blocks in the R101
# encoder; measured 235.5 -> 239.7 img/s on v5e, bf16 batch 8). Default
# follows the precision policy like the MSDA sampling precision: fused only
# when the encoder half (where RepVgg blocks live) already runs bf16 —
# i.e. the "bfloat16" policy; "mixed" deliberately pins the transformer
# half to exact fp32, so it stays unfused there like under "float32".
# Override with SPOTTER_TPU_REP_FUSE=0/1 (read at import, like the other
# process knobs).
def _rep_fuse_default() -> bool:
    flag = os.environ.get("SPOTTER_TPU_REP_FUSE", "").strip()
    if flag:
        return flag != "0"
    return compute_dtype() == jnp.bfloat16


REP_FUSE = _rep_fuse_default()


class RepVggBlock(nn.Module):
    features: int
    activation: str = "silu"
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if REP_FUSE:
            w3, b3 = ConvNormParams(
                self.features, 3, x.shape[-1], self.eps, name="conv1"
            )()
            w1, b1 = ConvNormParams(
                self.features, 1, x.shape[-1], self.eps, name="conv2"
            )()
            wf = w3.at[1:2, 1:2].add(w1)
            if int8_wanted(x.shape[-1], batch=x.shape[0]):
                # int8 MXU path on the already-fused kernel (utils/quant.py):
                # these 384-ch 3x3 convs are the encoder's measured hot spot
                # (tools/bench_int8_conv.py: 1.5-1.6x at 80^2/40^2)
                y = int8_conv(x, wf, (1, 1), ((1, 1), (1, 1)), self.dtype)
            else:
                y = jax.lax.conv_general_dilated(
                    x,
                    wf.astype(self.dtype),
                    window_strides=(1, 1),
                    padding=((1, 1), (1, 1)),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
            y = y + (b3 + b1).astype(self.dtype)
            return get_activation(self.activation)(y)
        y = ConvNorm(self.features, 3, 1, padding=1, eps=self.eps, dtype=self.dtype, name="conv1")(x)
        z = ConvNorm(self.features, 1, 1, padding=0, eps=self.eps, dtype=self.dtype, name="conv2")(x)
        return get_activation(self.activation)(y + z)


class CSPRepLayer(nn.Module):
    """Cross-stage-partial fusion block with RepVGG bottlenecks."""

    out_channels: int
    hidden_channels: int
    num_blocks: int = 3
    activation: str = "silu"
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h1 = ConvNorm(
            self.hidden_channels, 1, 1, activation=self.activation, eps=self.eps,
            dtype=self.dtype, name="conv1",
        )(x)
        for i in range(self.num_blocks):
            h1 = RepVggBlock(
                self.hidden_channels, self.activation, self.eps, self.dtype,
                name=f"bottleneck{i}",
            )(h1)
        h2 = ConvNorm(
            self.hidden_channels, 1, 1, activation=self.activation, eps=self.eps,
            dtype=self.dtype, name="conv2",
        )(x)
        y = h1 + h2
        if self.hidden_channels != self.out_channels:
            y = ConvNorm(
                self.out_channels, 1, 1, activation=self.activation, eps=self.eps,
                dtype=self.dtype, name="conv3",
            )(y)
        return y


class DeformableAttention(nn.Module):
    """Multiscale deformable cross-attention (RT-DETRv2 semantics).

    Sampling offsets are scaled by 1/n_points, the reference-box size, and
    `offset_scale` (v2); sampling itself is bilinear ("default") or
    nearest-integer ("discrete") over each level's value map.
    """

    d_model: int
    num_heads: int
    num_levels: int
    num_points: int
    offset_scale: float = 0.5
    method: str = "default"
    dtype: jnp.dtype = jnp.float32
    presorted: bool = False

    @nn.compact
    def __call__(
        self,
        hidden_states: jnp.ndarray,  # (B, Q, D)
        position_embeddings: Optional[jnp.ndarray],
        encoder_hidden_states: jnp.ndarray,  # (B, S, D)
        reference_points: jnp.ndarray,  # (B, Q, 4) normalized cxcywh
        spatial_shapes: tuple[tuple[int, int], ...],
    ) -> jnp.ndarray:
        b, q, _ = hidden_states.shape
        heads, levels, points = self.num_heads, self.num_levels, self.num_points
        head_dim = self.d_model // heads
        hs = hidden_states
        if position_embeddings is not None:
            hs = hs + position_embeddings

        value = nn.Dense(self.d_model, dtype=self.dtype, name="value_proj")(
            encoder_hidden_states
        )
        s = value.shape[1]
        value = value.reshape(b, s, heads, head_dim)

        if msda_prep_fused():
            # SPOTTER_TPU_MSDA_PREP=fused: the offset/attention projections,
            # softmax, and location arithmetic run inside the Pallas MSDA
            # kernel's prologue. DenseParams declares the SAME param paths
            # (sampling_offsets/attention_weights {kernel, bias}, identical
            # inits) as the nn.Dense calls below, so checkpoints swap freely
            # between the fused and unfused paths.
            w_off, b_off = DenseParams(
                heads * levels * points * 2, self.d_model, name="sampling_offsets"
            )()
            w_att, b_att = DenseParams(
                heads * levels * points, self.d_model, name="attention_weights"
            )()
            out = deformable_sampling_fused(
                value, hs, reference_points, w_off, b_off, w_att, b_att,
                spatial_shapes, points, offset_scale=self.offset_scale,
                method=self.method, presorted=self.presorted,
            )
            return nn.Dense(self.d_model, dtype=self.dtype, name="output_proj")(out)

        offsets = nn.Dense(
            heads * levels * points * 2, dtype=self.dtype, name="sampling_offsets"
        )(hs).reshape(b, q, heads, levels * points, 2)
        attn = nn.Dense(heads * levels * points, dtype=self.dtype, name="attention_weights")(
            hs
        ).reshape(b, q, heads, levels * points)
        attn = nn.softmax(attn.astype(jnp.float32), axis=-1).astype(self.dtype)

        # v2 offset semantics: offsets * (1/n_points) * ref_wh * offset_scale
        n_points_scale = np.repeat(
            1.0 / np.asarray([points] * levels, np.float32), points
        )[None, None, None, :, None]
        ref_xy = reference_points[:, :, None, None, :2]
        ref_wh = reference_points[:, :, None, None, 2:]
        loc = ref_xy + offsets * jnp.asarray(n_points_scale, self.dtype) * ref_wh * self.offset_scale
        # loc: (B, Q, H, L*P, 2) in [0, 1]

        # Shared sampling core (spotter_tpu/ops/msda.py): level-split one-hot
        # Pallas kernel on TPU, XLA row-gathers elsewhere (SPOTTER_TPU_MSDA).
        out = deformable_sampling(
            value, loc, attn, spatial_shapes, points, method=self.method,
            presorted=self.presorted,
        )
        return nn.Dense(self.d_model, dtype=self.dtype, name="output_proj")(out)


class DecoderLayer(nn.Module):
    config: RTDetrConfig
    dtype: jnp.dtype = jnp.float32
    presorted: bool = False

    @nn.compact
    def __call__(
        self,
        hidden_states: jnp.ndarray,
        position_embeddings: jnp.ndarray,
        encoder_hidden_states: jnp.ndarray,
        reference_points: jnp.ndarray,
        spatial_shapes: tuple[tuple[int, int], ...],
        self_attention_mask: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        cfg = self.config
        eps = cfg.layer_norm_eps
        attn_out = MultiHeadAttention(
            cfg.d_model, cfg.decoder_attention_heads, dtype=self.dtype, name="self_attn"
        )(hidden_states, position_embeddings=position_embeddings,
          attention_mask=self_attention_mask)
        h = nn.LayerNorm(epsilon=eps, dtype=self.dtype, name="self_attn_layer_norm")(
            hidden_states + attn_out
        )
        cross = DeformableAttention(
            cfg.d_model,
            cfg.decoder_attention_heads,
            cfg.num_feature_levels,
            cfg.decoder_n_points,
            offset_scale=cfg.decoder_offset_scale,
            method=cfg.decoder_method,
            dtype=self.dtype,
            presorted=self.presorted,
            name="encoder_attn",
        )(h, position_embeddings, encoder_hidden_states, reference_points, spatial_shapes)
        h = nn.LayerNorm(epsilon=eps, dtype=self.dtype, name="encoder_attn_layer_norm")(h + cross)
        y = nn.Dense(cfg.decoder_ffn_dim, dtype=self.dtype, name="fc1")(h)
        y = get_activation(cfg.decoder_activation_function)(y)
        y = nn.Dense(cfg.d_model, dtype=self.dtype, name="fc2")(y)
        return nn.LayerNorm(epsilon=eps, dtype=self.dtype, name="final_layer_norm")(h + y)


class RTDetrDetector(nn.Module):
    """Full RT-DETR(v2) detector: pixels (B, H, W, 3) -> logits + boxes.

    Returns a dict: logits (B, Q, C), pred_boxes (B, Q, 4) normalized cxcywh,
    aux_logits/aux_boxes stacked over decoder layers (for training losses),
    enc_topk_logits/enc_topk_bboxes (encoder auxiliary head).
    """

    config: RTDetrConfig
    dtype: jnp.dtype = jnp.float32
    # Optional separate backbone compute dtype ("mixed" policy): the ResNet's
    # convs are HBM-bandwidth-bound and win from bf16 (measured v5e R101
    # batch 8: 22.3 -> 17.9 ms) while the transformer+sampling half is
    # fastest fp32 — casting only at the 1/8-resolution feature boundary
    # keeps the decoder's fp32 fusions intact.
    backbone_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(
        self,
        pixel_values: jnp.ndarray,
        decoder_input_queries: Optional[jnp.ndarray] = None,
        decoder_input_ref_logits: Optional[jnp.ndarray] = None,
        self_attention_mask: Optional[jnp.ndarray] = None,
    ) -> dict:
        cfg = self.config
        feats = ResNetBackbone(
            cfg.backbone, dtype=self.backbone_dtype or self.dtype, name="backbone"
        )(pixel_values)
        feats = [f.astype(self.dtype) for f in feats]

        proj = [
            ConvNorm(
                cfg.encoder_hidden_dim, 1, 1, activation=None, eps=cfg.batch_norm_eps,
                dtype=self.dtype, name=f"enc_proj{i}",
            )(f)
            for i, f in enumerate(feats)
        ]

        # --- AIFI: transformer encoder on selected (stride-32) levels ---
        for i, enc_ind in enumerate(cfg.encode_proj_layers):
            b, h, w, c = proj[enc_ind].shape
            src = proj[enc_ind].reshape(b, h * w, c)
            pos = jnp.asarray(
                sincos_2d_position_embedding(
                    w, h, cfg.encoder_hidden_dim, cfg.positional_encoding_temperature
                ),
                self.dtype,
            )
            for j in range(cfg.encoder_layers):
                src = EncoderLayer(
                    cfg.encoder_hidden_dim,
                    cfg.encoder_attention_heads,
                    cfg.encoder_ffn_dim,
                    cfg.encoder_activation_function,
                    cfg.layer_norm_eps,
                    self.dtype,
                    name=f"aifi{i}_layer{j}",
                )(src, pos)
            proj[enc_ind] = src.reshape(b, h, w, c)

        # --- top-down FPN ---
        hidden_channels = int(cfg.encoder_hidden_dim * cfg.hidden_expansion)
        num_stages = len(cfg.encoder_in_channels) - 1
        fpn = [proj[-1]]
        for idx in range(num_stages):
            backbone_fm = proj[num_stages - idx - 1]
            top = ConvNorm(
                cfg.encoder_hidden_dim, 1, 1, activation=cfg.activation_function,
                eps=cfg.batch_norm_eps, dtype=self.dtype, name=f"lateral_conv{idx}",
            )(fpn[-1])
            fpn[-1] = top
            up = jnp.repeat(jnp.repeat(top, 2, axis=1), 2, axis=2)  # 2x nearest
            fused = jnp.concatenate([up, backbone_fm], axis=-1)
            fpn.append(
                CSPRepLayer(
                    cfg.encoder_hidden_dim, hidden_channels, cfg.csp_num_blocks,
                    cfg.activation_function, cfg.batch_norm_eps, self.dtype,
                    name=f"fpn_block{idx}",
                )(fused)
            )
        fpn = fpn[::-1]

        # --- bottom-up PAN ---
        pan = [fpn[0]]
        for idx in range(num_stages):
            down = ConvNorm(
                cfg.encoder_hidden_dim, 3, 2, activation=cfg.activation_function,
                eps=cfg.batch_norm_eps, dtype=self.dtype, name=f"downsample_conv{idx}",
            )(pan[-1])
            fused = jnp.concatenate([down, fpn[idx + 1]], axis=-1)
            pan.append(
                CSPRepLayer(
                    cfg.encoder_hidden_dim, hidden_channels, cfg.csp_num_blocks,
                    cfg.activation_function, cfg.batch_norm_eps, self.dtype,
                    name=f"pan_block{idx}",
                )(fused)
            )

        # --- decoder input projection + flatten ---
        sources = [
            ConvNorm(
                cfg.d_model, 1, 1, activation=None, eps=cfg.batch_norm_eps,
                dtype=self.dtype, name=f"dec_proj{i}",
            )(p)
            for i, p in enumerate(pan)
        ]
        for i in range(len(sources), cfg.num_feature_levels):
            sources.append(
                ConvNorm(
                    cfg.d_model, 3, 2, padding=1, activation=None, eps=cfg.batch_norm_eps,
                    dtype=self.dtype, name=f"dec_proj{i}",
                )(sources[-1])
            )

        spatial_shapes = tuple((s.shape[1], s.shape[2]) for s in sources)
        b = sources[0].shape[0]
        source_flatten = jnp.concatenate(
            [s.reshape(b, -1, cfg.d_model) for s in sources], axis=1
        )

        # --- encoder head: anchor scoring + top-k query selection ---
        anchors_np, valid_np = generate_anchors(spatial_shapes, cfg.anchor_grid_size)
        anchors = jnp.asarray(anchors_np, self.dtype)
        valid_mask = jnp.asarray(valid_np, self.dtype)

        memory = valid_mask * source_flatten
        output_memory = nn.Dense(cfg.d_model, dtype=self.dtype, name="enc_output_dense")(memory)
        output_memory = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="enc_output_norm"
        )(output_memory)

        enc_class = nn.Dense(cfg.num_labels, dtype=self.dtype, name="enc_score_head")(
            output_memory
        )
        enc_coord_logits = (
            MLPHead(cfg.d_model, 4, 3, dtype=self.dtype, name="enc_bbox_head")(output_memory)
            + anchors
        )

        # ops/topk.py: lax.top_k by default; SPOTTER_TPU_TOPK=bisect swaps in
        # the sort-free radix path (identical result, for wider-S hardware)
        _, topk_ind = fast_top_k(enc_class.max(-1), cfg.num_queries)
        gather = lambda arr: jnp.take_along_axis(arr, topk_ind[..., None], axis=1)
        reference_logits = gather(enc_coord_logits)
        enc_topk_logits = gather(enc_class)
        enc_topk_bboxes = nn.sigmoid(reference_logits.astype(jnp.float32))

        if cfg.learn_initial_query:
            target = self.param(
                "query_embed", nn.initializers.normal(1.0), (cfg.num_queries, cfg.d_model)
            )
            target = jnp.broadcast_to(target, (b, cfg.num_queries, cfg.d_model)).astype(self.dtype)
        else:
            target = jax.lax.stop_gradient(gather(output_memory))

        reference_logits = jax.lax.stop_gradient(reference_logits)

        # Denoising groups (training) enter here as extra queries.
        if decoder_input_queries is not None:
            target = jnp.concatenate([decoder_input_queries, target], axis=1)
            reference_logits = jnp.concatenate(
                [decoder_input_ref_logits, reference_logits], axis=1
            )

        # --- decoder with iterative refinement ---
        # Box-refinement arithmetic stays fp32 even under bf16 compute: the
        # sigmoid/inverse-sigmoid iteration across decoder layers would
        # otherwise accumulate bf16 rounding into multi-pixel box drift
        # (the heavy matmuls in DecoderLayer/MLPHead still run self.dtype).
        ref = nn.sigmoid(reference_logits.astype(jnp.float32))
        h = target
        # Model-level locality presort (ops/msda.py presort_wanted): the six
        # decoder layers share one spatial ordering of the queries, so sort
        # ONCE here by the initial reference centers (layer sampling points
        # cluster around them; later refinement moves boxes only slightly)
        # instead of paying argsort + two q-row permutes inside every
        # sampling op. Exact: queries are permutation-equivariant through
        # full self-attention, and outputs are un-permuted below. Skipped
        # when a self-attention mask is present (denoising training) —
        # ordering would have to permute the mask too; the in-op sort
        # handles that case unchanged.
        presort = presort_wanted() and self_attention_mask is None
        if presort:
            sort_q, unsort_q = locality_presort(ref[..., :2])
            h, ref = sort_q(h), sort_q(ref)
        query_pos_head = MLPHead(
            2 * cfg.d_model, cfg.d_model, 2, dtype=self.dtype, name="query_pos_head"
        )
        aux_logits, aux_boxes = [], []
        for i in range(cfg.decoder_layers):
            pos = query_pos_head(ref.astype(self.dtype))
            h = DecoderLayer(
                cfg, dtype=self.dtype, presorted=presort, name=f"decoder_layer{i}"
            )(
                h, pos, source_flatten, ref.astype(self.dtype), spatial_shapes,
                self_attention_mask,
            )
            box_delta = MLPHead(cfg.d_model, 4, 3, dtype=self.dtype, name=f"bbox_head{i}")(h)
            new_ref = nn.sigmoid(box_delta.astype(jnp.float32) + inverse_sigmoid(ref))
            logits_i = nn.Dense(cfg.num_labels, dtype=self.dtype, name=f"class_head{i}")(h)
            aux_logits.append(logits_i.astype(jnp.float32))
            aux_boxes.append(new_ref)
            ref = jax.lax.stop_gradient(new_ref)

        if presort:
            aux_logits = [unsort_q(a) for a in aux_logits]
            aux_boxes = [unsort_q(a) for a in aux_boxes]

        return {
            "logits": aux_logits[-1],
            "pred_boxes": aux_boxes[-1],
            "aux_logits": jnp.stack(aux_logits, axis=1),
            "aux_boxes": jnp.stack(aux_boxes, axis=1),
            "enc_topk_logits": enc_topk_logits.astype(jnp.float32),
            "enc_topk_bboxes": enc_topk_bboxes,
        }
