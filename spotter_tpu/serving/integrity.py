"""Output-integrity plane (ISSUE 17): silent-data-corruption immunity.

Every robustness tier so far catches a replica that is DEAD, SLOW, or
OVERLOADED. None of them catches a replica that is healthy, fast, and
WRONG — a flipped weight bit after a spot-capacity warm restore, a
poisoned persistent-compile-cache entry, a chip emitting plausible
garbage. At the north-star scale (PAPER.md; Spotlight's preempt→restore
churn and DeepServe's scale-to-zero restores in PAPERS.md) silent data
corruption is a *when*, not an *if*, and every restore path is an ingress
for it. Three layers, one module:

- **GoldenProbe** — a deterministic per-model-family probe image with a
  pinned reference answer, injected through the REAL batcher path (bulk
  class so it never displaces slo traffic; `key=None` so it can never
  pollute the ResultCache or coalesce onto a live flight) and compared
  with the shared obs/compare.py tolerance comparator. Families without
  a pinned registry entry self-pin at the `verifying` readiness gate —
  after attestation has already vouched for the weights — and every later
  probe must match that answer.
- **WeightsAttestor** — wraps the engine's jit'd on-device bitpattern
  checksum (`engine.attest()`): every param shard is checksummed WHERE IT
  LIVES under dp×tp and compared against the trusted host checkpoint
  copy, so a single bad chip's shard is caught and named. Runs at every
  readiness verification and on a period.
- **IntegrityPlane** — composes the two behind the `verifying` lifecycle
  state (serving/lifecycle.py): probe + attestation must pass before
  READY on cold start, warm compile-cache restore, OOM downgrade, and
  degraded-dp rebuild. A failure — at the gate or from the periodic
  loop — exits with `INTEGRITY_EXIT_CODE` (86) after pinning a
  flight-recorder trace; the supervisor cold-restarts with the suspect
  compile-cache dir quarantined (a warm restart would faithfully restore
  the exact state that just failed).

The fourth layer lives at the edge: **QuorumSampler** (used by
serving/router.py) dual-dispatches a deterministically-sampled slice of
live traffic to a second ranked replica — reusing the pool's transport
but COMPARING instead of racing, the inverse of a hedge — and tracks a
per-replica disagreement EWMA. On a disagreement it asks a third replica
to arbitrate, so the deviant is charged and the honest witness is not
(without arbitration a corrupt replica would drag every peer it is
compared against toward the threshold with it). A replica over threshold
is HARD-quarantined via `pool.quarantine()`: out of the ring at zero
weight — unlike gray soft-ejection's 5% trickle, because wrong answers
must not keep ANY trickle — with a pinned flight-recorder trace
(`integrity-quarantine-*`). Its own periodic probe then takes it through
the exit-86 → cold-restart path.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Callable, Optional

from spotter_tpu.obs import compare
from spotter_tpu.serving.lifecycle import INTEGRITY_EXIT_CODE
from spotter_tpu.serving.overload import BULK
from spotter_tpu.testing import faults

logger = logging.getLogger(__name__)

INTEGRITY_ENV = "SPOTTER_TPU_INTEGRITY"
PROBE_INTERVAL_ENV = "SPOTTER_TPU_PROBE_INTERVAL_S"
ATTEST_INTERVAL_ENV = "SPOTTER_TPU_ATTEST_INTERVAL_S"
QUORUM_PCT_ENV = "SPOTTER_TPU_QUORUM_PCT"
QUORUM_EWMA_ENV = "SPOTTER_TPU_QUORUM_EWMA"
QUORUM_MIN_SAMPLES_ENV = "SPOTTER_TPU_QUORUM_MIN_SAMPLES"
QUORUM_ALPHA_ENV = "SPOTTER_TPU_QUORUM_ALPHA"

DEFAULT_PROBE_INTERVAL_S = 30.0
DEFAULT_ATTEST_INTERVAL_S = 60.0
DEFAULT_QUORUM_PCT = 0.0  # off unless the edge opts in
DEFAULT_QUORUM_EWMA = 0.6
DEFAULT_QUORUM_MIN_SAMPLES = 6
DEFAULT_QUORUM_ALPHA = 0.25

# Probe canvas: small enough to be negligible engine work, big enough to
# exercise the real preprocess/postprocess path.
PROBE_HW = 32


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def integrity_enabled() -> bool:
    """Master switch (default ON): readiness verification + periodic
    probe/attest. `SPOTTER_TPU_INTEGRITY=0` disables the whole plane."""
    return os.environ.get(INTEGRITY_ENV, "1").strip() not in ("", "0")


def probe_image(family: str, size: int = PROBE_HW):
    """Deterministic probe image for a model family: a fixed arithmetic
    pixel pattern seeded by the family name. Built directly as a PIL
    array — never through an encoder — so the SAME bytes reach the
    engine on every platform, every process, every restart (a lossy
    JPEG round-trip would vary with codec build and sink the pinned
    references)."""
    import hashlib

    import numpy as np
    from PIL import Image

    seed = hashlib.blake2b(family.encode(), digest_size=2).digest()
    s0, s1 = seed[0], seed[1]
    y = np.arange(size, dtype=np.uint32)[:, None, None]
    x = np.arange(size, dtype=np.uint32)[None, :, None]
    c = np.arange(3, dtype=np.uint32)[None, None, :]
    arr = ((x * (3 + s0) + y * (7 + s1) + c * 11 + s0) % 256).astype("uint8")
    return Image.fromarray(arr, "RGB")


# Pinned reference answers per model family. The stub family's entry is
# the contract the model-free drills and the chaos matrix assert against:
# it pins BOTH the probe-image rule above AND the stub's content-hash
# detection rule (testing/stub_engine.py) — if either drifts, the probe
# fails loudly instead of the integrity plane silently verifying nothing.
# Real model families self-pin at the verifying gate (references captured
# after attestation passes) because their answers depend on checkpoint
# bytes this repo does not pin.
PROBE_REFERENCES: dict[str, list[dict]] = {
    "stub": [{"label": "tv", "score": 0.89, "box": [6.0, 6.0, 24.0, 28.0]}],
}


class GoldenProbe:
    """Golden-probe canary: ask the REAL serving path the question we
    already know the answer to, through the real batcher (bulk class,
    cache/coalescing-bypassed via `key=None`)."""

    def __init__(
        self,
        family: str,
        reference: Optional[list[dict]] = None,
        score_tol: float = compare.DEFAULT_SCORE_TOL,
        box_tol: float = compare.DEFAULT_BOX_TOL,
    ) -> None:
        self.family = family
        self.image = probe_image(family)
        self.reference = (
            list(reference)
            if reference is not None
            else PROBE_REFERENCES.get(family)
        )
        self.score_tol = score_tol
        self.box_tol = box_tol
        self.probes_total = 0
        self.failures_total = 0
        self.last_error: Optional[str] = None

    async def run(self, batcher) -> Optional[str]:
        """One probe through the batcher; None on pass, else the reason.
        `key=None` is load-bearing twice over: keyed submits are the only
        cache-filling path (a probe must never pollute the ResultCache)
        and the only coalescing path (a probe must never attach to a live
        flight and vacuously compare an answer it didn't produce)."""
        self.probes_total += 1
        try:
            dets = await batcher.submit(self.image, key=None, cls=BULK)
        except Exception as exc:  # a probe that can't run is a failure
            self.failures_total += 1
            self.last_error = f"probe submit failed: {exc!r}"
            return self.last_error
        if faults.take_corrupt_compile_cache():
            # miscompiled-restore chaos seam: weights attest clean but the
            # program computes garbage — only this probe can catch it
            dets = faults.perturb_detections(dets)
        if self.reference is None:
            # self-pin (families without a registry entry): trusted because
            # the verifying gate runs attestation BEFORE the first probe
            self.reference = [dict(d) for d in dets if isinstance(d, dict)]
            logger.info(
                "golden probe self-pinned %d reference detections for %r",
                len(self.reference), self.family,
            )
            return None
        reason = compare.diff_detections(
            self.reference, dets,
            score_tol=self.score_tol, box_tol=self.box_tol,
        )
        if reason is not None:
            self.failures_total += 1
            self.last_error = reason
        return reason

    def snapshot(self) -> dict:
        return {
            "family": self.family,
            "pinned": self.reference is not None,
            "probes_total": self.probes_total,
            "failures_total": self.failures_total,
            "last_error": self.last_error,
        }


class WeightsAttestor:
    """On-device weights attestation driver around `engine.attest()`."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self.attests_total = 0
        self.failures_total = 0
        self.last_error: Optional[str] = None
        self.last_duration_s: float = 0.0

    def attest(self) -> Optional[str]:
        """One attestation; None on pass, else the reason (naming the
        mismatched shard locations)."""
        self.attests_total += 1
        t0 = time.monotonic()
        try:
            result = self.engine.attest()
        except Exception as exc:
            self.last_duration_s = time.monotonic() - t0
            self.failures_total += 1
            self.last_error = f"attestation errored: {exc!r}"
            return self.last_error
        self.last_duration_s = time.monotonic() - t0
        if result.get("ok"):
            return None
        self.failures_total += 1
        self.last_error = (
            f"weights checksum mismatch on {result.get('mismatched')} "
            f"(digest {getattr(self.engine, 'weights_digest', lambda: '?')()})"
        )
        return self.last_error

    def snapshot(self) -> dict:
        return {
            "attests_total": self.attests_total,
            "failures_total": self.failures_total,
            "last_duration_s": round(self.last_duration_s, 6),
            "last_error": self.last_error,
        }


class IntegrityPlane:
    """Probe + attestation behind the `verifying` readiness gate and a
    periodic re-verification loop. `exit_cb` (default `os._exit`) is the
    86 path; tests inject a recorder."""

    def __init__(
        self,
        engine,
        batcher,
        family: Optional[str] = None,
        probe_interval_s: Optional[float] = None,
        attest_interval_s: Optional[float] = None,
        exit_cb: Callable[[int], None] = os._exit,
    ) -> None:
        if family is None:
            built = getattr(engine, "built", None)
            family = getattr(built, "model_name", None) or "stub"
        self.engine = engine
        self.batcher = batcher
        self.probe = GoldenProbe(family)
        self.attestor = WeightsAttestor(engine)
        self.probe_interval_s = (
            _env_float(PROBE_INTERVAL_ENV, DEFAULT_PROBE_INTERVAL_S)
            if probe_interval_s is None
            else probe_interval_s
        )
        self.attest_interval_s = (
            _env_float(ATTEST_INTERVAL_ENV, DEFAULT_ATTEST_INTERVAL_S)
            if attest_interval_s is None
            else attest_interval_s
        )
        self.exit_cb = exit_cb
        self.verifications_total = 0
        self.verification_failures_total = 0
        self.last_verify_s: float = 0.0
        self.last_error: Optional[str] = None
        self._task: Optional[asyncio.Task] = None

    async def verify(self, source: str) -> bool:
        """The `verifying` gate: attestation first (the weights vouch for
        the probe's self-pin), then the golden probe through the real
        batcher. Runs on cold start, warm compile-cache restore, OOM
        downgrade, and degraded-dp rebuild (`source` says which)."""
        self.verifications_total += 1
        t0 = time.monotonic()
        reason = self.attestor.attest()
        if reason is None:
            reason = await self.probe.run(self.batcher)
        self.last_verify_s = time.monotonic() - t0
        if reason is None:
            logger.info(
                "integrity verification passed (%s): attest+probe in %.3fs",
                source, self.last_verify_s,
            )
            return True
        self.verification_failures_total += 1
        self.last_error = f"{source}: {reason}"
        logger.error("integrity verification FAILED (%s): %s", source, reason)
        self._pin_trace(source, reason)
        return False

    def verify_blocking(self, source: str) -> bool:
        """Sync wrapper for non-async callers (the batcher's degraded-
        rebuild thread). Attestation runs inline; the probe is submitted
        onto the batcher's own loop and awaited from this thread."""
        reason = self.attestor.attest()
        if reason is None:
            loop = getattr(self.batcher, "_loop", None)
            if loop is not None and loop.is_running():
                fut = asyncio.run_coroutine_threadsafe(
                    self.probe.run(self.batcher), loop
                )
                reason = fut.result(timeout=60.0)
            else:
                reason = asyncio.run(self.probe.run(self.batcher))
        self.verifications_total += 1
        if reason is None:
            return True
        self.verification_failures_total += 1
        self.last_error = f"{source}: {reason}"
        logger.error("integrity verification FAILED (%s): %s", source, reason)
        self._pin_trace(source, reason)
        return False

    def _pin_trace(self, source: str, reason: str) -> None:
        """Pin a flight-recorder trace so the post-exit dump says WHAT
        disagreed, not just that something did."""
        try:
            from spotter_tpu import obs

            trace = obs.begin_trace(request_id=f"integrity-{source}")
            trace.set_error(f"integrity: {reason}")
            obs.get_recorder().record(trace)
        except Exception:
            logger.debug("could not pin integrity trace", exc_info=True)

    def integrity_exit(self, reason: str) -> None:
        """The 86 path: dump the flight recorder, then exit. The
        supervisor cold-restarts us with the compile-cache dir
        quarantined."""
        logger.error(
            "integrity failure (%s); exiting %d for a cold restart with "
            "the compile cache quarantined", reason, INTEGRITY_EXIT_CODE,
        )
        from spotter_tpu.obs.recorder import dump_for_exit

        dump_for_exit(INTEGRITY_EXIT_CODE)
        self.exit_cb(INTEGRITY_EXIT_CODE)

    async def start(self) -> None:
        """Start the periodic re-verification loop (probe and attest on
        their own cadences; either interval <= 0 disables that check)."""
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    async def _run(self) -> None:
        now = time.monotonic()
        next_probe = (
            now + self.probe_interval_s if self.probe_interval_s > 0 else None
        )
        next_attest = (
            now + self.attest_interval_s
            if self.attest_interval_s > 0
            else None
        )
        while next_probe is not None or next_attest is not None:
            due = min(t for t in (next_probe, next_attest) if t is not None)
            await asyncio.sleep(max(due - time.monotonic(), 0.01))
            reason = None
            source = None
            if next_attest is not None and time.monotonic() >= next_attest:
                next_attest = time.monotonic() + self.attest_interval_s
                source = "periodic-attest"
                reason = await asyncio.get_running_loop().run_in_executor(
                    None, self.attestor.attest
                )
            if (
                reason is None
                and next_probe is not None
                and time.monotonic() >= next_probe
            ):
                next_probe = time.monotonic() + self.probe_interval_s
                source = "periodic-probe"
                reason = await self.probe.run(self.batcher)
            if reason is not None:
                self.verification_failures_total += 1
                self.last_error = f"{source}: {reason}"
                self._pin_trace(source or "periodic", reason)
                self.integrity_exit(self.last_error)
                return

    async def aclose(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    def snapshot(self) -> dict:
        return {
            "verifications_total": self.verifications_total,
            "verification_failures_total": self.verification_failures_total,
            "last_verify_s": round(self.last_verify_s, 6),
            "last_error": self.last_error,
            "probe": self.probe.snapshot(),
            "attest": self.attestor.snapshot(),
        }


class QuorumSampler:
    """Edge quorum sampling: dual-dispatch a sampled slice of live
    traffic to a second ranked replica and compare (the inverse of a
    hedge — same transport, but disagreement is the signal, not
    latency). Disagreements are arbitrated by a third replica when one
    exists, so only the DEVIANT's EWMA is charged; a replica whose EWMA
    crosses the threshold is hard-quarantined out of the ring."""

    def __init__(
        self,
        pool,
        pct: Optional[float] = None,
        ewma_threshold: Optional[float] = None,
        min_samples: Optional[int] = None,
        alpha: Optional[float] = None,
        score_tol: float = compare.DEFAULT_SCORE_TOL,
        box_tol: float = compare.DEFAULT_BOX_TOL,
    ) -> None:
        self.pool = pool
        if pct is None:
            pct = _env_float(QUORUM_PCT_ENV, DEFAULT_QUORUM_PCT)
        self.pct = min(max(float(pct), 0.0), 100.0)
        self.ewma_threshold = (
            _env_float(QUORUM_EWMA_ENV, DEFAULT_QUORUM_EWMA)
            if ewma_threshold is None
            else ewma_threshold
        )
        self.min_samples = (
            _env_int(QUORUM_MIN_SAMPLES_ENV, DEFAULT_QUORUM_MIN_SAMPLES)
            if min_samples is None
            else min_samples
        )
        self.alpha = (
            _env_float(QUORUM_ALPHA_ENV, DEFAULT_QUORUM_ALPHA)
            if alpha is None
            else alpha
        )
        self.score_tol = score_tol
        self.box_tol = box_tol
        self._credit = 0.0
        self._ewma: dict[str, float] = {}
        self._samples: dict[str, int] = {}
        self.samples_total = 0
        self.compared_total = 0
        self.disagreements_total = 0
        self.arbitrations_total = 0
        self.errors_total = 0
        self.quarantines_total = 0

    def take(self) -> bool:
        """Deterministic Bresenham sampling, like the shadow lane and the
        flaky fault — drills assert exact shares, so no RNG."""
        if self.pct <= 0:
            return False
        self._credit += self.pct
        if self._credit >= 100.0:
            self._credit -= 100.0
            return True
        return False

    async def _ask(self, client, url: str, payload: dict) -> Optional[dict]:
        try:
            resp = await client.post(f"{url}/detect", json=payload)
            if resp.status_code != 200:
                return None
            return resp.json()
        except Exception:
            return None

    def _charge(self, url: str, disagreed: bool) -> None:
        prev = self._ewma.get(url, 0.0)
        self._ewma[url] = prev * (1.0 - self.alpha) + (
            self.alpha if disagreed else 0.0
        )
        self._samples[url] = self._samples.get(url, 0) + 1

    def _maybe_quarantine(self, url: str) -> None:
        if self._samples.get(url, 0) < self.min_samples:
            return
        if self._ewma.get(url, 0.0) < self.ewma_threshold:
            return
        reason = (
            f"quorum disagreement ewma {self._ewma[url]:.2f} >= "
            f"{self.ewma_threshold} over {self._samples[url]} samples"
        )
        if not self.pool.quarantine(url, reason=reason):
            return
        self.quarantines_total += 1
        try:
            from spotter_tpu import obs

            trace = obs.begin_trace(request_id=f"integrity-quarantine-{url}")
            trace.set_error(f"hard quarantine: {reason}")
            obs.get_recorder().record(trace)
        except Exception:
            logger.debug("could not pin quarantine trace", exc_info=True)

    async def run_one(
        self, client, payload: dict, primary_body, primary_url: str
    ) -> None:
        """One sampled comparison: ask a second ranked replica the same
        question, compare with the tolerance comparator, arbitrate
        disagreements with a third opinion. Everything here is contained:
        nothing on this lane can surface to a client."""
        import json as _json

        self.samples_total += 1
        witness_url = self.pool.pick_other(exclude=(primary_url,))
        if witness_url is None:
            return
        witness = await self._ask(client, witness_url, payload)
        if witness is None:
            self.errors_total += 1
            return
        try:
            primary = (
                _json.loads(primary_body)
                if isinstance(primary_body, (bytes, bytearray, str))
                else primary_body
            )
            primary_images = primary.get("images")
        except Exception:
            return  # uncomparable primary (frame body): skipped, not charged
        self.compared_total += 1
        agree = compare.images_equivalent(
            primary_images, witness.get("images"),
            score_tol=self.score_tol, box_tol=self.box_tol,
        )
        if agree:
            self._charge(primary_url, False)
            self._charge(witness_url, False)
            return
        self.disagreements_total += 1
        arbiter_url = self.pool.pick_other(
            exclude=(primary_url, witness_url)
        )
        arbiter = (
            await self._ask(client, arbiter_url, payload)
            if arbiter_url is not None
            else None
        )
        if arbiter is not None:
            self.arbitrations_total += 1
            arb_images = arbiter.get("images")
            primary_ok = compare.images_equivalent(
                primary_images, arb_images,
                score_tol=self.score_tol, box_tol=self.box_tol,
            )
            witness_ok = compare.images_equivalent(
                witness.get("images"), arb_images,
                score_tol=self.score_tol, box_tol=self.box_tol,
            )
            if primary_ok and not witness_ok:
                self._charge(primary_url, False)
                self._charge(witness_url, True)
            elif witness_ok and not primary_ok:
                self._charge(primary_url, True)
                self._charge(witness_url, False)
            else:
                # arbiter agreed with both (tolerance chains) or neither:
                # no majority — charge both, the EWMA sorts out repeats
                self._charge(primary_url, True)
                self._charge(witness_url, True)
        else:
            # no third replica: a 2-fleet can't attribute — charge both
            self._charge(primary_url, True)
            self._charge(witness_url, True)
        self._maybe_quarantine(primary_url)
        self._maybe_quarantine(witness_url)

    def snapshot(self) -> dict:
        return {
            "pct": self.pct,
            "samples_total": self.samples_total,
            "compared_total": self.compared_total,
            "disagreements_total": self.disagreements_total,
            "arbitrations_total": self.arbitrations_total,
            "errors_total": self.errors_total,
            "quarantines_total": self.quarantines_total,
            "ewma": {
                url: round(v, 4) for url, v in sorted(self._ewma.items())
            },
        }
