"""Durable control-plane state: CRC-framed journal + snapshot, leader
lease, and the endpoints manifest (ISSUE 16 tentpole, parts a and d).

PRs 1-15 made the data plane nearly unkillable, but the controllers that
drive it (serving/fleet.py, serving/rollout.py) held every piece of fleet
state in process memory: kill the controller mid-rollout and the canary is
stranded at a pinned weight forever. This module is the durability layer
under serving/reconcile.py: a desired-state spec that survives controller
death, a lease that makes exactly one of N controllers act, and a manifest
that lets a restarted controller *find* the replicas its predecessor
spawned instead of double-spawning or orphan-killing them.

Storage discipline — the SPTF frame-v2 rules (serving/wire.py), applied
to files:

- Every record on disk is framed `SPTS | ver | flags | payload_len |
  payload_crc | header_crc` + canonical-JSON payload. The header checksum
  covers the header fields, the payload checksum covers the bytes — so a
  flip anywhere (header OR payload) fails a CRC, and a truncation anywhere
  fails a length check. Corruption is *detected* (typed
  `StateCorruptError`), never silently replayed: Spotlight's argument for
  reconciling against observed capacity only works if the controller knows
  when its recorded intent is untrustworthy.
- The journal is append-only: one framed record per `append()`, flushed
  and fsync'd before the call returns. Records carry a strictly
  consecutive `seq`; a gap or regression is corruption (a lost or
  reordered write), not a quirk.
- Compaction writes the folded state as a single snapshot record to a
  temp file, fsyncs, `os.replace()`s over the snapshot, then truncates the
  journal the same way — the atomic-rename discipline every other
  persistent artifact in this repo uses (supervisor pidfile, result cache
  spill). A crash between the two replaces leaves snapshot(new) +
  journal(old tail with seqs <= snapshot seq): load() skips already-folded
  records by seq, so the overlap is harmless, not corrupt.

Why kill -9 still resumes: SIGKILL can't tear a completed write() — the
page cache outlives the process — so a controller killed mid-rollout
leaves an intact journal and its successor resumes the wave. Only real
damage (power loss mid-write, bit rot, an operator's stray dd) produces a
bad CRC, and that is exactly when replaying intent would be dangerous —
so the caller counts it and rebuilds from observation instead.

Leader lease (part d): a JSON lease file guarded by flock on a sidecar
lock. Acquisition increments a monotonic fencing epoch; every actuation
the reconciler performs is stamped with the epoch it was planned under and
re-checked (`LeaderLease.check()`) at the actuation boundary. A deposed
controller — paused past its TTL, then resumed — fails the epoch check
with `StaleLeaderError` before it can touch the fleet.
"""

import errno
import fcntl
import json
import os
import struct
import time
import zlib

# ---- framing (SPTS = SPoTter State) ----

STATE_MAGIC = b"SPTS"
STATE_VERSION = 1

# magic(4s) version(B) flags(B) payload_len(I) payload_crc(I) header_crc(I)
_HEADER = struct.Struct(">4sBBIII")
# header_crc covers everything before it
_HEADER_CRC_SPAN = _HEADER.size - 4

FLAG_SNAPSHOT = 0x01

JOURNAL_NAME = "journal.sptj"
SNAPSHOT_NAME = "snapshot.sptj"
LEASE_NAME = "leader.lease"

# Journals are small (a few KiB of intent); anything past this is damage,
# not state — a corrupted length field must not trigger a giant read.
MAX_PAYLOAD = 8 * 1024 * 1024


class StateError(Exception):
    """Base for control-plane state errors."""


class StateCorruptError(StateError):
    """The on-disk journal/snapshot failed a CRC, length, or sequence
    check. The caller's contract: count it, rebuild desired state from
    observation, never replay the damaged intent."""


class StaleLeaderError(StateError):
    """This controller's fencing epoch has been superseded — another
    controller acquired the lease. Every actuation must refuse."""


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def encode_record(payload: dict, *, snapshot: bool = False) -> bytes:
    """One framed state record: header (self-checksummed) + canonical
    JSON. Canonical (sorted keys, tight separators) so identical state
    always produces identical bytes — byte-diffable journals."""
    body = json.dumps(
        payload, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")
    if len(body) > MAX_PAYLOAD:
        raise StateError(f"state record too large ({len(body)} bytes)")
    flags = FLAG_SNAPSHOT if snapshot else 0
    head = _HEADER.pack(
        STATE_MAGIC, STATE_VERSION, flags, len(body), _crc(body), 0
    )
    head = head[:_HEADER_CRC_SPAN] + struct.pack(
        ">I", _crc(head[:_HEADER_CRC_SPAN])
    )
    return head + body


def decode_records(blob: bytes, where: str) -> list[tuple[int, dict]]:
    """All `(flags, payload)` records in a file image, validating every
    byte; raises StateCorruptError on any truncation, flip, or garbage."""
    records: list[tuple[int, dict]] = []
    off = 0
    n = len(blob)
    while off < n:
        if n - off < _HEADER.size:
            raise StateCorruptError(
                f"{where}: truncated header at offset {off} "
                f"({n - off} of {_HEADER.size} bytes)"
            )
        head = blob[off:off + _HEADER.size]
        magic, version, flags, plen, pcrc, hcrc = _HEADER.unpack(head)
        if _crc(head[:_HEADER_CRC_SPAN]) != hcrc:
            raise StateCorruptError(
                f"{where}: header checksum mismatch at offset {off}"
            )
        if magic != STATE_MAGIC:
            raise StateCorruptError(
                f"{where}: bad magic {magic!r} at offset {off}"
            )
        if version != STATE_VERSION:
            raise StateCorruptError(
                f"{where}: unsupported state version {version} at "
                f"offset {off}"
            )
        if plen > MAX_PAYLOAD:
            raise StateCorruptError(
                f"{where}: payload length {plen} exceeds cap at "
                f"offset {off}"
            )
        start = off + _HEADER.size
        if n - start < plen:
            raise StateCorruptError(
                f"{where}: truncated payload at offset {start} "
                f"({n - start} of {plen} bytes)"
            )
        body = blob[start:start + plen]
        if _crc(body) != pcrc:
            raise StateCorruptError(
                f"{where}: payload checksum mismatch at offset {start}"
            )
        try:
            payload = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            # A payload that passes CRC but fails JSON means the *writer*
            # was broken, which is just as untrustworthy.
            raise StateCorruptError(
                f"{where}: undecodable payload at offset {start}: {exc}"
            ) from None
        if not isinstance(payload, dict) or "seq" not in payload:
            raise StateCorruptError(
                f"{where}: record at offset {start} is not a "
                "sequence-stamped object"
            )
        records.append((flags, payload))
        off = start + plen
    return records


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + os.replace — readers see old bytes or new bytes,
    never a prefix."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---- desired-state store ----


def _fold(state: dict, op: dict) -> None:
    """Apply one journal op to the folded desired state, in place."""
    kind = op.get("op")
    if kind == "set_pool":
        pool = dict(op.get("pool") or {})
        name = op.get("name")
        if not isinstance(name, str) or not name:
            raise StateCorruptError("set_pool record without a pool name")
        state["pools"][name] = pool
    elif kind == "remove_pool":
        state["pools"].pop(op.get("name"), None)
    elif kind == "rollout":
        state["rollout"] = op.get("rollout")
    else:
        raise StateCorruptError(f"unknown journal op {kind!r}")


def empty_state() -> dict:
    return {"pools": {}, "rollout": None}


class StateStore:
    """Durable desired-state spec: `{"pools": {name: {"size", "class",
    "version", "canary_weight", ...}}, "rollout": {...}|None}`.

    `load()` replays snapshot + journal strictly (any damage raises
    StateCorruptError — the caller decides to rebuild). `append()` is the
    only mutation path and fsyncs before returning, so an op that returned
    survives kill -9. `compact()` folds the journal into the snapshot.
    """

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.state = empty_state()
        self.seq = 0  # last applied sequence number
        self.journal_records = 0
        self._journal_path = os.path.join(directory, JOURNAL_NAME)
        self._snapshot_path = os.path.join(directory, SNAPSHOT_NAME)

    # -- loading --

    @classmethod
    def load(cls, directory: str) -> "StateStore":
        """Replay snapshot then journal. Raises StateCorruptError on ANY
        damage; returns a store with `state`/`seq` reflecting the folded
        intent otherwise (fresh empty state when neither file exists)."""
        store = cls(directory)
        snap_blob = _read_optional(store._snapshot_path)
        if snap_blob:
            recs = decode_records(snap_blob, SNAPSHOT_NAME)
            if len(recs) != 1 or not (recs[0][0] & FLAG_SNAPSHOT):
                raise StateCorruptError(
                    f"{SNAPSHOT_NAME}: expected exactly one snapshot "
                    f"record, found {len(recs)}"
                )
            payload = recs[0][1]
            snap_state = payload.get("state")
            if not isinstance(snap_state, dict) or not isinstance(
                snap_state.get("pools"), dict
            ):
                raise StateCorruptError(
                    f"{SNAPSHOT_NAME}: snapshot payload is not a state"
                )
            store.state = {
                "pools": dict(snap_state["pools"]),
                "rollout": snap_state.get("rollout"),
            }
            store.seq = int(payload["seq"])
        journal_blob = _read_optional(store._journal_path)
        if journal_blob:
            for flags, op in decode_records(journal_blob, JOURNAL_NAME):
                if flags & FLAG_SNAPSHOT:
                    raise StateCorruptError(
                        f"{JOURNAL_NAME}: snapshot record inside journal"
                    )
                seq = int(op["seq"])
                if seq <= store.seq:
                    # Tail already folded into the snapshot (crash between
                    # compaction's two renames) — skip, don't re-apply.
                    continue
                if seq != store.seq + 1:
                    raise StateCorruptError(
                        f"{JOURNAL_NAME}: sequence gap ({store.seq} -> "
                        f"{seq}) — a journal write was lost"
                    )
                _fold(store.state, op)
                store.seq = seq
                store.journal_records += 1
        return store

    @classmethod
    def fresh(cls, directory: str) -> "StateStore":
        """Discard any on-disk state and start empty — the
        rebuild-from-observation path after StateCorruptError. The damaged
        files are kept aside (`.corrupt`) for the post-mortem."""
        store = cls(directory)
        for name in (JOURNAL_NAME, SNAPSHOT_NAME):
            path = os.path.join(directory, name)
            if os.path.exists(path):
                os.replace(path, path + ".corrupt")
        return store

    # -- mutation --

    def append(self, op: str, **fields) -> int:
        """Journal one op durably (fsync before return) and fold it into
        the in-memory state. Returns the record's sequence number."""
        seq = self.seq + 1
        record = {"op": op, "seq": seq, **fields}
        _fold(self.state, record)  # raises before any disk write if bad
        frame = encode_record(record)
        with open(self._journal_path, "ab") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        self.seq = seq
        self.journal_records += 1
        return seq

    def set_pool(self, name: str, **spec) -> int:
        """Desired pool spec: size, class ("spot"/"on_demand"), version,
        canary_weight — merged over the existing spec."""
        merged = dict(self.state["pools"].get(name) or {})
        merged.update(spec)
        return self.append("set_pool", name=name, pool=merged)

    def remove_pool(self, name: str) -> int:
        return self.append("remove_pool", name=name)

    def set_rollout(self, rollout: dict | None) -> int:
        """Record the in-flight rollout (or None when it finishes) — the
        wave/state/deadline block RolloutController journals so a crash
        mid-wave resumes (or expires into rollback)."""
        return self.append("rollout", rollout=rollout)

    def compact(self) -> None:
        """Fold journal into snapshot: atomic snapshot rewrite, then
        atomic journal truncation. Crash between the two leaves a
        harmless already-folded journal tail (load() skips by seq)."""
        payload = {"seq": self.seq, "state": self.state}
        _atomic_write(
            self._snapshot_path, encode_record(payload, snapshot=True)
        )
        _atomic_write(self._journal_path, b"")
        self.journal_records = 0


def _read_optional(path: str) -> bytes:
    try:
        with open(path, "rb") as f:
            return f.read()
    except FileNotFoundError:
        return b""


# ---- leader lease ----


class LeaderLease:
    """Active-passive leadership with a monotonic fencing epoch.

    The lease is a JSON file `{"epoch": N, "owner": ..., "expires": T}`
    rewritten atomically under flock (the flock serializes acquire /
    heartbeat races between live processes; the epoch fences *dead or
    paused* ones, which flock cannot). Wall-clock expiry is deliberate:
    the TTL is seconds and the competing controllers share a host (or a
    coherent clock), matching the single-host drill topology.

    Usage: `try_acquire()` each reconcile tick — True means this process
    leads for TTL from now and `epoch` is its fencing token. `check()` at
    every actuation boundary re-reads the file and raises
    StaleLeaderError when a higher epoch exists — the deposed-controller
    path the chaos matrix drills.
    """

    def __init__(self, path: str, owner: str, ttl_s: float = 3.0):
        self.path = path
        self.owner = owner
        self.ttl_s = ttl_s
        self.epoch = 0  # our fencing epoch; 0 = never led
        self.leading = False

    def _read(self) -> dict:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if not isinstance(data, dict):
                return {}
            return data
        except (OSError, json.JSONDecodeError):
            # Unreadable lease = no lease; acquisition rewrites it. The
            # lease is coordination, not state — safe to rebuild, unlike
            # the journal.
            return {}

    def _locked(self):
        lock_path = self.path + ".lock"
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
        except OSError:
            os.close(fd)
            raise
        return fd

    def try_acquire(self, now: float | None = None) -> bool:
        """Acquire or renew leadership. Returns True when this process
        holds the lease (epoch set), False when another live leader does.
        Renewal keeps the epoch; taking over from an expired or absent
        leader increments it (the fencing point)."""
        now = time.time() if now is None else now
        fd = self._locked()
        try:
            cur = self._read()
            cur_epoch = int(cur.get("epoch") or 0)
            expired = float(cur.get("expires") or 0.0) <= now
            ours = (
                cur.get("owner") == self.owner and cur_epoch == self.epoch
            )
            if ours and not expired:
                self._write(cur_epoch, now)  # renew, same epoch
                self.leading = True
                return True
            if not expired:
                self.leading = False
                return False
            # Absent/expired: take over with a HIGHER epoch, even when the
            # stale lease was our own — our pause may have let another
            # controller act, so our old epoch must die with the pause.
            self.epoch = cur_epoch + 1
            self._write(self.epoch, now)
            self.leading = True
            return True
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _write(self, epoch: int, now: float) -> None:
        self.epoch = epoch
        payload = json.dumps(
            {
                "epoch": epoch,
                "owner": self.owner,
                "expires": now + self.ttl_s,
            },
            sort_keys=True,
        ).encode("utf-8")
        _atomic_write(self.path, payload)

    def check(self) -> int:
        """Fencing check at the actuation boundary: re-read the lease and
        raise StaleLeaderError when our epoch has been superseded (or we
        never led). Returns the current epoch for stamping."""
        if not self.leading or self.epoch <= 0:
            raise StaleLeaderError(
                f"{self.owner}: not the leader (epoch {self.epoch})"
            )
        cur = self._read()
        cur_epoch = int(cur.get("epoch") or 0)
        if cur_epoch != self.epoch or cur.get("owner") != self.owner:
            self.leading = False
            raise StaleLeaderError(
                f"{self.owner}: fencing epoch {self.epoch} superseded "
                f"by {cur_epoch} (owner {cur.get('owner')!r})"
            )
        return self.epoch

    def release(self) -> None:
        """Voluntary step-down (clean shutdown): expire our own lease so
        the standby takes over immediately instead of waiting the TTL."""
        if not self.leading:
            return
        fd = self._locked()
        try:
            cur = self._read()
            if (
                cur.get("owner") == self.owner
                and int(cur.get("epoch") or 0) == self.epoch
            ):
                cur["expires"] = 0.0
                _atomic_write(
                    self.path,
                    json.dumps(cur, sort_keys=True).encode("utf-8"),
                )
        finally:
            self.leading = False
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)


# ---- endpoints manifest ----


class EndpointsManifest:
    """Where a restarted controller finds its predecessor's replicas.

    A JSON file `{"entries": {url: {pool, pidfile, preempt_file,
    supervisor_pid, version}}}` updated read-modify-write under flock +
    atomic rename. Supervisors register themselves at spawn and deregister
    on PERMANENT exit (clean stop, bringup-failed, crash-loop) but stay
    registered across preemption restarts — so the manifest stays accurate
    while the controller is dead, which is the whole point: orphan
    adoption reads it, probes each entry's /healthz identity block, and
    adopts live members instead of double-spawning.

    Entries are advisory, never trusted blindly: adoption verifies
    liveness (supervisor pid + /healthz) before adopting and prunes
    entries whose supervisor is gone. An unreadable manifest is treated
    as empty (it is a cache of observations, rebuilt by the next spawn —
    unlike the journal, there is no intent to mis-replay).
    """

    def __init__(self, path: str):
        self.path = path

    def _mutate(self, fn) -> None:
        lock_path = self.path + ".lock"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            entries = self.entries()
            fn(entries)
            payload = json.dumps(
                {"entries": entries}, sort_keys=True
            ).encode("utf-8")
            _atomic_write(self.path, payload)
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def entries(self) -> dict:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return {}
        got = data.get("entries") if isinstance(data, dict) else None
        return dict(got) if isinstance(got, dict) else {}

    def add(self, url: str, **entry) -> None:
        """Upsert: a supervisor restarting its child re-registers with a
        fresh supervisor_pid; the url stays the stable key."""
        def _add(entries):
            merged = dict(entries.get(url) or {})
            merged.update(entry)
            entries[url] = merged
        self._mutate(_add)

    def remove(self, url: str) -> None:
        def _remove(entries):
            entries.pop(url, None)
        self._mutate(_remove)


def supervisor_alive(pid: int | None) -> bool:
    """Is the supervising process still running? (signal-0 probe; EPERM
    means alive-but-not-ours, which still counts as alive). A zombie —
    exited but not yet reaped by ITS parent, which may be a test harness
    that only reaps at teardown — still answers signal 0, but it serves
    nothing and will never again: it counts as dead, so adoption skips it
    and `ManifestHandle.shutdown` doesn't wait a full escalation timeout
    for a process that already exited."""
    if not pid or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError as exc:
        return exc.errno == errno.EPERM
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read()
        # field 3, after the parenthesised comm (which may contain spaces)
        state = stat.rsplit(b")", 1)[-1].split()[0]
        return state != b"Z"
    except (OSError, IndexError):
        return True  # no /proc (non-Linux): keep the signal-0 answer
