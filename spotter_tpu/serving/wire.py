"""Binary wire format + data-plane headers for the /detect hot path.

JSON+base64 is the reference wire contract and stays the default — byte
identical when nothing is negotiated. But base64 is a ~33% tax on every
annotated JPEG this service returns (detector.py pays it on every
success), and at fleet scale that tax is paid twice per request (replica →
router → client). A client or edge that sends

    Accept: application/x-spotter-frame

gets the same response as a length-prefixed binary frame instead: the
JSON body with every `labeled_image_base64` string swapped for an
`image_segment` index into raw JPEG segments appended after the header,
and the header itself deflate-compressed (the detection dicts and
description are highly compressible JSON; raw JPEG is not, so ONLY the
header is compressed).

Frame layout (all integers big-endian). Version 2 (ISSUE 14) adds wire
integrity: a checksum of the (possibly deflated) header bytes and one per
segment, so a flipped bit anywhere in the payload is a typed
`FrameCorruptError` — counted, replayed against the next ranked holder at
the edge, never a silent garbage decode or a client-visible 500. Version 1
frames (no checksums) still parse; `SPOTTER_TPU_WIRE_CRC=0` makes the
encoder emit v1 for interop with pre-checksum peers.

    offset  size  field
    0       4     magic "SPTF"
    4       1     version (2; decoder also accepts 1)
    5       1     flags (bit 0: header is deflated; bit 1: preset dict)
    6       2     reserved (0)
    8       4     segment count N
    12      4     header length H
    16      4     header checksum (v2 only; CRC over the H header bytes)
    20      H     header JSON (per flags, possibly deflated)
    20+H    ...   N segments, each: u32 length + u32 checksum + raw bytes
                  (v1 segments carry no checksum)

The checksum is `zlib.crc32` (CRC-32/ISO-HDLC). CRC32C (Castagnoli) would
be the textbook pick for storage/wire integrity, but CPython ships no
C-speed Castagnoli and a pure-Python table walk costs ~milliseconds per
JPEG segment — a wire-integrity layer must be effectively free, and
zlib's C CRC-32 detects the same burst/bit-flip corruption class at
GB/s. The polynomial is part of the wire contract: changing it is a
version bump.

The header JSON is exactly the `DetectionResponse.model_dump(
exclude_none=True)` dict, except each success image carries
`"image_segment": <idx>` in place of `"labeled_image_base64"`. Decoding
restores the base64 field, so `decode_frame(encode_frame(x)) == x` and a
frame can be re-serialized to the byte-identical default JSON with
`to_json_bytes` (the router does this when it speaks frames to replicas
but JSON to a legacy client).

Also here: the additive data-plane headers —

- `X-Cache: hit|miss|negative|coalesced` (ISSUE 11 satellite): how the
  caching tier treated this request, so tests and the affinity bench can
  observe hit locality without scraping /metrics. Multi-image requests
  summarize: any negative verdict -> "negative", else all images cached ->
  "hit", else any coalesced and the rest cached -> "coalesced", else
  "miss".
- `X-Spotter-Negative`: the replica's deterministic-failure verdicts
  (non-retryable 4xx by URL, poison by content hash — surfaced against the
  URL that carried the bytes), RFC-8941-ish
  `u=<quoted-url>;k=<kind>;t=<ttl>;e=<quoted-error>` items, comma-joined.
  The router folds them into its `EdgeNegativeCache` so a known-bad URL is
  answered at the edge without burning a replica round trip. Only the
  PR 5 taxonomy's deterministic failures ride here — 5xx/timeouts/sheds
  are retryable and never become verdicts.

Stdlib-only and jax-free: the router process imports this.
"""

import base64
import json
import os
import struct
import time
import zlib
from urllib.parse import quote, unquote

from spotter_tpu.caching.keys import normalize_url

FRAME_CONTENT_TYPE = "application/x-spotter-frame"
FRAME_MAGIC = b"SPTF"
FRAME_VERSION = 2  # v2: header + per-segment checksums (ISSUE 14)
FRAME_VERSION_V1 = 1  # still parsed; emitted when SPOTTER_TPU_WIRE_CRC=0
_FLAG_DEFLATED = 0x01  # header is zlib-compressed
_FLAG_DICT = 0x02  # header is RAW deflate against the preset dictionary
_HEAD = struct.Struct(">4sBBHII")  # magic, version, flags, reserved, nseg, hlen
_U32 = struct.Struct(">I")

WIRE_CRC_ENV = "SPOTTER_TPU_WIRE_CRC"


def _crc(data: bytes) -> int:
    """The frame checksum (see the module docstring for why CRC-32 over
    CRC32C here): zlib's C implementation, masked to u32."""
    return zlib.crc32(data) & 0xFFFFFFFF


def crc_enabled() -> bool:
    """Frame checksums are the default; SPOTTER_TPU_WIRE_CRC=0 emits
    checksum-less v1 frames (decoding always accepts both versions)."""
    return os.environ.get(WIRE_CRC_ENV, "1").strip() not in ("", "0")

# Preset deflate dictionary (the SPDY header-dict trick): the response
# vocabulary is fixed protocol-side, so seeding the compressor with it
# roughly halves the compressed header for small responses — which is what
# pushes the total frame saving past the bare ~25% base64 tax even for
# single-image responses. Changing this dictionary is a WIRE CHANGE: bump
# FRAME_VERSION with it.
FRAME_ZDICT = json.dumps(
    {
        "amenities_description": (
            "The property contains: No relevant amenities detected."
        ),
        "images": [
            {
                "url": "https://http://",
                "detections": [{"label": "", "box": []}],
                "image_segment": 0,
                "error": (
                    "Fetch Error: HTTP Error: Processing Error: "
                    "Deadline exceeded: Overloaded: "
                ),
            }
        ],
        "degraded": ["stale", "bucket_cap", "threshold"],
    },
    separators=(",", ":"),
).encode("utf-8")

X_CACHE_HEADER = "X-Cache"
NEGATIVE_HEADER = "X-Spotter-Negative"
# Which replica produced this response (ISSUE 14 satellite): the ISSUE 12
# identity stamp (`replica_id` from /metrics) echoed as a header at the
# replica AND forwarded by the edge, so any slow or corrupt response joins
# /debug/fleet rows and stitched traces by replica id without scraping.
# Fan-in responses carry every contributing replica, comma-joined.
REPLICA_HEADER = "X-Spotter-Replica"
# Which deploy version produced this response (ISSUE 15): the identity
# stamp's build version echoed at the replica and forwarded by the edge
# (fan-in responses carry every distinct contributing version,
# comma-joined). The pool learns per-replica versions from this header —
# the substrate for mixed-version replay/hedge pinning and the rollout
# verdict's canary-vs-baseline split.
VERSION_HEADER = "X-Spotter-Version"

# cap the per-verdict error text: headers are not a payload channel
_MAX_ERROR_CHARS = 200

EDGE_NEGATIVE_TTL_ENV = "SPOTTER_TPU_EDGE_NEGATIVE_TTL_S"
DEFAULT_EDGE_NEGATIVE_TTL_S = 5.0
MAX_EDGE_NEGATIVE_ENTRIES = 4096


class FrameError(ValueError):
    """Malformed frame (bad magic/version, truncated segment, bad index)."""


class FrameCorruptError(FrameError):
    """A frame whose bytes fail their checksum (header or segment): the
    payload was damaged in transit or at rest. Distinct from FrameError so
    the edge can count corruption separately and treat it as a transport
    failure of the replica that produced it (replay on the next ranked
    holder) rather than a protocol bug."""


def wants_frame(accept: str | None) -> bool:
    """Content negotiation: the frame is opt-in per request via Accept."""
    return bool(accept) and FRAME_CONTENT_TYPE in accept.lower()


def to_json_bytes(body: dict) -> bytes:
    """The default wire encoding — byte-identical to what
    `aiohttp.web.json_response(body)` puts on the socket (plain
    `json.dumps`), so a frame-decoded response re-encodes to exactly the
    bytes a non-negotiating client would have received."""
    return json.dumps(body).encode("utf-8")


# -- frame encode/decode -----------------------------------------------------


def strip_segments(body: dict) -> tuple[dict, list[bytes]]:
    """(header, segments): every success image's base64 payload decoded out
    into a raw segment, the image dict rewritten with `image_segment`. The
    input dict is not mutated."""
    segments: list[bytes] = []
    header = dict(body)
    images = []
    for img in body.get("images", ()):
        b64 = img.get("labeled_image_base64") if isinstance(img, dict) else None
        if b64 is None:
            images.append(img)
            continue
        out = {k: v for k, v in img.items() if k != "labeled_image_base64"}
        out["image_segment"] = len(segments)
        segments.append(base64.b64decode(b64))
        images.append(out)
    header["images"] = images
    return header, segments


def restore_segments(header: dict, segments: list[bytes]) -> dict:
    """Inverse of `strip_segments`: base64 back in, `image_segment` gone."""
    body = dict(header)
    images = []
    for img in header.get("images", ()):
        idx = img.get("image_segment") if isinstance(img, dict) else None
        if idx is None:
            images.append(img)
            continue
        if not isinstance(idx, int) or not 0 <= idx < len(segments):
            raise FrameError(f"image_segment {idx!r} out of range")
        out = {k: v for k, v in img.items() if k != "image_segment"}
        out["labeled_image_base64"] = base64.b64encode(
            segments[idx]
        ).decode("utf-8")
        images.append(out)
    body["images"] = images
    return body


def build_frame(
    header: dict, segments: list[bytes], crc: bool | None = None
) -> bytes:
    """Serialize an already-split (header, segments) pair. The header is
    deflated when that actually shrinks it (it always does for real
    responses; tiny test fixtures may not). `crc` (default
    `SPOTTER_TPU_WIRE_CRC`) selects the v2 checksummed layout; False emits
    a checksum-less v1 frame for pre-checksum peers."""
    if crc is None:
        crc = crc_enabled()
    raw = json.dumps(header, separators=(",", ":")).encode("utf-8")
    co = zlib.compressobj(9, zlib.DEFLATED, -15, zdict=FRAME_ZDICT)
    deflated = co.compress(raw) + co.flush()
    flags = 0
    if len(deflated) < len(raw):
        raw, flags = deflated, _FLAG_DEFLATED | _FLAG_DICT
    version = FRAME_VERSION if crc else FRAME_VERSION_V1
    head = _HEAD.pack(FRAME_MAGIC, version, flags, 0, len(segments), len(raw))
    parts = [head]
    if crc:
        # the header checksum covers the fixed preamble too, so a flipped
        # bit in flags/reserved/counts is caught even where the structure
        # would still parse
        parts.append(_U32.pack(_crc(head + raw)))
    parts.append(raw)
    for seg in segments:
        parts.append(_U32.pack(len(seg)))
        if crc:
            parts.append(_U32.pack(_crc(seg)))
        parts.append(seg)
    return b"".join(parts)


def split_frame(data: bytes) -> tuple[dict, list[bytes]]:
    """Parse a frame into (header, segments) without touching base64 — the
    router's merge path re-frames segments as-is. Raises FrameError on any
    structural damage (truncation, bad magic/version/JSON) and
    FrameCorruptError when a v2 checksum does not match its bytes — never
    struct.error/KeyError/UnicodeDecodeError, and never a garbage decode
    (the fuzz contract, tests/test_wire.py)."""
    if len(data) < _HEAD.size:
        raise FrameError(f"frame truncated at {len(data)} bytes")
    magic, version, flags, _, nseg, hlen = _HEAD.unpack_from(data, 0)
    if magic != FRAME_MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if version not in (FRAME_VERSION_V1, FRAME_VERSION):
        raise FrameError(f"unsupported frame version {version}")
    checked = version >= FRAME_VERSION
    off = _HEAD.size
    header_crc = None
    if checked:
        if len(data) < off + _U32.size:
            raise FrameError("frame header checksum truncated")
        (header_crc,) = _U32.unpack_from(data, off)
        off += _U32.size
    if hlen > len(data) - off:
        raise FrameError("frame header truncated")
    raw = data[off:off + hlen]
    off += hlen
    if header_crc is not None:
        got = _crc(data[: _HEAD.size] + raw)
        if got != header_crc:
            raise FrameCorruptError(
                f"frame header checksum mismatch "
                f"(expected {header_crc:#010x}, got {got:#010x})"
            )
    if flags & _FLAG_DEFLATED:
        try:
            if flags & _FLAG_DICT:
                do = zlib.decompressobj(-15, zdict=FRAME_ZDICT)
                raw = do.decompress(raw) + do.flush()
            else:
                raw = zlib.decompress(raw)
        except zlib.error as exc:
            raise FrameError(f"bad deflated header: {exc}") from None
    try:
        header = json.loads(raw)
    except (json.JSONDecodeError, UnicodeDecodeError) as exc:
        raise FrameError(f"bad header JSON: {exc}") from None
    if not isinstance(header, dict):
        raise FrameError("frame header is not an object")
    segments: list[bytes] = []
    for _ in range(nseg):
        if len(data) < off + _U32.size:
            raise FrameError("frame segment table truncated")
        (seg_len,) = _U32.unpack_from(data, off)
        off += _U32.size
        seg_crc = None
        if checked:
            if len(data) < off + _U32.size:
                raise FrameError("frame segment checksum truncated")
            (seg_crc,) = _U32.unpack_from(data, off)
            off += _U32.size
        if seg_len > len(data) - off:
            raise FrameError("frame segment truncated")
        seg = data[off:off + seg_len]
        off += seg_len
        if seg_crc is not None and _crc(seg) != seg_crc:
            raise FrameCorruptError(
                f"frame segment {len(segments)} checksum mismatch "
                f"(expected {seg_crc:#010x}, got {_crc(seg):#010x})"
            )
        segments.append(seg)
    return header, segments


def verify_frame(data: bytes) -> None:
    """Full structural + checksum validation of a frame, result discarded:
    the replica-pool `validator` hook's body (the router passes this over
    frame-typed sub-responses so a corrupt frame is replayed like a
    transport failure, ISSUE 14)."""
    split_frame(data)


def encode_frame(body: dict) -> bytes:
    """JSON-shaped response dict (base64 images) -> frame bytes."""
    header, segments = strip_segments(body)
    return build_frame(header, segments)


def decode_frame(data: bytes) -> dict:
    """Frame bytes -> the JSON-shaped response dict (base64 restored)."""
    return restore_segments(*split_frame(data))


# -- fan-in merge ------------------------------------------------------------


def merge_images(
    image_slots: list[dict | None], degraded: set[str]
) -> tuple[dict, list[bytes]]:
    """Reassemble one response from per-image slots gathered across owners
    (split-frame image dicts — `image_segment` entries carry a `_bytes` key
    with the raw segment). Recomputes `amenities_description` exactly the
    way the detector does (sorted label union over successes), so a merged
    response is indistinguishable from a single replica having served every
    URL. Returns a (header, segments) pair ready for `build_frame` or
    `restore_segments`."""
    amenities: set[str] = set()
    images: list[dict] = []
    segments: list[bytes] = []
    for slot in image_slots:
        img = dict(slot) if slot is not None else {"url": "", "error": "missing"}
        raw = img.pop("_bytes", None)
        if raw is not None:
            img["image_segment"] = len(segments)
            segments.append(raw)
        if "detections" in img:
            amenities.update(
                d.get("label") for d in img["detections"]
                if isinstance(d, dict) and d.get("label")
            )
        images.append(img)
    description = (
        f"The property contains: {', '.join(sorted(amenities))}."
        if amenities
        else "No relevant amenities detected."
    )
    header: dict = {"amenities_description": description, "images": images}
    if degraded:
        header["degraded"] = sorted(degraded)
    return header, segments


# -- X-Cache summary ---------------------------------------------------------


def summarize_cache_outcomes(outcomes) -> str | None:
    """One `X-Cache` value for a (possibly multi-image) request; None when
    the caching tier produced no observation (tier off)."""
    seen = [o for o in outcomes if o]
    if not seen:
        return None
    if "negative" in seen:
        return "negative"
    if all(o == "hit" for o in seen):
        return "hit"
    if "coalesced" in seen and all(o in ("hit", "coalesced") for o in seen):
        return "coalesced"
    return "miss"


# -- negative-verdict header -------------------------------------------------


def encode_negative_header(verdicts: dict[str, dict]) -> str | None:
    """{url: {"kind", "ttl_s", "error"}} -> header value (None when empty)."""
    items = []
    for url, v in verdicts.items():
        err = str(v.get("error", ""))[:_MAX_ERROR_CHARS]
        items.append(
            f"u={quote(url, safe='')};k={v.get('kind', 'fetch')}"
            f";t={float(v.get('ttl_s', 0.0)):.1f};e={quote(err, safe='')}"
        )
    return ", ".join(items) if items else None


def parse_negative_header(value: str | None) -> list[dict]:
    """Header value -> [{url, kind, ttl_s, error}]; malformed items are
    skipped (a half-parsed verdict must degrade to a replica round trip,
    never to a wrong edge answer)."""
    out: list[dict] = []
    if not value:
        return out
    for item in value.split(","):
        fields: dict[str, str] = {}
        for part in item.strip().split(";"):
            k, sep, v = part.partition("=")
            if sep:
                fields[k.strip()] = v
        url = fields.get("u")
        if not url:
            continue
        try:
            ttl_s = float(fields.get("t", "0"))
        except ValueError:
            continue
        if ttl_s <= 0:
            continue
        out.append(
            {
                "url": unquote(url),
                "kind": fields.get("k", "fetch"),
                "ttl_s": ttl_s,
                "error": unquote(fields.get("e", "")),
            }
        )
    return out


class EdgeNegativeCache:
    """The router's short-TTL verdict table: fleet-shared negative cache
    (ISSUE 11). Entries come ONLY from replica `X-Spotter-Negative` headers
    (i.e. the replica's own deterministic-failure taxonomy); the edge TTL
    is the MIN of the replica's remaining TTL and the edge cap, so the edge
    can never remember a verdict longer than the replica that issued it.
    Event-loop confined (router handler only) — no lock."""

    def __init__(
        self,
        max_ttl_s: float = DEFAULT_EDGE_NEGATIVE_TTL_S,
        clock=time.monotonic,
    ) -> None:
        self.max_ttl_s = max_ttl_s
        self._clock = clock
        # url -> (error, kind, expires_at)
        self._entries: dict[str, tuple[str, str, float]] = {}
        self.hits_total = 0
        self.entries_added_total = 0

    def put(self, url: str, error: str, kind: str, ttl_s: float) -> None:
        # keyed by the SAME normalization the affinity ring uses
        # (caching/keys.py) so a verdict recorded off one replica's header
        # is found by the lookup the router does per request URL
        url = normalize_url(url)
        ttl = min(float(ttl_s), self.max_ttl_s)
        if ttl <= 0:
            return
        if len(self._entries) >= MAX_EDGE_NEGATIVE_ENTRIES and url not in self._entries:
            self._purge()
            if len(self._entries) >= MAX_EDGE_NEGATIVE_ENTRIES:
                return  # full of live verdicts: drop, never evict live ones
        self._entries[url] = (error, kind, self._clock() + ttl)
        self.entries_added_total += 1

    def get(self, url: str) -> tuple[str, str] | None:
        """(error, kind) for a live verdict, else None; counts the hit."""
        url = normalize_url(url)
        entry = self._entries.get(url)
        if entry is None:
            return None
        if entry[2] <= self._clock():
            del self._entries[url]
            return None
        self.hits_total += 1
        return entry[0], entry[1]

    def absorb(self, header_value: str | None) -> int:
        """Fold one replica response's verdict header in; returns count."""
        verdicts = parse_negative_header(header_value)
        for v in verdicts:
            self.put(v["url"], v["error"], v["kind"], v["ttl_s"])
        return len(verdicts)

    def _purge(self) -> None:
        now = self._clock()
        dead = [u for u, e in self._entries.items() if e[2] <= now]
        for u in dead:
            del self._entries[u]

    def snapshot(self) -> dict:
        # nested under "edge_negative" in the router snapshot, so these
        # flatten to edge_negative_{hits,entries_added}_total in the prom
        # exposition
        self._purge()
        return {
            "entries": len(self._entries),
            "max_ttl_s": self.max_ttl_s,
            "hits_total": self.hits_total,
            "entries_added_total": self.entries_added_total,
        }
