"""AmenitiesDetector: fetch -> detect -> draw -> encode, per-image error containment.

Behavior contract with the reference detector (serve.py:64-196), observable
bit-for-bit at the /detect wire:
- async URL fetch with tenacity retry (3 attempts, exponential backoff
  multiplier 1, min 4 s, max 10 s, reraise) — serve.py:84-91
- PIL open + convert("RGB") — serve.py:96-97
- detections filtered through AMENITIES_MAPPING; irrelevant labels dropped —
  serve.py:123-126
- red box width 3, amenity text at (x+5, y+5), white fill / black stroke —
  serve.py:127-134
- JPEG + base64 of the annotated image — serve.py:139-142
- httpx errors -> "HTTP Error: ..."; anything else -> "Processing Error: ..."
  with traceback; one bad URL never fails the batch — serve.py:150-157
- response joins detected amenities into "The property contains: ..." /
  "No relevant amenities detected." — serve.py:190-194

The difference is under the hood: detection goes through the MicroBatcher into
the jit-compiled TPU engine instead of a per-image torch forward.

Request-lifecycle hardening (ISSUE 1): an optional per-request `Deadline`
(env `SPOTTER_TPU_REQUEST_DEADLINE_MS`) bounds fetch+retries, queue wait, and
the device call — on expiry the image gets a structured
`DetectionErrorResult` ("Deadline exceeded: ...") instead of hanging through
22+ s of retry backoff. Admission rejections (queue full, breaker open,
draining) stay per-image errors when the request is partially served, but a
fully-shed request re-raises so the HTTP layer can answer 429/503 with
Retry-After. tenacity is optional: when absent (minimal images) a local
retry loop preserves the same 3-attempt/4-10 s-backoff contract.

Fetch hardening (ISSUE 4 satellite): fetches are bounded in time
(`SPOTTER_TPU_FETCH_TIMEOUT_S`) and bytes (`SPOTTER_TPU_FETCH_MAX_BYTES`,
content-length reject + streamed read cap), failures are a typed
`FetchError`, deterministic 4xx statuses are not retried, and
`SPOTTER_TPU_MAX_IMAGE_PIXELS` rejects decode bombs before convert()
decodes them.

Caching tier (ISSUE 5, opt-in via `SPOTTER_TPU_CACHE_MAX_MB`): listing-photo
traffic is heavily duplicated and detection is deterministic per
(model, image bytes, threshold), so the detector front-loads three exact
short-circuits before any engine work: (1) URL-level single-flight — N
concurrent requests for one URL share ONE fetch; (2) a negative cache —
a recently-seen deterministic failure (non-retryable 4xx fetch, poison
image) re-raises instantly instead of re-fetching/re-bisecting; (3) a
content-addressed result cache — byte-identical images skip the engine
entirely (the hit still decodes + draws, so the wire response is
unchanged). Misses submit with the content hash as `key`, which the
MicroBatcher uses for hash-level coalescing and cache fill. With the knob
unset/0 none of this machinery is constructed and the path is bit-identical
to a cache-less build.
"""

import asyncio
import base64
import traceback
from io import BytesIO

import httpx
from PIL import Image, ImageDraw

try:
    from tenacity import (
        AsyncRetrying,
        retry_if_exception,
        stop_after_attempt,
        wait_exponential,
    )

    _HAVE_TENACITY = True
except ImportError:  # minimal image — fallback loop below keeps the contract
    _HAVE_TENACITY = False

from spotter_tpu import obs
from spotter_tpu.caching.keys import content_key, url_key
from spotter_tpu.caching.result_cache import ResultCache
from spotter_tpu.caching.singleflight import SingleFlight
from spotter_tpu.caching.text_cache import TextQueryResolver
from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.errors import PoisonImageError
from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.schemas import (
    DetectionErrorResult,
    DetectionRequest,
    DetectionResponse,
    DetectionResult,
    DetectionSuccessResult,
    ImageResult,
)
from spotter_tpu.serving.overload import BULK, BrownoutShedError
from spotter_tpu.serving.resilience import (
    AdmissionError,
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceededError,
    DrainingError,
    _env_float,
    _env_int,
    jittered_retry_after,
)
from spotter_tpu.ops.preprocess import check_image_pixels
from spotter_tpu.taxonomy import AMENITIES_MAPPING
from spotter_tpu.testing import faults

# Fetch retry policy (serve.py:84-88). Module-level so tests can zero the
# backoff instead of sleeping through it.
FETCH_RETRY_ATTEMPTS = 3
FETCH_RETRY_WAIT_MIN_S = 4.0
FETCH_RETRY_WAIT_MAX_S = 10.0

# Fetch hardening (ISSUE 4 satellite): every outbound image fetch is bounded
# in time and bytes, and client errors that can never succeed (404 and
# friends) are not retried through 22 s of backoff.
FETCH_TIMEOUT_ENV = "SPOTTER_TPU_FETCH_TIMEOUT_S"
DEFAULT_FETCH_TIMEOUT_S = 15.0
FETCH_MAX_BYTES_ENV = "SPOTTER_TPU_FETCH_MAX_BYTES"
DEFAULT_FETCH_MAX_BYTES = 32 * 1024 * 1024
# 4xx statuses that ARE worth retrying (timeout, rate limit); every other
# 4xx is deterministic and fails fast
RETRYABLE_4XX = (408, 429)


class QueriesUnsupportedError(ValueError):
    """A /detect carried free-text `queries` but the served model family is
    closed-set (no text encoder). The HTTP layer answers 400 — the request
    can never succeed on this deployment, so retrying or 500ing would both
    mislead the client."""


class FetchError(RuntimeError):
    """Typed image-fetch failure (size cap, retries exhausted). Replaces the
    bare `Exception("Failed to fetch image after retries")`; `retryable`
    tells the retry loop whether another attempt could possibly succeed."""

    def __init__(self, message: str, retryable: bool = False) -> None:
        super().__init__(message)
        self.retryable = retryable


def _fetch_retryable(exc: BaseException) -> bool:
    """Retry connect/timeout/5xx; never deterministic failures (non-408/429
    4xx, size-cap rejections)."""
    if isinstance(exc, FetchError):
        return exc.retryable
    if isinstance(exc, httpx.HTTPStatusError):
        code = exc.response.status_code
        if 400 <= code < 500:
            return code in RETRYABLE_4XX
    return True


# default for AmenitiesDetector(cache=...): build from the env knobs (None
# when SPOTTER_TPU_CACHE_MAX_MB is unset/0). Pass None to force the tier off
# or a ResultCache instance to use it regardless of the env.
_CACHE_FROM_ENV = object()


def _mark_outcome(info: dict | None, url: str, outcome: str) -> None:
    """Per-URL caching-tier outcome for the `X-Cache` header (ISSUE 11
    satellite). First write wins: "the cache served this" outranks any
    later bookkeeping on the same URL."""
    if info is not None:
        info.setdefault("cache", {}).setdefault(url, outcome)


def _note_verdict(
    info: dict | None, url: str, kind: str, error: str, ttl_s: float
) -> None:
    """Record a deterministic-failure verdict for this URL so the HTTP
    layer can surface it in `X-Spotter-Negative` (ISSUE 11): the edge
    router folds these into its fleet-shared negative cache. ONLY the
    PR 5 taxonomy's deterministic failures may land here."""
    if info is not None:
        info.setdefault("negative", {})[url] = {
            "kind": kind,
            "error": error,
            "ttl_s": ttl_s,
        }


class AmenitiesDetector:
    """Framework-agnostic core; Ray Serve / aiohttp adapters wrap this."""

    def __init__(
        self,
        engine: InferenceEngine,
        batcher: MicroBatcher | None = None,
        client: httpx.AsyncClient | None = None,
        cache: ResultCache | None | object = _CACHE_FROM_ENV,
    ) -> None:
        self.engine = engine
        self.batcher = batcher or MicroBatcher(engine)
        self.fetch_timeout_s = _env_float(FETCH_TIMEOUT_ENV, DEFAULT_FETCH_TIMEOUT_S)
        self.fetch_max_bytes = _env_int(FETCH_MAX_BYTES_ENV, DEFAULT_FETCH_MAX_BYTES)
        self.client = client or httpx.AsyncClient(timeout=self.fetch_timeout_s)
        # Caching tier (ISSUE 5): per-detector, never global — two detectors
        # in one process (tests, replicas) must not share entries. None means
        # the tier is fully off and every path below is bit-identical to a
        # cache-less build.
        if cache is _CACHE_FROM_ENV:
            cache = ResultCache.from_env(metrics=engine.metrics)
        self.cache: ResultCache | None = cache
        self._fetch_flights = SingleFlight(
            on_coalesced=engine.metrics.record_coalesced_fetch
        )
        if self.cache is not None and self.batcher.result_cache is None:
            self.batcher.result_cache = self.cache
        # content-key ingredients: the engine's identity half of the key
        built = getattr(engine, "built", None)
        self._cache_model = getattr(built, "model_name", None) or type(engine).__name__
        self._cache_threshold = float(getattr(engine, "threshold", 0.5))
        # Open vocabulary (ISSUE 13): text-conditioned families get a
        # memoized query-set resolver (the text-embedding cache); closed-set
        # families keep None and /detect `queries` answer 400.
        text_encoder = getattr(built, "text_encoder", None)
        self._text_resolver = (
            TextQueryResolver(
                self._cache_model, text_encoder, metrics=engine.metrics
            )
            if text_encoder is not None
            else None
        )
        # Tenant isolation plane (ISSUE 19): None unless the serving layer
        # wires one via attach_tenancy() — every tenant-aware branch below
        # is a no-op then (bit-identical serving).
        self.tenancy = None

    def attach_tenancy(self, plane) -> None:
        """Wire the tenant isolation plane (ISSUE 19) through the detector
        and down into the batcher's arbiters (scheduler DRR, limiter
        revocation scoping, per-tenant brownout). None is a no-op."""
        if plane is None:
            return
        self.tenancy = plane
        self.batcher.attach_tenancy(plane)

    def _check_fetch_size(self, url: str, nbytes: int) -> None:
        if self.fetch_max_bytes > 0 and nbytes > self.fetch_max_bytes:
            raise FetchError(
                f"image at {url} is {nbytes} bytes, over "
                f"{FETCH_MAX_BYTES_ENV}={self.fetch_max_bytes}",
                retryable=False,
            )

    async def _fetch_streamed(self, url: str) -> bytes:
        """Streamed fetch with the byte cap enforced as bytes arrive: a
        mis-labeled (or absent) content-length cannot buffer past the cap."""
        async with self.client.stream("GET", url) as response:
            response.raise_for_status()
            declared = response.headers.get("content-length")
            if declared is not None:
                try:
                    self._check_fetch_size(url, int(declared))
                except ValueError:
                    pass  # unparsable header: the read cap still applies
            chunks: list[bytes] = []
            total = 0
            async for chunk in response.aiter_bytes():
                total += len(chunk)
                self._check_fetch_size(url, total)
                chunks.append(chunk)
            return b"".join(chunks)

    async def _fetch_image_bytes(self, url: str) -> bytes:
        injected = await faults.on_fetch(url)
        if injected is not None:
            return injected
        # Streaming (early content-length reject + incremental read cap)
        # needs a REAL httpx client; duck-typed stand-ins (the stub engine's
        # canned fetcher, mocked clients in tests) keep the plain get()
        # contract and still get the post-hoc size check.
        if type(self.client) is httpx.AsyncClient:
            return await self._fetch_streamed(url)
        response = await self.client.get(url)
        response.raise_for_status()
        self._check_fetch_size(url, len(response.content))
        return response.content

    async def _fetch_with_retries(
        self, url: str, deadline: Deadline | None = None
    ) -> bytes:
        """3 attempts, exponential backoff in [min, max] s, reraise — the
        reference policy, with or without tenacity installed. Deterministic
        failures (non-408/429 4xx, size-cap rejections) are NOT retried: a
        404 re-fetched 3 times through 22 s of backoff is pure added load
        and latency with an unchanged outcome.

        Deadline-aware attempts (ISSUE 8 satellite): with a `deadline`,
        each attempt's timeout is clamped to
        `min(SPOTTER_TPU_FETCH_TIMEOUT_S, deadline.remaining)` and the
        retry loop STOPS once the remaining budget cannot cover the
        backoff plus another attempt — a 15 s per-attempt default must not
        burn a 200 ms deadline three times over. Deadline-free calls keep
        the exact reference policy (tenacity when installed)."""
        if deadline is None and _HAVE_TENACITY:
            image_bytes = None
            retries = AsyncRetrying(
                stop=stop_after_attempt(FETCH_RETRY_ATTEMPTS),
                wait=wait_exponential(
                    multiplier=1, min=FETCH_RETRY_WAIT_MIN_S, max=FETCH_RETRY_WAIT_MAX_S
                ),
                retry=retry_if_exception(_fetch_retryable),
                reraise=True,
            )
            async for attempt in retries:
                with attempt:
                    image_bytes = await self._fetch_image_bytes(url)
            if image_bytes is None:
                raise FetchError("failed to fetch image after retries")
            return image_bytes
        for attempt in range(1, FETCH_RETRY_ATTEMPTS + 1):
            attempt_timeout = self.fetch_timeout_s
            if deadline is not None:
                remaining = deadline.remaining()
                if remaining <= 0:
                    raise deadline.exceeded("image fetch")
                if attempt_timeout > 0:
                    attempt_timeout = min(attempt_timeout, remaining)
                else:
                    attempt_timeout = remaining
            try:
                fetch = self._fetch_image_bytes(url)
                if attempt_timeout > 0:
                    try:
                        return await asyncio.wait_for(fetch, attempt_timeout)
                    except asyncio.TimeoutError:
                        raise FetchError(
                            f"fetch attempt timed out after "
                            f"{attempt_timeout:.3f} s",
                            retryable=True,
                        ) from None
                return await fetch
            except Exception as exc:
                if attempt == FETCH_RETRY_ATTEMPTS or not _fetch_retryable(exc):
                    raise
                wait = min(
                    max(float(2**attempt), FETCH_RETRY_WAIT_MIN_S),
                    FETCH_RETRY_WAIT_MAX_S,
                )
                if deadline is not None and deadline.remaining() <= wait:
                    # the budget cannot cover the backoff, let alone the
                    # attempt after it: skip the pointless retries and
                    # surface the real failure now
                    raise
                await asyncio.sleep(wait)
        raise FetchError("failed to fetch image after retries")  # unreachable

    async def _fetch_flight(self, url: str) -> bytes:
        """The shared fetch flight body (cache tier on): one per URL at a
        time, deadline-free — waiters apply their own budgets around it.
        Deterministic failures land in the negative cache on the way out;
        retryable ones (5xx, 429/408, timeouts, connect errors) never do."""
        try:
            return await self._fetch_with_retries(url)
        except FetchError as exc:
            if not exc.retryable:
                self.cache.put_negative(url_key(url), exc)
            raise
        except httpx.HTTPStatusError as exc:
            code = exc.response.status_code
            if 400 <= code < 500 and code not in RETRYABLE_4XX:
                self.cache.put_negative(url_key(url), exc)
            raise

    async def _fetch_for_request(
        self, url: str, deadline: Deadline | None, info: dict | None = None
    ) -> bytes:
        if self.cache is None:  # tier off: the exact pre-cache path
            fetch = self._fetch_with_retries(url, deadline)
            if deadline is not None:
                return await deadline.wait_for(fetch, "image fetch")
            return await fetch
        cached_failure = self.cache.get_negative(url_key(url))
        if cached_failure is not None:
            _mark_outcome(info, url, "negative")
            raise cached_failure
        return await self._fetch_flights.run(
            url,
            lambda: self._fetch_flight(url),
            deadline=deadline,
            what="image fetch",
        )

    async def _process_single_image(
        self,
        url: str,
        deadline: Deadline | None = None,
        cls: str | None = None,
        degraded: set[str] | None = None,
        info: dict | None = None,
        qset=None,
        tenant: str | None = None,
    ) -> ImageResult:
        # the ambient request trace (ISSUE 7): span capture below is a
        # monotonic read + list append per stage; None (recorder off, or a
        # bare library call) makes every `with obs.span(...)` a no-op
        trace = obs.current_trace()
        brownout = self.batcher.brownout
        # brownout threshold rung (ISSUE 8): read once, up front — the
        # annotated fast path below is only valid at the BASE threshold
        # (the sidecar JPEG was drawn without a boost), and the filter
        # further down must agree with that decision for this request
        boost = brownout.threshold_boost_value() if brownout is not None else 0.0
        try:
            with obs.span(obs.FETCH, trace):
                image_bytes = await self._fetch_for_request(url, deadline, info)

            with obs.span(obs.DECODE, trace):
                cache_key: str | None = None
                raw_detections: list[dict] | None = None
                annotated: dict | None = None
                if self.cache is not None:
                    cache_key = content_key(
                        self._cache_model, image_bytes, self._cache_threshold
                    )
                    if qset is not None:
                        # the detections depend on the vocabulary too: a
                        # closed-set hit must never answer a queried request
                        # (or two different vocabularies each other)
                        cache_key = f"{cache_key}|q{qset.digest}"
                    # repeat poison: re-raise the cached verdict instead of
                    # letting the same bytes re-poison a batch through the
                    # bisect machinery
                    cached_failure = self.cache.get_negative(cache_key)
                    if cached_failure is not None:
                        _mark_outcome(info, url, "negative")
                        raise cached_failure
                    # brownout serve-stale rung (ISSUE 8): under sustained
                    # saturation an expired-TTL entry beats an engine pass —
                    # the response is marked `degraded: ["stale"]`
                    raw_detections, was_stale, annotated = (
                        self.cache.get_entry_full(
                            cache_key,
                            stale_ok=brownout is not None
                            and brownout.stale_ok(),
                        )
                    )
                    if was_stale and degraded is not None:
                        degraded.add("stale")
                    if raw_detections is not None:
                        _mark_outcome(info, url, "hit")

                # annotated fast hit (ISSUE 11 satellite): the entry carries
                # the finished JPEG + filtered boxes, so the whole pillow
                # round trip (decode + draw + re-encode — most of PR 5's
                # ~3.3 ms hit p50) is skipped. Only at the base threshold:
                # a boosted view must re-filter and re-draw.
                use_annotated = (
                    raw_detections is not None
                    and annotated is not None
                    and boost == 0.0
                )
                if not use_annotated:
                    with Image.open(BytesIO(image_bytes)) as img_raw:
                        # decode-bomb guard: the header-declared pixel count
                        # is checked BEFORE convert() decodes anything
                        # (preprocess.py)
                        check_image_pixels(img_raw)
                        image = img_raw.convert("RGB")

            if use_annotated:
                with obs.span(obs.POSTPROCESS, trace):
                    return DetectionSuccessResult(
                        url=url,
                        detections=[
                            DetectionResult(
                                label=d["label"], box=list(d["box"])
                            )
                            for d in annotated["detections"]
                        ],
                        labeled_image_base64=base64.b64encode(
                            annotated["jpeg"]
                        ).decode("utf-8"),
                    )

            if raw_detections is None:
                # miss: the content hash rides into the batcher for
                # hash-level coalescing + cache fill on completion
                if cache_key is not None:
                    _mark_outcome(
                        info,
                        url,
                        "coalesced"
                        if self.batcher.in_flight(cache_key)
                        else "miss",
                    )
                raw_detections = await self.batcher.submit(
                    image, deadline=deadline, key=cache_key, cls=cls,
                    qset=qset, tenant=tenant,
                )

            # brownout threshold rung (ISSUE 8): raise the effective
            # detection bar so fewer boxes survive into the draw/encode
            # path (cache entries keep the BASE threshold key — the boost
            # is a view over them, not a new key space)
            if boost > 0.0:
                eff_threshold = min(self._cache_threshold + boost, 0.99)
                raw_detections = [
                    d for d in raw_detections
                    if d.get("score", 1.0) >= eff_threshold
                ]

            with obs.span(obs.POSTPROCESS, trace):
                draw = ImageDraw.Draw(image)
                image_detections: list[DetectionResult] = []
                for det in raw_detections:
                    # open-vocab (ISSUE 13): the client's own queries ARE the
                    # label set — the amenity taxonomy filter only applies to
                    # the closed-set deployment vocabulary
                    amenity = (
                        det["label"] if qset is not None
                        else AMENITIES_MAPPING.get(det["label"])
                    )
                    if amenity is None:
                        continue
                    box = det["box"]
                    draw.rectangle(box, outline="red", width=3)
                    draw.text(
                        xy=(box[0] + 5, box[1] + 5),
                        text=amenity,
                        fill="white",
                        stroke_width=1,
                        stroke_fill="black",
                    )
                    image_detections.append(
                        DetectionResult(label=amenity, box=box)
                    )

                buffer = BytesIO()
                image.save(buffer, format="JPEG")
                jpeg_bytes = buffer.getvalue()
                image_b64 = base64.b64encode(jpeg_bytes).decode("utf-8")

            # annotated sidecar fill (ISSUE 11 satellite): the next hit on
            # this content skips the pillow work we just did. Base
            # threshold only — a boosted view must not poison the base
            # entry with its narrower box set — and attach_annotated
            # itself refuses stale/absent entries.
            if (
                self.cache is not None
                and cache_key is not None
                and boost == 0.0
            ):
                self.cache.attach_annotated(
                    cache_key,
                    jpeg_bytes,
                    [
                        {"label": d.label, "box": list(d.box)}
                        for d in image_detections
                    ],
                )

            return DetectionSuccessResult(
                url=url, detections=image_detections, labeled_image_base64=image_b64
            )
        except DeadlineExceededError as e:
            # structured, bounded-time answer — never a hang (ISSUE 1)
            if trace is not None:
                trace.set_error("deadline", str(e))
            return DetectionErrorResult(url=url, error=f"Deadline exceeded: {e}")
        except AdmissionError:
            # propagate so detect() can turn a fully-shed request into
            # HTTP 429/503; partially-shed requests degrade per image there
            raise
        except FetchError as e:
            if trace is not None:
                trace.set_error("fetch_error", str(e))
            if self.cache is not None and not e.retryable:
                _note_verdict(
                    info, url, "fetch", f"Fetch Error: {e}",
                    self.cache.negative_ttl_s,
                )
            return DetectionErrorResult(url=url, error=f"Fetch Error: {e}")
        except httpx.HTTPError as e:
            if trace is not None:
                trace.set_error("fetch_error", str(e))
            if (
                self.cache is not None
                and isinstance(e, httpx.HTTPStatusError)
                and 400 <= e.response.status_code < 500
                and e.response.status_code not in RETRYABLE_4XX
            ):
                _note_verdict(
                    info, url, "fetch", f"HTTP Error: {e}",
                    self.cache.negative_ttl_s,
                )
            return DetectionErrorResult(url=url, error=f"HTTP Error: {e}")
        except Exception as e:
            tb_str = traceback.format_exc()
            if trace is not None:
                # poison/engine failures pin the trace in the flight
                # recorder's error set under their exception type
                trace.set_error(type(e).__name__, str(e))
            if self.cache is not None and isinstance(e, PoisonImageError):
                # poison is keyed by content hash in the replica cache, but
                # the edge only knows URLs: surface the verdict against the
                # URL that carried the bytes (short TTL bounds the harm if
                # the URL later serves different content)
                _note_verdict(
                    info, url, "poison", f"Processing Error: {e}",
                    self.cache.negative_ttl_s,
                )
            return DetectionErrorResult(url=url, error=f"Processing Error: {e}\n{tb_str}")

    async def detect(
        self,
        payload: dict,
        deadline: Deadline | None = None,
        cls: str | None = None,
        info: dict | None = None,
        tenant: str | None = None,
    ) -> DetectionResponse:
        """`info` (ISSUE 11, optional dict) collects per-URL data-plane
        observations for the HTTP layer: `info["cache"]` maps url ->
        hit|miss|negative|coalesced (the X-Cache header) and
        `info["negative"]` carries deterministic-failure verdicts for the
        X-Spotter-Negative header. Pass None (the default) and nothing is
        collected — the pre-ISSUE-11 path, bit-identical. `tenant`
        (ISSUE 19) rides into every batcher submit so the scheduler's DRR
        ordering and the limiter's revocation scoping see it; None keeps
        the tenant-blind path."""
        request = DetectionRequest.model_validate(payload)
        if deadline is None:
            deadline = Deadline.from_env()
        # Open vocabulary (ISSUE 13): resolve the request's query set ONCE
        # through the text-embedding cache (a repeated vocabulary costs a
        # dict lookup, a novel one pays the text-tower encode off the event
        # loop) — every image in the request shares the resolved set, which
        # is also its batch-compatibility group downstream.
        qset = None
        if request.queries:
            if self._text_resolver is None:
                raise QueriesUnsupportedError(
                    f"model '{self._cache_model}' is closed-set: free-text "
                    f"`queries` need a text-conditioned family (OWL-ViT/OWLv2)"
                )
            qset = await asyncio.get_running_loop().run_in_executor(
                None, self._text_resolver.resolve, list(request.queries)
            )
        urls = [str(u) for u in request.image_urls]
        degraded: set[str] = set()
        tasks = [
            self._process_single_image(
                u, deadline, cls=cls, degraded=degraded, info=info, qset=qset,
                tenant=tenant,
            )
            for u in urls
        ]
        gathered = await asyncio.gather(*tasks, return_exceptions=True)

        shed = [r for r in gathered if isinstance(r, AdmissionError)]
        if shed and len(shed) == len(gathered):
            raise shed[0]  # whole request shed -> HTTP 429/503 + Retry-After

        results: list[ImageResult] = []
        for url, r in zip(urls, gathered):
            if isinstance(r, AdmissionError):
                results.append(DetectionErrorResult(url=url, error=f"Overloaded: {r}"))
            elif isinstance(r, BaseException):
                raise r  # unexpected: _process_single_image contains the rest
            else:
                results.append(r)

        amenities: set[str] = set()
        for result in results:
            if isinstance(result, DetectionSuccessResult):
                amenities.update(d.label for d in result.detections)

        description = (
            f"The property contains: {', '.join(sorted(amenities))}."
            if amenities
            else "No relevant amenities detected."
        )
        # the `degraded:` marker contract (ISSUE 8): absent from the wire
        # unless a brownout concession actually shaped THIS response —
        # "stale" when any image was served from an expired cache entry,
        # plus the globally-active rung markers ("bucket_cap", "threshold")
        brownout = self.batcher.brownout
        if brownout is not None:
            degraded.update(brownout.markers())
        return DetectionResponse(
            amenities_description=description,
            images=results,
            degraded=sorted(degraded) if degraded else None,
        )

    def check_admission(
        self, cls: str | None = None, tenant: str | None = None
    ) -> AdmissionError | None:
        """HTTP-layer fast path: an AdmissionError to answer with (mapped to
        429/503 + Retry-After) before any fetch work, or None to proceed.
        Never consumes the breaker's half-open probe slot — a request that
        could probe must reach `MicroBatcher.submit` to do so. `cls`
        ("slo"|"bulk") lets the deepest brownout rung shed bulk BEFORE the
        fetch spends bytes on work the batcher would refuse anyway;
        `tenant` (ISSUE 19) scopes that rung so only over-share tenants
        brown out while in-quota tenants keep full service."""
        if self.batcher.draining:
            self.engine.metrics.record_shed()
            return DrainingError("server draining")
        breaker = self.batcher.breaker
        if breaker.would_reject():
            self.engine.metrics.record_shed()
            return CircuitOpenError(
                "circuit breaker open", retry_after_s=breaker.retry_after_s()
            )
        brownout = self.batcher.brownout
        if brownout is not None and cls == BULK:
            brownout.evaluate()
            if brownout.shed_bulk(tenant):
                self.engine.metrics.record_shed()
                self.engine.metrics.record_admit_shed(BULK)
                return BrownoutShedError(
                    f"brownout: bulk traffic shed (rung {brownout.rung})",
                    retry_after_s=jittered_retry_after(brownout.disarm_s),
                )
        return None

    def health(self) -> dict:
        """Readiness snapshot for /healthz: not-ready while the breaker is
        open/probing or a drain is in progress (liveness is /livez)."""
        breaker = self.batcher.breaker
        draining = self.batcher.draining
        ready = breaker.state == CircuitBreaker.CLOSED and not draining
        dp = getattr(self.engine, "dp", 1)
        initial_dp = getattr(self.engine, "initial_dp", dp)
        # brownout state (ISSUE 8): a browned-out replica is READY (it
        # serves, shedding quality for survival) but /healthz says so —
        # `status=brownout` outranks the dp-degraded label because it is
        # the condition an operator can influence (shift load away)
        brownout = self.batcher.brownout
        brownout_rung = brownout.evaluate() if brownout is not None else 0
        return {
            "status": (
                "brownout" if ready and brownout_rung > 0
                else "ok" if ready and dp >= initial_dp
                else "degraded" if ready
                else "unready"
            ),
            # overload-control tier state: absent-as-disabled mirrors the
            # cache block below
            "brownout": (
                brownout.snapshot() if brownout is not None
                else {"enabled": False}
            ),
            "admit": (
                self.batcher.limiter.snapshot()
                if self.batcher.limiter is not None
                else {"enabled": False}
            ),
            "ready": ready,
            "breaker": breaker.state,
            "draining": draining,
            # deployment identity (ISSUE 15): which build/weights this
            # replica serves — a mixed-version window during a rollout is
            # auditable per pod, same as the topology flags below
            "version": self.engine.metrics.version,
            # ingest/topology config (ISSUE 3): which serving shape this
            # replica runs — dp width and whether preprocess is on-device —
            # so a fleet rollout of the new pipeline is auditable per pod
            "dp": dp,
            # tensor-parallel topology (ISSUE 13): the RESOLVED mesh this
            # replica actually serves on (tp=1 single-chip included) plus
            # which knob produced it — the MESH-vs-SERVE_DP/TP precedence
            # is auditable here instead of silently losing (satellite 2)
            "tp": getattr(self.engine, "tp", 1),
            "mesh": (
                {
                    "dp": dp,
                    "tp": getattr(self.engine, "tp", 1),
                    "source": getattr(self.engine, "mesh_source", None),
                }
                if getattr(self.engine, "mesh", None) is not None
                else None
            ),
            # open-vocabulary capability (ISSUE 13): whether this replica
            # accepts free-text `queries`, with the text-embedding cache's
            # size state when it does
            "open_vocab": (
                self._text_resolver.stats()
                if self._text_resolver is not None
                else {"enabled": False}
            ),
            "device_preprocess": getattr(self.engine, "device_preprocess", False),
            # ragged scheduling (ISSUE 9): which dispatch policy this
            # replica runs (FIFO unless SPOTTER_TPU_RAGGED=1), auditable
            # per pod like the ingest/topology flags above
            "ragged": self.batcher.scheduler.ragged,
            # engine fault domain (ISSUE 4): lost-shard degradation state
            "dp_degraded": (
                {"from": initial_dp, "to": dp} if dp < initial_dp else None
            ),
            "engine_generation": getattr(self.engine, "generation", 0),
            # caching tier (ISSUE 5): size state for fleet dashboards; the
            # hit/miss/coalesce counters live in /metrics
            "cache": (
                self.cache.stats() if self.cache is not None
                else {"enabled": False}
            ),
            # device-efficiency plane (ISSUE 10): fast/slow-window error-
            # budget burn over deadline misses + sheds — the brownout
            # ladder's effect shows up here as budget recovery
            "slo_burn": self.engine.metrics.perf.slo.block(),
            # tenant isolation plane (ISSUE 19): quota/fairness state when
            # configured; absent-as-disabled mirrors the cache block
            "tenancy": (
                self.tenancy.snapshot() if self.tenancy is not None
                else {"enabled": False}
            ),
        }

    async def drain(self, timeout_s: float | None = None) -> dict:
        """Stop admitting, flush the queue, wait for in-flight batches.
        `timeout_s` (ISSUE 15) overrides the env-default drain window —
        the /drain handler maps its `deadline_ms` body field here so a
        rollout retire (or k8s preStop) waits exactly as long as it can
        afford."""
        return await self.batcher.drain(timeout_s)

    async def aclose(self) -> None:
        await self.batcher.stop()
        await self.client.aclose()
