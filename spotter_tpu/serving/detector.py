"""AmenitiesDetector: fetch -> detect -> draw -> encode, per-image error containment.

Behavior contract with the reference detector (serve.py:64-196), observable
bit-for-bit at the /detect wire:
- async URL fetch with tenacity retry (3 attempts, exponential backoff
  multiplier 1, min 4 s, max 10 s, reraise) — serve.py:84-91
- PIL open + convert("RGB") — serve.py:96-97
- detections filtered through AMENITIES_MAPPING; irrelevant labels dropped —
  serve.py:123-126
- red box width 3, amenity text at (x+5, y+5), white fill / black stroke —
  serve.py:127-134
- JPEG + base64 of the annotated image — serve.py:139-142
- httpx errors -> "HTTP Error: ..."; anything else -> "Processing Error: ..."
  with traceback; one bad URL never fails the batch — serve.py:150-157
- response joins detected amenities into "The property contains: ..." /
  "No relevant amenities detected." — serve.py:190-194

The difference is under the hood: detection goes through the MicroBatcher into
the jit-compiled TPU engine instead of a per-image torch forward.

Request-lifecycle hardening (ISSUE 1): an optional per-request `Deadline`
(env `SPOTTER_TPU_REQUEST_DEADLINE_MS`) bounds fetch+retries, queue wait, and
the device call — on expiry the image gets a structured
`DetectionErrorResult` ("Deadline exceeded: ...") instead of hanging through
22+ s of retry backoff. Admission rejections (queue full, breaker open,
draining) stay per-image errors when the request is partially served, but a
fully-shed request re-raises so the HTTP layer can answer 429/503 with
Retry-After. tenacity is optional: when absent (minimal images) a local
retry loop preserves the same 3-attempt/4-10 s-backoff contract.
"""

import asyncio
import base64
import traceback
from io import BytesIO

import httpx
from PIL import Image, ImageDraw

try:
    from tenacity import AsyncRetrying, stop_after_attempt, wait_exponential

    _HAVE_TENACITY = True
except ImportError:  # minimal image — fallback loop below keeps the contract
    _HAVE_TENACITY = False

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.schemas import (
    DetectionErrorResult,
    DetectionRequest,
    DetectionResponse,
    DetectionResult,
    DetectionSuccessResult,
    ImageResult,
)
from spotter_tpu.serving.resilience import (
    AdmissionError,
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceededError,
    DrainingError,
)
from spotter_tpu.taxonomy import AMENITIES_MAPPING
from spotter_tpu.testing import faults

# Fetch retry policy (serve.py:84-88). Module-level so tests can zero the
# backoff instead of sleeping through it.
FETCH_RETRY_ATTEMPTS = 3
FETCH_RETRY_WAIT_MIN_S = 4.0
FETCH_RETRY_WAIT_MAX_S = 10.0


class AmenitiesDetector:
    """Framework-agnostic core; Ray Serve / aiohttp adapters wrap this."""

    def __init__(
        self,
        engine: InferenceEngine,
        batcher: MicroBatcher | None = None,
        client: httpx.AsyncClient | None = None,
    ) -> None:
        self.engine = engine
        self.batcher = batcher or MicroBatcher(engine)
        self.client = client or httpx.AsyncClient()

    async def _fetch_image_bytes(self, url: str) -> bytes:
        injected = await faults.on_fetch(url)
        if injected is not None:
            return injected
        response = await self.client.get(url)
        response.raise_for_status()
        return response.content

    async def _fetch_with_retries(self, url: str) -> bytes:
        """3 attempts, exponential backoff in [min, max] s, reraise — the
        reference policy, with or without tenacity installed."""
        if _HAVE_TENACITY:
            image_bytes = None
            retries = AsyncRetrying(
                stop=stop_after_attempt(FETCH_RETRY_ATTEMPTS),
                wait=wait_exponential(
                    multiplier=1, min=FETCH_RETRY_WAIT_MIN_S, max=FETCH_RETRY_WAIT_MAX_S
                ),
                reraise=True,
            )
            async for attempt in retries:
                with attempt:
                    image_bytes = await self._fetch_image_bytes(url)
            if image_bytes is None:
                raise Exception("Failed to fetch image after retries")
            return image_bytes
        for attempt in range(1, FETCH_RETRY_ATTEMPTS + 1):
            try:
                return await self._fetch_image_bytes(url)
            except Exception:
                if attempt == FETCH_RETRY_ATTEMPTS:
                    raise
                wait = min(
                    max(float(2**attempt), FETCH_RETRY_WAIT_MIN_S),
                    FETCH_RETRY_WAIT_MAX_S,
                )
                await asyncio.sleep(wait)
        raise Exception("Failed to fetch image after retries")  # unreachable

    async def _process_single_image(
        self, url: str, deadline: Deadline | None = None
    ) -> ImageResult:
        try:
            fetch = self._fetch_with_retries(url)
            if deadline is not None:
                image_bytes = await deadline.wait_for(fetch, "image fetch")
            else:
                image_bytes = await fetch

            with Image.open(BytesIO(image_bytes)) as img_raw:
                image = img_raw.convert("RGB")

            raw_detections = await self.batcher.submit(image, deadline=deadline)

            draw = ImageDraw.Draw(image)
            image_detections: list[DetectionResult] = []
            for det in raw_detections:
                amenity = AMENITIES_MAPPING.get(det["label"])
                if amenity is None:
                    continue
                box = det["box"]
                draw.rectangle(box, outline="red", width=3)
                draw.text(
                    xy=(box[0] + 5, box[1] + 5),
                    text=amenity,
                    fill="white",
                    stroke_width=1,
                    stroke_fill="black",
                )
                image_detections.append(DetectionResult(label=amenity, box=box))

            buffer = BytesIO()
            image.save(buffer, format="JPEG")
            image_b64 = base64.b64encode(buffer.getvalue()).decode("utf-8")

            return DetectionSuccessResult(
                url=url, detections=image_detections, labeled_image_base64=image_b64
            )
        except DeadlineExceededError as e:
            # structured, bounded-time answer — never a hang (ISSUE 1)
            return DetectionErrorResult(url=url, error=f"Deadline exceeded: {e}")
        except AdmissionError:
            # propagate so detect() can turn a fully-shed request into
            # HTTP 429/503; partially-shed requests degrade per image there
            raise
        except httpx.HTTPError as e:
            return DetectionErrorResult(url=url, error=f"HTTP Error: {e}")
        except Exception as e:
            tb_str = traceback.format_exc()
            return DetectionErrorResult(url=url, error=f"Processing Error: {e}\n{tb_str}")

    async def detect(
        self, payload: dict, deadline: Deadline | None = None
    ) -> DetectionResponse:
        request = DetectionRequest.model_validate(payload)
        if deadline is None:
            deadline = Deadline.from_env()
        urls = [str(u) for u in request.image_urls]
        tasks = [self._process_single_image(u, deadline) for u in urls]
        gathered = await asyncio.gather(*tasks, return_exceptions=True)

        shed = [r for r in gathered if isinstance(r, AdmissionError)]
        if shed and len(shed) == len(gathered):
            raise shed[0]  # whole request shed -> HTTP 429/503 + Retry-After

        results: list[ImageResult] = []
        for url, r in zip(urls, gathered):
            if isinstance(r, AdmissionError):
                results.append(DetectionErrorResult(url=url, error=f"Overloaded: {r}"))
            elif isinstance(r, BaseException):
                raise r  # unexpected: _process_single_image contains the rest
            else:
                results.append(r)

        amenities: set[str] = set()
        for result in results:
            if isinstance(result, DetectionSuccessResult):
                amenities.update(d.label for d in result.detections)

        description = (
            f"The property contains: {', '.join(sorted(amenities))}."
            if amenities
            else "No relevant amenities detected."
        )
        return DetectionResponse(amenities_description=description, images=results)

    def check_admission(self) -> AdmissionError | None:
        """HTTP-layer fast path: an AdmissionError to answer with (mapped to
        429/503 + Retry-After) before any fetch work, or None to proceed.
        Never consumes the breaker's half-open probe slot — a request that
        could probe must reach `MicroBatcher.submit` to do so."""
        if self.batcher.draining:
            self.engine.metrics.record_shed()
            return DrainingError("server draining")
        breaker = self.batcher.breaker
        if breaker.would_reject():
            self.engine.metrics.record_shed()
            return CircuitOpenError(
                "circuit breaker open", retry_after_s=breaker.retry_after_s()
            )
        return None

    def health(self) -> dict:
        """Readiness snapshot for /healthz: not-ready while the breaker is
        open/probing or a drain is in progress (liveness is /livez)."""
        breaker = self.batcher.breaker
        draining = self.batcher.draining
        ready = breaker.state == CircuitBreaker.CLOSED and not draining
        return {
            "status": "ok" if ready else "unready",
            "ready": ready,
            "breaker": breaker.state,
            "draining": draining,
            # ingest/topology config (ISSUE 3): which serving shape this
            # replica runs — dp width and whether preprocess is on-device —
            # so a fleet rollout of the new pipeline is auditable per pod
            "dp": getattr(self.engine, "dp", 1),
            "device_preprocess": getattr(self.engine, "device_preprocess", False),
        }

    async def drain(self) -> dict:
        """Stop admitting, flush the queue, wait for in-flight batches."""
        return await self.batcher.drain()

    async def aclose(self) -> None:
        await self.batcher.stop()
        await self.client.aclose()
