"""AmenitiesDetector: fetch -> detect -> draw -> encode, per-image error containment.

Behavior contract with the reference detector (serve.py:64-196), observable
bit-for-bit at the /detect wire:
- async URL fetch with tenacity retry (3 attempts, exponential backoff
  multiplier 1, min 4 s, max 10 s, reraise) — serve.py:84-91
- PIL open + convert("RGB") — serve.py:96-97
- detections filtered through AMENITIES_MAPPING; irrelevant labels dropped —
  serve.py:123-126
- red box width 3, amenity text at (x+5, y+5), white fill / black stroke —
  serve.py:127-134
- JPEG + base64 of the annotated image — serve.py:139-142
- httpx errors -> "HTTP Error: ..."; anything else -> "Processing Error: ..."
  with traceback; one bad URL never fails the batch — serve.py:150-157
- response joins detected amenities into "The property contains: ..." /
  "No relevant amenities detected." — serve.py:190-194

The difference is under the hood: detection goes through the MicroBatcher into
the jit-compiled TPU engine instead of a per-image torch forward.
"""

import asyncio
import base64
import traceback
from io import BytesIO

import httpx
from PIL import Image, ImageDraw
from tenacity import AsyncRetrying, stop_after_attempt, wait_exponential

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.schemas import (
    DetectionErrorResult,
    DetectionRequest,
    DetectionResponse,
    DetectionResult,
    DetectionSuccessResult,
    ImageResult,
)
from spotter_tpu.taxonomy import AMENITIES_MAPPING

# Fetch retry policy (serve.py:84-88). Module-level so tests can zero the
# backoff instead of sleeping through it.
FETCH_RETRY_ATTEMPTS = 3
FETCH_RETRY_WAIT_MIN_S = 4.0
FETCH_RETRY_WAIT_MAX_S = 10.0


class AmenitiesDetector:
    """Framework-agnostic core; Ray Serve / aiohttp adapters wrap this."""

    def __init__(
        self,
        engine: InferenceEngine,
        batcher: MicroBatcher | None = None,
        client: httpx.AsyncClient | None = None,
    ) -> None:
        self.engine = engine
        self.batcher = batcher or MicroBatcher(engine)
        self.client = client or httpx.AsyncClient()

    async def _fetch_image_bytes(self, url: str) -> bytes:
        response = await self.client.get(url)
        response.raise_for_status()
        return response.content

    async def _process_single_image(self, url: str) -> ImageResult:
        try:
            image_bytes = None
            retries = AsyncRetrying(
                stop=stop_after_attempt(FETCH_RETRY_ATTEMPTS),
                wait=wait_exponential(
                    multiplier=1, min=FETCH_RETRY_WAIT_MIN_S, max=FETCH_RETRY_WAIT_MAX_S
                ),
                reraise=True,
            )
            async for attempt in retries:
                with attempt:
                    image_bytes = await self._fetch_image_bytes(url)
            if image_bytes is None:
                raise Exception("Failed to fetch image after retries")

            with Image.open(BytesIO(image_bytes)) as img_raw:
                image = img_raw.convert("RGB")

            raw_detections = await self.batcher.submit(image)

            draw = ImageDraw.Draw(image)
            image_detections: list[DetectionResult] = []
            for det in raw_detections:
                amenity = AMENITIES_MAPPING.get(det["label"])
                if amenity is None:
                    continue
                box = det["box"]
                draw.rectangle(box, outline="red", width=3)
                draw.text(
                    xy=(box[0] + 5, box[1] + 5),
                    text=amenity,
                    fill="white",
                    stroke_width=1,
                    stroke_fill="black",
                )
                image_detections.append(DetectionResult(label=amenity, box=box))

            buffer = BytesIO()
            image.save(buffer, format="JPEG")
            image_b64 = base64.b64encode(buffer.getvalue()).decode("utf-8")

            return DetectionSuccessResult(
                url=url, detections=image_detections, labeled_image_base64=image_b64
            )
        except httpx.HTTPError as e:
            return DetectionErrorResult(url=url, error=f"HTTP Error: {e}")
        except Exception as e:
            tb_str = traceback.format_exc()
            return DetectionErrorResult(url=url, error=f"Processing Error: {e}\n{tb_str}")

    async def detect(self, payload: dict) -> DetectionResponse:
        request = DetectionRequest.model_validate(payload)
        tasks = [self._process_single_image(str(u)) for u in request.image_urls]
        results = await asyncio.gather(*tasks)

        amenities: set[str] = set()
        for result in results:
            if isinstance(result, DetectionSuccessResult):
                amenities.update(d.label for d in result.detections)

        description = (
            f"The property contains: {', '.join(sorted(amenities))}."
            if amenities
            else "No relevant amenities detected."
        )
        return DetectionResponse(amenities_description=description, images=list(results))

    async def aclose(self) -> None:
        await self.batcher.stop()
        await self.client.aclose()
