"""Crash-safe control plane (ISSUE 16 tentpole, parts b-d): the reconcile
loop that converges observed fleet state onto the durable desired-state
spec, with orphan adoption, leader fencing, and rebuild-from-observation.

The FleetController (serving/fleet.py) and RolloutController
(serving/rollout.py) are good ACTUATORS — spawn, drain, retire, re-pin —
but before this module they were also the only copy of the fleet's intent:
kill the controller mid-rollout and the canary was stranded at a pinned
weight forever; kill it mid-storm and dead members were never respawned.
This module splits intent from actuation:

- **Desired state** lives in `statestore.StateStore` (CRC-framed journal +
  snapshot). The reconciler never trusts memory over the journal, and
  never trusts the journal over a failed CRC: `load_or_rebuild` turns
  `StateCorruptError` into a counted rebuild-from-observation (adopt what
  is verifiably running, journal THAT as the new desired state) — the
  Spotlight posture, where observed spot capacity outranks replayed
  intent.
- **Orphan adoption**: supervisors register their replica in an
  `EndpointsManifest` (url -> pool/version/pidfile/preempt_file/
  supervisor_pid) and deregister only on permanent exit, so the manifest
  stays truthful while no controller is alive. A (re)started controller
  adopts every still-live entry — `ManifestHandle` rebuilds the
  MemberHandle surface from the manifest entry alone — instead of
  double-spawning next to it or killing it as unknown. The /healthz
  identity block (replica_id, version, weights_digest — PR 12/15) is
  probed to confirm what was adopted.
- **Leader fencing**: with a `LeaderLease`, any number of controllers can
  run; exactly one acts. Every actuation path (the controller's spawns
  via its `fence` hook, the rollout spawner, the reconciler's own
  convergence steps) calls `Reconciler.fence()` — `LeaderLease.check()`
  plus a counted `StaleLeaderError` — so a deposed controller (paused
  past its TTL, then resumed) is refused at the actuation boundary, not
  after it has half-acted.
- **Drift** is the reconciler's public health signal: per pool,
  `desired - ready`. `/healthz` on an edge wired with a reconciler
  reports leadership + drift; `tools/fleet_top.py` renders the same
  block; the drill gates on drift reconverging to zero after every chaos
  scenario.

`python -m spotter_tpu.serving.reconcile` is the standalone controller
process `bench.py --controller-crash` kills and restarts: it stands by on
the lease, loads-or-rebuilds the journal, adopts orphans, runs the fleet
tick + reconcile loop + (resumable) rollout, and writes an atomic status
JSON each tick for the drill to parse.
"""

import argparse
import asyncio
import json
import logging
import os
import signal
import sys
import time
from typing import Callable, Optional

from spotter_tpu.engine.metrics import ControlPlaneMetrics
from spotter_tpu.serving.statestore import (
    JOURNAL_NAME,
    EndpointsManifest,
    LeaderLease,
    StaleLeaderError,
    StateCorruptError,
    StateStore,
    _atomic_write,
    supervisor_alive,
)

logger = logging.getLogger(__name__)

DEFAULT_INTERVAL_S = 0.25
IDENTITY_PROBE_TIMEOUT_S = 1.5


class ManifestHandle:
    """A fleet MemberHandle reconstructed from an endpoints-manifest entry
    — what orphan adoption hands the controller when the process object
    that spawned the member died with the previous controller. Same
    surface as testing/cluster.py::FleetMember, driven through the
    supervisor pid and the maintenance file instead of a Popen handle."""

    def __init__(self, url: str, entry: dict) -> None:
        self.url = url.rstrip("/")
        self.pool = str(entry.get("pool") or "")
        self.version = str(entry.get("version") or "")
        self.pidfile = entry.get("pidfile") or ""
        self.preempt_file = entry.get("preempt_file") or ""
        self.supervisor_pid = int(entry.get("supervisor_pid") or 0)

    def alive(self) -> bool:
        return supervisor_alive(self.supervisor_pid)

    def preempt(self) -> None:
        if not self.preempt_file:
            raise RuntimeError(f"{self.url}: no maintenance file to write")
        tmp = f"{self.preempt_file}.tmp"
        with open(tmp, "w") as f:
            f.write("preempted by reconciler")
        os.replace(tmp, self.preempt_file)

    def clear_preemption(self) -> None:
        try:
            os.unlink(self.preempt_file)
        except OSError:
            pass

    def shutdown(self, timeout_s: float = 10.0) -> str:
        """SIGTERM the supervisor (it forwards to the child and deregisters
        itself from the manifest on exit); escalate to SIGKILL past the
        timeout."""
        if not self.alive():
            return ""
        try:
            os.kill(self.supervisor_pid, signal.SIGTERM)
        except OSError:
            return ""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self.alive():
                return ""
            time.sleep(0.05)
        try:
            os.kill(self.supervisor_pid, signal.SIGKILL)
        except OSError:
            pass
        return ""


def load_or_rebuild(
    state_dir: str, metrics: ControlPlaneMetrics
) -> StateStore:
    """Load the journal strictly; on ANY corruption, count a rebuild and
    start from empty state (the caller re-seeds desired state from what it
    OBSERVES running). The damaged files are kept aside as `.corrupt` —
    detected and quarantined, never silently replayed, never a crash
    loop."""
    try:
        return StateStore.load(state_dir)
    except StateCorruptError as exc:
        logger.error(
            "state journal corrupt (%s); rebuilding desired state from "
            "observation", exc,
        )
        metrics.journal_rebuilds_total += 1
        return StateStore.fresh(state_dir)


class Reconciler:
    """Converges observed fleet membership onto the journaled desired
    state through a FleetController's actuators, one `step()` at a time.

    Each step: (1) hold/renew the lease (standby short-circuits; a
    controller deposed mid-reign books a fencing rejection and demotes);
    (2) adopt manifest orphans into their pools and prune dead entries;
    (3) converge pool target sizes and populations (all spawns fenced);
    (4) publish per-pool drift. Everything is event-loop-confined, like
    the controller it drives."""

    def __init__(
        self,
        controller,
        store: StateStore,
        lease: Optional[LeaderLease] = None,
        manifest: Optional[EndpointsManifest] = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        metrics: Optional[ControlPlaneMetrics] = None,
    ) -> None:
        self.controller = controller
        self.store = store
        self.lease = lease
        self.manifest = manifest
        self.interval_s = interval_s
        self.metrics = metrics if metrics is not None else ControlPlaneMetrics()
        self.was_leading = False
        self._task: Optional[asyncio.Task] = None
        self._client = None

    # ---- fencing ----

    @property
    def leading(self) -> bool:
        return self.lease.leading if self.lease is not None else True

    def fence(self) -> int:
        """The actuation-boundary check every mutation goes through
        (installed as `controller.fence`, wrapped around spawners): the
        current fencing epoch, or a counted StaleLeaderError for a deposed
        controller."""
        if self.lease is None:
            return 0
        try:
            return self.lease.check()
        except StaleLeaderError:
            self.metrics.fencing_rejections_total += 1
            raise

    def fenced_spawner(self, spawner: Callable) -> Callable:
        """Wrap a member spawner: refuse when deposed, count when it
        runs — the `spawns_total` the drill uses to prove 0 double-spawns
        after adoption."""

        def spawn():
            self.fence()
            member = spawner()
            self.metrics.spawns_total += 1
            return member

        return spawn

    # ---- adoption ----

    def adopt_existing(self) -> int:
        """Pre-start adoption: push a ManifestHandle for every still-live
        manifest entry into its pool's spec.handles, so
        `FleetController.start()` adopts them FIRST and spawns only the
        genuinely missing remainder. This is what makes a controller
        restart free of double-spawns."""
        if self.manifest is None:
            return 0
        adopted = 0
        for url, entry in sorted(self.manifest.entries().items()):
            handle = ManifestHandle(url, entry)
            if not handle.alive():
                continue  # step() prunes; don't mutate the manifest here
            fp = self.controller.pools.get(handle.pool)
            if fp is None or fp.member_for(url) is not None:
                continue
            if any(h.url.rstrip("/") == handle.url for h in fp.spec.handles):
                continue
            fp.spec.handles.append(handle)
            if handle.preempt_file and os.path.exists(handle.preempt_file):
                # a storm marker that outlived its controller: the storm is
                # over once a new controller owns the fleet — clear it so
                # the restarted child doesn't re-preempt itself forever
                handle.clear_preemption()
            if handle.version:
                fp.pool.set_version(url, handle.version)
            adopted += 1
            self.metrics.adoptions_total += 1
            logger.info(
                "adopting orphan %s into pool %s (supervisor pid %d)",
                url, handle.pool, handle.supervisor_pid,
            )
        return adopted

    async def _adopt_orphans(self) -> None:
        """Steady-state adoption + manifest pruning: entries that appeared
        since start (a supervisor another actor spawned) are adopted;
        entries whose supervisor died are pruned once no pool claims
        them."""
        if self.manifest is None:
            return
        known = {
            m.url
            for fp in self.controller.pools.values()
            for m in fp.members
        }
        for url, entry in sorted(self.manifest.entries().items()):
            handle = ManifestHandle(url, entry)
            if not handle.alive():
                if url not in known:
                    self.manifest.remove(url)
                    self.metrics.manifest_pruned_total += 1
                continue
            if url in known or handle.pool not in self.controller.pools:
                continue
            self.fence()
            if self.controller.adopt_endpoint(
                handle.pool, handle, version=handle.version
            ):
                if handle.preempt_file and os.path.exists(
                    handle.preempt_file
                ):
                    handle.clear_preemption()
                self.metrics.adoptions_total += 1
                identity = await self.probe_identity(url)
                logger.info(
                    "adopted orphan %s into pool %s (identity: %s)",
                    url, handle.pool, identity,
                )

    async def probe_identity(self, url: str) -> Optional[dict]:
        """The /healthz identity block (replica_id, version,
        weights_digest, pool — PR 12/15): confirms WHAT was adopted.
        Best-effort — a member mid-restart answers later; adoption is
        gated on the supervisor, not the child."""
        try:
            import httpx

            if self._client is None:
                self._client = httpx.AsyncClient(
                    timeout=IDENTITY_PROBE_TIMEOUT_S
                )
            resp = await self._client.get(f"{url}/healthz")
            body = resp.json()
            return {
                "pool": body.get("pool"),
                **(body.get("replica") or {}),
            }
        except Exception:
            return None

    # ---- convergence ----

    async def _converge(self) -> None:
        for name, spec in dict(self.store.state["pools"]).items():
            fp = self.controller.pools.get(name)
            if fp is None:
                continue  # not a pool this controller actuates (e.g. the
                # rollout-managed pool — drift still covers it via spec)
            size = spec.get("size")
            if size is not None and int(size) != fp.spec.target_size:
                self.fence()
                await self.controller.set_target_size(name, int(size))
            self.controller.ensure_population(name)

    def compute_drift(self) -> dict:
        """Per-pool desired-vs-ready drift (positive = under-provisioned),
        published via metrics, /healthz, and fleet_top."""
        now = time.monotonic()
        detail = {}
        for name, fp in self.controller.pools.items():
            desired = int(
                (self.store.state["pools"].get(name) or {}).get(
                    "size", fp.spec.target_size
                )
            )
            ready = fp.member_states(now).get("ready", 0)
            detail[name] = {
                "desired": desired,
                "ready": ready,
                "drift": desired - ready,
            }
        self.metrics.set_drift(
            {name: d["drift"] for name, d in detail.items()}, detail
        )
        return detail

    # ---- the loop ----

    async def step(self) -> str:
        """One reconcile round; returns "leading" or "standby"."""
        self.metrics.reconcile_loops_total += 1
        if self.lease is not None:
            acquired = False
            try:
                acquired = self.lease.try_acquire()
            except OSError:
                logger.exception("lease acquisition failed")
            if not acquired:
                if self.was_leading:
                    # deposed mid-reign (paused past TTL, another controller
                    # took over): the round in flight dies at the fencing
                    # check — counted, demoted, never actuated
                    try:
                        self.fence()
                    except StaleLeaderError:
                        logger.warning(
                            "deposed: fencing epoch superseded; demoting"
                        )
                    self.was_leading = False
                return "standby"
            self.was_leading = True
        try:
            await self._adopt_orphans()
            await self._converge()
        except StaleLeaderError:
            # fence() already counted it; this controller stops acting now
            self.was_leading = False
            return "standby"
        self.compute_drift()
        return "leading"

    async def _run(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("reconcile step failed")
            await asyncio.sleep(self.interval_s)

    def start(self) -> asyncio.Task:
        if self._task is None:
            self._task = asyncio.create_task(self._run())
        return self._task

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._client is not None:
            await self._client.aclose()
            self._client = None

    # ---- observability ----

    def snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap.update(
            {
                "leader": self.leading,
                "epoch": self.lease.epoch if self.lease is not None else 0,
                "owner": self.lease.owner if self.lease is not None else "",
            }
        )
        return snap


def healthz_block(reconciler: Optional["Reconciler"]) -> dict:
    """The leadership + drift block /healthz grows on reconciler-wired
    edges (router.py, fleet.py) — None-safe so unwired edges stay
    byte-identical."""
    if reconciler is None:
        return {}
    snap = reconciler.snapshot()
    return {
        "control_plane": {
            "leader": snap["leader"],
            "epoch": snap["epoch"],
            "drift": snap["drift"],
            "converged": snap["converged"],
        }
    }


# ---- standalone controller process (the drill target) ----


def parse_pool_args(pairs: list[str], flag: str = "--pool") -> dict[str, int]:
    pools: dict[str, int] = {}
    for pair in pairs or []:
        name, sep, size = pair.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(f"bad {flag} {pair!r}: expected NAME=SIZE")
        try:
            pools[name] = int(size)
        except ValueError:
            raise ValueError(f"bad {flag} {pair!r}: SIZE must be int") from None
    return pools


def _alive_entries(manifest: EndpointsManifest) -> dict:
    return {
        url: e
        for url, e in manifest.entries().items()
        if supervisor_alive(int(e.get("supervisor_pid") or 0))
    }


def _seed_desired(
    store: StateStore,
    manifest: EndpointsManifest,
    pool_sizes: dict[str, int],
    serve_pool: str,
    serve_size: int,
    serve_version: str,
) -> None:
    """First boot or post-corruption: desired state comes from OBSERVATION
    first (live manifest counts), CLI seed second — a corrupt journal next
    to a healthy running fleet converges to the fleet, not to replayed or
    default intent."""
    observed: dict[str, int] = {}
    for _url, entry in _alive_entries(manifest).items():
        pool = str(entry.get("pool") or "")
        observed[pool] = observed.get(pool, 0) + 1
    for name, size in pool_sizes.items():
        store.set_pool(name, size=observed.get(name) or size, **{"class": name})
    if serve_pool:
        store.set_pool(
            serve_pool,
            size=observed.get(serve_pool) or serve_size,
            version=serve_version,
        )


def _flip_journal_byte(state_dir: str) -> bool:
    """The `journal_corrupt` fault: flip one byte mid-journal on disk so
    the NEXT controller's load fails the CRC (detected, quarantined,
    rebuilt from observation — never silently replayed)."""
    path = os.path.join(state_dir, JOURNAL_NAME)
    try:
        with open(path, "r+b") as f:
            blob = bytearray(f.read())
            if not blob:
                return False
            idx = len(blob) // 2
            blob[idx] ^= 0xFF
            f.seek(0)
            f.write(bytes(blob))
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        return False
    logger.error("journal_corrupt fault: flipped a byte of %s", path)
    return True


async def _amain(args) -> int:
    from spotter_tpu.serving import rollout as rollout_mod
    from spotter_tpu.serving.fleet import FleetController, PoolSpec
    from spotter_tpu.serving.replica_pool import ReplicaPool
    from spotter_tpu.testing import cluster, faults

    os.makedirs(args.state_dir, exist_ok=True)
    workdir = args.workdir or args.state_dir
    os.makedirs(workdir, exist_ok=True)
    metrics = ControlPlaneMetrics()
    manifest = EndpointsManifest(args.manifest)
    lease = LeaderLease(
        os.path.join(args.state_dir, "leader.lease"),
        owner=args.owner,
        ttl_s=args.lease_ttl,
    )
    status_path = args.status_file or os.path.join(
        args.state_dir, f"status-{args.owner}.json"
    )
    stop_event = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop_event.set)

    def write_status(phase: str, extra: Optional[dict] = None) -> None:
        payload = {
            "pid": os.getpid(),
            "owner": args.owner,
            "phase": phase,
            "leader": lease.leading,
            "epoch": lease.epoch,
            "reconcile": metrics.snapshot(),
            "ts": time.time(),
        }
        if extra:
            payload.update(extra)
        try:
            _atomic_write(
                status_path, json.dumps(payload, sort_keys=True).encode()
            )
        except OSError:
            logger.exception("writing status failed")

    # -- standby: wait for the lease (the passive half of active-passive) --
    while not stop_event.is_set():
        if lease.try_acquire():
            break
        write_status("standby")
        try:
            await asyncio.wait_for(stop_event.wait(), args.tick)
        except asyncio.TimeoutError:
            pass
    if stop_event.is_set():
        write_status("stopped")
        return 0
    logger.info("%s leading with fencing epoch %d", args.owner, lease.epoch)

    # -- desired state: journal, or rebuild from observation --
    store = load_or_rebuild(args.state_dir, metrics)
    pool_sizes = parse_pool_args(args.pool)
    if not store.state["pools"]:
        _seed_desired(
            store, manifest, pool_sizes, args.serve_pool,
            args.serve_size, args.serve_version,
        )

    # -- fleet controller over the journaled pools (minus the rollout's) --
    member_env = {}
    if args.member_env:
        member_env = dict(
            pair.split("=", 1) for pair in args.member_env.split(",") if pair
        )
    specs = []
    for name, spec in store.state["pools"].items():
        if name == args.serve_pool:
            continue
        specs.append(
            PoolSpec(
                name,
                spawner=cluster.fleet_spawner(
                    workdir, name, env=member_env, manifest=args.manifest
                ),
                target_size=int(spec.get("size") or 0),
            )
        )
    controller = None
    reconciler = None
    if specs:
        controller = FleetController(specs, tick_s=args.tick)
        reconciler = Reconciler(
            controller, store, lease=lease, manifest=manifest,
            interval_s=args.tick, metrics=metrics,
        )
        controller.fence = reconciler.fence
        for spec in specs:
            spec.spawner = reconciler.fenced_spawner(spec.spawner)
        adopted = reconciler.adopt_existing()
        logger.info("pre-start adoption: %d members", adopted)
        await controller.start()
        reconciler.start()

    # -- rollout: resume the journaled wave, or start a requested one --
    serve_rp = None
    rollout_ctl = None
    rollout_task = None
    if args.serve_pool:
        serve_entries = {
            url: e
            for url, e in _alive_entries(manifest).items()
            if e.get("pool") == args.serve_pool
        }
        serve_rp = ReplicaPool(list(serve_entries), allow_empty=True)
        for url, entry in serve_entries.items():
            if entry.get("version"):
                serve_rp.set_version(url, str(entry["version"]))
        # serve members found in the manifest are adoptions too — the
        # rollout pool's members survived the previous controller
        metrics.adoptions_total += len(serve_entries)
        await serve_rp.start()
        plan = rollout_mod.resume_plan(store.state.get("rollout"))
        version_to = (plan or {}).get("version_to") or args.rollout_to
        versions = {str(e.get("version") or "") for e in serve_entries.values()}
        if version_to and (plan or versions != {version_to}):
            canary_url = (plan or {}).get("canary_url")
            old = [
                rollout_mod.RolloutMember(
                    url=url,
                    handle=ManifestHandle(url, entry),
                    version=str(entry.get("version") or ""),
                )
                for url, entry in sorted(serve_entries.items())
                if url != canary_url
                and str(entry.get("version") or "") != version_to
            ]
            resume = None
            resume_handle = None
            if plan is not None:
                if canary_url and canary_url in serve_entries:
                    resume_handle = ManifestHandle(
                        canary_url, serve_entries[canary_url]
                    )
                else:
                    canary_url = None  # canary died with the controller:
                    # restart the wave from a fresh spawn
                resume = {
                    "wave": int(plan.get("wave") or 0),
                    "canary_url": canary_url,
                    "window_s": plan.get("window_s"),
                    "expired": plan.get("action") == "rollback",
                }
                metrics.rollout_resumes_total += 1
                logger.info("resuming journaled rollout: %s", plan)
            spawner = cluster.rollout_spawner(
                workdir, version_to, pool=args.serve_pool,
                env=member_env, manifest=args.manifest,
            )
            if reconciler is not None:
                spawner = reconciler.fenced_spawner(spawner)
            rollout_ctl = rollout_mod.RolloutController(
                serve_rp,
                old,
                spawner,
                version_to,
                version_from=args.serve_version,
                window_s=args.rollout_window,
                confirm_window_s=args.rollout_window,
                min_requests=args.rollout_min_requests,
                spawn_wait_s=args.spawn_wait,
                drain_deadline_ms=args.drain_ms,
                store=store,
                resume=resume,
                resume_handle=resume_handle,
            )
            rollout_task = asyncio.create_task(rollout_ctl.run())

    # -- autoscale actuation seam (ISSUE 20): once the initial population
    # converges, apply --scale-pool sizes through the brain's fenced +
    # journaled path — the chaos harness times a kill -9 against this to
    # prove a successor adopts mid-scale-up instead of double-spawning --
    scale_sizes = parse_pool_args(args.scale_pool, flag="--scale-pool")
    scale_sizes = {
        n: s for n, s in scale_sizes.items()
        if controller is not None and n in controller.pools
    }
    scale_brain = None
    if scale_sizes:
        from spotter_tpu.serving.autoscale import AutoscalerBrain, ModelPool

        scale_brain = AutoscalerBrain(
            controller,
            [
                ModelPool(model=n, max_size=max(s, 1))
                for n, s in scale_sizes.items()
            ],
            store=store,
            fence=reconciler.fence if reconciler is not None else None,
        )
    scaled = False

    # -- run until told to stop --
    rollout_result = None
    while not stop_event.is_set():
        # control-plane chaos seams (ISSUE 16): a deterministic kill -9 at
        # a chosen tick, and a one-shot journal byte-flip the NEXT load
        # must detect. Checked first so the crash lands mid-cycle, with
        # journaled state exactly as a real kill would leave it.
        if faults.take_journal_corrupt():
            _flip_journal_byte(args.state_dir)
        if faults.take_controller_crash():
            logger.error("controller_crash fault: SIGKILL self (pid %d)",
                         os.getpid())
            os.kill(os.getpid(), signal.SIGKILL)
        if rollout_task is not None and rollout_task.done():
            try:
                rollout_result = rollout_task.result()
            except Exception as exc:
                rollout_result = f"error: {exc!r}"
                logger.exception("rollout task failed")
            rollout_task = None
            # the rollout reached a terminal state: fold the journal into
            # a fresh snapshot (the compaction path, exercised live)
            try:
                store.compact()
            except OSError:
                logger.exception("journal compaction failed")
        if reconciler is None and lease is not None:
            # rollout-only controller still heartbeats its lease
            lease.try_acquire()
        if scale_brain is not None and not scaled:
            converged = all(
                controller.pools[n].pool.has_available()
                and len(controller.pools[n].members)
                >= controller.pools[n].spec.target_size
                for n in scale_sizes
            )
            if converged:
                try:
                    for n, s in scale_sizes.items():
                        scale_brain.actuate(n, s, "drill: --scale-pool")
                    scaled = True
                except Exception:
                    logger.exception("--scale-pool actuation failed")
                    scaled = True  # fenced-out or broken: do not retry-spam
        extra = {
            "rollout": rollout_ctl.snapshot() if rollout_ctl else None,
            "rollout_result": rollout_result,
            "fleet": controller.snapshot() if controller else None,
            "seq": store.seq,
            "scaled": scaled,
        }
        write_status("leading" if lease.leading else "deposed", extra)
        try:
            await asyncio.wait_for(stop_event.wait(), args.tick)
        except asyncio.TimeoutError:
            pass

    # -- clean stop: members OUTLIVE the controller (that is the point) --
    if rollout_task is not None:
        rollout_task.cancel()
        try:
            await rollout_task
        except (asyncio.CancelledError, Exception):
            pass
    if rollout_ctl is not None:
        await rollout_ctl.stop()
    if serve_rp is not None:
        await serve_rp.stop()
    if reconciler is not None:
        await reconciler.stop()
    if controller is not None:
        await controller.stop(shutdown_members=args.shutdown_members)
    lease.release()
    write_status("stopped", {"rollout_result": rollout_result})
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="spotter-tpu crash-safe fleet controller "
        "(durable desired state + reconcile loop + leader lease)"
    )
    parser.add_argument("--state-dir", required=True,
                        help="journal/snapshot/lease directory")
    parser.add_argument("--manifest", required=True,
                        help="endpoints manifest path (shared with supervisors)")
    parser.add_argument("--workdir", default=None,
                        help="member pidfiles/logs (default: state dir)")
    parser.add_argument("--owner", default=f"ctrl-{os.getpid()}",
                        help="lease owner name (status file suffix)")
    parser.add_argument("--lease-ttl", type=float, default=2.0)
    parser.add_argument("--tick", type=float, default=DEFAULT_INTERVAL_S)
    parser.add_argument("--status-file", default=None)
    parser.add_argument("--pool", action="append", default=[],
                        metavar="NAME=SIZE",
                        help="fleet-managed pool seed (repeatable)")
    parser.add_argument("--scale-pool", action="append", default=[],
                        metavar="NAME=SIZE",
                        help="after initial convergence, scale this pool to "
                        "SIZE through the fenced+journaled autoscaler path "
                        "(repeatable; the crash-mid-scale drill seam)")
    parser.add_argument("--serve-pool", default="",
                        help="rollout-managed pool name (not fleet-spawned)")
    parser.add_argument("--serve-size", type=int, default=0)
    parser.add_argument("--serve-version", default="")
    parser.add_argument("--rollout-to", default="",
                        help="start (or resume) a rollout to this version")
    parser.add_argument("--rollout-window", type=float, default=8.0)
    parser.add_argument("--rollout-min-requests", type=int, default=0)
    parser.add_argument("--spawn-wait", type=float, default=30.0)
    parser.add_argument("--drain-ms", type=float, default=1000.0)
    parser.add_argument("--member-env", default="",
                        help="extra child env as K=V[,K=V...]")
    parser.add_argument("--shutdown-members", action="store_true",
                        help="tear the fleet down on clean exit (default: "
                        "members outlive the controller)")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s {args.owner} %(levelname)s %(name)s: %(message)s",
    )
    from spotter_tpu.testing import faults

    plan = faults.maybe_activate_from_env()
    if plan is not None:
        logger.warning("CONTROLLER FAULT PLAN ACTIVE: %s", plan)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
