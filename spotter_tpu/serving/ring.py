"""Rendezvous (highest-random-weight) hashing for cache-affinity routing.

PR 5's result cache is per-replica, so a router that picks replicas blind
to keys decays the fleet hit rate ~1/N as replicas scale (ROADMAP item 4;
DeepServe makes the same point for serverless LLM state). HRW fixes that
with no ring state to maintain: every member gets a deterministic
pseudo-random weight per key (`blake2b(member "|" key)`), the key's owner
is the highest weight, and the full weight ordering IS the failover plan —
when the owner is ejected or draining, the next-highest member takes the
key, and ONLY that key's traffic moves. Membership churn has the same
property: adding or removing one of N members remaps ~1/N of the key space
(the keys the new member now wins / the dead member owned) and leaves
every other key exactly where it was, so the surviving replicas keep their
warm caches through a preemption storm.

Chosen over a vnode consistent-hash ring because the member counts here
are small (a handful of replicas per pool): HRW is exactly balanced with
zero tuning, needs no virtual-node bookkeeping, and `ranked()` falls out
for free as the failover order. Scoring is O(members) per key — at fleet
sizes of 2-64 that is nanoseconds against a millisecond HTTP hop.

Stdlib-only and jax-free on purpose: the router process imports this.
"""

import hashlib


def _score(member: str, key: str) -> int:
    """Deterministic 64-bit weight of `member` for `key`. blake2b rather
    than Python's `hash()`: stable across processes and PYTHONHASHSEED, so
    every router replica computes the same placement."""
    h = hashlib.blake2b(digest_size=8)
    h.update(member.encode("utf-8", "surrogatepass"))
    h.update(b"|")
    h.update(key.encode("utf-8", "surrogatepass"))
    return int.from_bytes(h.digest(), "big")


class RendezvousRing:
    """Immutable member set with per-key ownership ranking. Rebuild on
    membership change (the router watches the pool and counts churn);
    rebuilding is just storing the new tuple — all state is derived."""

    def __init__(self, members: list[str]) -> None:
        # sorted + deduped: placement must not depend on discovery order
        self.members: tuple[str, ...] = tuple(sorted(set(members)))

    def ranked(self, key: str) -> list[str]:
        """Every member, highest weight first — index 0 is the owner, the
        rest is the deterministic failover order for this key. Ties (a
        64-bit collision) break on the member string so the order is still
        total and identical everywhere."""
        return sorted(
            self.members, key=lambda m: (_score(m, key), m), reverse=True
        )

    def owner(self, key: str) -> str | None:
        if not self.members:
            return None
        return max(self.members, key=lambda m: (_score(m, key), m))
