from spotter_tpu.serving.detector import AmenitiesDetector  # noqa: F401
