"""Serving package. `AmenitiesDetector` is re-exported lazily (PEP 562):
`engine.batcher` imports `serving.resilience`, and an eager detector import
here would close a cycle (detector -> batcher -> serving package init ->
detector) whenever the batcher is imported before the serving package."""


def __getattr__(name: str):
    if name == "AmenitiesDetector":
        from spotter_tpu.serving.detector import AmenitiesDetector

        return AmenitiesDetector
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
