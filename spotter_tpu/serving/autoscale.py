"""Model-multiplexed autoscaling: one fleet, per-model pools, live signals.

ROADMAP item 2's control loop, assembled from parts every prior arc built:
PR 12's FleetAggregator computes the scaling signals (queue depth, fast-window
SLO burn, cache-miss rate), PR 15/16's reconcile plane is the crash-safe
actuator (journaled desired sizes, leader-fenced spawns), PR 6 proved
scale-to-zero with compile-cache restore, and PR 13 made dp×tp pool shape a
per-model decision. DeepServe (arXiv:2501.14417) is the blueprint: a shared
fleet serves many models, each model family gets its own pool, and pool sizes
follow live demand instead of static provisioning.

Two halves:

- **Model routing** (`AutoscalerBrain.route`): every /detect request resolves
  to a model pool — `X-Spotter-Model` header first, then a `model` payload
  key (stripped before forwarding, like `request_class`), then `queries`
  presence (open-vocabulary detection needs an OWL-ViT-capable pool), then
  the fleet's default pool. Names resolve through the same
  earliest-start-then-longest substring scoring as `models/registry.py`, so
  "dab-detr-resnet-50" lands on the dab_detr pool, not plain detr. Unknown
  models and `queries` against a closed-set-only fleet are 400s that NAME the
  registry — a client can self-correct from the error body alone.
- **Scaling policy** (`AutoscalerBrain.step`): per pool, desired size follows
  (1) edge demand the brain counts itself at route time — only ADMITTED
  requests, which is what makes the loop flood-proof: `TenantPlane` sheds
  over-quota traffic 429 before routing, so a flood never shows up as demand;
  (2) aggregator boosters — summed `decode_pool_queue_depth`, fast-window
  `slo_burn_rate` > 1, cache-miss rate; (3) `TenantPlane.metrics_view()` shed
  pressure as a guard: when sheds are rising and in-quota signals are flat,
  the brain records an explicit hold (`flood_suppressions_total`) instead of
  scaling — quotas hold abusive load flat, the scaler serves what the quotas
  admit. Idle pools step down and eventually scale to zero through the
  controller's idle timer; the next routed request wakes them and the cold
  restore (persistent compile cache) is measured per restore as
  `time_to_ready_s`.

Every actuation is leader-fenced (the reconciler's fence raises
StaleLeaderError for a deposed controller) and journaled through
`statestore.py` BEFORE the controller's target changes, so a kill -9
mid-scale-up leaves a successor that adopts live members and converges to
the journaled size — never a double-spawn.
"""

import asyncio
import logging
import os
import time
from dataclasses import dataclass
from typing import Callable, Optional

from spotter_tpu.models.registry import match_score

logger = logging.getLogger(__name__)

MODEL_HEADER = "X-Spotter-Model"
MODEL_KEY = "model"

# The zoo's open-vocabulary-capable families (text queries at inference).
OPEN_VOCAB_FAMILIES = ("owlvit",)

# Per-family pool shape (ISSUE 20d): big dual-tower models shard tp over the
# PR 13 mesh; small single-tower detectors pack dp replicas instead.
POOL_SHAPES: dict[str, tuple[int, int]] = {
    "owlvit": (2, 1),           # CLIP towers shard cleanly over tp=2
    "deformable_detr": (2, 1),  # heaviest closed-set family in the zoo
}
DEFAULT_SHAPE = (1, 2)

TICK_ENV = "SPOTTER_TPU_AUTOSCALE_TICK_S"
MAX_SIZE_ENV = "SPOTTER_TPU_AUTOSCALE_MAX_SIZE"
QUEUE_HIGH_ENV = "SPOTTER_TPU_AUTOSCALE_QUEUE_HIGH"
BURN_HIGH_ENV = "SPOTTER_TPU_AUTOSCALE_BURN_HIGH"
MISS_HIGH_ENV = "SPOTTER_TPU_AUTOSCALE_MISS_HIGH"
INFLIGHT_HIGH_ENV = "SPOTTER_TPU_AUTOSCALE_INFLIGHT_HIGH"
DOWN_STEPS_ENV = "SPOTTER_TPU_AUTOSCALE_DOWN_STEPS"

DEFAULT_TICK_S = 1.0
DEFAULT_MAX_SIZE = 4
DEFAULT_QUEUE_HIGH = 4.0       # queued items per ready replica
DEFAULT_BURN_HIGH = 1.0        # fast-window burn > 1 = eating error budget
DEFAULT_MISS_HIGH = 0.5        # cache-miss rate marking a cold working set
DEFAULT_INFLIGHT_HIGH = 2.0    # edge in-flight per ready replica
DEFAULT_DOWN_STEPS = 3         # consecutive idle decides before stepping down


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


class ModelRoutingError(ValueError):
    """A request the model router cannot place. Always a client error (400)
    with a structured body that NAMES the registry, so the caller can fix
    the request without reading server logs."""

    status = 400
    kind = "model_routing"

    def __init__(self, message: str, families: dict[str, tuple]) -> None:
        super().__init__(message)
        self.families = {k: list(v) for k, v in families.items()}


class UnknownModelError(ModelRoutingError):
    kind = "unknown_model"


class ClosedSetQueriesError(ModelRoutingError):
    """`queries` (open-vocabulary text prompts) sent to a fleet — or an
    explicitly-named model — that only serves closed-set detectors."""

    kind = "closed_set_queries"


@dataclass(frozen=True)
class ModelPool:
    """One model family's pool: routing patterns + shape + size bounds.
    The pool name doubles as the FleetController pool name."""

    model: str                     # family name (models/registry.py)
    matches: tuple = ()            # substrings of MODEL_NAME that select it
    open_vocab: bool = False       # can serve `queries` (OWL-ViT lineage)
    tp: int = 1                    # tensor-parallel ways per member
    dp: int = 1                    # data-parallel replicas per member
    min_size: int = 0              # floor the brain never steps below
    max_size: int = DEFAULT_MAX_SIZE
    default: bool = False          # unrouted traffic lands here

    @property
    def name(self) -> str:
        return self.model

    @property
    def chips_per_member(self) -> int:
        return max(self.tp, 1) * max(self.dp, 1)


def pool_shape(family_name: str) -> tuple[int, int]:
    """(tp, dp) for one family — POOL_SHAPES with a dp-packing default."""
    return POOL_SHAPES.get(family_name, DEFAULT_SHAPE)


def model_pools_from_registry(
    max_size: Optional[int] = None, default_family: str = "rtdetr"
) -> list[ModelPool]:
    """One ModelPool per registered zoo family. Lazy zoo import (jax/PIL) —
    tests and the CPU bench construct explicit ModelPool lists instead."""
    from spotter_tpu.models import zoo  # noqa: F401  (self-registers families)
    from spotter_tpu.models.registry import MODEL_REGISTRY

    cap = max_size if max_size is not None else _env_int(
        MAX_SIZE_ENV, DEFAULT_MAX_SIZE
    )
    pools = []
    names = list(MODEL_REGISTRY)
    default = default_family if default_family in names else names[0]
    for name, family in MODEL_REGISTRY.items():
        tp, dp = pool_shape(name)
        pools.append(
            ModelPool(
                model=name,
                matches=tuple(family.matches),
                open_vocab=name in OPEN_VOCAB_FAMILIES,
                tp=tp,
                dp=dp,
                max_size=cap,
                default=name == default,
            )
        )
    return pools


@dataclass
class ScaleDecision:
    """One applied (or explicitly held) sizing decision, kept per pool for
    /metrics and fleet_top."""

    pool: str
    current: int
    desired: int
    reason: str
    at: float = 0.0


class _Track:
    """Edge in-flight tracking for one routed request. `done` is idempotent
    (the handler calls it with the real status AND from a finally leak
    guard, mirroring the tenancy admission discipline)."""

    __slots__ = ("_brain", "_pool", "_done")

    def __init__(self, brain: "AutoscalerBrain", pool: str) -> None:
        self._brain = brain
        self._pool = pool
        self._done = False

    def done(self, status: Optional[int] = None) -> None:
        if self._done:
            return
        self._done = True
        st = self._brain._pool_state[self._pool]
        st["inflight"] = max(st["inflight"] - 1, 0)
        if status is not None:
            if 200 <= status < 500 and status not in (429, 503):
                st["ok_total"] += 1
            else:
                st["fail_total"] += 1


class AutoscalerBrain:
    """Per-model-pool routing + scaling over a FleetController.

    The brain owns no replicas: the controller is the actuator (spawn,
    retire, scale-to-zero, restore), the state store is the intent journal,
    and the fence is the leadership check. `step()` is one decision round —
    the background loop calls it every `tick_s`; deterministic tests call it
    directly."""

    def __init__(
        self,
        controller,
        pools: list[ModelPool],
        aggregator=None,
        tenancy_plane=None,
        store=None,
        fence: Optional[Callable[[], object]] = None,
        tick_s: Optional[float] = None,
        queue_high: Optional[float] = None,
        burn_high: Optional[float] = None,
        miss_high: Optional[float] = None,
        inflight_high: Optional[float] = None,
        down_steps: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not pools:
            raise ValueError("AutoscalerBrain needs at least one ModelPool")
        self.controller = controller
        self.pools: dict[str, ModelPool] = {}
        for p in pools:
            if p.name in self.pools:
                raise ValueError(f"duplicate model pool {p.name!r}")
            if p.name not in controller.pools:
                raise ValueError(
                    f"model pool {p.name!r} has no FleetController pool"
                )
            self.pools[p.name] = p
        self.aggregator = aggregator
        self.tenancy_plane = tenancy_plane
        self.store = store
        self.fence = fence
        self.tick_s = tick_s if tick_s is not None else _env_float(
            TICK_ENV, DEFAULT_TICK_S
        )
        self.queue_high = queue_high if queue_high is not None else _env_float(
            QUEUE_HIGH_ENV, DEFAULT_QUEUE_HIGH
        )
        self.burn_high = burn_high if burn_high is not None else _env_float(
            BURN_HIGH_ENV, DEFAULT_BURN_HIGH
        )
        self.miss_high = miss_high if miss_high is not None else _env_float(
            MISS_HIGH_ENV, DEFAULT_MISS_HIGH
        )
        self.inflight_high = (
            inflight_high if inflight_high is not None
            else _env_float(INFLIGHT_HIGH_ENV, DEFAULT_INFLIGHT_HIGH)
        )
        self.down_steps = down_steps if down_steps is not None else _env_int(
            DOWN_STEPS_ENV, DEFAULT_DOWN_STEPS
        )
        self._clock = clock
        self._default = next(
            (p for p in self.pools.values() if p.default),
            next(iter(self.pools.values())),
        )
        self._open_vocab = next(
            (p for p in self.pools.values() if p.open_vocab), None
        )
        self._pool_state: dict[str, dict] = {
            name: {
                "admits_total": 0,
                "ok_total": 0,
                "fail_total": 0,
                "inflight": 0,
                "last_admits": 0,
                "idle_streak": 0,
                "last_decision": None,
            }
            for name in self.pools
        }
        self._last_step = self._clock()
        self._last_sheds: Optional[float] = None
        self._task: Optional[asyncio.Task] = None
        # counters (the `autoscale` /metrics block)
        self.decisions_total = 0
        self.scale_ups_total = 0
        self.scale_downs_total = 0
        self.wakes_total = 0
        self.flood_suppressions_total = 0
        self.routing_rejections_total = 0

    # ---- model routing (the data plane half) ----

    def _families(self) -> dict[str, tuple]:
        return {p.model: p.matches for p in self.pools.values()}

    def resolve_model(self, name: str) -> Optional[ModelPool]:
        """Pool for one model name: exact family-name match first (so bare
        "rtdetr" works), then the registry's earliest-start-then-longest
        substring scoring over each pool's patterns."""
        key = name.strip().lower()
        if not key:
            return None
        if key in self.pools:
            return self.pools[key]
        best = None
        best_score = None
        for p in self.pools.values():
            score = match_score(key, tuple(p.matches))
            if score is not None and (best_score is None or score < best_score):
                best, best_score = p, score
        return best

    def route(self, headers=None, payload=None) -> tuple[str, dict]:
        """(pool_name, forwardable_payload). Precedence: X-Spotter-Model
        header, `model` payload key (stripped — routing metadata, not
        detector input), `queries` presence -> the open-vocab pool, default
        pool. Raises ModelRoutingError subclasses for unplaceable requests;
        counts admitted demand and wakes scaled-to-zero pools."""
        name = ""
        if headers is not None:
            name = str(headers.get(MODEL_HEADER, "")).strip()
        has_queries = isinstance(payload, dict) and bool(payload.get("queries"))
        if isinstance(payload, dict):
            if not name:
                name = str(payload.get(MODEL_KEY, "")).strip()
            if MODEL_KEY in payload:
                payload = {k: v for k, v in payload.items() if k != MODEL_KEY}
        if name:
            pool = self.resolve_model(name)
            if pool is None:
                self.routing_rejections_total += 1
                raise UnknownModelError(
                    f"model '{name}' does not match any pool in this fleet",
                    self._families(),
                )
            if has_queries and not pool.open_vocab:
                self.routing_rejections_total += 1
                raise ClosedSetQueriesError(
                    f"model '{name}' resolves to closed-set family "
                    f"'{pool.model}' but the payload carries open-vocabulary "
                    f"`queries`",
                    self._families(),
                )
        elif has_queries:
            pool = self._open_vocab
            if pool is None:
                self.routing_rejections_total += 1
                raise ClosedSetQueriesError(
                    "payload carries open-vocabulary `queries` but this "
                    "fleet serves closed-set families only",
                    self._families(),
                )
        else:
            pool = self._default
        st = self._pool_state[pool.name]
        st["admits_total"] += 1
        self._maybe_wake(pool)
        return pool.name, payload

    def track(self, pool_name: str) -> _Track:
        st = self._pool_state[pool_name]
        st["inflight"] += 1
        return _Track(self, pool_name)

    # ---- actuation (journal first, fence always) ----

    def _journal(self, pool: ModelPool, size: int) -> None:
        if self.store is None:
            return
        self.store.set_pool(
            pool.name, size=size, model=pool.model, tp=pool.tp, dp=pool.dp
        )

    def _record(self, pool: ModelPool, current: int, desired: int,
                reason: str) -> ScaleDecision:
        dec = ScaleDecision(
            pool=pool.name, current=current, desired=desired,
            reason=reason, at=self._clock(),
        )
        self._pool_state[pool.name]["last_decision"] = dec
        self.decisions_total += 1
        return dec

    def _grow(self, pool: ModelPool, desired: int, reason: str) -> None:
        """Synchronous scale-up: fence, journal intent, raise the target,
        spawn the missing population. Sync so `route()` can wake a cold
        pool in the request path — the demand restore must not wait for
        the next policy tick."""
        fp = self.controller.pools[pool.name]
        current = fp.spec.target_size
        if self.fence is not None:
            self.fence()  # StaleLeaderError for a deposed controller
        self._journal(pool, desired)
        fp.spec.target_size = desired
        if fp.scaled_to_zero or not fp.members:
            # demand restore: the controller measures time_to_ready_s
            # restore-trigger -> first available member
            self.controller._maybe_restore(fp)
        else:
            self.controller.ensure_population(pool.name)
        self._record(pool, current, desired, reason)
        logger.info(
            "autoscale %s: %d -> %d (%s)", pool.name, current, desired, reason
        )

    async def _shrink(self, pool: ModelPool, desired: int, reason: str) -> None:
        current = self.controller.pools[pool.name].spec.target_size
        if self.fence is not None:
            self.fence()
        self._journal(pool, desired)
        await self.controller.set_target_size(pool.name, desired)
        self._record(pool, current, desired, reason)
        logger.info(
            "autoscale %s: %d -> %d (%s)", pool.name, current, desired, reason
        )

    def actuate(self, pool_name: str, size: int, reason: str) -> None:
        """One externally-driven sizing actuation through the full fenced +
        journaled path (the reconcile CLI's --scale-pool seam). Growth only
        spawns; a smaller size journals intent and lets the reconcile loop
        converge the shrink."""
        pool = self.pools[pool_name]
        size = max(min(int(size), pool.max_size), 0)
        fp = self.controller.pools[pool_name]
        if size >= fp.spec.target_size:
            self._grow(pool, size, reason)
        else:
            # journal the shrink intent; the reconcile loop converges it
            current = fp.spec.target_size
            if self.fence is not None:
                self.fence()
            self._journal(pool, size)
            fp.spec.target_size = size
            self._record(pool, current, size, reason)

    def _maybe_wake(self, pool: ModelPool) -> None:
        fp = self.controller.pools[pool.name]
        if fp.spec.spawner is None:
            return
        if fp.spec.target_size > 0 and not fp.scaled_to_zero:
            return
        desired = max(pool.min_size, 1)
        self.wakes_total += 1
        self._grow(pool, max(desired, fp.spec.target_size), "wake: demand after idle")

    # ---- scaling policy (the control loop half) ----

    def _aggregator_signals(self, fp) -> dict:
        """Per-pool sums over the aggregator's member snapshots: queue
        depth, fast-window burn, cache-miss rate. Zeroes when the
        aggregator is off or hasn't scraped — the edge demand counters
        carry the loop alone then."""
        out = {"queue_depth": 0.0, "burn_fast": 0.0, "cache_miss_rate": 0.0}
        agg = self.aggregator
        if agg is None or not getattr(agg, "enabled", False):
            return out
        hits = misses = 0.0
        for m in fp.members:
            snap = agg.member_snapshot(m.url)
            if not snap:
                continue
            qd = snap.get("decode_pool_queue_depth")
            if isinstance(qd, (int, float)):
                out["queue_depth"] += float(qd)
            burn = snap.get("slo_burn_rate")
            if isinstance(burn, dict):
                fast = burn.get("fast")
                if isinstance(fast, (int, float)):
                    out["burn_fast"] = max(out["burn_fast"], float(fast))
            hits += float(snap.get("cache_hits_total") or 0.0)
            misses += float(snap.get("cache_misses_total") or 0.0)
        if hits + misses > 0:
            out["cache_miss_rate"] = misses / (hits + misses)
        return out

    def _shed_pressure(self) -> bool:
        """True while the tenant plane's total shed count is RISING — the
        flood-in-progress marker. Demand already excludes shed traffic;
        this only gates the explicit `flood hold` bookkeeping."""
        if self.tenancy_plane is None:
            return False
        total = 0.0
        for row in self.tenancy_plane.metrics_view().values():
            total += float(row.get("sheds_rate_total", 0.0))
            total += float(row.get("sheds_inflight_total", 0.0))
        last = self._last_sheds
        self._last_sheds = total
        return last is not None and total > last

    async def step(self) -> list[ScaleDecision]:
        """One decision round over every pool. Returns the decisions
        APPLIED this round (holds are recorded in flood counters, not
        returned)."""
        now = self._clock()
        dt = max(now - self._last_step, 1e-6)
        self._last_step = now
        flood = self._shed_pressure()
        applied: list[ScaleDecision] = []
        for name, pool in self.pools.items():
            fp = self.controller.pools[name]
            if fp.spec.spawner is None:
                continue  # static pools are someone else's capacity plan
            st = self._pool_state[name]
            admits = st["admits_total"] - st["last_admits"]
            st["last_admits"] = st["admits_total"]
            demand_rps = admits / dt
            ready = fp.member_states(now)["ready"]
            target = fp.spec.target_size
            sig = self._aggregator_signals(fp)
            inflight = st["inflight"]
            per_ready = max(ready, 1)
            overload = (
                sig["queue_depth"] / per_ready >= self.queue_high
                or sig["burn_fast"] > self.burn_high
                or inflight / per_ready >= self.inflight_high
                or (
                    sig["cache_miss_rate"] >= self.miss_high
                    and sig["queue_depth"] / per_ready >= self.queue_high / 2
                )
            )
            if (target == 0 or fp.scaled_to_zero) and admits > 0:
                # normally route() already woke the pool; this catches
                # demand observed between wake and a racing scale-down
                self._maybe_wake(pool)
                applied.append(st["last_decision"])
                continue
            if overload and target < pool.max_size and ready > 0:
                st["idle_streak"] = 0
                if flood and admits == 0:
                    # shed pressure with no in-quota demand: the overload
                    # signal is the flood knocking, not real work — hold
                    self.flood_suppressions_total += 1
                    self._record(
                        pool, target, target,
                        "hold: sheds rising, no in-quota demand",
                    )
                    continue
                reasons = []
                if sig["queue_depth"] / per_ready >= self.queue_high:
                    reasons.append(f"queue {sig['queue_depth']:.0f}")
                if sig["burn_fast"] > self.burn_high:
                    reasons.append(f"burn {sig['burn_fast']:.2f}")
                if inflight / per_ready >= self.inflight_high:
                    reasons.append(f"inflight {inflight}")
                if sig["cache_miss_rate"] >= self.miss_high:
                    reasons.append(f"miss {sig['cache_miss_rate']:.2f}")
                self._grow(
                    pool, target + 1, "up: " + ", ".join(reasons or ["overload"])
                )
                self.scale_ups_total += 1
                applied.append(st["last_decision"])
                continue
            if flood and admits == 0 and st["inflight"] == 0 and target > 0:
                # flood in progress, this pool has zero in-quota demand:
                # record the hold that proves we never scale INTO a flood
                self.flood_suppressions_total += 1
            floor = max(
                pool.min_size, 1 if fp.scale_to_zero_s > 0 else pool.min_size
            )
            if admits == 0 and inflight == 0 and target > floor:
                st["idle_streak"] += 1
                if st["idle_streak"] >= self.down_steps:
                    st["idle_streak"] = 0
                    await self._shrink(
                        pool, target - 1,
                        f"down: idle {self.down_steps} rounds",
                    )
                    self.scale_downs_total += 1
                    applied.append(st["last_decision"])
            else:
                if demand_rps > 0 or inflight > 0:
                    st["idle_streak"] = 0
        return applied

    # ---- lifecycle ----

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("autoscale step failed")
            await asyncio.sleep(self.tick_s)

    # ---- observability ----

    def snapshot(self) -> dict:
        """The `autoscale` /metrics block: per-pool desired/ready, shape,
        last decision + reason + age, restore timing; loop totals."""
        now = self._clock()
        pools = {}
        for name, pool in self.pools.items():
            fp = self.controller.pools[name]
            st = self._pool_state[name]
            dec = st["last_decision"]
            pools[name] = {
                "model": pool.model,
                "open_vocab": pool.open_vocab,
                "tp": pool.tp,
                "dp": pool.dp,
                "desired": fp.spec.target_size,
                "size": len(fp.members),
                "ready": fp.member_states(now)["ready"],
                "max_size": pool.max_size,
                "scaled_to_zero": fp.scaled_to_zero,
                "restoring": fp.restoring,
                "time_to_ready_s": fp.time_to_ready_s,
                "restores_total": fp.restores_total,
                "admits_total": st["admits_total"],
                "inflight": st["inflight"],
                "ok_total": st["ok_total"],
                "fail_total": st["fail_total"],
                "last_decision": (
                    None if dec is None else {
                        "desired": dec.desired,
                        "current": dec.current,
                        "reason": dec.reason,
                        "age_s": round(max(now - dec.at, 0.0), 3),
                    }
                ),
            }
        return {
            "pools": pools,
            "default_pool": self._default.name,
            "open_vocab_pool": (
                self._open_vocab.name if self._open_vocab else None
            ),
            "decisions_total": self.decisions_total,
            "scale_ups_total": self.scale_ups_total,
            "scale_downs_total": self.scale_downs_total,
            "wakes_total": self.wakes_total,
            "flood_suppressions_total": self.flood_suppressions_total,
            "routing_rejections_total": self.routing_rejections_total,
        }

    def chips_desired(self) -> int:
        """Chip budget implied by current targets (tp×dp per member) — the
        capacity-vs-static accounting `bench.py --multi-model` records."""
        return sum(
            self.controller.pools[name].spec.target_size
            * pool.chips_per_member
            for name, pool in self.pools.items()
        )
