"""Tiny edge router over a ReplicaPool: `python -m spotter_tpu.serving.router`.

The C++ manager proxy stays a deliberate pass-through (README "Decision");
this router is the piece that sits where a client-side pool can't — in
front of browsers/SDKs that speak plain HTTP to ONE address while the
replica fleet behind it churns (preemptions, restarts, drains). Routes:

- POST /detect  — forwarded through the pool (health-aware selection,
  ejection, replay, optional hedging); a request fails only when EVERY
  replica fails. A pool with nothing available (all ejected, or scaled to
  zero) answers 503 IMMEDIATELY with a Retry-After derived from the
  soonest un-ejection — it does not burn the client's deadline against an
  empty candidate set (ISSUE 6 bugfix).
- GET  /healthz — 200 while at least one replica is available (the router
  itself is an LB target).
- GET  /livez   — router process liveness.
- GET  /metrics — pool counters + per-replica state (ejections, replays,
  hedges, retry-budget exhaustions, failures).

Endpoints come from --endpoints or SPOTTER_TPU_REPLICAS (comma-separated
base URLs). With --spot-endpoints (or SPOTTER_TPU_SPOT_REPLICAS) the router
upgrades to the spot-aware fleet edge (serving/fleet.py): --endpoints
become the on_demand pool, SLO traffic pins there, and bulk traffic drains
to the spot pool. This is the edge half of the failover acceptance test:
the chaos suite drives the same ReplicaPool in-process.
"""

import argparse
import json
import logging
import os
import time

from aiohttp import web

from spotter_tpu import obs
from spotter_tpu.obs import http as obs_http
from spotter_tpu.obs import logs as obs_logs
from spotter_tpu.serving.fleet import (
    REQUEST_CLASS_HEADER,
    classify_request,
    retry_after_header,
)
from spotter_tpu.serving.overload import (
    AdaptiveLimiter,
    edge_limiter_from_env,
)
from spotter_tpu.serving.replica_pool import PoolExhaustedError, ReplicaPool
from spotter_tpu.serving.resilience import jittered_retry_after

logger = logging.getLogger(__name__)

REPLICAS_ENV = "SPOTTER_TPU_REPLICAS"
SPOT_REPLICAS_ENV = "SPOTTER_TPU_SPOT_REPLICAS"
HEDGE_ENV = "SPOTTER_TPU_HEDGE_MS"


def edge_shed_response(limiter: AdaptiveLimiter, cls: str) -> web.Response:
    """429 for an edge-limiter shed: the limit is load state, not failure —
    clients should retry after the (jittered) hint."""
    return web.json_response(
        {
            "error": f"edge admission limit hit ({limiter.limit} in flight)",
            "status": 429,
            "request_class": cls,
        },
        status=429,
        headers={
            "Retry-After": f"{max(1, round(jittered_retry_after(1.0)))}"
        },
    )


def make_router_app(
    pool: ReplicaPool, limiter: AdaptiveLimiter | None = None
) -> web.Application:
    """`limiter` (default: `SPOTTER_TPU_ADMIT_EDGE_TARGET_MS` via
    `edge_limiter_from_env`, None = off) adds the ISSUE 8 AIMD edge gate:
    concurrency toward the replicas is bounded adaptively on observed
    round-trip latency, shedding bulk (X-Request-Class) before slo."""
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["pool"] = pool
    app["edge_limiter"] = limiter
    # Edge SLO burn-rate (ISSUE 10): the device plane's burn windows,
    # measured at the edge over what CLIENTS saw — sheds (429/503) and
    # downstream 5xx spend the budget; everything else is good. This is
    # where "did the brownout ladder actually protect the SLO" is read.
    slo_burn = obs.SloBurn()
    app["slo_burn"] = slo_burn

    async def on_startup(app: web.Application) -> None:
        await pool.start()

    async def on_cleanup(app: web.Application) -> None:
        await pool.stop()

    async def detect(request: web.Request) -> web.Response:
        # Edge half of the trace (ISSUE 7): mint/continue the ids, forward
        # traceparent + X-Request-ID to the replica, and merge the
        # replica's Server-Timing back so ONE trace carries route + every
        # replica stage. X-Request-ID is echoed on every outcome —
        # PoolSuspendedError fast-fails included.
        trace, request_id = obs_http.begin_http_trace(request)

        def done(resp: web.Response) -> web.Response:
            if resp.status in (429, 503) or resp.status >= 500:
                slo_burn.bad()
            else:
                slo_burn.good()
            return obs_http.finish_http_trace(
                trace, request_id, resp, server_timing=True
            )

        with obs.span(obs.ROUTE, trace):
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                return done(web.Response(status=400, text="Invalid JSON body"))
            cls, payload = classify_request(request.headers, payload)
        adm = None
        if limiter is not None:
            adm = limiter.try_admit(cls)
            if adm is None:  # over the adaptive limit: bulk sheds first
                return done(edge_shed_response(limiter, cls))
        headers = obs_http.forward_headers(trace, request_id)
        # the class rides downstream so the replica's limiter/brownout
        # apply the same bulk-before-slo ordering
        headers[REQUEST_CLASS_HEADER] = cls
        t_fwd = time.monotonic()
        try:
            resp = await pool.request("/detect", payload, headers=headers)
        except PoolExhaustedError as exc:
            return done(
                web.json_response(
                    {"error": str(exc), "status": 503},
                    status=503,
                    headers=retry_after_header(exc),
                )
            )
        finally:
            elapsed_s = time.monotonic() - t_fwd
            if limiter is not None:
                # edge control signal: downstream round-trip latency
                limiter.observe(elapsed_s * 1000.0)
            if adm is not None:
                adm.release()
        with obs.span(obs.ROUTE, trace):
            # replica stages + the transport remainder as a network span:
            # the edge trace tiles against the latency the client saw
            obs_http.merge_downstream(trace, resp.headers, elapsed_s)
            out = web.Response(
                status=resp.status_code,
                body=resp.content,
                content_type="application/json",
            )
        return done(out)

    async def healthz(request: web.Request) -> web.Response:
        now = time.monotonic()
        available = sum(1 for r in pool.replicas if r.available(now))
        return web.json_response(
            {
                "available_replicas": available,
                "total_replicas": len(pool.replicas),
                # edge error-budget state (ISSUE 10): same block shape as
                # the replica's /healthz slo_burn
                "slo_burn": slo_burn.block(),
            },
            status=200 if available > 0 else 503,
        )

    async def livez(request: web.Request) -> web.Response:
        return web.json_response({"status": "alive"})

    async def metrics(request: web.Request) -> web.Response:
        # JSON unchanged; ?format=prometheus / Accept: text/plain for the
        # text exposition of the same pool gauges (ISSUE 7). The edge
        # limiter's state rides along under "edge_admit" when armed.
        snap = pool.snapshot()
        if limiter is not None:
            snap["edge_admit"] = limiter.snapshot()
        # burn-rate gauges ride the pool snapshot additively (ISSUE 10);
        # prom renders slo_burn_rate{window="fast"|"slow"}
        snap["slo_target_pct"] = slo_burn.target_pct
        snap["slo_burn_rate"] = slo_burn.rates()
        return obs_http.metrics_response(request, snap)

    app.router.add_post("/detect", detect)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/livez", livez)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/traces", obs_http.make_debug_traces_handler())
    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def main() -> None:
    parser = argparse.ArgumentParser(description="spotter-tpu failover edge router")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--endpoints",
        default=os.environ.get(REPLICAS_ENV, ""),
        help=f"comma-separated replica base URLs (default {REPLICAS_ENV})",
    )
    parser.add_argument(
        "--spot-endpoints",
        default=os.environ.get(SPOT_REPLICAS_ENV, ""),
        help="comma-separated SPOT replica base URLs (default "
        f"{SPOT_REPLICAS_ENV}); when given, the router runs the spot-aware "
        "fleet edge: --endpoints serve SLO traffic, these serve bulk",
    )
    parser.add_argument(
        "--hedge-ms",
        type=float,
        default=float(os.environ.get(HEDGE_ENV, "0") or "0"),
        help="hedge a second replica after this many ms (0 = off)",
    )
    args = parser.parse_args()
    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    spot_endpoints = [
        e.strip() for e in args.spot_endpoints.split(",") if e.strip()
    ]
    if not endpoints and not spot_endpoints:
        raise SystemExit(f"no replica endpoints: pass --endpoints or set {REPLICAS_ENV}")
    logging.basicConfig(level=logging.INFO)
    obs_logs.maybe_setup_json_logging()
    if spot_endpoints:
        from spotter_tpu.serving.fleet import make_fleet_app, static_fleet

        controller = static_fleet(endpoints, spot_endpoints)
        web.run_app(
            make_fleet_app(controller, limiter=edge_limiter_from_env()),
            host=args.host,
            port=args.port,
        )
        return
    pool = ReplicaPool(
        endpoints,
        hedge_after_s=args.hedge_ms / 1000.0 if args.hedge_ms > 0 else None,
    )
    web.run_app(
        make_router_app(pool, limiter=edge_limiter_from_env()),
        host=args.host,
        port=args.port,
    )


if __name__ == "__main__":
    main()
