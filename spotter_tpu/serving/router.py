"""Edge data plane over a ReplicaPool: `python -m spotter_tpu.serving.router`.

The C++ manager proxy stays a deliberate pass-through (README "Decision");
this router is the piece that sits where a client-side pool can't — in
front of browsers/SDKs that speak plain HTTP to ONE address while the
replica fleet behind it churns (preemptions, restarts, drains). Since
ISSUE 11 it is a real data plane, not just a failover proxy:

- **Cache-affinity routing**: every image URL rendezvous-hashes
  (serving/ring.py) onto the replica set, so same-key requests land on the
  replica whose PR 5 result cache already holds the answer — the fleet hit
  rate stays ≈ the single-replica hit rate instead of decaying ~1/N. A
  request with mixed keys splits into per-owner sub-requests and
  reassembles in order (description and `degraded` recomputed exactly the
  way one replica would have). The ring's full weight ordering rides into
  `ReplicaPool.request(prefer=...)`: a dead/ejected owner falls to the
  deterministic next-highest-weight holder, keys rehash, zero client
  failures. `SPOTTER_TPU_AFFINITY=0` restores blind round-robin.
- **Fleet-shared negative cache**: replicas surface deterministic-failure
  verdicts (non-retryable 4xx by URL, poison — the PR 5 taxonomy; never
  5xx/timeouts/sheds) in `X-Spotter-Negative` response headers; the router
  keeps a short-TTL edge verdict table (`SPOTTER_TPU_EDGE_NEGATIVE_TTL_S`,
  0 disables) and answers known-bad URLs at the edge without burning a
  replica round trip.
- **Binary wire format**: `Accept: application/x-spotter-frame` negotiates
  the length-prefixed frame (serving/wire.py) on both hops — raw JPEG
  segments instead of base64-in-JSON. Not negotiated -> the JSON body is
  byte-identical to the pre-frame wire contract.

Routes:

- POST /detect  — the data plane above, composed with health-aware
  selection, ejection, replay, retry budgets, and the ISSUE 8 class-aware
  edge admission; a request fails only when EVERY replica fails. A pool
  with nothing available answers 503 IMMEDIATELY with a Retry-After
  derived from the soonest un-ejection (ISSUE 6 bugfix).
- GET  /healthz — 200 while at least one replica is available (the router
  itself is an LB target); reports the data-plane config.
- GET  /livez   — router process liveness.
- GET  /metrics — pool counters + per-replica state, plus
  `wire_bytes_{in,out}_total` (and the per-request gauge),
  `affinity_hit_rate` + ring-churn counters, and
  `edge_negative_hits_total` — all flowing through the ISSUE 7 prom
  renderer. With the ISSUE 12 aggregator armed (default), a `fleet` block
  carries the merged member view: counters summed reset-aware, fleet
  p50/p99/burn/MFU recomputed from raw state, per-replica gauges labeled
  by url.
- GET  /debug/fleet — admin-gated per-replica table (goodput, p50/p99,
  burn, MFU, HBM, brownout rung, cache hit rate, staleness/generation).
- GET  /debug/traces?fleet=1 — the edge's slowest-K traces stitched with
  the owning replica's flight-recorder spans by trace id.

Endpoints come from --endpoints or SPOTTER_TPU_REPLICAS (comma-separated
base URLs). With --spot-endpoints (or SPOTTER_TPU_SPOT_REPLICAS) the router
upgrades to the spot-aware fleet edge (serving/fleet.py): --endpoints
become the on_demand pool, SLO traffic pins there, and bulk traffic drains
to the spot pool. This is the edge half of the failover acceptance test:
the chaos suite drives the same ReplicaPool in-process.
"""

import argparse
import asyncio
import json
import logging
import math
import os
import time

from aiohttp import web

from spotter_tpu import obs
from spotter_tpu.caching import keys
from spotter_tpu.obs import http as obs_http
from spotter_tpu.obs import logs as obs_logs
from spotter_tpu.obs.aggregate import FleetAggregator
from spotter_tpu.serving import reconcile as reconcile_mod
from spotter_tpu.serving import tenancy
from spotter_tpu.serving import wire
from spotter_tpu.serving.fleet import (
    REQUEST_CLASS_HEADER,
    classify_request,
    retry_after_header,
)
from spotter_tpu.serving.integrity import QuorumSampler
from spotter_tpu.serving.overload import (
    AdaptiveLimiter,
    edge_limiter_from_env,
)
from spotter_tpu.serving.replica_pool import PoolExhaustedError, ReplicaPool
from spotter_tpu.serving.resilience import _env_float, jittered_retry_after
from spotter_tpu.serving.ring import RendezvousRing

logger = logging.getLogger(__name__)

REPLICAS_ENV = "SPOTTER_TPU_REPLICAS"
SPOT_REPLICAS_ENV = "SPOTTER_TPU_SPOT_REPLICAS"
HEDGE_ENV = "SPOTTER_TPU_HEDGE_MS"
AFFINITY_ENV = "SPOTTER_TPU_AFFINITY"


def affinity_from_env() -> bool:
    """Cache-affinity routing is the default data plane; 0 restores the
    pre-ISSUE-11 blind round-robin."""
    return os.environ.get(AFFINITY_ENV, "1").strip() not in ("", "0")


def edge_shed_response(limiter: AdaptiveLimiter, cls: str) -> web.Response:
    """429 for an edge-limiter shed: the limit is load state, not failure —
    clients should retry after the (jittered) hint."""
    return web.json_response(
        {
            "error": f"edge admission limit hit ({limiter.limit} in flight)",
            "status": 429,
            "request_class": cls,
        },
        status=429,
        headers={
            "Retry-After": f"{max(1, round(jittered_retry_after(1.0)))}"
        },
    )


def tenant_shed_response(exc: tenancy.TenantQuotaError) -> web.Response:
    """429 for an over-quota tenant (ISSUE 19): the Retry-After hint is
    tenant-scoped (that tenant's own bucket refill), already jittered by
    the plane. The header is integer seconds and must never render 0 —
    a sub-second hint would invite the exact immediate retries the shed
    exists to push back, so the precise float rides in the body and the
    header ceils to at least 1 (the fleet's retry_after_header floor)."""
    return web.json_response(
        {
            "error": str(exc),
            "status": exc.status,
            "tenant": exc.tenant,
            "retry_after_s": round(max(exc.retry_after_s, 0.0), 3),
        },
        status=exc.status,
        headers={"Retry-After": f"{max(1, math.ceil(exc.retry_after_s))}"},
    )


def model_routing_response(exc) -> web.Response:
    """400 for a request the model router cannot place (ISSUE 20: unknown
    model, or open-vocabulary `queries` against a closed-set fleet). Same
    shed contract as the 429s — structured body with `status` and `error`,
    X-Request-ID echoed by the edge trace — but no Retry-After: a routing
    400 is a CLIENT defect, not load state, and retrying it unchanged can
    never succeed. The body names the registry (`families`) so the caller
    can self-correct from the response alone."""
    return web.json_response(
        {
            "error": str(exc),
            "status": exc.status,
            "kind": exc.kind,
            "families": exc.families,
        },
        status=exc.status,
    )


class _BadGateway(RuntimeError):
    """A sub-response the fan-in cannot merge (non-200 in a split request,
    malformed frame): surfaced to the client as 502."""


def frame_response_validator(resp) -> None:
    """ReplicaPool `validator` (ISSUE 14): full structural + checksum
    verification of every frame-typed 200 body INSIDE the replay loop, so
    a corrupt frame is treated exactly like a transport failure of the
    replica that produced it — counted, ejection-relevant, replayed
    against the next ranked holder — and never reaches a client. JSON
    bodies pass through untouched (the frame is the only hop encoding
    with checksums)."""
    if resp.headers.get("content-type", "").startswith(
        wire.FRAME_CONTENT_TYPE
    ):
        wire.verify_frame(resp.content)


def make_router_app(
    pool: ReplicaPool,
    limiter: AdaptiveLimiter | None = None,
    affinity: bool | None = None,
    edge_negative_ttl_s: float | None = None,
    aggregator: FleetAggregator | None = None,
    rollout=None,
    reconciler=None,
    quorum: QuorumSampler | None = None,
    tenancy_plane: tenancy.TenantPlane | None = None,
) -> web.Application:
    """`limiter` (default: `SPOTTER_TPU_ADMIT_EDGE_TARGET_MS` via
    `edge_limiter_from_env`, None = off) adds the ISSUE 8 AIMD edge gate:
    concurrency toward the replicas is bounded adaptively on observed
    round-trip latency, shedding bulk (X-Request-Class) before slo.
    `affinity` (default `SPOTTER_TPU_AFFINITY`, on) arms cache-affinity
    routing; `edge_negative_ttl_s` (default
    `SPOTTER_TPU_EDGE_NEGATIVE_TTL_S`, 5 s; <= 0 disables) caps the edge
    verdict table's TTL. `aggregator` (default: built over the pool's
    members from `SPOTTER_TPU_FLEET_SCRAPE_S`, 2 s; 0 disables) is the
    ISSUE 12 fleet telemetry plane: member /metrics scraped and merged
    into a `fleet` block on this /metrics, the /debug/fleet per-replica
    table, and /debug/traces?fleet=1 cross-replica trace stitching.
    `rollout` (ISSUE 15, default None) attaches a
    `rollout.RolloutController`: its shadow lane mirrors sampled /detect
    traffic to the canary (responses discarded, never client-visible) and
    its state/counters ride /metrics under `rollout` — idle cost is one
    None/state check per request. `reconciler` (ISSUE 16, default None)
    attaches a `reconcile.Reconciler`: /healthz grows a `control_plane`
    block (leadership + desired-vs-observed drift) and /metrics a
    `reconcile` block (loop/adoption/fencing/rebuild counters).
    `tenancy_plane` (ISSUE 19, default `tenancy.from_env()` — None when
    unconfigured) arms per-tenant edge quotas: over-quota tenants shed
    429 with a tenant-scoped Retry-After BEFORE the body is read, the
    resolved id is forwarded downstream in X-Spotter-Tenant, and
    per-tenant admit/shed/occupancy counters ride /metrics under
    `tenants` plus the admin-gated /debug/tenants full table."""
    if affinity is None:
        affinity = affinity_from_env()
    if tenancy_plane is None:
        tenancy_plane = tenancy.from_env()
    if edge_negative_ttl_s is None:
        edge_negative_ttl_s = _env_float(
            wire.EDGE_NEGATIVE_TTL_ENV, wire.DEFAULT_EDGE_NEGATIVE_TTL_S
        )
    negcache = (
        wire.EdgeNegativeCache(max_ttl_s=edge_negative_ttl_s)
        if affinity and edge_negative_ttl_s > 0
        else None
    )
    if aggregator is None:
        aggregator = FleetAggregator(lambda: [r.url for r in pool.replicas])
    # wire-integrity validation (ISSUE 14): every frame-typed sub-response
    # is structurally + checksum verified INSIDE the pool's replay loop, so
    # a corrupt frame is replayed like a transport failure instead of
    # reaching a client. SPOTTER_TPU_WIRE_CRC=0 disables end to end (the
    # replicas then emit checksum-less v1 frames there is nothing to check).
    pool_validator = frame_response_validator if wire.crc_enabled() else None
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["pool"] = pool
    app["edge_limiter"] = limiter
    app["edge_negative"] = negcache
    app["fleet_aggregator"] = aggregator
    app["rollout"] = rollout
    if quorum is None:
        quorum = QuorumSampler(pool)  # inert at the default 0% sample
    app["quorum"] = quorum
    # Edge SLO burn-rate (ISSUE 10): the device plane's burn windows,
    # measured at the edge over what CLIENTS saw — sheds (429/503) and
    # downstream 5xx spend the budget; everything else is good. This is
    # where "did the brownout ladder actually protect the SLO" is read.
    slo_burn = obs.SloBurn()
    app["slo_burn"] = slo_burn
    # Edge wire accounting (ISSUE 11): flat counters, event-loop confined.
    # Nested under "wire" in /metrics so the prom renderer flattens them to
    # spotter_tpu_wire_bytes_in_total etc.
    wire_stats = {
        "bytes_in_total": 0,
        "bytes_out_total": 0,
        "replica_bytes_in_total": 0,
        "replica_bytes_out_total": 0,
        "requests_total": 0,
        "frame_responses_total": 0,
        "json_responses_total": 0,
    }
    app["wire_stats"] = wire_stats
    # Affinity/ring accounting: owner-hit rate is THE fleet-cache-locality
    # signal (affinity_hit_rate in /metrics); churn counts membership edits
    # observed between requests (each one remaps ~1/N of the key space).
    aff_stats = {
        "routed_total": 0,  # sub-requests routed with a preference order
        "owner_hits_total": 0,  # served by their top-ranked owner
        "fallback_total": 0,  # served by a lower-ranked holder (failover)
        "ring_members": 0,
        "ring_rebuilds_total": 0,
        "ring_churn_total": 0,  # members added+removed across rebuilds
    }
    app["affinity_stats"] = aff_stats
    ring_state: dict = {"members": None, "ring": None}

    def ring_for_pool() -> RendezvousRing:
        members = tuple(sorted(r.url for r in pool.replicas))
        if members != ring_state["members"]:
            if ring_state["members"] is not None:
                aff_stats["ring_churn_total"] += len(
                    set(members) ^ set(ring_state["members"])
                )
                aff_stats["ring_rebuilds_total"] += 1
            ring_state["members"] = members
            ring_state["ring"] = RendezvousRing(list(members))
            aff_stats["ring_members"] = len(members)
        return ring_state["ring"]

    async def on_startup(app: web.Application) -> None:
        await pool.start()
        await aggregator.start()  # no-op when SPOTTER_TPU_FLEET_SCRAPE_S=0

    async def on_cleanup(app: web.Application) -> None:
        await aggregator.stop()
        await pool.stop()

    def _record_response(body_len: int, frame: bool) -> None:
        wire_stats["requests_total"] += 1
        wire_stats["bytes_out_total"] += body_len
        if frame:
            wire_stats["frame_responses_total"] += 1
        else:
            wire_stats["json_responses_total"] += 1

    def _passthrough(resp, client_frame: bool) -> web.Response:
        """Single-owner fast path: the replica's body crosses unchanged —
        the byte-identity contract holds trivially."""
        is_frame = resp.headers.get("content-type", "").startswith(
            wire.FRAME_CONTENT_TYPE
        )
        out = web.Response(
            status=resp.status_code,
            body=resp.content,
            content_type=(
                wire.FRAME_CONTENT_TYPE if is_frame else "application/json"
            ),
        )
        x_cache = resp.headers.get(wire.X_CACHE_HEADER)
        if x_cache:
            out.headers[wire.X_CACHE_HEADER] = x_cache
        rid = resp.headers.get(wire.REPLICA_HEADER)
        if rid:  # replica identity rides through the edge (ISSUE 14)
            out.headers[wire.REPLICA_HEADER] = rid
        ver = resp.headers.get(wire.VERSION_HEADER)
        if ver:  # deploy version rides through too (ISSUE 15)
            out.headers[wire.VERSION_HEADER] = ver
        _record_response(len(resp.content), is_frame)
        return out

    def _absorb_sub(owner: str, resp) -> None:
        """Per-sub-response bookkeeping: wire bytes, negative verdicts, and
        did-the-owner-serve-it affinity accounting."""
        wire_stats["replica_bytes_in_total"] += len(resp.content)
        if negcache is not None:
            negcache.absorb(resp.headers.get(wire.NEGATIVE_HEADER))
        if owner:
            if str(resp.url).startswith(owner + "/"):
                aff_stats["owner_hits_total"] += 1
            else:
                aff_stats["fallback_total"] += 1

    def _base_url(resp) -> str:
        """Replica base URL a sub-response came from (quorum attribution)."""
        return str(resp.url).rsplit("/detect", 1)[0].rstrip("/")

    async def _forward_affinity(
        urls: list[str], payload: dict, headers: dict, client_frame: bool
    ) -> tuple[web.Response, list, str | None]:
        """Fan-out/fan-in: group URLs by ring owner, forward each group with
        the ring's weight ordering as the failover preference, reassemble
        in request order. Returns (response, downstream headers list,
        primary replica URL when exactly ONE replica served the whole
        request — the only shape quorum sampling can attribute)."""
        ring = ring_for_pool()
        slots: list[dict | None] = [None] * len(urls)
        x_cache_vals: list[str | None] = []
        groups: dict[str, list[int]] = {}
        prefer: dict[str, list[str]] = {}
        edge_answered = 0
        for i, u in enumerate(urls):
            akey = keys.affinity_key(u)
            if negcache is not None:
                verdict = negcache.get(akey)
                if verdict is not None:
                    # known-bad URL: answered at the edge, zero replica work
                    slots[i] = {"url": u, "error": verdict[0]}
                    x_cache_vals.append("negative")
                    edge_answered += 1
                    continue
            ranked = ring.ranked(akey)
            owner = ranked[0] if ranked else ""
            idxs = groups.setdefault(owner, [])
            if not idxs:
                # the group fails over as one unit, by its first key's
                # deterministic weight order
                prefer[owner] = ranked
            idxs.append(i)

        downstream: list = []
        degraded: set[str] = set()
        replica_ids: list[str] = []
        versions: list[str] = []
        if groups:
            aff_stats["routed_total"] += len(groups)

            async def sub(owner: str, idxs: list[int]):
                sub_payload = dict(payload)
                sub_payload["image_urls"] = [urls[i] for i in idxs]
                wire_stats["replica_bytes_out_total"] += len(
                    wire.to_json_bytes(sub_payload)
                )
                return await pool.request(
                    "/detect",
                    sub_payload,
                    headers=headers,
                    prefer=prefer[owner] or None,
                    validator=pool_validator,
                )

            gathered = await asyncio.gather(
                *(sub(o, ix) for o, ix in groups.items()),
                return_exceptions=True,
            )
            for res in gathered:
                if isinstance(res, BaseException):
                    raise res
            for (owner, idxs), resp in zip(groups.items(), gathered):
                _absorb_sub(owner, resp)
                downstream.append(resp.headers)
                rid = resp.headers.get(wire.REPLICA_HEADER)
                if rid and rid not in replica_ids:
                    replica_ids.append(rid)
                ver = resp.headers.get(wire.VERSION_HEADER)
                if ver and ver not in versions:
                    versions.append(ver)
                if len(groups) == 1 and not edge_answered:
                    return (
                        _passthrough(resp, client_frame),
                        downstream,
                        _base_url(resp),
                    )
                if resp.status_code != 200:
                    # a split request can't merge a replica error body;
                    # surface the first one as a gateway failure
                    raise _BadGateway(
                        f"sub-request for {len(idxs)} url(s) answered "
                        f"HTTP {resp.status_code}"
                    )
                ctype = resp.headers.get("content-type", "")
                try:
                    if ctype.startswith(wire.FRAME_CONTENT_TYPE):
                        header, segments = wire.split_frame(resp.content)
                    else:
                        header, segments = wire.strip_segments(
                            json.loads(resp.content)
                        )
                except (wire.FrameError, json.JSONDecodeError, TypeError) as exc:
                    raise _BadGateway(f"unparseable sub-response: {exc}")
                images = header.get("images") or []
                if len(images) != len(idxs):
                    raise _BadGateway(
                        f"sub-response carried {len(images)} images "
                        f"for {len(idxs)} urls"
                    )
                for img, i in zip(images, idxs):
                    slot = dict(img)
                    seg = slot.pop("image_segment", None)
                    if seg is not None:
                        slot["_bytes"] = segments[seg]
                    slots[i] = slot
                degraded.update(header.get("degraded") or [])
                x_cache_vals.append(resp.headers.get(wire.X_CACHE_HEADER))

        header, segments = wire.merge_images(slots, degraded)
        if client_frame:
            body = wire.build_frame(header, segments)
            ctype = wire.FRAME_CONTENT_TYPE
        else:
            body = wire.to_json_bytes(wire.restore_segments(header, segments))
            ctype = "application/json"
        out = web.Response(status=200, body=body, content_type=ctype)
        x_cache = wire.summarize_cache_outcomes(x_cache_vals)
        if x_cache is not None:
            out.headers[wire.X_CACHE_HEADER] = x_cache
        if replica_ids:
            # every replica that contributed to the fan-in, comma-joined in
            # owner order (ISSUE 14): a slow merged response decomposes
            # back to the member(s) that served it
            out.headers[wire.REPLICA_HEADER] = ",".join(replica_ids)
        if versions:
            # every distinct deploy version that contributed (ISSUE 15): a
            # >1-entry value IS the mixed-version-window signal
            out.headers[wire.VERSION_HEADER] = ",".join(versions)
        _record_response(len(body), client_frame)
        return out, downstream, None

    async def detect(request: web.Request) -> web.Response:
        # Edge half of the trace (ISSUE 7): mint/continue the ids, forward
        # traceparent + X-Request-ID to the replica, and merge the
        # replica's Server-Timing back so ONE trace carries route + every
        # replica stage. X-Request-ID is echoed on every outcome —
        # PoolSuspendedError fast-fails included.
        trace, request_id = obs_http.begin_http_trace(request)
        tenant = None
        tadm = None

        def done(resp: web.Response) -> web.Response:
            if resp.status in (429, 503) or resp.status >= 500:
                slo_burn.bad()
            else:
                slo_burn.good()
            # per-tenant occupancy + SLO accounting (ISSUE 19): release
            # exactly once, burning the tenant's budget on sheds/5xx
            if tadm is not None:
                tadm.release(
                    good=resp.status not in (429, 503) and resp.status < 500
                )
            return obs_http.finish_http_trace(
                trace, request_id, resp, server_timing=True
            )

        if tenancy_plane is not None:
            # edge quota (ISSUE 19): identity comes from headers alone, so
            # an over-quota tenant is shed 429 BEFORE the body is even
            # read — strictly before any in-quota shed below
            tenant = tenancy_plane.resolve(request.headers)
            try:
                tadm = tenancy_plane.try_admit(tenant)
            except tenancy.TenantQuotaError as exc:
                return done(tenant_shed_response(exc))
        try:
            with obs.span(obs.ROUTE, trace):
                raw = await request.read()
                wire_stats["bytes_in_total"] += len(raw)
                try:
                    payload = json.loads(raw)
                    if not isinstance(payload, dict):
                        raise json.JSONDecodeError("not an object", "{}", 0)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    return done(web.Response(status=400, text="Invalid JSON body"))
                cls, payload = classify_request(request.headers, payload)
            adm = None
            if limiter is not None:
                adm = limiter.try_admit(cls)
                if adm is None:  # over the adaptive limit: bulk sheds first
                    return done(edge_shed_response(limiter, cls))
            headers = obs_http.forward_headers(trace, request_id)
            # the class rides downstream so the replica's limiter/brownout
            # apply the same bulk-before-slo ordering
            headers[REQUEST_CLASS_HEADER] = cls
            if tenant is not None:
                # the resolved tenant id rides downstream alongside
                # X-Request-ID (ISSUE 19) so the replica's QueueItem, DRR
                # ordering and per-tenant brownout see the same identity —
                # fan-out sub-requests inherit these headers unchanged.
                # stamp() adds the edge-attestation token when configured,
                # so the replica's plane honors the id (REVIEW: a bare
                # forwarded header is otherwise untrusted there too)
                tenancy_plane.stamp(headers, tenant)
            # wire negotiation rides downstream too: when the client speaks
            # frames, the router->replica hop does as well — the base64 tax is
            # paid on neither hop
            client_frame = wire.wants_frame(request.headers.get("Accept"))
            if client_frame:
                headers["Accept"] = wire.FRAME_CONTENT_TYPE
            urls = payload.get("image_urls")
            splittable = (
                affinity
                and isinstance(urls, list)
                and bool(urls)
                and all(isinstance(u, str) for u in urls)
            )
            t_fwd = time.monotonic()
            downstream: list = []
            primary_url: str | None = None
            try:
                if splittable:
                    out, downstream, primary_url = await _forward_affinity(
                        urls, payload, headers, client_frame
                    )
                else:
                    resp = await pool.request(
                        "/detect", payload, headers=headers,
                        validator=pool_validator,
                    )
                    downstream = [resp.headers]
                    _absorb_sub("", resp)
                    out = _passthrough(resp, client_frame)
                    primary_url = _base_url(resp)
            except PoolExhaustedError as exc:
                return done(
                    web.json_response(
                        {"error": str(exc), "status": 503},
                        status=503,
                        headers=retry_after_header(exc),
                    )
                )
            except _BadGateway as exc:
                return done(
                    web.json_response(
                        {"error": str(exc), "status": 502}, status=502
                    )
                )
            finally:
                elapsed_s = time.monotonic() - t_fwd
                if limiter is not None:
                    # edge control signal: downstream round-trip latency
                    limiter.observe(elapsed_s * 1000.0)
                if adm is not None:
                    adm.release()
            with obs.span(obs.ROUTE, trace):
                # replica stages + the transport remainder as a network span:
                # the edge trace tiles against the latency the client saw.
                # Fanned-out sub-requests ran concurrently, so the remainder is
                # measured against the SLOWEST hop's attributed time.
                merged_max = 0.0
                for hdrs in downstream:
                    merged_max = max(
                        merged_max,
                        obs_http.merge_server_timing(
                            trace, hdrs.get(obs_http.SERVER_TIMING_HEADER)
                        ),
                    )
                if downstream and trace is not None:
                    net_ms = elapsed_s * 1e3 - merged_max
                    if net_ms > 0.0:
                        trace.add_span_ms(obs_http.NETWORK, 0.0, net_ms)
            # shadow lane (ISSUE 15): mirror this already-served request to the
            # rollout canary on the sampled lane — fire-and-forget, response
            # discarded, so nothing here can touch what the client got. Frame
            # bodies are skipped (the lane compares JSON detections).
            if (
                rollout is not None
                and out.status == 200
                and not client_frame
            ):
                rollout.maybe_shadow(payload, out.body)
            # quorum sampling (ISSUE 17): re-ask this already-served request of
            # a SECOND ranked replica and compare — fire-and-forget like the
            # shadow lane, so disagreement detection never adds client latency.
            # Only single-replica-served JSON responses are attributable.
            if (
                out.status == 200
                and not client_frame
                and primary_url
                and quorum.take()
            ):
                asyncio.ensure_future(
                    quorum.run_one(pool.client, payload, out.body, primary_url)
                )
            return done(out)
        finally:
            # leak guard (REVIEW): a client disconnect (CancelledError
            # in any await) or an uncaught error below must still free
            # the tenant's inflight slot, or the tenant is permanently
            # 429-locked at its inflight cap and its occupancy skews
            # the limiter/brownout forever. Idempotent: when done()
            # ran, it already released with the real outcome; this
            # no-outcome release never touches the SLO burn.
            if tadm is not None:
                tadm.release(good=None)

    async def healthz(request: web.Request) -> web.Response:
        now = time.monotonic()
        available = sum(1 for r in pool.replicas if r.available(now))
        return web.json_response(
            {
                "available_replicas": available,
                "total_replicas": len(pool.replicas),
                # data-plane config (ISSUE 11): auditable per edge, like
                # the replica's dp/ragged/device_preprocess flags
                "affinity": affinity,
                "edge_negative_ttl_s": (
                    negcache.max_ttl_s if negcache is not None else 0.0
                ),
                # gray-failure immune plane config (ISSUE 14): auditable
                # per edge like the affinity/wire flags
                "adaptive_hedge": pool.adaptive_hedge,
                "outlier_ratio": pool.outlier_ratio,
                "wire_crc": wire.crc_enabled(),
                # edge error-budget state (ISSUE 10): same block shape as
                # the replica's /healthz slo_burn
                "slo_burn": slo_burn.block(),
                # output-integrity plane config (ISSUE 17): sampling share
                # auditable per edge; 0 = quorum comparison off
                "quorum_pct": quorum.pct,
                # tenant isolation plane config (ISSUE 19): auditable per
                # edge like the affinity/wire flags
                "tenancy": tenancy_plane is not None,
                # control plane (ISSUE 16): leadership + fencing epoch +
                # desired-vs-observed drift, same block the fleet app serves
                **reconcile_mod.healthz_block(reconciler),
            },
            status=200 if available > 0 else 503,
        )

    async def livez(request: web.Request) -> web.Response:
        return web.json_response({"status": "alive"})

    async def metrics(request: web.Request) -> web.Response:
        # JSON unchanged; ?format=prometheus / Accept: text/plain for the
        # text exposition of the same pool gauges (ISSUE 7). The edge
        # limiter's state rides along under "edge_admit" when armed.
        snap = pool.snapshot()
        if limiter is not None:
            snap["edge_admit"] = limiter.snapshot()
        # burn-rate gauges ride the pool snapshot additively (ISSUE 10);
        # prom renders slo_burn_rate{window="fast"|"slow"}
        snap["slo_target_pct"] = slo_burn.target_pct
        snap["slo_burn_rate"] = slo_burn.rates()
        # edge data plane (ISSUE 11): wire bytes, affinity locality, ring
        # churn, edge negative-cache hits — flattened by the prom renderer
        # to spotter_tpu_wire_bytes_in_total, spotter_tpu_affinity_hit_rate,
        # spotter_tpu_edge_negative_hits_total, ...
        requests = wire_stats["requests_total"]
        snap["wire"] = {
            **wire_stats,
            "bytes_out_per_request": (
                wire_stats["bytes_out_total"] / requests if requests else 0.0
            ),
        }
        routed = aff_stats["routed_total"]
        snap["affinity"] = {
            "enabled": affinity,
            **aff_stats,
            "hit_rate": (
                aff_stats["owner_hits_total"] / routed if routed else 0.0
            ),
        }
        snap["edge_negative"] = (
            negcache.snapshot()
            if negcache is not None
            else {"entries": 0, "hits_total": 0, "entries_added_total": 0}
        )
        # fleet telemetry plane (ISSUE 12): the merged member view —
        # counters summed (reset-aware), quantiles/burn/MFU recomputed
        # from raw state, per-replica rows labeled {url=...} in the prom
        # exposition. This is THE single scrape target for "what is the
        # fleet's goodput/burn/MFU right now".
        if aggregator.enabled:
            snap["fleet"] = aggregator.fleet_snapshot()
        # deployment plane (ISSUE 15): rollout state machine + verdict +
        # shadow-lane counters; prom renders rollouts_total{verdict=...}
        if rollout is not None:
            snap["rollout"] = rollout.snapshot()
        # control plane (ISSUE 16): reconcile loop counters + drift gauge;
        # prom renders reconcile_loops_total, drift{pool=...}, ...
        if reconciler is not None:
            snap["reconcile"] = reconciler.snapshot()
        # output-integrity plane (ISSUE 17): quorum sample/disagreement/
        # quarantine counters + per-replica disagreement EWMAs; prom renders
        # integrity_quorum_disagreements_total, ...
        snap["integrity"] = {"quorum": quorum.snapshot()}
        # tenant isolation plane (ISSUE 19): bounded top-K per-tenant rows;
        # prom renders tenant_stat{tenant=...,stat=...}
        if tenancy_plane is not None:
            snap["tenants"] = tenancy_plane.metrics_view()
        return obs_http.metrics_response(request, snap)

    async def debug_tenants(request: web.Request) -> web.Response:
        """Full per-tenant table (ISSUE 19) — admin-token-gated like the
        replica's /profile; the bounded top-K view lives in /metrics."""
        rejected = obs_http.admin_rejection(request)
        if rejected is not None:
            return rejected
        if tenancy_plane is None:
            return web.json_response({"enabled": False})
        return web.json_response(tenancy_plane.snapshot())

    app.router.add_post("/detect", detect)
    app.router.add_get("/debug/tenants", debug_tenants)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/livez", livez)
    app.router.add_get("/metrics", metrics)
    app.router.add_get(
        "/debug/traces",
        obs_http.make_debug_traces_handler(aggregator=aggregator),
    )
    app.router.add_get(
        "/debug/fleet", obs_http.make_debug_fleet_handler(aggregator)
    )
    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def main() -> None:
    parser = argparse.ArgumentParser(description="spotter-tpu edge data-plane router")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--endpoints",
        default=os.environ.get(REPLICAS_ENV, ""),
        help=f"comma-separated replica base URLs (default {REPLICAS_ENV})",
    )
    parser.add_argument(
        "--spot-endpoints",
        default=os.environ.get(SPOT_REPLICAS_ENV, ""),
        help="comma-separated SPOT replica base URLs (default "
        f"{SPOT_REPLICAS_ENV}); when given, the router runs the spot-aware "
        "fleet edge: --endpoints serve SLO traffic, these serve bulk",
    )
    parser.add_argument(
        "--hedge-ms",
        default=os.environ.get(HEDGE_ENV, "0") or "0",
        help="hedge a second replica after this many ms (0 = off), or "
        "'auto' for the adaptive trigger (ISSUE 14): hedge at the live "
        "pool p95, spend capped by the SPOTTER_TPU_HEDGE_BUDGET_PCT "
        "sliding-window budget",
    )
    parser.add_argument(
        "--no-affinity",
        action="store_true",
        help=f"disable cache-affinity routing ({AFFINITY_ENV}=0): blind "
        "round-robin, the pre-ISSUE-11 behavior",
    )
    args = parser.parse_args()
    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    spot_endpoints = [
        e.strip() for e in args.spot_endpoints.split(",") if e.strip()
    ]
    if not endpoints and not spot_endpoints:
        raise SystemExit(f"no replica endpoints: pass --endpoints or set {REPLICAS_ENV}")
    logging.basicConfig(level=logging.INFO)
    obs_logs.maybe_setup_json_logging()
    if args.no_affinity:
        os.environ[AFFINITY_ENV] = "0"
    if spot_endpoints:
        from spotter_tpu.serving.fleet import make_fleet_app, static_fleet

        controller = static_fleet(endpoints, spot_endpoints)
        web.run_app(
            make_fleet_app(controller, limiter=edge_limiter_from_env()),
            host=args.host,
            port=args.port,
        )
        return
    hedge_raw = str(args.hedge_ms).strip().lower()
    adaptive_hedge = hedge_raw == "auto"
    try:
        hedge_ms = 0.0 if adaptive_hedge else float(hedge_raw or "0")
    except ValueError:
        raise SystemExit(
            f"--hedge-ms must be a number of milliseconds or 'auto', "
            f"got {args.hedge_ms!r}"
        )
    pool = ReplicaPool(
        endpoints,
        hedge_after_s=hedge_ms / 1000.0 if hedge_ms > 0 else None,
        adaptive_hedge=adaptive_hedge,
    )
    web.run_app(
        make_router_app(pool, limiter=edge_limiter_from_env()),
        host=args.host,
        port=args.port,
    )


if __name__ == "__main__":
    main()
