"""Request-lifecycle resilience: deadlines, admission errors, circuit breaking.

The north star is a serving system under heavy traffic (ROADMAP.md), and
DeepServe's serverless results (PAPERS.md) say the difference between a
system that degrades and one that collapses is admission control plus fast
failure detection. This module is the shared vocabulary for that story:

- `Deadline`: a per-request time budget threaded from the HTTP edge through
  fetch, queue wait, and the device call (`SPOTTER_TPU_REQUEST_DEADLINE_MS`).
  On expiry the caller gets `DeadlineExceededError` — never an unbounded wait.
- Admission errors (`QueueFullError`, `CircuitOpenError`, `DrainingError`):
  raised at `MicroBatcher.submit` time, mapped to HTTP 429/503 with a
  `Retry-After` hint by the runtime (serving/standalone.py).
- `CircuitBreaker`: trips after N consecutive batch failures, flips
  readiness (`/healthz` -> 503) while liveness stays green, and half-opens
  with a probe request after a cooldown. State transitions are recorded in
  `engine.metrics` so `/metrics` exposes them.

Everything here is event-loop-thread code except the breaker, which is also
touched from batch tasks; a lock keeps it safe either way.
"""

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

DEADLINE_ENV = "SPOTTER_TPU_REQUEST_DEADLINE_MS"
QUEUE_DEPTH_ENV = "SPOTTER_TPU_QUEUE_DEPTH"
BATCH_TIMEOUT_ENV = "SPOTTER_TPU_BATCH_TIMEOUT_MS"
BREAKER_THRESHOLD_ENV = "SPOTTER_TPU_BREAKER_THRESHOLD"
BREAKER_COOLDOWN_ENV = "SPOTTER_TPU_BREAKER_COOLDOWN_S"
DRAIN_TIMEOUT_ENV = "SPOTTER_TPU_DRAIN_TIMEOUT_S"

DEFAULT_QUEUE_DEPTH = 64
DEFAULT_BATCH_TIMEOUT_MS = 120_000.0
DEFAULT_BREAKER_THRESHOLD = 5
DEFAULT_BREAKER_COOLDOWN_S = 10.0
DEFAULT_DRAIN_TIMEOUT_S = 30.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}") from None


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from None


# Retry-After jitter (ISSUE 8 satellite): deterministic hints synchronize
# client retry waves — every 429 shed at t0 with "Retry-After: 1" re-arrives
# as one thundering herd at t0+1. Jittering the hint +-25% (full jitter over
# the band) decorrelates the waves. Shares the supervisor's
# SPOTTER_TPU_BACKOFF_JITTER knob (default ON; 0/off/false disables) so one
# switch governs every backoff-shaped randomness in the system.
BACKOFF_JITTER_ENV = "SPOTTER_TPU_BACKOFF_JITTER"
RETRY_AFTER_JITTER_FRAC = 0.25
_jitter_rng = random.Random()


def jitter_enabled_from_env() -> bool:
    """Default ON: only an explicit 0/off/false disables it."""
    return os.environ.get(BACKOFF_JITTER_ENV, "1").strip().lower() not in (
        "0", "off", "false",
    )


def jittered_retry_after(
    seconds: float,
    rng: Optional[random.Random] = None,
    enabled: Optional[bool] = None,
) -> float:
    """`seconds` +-25%, uniform over the band; the exact input when the
    jitter knob is off (or seconds <= 0). `rng` is injectable so tests pin
    the draw with a seed."""
    if enabled is None:
        enabled = jitter_enabled_from_env()
    if not enabled or seconds <= 0:
        return seconds
    r = rng if rng is not None else _jitter_rng
    return seconds * (
        1.0 + RETRY_AFTER_JITTER_FRAC * (2.0 * r.random() - 1.0)
    )


class Ewma:
    """Exponentially-weighted moving average with a sample count — the
    gray-failure outlier score's smoothing primitive (ISSUE 14). Shared
    vocabulary here (like Deadline/CircuitBreaker) rather than buried in
    the replica pool: one replica's request latency and its health-probe
    latency are tracked by two instances with the same semantics, and the
    sample count is what gates "enough evidence to call this replica an
    outlier" (a single slow response must not soft-eject anyone)."""

    __slots__ = ("alpha", "value", "samples")

    def __init__(self, alpha: float = 0.3) -> None:
        self.alpha = min(max(float(alpha), 0.0), 1.0)
        self.value = 0.0
        self.samples = 0

    def update(self, x: float) -> float:
        self.samples += 1
        if self.samples == 1:
            self.value = float(x)
        else:
            self.value += self.alpha * (float(x) - self.value)
        return self.value

    def reset(self) -> None:
        self.value = 0.0
        self.samples = 0


class DeadlineExceededError(TimeoutError):
    """The request's time budget ran out (fetch, queue wait, or device call)."""


class AdmissionError(RuntimeError):
    """Base for load-shedding rejections; carries HTTP mapping hints."""

    status = 503
    retry_after_s = 1.0

    def __init__(self, message: str, retry_after_s: Optional[float] = None) -> None:
        super().__init__(message)
        if retry_after_s is not None:
            self.retry_after_s = retry_after_s


class QueueFullError(AdmissionError):
    """Bounded batcher queue is full — shed with 429 (client should retry)."""

    status = 429


class CircuitOpenError(AdmissionError):
    """Circuit breaker is open — the engine is failing; shed with 503."""

    status = 503


class DrainingError(AdmissionError):
    """Server is draining (preStop) or stopped — shed with 503, don't retry here."""

    status = 503


@dataclass
class Deadline:
    """Monotonic-clock budget. `None` (no deadline) is represented by the
    absence of a Deadline, not a sentinel — `Deadline.from_env()` returns
    None when the knob is unset/0 so the no-deadline path costs nothing."""

    expires_at: float
    budget_s: float

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(expires_at=time.monotonic() + seconds, budget_s=seconds)

    @classmethod
    def from_env(cls) -> Optional["Deadline"]:
        ms = _env_float(DEADLINE_ENV, 0.0)
        return cls.after(ms / 1000.0) if ms > 0 else None

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def exceeded(self, what: str) -> DeadlineExceededError:
        return DeadlineExceededError(
            f"deadline of {self.budget_s * 1000.0:.0f} ms exceeded during {what}"
        )

    async def wait_for(self, awaitable, what: str):
        """Bound an awaitable by the remaining budget; DeadlineExceededError
        on expiry (the awaitable is cancelled)."""
        import asyncio

        try:
            return await asyncio.wait_for(awaitable, max(self.remaining(), 0.0))
        except asyncio.TimeoutError:
            raise self.exceeded(what) from None


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing.

    closed -> (threshold consecutive failures) -> open
    open   -> (cooldown elapsed, next allow() admits ONE probe) -> half_open
    half_open -> probe success -> closed; probe failure -> open again

    `threshold <= 0` disables the breaker (always closed). Transitions are
    pushed to `metrics.record_breaker_transition` so /metrics shows them.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        threshold: int = DEFAULT_BREAKER_THRESHOLD,
        cooldown_s: float = DEFAULT_BREAKER_COOLDOWN_S,
        metrics=None,
        clock=time.monotonic,
    ) -> None:
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False

    @classmethod
    def from_env(cls, metrics=None) -> "CircuitBreaker":
        return cls(
            threshold=_env_int(BREAKER_THRESHOLD_ENV, DEFAULT_BREAKER_THRESHOLD),
            cooldown_s=_env_float(BREAKER_COOLDOWN_ENV, DEFAULT_BREAKER_COOLDOWN_S),
            metrics=metrics,
        )

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, new_state: str) -> None:
        # caller holds the lock
        if new_state == self._state:
            return
        self._state = new_state
        if self.metrics is not None:
            self.metrics.record_breaker_transition(new_state)

    def allow(self) -> bool:
        """Admission check — consumes the half-open probe slot when it grants
        one, so exactly one request probes a recovering engine at a time."""
        if self.threshold <= 0:
            return True
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                self._transition(self.HALF_OPEN)
                self._probe_in_flight = True
                return True
            # HALF_OPEN: one probe at a time
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def would_reject(self) -> bool:
        """Non-consuming peek for HTTP pre-checks: True only while OPEN
        inside the cooldown. A cooldown-elapsed or half-open request must
        reach `allow()` so probing can happen — this never blocks it."""
        if self.threshold <= 0:
            return False
        with self._lock:
            return (
                self._state == self.OPEN
                and self._clock() - self._opened_at < self.cooldown_s
            )

    def record_success(self) -> None:
        if self.threshold <= 0:
            return
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != self.CLOSED:
                self._transition(self.CLOSED)

    def record_failure(self) -> None:
        if self.threshold <= 0:
            return
        with self._lock:
            self._probe_in_flight = False
            if self._state == self.HALF_OPEN:
                self._opened_at = self._clock()
                self._transition(self.OPEN)
                return
            self._consecutive_failures += 1
            if self._state == self.CLOSED and self._consecutive_failures >= self.threshold:
                self._opened_at = self._clock()
                self._transition(self.OPEN)

    def retry_after_s(self) -> float:
        # jittered (+-25%, SPOTTER_TPU_BACKOFF_JITTER): a deterministic
        # cooldown hint re-synchronizes every shed client into one retry
        # wave exactly when the breaker half-opens — the worst possible
        # moment for a thundering herd (ISSUE 8 satellite)
        with self._lock:
            if self._state != self.OPEN:
                return jittered_retry_after(1.0)
            return jittered_retry_after(
                max(self.cooldown_s - (self._clock() - self._opened_at), 1.0)
            )
