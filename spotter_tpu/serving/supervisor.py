"""Subprocess supervisor: restart a crashed/preempted replica with backoff.

k8s restarts pods, but inside a pod (and on bare VMs, and in the failover
test/bench harness) something must bring a dead server back — and do it in
seconds when the death was a preemption that already drained cleanly, while
NOT hot-looping when the server crashes at import time. Policy:

- exit 0 (operator stop) → supervisor exits 0;
- `PREEMPTED_EXIT_CODE` (drained preemption exit, serving/lifecycle.py) →
  immediate restart, backoff reset: the replica told us it shut down
  healthy. But a preemption SOURCE can outlive the child (the maintenance
  file is not deleted, a GCE maintenance window spans minutes), so only the
  first `--preempt-fast` consecutive sub-min-uptime preemption exits restart
  for free — after that the normal exponential backoff applies so the pair
  cannot hot-loop spawn→drain→exit;
- `FATAL_ENGINE_EXIT_CODE` (engine/errors.py: fatal device error with
  nothing left to degrade to) → immediate warm restart: the persistent
  compile cache makes the respawn cheap and the device usually comes back
  healthy after a re-init. Same fast-limit guard as preemption — a chip
  that stays dead must not hot-loop spawn→fatal→exit;
- `INTEGRITY_EXIT_CODE` (serving/lifecycle.py: weights attestation or
  golden-probe failure, ISSUE 17) → COLD restart with the persistent
  compile-cache dir quarantined (renamed aside, preserved for forensics):
  a warm restart would faithfully restore the exact cached state that just
  produced wrong answers, so this is the one exit where the cache is
  suspect by construction. Same fast-limit guard — corruption that
  survives a cold rebuild (bad checkpoint on disk, bad chip) must not
  hot-loop;
- any other exit → restart after exponential backoff (`--backoff-base`,
  doubling to `--backoff-max`); a child that stayed up ≥ `--min-uptime`
  resets the backoff. Backoff waits are FULL-JITTERED by default
  (`SPOTTER_TPU_BACKOFF_JITTER=0` disables): the actual wait is drawn
  uniformly from (0, cap] while the cap keeps its deterministic doubling.
  A fleet of supervisors preempted by the same maintenance wave would
  otherwise re-enter backoff in lockstep and thunder-herd the restarts
  (ISSUE 6) — with full jitter, seeded differently per process, they
  desynchronize;
- crash-loop circuit: more than `--crash-loop` consecutive sub-min-uptime
  crashes → give up and exit non-zero (let the orchestrator above decide).

Each (re)start exports `SPOTTER_TPU_RESTARTS=<n>` to the child so
`restarts_total` lands in the replica's /metrics, and rewrites `--pidfile`
so harnesses (tests, bench.py --failover) can target the CURRENT child with
preemption faults. SIGTERM to the supervisor forwards to the child and
exits with the child's code — the pod-level preStop path stays intact.

With `--manifest PATH --url URL` (ISSUE 16) the supervisor registers its
replica in the shared endpoints manifest at startup and deregisters only
on PERMANENT exit (clean stop, crash-loop circuit, SIGTERM) — it stays
registered across preemption (83) and fatal-engine (85) restarts, because
the replica identity survives them. That makes the manifest the control
plane's observation of record: a restarted controller adopts every entry
whose supervisor pid is still alive instead of double-spawning, and prunes
entries whose supervisor died without the finally block running (kill -9).
"""

import argparse
import logging
import os
import random
import signal
import subprocess
import sys
import threading
import time

from spotter_tpu.engine.errors import FATAL_ENGINE_EXIT_CODE
from spotter_tpu.serving.lifecycle import (
    COMPILE_CACHE_ENV,
    INTEGRITY_EXIT_CODE,
    PREEMPTED_EXIT_CODE,
    RESTARTS_ENV,
)

# The jitter knob moved to serving/resilience.py (ISSUE 8 satellite: the
# same switch now also governs the +-25% Retry-After jitter on 429/503
# hints); re-exported here so existing imports keep working.
from spotter_tpu.serving.resilience import (
    BACKOFF_JITTER_ENV,  # noqa: F401
    jitter_enabled_from_env,
)

logger = logging.getLogger(__name__)

DEFAULT_BACKOFF_BASE_S = 0.5
DEFAULT_BACKOFF_MAX_S = 30.0
DEFAULT_MIN_UPTIME_S = 5.0
DEFAULT_CRASH_LOOP_LIMIT = 5
DEFAULT_PREEMPT_FAST_LIMIT = 3
CRASH_LOOP_EXIT_CODE = 84  # distinct from the child's codes and from 83


def quarantine_compile_cache() -> str | None:
    """Move the persistent compile-cache dir aside (ISSUE 17).

    Called before respawning after an integrity exit (86): the cache is
    the one piece of state a cold restart would otherwise faithfully
    re-ingest, so it is renamed — never deleted, the quarantined copy IS
    the forensic artifact — to `<dir>.quarantined.<n>`. The child then
    recreates the dir empty and recompiles from scratch. Returns the
    quarantine path, or None when no cache dir is configured/present."""
    cache_dir = os.environ.get(COMPILE_CACHE_ENV, "").strip()
    if not cache_dir or not os.path.isdir(cache_dir):
        return None
    n = 0
    while True:
        target = f"{cache_dir.rstrip(os.sep)}.quarantined.{n}"
        if not os.path.exists(target):
            break
        n += 1
    try:
        os.rename(cache_dir, target)
    except OSError:
        logger.exception("could not quarantine compile cache %s", cache_dir)
        return None
    logger.warning(
        "quarantined suspect compile cache: %s -> %s", cache_dir, target
    )
    return target

class Supervisor:
    def __init__(
        self,
        cmd: list[str],
        backoff_base_s: float = DEFAULT_BACKOFF_BASE_S,
        backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
        min_uptime_s: float = DEFAULT_MIN_UPTIME_S,
        crash_loop_limit: int = DEFAULT_CRASH_LOOP_LIMIT,
        preempt_fast_limit: int = DEFAULT_PREEMPT_FAST_LIMIT,
        pidfile: str | None = None,
        jitter: bool | None = None,
        rng: random.Random | None = None,
    ) -> None:
        if not cmd:
            raise ValueError("supervisor needs a command")
        self.cmd = cmd
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.min_uptime_s = min_uptime_s
        self.crash_loop_limit = crash_loop_limit
        self.preempt_fast_limit = preempt_fast_limit
        self.pidfile = pidfile
        self.jitter = jitter_enabled_from_env() if jitter is None else jitter
        # per-process RNG (seedable in tests): two supervisors restarted by
        # the same preemption wave draw different waits and desynchronize
        self._rng = rng if rng is not None else random.Random()
        self._backoff_s = 0.0  # deterministic doubling cap; waits jitter off it
        self.restarts_total = 0
        self.child: subprocess.Popen | None = None
        self._terminating = False
        # Set by _forward_term so the backoff wait wakes immediately instead
        # of time.sleep resuming after the handler (PEP 475) and the loop
        # spawning a child nobody asked for.
        self._term_event = threading.Event()

    def _spawn(self) -> subprocess.Popen:
        env = dict(os.environ)
        env[RESTARTS_ENV] = str(self.restarts_total)
        child = subprocess.Popen(self.cmd, env=env)
        if self.pidfile:
            tmp = f"{self.pidfile}.tmp"
            with open(tmp, "w") as f:
                f.write(str(child.pid))
            os.replace(tmp, self.pidfile)  # atomic: readers never see partial
        logger.info(
            "spawned child pid=%d (restart #%d): %s",
            child.pid, self.restarts_total, " ".join(self.cmd),
        )
        return child

    def _forward_term(self, signum, frame) -> None:
        self._terminating = True
        self._term_event.set()
        if self.child is not None and self.child.poll() is None:
            self.child.send_signal(signal.SIGTERM)

    def _reset_backoff(self) -> None:
        self._backoff_s = 0.0

    def _bump_backoff(self) -> float:
        """Advance the deterministic doubling cap, then draw the actual wait:
        full jitter (uniform over (0, cap]) when enabled, else the cap
        itself. The cap trajectory stays identical across supervisors (so
        the crash-loop window is predictable); only the waits decorrelate."""
        self._backoff_s = min(
            max(self._backoff_s * 2.0, self.backoff_base_s), self.backoff_max_s
        )
        if not self.jitter:
            return self._backoff_s
        return self._rng.uniform(0.0, self._backoff_s)

    def run(self) -> int:
        """Supervise until the child exits cleanly, the crash-loop circuit
        trips, or SIGTERM. Returns the exit code to propagate."""
        signal.signal(signal.SIGTERM, self._forward_term)
        self._reset_backoff()
        consecutive_fast_crashes = 0
        consecutive_fast_preempts = 0
        consecutive_fast_fatals = 0
        consecutive_fast_integrity = 0
        code = 0
        while True:
            if self._terminating:
                # SIGTERM landed while no child was running (e.g. during the
                # backoff wait): do NOT spawn a replacement the signal could
                # never reach — propagate the last child's code.
                logger.info("terminated between children; exiting %d", code)
                return code
            started = time.monotonic()
            self.child = self._spawn()
            if self._terminating and self.child.poll() is None:
                # signal raced the spawn: the handler ran before self.child
                # pointed at this child, so forward SIGTERM ourselves
                self.child.send_signal(signal.SIGTERM)
            code = self.child.wait()
            uptime = time.monotonic() - started
            if self._terminating:
                logger.info("terminated; child exited %d", code)
                return code
            if code == 0:
                logger.info("child exited cleanly; supervisor done")
                return 0
            if code == FATAL_ENGINE_EXIT_CODE:
                # controlled fatal-device exit (engine fault domain): restart
                # immediately — the persistent compile cache makes it a warm
                # bring-up and a re-initialized runtime usually gets the
                # device back. Same hot-loop guard as preemption: a chip
                # that STAYS dead falls back to exponential backoff after
                # `preempt_fast_limit` consecutive fast exits.
                consecutive_fast_crashes = 0
                consecutive_fast_preempts = 0
                consecutive_fast_integrity = 0
                if uptime >= self.min_uptime_s:
                    consecutive_fast_fatals = 0
                else:
                    consecutive_fast_fatals += 1
                if consecutive_fast_fatals <= self.preempt_fast_limit:
                    logger.warning(
                        "child hit a fatal engine error (exit %d); immediate "
                        "warm restart via compile cache", code,
                    )
                    self._reset_backoff()
                else:
                    wait_s = self._bump_backoff()
                    logger.warning(
                        "child hit fatal engine errors (exit %d) %d times under "
                        "%.1f s uptime — device appears to stay dead; "
                        "restarting in %.2f s",
                        code, consecutive_fast_fatals, self.min_uptime_s, wait_s,
                    )
                    if self._term_event.wait(wait_s):
                        logger.info("terminated during backoff; exiting %d", code)
                        return code
            elif code == INTEGRITY_EXIT_CODE:
                # integrity failure (ISSUE 17): attestation or golden probe
                # caught wrong outputs. COLD restart — quarantine the
                # compile-cache dir first, because a warm restart would
                # faithfully restore the exact state that just failed. The
                # fast-limit guard catches corruption a cold rebuild cannot
                # fix (bad checkpoint on disk, bad chip): backoff, don't
                # hot-loop recompiles.
                consecutive_fast_crashes = 0
                consecutive_fast_preempts = 0
                consecutive_fast_fatals = 0
                if uptime >= self.min_uptime_s:
                    consecutive_fast_integrity = 0
                else:
                    consecutive_fast_integrity += 1
                quarantine_compile_cache()
                if consecutive_fast_integrity <= self.preempt_fast_limit:
                    logger.warning(
                        "child failed integrity verification (exit %d); "
                        "cold restart with compile cache quarantined", code,
                    )
                    self._reset_backoff()
                else:
                    wait_s = self._bump_backoff()
                    logger.warning(
                        "child failed integrity verification (exit %d) %d "
                        "times under %.1f s uptime — corruption survives "
                        "cold restarts; restarting in %.2f s",
                        code, consecutive_fast_integrity, self.min_uptime_s,
                        wait_s,
                    )
                    if self._term_event.wait(wait_s):
                        logger.info("terminated during backoff; exiting %d", code)
                        return code
            elif code == PREEMPTED_EXIT_CODE:
                # drained preemption: the replica is healthy software on
                # yanked capacity — restart immediately, no backoff debt. But
                # the source can persist (the maintenance file is never
                # deleted, a GCE window spans minutes), so only the first
                # `preempt_fast_limit` consecutive sub-min-uptime preemption
                # exits restart for free; after that, normal backoff.
                consecutive_fast_crashes = 0
                consecutive_fast_fatals = 0
                consecutive_fast_integrity = 0
                if uptime >= self.min_uptime_s:
                    consecutive_fast_preempts = 0
                else:
                    consecutive_fast_preempts += 1
                if consecutive_fast_preempts <= self.preempt_fast_limit:
                    logger.warning(
                        "child preempted (exit %d); immediate warm restart", code
                    )
                    self._reset_backoff()
                else:
                    wait_s = self._bump_backoff()
                    logger.warning(
                        "child preempted (exit %d) %d times under %.1f s uptime "
                        "— preemption source persists; restarting in %.2f s",
                        code, consecutive_fast_preempts, self.min_uptime_s, wait_s,
                    )
                    if self._term_event.wait(wait_s):
                        logger.info("terminated during backoff; exiting %d", code)
                        return code
            else:
                consecutive_fast_preempts = 0
                consecutive_fast_fatals = 0
                consecutive_fast_integrity = 0
                if uptime >= self.min_uptime_s:
                    self._reset_backoff()
                    consecutive_fast_crashes = 0
                else:
                    consecutive_fast_crashes += 1
                    if consecutive_fast_crashes > self.crash_loop_limit:
                        logger.error(
                            "crash loop: %d consecutive crashes under %.1f s "
                            "uptime; giving up",
                            consecutive_fast_crashes, self.min_uptime_s,
                        )
                        # persist whatever the supervisor-side flight
                        # recorder holds (ISSUE 7; usually empty — the
                        # replica's own ring dumps on 83/85 in-process)
                        from spotter_tpu.obs.recorder import dump_for_exit

                        dump_for_exit(CRASH_LOOP_EXIT_CODE)
                        return CRASH_LOOP_EXIT_CODE
                wait_s = self._bump_backoff()
                logger.warning(
                    "child crashed (exit %d, uptime %.1f s); restarting in %.2f s",
                    code, uptime, wait_s,
                )
                if self._term_event.wait(wait_s):
                    logger.info("terminated during backoff; exiting %d", code)
                    return code
            self.restarts_total += 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="spotter-tpu replica supervisor",
        usage="python -m spotter_tpu.serving.supervisor [opts] -- CMD [ARG...]",
    )
    parser.add_argument("--backoff-base", type=float, default=DEFAULT_BACKOFF_BASE_S)
    parser.add_argument("--backoff-max", type=float, default=DEFAULT_BACKOFF_MAX_S)
    parser.add_argument("--min-uptime", type=float, default=DEFAULT_MIN_UPTIME_S)
    parser.add_argument("--crash-loop", type=int, default=DEFAULT_CRASH_LOOP_LIMIT)
    parser.add_argument("--preempt-fast", type=int, default=DEFAULT_PREEMPT_FAST_LIMIT,
                        help="consecutive sub-min-uptime preemption exits that "
                        "restart immediately before normal backoff applies")
    parser.add_argument("--backoff-jitter", choices=["on", "off"], default=None,
                        help=f"full-jitter backoff waits (default from "
                        f"{BACKOFF_JITTER_ENV}, on unless set to 0)")
    parser.add_argument("--pidfile", default=None,
                        help="rewritten with the current child pid on every spawn")
    parser.add_argument("--manifest", default=None,
                        help="endpoints manifest (serving/statestore.py) to "
                        "register this replica in for controller adoption")
    parser.add_argument("--url", default=None,
                        help="replica base URL recorded in --manifest")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="child command (after --)")
    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no child command given (use -- CMD ARG...)")
    if args.manifest and not args.url:
        parser.error("--manifest requires --url (the manifest key)")
    logging.basicConfig(level=logging.INFO)
    sup = Supervisor(
        cmd,
        backoff_base_s=args.backoff_base,
        backoff_max_s=args.backoff_max,
        min_uptime_s=args.min_uptime,
        crash_loop_limit=args.crash_loop,
        preempt_fast_limit=args.preempt_fast,
        pidfile=args.pidfile,
        jitter=None if args.backoff_jitter is None
        else args.backoff_jitter == "on",
    )
    manifest = None
    if args.manifest:
        # stdlib-only import (no jax/httpx): keep supervisor bring-up light
        from spotter_tpu.serving.statestore import EndpointsManifest

        manifest = EndpointsManifest(args.manifest)
        manifest.add(
            args.url,
            pool=os.environ.get("SPOTTER_TPU_POOL", ""),
            version=os.environ.get("SPOTTER_TPU_BUILD_VERSION", ""),
            preempt_file=os.environ.get("SPOTTER_TPU_PREEMPTION_FILE", ""),
            pidfile=args.pidfile or "",
            supervisor_pid=os.getpid(),
        )
    try:
        return sup.run()
    finally:
        if manifest is not None:
            # permanent exit only: preemption/fatal restarts never reach here
            try:
                manifest.remove(args.url)
            except OSError:
                pass  # best-effort — the reconciler prunes dead entries


if __name__ == "__main__":
    sys.exit(main())
