"""Fleet controller: spot-aware pools above supervisor + replica_pool + router.

PRs 1-4 made ONE replica survivable (drain/exit-83 lifecycle, supervisor,
engine fault domain); the fleet above it was still a flat list — one
correlated preemption wave, the NORMAL failure mode of spot/preemptible TPU
capacity (Spotlight, arXiv:2606.19004), took down SLO and bulk traffic alike
and then amplified the damage with unbudgeted replays. This module is the
tier that makes preemptible capacity first-class (DeepServe,
arXiv:2501.14417, is the blueprint for the serverless half):

- **Pools**: replicas are grouped into `on_demand` and `spot` pools, each a
  `ReplicaPool` (health loop, ejection, replay) with its own retry-budget
  slice, supervised members, and gauges. Requests are CLASSED — an
  `X-Request-Class: slo|bulk` header or a `request_class` payload key (a
  payload carrying `deadline_ms` defaults to slo) — and SLO traffic is
  PINNED to on_demand while bulk drains to spot. Bulk never spills onto the
  on_demand pool while spot capacity exists: protecting the SLO pool from a
  bulk stampede is the point of the split. (Bulk falls back to on_demand
  only when NO spot capacity is configured at all.)
- **Preemption-storm survival**: a maintenance signal on a spot member
  (exit 83, SPOTTER_TPU_PREEMPTION_FILE/_URL — the PR 2 machinery) drains
  only that member; its in-flight and queued work replays onto survivors
  under the pool's retry budget (SPOTTER_TPU_RETRY_BUDGET_PCT,
  replica_pool.RetryBudget), so spot loss degrades bulk goodput but never
  fails an SLO request. Members whose SUPERVISOR process dies (crash-loop
  exit 84, host gone) are re-spawned with full-jittered exponential backoff
  so a storm's restarts don't thunder-herd. The chaos harness can inject a
  storm in-process: `SPOTTER_TPU_FAULTS=preempt_storm=N` preempts N ready
  spot members through their handles (testing/faults.py).
- **Scale-to-zero + restore**: a managed pool idle for
  `SPOTTER_TPU_SCALE_TO_ZERO_S` drains and stops all members; the next
  classed request triggers a demand restore through the persistent compile
  cache (SPOTTER_TPU_COMPILE_CACHE_DIR), with `time_to_ready_s` measured
  restore-trigger -> first member available and published in /metrics —
  the <15 s (stubbed) gate `bench.py --preemption-storm` records.

`make_fleet_app` is the HTTP surface (/detect with classification,
/healthz, /livez, /metrics with `pool_size{pool,state}`,
`preemptions_total`, `replays_total`, `retry_budget_exhausted_total`);
`python -m spotter_tpu.serving.fleet` runs it over static endpoint lists,
and `python -m spotter_tpu.serving.router --spot-endpoints ...` reuses the
same app from the existing edge entrypoint. Managed (spawning) fleets are
built in-process: `testing/cluster.py::fleet_spawner` supplies subprocess
member handles for the bench and chaos tests.
"""

import argparse
import asyncio
import json
import logging
import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol

from aiohttp import web

from spotter_tpu import obs
from spotter_tpu.obs import http as obs_http
from spotter_tpu.obs import logs as obs_logs
from spotter_tpu.obs.aggregate import FleetAggregator
from spotter_tpu.serving import wire
from spotter_tpu.serving.replica_pool import (
    PoolExhaustedError,
    ReplicaPool,
    RetryBudget,
)
from spotter_tpu.testing import faults

logger = logging.getLogger(__name__)

# request classes
SLO = "slo"
BULK = "bulk"
# canonical pool names (specs may add others; these two get the routing rules)
ON_DEMAND = "on_demand"
SPOT = "spot"

REQUEST_CLASS_HEADER = "X-Request-Class"
REQUEST_CLASS_KEY = "request_class"

DEFAULT_CLASS_ENV = "SPOTTER_TPU_POOL_DEFAULT_CLASS"
SCALE_TO_ZERO_ENV = "SPOTTER_TPU_SCALE_TO_ZERO_S"
RESTORE_WAIT_ENV = "SPOTTER_TPU_POOL_RESTORE_WAIT_S"
UNAVAILABLE_WAIT_ENV = "SPOTTER_TPU_POOL_UNAVAILABLE_WAIT_S"
RESPAWN_BASE_ENV = "SPOTTER_TPU_POOL_RESPAWN_BASE_S"

DEFAULT_RESTORE_WAIT_S = 20.0
DEFAULT_UNAVAILABLE_WAIT_S = 3.0
DEFAULT_RESPAWN_BASE_S = 0.5
DEFAULT_RESPAWN_MAX_S = 30.0
DEFAULT_TICK_S = 0.2

# member states for the pool_size{pool,state} gauge
READY = "ready"
STARTING = "starting"
DOWN = "down"
DEAD = "dead"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def default_class_from_env() -> str:
    """Unclassified traffic defaults to SLO: treating unknown requests as
    latency-critical (pinned to on-demand) is the conservative choice —
    bulk must OPT IN to ride preemptible capacity."""
    raw = os.environ.get(DEFAULT_CLASS_ENV, "").strip().lower()
    return raw if raw in (SLO, BULK) else SLO


def classify_request(
    headers=None, payload=None, default: Optional[str] = None
) -> tuple[str, dict]:
    """(request_class, forwardable_payload). Precedence: the
    X-Request-Class header, then a `request_class` payload key (stripped
    before forwarding — it is fleet routing metadata, not detector input),
    then "slo" for payloads carrying a deadline tag, then the default."""
    cls = ""
    if headers is not None:
        cls = str(headers.get(REQUEST_CLASS_HEADER, "")).strip().lower()
    if isinstance(payload, dict):
        if not cls:
            cls = str(payload.get(REQUEST_CLASS_KEY, "")).strip().lower()
        if REQUEST_CLASS_KEY in payload:
            payload = {
                k: v for k, v in payload.items() if k != REQUEST_CLASS_KEY
            }
        if not cls and "deadline_ms" in payload:
            cls = SLO
    if cls not in (SLO, BULK):
        cls = default if default in (SLO, BULK) else default_class_from_env()
    return cls, payload


class MemberHandle(Protocol):
    """What the controller needs from a managed member: the subprocess
    implementation is testing/cluster.py::FleetMember (supervisor +
    standalone stub server + per-member maintenance file); tests substitute
    in-process fakes."""

    url: str

    def alive(self) -> bool: ...

    def preempt(self) -> None: ...

    def clear_preemption(self) -> None: ...

    def shutdown(self, timeout_s: float = 10.0) -> str: ...


@dataclass
class PoolSpec:
    """One pool's configuration. Exactly one population style per spec:
    `endpoints` (static, unmanaged — no respawn/scale-to-zero),
    `handles` (pre-spawned managed members), or `spawner` + `target_size`
    (the controller spawns and maintains the population)."""

    name: str
    endpoints: list[str] = field(default_factory=list)
    handles: list = field(default_factory=list)
    spawner: Optional[Callable[[], MemberHandle]] = None
    target_size: int = 0
    # None -> SPOTTER_TPU_SCALE_TO_ZERO_S (managed pools only); <= 0 -> off
    scale_to_zero_s: Optional[float] = None


class _Member:
    def __init__(self, url: str, handle: Optional[MemberHandle] = None) -> None:
        self.url = url.rstrip("/")
        self.handle = handle
        self.was_available = False
        self.ever_available = False
        self.preempt_pending = False


class FleetPool:
    """A named pool: its ReplicaPool (routing/health/replay), its managed
    members, and its lifecycle state (scale-to-zero, restore timing)."""

    def __init__(self, spec: PoolSpec, pool: ReplicaPool,
                 scale_to_zero_s: float) -> None:
        self.spec = spec
        self.pool = pool
        self.scale_to_zero_s = scale_to_zero_s
        self.members: list[_Member] = [_Member(u) for u in spec.endpoints]
        self.last_used = time.monotonic()
        self.scaled_to_zero = False
        self.restoring = False
        self.restore_started: Optional[float] = None
        self._restore_counts = False  # True only for post-scale-to-zero restores
        self.time_to_ready_s: Optional[float] = None
        self.available = asyncio.Event()
        # gauges/counters
        self.preemptions_total = 0
        self.respawns_total = 0
        self.scale_to_zero_total = 0
        self.restores_total = 0
        # jittered-respawn state
        self._respawn_backoff_s = 0.0
        self._respawn_due: list[float] = []

    @property
    def managed(self) -> bool:
        return self.spec.spawner is not None or any(
            m.handle is not None for m in self.members
        )

    def has_capacity(self) -> bool:
        """Can this pool EVER serve — members now, or a spawner that can
        make some? (Routing falls back across pools only when this is
        False: an empty-because-scaled-to-zero pool still has capacity.)"""
        if self.members:
            return True
        return self.spec.spawner is not None and self.spec.target_size > 0

    def member_for(self, url: str) -> Optional[_Member]:
        url = url.rstrip("/")
        for m in self.members:
            if m.url == url:
                return m
        return None

    def member_states(self, now: float) -> dict[str, int]:
        sizes = {READY: 0, STARTING: 0, DOWN: 0, DEAD: 0}
        for m in self.members:
            if m.handle is not None and not m.handle.alive():
                sizes[DEAD] += 1
                continue
            r = self.pool.replica_for(m.url)
            if r is not None and r.available(now):
                sizes[READY] += 1
            elif m.ever_available:
                sizes[DOWN] += 1
            else:
                sizes[STARTING] += 1
        return sizes


class FleetController:
    """Routes classed traffic to pools and keeps the pools alive: observes
    member health transitions, re-spawns dead members with jittered backoff,
    applies injected preemption storms, scales idle pools to zero, and
    restores them on demand. One background tick task; all state is
    event-loop-confined."""

    def __init__(
        self,
        specs: list[PoolSpec],
        tick_s: float = DEFAULT_TICK_S,
        retry_budget_pct: Optional[float] = None,
        restore_wait_s: Optional[float] = None,
        unavailable_wait_s: Optional[float] = None,
        respawn_base_s: Optional[float] = None,
        respawn_max_s: float = DEFAULT_RESPAWN_MAX_S,
        rng: Optional[random.Random] = None,
        pool_kwargs: Optional[dict] = None,
    ) -> None:
        if not specs:
            raise ValueError("FleetController needs at least one PoolSpec")
        self.tick_s = tick_s
        self.restore_wait_s = (
            restore_wait_s
            if restore_wait_s is not None
            else _env_float(RESTORE_WAIT_ENV, DEFAULT_RESTORE_WAIT_S)
        )
        self.unavailable_wait_s = (
            unavailable_wait_s
            if unavailable_wait_s is not None
            else _env_float(UNAVAILABLE_WAIT_ENV, DEFAULT_UNAVAILABLE_WAIT_S)
        )
        self.respawn_base_s = (
            respawn_base_s
            if respawn_base_s is not None
            else _env_float(RESPAWN_BASE_ENV, DEFAULT_RESPAWN_BASE_S)
        )
        self.respawn_max_s = respawn_max_s
        self._rng = rng if rng is not None else random.Random()
        self.default_class = default_class_from_env()
        env_stz = _env_float(SCALE_TO_ZERO_ENV, 0.0)
        self.pools: dict[str, FleetPool] = {}
        for spec in specs:
            if spec.name in self.pools:
                raise ValueError(f"duplicate pool {spec.name!r}")
            # each pool gets its OWN budget slice: a bulk-tier storm must not
            # starve SLO-tier failover of replay tokens
            rp = ReplicaPool(
                list(spec.endpoints),
                allow_empty=True,
                retry_budget=RetryBudget(pct=retry_budget_pct),
                **(pool_kwargs or {}),
            )
            stz = spec.scale_to_zero_s
            if stz is None:
                stz = env_stz if (spec.spawner is not None) else 0.0
            self.pools[spec.name] = FleetPool(spec, rp, stz)
        self._task: Optional[asyncio.Task] = None
        self.storms_total = 0
        self.class_requests = {SLO: 0, BULK: 0}
        self.class_failures = {SLO: 0, BULK: 0}
        # leader fencing hook (ISSUE 16): when set (serving/reconcile.py
        # installs `Reconciler.fence`), every spawn re-checks leadership
        # and raises statestore.StaleLeaderError for a deposed controller
        # — stale actuations are refused at the boundary, not logged after
        self.fence: Optional[Callable[[], object]] = None

    # ---- lifecycle ----

    async def start(self) -> None:
        for fp in self.pools.values():
            for h in fp.spec.handles:
                self._adopt(fp, h)
            if fp.spec.spawner is not None:
                while len(fp.members) < fp.spec.target_size:
                    self._spawn(fp)
            if fp.members and fp.pool.has_available() is False:
                # initial bring-up: measure time-to-first-available
                fp.restoring = True
                fp.restore_started = time.monotonic()
                fp._restore_counts = False
            await fp.pool.start()
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    async def stop(self, shutdown_members: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        for fp in self.pools.values():
            await fp.pool.stop()
        if shutdown_members:
            loop = asyncio.get_running_loop()
            waits = [
                loop.run_in_executor(None, m.handle.shutdown)
                for fp in self.pools.values()
                for m in fp.members
                if m.handle is not None
            ]
            if waits:
                await asyncio.gather(*waits, return_exceptions=True)

    def _adopt(self, fp: FleetPool, handle: MemberHandle) -> None:
        fp.pool.add_endpoint(handle.url, healthy=False)
        fp.members.append(_Member(handle.url, handle))

    def _spawn(self, fp: FleetPool) -> None:
        if self.fence is not None:
            self.fence()  # StaleLeaderError for a deposed controller
        handle = fp.spec.spawner()
        self._adopt(fp, handle)
        logger.info("pool %s: spawned member %s", fp.spec.name, handle.url)

    # ---- reconciler surface (ISSUE 16) ----

    def adopt_endpoint(
        self, pool_name: str, handle: MemberHandle,
        version: Optional[str] = None,
    ) -> bool:
        """Adopt an already-running member (orphan adoption: the reconcile
        loop found it in the endpoints manifest after a controller
        restart). Idempotent per URL — re-adoption of a known member is a
        no-op, which is what makes restart free of double-spawns."""
        fp = self.pools.get(pool_name)
        if fp is None or fp.member_for(handle.url) is not None:
            return False
        self._adopt(fp, handle)
        if version:
            fp.pool.set_version(handle.url, version)
        logger.info("pool %s: adopted member %s", pool_name, handle.url)
        return True

    async def set_target_size(self, pool_name: str, n: int) -> None:
        """Apply a journaled desired size. Growth is satisfied by
        `ensure_population` on the next reconcile step; shrink retires the
        newest members past the target (remove from routing first, then
        shut down — the scale-to-zero discipline, per member)."""
        fp = self.pools[pool_name]
        fp.spec.target_size = max(int(n), 0)
        excess = list(fp.members)[fp.spec.target_size:]
        if not excess:
            return
        for m in excess:
            fp.pool.remove_endpoint(m.url)
            fp.members.remove(m)
        logger.info(
            "pool %s: shrunk to target %d (%d members retired)",
            pool_name, fp.spec.target_size, len(excess),
        )
        loop = asyncio.get_running_loop()
        waits = [
            loop.run_in_executor(None, m.handle.shutdown)
            for m in excess
            if m.handle is not None
        ]
        if waits:
            await asyncio.gather(*waits, return_exceptions=True)

    def ensure_population(self, pool_name: str) -> int:
        """Spawn up to the desired size, counting members a retire already
        scheduled for jittered respawn — the reconcile loop's convergence
        step must not race the controller's own backoff machinery into
        double-spawning."""
        fp = self.pools.get(pool_name)
        if fp is None or fp.spec.spawner is None or fp.scaled_to_zero:
            return 0
        spawned = 0
        while len(fp.members) + len(fp._respawn_due) < fp.spec.target_size:
            self._spawn(fp)
            spawned += 1
        return spawned

    # ---- routing ----

    def pool_for_class(self, cls: str) -> FleetPool:
        """SLO pins to on_demand; bulk drains to spot. The fallback pool is
        used only when the preferred one has NO capacity configured at all
        (a storm-suspended or scaled-to-zero pool still HAS capacity — bulk
        rides out the storm on spot rather than stampeding the SLO pool)."""
        preferred = ON_DEMAND if cls == SLO else SPOT
        fallback = ON_DEMAND if cls == BULK else SPOT
        fp = self.pools.get(preferred)
        if fp is not None and fp.has_capacity():
            return fp
        alt = self.pools.get(fallback)
        if alt is not None and alt.has_capacity():
            return alt
        pick = fp or alt
        return pick if pick is not None else next(iter(self.pools.values()))

    def _maybe_restore(self, fp: FleetPool) -> None:
        """Demand restore: spawn the missing population NOW (no backoff —
        this is deliberate demand, not a crash loop) and start the
        time-to-ready clock."""
        if fp.spec.spawner is None or fp.restoring:
            return
        missing = fp.spec.target_size - len(fp.members)
        if missing <= 0:
            return
        fp.restoring = True
        fp.restore_started = time.monotonic()
        fp._restore_counts = fp.scaled_to_zero
        fp._respawn_due.clear()
        for _ in range(missing):
            self._spawn(fp)

    async def request(
        self,
        path: str,
        payload: dict,
        cls: Optional[str] = None,
        headers: Optional[dict] = None,
        pool: Optional[str] = None,
    ):
        """Route one classed request through its pool, waking a
        scaled-to-zero pool on the way. Bulk requests tolerate a bounded
        wait for a restoring/stormed pool; SLO requests fail fast (the
        caller turns PoolExhaustedError subclasses into 503 + Retry-After).
        `pool` (ISSUE 20) overrides class routing with a named pool — the
        model-multiplexed edge resolves the model FIRST and pins the
        request to that family's pool; the class still drives wait/accounting
        behavior."""
        if cls not in (SLO, BULK):
            cls = self.default_class
        self.class_requests[cls] += 1
        fp = self.pools[pool] if pool is not None else self.pool_for_class(cls)
        fp.last_used = time.monotonic()
        if not fp.pool.has_available():
            self._maybe_restore(fp)
            if fp.restoring or cls == BULK:
                wait_s = (
                    self.restore_wait_s if fp.restoring
                    else self.unavailable_wait_s
                )
                deadline = time.monotonic() + wait_s
                # re-check REAL availability each wakeup: the event may be
                # stale-set for a beat around a scale-down/retire transition
                while not fp.pool.has_available():
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break  # fall through: the pool raises its fast 503
                    try:
                        await asyncio.wait_for(
                            fp.available.wait(), min(remaining, self.tick_s)
                        )
                    except asyncio.TimeoutError:
                        pass
            fp.last_used = time.monotonic()
        try:
            return await fp.pool.request(path, payload, headers=headers)
        except PoolExhaustedError:
            self.class_failures[cls] += 1
            raise

    async def detect(self, payload: dict, cls: Optional[str] = None) -> dict:
        resp = await self.request("/detect", payload, cls)
        return resp.json()

    # ---- supervision tick ----

    async def _run(self) -> None:
        while True:
            try:
                await self._tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("fleet tick failed")
            await asyncio.sleep(self.tick_s)

    async def _tick(self) -> None:
        now = time.monotonic()
        self._apply_storm()
        for fp in self.pools.values():
            self._observe_members(fp, now)
            self._respawn_due_members(fp, now)
            await self._maybe_scale_to_zero(fp, now)
            if fp.pool.has_available():
                if fp.restoring:
                    fp.restoring = False
                    fp.time_to_ready_s = time.monotonic() - fp.restore_started
                    if fp._restore_counts:
                        fp.restores_total += 1
                    fp.scaled_to_zero = False
                    # the idle clock starts when capacity is READY: a
                    # bring-up longer than scale_to_zero_s must not get the
                    # fresh pool reclaimed on the very next tick
                    fp.last_used = time.monotonic()
                    logger.info(
                        "pool %s: available after %.2f s",
                        fp.spec.name, fp.time_to_ready_s,
                    )
                fp.available.set()
            else:
                fp.available.clear()

    def _apply_storm(self) -> None:
        """Injected preemption storm (SPOTTER_TPU_FAULTS=preempt_storm=N or
        faults.inject in-process): preempt up to N currently-available spot
        members through their handles — the chaos entry point for
        `bench.py --preemption-storm`."""
        spot = self.pools.get(SPOT)
        now = time.monotonic()
        candidates = []
        for m in (spot.members if spot is not None else []):
            if m.handle is None:
                continue
            r = spot.pool.replica_for(m.url)
            if r is not None and r.available(now):
                candidates.append(m)
        if not candidates:
            # leave an armed storm for a tick that HAS ready targets: a
            # maintenance wave hits running capacity, not an empty pool
            return
        n = faults.take_preempt_storm()
        if n <= 0:
            return
        targets = candidates[:n]
        for m in targets:
            try:
                m.handle.preempt()
                m.preempt_pending = True
            except Exception:
                logger.exception("storm: preempting %s failed", m.url)
        if targets:
            self.storms_total += 1
            logger.warning(
                "preemption storm injected: %d of %d spot members",
                len(targets), len(spot.members),
            )

    def _observe_members(self, fp: FleetPool, now: float) -> None:
        for m in list(fp.members):
            if m.handle is not None and not m.handle.alive():
                # the SUPERVISOR process died (crash-loop exit 84, host
                # gone): retire the member and re-spawn on jittered backoff
                self._retire(fp, m, now)
                continue
            r = fp.pool.replica_for(m.url)
            avail = r is not None and r.available(now)
            if avail:
                m.ever_available = True
            if m.was_available and not avail:
                if fp.spec.name == SPOT:
                    # a spot member dropping out of ready IS a preemption in
                    # this capacity class (drain via maintenance signal or a
                    # straight kill) — the gauge the storm bench watches
                    fp.preemptions_total += 1
                if m.preempt_pending and m.handle is not None:
                    # the maintenance file did its job (the child saw it and
                    # drained): clear it so the supervisor's respawned child
                    # doesn't immediately re-preempt itself
                    try:
                        m.handle.clear_preemption()
                    except Exception:
                        logger.exception("clearing preemption on %s failed", m.url)
                    m.preempt_pending = False
            m.was_available = avail

    def _retire(self, fp: FleetPool, m: _Member, now: float) -> None:
        fp.pool.remove_endpoint(m.url)
        fp.members.remove(m)
        logger.warning("pool %s: member %s dead; retired", fp.spec.name, m.url)
        if fp.spec.spawner is None or fp.scaled_to_zero:
            return
        # full-jitter exponential backoff on the replacement spawn: a storm
        # that kills many members at once must not respawn them in lockstep
        fp._respawn_backoff_s = min(
            max(fp._respawn_backoff_s * 2.0, self.respawn_base_s),
            self.respawn_max_s,
        )
        delay = self._rng.uniform(0.0, fp._respawn_backoff_s)
        fp._respawn_due.append(now + delay)
        fp._respawn_due.sort()

    def _respawn_due_members(self, fp: FleetPool, now: float) -> None:
        while (
            fp._respawn_due
            and fp._respawn_due[0] <= now
            and len(fp.members) < fp.spec.target_size
        ):
            fp._respawn_due.pop(0)
            self._spawn(fp)
            fp.respawns_total += 1
        if (
            not fp._respawn_due
            and fp.members
            and len(fp.members) >= fp.spec.target_size
            and fp.pool.has_available()
        ):
            fp._respawn_backoff_s = 0.0

    async def _maybe_scale_to_zero(self, fp: FleetPool, now: float) -> None:
        if (
            fp.scale_to_zero_s <= 0
            or fp.scaled_to_zero
            or fp.restoring
            or not fp.members
            or fp.spec.spawner is None
            or now - fp.last_used < fp.scale_to_zero_s
        ):
            return
        members = list(fp.members)
        logger.info(
            "pool %s: idle %.1f s; scaling %d members to zero",
            fp.spec.name, now - fp.last_used, len(members),
        )
        fp.scaled_to_zero = True
        fp.scale_to_zero_total += 1
        fp._respawn_due.clear()
        for m in members:
            fp.pool.remove_endpoint(m.url)
            fp.members.remove(m)
        # clear availability NOW: the member shutdowns awaited below take
        # seconds, and a demand-restore request landing in that window must
        # wait on the event, not sail through on its stale set state
        fp.available.clear()
        loop = asyncio.get_running_loop()
        waits = [
            loop.run_in_executor(None, m.handle.shutdown)
            for m in members
            if m.handle is not None
        ]
        if waits:
            await asyncio.gather(*waits, return_exceptions=True)

    # ---- observability ----

    def snapshot(self) -> dict:
        now = time.monotonic()
        pools = {}
        pool_size = {}
        preemptions = replays = budget_exhausted = suspended = 0
        time_to_ready = {}
        for name, fp in self.pools.items():
            sizes = fp.member_states(now)
            psnap = fp.pool.snapshot()
            preemptions += fp.preemptions_total
            replays += psnap["pool_replays_total"]
            budget_exhausted += psnap["pool_retry_budget_exhausted_total"]
            suspended += psnap["pool_suspended_total"]
            pool_size[name] = sizes
            time_to_ready[name] = fp.time_to_ready_s
            pools[name] = {
                "size": len(fp.members),
                "target_size": fp.spec.target_size,
                "state": sizes,
                "managed": fp.managed,
                "scaled_to_zero": fp.scaled_to_zero,
                "restoring": fp.restoring,
                "scale_to_zero_s": fp.scale_to_zero_s,
                "time_to_ready_s": fp.time_to_ready_s,
                "preemptions_total": fp.preemptions_total,
                "respawns_total": fp.respawns_total,
                "scale_to_zero_total": fp.scale_to_zero_total,
                "restores_total": fp.restores_total,
                "pool": psnap,
            }
        return {
            "pool_size": pool_size,
            "pools": pools,
            "preemptions_total": preemptions,
            "replays_total": replays,
            "retry_budget_exhausted_total": budget_exhausted,
            "suspended_total": suspended,
            "storms_total": self.storms_total,
            "requests_total": dict(self.class_requests),
            "failures_total": dict(self.class_failures),
            "time_to_ready_s": time_to_ready,
        }


# ---- HTTP surface ----


def retry_after_header(exc: PoolExhaustedError) -> dict[str, str]:
    return {"Retry-After": f"{max(1, round(getattr(exc, 'retry_after_s', 1.0)))}"}


def fleet_member_urls(controller: FleetController) -> list[str]:
    """Every member URL across every pool — the fleet aggregator's
    membership source (re-read each scrape, so spot churn, respawns and
    scale-to-zero are followed)."""
    return [
        m.url for fp in controller.pools.values() for m in fp.members
    ]


def make_fleet_app(
    controller: FleetController, limiter=None,
    aggregator: FleetAggregator | None = None,
    reconciler=None,
    tenancy_plane=None,
    autoscaler=None,
) -> web.Application:
    """The fleet edge: /detect classifies (header/payload) and routes
    through the controller; /metrics serves the pool gauges the storm bench
    parses. The controller's tick loop starts/stops with the app.
    `limiter` (an `overload.AdaptiveLimiter`, default off; armed via
    `SPOTTER_TPU_ADMIT_EDGE_TARGET_MS` by the entrypoints) is the ISSUE 8
    AIMD edge gate: adaptive concurrency on observed round-trip latency,
    shedding bulk before slo when the limit is hit. `aggregator` (default:
    built over every pool's members from `SPOTTER_TPU_FLEET_SCRAPE_S`; 0
    disables) is the ISSUE 12 fleet telemetry plane — the merged `fleet`
    /metrics block, /debug/fleet, and /debug/traces?fleet=1 stitching.
    `reconciler` (ISSUE 16, default None) attaches a
    `reconcile.Reconciler`: /healthz grows the leadership + drift block
    and /metrics the `reconcile` counters (adoptions, fencing rejections,
    journal rebuilds, per-pool drift). `tenancy_plane` (ISSUE 19, default
    `tenancy.from_env()` — None when unconfigured) arms per-tenant edge
    quotas exactly like the plain router: over-quota tenants shed 429
    with a tenant-scoped Retry-After before the body is read, and the
    resolved id rides downstream in X-Spotter-Tenant. `autoscaler` (ISSUE
    20, default None) attaches an `autoscale.AutoscalerBrain`: /detect
    resolves a MODEL pool (X-Spotter-Model header / `model` payload key /
    `queries` -> open-vocab pool) before class routing, unplaceable
    requests get a structured 400 naming the registry, and /metrics grows
    the `autoscale` per-model-pool block fleet_top renders."""
    from spotter_tpu.serving import tenancy

    if aggregator is None:
        aggregator = FleetAggregator(lambda: fleet_member_urls(controller))
    if tenancy_plane is None:
        tenancy_plane = tenancy.from_env()
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["fleet"] = controller
    app["edge_limiter"] = limiter
    app["fleet_aggregator"] = aggregator
    app["tenancy"] = tenancy_plane
    app["autoscaler"] = autoscaler

    async def on_startup(app: web.Application) -> None:
        await controller.start()
        await aggregator.start()
        if autoscaler is not None:
            await autoscaler.start()

    async def on_cleanup(app: web.Application) -> None:
        if autoscaler is not None:
            await autoscaler.stop()
        await aggregator.stop()
        await controller.stop()

    async def detect(request: web.Request) -> web.Response:
        # Same edge-trace contract as the plain router (ISSUE 7): ids
        # minted/continued and echoed on EVERY outcome (storm 503s
        # included), traceparent forwarded, replica Server-Timing merged
        # behind a route span that also covers the pool pick.
        trace, request_id = obs_http.begin_http_trace(request)
        tenant = None
        tadm = None
        mtrack = None

        def done(resp: web.Response) -> web.Response:
            # per-tenant occupancy + SLO accounting (ISSUE 19)
            if tadm is not None:
                tadm.release(
                    good=resp.status not in (429, 503) and resp.status < 500
                )
            # per-model-pool edge accounting (ISSUE 20)
            if mtrack is not None:
                mtrack.done(resp.status)
            return obs_http.finish_http_trace(
                trace, request_id, resp, server_timing=True
            )

        if tenancy_plane is not None:
            # edge quota (ISSUE 19): header-only identity, shed 429 before
            # the body is read — strictly before any in-quota shed below
            from spotter_tpu.serving import tenancy as tenancy_mod
            from spotter_tpu.serving.router import tenant_shed_response

            tenant = tenancy_plane.resolve(request.headers)
            try:
                tadm = tenancy_plane.try_admit(tenant)
            except tenancy_mod.TenantQuotaError as exc:
                return done(tenant_shed_response(exc))
        try:
            with obs.span(obs.ROUTE, trace):
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    return done(web.Response(status=400, text="Invalid JSON body"))
                cls, payload = classify_request(
                    request.headers, payload, default=controller.default_class
                )
            model_pool = None
            if autoscaler is not None:
                # model-multiplexed routing (ISSUE 20): resolve the MODEL
                # pool before class routing; unplaceable requests are
                # structured 400s naming the registry, through done() so
                # the request id echoes like every other shed
                from spotter_tpu.serving.autoscale import ModelRoutingError
                from spotter_tpu.serving.router import model_routing_response

                try:
                    model_pool, payload = autoscaler.route(
                        request.headers, payload
                    )
                except ModelRoutingError as exc:
                    return done(model_routing_response(exc))
                mtrack = autoscaler.track(model_pool)
            adm = None
            if limiter is not None:
                adm = limiter.try_admit(cls)
                if adm is None:  # over the adaptive edge limit: bulk sheds first
                    from spotter_tpu.serving.router import edge_shed_response

                    return done(edge_shed_response(limiter, cls))
            # forward the class so replica-level overload control (limiter
            # class ordering, brownout bulk rung) sees the same verdict
            headers = obs_http.forward_headers(trace, request_id)
            headers[REQUEST_CLASS_HEADER] = cls
            if tenant is not None:
                # resolved tenant id rides downstream alongside X-Request-ID
                # (ISSUE 19) so the replica scopes by the same identity;
                # stamp() adds the edge-attestation token when configured
                # (REVIEW: a bare forwarded header is untrusted there too)
                tenancy_plane.stamp(headers, tenant)
            t_fwd = time.monotonic()
            try:
                resp = await controller.request(
                    "/detect", payload, cls, headers=headers, pool=model_pool
                )
            except PoolExhaustedError as exc:
                return done(
                    web.json_response(
                        {"error": str(exc), "status": 503, "request_class": cls},
                        status=503,
                        headers=retry_after_header(exc),
                    )
                )
            finally:
                elapsed_s = time.monotonic() - t_fwd
                if limiter is not None:
                    limiter.observe(elapsed_s * 1000.0)
                if adm is not None:
                    adm.release()
            with obs.span(obs.ROUTE, trace):
                # replica stages + the transport remainder as a network span:
                # the edge trace tiles against the latency the client saw
                obs_http.merge_downstream(trace, resp.headers, elapsed_s)
                out = web.Response(
                    status=resp.status_code,
                    body=resp.content,
                    content_type="application/json",
                )
                rid = resp.headers.get(wire.REPLICA_HEADER)
                if rid:  # replica identity rides through the fleet edge too
                    out.headers[wire.REPLICA_HEADER] = rid
                ver = resp.headers.get(wire.VERSION_HEADER)
                if ver:  # deploy version too (ISSUE 15)
                    out.headers[wire.VERSION_HEADER] = ver
            return done(out)
        finally:
            # leak guard (REVIEW): a client disconnect (CancelledError
            # in any await) or an uncaught error below must still free
            # the tenant's inflight slot, or the tenant is permanently
            # 429-locked at its inflight cap and its occupancy skews
            # the limiter/brownout forever. Idempotent: when done()
            # ran, it already released with the real outcome; this
            # no-outcome release never touches the SLO burn.
            if tadm is not None:
                tadm.release(good=None)
            if mtrack is not None:
                mtrack.done(None)

    async def healthz(request: web.Request) -> web.Response:
        available = {
            name: fp.pool.has_available()
            for name, fp in controller.pools.items()
        }
        body: dict = {"pools_available": available}
        if reconciler is not None:
            # control-plane block (ISSUE 16): leadership + per-pool drift
            from spotter_tpu.serving.reconcile import healthz_block

            body.update(healthz_block(reconciler))
        return web.json_response(
            body,
            status=200 if any(available.values()) else 503,
        )

    async def livez(request: web.Request) -> web.Response:
        return web.json_response({"status": "alive"})

    async def metrics(request: web.Request) -> web.Response:
        # JSON unchanged; Prometheus text exposition of the pool_size /
        # preemption / replay gauges behind the standard negotiation. The
        # edge limiter's state rides along under "edge_admit" when armed.
        snap = controller.snapshot()
        if limiter is not None:
            snap["edge_admit"] = limiter.snapshot()
        # fleet telemetry plane (ISSUE 12): the merged member view across
        # every pool — the single answer to "what is the fleet's goodput/
        # burn/MFU right now", and the autoscaling signal source for
        # ROADMAP item 2
        if aggregator.enabled:
            snap["fleet"] = aggregator.fleet_snapshot()
        # crash-safe control plane (ISSUE 16): reconcile loop counters +
        # the desired-vs-ready drift gauge, labeled per pool by prom
        if reconciler is not None:
            snap["reconcile"] = reconciler.snapshot()
        # tenant isolation plane (ISSUE 19): bounded top-K per-tenant rows
        if tenancy_plane is not None:
            snap["tenants"] = tenancy_plane.metrics_view()
        # model-multiplexed autoscaler (ISSUE 20): per-model-pool desired/
        # ready, last decision + reason, restore timing — fleet_top's rows
        if autoscaler is not None:
            snap["autoscale"] = autoscaler.snapshot()
        return obs_http.metrics_response(request, snap)

    async def debug_tenants(request: web.Request) -> web.Response:
        """Full per-tenant table (ISSUE 19) — admin-token-gated."""
        rejected = obs_http.admin_rejection(request)
        if rejected is not None:
            return rejected
        if tenancy_plane is None:
            return web.json_response({"enabled": False})
        return web.json_response(tenancy_plane.snapshot())

    app.router.add_post("/detect", detect)
    app.router.add_get("/debug/tenants", debug_tenants)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/livez", livez)
    app.router.add_get("/metrics", metrics)
    app.router.add_get(
        "/debug/traces",
        obs_http.make_debug_traces_handler(aggregator=aggregator),
    )
    app.router.add_get(
        "/debug/fleet", obs_http.make_debug_fleet_handler(aggregator)
    )
    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def static_fleet(
    on_demand: list[str], spot: list[str], **controller_kwargs
) -> FleetController:
    """Fleet over fixed endpoint lists (no spawning — the
    router-as-data-plane deployment where members are k8s pods someone else
    manages)."""
    specs = []
    if on_demand:
        specs.append(PoolSpec(ON_DEMAND, endpoints=on_demand))
    if spot:
        specs.append(PoolSpec(SPOT, endpoints=spot))
    return FleetController(specs, **controller_kwargs)


def main() -> None:
    parser = argparse.ArgumentParser(
        description="spotter-tpu spot-aware fleet edge"
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--on-demand",
        default=os.environ.get("SPOTTER_TPU_REPLICAS", ""),
        help="comma-separated on-demand replica base URLs "
        "(default SPOTTER_TPU_REPLICAS)",
    )
    parser.add_argument(
        "--spot",
        default=os.environ.get("SPOTTER_TPU_SPOT_REPLICAS", ""),
        help="comma-separated spot replica base URLs "
        "(default SPOTTER_TPU_SPOT_REPLICAS)",
    )
    args = parser.parse_args()
    on_demand = [e.strip() for e in args.on_demand.split(",") if e.strip()]
    spot = [e.strip() for e in args.spot.split(",") if e.strip()]
    if not on_demand and not spot:
        raise SystemExit("no endpoints: pass --on-demand and/or --spot")
    logging.basicConfig(level=logging.INFO)
    obs_logs.maybe_setup_json_logging()
    from spotter_tpu.serving.overload import edge_limiter_from_env

    controller = static_fleet(on_demand, spot)
    web.run_app(
        make_fleet_app(controller, limiter=edge_limiter_from_env()),
        host=args.host,
        port=args.port,
    )


if __name__ == "__main__":
    main()
