"""Adaptive overload control: AIMD admission + brownout degradation ladder.

Every overload defense before this PR was static and binary — a fixed queue
depth that sheds 429, a fixed-threshold breaker, fixed Retry-After hints —
so the system had exactly two operating points, "fine" and "shedding".
DeepServe (PAPERS.md) shows serverless serving fleets live or die by
admission control and graceful degradation under demand spikes, and
Spotlight motivates class-aware treatment of bulk vs. SLO traffic under
capacity loss. This module is the control plane that *measures* saturation
and *degrades gracefully* instead of flipping to 503:

- `AdaptiveLimiter` — an AIMD concurrency limiter. The control signal is
  queue_wait p90 (the PR 7 stage histograms' vocabulary: submit -> batch
  dispatch) against `SPOTTER_TPU_ADMIT_TARGET_MS`. Under target the limit
  grows additively (`SPOTTER_TPU_ADMIT_INCREASE` per control interval);
  over target it shrinks multiplicatively (`SPOTTER_TPU_ADMIT_DECREASE`),
  clamped to [`SPOTTER_TPU_ADMIT_FLOOR`, `SPOTTER_TPU_ADMIT_CEILING`].
  Admission is CLASS-AWARE: when the limit is hit, bulk sheds strictly
  before slo — a new slo request first revokes the NEWEST queued bulk
  admission (LIFO-ish: the freshest bulk work has the least sunk cost),
  and if no bulk is revocable it rides a bounded soft overage while any
  bulk still holds a slot, so slo is never shed while bulk occupies
  capacity. The tier is OPT-IN: with `SPOTTER_TPU_ADMIT_TARGET_MS`
  unset/0, `from_env()` returns None and the static queue-depth check
  keeps today's semantics bit-identically.

- `BrownoutController` — a monotonic degradation ladder armed by SUSTAINED
  saturation (the limiter pinned at its floor, or queue_wait p90 above the
  deadline slack `SPOTTER_TPU_BROWNOUT_SLACK_MS`) for
  `SPOTTER_TPU_BROWNOUT_ARM_S`. Rungs, entered one at a time:

      1 stale       serve expired-TTL result-cache entries (marked
                    `degraded: ["stale"]` on the wire)
      2 bucket_cap  cap the batcher's dispatch bucket one rung down the
                    ladder (smaller padded batches -> fewer wasted pad
                    FLOPs per dispatch and a shorter per-batch device
                    window, the PR 4 bucket-downgrade machinery driven by
                    load instead of OOM)
      3 threshold   raise the effective detection threshold by
                    `SPOTTER_TPU_BROWNOUT_THRESHOLD_BOOST` (fewer boxes ->
                    cheaper postprocess/draw/encode)
      4 bulk_503    shed ALL bulk traffic with 503 + Retry-After; slo
                    keeps serving

  Each rung is exited automatically (one at a time, newest concession
  returned first) after saturation stays clear for
  `SPOTTER_TPU_BROWNOUT_DISARM_S` — the enter/exit thresholds differ, so
  the ladder cannot flap across the boundary. Every transition bumps the
  `brownout_rung` gauge, counts in `brownout_transitions_total`, and pins
  a synthetic trace in the flight recorder so `/debug/traces` shows when
  and why the replica browned out.

Everything here is engine-free and clock-injectable: the limiter state
machine and the ladder hysteresis are unit-testable with a fake clock and
a scripted saturation signal (tests/test_overload.py).
"""

import logging
import threading
import time
from typing import Callable, Optional

from spotter_tpu.serving.resilience import (
    AdmissionError,
    _env_float,
    _env_int,
)
from spotter_tpu.testing import faults

logger = logging.getLogger(__name__)

# Request classes (same strings as serving/fleet.py — kept here too so the
# batcher does not have to import the aiohttp-heavy fleet module).
SLO = "slo"
BULK = "bulk"

ADMIT_TARGET_ENV = "SPOTTER_TPU_ADMIT_TARGET_MS"
ADMIT_EDGE_TARGET_ENV = "SPOTTER_TPU_ADMIT_EDGE_TARGET_MS"
ADMIT_FLOOR_ENV = "SPOTTER_TPU_ADMIT_FLOOR"
ADMIT_CEILING_ENV = "SPOTTER_TPU_ADMIT_CEILING"
ADMIT_INCREASE_ENV = "SPOTTER_TPU_ADMIT_INCREASE"
ADMIT_DECREASE_ENV = "SPOTTER_TPU_ADMIT_DECREASE"
ADMIT_INTERVAL_ENV = "SPOTTER_TPU_ADMIT_INTERVAL_S"

BROWNOUT_ARM_ENV = "SPOTTER_TPU_BROWNOUT_ARM_S"
BROWNOUT_DISARM_ENV = "SPOTTER_TPU_BROWNOUT_DISARM_S"
BROWNOUT_SLACK_ENV = "SPOTTER_TPU_BROWNOUT_SLACK_MS"
BROWNOUT_MAX_RUNG_ENV = "SPOTTER_TPU_BROWNOUT_MAX_RUNG"
BROWNOUT_THRESHOLD_BOOST_ENV = "SPOTTER_TPU_BROWNOUT_THRESHOLD_BOOST"

DEFAULT_ADMIT_FLOOR = 4
DEFAULT_ADMIT_CEILING = 256
DEFAULT_ADMIT_INCREASE = 2.0
DEFAULT_ADMIT_DECREASE = 0.7
DEFAULT_ADMIT_INTERVAL_S = 0.25
DEFAULT_BROWNOUT_ARM_S = 2.0
DEFAULT_BROWNOUT_THRESHOLD_BOOST = 0.15
# saturation bar default: 8x the limiter's queue-wait target — "p90 so far
# over target that the deadline slack is gone" without needing a deadline
DEFAULT_SLACK_FACTOR = 8.0

# brownout rungs, in escalation order (monotonic ladder)
RUNG_NONE = 0
RUNG_STALE = 1
RUNG_BUCKET_CAP = 2
RUNG_THRESHOLD = 3
RUNG_BULK_503 = 4
MAX_RUNG = RUNG_BULK_503

RUNG_NAMES = {
    RUNG_NONE: "ok",
    RUNG_STALE: "stale",
    RUNG_BUCKET_CAP: "bucket_cap",
    RUNG_THRESHOLD: "threshold",
    RUNG_BULK_503: "bulk_503",
}


class AdmitLimitError(AdmissionError):
    """The adaptive concurrency limit is hit — shed with 429 (retry)."""

    status = 429


class BrownoutShedError(AdmissionError):
    """The deepest brownout rung: bulk traffic is shed with 503 while slo
    keeps serving. Clients should back off, not hot-retry."""

    status = 503


class Admission:
    """One admitted slot. `release()` is idempotent (future done-callbacks
    and the limiter's own revocation path may both call it); a bulk
    admission may carry a revoke callback so a later slo arrival can
    reclaim the slot while the work is still queued."""

    __slots__ = (
        "cls", "tenant", "_limiter", "_revoke_cb", "_released", "_revocable",
    )

    def __init__(
        self,
        limiter: "AdaptiveLimiter",
        cls: str,
        tenant: Optional[str] = None,
    ) -> None:
        self.cls = cls
        # tenant identity (ISSUE 19): lets the revocation path pick the
        # top-occupancy tenant's bulk first; None when tenancy is off
        self.tenant = tenant
        self._limiter = limiter
        self._revoke_cb: Optional[Callable[[], None]] = None
        self._released = False
        self._revocable = False

    def attach_revoke(self, cb: Callable[[], None]) -> None:
        """Make this (bulk) admission revocable: `cb` fails the queued work
        when a slo arrival reclaims the slot."""
        self._revoke_cb = cb
        self._limiter._make_revocable(self)

    def make_unrevocable(self) -> None:
        """Called when the queued work is dispatched: failing it now would
        waste engine work, so it leaves the revocation stack."""
        self._limiter._make_unrevocable(self)

    def release(self) -> None:
        self._limiter._release(self)


class AdaptiveLimiter:
    """AIMD concurrency limiter over a queue-wait (or edge-latency) signal.

    Thread-safe (an RLock around the counters: admissions happen on the
    event loop, observations may arrive from batch tasks, and tests poke
    it from anywhere); the clock is injectable so the state machine is
    unit-testable without sleeping.
    """

    def __init__(
        self,
        target_ms: float,
        floor: int = DEFAULT_ADMIT_FLOOR,
        ceiling: int = DEFAULT_ADMIT_CEILING,
        increase: float = DEFAULT_ADMIT_INCREASE,
        decrease: float = DEFAULT_ADMIT_DECREASE,
        interval_s: float = DEFAULT_ADMIT_INTERVAL_S,
        clock=time.monotonic,
        metrics=None,
        tenancy=None,
    ) -> None:
        if target_ms <= 0:
            raise ValueError("target_ms must be > 0 (unset disables the tier)")
        # tenant isolation plane (ISSUE 19): when attached, revocation
        # prefers the top-occupancy tenant's bulk. None (the default and
        # every unconfigured deployment) keeps revocation bit-identical.
        self.tenancy = tenancy
        self.target_ms = target_ms
        self.floor = max(1, int(floor))
        self.ceiling = max(self.floor, int(ceiling))
        self.increase = max(0.0, increase)
        self.decrease = min(max(decrease, 0.05), 1.0)
        self.interval_s = max(0.01, interval_s)
        self._clock = clock
        self.metrics = metrics
        self._lock = threading.RLock()
        # start at the ceiling (optimistic): the first congested interval
        # cuts multiplicatively, which converges in a few intervals, while
        # starting low would throttle a healthy service for no reason
        self._limit = float(self.ceiling)
        self._in_flight = 0
        self._bulk_in_flight = 0
        # newest-last stack of revocable (queued, bulk) admissions
        self._revocable: list[Admission] = []
        self._samples: list[float] = []
        self._last_update = self._clock()
        self.last_p90_ms = 0.0
        self.decreases_total = 0
        self.increases_total = 0
        self.revoked_total = 0
        self.sheds_total = {SLO: 0, BULK: 0}

    @classmethod
    def from_env(
        cls, metrics=None, target_env: str = ADMIT_TARGET_ENV
    ) -> Optional["AdaptiveLimiter"]:
        """An armed limiter, or None when the tier is off (`target_env`
        unset or <= 0) — None means every caller takes the exact static
        queue-depth path, bit-identical to a pre-overload-control build."""
        target_ms = _env_float(target_env, 0.0)
        if target_ms <= 0:
            return None
        return cls(
            target_ms=target_ms,
            floor=_env_int(ADMIT_FLOOR_ENV, DEFAULT_ADMIT_FLOOR),
            ceiling=_env_int(ADMIT_CEILING_ENV, DEFAULT_ADMIT_CEILING),
            increase=_env_float(ADMIT_INCREASE_ENV, DEFAULT_ADMIT_INCREASE),
            decrease=_env_float(ADMIT_DECREASE_ENV, DEFAULT_ADMIT_DECREASE),
            interval_s=_env_float(ADMIT_INTERVAL_ENV, DEFAULT_ADMIT_INTERVAL_S),
            metrics=metrics,
        )

    # -- signal --

    @property
    def limit(self) -> int:
        return int(self._limit)

    @property
    def in_flight(self) -> int:
        return self._in_flight

    def pinned_at_floor(self) -> bool:
        """True while AIMD has cut the limit all the way to its floor — the
        'admission control alone cannot shield the engine' signal that arms
        the brownout ladder."""
        with self._lock:
            return self._limit <= self.floor

    def observe(self, wait_ms: float) -> None:
        """Feed one queue-wait (or edge-latency) sample; runs the AIMD
        update when a control interval has elapsed."""
        with self._lock:
            self._samples.append(wait_ms)
            self._maybe_update(self._clock())

    def tick(self) -> None:
        """Idle-path control tick (no sample): lets the limit climb back
        toward the ceiling after a storm even when no traffic is flowing —
        without it a floor-pinned limiter would stay 'saturated' forever
        and the brownout ladder could never disarm."""
        with self._lock:
            self._maybe_update(self._clock())

    def _maybe_update(self, now: float) -> None:
        # caller holds the lock
        if now - self._last_update < self.interval_s:
            return
        self._last_update = now
        samples, self._samples = self._samples, []
        if faults.take_overload_spike():
            # injected overload (`overload_spike=N`): this control tick
            # sees a synthetic far-over-target p90 — the deterministic way
            # for chaos tests to drive the AIMD cut + brownout arm without
            # generating real queue pressure
            p90 = self.target_ms * 10.0
        elif samples:
            samples.sort()
            p90 = samples[min(int(0.9 * len(samples)), len(samples) - 1)]
        else:
            # no traffic this interval: no queueing is happening, so probe
            # upward (classic AIMD additive recovery) and let the
            # saturation signal decay
            self.last_p90_ms = 0.0
            self._limit = min(float(self.ceiling), self._limit + self.increase)
            self._publish()
            return
        self.last_p90_ms = p90
        if p90 > self.target_ms:
            self._limit = max(float(self.floor), self._limit * self.decrease)
            self.decreases_total += 1
        else:
            self._limit = min(float(self.ceiling), self._limit + self.increase)
            self.increases_total += 1
        self._publish()

    def _publish(self) -> None:
        # caller holds the lock
        if self.metrics is not None:
            self.metrics.set_admit_state(self.limit, self._in_flight)

    # -- admission --

    def try_admit(
        self, cls: str = SLO, tenant: Optional[str] = None
    ) -> Optional[Admission]:
        """One admission attempt. Returns a slot, or None (shed).

        Class order is structural: when the limit is hit, a bulk arrival
        always sheds; an slo arrival first revokes the newest queued bulk
        admission, and failing that rides a soft overage while ANY bulk
        still holds a slot (each overage slot is backed by at least one
        bulk slot, so the true engine pressure stays <= limit once bulk
        drains) — slo is shed only when the limit is hit by slo alone.
        With the tenancy plane attached (ISSUE 19) the revocation victim
        is the TOP-OCCUPANCY tenant's newest queued bulk, so the flooding
        tenant pays for the reclaimed slot before anyone else does.
        """
        if cls not in (SLO, BULK):
            cls = SLO
        with self._lock:
            self._maybe_update(self._clock())
            if self._in_flight < self.limit:
                return self._admit(cls, tenant)
            if cls == BULK:
                return self._shed(cls)
            victim = self._pop_revocable()
            if victim is not None:
                self._revoke(victim)
                return self._admit(cls, tenant)
            if self._bulk_in_flight > 0:
                # bounded soft overage (see above)
                return self._admit(cls, tenant)
            return self._shed(cls)

    def _admit(self, cls: str, tenant: Optional[str] = None) -> Admission:
        # caller holds the lock
        self._in_flight += 1
        if cls == BULK:
            self._bulk_in_flight += 1
        return Admission(self, cls, tenant)

    def _shed(self, cls: str) -> None:
        # caller holds the lock
        self.sheds_total[cls] += 1
        if self.metrics is not None:
            self.metrics.record_admit_shed(cls)
        return None

    def _pop_revocable(self) -> Optional[Admission]:
        # caller holds the lock; newest first (LIFO-ish: the freshest bulk
        # work has waited least and wasted least). With the tenancy plane
        # attached (ISSUE 19), the TOP-OCCUPANCY tenant's newest revocable
        # bulk is preferred — over-share bulk pays before anyone else's —
        # falling back to plain newest-first when that tenant holds none.
        self._revocable = [a for a in self._revocable if not a._released]
        if not self._revocable:
            return None
        if self.tenancy is not None:
            top = self.tenancy.top_occupancy_tenant()
            if top is not None:
                for adm in reversed(self._revocable):
                    if adm.tenant == top:
                        self._revocable.remove(adm)
                        return adm
        return self._revocable.pop()

    def _revoke(self, adm: Admission) -> None:
        # caller holds the lock; free the slot NOW (the victim's own
        # done-callback release becomes an idempotent no-op later)
        self.revoked_total += 1
        self.sheds_total[BULK] += 1
        if self.metrics is not None:
            self.metrics.record_admit_shed(BULK)
        self._do_release(adm)
        cb = adm._revoke_cb
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("bulk admission revoke callback failed")

    def _make_revocable(self, adm: Admission) -> None:
        with self._lock:
            if not adm._released and not adm._revocable:
                adm._revocable = True
                self._revocable.append(adm)

    def _make_unrevocable(self, adm: Admission) -> None:
        with self._lock:
            if adm._revocable:
                adm._revocable = False
                try:
                    self._revocable.remove(adm)
                except ValueError:
                    pass

    def _release(self, adm: Admission) -> None:
        with self._lock:
            self._do_release(adm)

    def _do_release(self, adm: Admission) -> None:
        # caller holds the lock
        if adm._released:
            return
        adm._released = True
        if adm._revocable:
            adm._revocable = False
            try:
                self._revocable.remove(adm)
            except ValueError:
                pass
        self._in_flight = max(0, self._in_flight - 1)
        if adm.cls == BULK:
            self._bulk_in_flight = max(0, self._bulk_in_flight - 1)

    # -- introspection --

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "limit": self.limit,
                "floor": self.floor,
                "ceiling": self.ceiling,
                "in_flight": self._in_flight,
                "bulk_in_flight": self._bulk_in_flight,
                "last_p90_ms": round(self.last_p90_ms, 3),
                "target_ms": self.target_ms,
                "pinned_at_floor": self._limit <= self.floor,
                "increases_total": self.increases_total,
                "decreases_total": self.decreases_total,
                "revoked_total": self.revoked_total,
                "sheds_total": dict(self.sheds_total),
            }


def saturation_signals(
    limiter: AdaptiveLimiter, slack_ms: float, metrics=None
) -> tuple[Callable[[], bool], Callable[[], bool]]:
    """The default brownout signal pair `(saturated, hold)`.

    `saturated` ESCALATES the ladder: the limiter pinned at its floor, or
    queue_wait p90 over the slack bar — hard evidence admission control
    alone cannot shield the engine. `hold` only BLOCKS de-escalation:
    requests are still actively being shed. The asymmetry matters twice
    over — mere sustained shedding must not walk a healthy limiter's
    system to bulk-503 (the limiter shedding bulk at 1.5x capacity is
    working as designed, not browning out), but at the deepest rung the
    measured queue goes quiet precisely BECAUSE the flood is being 503'd,
    and without the hold term the ladder would read that calm as recovery,
    step down, re-admit the flood, and cycle across the top rung boundary.
    """
    last_sheds = [metrics.admit_sheds_count() if metrics is not None else 0]

    def saturated() -> bool:
        return limiter.pinned_at_floor() or limiter.last_p90_ms > slack_ms

    def hold() -> bool:
        if metrics is None:
            return False
        total = metrics.admit_sheds_count()
        shedding = total > last_sheds[0]
        last_sheds[0] = total
        return shedding

    return saturated, hold


class BrownoutController:
    """Monotonic degradation ladder with enter/exit hysteresis.

    `saturated()` is the armed signal (default from `from_env`: limiter
    pinned at floor OR queue_wait p90 over the slack bar). The rung
    escalates one step after the signal holds continuously for `arm_s`,
    and de-escalates one step after it stays continuously clear for
    `disarm_s` (default 2x arm_s) — a signal oscillating faster than
    either window moves nothing, which is the no-flap contract the unit
    tests pin. `evaluate()` is a lazy clock-driven tick: call it from
    admission paths, control loops, and health checks; it is cheap and
    idempotent within a tick.
    """

    def __init__(
        self,
        saturated: Callable[[], bool],
        arm_s: float = DEFAULT_BROWNOUT_ARM_S,
        disarm_s: Optional[float] = None,
        max_rung: int = MAX_RUNG,
        threshold_boost: float = DEFAULT_BROWNOUT_THRESHOLD_BOOST,
        clock=time.monotonic,
        metrics=None,
        recorder=None,
        hold: Optional[Callable[[], bool]] = None,
        tenancy=None,
    ) -> None:
        # tenant isolation plane (ISSUE 19): when attached, the bulk_503
        # rung is scoped to OVER-SHARE tenants only; None keeps the
        # class-wide rung bit-identical.
        self.tenancy = tenancy
        self.saturated = saturated
        # `hold` (optional): blocks DE-escalation without driving
        # escalation — see saturation_signals for why the asymmetry exists
        self.hold = hold
        self.arm_s = max(0.01, arm_s)
        self.disarm_s = self.arm_s * 2.0 if disarm_s is None else max(0.01, disarm_s)
        self.max_rung = min(max(0, int(max_rung)), MAX_RUNG)
        self.threshold_boost = max(0.0, threshold_boost)
        self._clock = clock
        self.metrics = metrics
        self._recorder = recorder
        self._lock = threading.RLock()
        self._rung = RUNG_NONE
        self._sat_since: Optional[float] = None
        self._clear_since: Optional[float] = None
        self._last_change = self._clock()
        self.transitions_total = 0

    @classmethod
    def from_env(
        cls, limiter: Optional[AdaptiveLimiter], metrics=None, tenancy=None
    ) -> Optional["BrownoutController"]:
        """Armed together with the limiter: one knob
        (`SPOTTER_TPU_ADMIT_TARGET_MS`) opts the whole overload-control
        tier in; `SPOTTER_TPU_BROWNOUT_MAX_RUNG=0` keeps the limiter but
        disables the ladder."""
        if limiter is None:
            return None
        max_rung = _env_int(BROWNOUT_MAX_RUNG_ENV, MAX_RUNG)
        if max_rung <= 0:
            return None
        slack_ms = _env_float(
            BROWNOUT_SLACK_ENV, limiter.target_ms * DEFAULT_SLACK_FACTOR
        )
        saturated, hold = saturation_signals(limiter, slack_ms, metrics=metrics)
        return cls(
            saturated,
            arm_s=_env_float(BROWNOUT_ARM_ENV, DEFAULT_BROWNOUT_ARM_S),
            disarm_s=_env_float(BROWNOUT_DISARM_ENV, 0.0) or None,
            max_rung=max_rung,
            threshold_boost=_env_float(
                BROWNOUT_THRESHOLD_BOOST_ENV, DEFAULT_BROWNOUT_THRESHOLD_BOOST
            ),
            metrics=metrics,
            hold=hold,
            tenancy=tenancy,
        )

    # -- state machine --

    @property
    def rung(self) -> int:
        return self._rung

    def evaluate(self) -> int:
        """Advance the ladder state machine against the clock; returns the
        (possibly new) rung."""
        with self._lock:
            now = self._clock()
            if self.saturated():
                self._clear_since = None
                if self._sat_since is None:
                    self._sat_since = now
                if (
                    self._rung < self.max_rung
                    and now - self._sat_since >= self.arm_s
                    and now - self._last_change >= self.arm_s
                ):
                    self._set_rung(self._rung + 1, now)
            elif self._rung > RUNG_NONE and self.hold is not None and self.hold():
                # still shedding: not saturated enough to escalate, not
                # recovered enough to give a concession back — the clear
                # window restarts
                self._sat_since = None
                self._clear_since = None
            else:
                self._sat_since = None
                if self._clear_since is None:
                    self._clear_since = now
                if (
                    self._rung > RUNG_NONE
                    and now - self._clear_since >= self.disarm_s
                    and now - self._last_change >= self.disarm_s
                ):
                    self._set_rung(self._rung - 1, now)
            return self._rung

    def _set_rung(self, new_rung: int, now: float) -> None:
        # caller holds the lock
        old = self._rung
        self._rung = new_rung
        self._last_change = now
        self.transitions_total += 1
        if self.metrics is not None:
            self.metrics.set_brownout_rung(new_rung)
            self.metrics.record_brownout_transition()
        direction = "entered" if new_rung > old else "exited"
        logger.warning(
            "brownout rung %d (%s) %s (was %d/%s)",
            new_rung, RUNG_NAMES.get(new_rung, "?"), direction,
            old, RUNG_NAMES.get(old, "?"),
        )
        self._pin_transition_trace(old, new_rung)

    def _pin_transition_trace(self, old: int, new: int) -> None:
        """Pin a synthetic trace in the flight recorder so `/debug/traces`
        answers 'when did this replica brown out, and how deep'. Best
        effort: recording must never fail a transition."""
        try:
            from spotter_tpu import obs

            recorder = self._recorder or obs.get_recorder()
            if not recorder.enabled:
                return
            request_id = (
                f"brownout-{self.transitions_total}-"
                f"rung{old}-to-rung{new}"
            )
            trace = obs.Trace(obs.trace_id_for_request(request_id), request_id)
            trace.set_error(
                "brownout",
                f"rung {old} ({RUNG_NAMES.get(old)}) -> "
                f"{new} ({RUNG_NAMES.get(new)})",
            )
            recorder.record(trace)
        except Exception:
            logger.exception("pinning brownout transition trace failed")

    # -- rung effects (queried by batcher / detector / cache) --

    def stale_ok(self) -> bool:
        """Rung >= 1: expired-TTL result-cache entries become acceptable."""
        return self._rung >= RUNG_STALE

    def bucket_cap_active(self) -> bool:
        """Rung >= 2: the batcher caps its dispatch bucket one rung down."""
        return self._rung >= RUNG_BUCKET_CAP

    def threshold_boost_value(self) -> float:
        """Rung >= 3: how much to raise the effective detection threshold."""
        return self.threshold_boost if self._rung >= RUNG_THRESHOLD else 0.0

    def shed_bulk(self, tenant: Optional[str] = None) -> bool:
        """Rung >= 4: bulk traffic is shed with 503 at admission.

        Per-tenant scoping (ISSUE 19): with the tenancy plane attached,
        only tenants holding MORE than their weight-fair share of current
        occupancy are shed — an in-quota tenant keeps full service even
        at the deepest rung. With the plane off (or no tenant known) the
        rung stays class-wide, exactly the pre-tenancy behavior."""
        if self._rung < RUNG_BULK_503:
            return False
        if self.tenancy is not None and tenant is not None:
            return self.tenancy.over_share(tenant)
        return True

    def markers(self) -> list[str]:
        """Active degradation markers for the response-level `degraded`
        field (the `stale` marker is added per-response by the detector,
        only when a stale entry was actually served)."""
        out = []
        if self._rung >= RUNG_BUCKET_CAP:
            out.append("bucket_cap")
        if self._rung >= RUNG_THRESHOLD:
            out.append("threshold")
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "rung": self._rung,
                "rung_name": RUNG_NAMES.get(self._rung, "?"),
                "max_rung": self.max_rung,
                "arm_s": self.arm_s,
                "disarm_s": self.disarm_s,
                "transitions_total": self.transitions_total,
            }


def edge_limiter_from_env(metrics=None) -> Optional[AdaptiveLimiter]:
    """The router/fleet edge's own AIMD gate: armed by
    `SPOTTER_TPU_ADMIT_EDGE_TARGET_MS` (a ROUND-TRIP latency target — the
    edge cannot see the replica's queue_wait, so it steers on what it can
    measure), sharing the SPOTTER_TPU_ADMIT_* shape knobs. None = off."""
    return AdaptiveLimiter.from_env(
        metrics=metrics, target_env=ADMIT_EDGE_TARGET_ENV
    )


def build_overload_control(
    metrics=None, target_env: str = ADMIT_TARGET_ENV, tenancy=None
) -> tuple[Optional[AdaptiveLimiter], Optional[BrownoutController]]:
    """The serving wiring: (limiter, brownout) from the env, both None when
    the tier is off. `tenancy` (ISSUE 19) scopes revocation and the
    bulk_503 rung per tenant when the isolation plane is armed."""
    limiter = AdaptiveLimiter.from_env(metrics=metrics, target_env=target_env)
    if limiter is not None:
        limiter.tenancy = tenancy
    brownout = BrownoutController.from_env(
        limiter, metrics=metrics, tenancy=tenancy
    )
    return limiter, brownout
