"""Async failover client pool: health-checked replicas, ejection, replay.

A single hardened replica (ISSUE 1) still leaves clients staring at hard
errors the moment that replica is preempted — on spot TPU capacity that is
routine, not exceptional (Spotlight, arXiv:2606.19004). This pool is the
fleet-side answer, DeepServe-style health-aware routing (arXiv:2501.14417)
in one file:

- **Selection**: round-robin over replicas that are neither ejected nor
  marked unhealthy by the background health loop (`/healthz` readiness, so
  a draining or breaker-open replica stops receiving traffic BEFORE it
  starts refusing connections).
- **Outlier ejection**: `eject_threshold` consecutive transport failures
  eject a replica for an exponentially growing backoff (doubling up to
  `backoff_max_s`); a later health-check success resets it.
- **Replay**: a `/detect` attempt that dies on a transport error
  (connection reset — the signature of a killed replica), times out, or
  answers 5xx/429 is replayed against the next replica. Detection is
  idempotent, so replay is safe; the client sees one answer, not the
  preemption. Replays spend from a `RetryBudget` (ISSUE 6): a correlated
  failure — a preemption storm taking half the fleet — must not amplify
  offered load with unbudgeted retries, so replays in a sliding window are
  capped at `SPOTTER_TPU_RETRY_BUDGET_PCT` of the recent request count
  (with a small floor so single-replica deaths still fail over cleanly);
  an exhausted budget fails the request FAST with a 503-shaped error
  instead of piling more attempts onto survivors.
- **Fast-fail when suspended** (ISSUE 6 bugfix): when every replica is
  ejected or health-marked down — or the pool is empty because its tier
  scaled to zero — `request()` raises `PoolSuspendedError` immediately
  (with a Retry-After hint derived from the soonest un-ejection) instead of
  burning the client's whole deadline on a candidate set that cannot serve.
- **Hedging** (optional): after `hedge_after_s` with no answer, a duplicate
  fires at a second replica and the first response wins — the tail-latency
  insurance for a replica that is technically alive but drowning. Hedges
  are bounded by their own counters and do NOT spend retry budget: they are
  latency insurance against a live replica, not recovery from a dead one.

Membership is dynamic (`add_endpoint` / `remove_endpoint`): the fleet
controller (serving/fleet.py) grows and shrinks pools as spot capacity
churns and idle tiers scale to zero.

`bench.py --failover` drives this pool; `python -m spotter_tpu.serving.router`
runs it as a tiny edge router. Counters surface in `snapshot()` (and the
router's /metrics): ejections, replays, hedges, budget exhaustions,
client-visible failures.
"""

import asyncio
import itertools
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import httpx

logger = logging.getLogger(__name__)

DEFAULT_EJECT_THRESHOLD = 3
DEFAULT_BACKOFF_BASE_S = 0.5
DEFAULT_BACKOFF_MAX_S = 30.0
DEFAULT_HEALTH_INTERVAL_S = 0.5
DEFAULT_REQUEST_TIMEOUT_S = 30.0

RETRY_BUDGET_PCT_ENV = "SPOTTER_TPU_RETRY_BUDGET_PCT"
RETRY_BUDGET_MIN_ENV = "SPOTTER_TPU_RETRY_BUDGET_MIN"
DEFAULT_RETRY_BUDGET_PCT = 10.0
# Floor: a single killed replica can strand up to a client-concurrency's
# worth of in-flight requests at once; those replays must never be the ones
# the budget refuses, or plain one-replica failover (ISSUE 2) breaks.
DEFAULT_RETRY_BUDGET_MIN = 10
DEFAULT_RETRY_BUDGET_WINDOW_S = 30.0

# statuses that mean "this replica can't serve it right now, another might":
# 429 queue-full, 503 draining/breaker, 500 engine fault
REPLAYABLE_STATUSES = frozenset({429, 500, 502, 503})


class PoolExhaustedError(RuntimeError):
    """Every replica failed or was ejected for one request."""

    def __init__(self, msg: str, retry_after_s: float = 1.0) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class PoolSuspendedError(PoolExhaustedError):
    """No replica is even worth trying right now (all ejected/down, or the
    pool is empty): fail fast with a Retry-After instead of waiting out the
    request deadline against a candidate set that cannot serve."""


class RetryBudgetExhaustedError(PoolExhaustedError):
    """A replay was needed but the budget refuses to amplify load further."""


class RetryBudget:
    """Sliding-window retry budget (Envoy-style, rate-based): replays in the
    last `window_s` seconds are capped at max(`min_retries`,
    `pct`% of requests seen in the same window). Shared budgets are fine —
    the fleet controller gives each pool its own slice so a bulk-tier storm
    cannot starve SLO-tier failover.
    """

    def __init__(
        self,
        pct: Optional[float] = None,
        min_retries: Optional[int] = None,
        window_s: float = DEFAULT_RETRY_BUDGET_WINDOW_S,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if pct is None:
            raw = os.environ.get(RETRY_BUDGET_PCT_ENV, "").strip()
            pct = float(raw) if raw else DEFAULT_RETRY_BUDGET_PCT
        if min_retries is None:
            raw = os.environ.get(RETRY_BUDGET_MIN_ENV, "").strip()
            min_retries = int(raw) if raw else DEFAULT_RETRY_BUDGET_MIN
        self.pct = max(float(pct), 0.0)
        self.min_retries = max(int(min_retries), 0)
        self.window_s = window_s
        self._clock = clock
        self._requests: deque[float] = deque()
        self._retries: deque[float] = deque()
        self.exhausted_total = 0

    def _trim(self, now: float) -> None:
        horizon = now - self.window_s
        while self._requests and self._requests[0] < horizon:
            self._requests.popleft()
        while self._retries and self._retries[0] < horizon:
            self._retries.popleft()

    def record_request(self) -> None:
        now = self._clock()
        self._trim(now)
        self._requests.append(now)

    def allowed(self) -> float:
        """Replays currently permitted in the window."""
        self._trim(self._clock())
        return max(
            float(self.min_retries), self.pct / 100.0 * len(self._requests)
        )

    def try_spend(self) -> bool:
        """Reserve one replay; False (and a bumped exhausted counter) when
        the window is already at its cap."""
        now = self._clock()
        self._trim(now)
        if len(self._retries) + 1 > self.allowed():
            self.exhausted_total += 1
            return False
        self._retries.append(now)
        return True

    def snapshot(self) -> dict:
        now = self._clock()
        self._trim(now)
        return {
            "pct": self.pct,
            "min_retries": self.min_retries,
            "window_s": self.window_s,
            "window_requests": len(self._requests),
            "window_retries": len(self._retries),
            "allowed": self.allowed(),
            "exhausted_total": self.exhausted_total,
        }


@dataclass
class Replica:
    url: str  # base URL, e.g. http://127.0.0.1:8001
    healthy: bool = True
    consecutive_failures: int = 0
    ejected_until: float = 0.0
    eject_backoff_s: float = 0.0
    # diagnostics
    requests: int = 0
    failures: int = 0
    ejections: int = 0
    last_error: str = ""
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    def available(self, now: float) -> bool:
        return self.healthy and now >= self.ejected_until


class ReplicaPool:
    def __init__(
        self,
        endpoints: list[str],
        client: Optional[httpx.AsyncClient] = None,
        eject_threshold: int = DEFAULT_EJECT_THRESHOLD,
        backoff_base_s: float = DEFAULT_BACKOFF_BASE_S,
        backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
        health_interval_s: float = DEFAULT_HEALTH_INTERVAL_S,
        request_timeout_s: float = DEFAULT_REQUEST_TIMEOUT_S,
        hedge_after_s: Optional[float] = None,
        max_rounds: int = 2,
        round_pause_s: float = 0.25,
        retry_budget: Optional[RetryBudget] = None,
        allow_empty: bool = False,
    ) -> None:
        if not endpoints and not allow_empty:
            raise ValueError("ReplicaPool needs at least one endpoint")
        self.replicas = [Replica(url=u.rstrip("/")) for u in endpoints]
        self.retry_budget = retry_budget or RetryBudget()
        self.client = client or httpx.AsyncClient(
            timeout=httpx.Timeout(request_timeout_s, connect=2.0)
        )
        self._owns_client = client is None
        self.eject_threshold = max(1, eject_threshold)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.health_interval_s = health_interval_s
        self.hedge_after_s = hedge_after_s
        self.max_rounds = max(1, max_rounds)
        self.round_pause_s = round_pause_s
        self._rr = itertools.count()
        self._health_task: Optional[asyncio.Task] = None
        # counters (event-loop only — no lock needed)
        self.requests_total = 0
        self.replays_total = 0
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.ejections_total = 0
        self.failures_total = 0  # client-visible (pool exhausted)
        self.suspended_total = 0  # fast-failed: nothing worth trying

    # ---- membership (fleet controller: spot churn, scale-to-zero) ----

    def add_endpoint(self, url: str, healthy: bool = False) -> Replica:
        """Add a replica at runtime. New members default to `healthy=False`
        ("starting"): the health loop promotes them on the first /healthz 200,
        so live traffic never races a replica that is still binding/compiling."""
        url = url.rstrip("/")
        existing = self.replica_for(url)
        if existing is not None:
            return existing
        r = Replica(url=url, healthy=healthy)
        self.replicas.append(r)
        return r

    def remove_endpoint(self, url: str) -> Optional[Replica]:
        url = url.rstrip("/")
        r = self.replica_for(url)
        if r is not None:
            self.replicas.remove(r)
        return r

    def replica_for(self, url: str) -> Optional[Replica]:
        url = url.rstrip("/")
        for r in self.replicas:
            if r.url == url:
                return r
        return None

    def has_available(self) -> bool:
        now = time.monotonic()
        return any(r.available(now) for r in self.replicas)

    # ---- lifecycle ----

    async def start(self) -> None:
        if self._health_task is None:
            self._health_task = asyncio.create_task(self._health_loop())

    async def stop(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None
        if self._owns_client:
            await self.client.aclose()

    # ---- health ----

    async def _probe(self, r: Replica) -> None:
        try:
            resp = await self.client.get(f"{r.url}/healthz", timeout=2.0)
            ok = resp.status_code == 200
        except Exception as exc:
            ok = False
            r.last_error = f"health: {exc!r}"
        if not ok:
            r.healthy = False
        elif not r.available(time.monotonic()):
            # only an UNAVAILABLE replica is promoted by a probe success; on
            # an available one the success is a no-op so probes cannot reset
            # the consecutive-failure count live traffic is accumulating
            self._record_success(r)

    async def _health_loop(self) -> None:
        """Probe every replica: an unavailable one so recovery (supervisor
        restart, breaker close, drain replaced by a fresh pod) un-ejects it
        without risking live traffic on a dead endpoint, and an available
        one so a readiness flip (drain, maintenance notice — the preemption
        signature the fleet controller watches) stops routing BEFORE the
        replica starts refusing connections, even on an idle pool."""
        while True:
            probes = [self._probe(r) for r in self.replicas]
            if probes:
                await asyncio.gather(*probes, return_exceptions=True)
            await asyncio.sleep(self.health_interval_s)

    def _record_success(self, r: Replica) -> None:
        r.consecutive_failures = 0
        r.eject_backoff_s = 0.0
        r.ejected_until = 0.0
        r.healthy = True

    def _record_failure(self, r: Replica, err: str) -> None:
        r.failures += 1
        r.last_error = err
        r.consecutive_failures += 1
        if r.consecutive_failures >= self.eject_threshold:
            r.eject_backoff_s = min(
                max(r.eject_backoff_s * 2.0, self.backoff_base_s),
                self.backoff_max_s,
            )
            r.ejected_until = time.monotonic() + r.eject_backoff_s
            r.ejections += 1
            self.ejections_total += 1
            logger.warning(
                "replica %s ejected for %.1f s after %d consecutive failures (%s)",
                r.url, r.eject_backoff_s, r.consecutive_failures, err,
            )

    # ---- routing ----

    def _pick(
        self, exclude: set[str], prefer: Optional[list[str]] = None
    ) -> Optional[Replica]:
        """Next replica to try. `prefer` (cache-affinity routing, ISSUE 11)
        is a ranked candidate order — the rendezvous ring's weight ordering
        for this request's key: the first AVAILABLE preferred replica wins,
        so a dead/ejected/draining owner deterministically falls to the
        next-highest-weight holder instead of a random survivor. With the
        preference order exhausted (or absent) selection is the original
        round-robin over whatever is left."""
        now = time.monotonic()
        if prefer:
            for url in prefer:
                if url in exclude:
                    continue
                r = self.replica_for(url)
                if r is not None and r.available(now):
                    return r
        candidates = [
            r for r in self.replicas
            if r.url not in exclude and r.available(now)
        ]
        if not candidates:
            return None
        return candidates[next(self._rr) % len(candidates)]

    def _raise_if_suspended(self) -> None:
        """Fail fast when nothing is worth trying: the pool is empty (scaled
        to zero) or every replica is ejected/down. The Retry-After hint is
        the soonest un-ejection (or one health-probe interval for replicas
        merely marked down), so clients back off just long enough."""
        now = time.monotonic()
        if any(r.available(now) for r in self.replicas):
            return
        waits = [
            r.ejected_until - now
            for r in self.replicas
            if r.ejected_until > now
        ]
        if waits:
            retry_after = min(waits)
        elif self.replicas:  # health-marked down: next probe may revive them
            retry_after = self.health_interval_s
        else:  # empty pool — membership has to change first
            retry_after = 1.0
        retry_after = min(max(retry_after, 0.5), self.backoff_max_s)
        self.suspended_total += 1
        self.failures_total += 1
        raise PoolSuspendedError(
            f"pool suspended: 0 of {len(self.replicas)} replicas available",
            retry_after_s=retry_after,
        )

    async def _attempt(
        self, r: Replica, path: str, payload: dict,
        headers: Optional[dict] = None,
    ):
        r.requests += 1
        resp = await self.client.post(
            f"{r.url}{path}", json=payload, headers=headers
        )
        return resp

    async def request(
        self,
        path: str,
        payload: dict,
        headers: Optional[dict] = None,
        prefer: Optional[list[str]] = None,
    ) -> httpx.Response:
        """POST `payload` with failover: try each distinct replica at most
        once per round, replaying on transport errors and replayable
        statuses; after a fully-failed round, pause briefly and run up to
        `max_rounds - 1` more (a preemption that takes the whole pool down
        for a beat — e.g. both replicas mid-drain — should cost the client
        milliseconds, not an error). Every attempt after the first spends
        from the retry budget; an exhausted budget raises
        RetryBudgetExhaustedError rather than amplifying a correlated
        failure. A pool with NO available replica fails fast with
        PoolSuspendedError (503 + Retry-After at the router) instead of
        waiting out the request deadline. Raises PoolExhaustedError when
        every round exhausted every replica."""
        self.requests_total += 1
        self.retry_budget.record_request()
        self._raise_if_suspended()
        last_err = ""
        first_attempt = True
        for round_idx in range(self.max_rounds):
            if round_idx:
                await asyncio.sleep(self.round_pause_s)
            tried: set[str] = set()
            for attempt in range(len(self.replicas)):
                r = self._pick(tried, prefer)
                if r is None:
                    if not self.has_available():
                        # everything got ejected mid-request (e.g. a storm
                        # took the last survivor): stop burning the deadline
                        self._raise_if_suspended()
                    break  # all available replicas tried — next round
                if not first_attempt:
                    # about to replay: spend budget BEFORE the attempt, so a
                    # correlated failure cannot amplify offered load
                    if not self.retry_budget.try_spend():
                        self.failures_total += 1
                        raise RetryBudgetExhaustedError(
                            f"retry budget exhausted "
                            f"({self.retry_budget.snapshot()['window_retries']}"
                            f" replays in {self.retry_budget.window_s:.0f} s "
                            f"window; last: {last_err})",
                            retry_after_s=1.0,
                        )
                    self.replays_total += 1
                first_attempt = False
                tried.add(r.url)
                try:
                    if self.hedge_after_s is not None and attempt == 0:
                        resp = await self._hedged_attempt(
                            r, tried, path, payload, headers, prefer
                        )
                    else:
                        resp = await self._attempt(r, path, payload, headers)
                except Exception as exc:  # connect/reset/timeout — kill signature
                    self._record_failure(r, repr(exc))
                    last_err = f"{r.url}: {exc!r}"
                    continue
                if resp.status_code in REPLAYABLE_STATUSES:
                    # the replica answered but can't serve (draining,
                    # breaker, queue full, engine fault): not a transport
                    # outlier unless it keeps happening — count a failure,
                    # replay elsewhere
                    self._record_failure(r, f"HTTP {resp.status_code}")
                    last_err = f"{r.url}: HTTP {resp.status_code}"
                    continue
                self._record_success(r)
                return resp
        self.failures_total += 1
        raise PoolExhaustedError(
            f"all {len(self.replicas)} replicas failed over "
            f"{self.max_rounds} rounds (last: {last_err})"
        )

    async def _hedged_attempt(
        self, first: Replica, tried: set[str], path: str, payload: dict,
        headers: Optional[dict] = None, prefer: Optional[list[str]] = None,
    ) -> httpx.Response:
        """Fire at `first`; if no answer within hedge_after_s, also fire at a
        second replica and take whichever succeeds first (the loser is
        cancelled). An error from every in-flight attempt propagates so
        request()'s replay logic treats it like an unhedged failure."""
        primary = asyncio.create_task(self._attempt(first, path, payload, headers))
        done, _ = await asyncio.wait({primary}, timeout=self.hedge_after_s)
        if done:
            return primary.result()  # success or raise-through to replay
        backup_replica = self._pick(tried | {first.url}, prefer)
        if backup_replica is None:  # nowhere to hedge: wait the primary out
            return await primary
        self.hedges_total += 1
        backup = asyncio.create_task(
            self._attempt(backup_replica, path, payload, headers)
        )
        pending = {primary, backup}
        last_exc: Optional[BaseException] = None
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                if t.exception() is None:
                    for p in pending:
                        p.cancel()
                    if t is backup:
                        self.hedge_wins_total += 1
                        self._record_success(backup_replica)
                    return t.result()
                last_exc = t.exception()
                if t is backup:  # request() only accounts for `first`
                    self._record_failure(backup_replica, repr(last_exc))
        assert last_exc is not None
        raise last_exc

    async def detect(self, payload: dict) -> dict:
        """POST /detect through the pool; returns the decoded JSON body."""
        resp = await self.request("/detect", payload)
        return resp.json()

    # ---- observability ----

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {
            "pool_requests_total": self.requests_total,
            "pool_replays_total": self.replays_total,
            "pool_hedges_total": self.hedges_total,
            "pool_hedge_wins_total": self.hedge_wins_total,
            "pool_ejections_total": self.ejections_total,
            "pool_failures_total": self.failures_total,
            "pool_suspended_total": self.suspended_total,
            "pool_retry_budget_exhausted_total": self.retry_budget.exhausted_total,
            "retry_budget": self.retry_budget.snapshot(),
            "replicas": [
                {
                    "url": r.url,
                    "healthy": r.healthy,
                    "available": r.available(now),
                    "ejected_for_s": max(r.ejected_until - now, 0.0),
                    "consecutive_failures": r.consecutive_failures,
                    "requests": r.requests,
                    "failures": r.failures,
                    "ejections": r.ejections,
                    "last_error": r.last_error,
                }
                for r in self.replicas
            ],
        }
