"""Async failover client pool: health-checked replicas, ejection, replay.

A single hardened replica (ISSUE 1) still leaves clients staring at hard
errors the moment that replica is preempted — on spot TPU capacity that is
routine, not exceptional (Spotlight, arXiv:2606.19004). This pool is the
fleet-side answer, DeepServe-style health-aware routing (arXiv:2501.14417)
in one file:

- **Selection**: round-robin over replicas that are neither ejected nor
  marked unhealthy by the background health loop (`/healthz` readiness, so
  a draining or breaker-open replica stops receiving traffic BEFORE it
  starts refusing connections).
- **Outlier ejection**: `eject_threshold` consecutive transport failures
  eject a replica for an exponentially growing backoff (doubling up to
  `backoff_max_s`); a later health-check success resets it.
- **Replay**: a `/detect` attempt that dies on a transport error
  (connection reset — the signature of a killed replica), times out, or
  answers 5xx/429 is replayed against the next replica. Detection is
  idempotent, so replay is safe; the client sees one answer, not the
  preemption.
- **Hedging** (optional): after `hedge_after_s` with no answer, a duplicate
  fires at a second replica and the first response wins — the tail-latency
  insurance for a replica that is technically alive but drowning.

`bench.py --failover` drives this pool; `python -m spotter_tpu.serving.router`
runs it as a tiny edge router. Counters surface in `snapshot()` (and the
router's /metrics): ejections, replays, hedges, client-visible failures.
"""

import asyncio
import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Optional

import httpx

logger = logging.getLogger(__name__)

DEFAULT_EJECT_THRESHOLD = 3
DEFAULT_BACKOFF_BASE_S = 0.5
DEFAULT_BACKOFF_MAX_S = 30.0
DEFAULT_HEALTH_INTERVAL_S = 0.5
DEFAULT_REQUEST_TIMEOUT_S = 30.0

# statuses that mean "this replica can't serve it right now, another might":
# 429 queue-full, 503 draining/breaker, 500 engine fault
REPLAYABLE_STATUSES = frozenset({429, 500, 502, 503})


class PoolExhaustedError(RuntimeError):
    """Every replica failed or was ejected for one request."""


@dataclass
class Replica:
    url: str  # base URL, e.g. http://127.0.0.1:8001
    healthy: bool = True
    consecutive_failures: int = 0
    ejected_until: float = 0.0
    eject_backoff_s: float = 0.0
    # diagnostics
    requests: int = 0
    failures: int = 0
    ejections: int = 0
    last_error: str = ""
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    def available(self, now: float) -> bool:
        return self.healthy and now >= self.ejected_until


class ReplicaPool:
    def __init__(
        self,
        endpoints: list[str],
        client: Optional[httpx.AsyncClient] = None,
        eject_threshold: int = DEFAULT_EJECT_THRESHOLD,
        backoff_base_s: float = DEFAULT_BACKOFF_BASE_S,
        backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
        health_interval_s: float = DEFAULT_HEALTH_INTERVAL_S,
        request_timeout_s: float = DEFAULT_REQUEST_TIMEOUT_S,
        hedge_after_s: Optional[float] = None,
        max_rounds: int = 2,
        round_pause_s: float = 0.25,
    ) -> None:
        if not endpoints:
            raise ValueError("ReplicaPool needs at least one endpoint")
        self.replicas = [Replica(url=u.rstrip("/")) for u in endpoints]
        self.client = client or httpx.AsyncClient(
            timeout=httpx.Timeout(request_timeout_s, connect=2.0)
        )
        self._owns_client = client is None
        self.eject_threshold = max(1, eject_threshold)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.health_interval_s = health_interval_s
        self.hedge_after_s = hedge_after_s
        self.max_rounds = max(1, max_rounds)
        self.round_pause_s = round_pause_s
        self._rr = itertools.count()
        self._health_task: Optional[asyncio.Task] = None
        # counters (event-loop only — no lock needed)
        self.requests_total = 0
        self.replays_total = 0
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.ejections_total = 0
        self.failures_total = 0  # client-visible (pool exhausted)

    # ---- lifecycle ----

    async def start(self) -> None:
        if self._health_task is None:
            self._health_task = asyncio.create_task(self._health_loop())

    async def stop(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None
        if self._owns_client:
            await self.client.aclose()

    # ---- health ----

    async def _probe(self, r: Replica) -> None:
        try:
            resp = await self.client.get(f"{r.url}/healthz", timeout=2.0)
            ok = resp.status_code == 200
        except Exception as exc:
            ok = False
            r.last_error = f"health: {exc!r}"
        if ok:
            self._record_success(r)
        else:
            r.healthy = False

    async def _health_loop(self) -> None:
        """Probe unavailable replicas so recovery (supervisor restart,
        breaker close, drain replaced by a fresh pod) un-ejects them without
        risking live traffic on a dead endpoint."""
        while True:
            now = time.monotonic()
            probes = [
                self._probe(r)
                for r in self.replicas
                if not r.healthy or r.ejected_until > now
            ]
            if probes:
                await asyncio.gather(*probes, return_exceptions=True)
            await asyncio.sleep(self.health_interval_s)

    def _record_success(self, r: Replica) -> None:
        r.consecutive_failures = 0
        r.eject_backoff_s = 0.0
        r.ejected_until = 0.0
        r.healthy = True

    def _record_failure(self, r: Replica, err: str) -> None:
        r.failures += 1
        r.last_error = err
        r.consecutive_failures += 1
        if r.consecutive_failures >= self.eject_threshold:
            r.eject_backoff_s = min(
                max(r.eject_backoff_s * 2.0, self.backoff_base_s),
                self.backoff_max_s,
            )
            r.ejected_until = time.monotonic() + r.eject_backoff_s
            r.ejections += 1
            self.ejections_total += 1
            logger.warning(
                "replica %s ejected for %.1f s after %d consecutive failures (%s)",
                r.url, r.eject_backoff_s, r.consecutive_failures, err,
            )

    # ---- routing ----

    def _pick(self, exclude: set[str]) -> Optional[Replica]:
        now = time.monotonic()
        candidates = [
            r for r in self.replicas
            if r.url not in exclude and r.available(now)
        ]
        if not candidates:
            # last resort: an ejected-but-not-excluded replica beats failing
            # the client outright (its ejection may be stale)
            candidates = [r for r in self.replicas if r.url not in exclude]
        if not candidates:
            return None
        return candidates[next(self._rr) % len(candidates)]

    async def _attempt(self, r: Replica, path: str, payload: dict):
        r.requests += 1
        resp = await self.client.post(f"{r.url}{path}", json=payload)
        return resp

    async def request(self, path: str, payload: dict) -> httpx.Response:
        """POST `payload` with failover: try each distinct replica at most
        once per round, replaying on transport errors and replayable
        statuses; after a fully-failed round, pause briefly and run up to
        `max_rounds - 1` more (a preemption that takes the whole pool down
        for a beat — e.g. both replicas mid-drain — should cost the client
        milliseconds, not an error). Raises PoolExhaustedError when every
        round exhausted every replica."""
        self.requests_total += 1
        last_err = ""
        for round_idx in range(self.max_rounds):
            if round_idx:
                await asyncio.sleep(self.round_pause_s)
            tried: set[str] = set()
            for attempt in range(len(self.replicas)):
                r = self._pick(tried)
                if r is None:
                    break
                tried.add(r.url)
                try:
                    if self.hedge_after_s is not None and attempt == 0:
                        resp = await self._hedged_attempt(r, tried, path, payload)
                    else:
                        resp = await self._attempt(r, path, payload)
                except Exception as exc:  # connect/reset/timeout — kill signature
                    self._record_failure(r, repr(exc))
                    last_err = f"{r.url}: {exc!r}"
                    self.replays_total += 1
                    continue
                if resp.status_code in REPLAYABLE_STATUSES:
                    # the replica answered but can't serve (draining,
                    # breaker, queue full, engine fault): not a transport
                    # outlier unless it keeps happening — count a failure,
                    # replay elsewhere
                    self._record_failure(r, f"HTTP {resp.status_code}")
                    last_err = f"{r.url}: HTTP {resp.status_code}"
                    self.replays_total += 1
                    continue
                self._record_success(r)
                return resp
        self.failures_total += 1
        raise PoolExhaustedError(
            f"all {len(self.replicas)} replicas failed over "
            f"{self.max_rounds} rounds (last: {last_err})"
        )

    async def _hedged_attempt(
        self, first: Replica, tried: set[str], path: str, payload: dict
    ) -> httpx.Response:
        """Fire at `first`; if no answer within hedge_after_s, also fire at a
        second replica and take whichever succeeds first (the loser is
        cancelled). An error from every in-flight attempt propagates so
        request()'s replay logic treats it like an unhedged failure."""
        primary = asyncio.create_task(self._attempt(first, path, payload))
        done, _ = await asyncio.wait({primary}, timeout=self.hedge_after_s)
        if done:
            return primary.result()  # success or raise-through to replay
        backup_replica = self._pick(tried | {first.url})
        if backup_replica is None:  # nowhere to hedge: wait the primary out
            return await primary
        self.hedges_total += 1
        backup = asyncio.create_task(self._attempt(backup_replica, path, payload))
        pending = {primary, backup}
        last_exc: Optional[BaseException] = None
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                if t.exception() is None:
                    for p in pending:
                        p.cancel()
                    if t is backup:
                        self.hedge_wins_total += 1
                        self._record_success(backup_replica)
                    return t.result()
                last_exc = t.exception()
                if t is backup:  # request() only accounts for `first`
                    self._record_failure(backup_replica, repr(last_exc))
        assert last_exc is not None
        raise last_exc

    async def detect(self, payload: dict) -> dict:
        """POST /detect through the pool; returns the decoded JSON body."""
        resp = await self.request("/detect", payload)
        return resp.json()

    # ---- observability ----

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {
            "pool_requests_total": self.requests_total,
            "pool_replays_total": self.replays_total,
            "pool_hedges_total": self.hedges_total,
            "pool_hedge_wins_total": self.hedge_wins_total,
            "pool_ejections_total": self.ejections_total,
            "pool_failures_total": self.failures_total,
            "replicas": [
                {
                    "url": r.url,
                    "healthy": r.healthy,
                    "available": r.available(now),
                    "ejected_for_s": max(r.ejected_until - now, 0.0),
                    "consecutive_failures": r.consecutive_failures,
                    "requests": r.requests,
                    "failures": r.failures,
                    "ejections": r.ejections,
                    "last_error": r.last_error,
                }
                for r in self.replicas
            ],
        }
