"""Async failover client pool: health-checked replicas, ejection, replay.

A single hardened replica (ISSUE 1) still leaves clients staring at hard
errors the moment that replica is preempted — on spot TPU capacity that is
routine, not exceptional (Spotlight, arXiv:2606.19004). This pool is the
fleet-side answer, DeepServe-style health-aware routing (arXiv:2501.14417)
in one file:

- **Selection**: round-robin over replicas that are neither ejected nor
  marked unhealthy by the background health loop (`/healthz` readiness, so
  a draining or breaker-open replica stops receiving traffic BEFORE it
  starts refusing connections).
- **Outlier ejection**: `eject_threshold` consecutive transport failures
  eject a replica for an exponentially growing backoff (doubling up to
  `backoff_max_s`); a later health-check success resets it.
- **Gray-failure scoring + soft ejection** (ISSUE 14): hard ejection only
  fires on transport FAILURES, so a replica that answers /healthz but
  serves 10x slow — spot-VM throttling, a noisy neighbor (Spotlight's
  gray-failure signature) — used to poison fleet p99 indefinitely. Every
  replica now carries two latency EWMAs (request latency and health-probe
  latency; the probe one means a silent-slow replica is detected with ZERO
  traffic) compared against the pool median of the same kind: a score of
  `ewma / median`, taking the worse of the two kinds. A score past
  `SPOTTER_TPU_OUTLIER_RATIO` soft-ejects the replica — it stays in the
  ring but its selection weight drops to `SPOTTER_TPU_OUTLIER_WEIGHT`
  (default 5%), in both the round-robin path (smooth weighted RR) and the
  cache-affinity `prefer` path (deterministic thinning: the gray owner
  keeps a weight-sized trickle of its keyed traffic, the rest falls to the
  next-ranked holder). The trickle plus the probes keep the EWMAs honest;
  once the score recovers under the restore ratio the replica enters a
  CANARY state (quarter weight) and only returns to full weight after
  `canary_ok` consecutive good responses — no binary eject flap. The last
  available non-gray replica is never soft-ejected, and scores below an
  absolute floor (`SPOTTER_TPU_OUTLIER_MIN_MS`) never trip it, so
  microsecond-noise on a fast fleet cannot manufacture outliers.
- **Replay**: a `/detect` attempt that dies on a transport error
  (connection reset — the signature of a killed replica), times out,
  answers 5xx/429, or fails the caller's response `validator` (a corrupt
  binary frame — wire.py CRC, ISSUE 14) is replayed against the next
  replica. Detection is idempotent, so replay is safe; the client sees one
  answer, not the preemption. Replays spend from a `RetryBudget` (ISSUE 6):
  a correlated failure — a preemption storm taking half the fleet — must
  not amplify offered load with unbudgeted retries, so replays in a sliding
  window are capped at `SPOTTER_TPU_RETRY_BUDGET_PCT` of the recent request
  count (with a small floor so single-replica deaths still fail over
  cleanly); an exhausted budget fails the request FAST with a 503-shaped
  error instead of piling more attempts onto survivors.
- **Fast-fail when suspended** (ISSUE 6 bugfix): when every replica is
  ejected or health-marked down — or the pool is empty because its tier
  scaled to zero — `request()` raises `PoolSuspendedError` immediately
  (with a Retry-After hint derived from the soonest un-ejection) instead of
  burning the client's whole deadline on a candidate set that cannot serve.
- **Budgeted adaptive hedging** (ISSUE 14, upgrading the ISSUE 2 fixed
  timer): with `adaptive_hedge=True` the hedge trigger is the live pool
  p95 (a sliding window of observed request latencies) instead of a static
  `hedge_after_s` — the timer tracks what "slow" means for THIS pool under
  THIS load. Hedge spend is capped by a sliding-window hedge budget
  (`SPOTTER_TPU_HEDGE_BUDGET_PCT` of recent requests, floor
  `SPOTTER_TPU_HEDGE_BUDGET_MIN`) exactly like the retry budget: an
  exhausted budget falls back to un-hedged waiting (never an error).
  The losing attempt is CANCELLED (the underlying HTTP request torn down,
  awaited to completion) and excluded from breaker/ejection counts — a
  cancelled loser is the hedge's fault, not the replica's — though its
  elapsed time does feed the loser's latency EWMA, so chronic hedge losers
  converge to gray.

Membership is dynamic (`add_endpoint` / `remove_endpoint`): the fleet
controller (serving/fleet.py) grows and shrinks pools as spot capacity
churns and idle tiers scale to zero.

`bench.py --failover` and `bench.py --gray-storm` drive this pool;
`python -m spotter_tpu.serving.router` runs it as a tiny edge router.
Counters surface in `snapshot()` (and the router's /metrics): ejections,
soft ejections/restores, replays, hedges (+ budget exhaustions and loser
cancellations), invalid responses, budget exhaustions, client-visible
failures.
"""

import asyncio
import itertools
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import httpx

from spotter_tpu.serving.resilience import Ewma
from spotter_tpu.serving.wire import VERSION_HEADER

logger = logging.getLogger(__name__)

DEFAULT_EJECT_THRESHOLD = 3
DEFAULT_BACKOFF_BASE_S = 0.5
DEFAULT_BACKOFF_MAX_S = 30.0
DEFAULT_HEALTH_INTERVAL_S = 0.5
DEFAULT_REQUEST_TIMEOUT_S = 30.0

RETRY_BUDGET_PCT_ENV = "SPOTTER_TPU_RETRY_BUDGET_PCT"
RETRY_BUDGET_MIN_ENV = "SPOTTER_TPU_RETRY_BUDGET_MIN"
DEFAULT_RETRY_BUDGET_PCT = 10.0
# Floor: a single killed replica can strand up to a client-concurrency's
# worth of in-flight requests at once; those replays must never be the ones
# the budget refuses, or plain one-replica failover (ISSUE 2) breaks.
DEFAULT_RETRY_BUDGET_MIN = 10
DEFAULT_RETRY_BUDGET_WINDOW_S = 30.0

# Gray-failure outlier scoring (ISSUE 14). Ratios are against the pool
# median of the same latency kind; the restore ratio sits well under the
# trip ratio (hysteresis) so a replica hovering at the boundary doesn't
# flap between full and thinned weight.
OUTLIER_RATIO_ENV = "SPOTTER_TPU_OUTLIER_RATIO"
OUTLIER_RESTORE_RATIO_ENV = "SPOTTER_TPU_OUTLIER_RESTORE_RATIO"
OUTLIER_ALPHA_ENV = "SPOTTER_TPU_OUTLIER_ALPHA"
OUTLIER_WEIGHT_ENV = "SPOTTER_TPU_OUTLIER_WEIGHT"
OUTLIER_MIN_SAMPLES_ENV = "SPOTTER_TPU_OUTLIER_MIN_SAMPLES"
OUTLIER_MIN_MS_ENV = "SPOTTER_TPU_OUTLIER_MIN_MS"
DEFAULT_OUTLIER_RATIO = 3.0  # <= 0 disables the scorer entirely
DEFAULT_OUTLIER_RESTORE_RATIO = 1.5
DEFAULT_OUTLIER_ALPHA = 0.3
DEFAULT_OUTLIER_WEIGHT = 0.05  # gray replica's traffic share
DEFAULT_OUTLIER_MIN_SAMPLES = 8
DEFAULT_OUTLIER_MIN_MS = 20.0  # below this an EWMA can never be an outlier
CANARY_WEIGHT = 0.25  # re-probe share while confirming recovery
CANARY_OK_REQUIRED = 3  # consecutive good canary responses to restore

# replica outlier states
OUTLIER_OK = "ok"
OUTLIER_GRAY = "gray"
OUTLIER_CANARY = "canary"

# Budgeted adaptive hedging (ISSUE 14)
HEDGE_BUDGET_PCT_ENV = "SPOTTER_TPU_HEDGE_BUDGET_PCT"
HEDGE_BUDGET_MIN_ENV = "SPOTTER_TPU_HEDGE_BUDGET_MIN"
DEFAULT_HEDGE_BUDGET_PCT = 10.0
DEFAULT_HEDGE_BUDGET_MIN = 5
DEFAULT_HEDGE_QUANTILE = 0.95
# adaptive trigger needs this many windowed samples before the observed
# quantile is trusted; colder pools fall back to the static timer (if any)
HEDGE_MIN_SAMPLES = 20
HEDGE_WINDOW = 512  # sliding sample window behind the adaptive trigger
# The trigger is floored at this multiple of the observed p50: on a TIGHT
# latency distribution the p95 sits just above typical, so a bare-quantile
# trigger would hedge ~5% of perfectly healthy requests by construction —
# pure duplicate load for zero tail win (measured +1.3% unloaded p50).
# Hedging only pays when the tail is DETACHED from typical (a drowning
# replica), which is exactly tail >= 2x p50.
HEDGE_MIN_P50_RATIO = 2.0
# the sorted-window quantile is recomputed at most every this many new
# samples (a 512-float sort per request is measurable at 20 ms services)
_HEDGE_RECOMPUTE_EVERY = 16

# statuses that mean "this replica can't serve it right now, another might":
# 429 queue-full, 503 draining/breaker, 500 engine fault
REPLAYABLE_STATUSES = frozenset({429, 500, 502, 503})


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


class PoolExhaustedError(RuntimeError):
    """Every replica failed or was ejected for one request."""

    def __init__(self, msg: str, retry_after_s: float = 1.0) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class PoolSuspendedError(PoolExhaustedError):
    """No replica is even worth trying right now (all ejected/down, or the
    pool is empty): fail fast with a Retry-After instead of waiting out the
    request deadline against a candidate set that cannot serve."""


class RetryBudgetExhaustedError(PoolExhaustedError):
    """A replay was needed but the budget refuses to amplify load further."""


class RetryBudget:
    """Sliding-window retry budget (Envoy-style, rate-based): replays in the
    last `window_s` seconds are capped at max(`min_retries`,
    `pct`% of requests seen in the same window). Shared budgets are fine —
    the fleet controller gives each pool its own slice so a bulk-tier storm
    cannot starve SLO-tier failover. The hedge budget (ISSUE 14) is a
    second instance of this same class over its own knobs: hedges are
    deliberate load amplification too, just cheaper per event.
    """

    def __init__(
        self,
        pct: Optional[float] = None,
        min_retries: Optional[int] = None,
        window_s: float = DEFAULT_RETRY_BUDGET_WINDOW_S,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if pct is None:
            raw = os.environ.get(RETRY_BUDGET_PCT_ENV, "").strip()
            pct = float(raw) if raw else DEFAULT_RETRY_BUDGET_PCT
        if min_retries is None:
            raw = os.environ.get(RETRY_BUDGET_MIN_ENV, "").strip()
            min_retries = int(raw) if raw else DEFAULT_RETRY_BUDGET_MIN
        self.pct = max(float(pct), 0.0)
        self.min_retries = max(int(min_retries), 0)
        self.window_s = window_s
        self._clock = clock
        self._requests: deque[float] = deque()
        self._retries: deque[float] = deque()
        self.exhausted_total = 0

    @classmethod
    def for_hedging(cls, clock: Callable[[], float] = time.monotonic) -> "RetryBudget":
        """The hedge-spend budget from its own env knobs (ISSUE 14)."""
        return cls(
            pct=_env_float(HEDGE_BUDGET_PCT_ENV, DEFAULT_HEDGE_BUDGET_PCT),
            min_retries=_env_int(
                HEDGE_BUDGET_MIN_ENV, DEFAULT_HEDGE_BUDGET_MIN
            ),
            clock=clock,
        )

    def _trim(self, now: float) -> None:
        horizon = now - self.window_s
        while self._requests and self._requests[0] < horizon:
            self._requests.popleft()
        while self._retries and self._retries[0] < horizon:
            self._retries.popleft()

    def record_request(self) -> None:
        now = self._clock()
        self._trim(now)
        self._requests.append(now)

    def allowed(self) -> float:
        """Replays currently permitted in the window."""
        self._trim(self._clock())
        return max(
            float(self.min_retries), self.pct / 100.0 * len(self._requests)
        )

    def try_spend(self) -> bool:
        """Reserve one replay; False (and a bumped exhausted counter) when
        the window is already at its cap."""
        now = self._clock()
        self._trim(now)
        if len(self._retries) + 1 > self.allowed():
            self.exhausted_total += 1
            return False
        self._retries.append(now)
        return True

    def snapshot(self) -> dict:
        now = self._clock()
        self._trim(now)
        return {
            "pct": self.pct,
            "min_retries": self.min_retries,
            "window_s": self.window_s,
            "window_requests": len(self._requests),
            "window_retries": len(self._retries),
            "allowed": self.allowed(),
            "exhausted_total": self.exhausted_total,
        }


@dataclass
class Replica:
    url: str  # base URL, e.g. http://127.0.0.1:8001
    healthy: bool = True
    consecutive_failures: int = 0
    ejected_until: float = 0.0
    eject_backoff_s: float = 0.0
    # gray-failure scoring state (ISSUE 14): request-latency and
    # probe-latency EWMAs, the score vs the pool median, the soft-eject
    # state machine, and the deterministic weighted-selection accumulators
    req_ewma: Ewma = field(default_factory=Ewma)
    probe_ewma: Ewma = field(default_factory=Ewma)
    outlier_state: str = OUTLIER_OK
    outlier_score: float = 0.0
    canary_ok: int = 0
    soft_ejections: int = 0
    wrr_credit: float = 0.0  # smooth weighted round-robin accumulator
    prefer_credit: float = 0.0  # affinity-path thinning accumulator
    # deployment identity (ISSUE 15): which build this replica serves —
    # set by the rollout controller at membership time and kept fresh from
    # the X-Spotter-Version response header. "" = unknown (pre-version
    # fleets), which matches every pin.
    version: str = ""
    # externally pinned selection weight (rollout canary hold): None =
    # unpinned; combined with the outlier-state weight by taking the min
    pinned_weight: Optional[float] = None
    # hard quarantine (ISSUE 17): set by the integrity plane when the
    # replica's answers disagree with the quorum. Unlike gray soft
    # ejection (a 5% trickle so latency can recover), quarantine is
    # ABSOLUTE — zero weight, no canary trickle, no health-loop
    # restoration — because a wrong answer served is a wrong answer a
    # client acted on. Only an explicit unquarantine (operator, or the
    # replica's verified post-86 restart) lifts it.
    quarantined: bool = False
    quarantine_reason: str = ""
    # diagnostics
    requests: int = 0
    failures: int = 0
    ejections: int = 0
    last_error: str = ""
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    def available(self, now: float) -> bool:
        return (
            self.healthy and not self.quarantined and now >= self.ejected_until
        )


def _median(values: list[float]) -> Optional[float]:
    if not values:
        return None
    vals = sorted(values)
    n = len(vals)
    if n % 2:
        return vals[n // 2]
    return 0.5 * (vals[n // 2 - 1] + vals[n // 2])


class ReplicaPool:
    def __init__(
        self,
        endpoints: list[str],
        client: Optional[httpx.AsyncClient] = None,
        eject_threshold: int = DEFAULT_EJECT_THRESHOLD,
        backoff_base_s: float = DEFAULT_BACKOFF_BASE_S,
        backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
        health_interval_s: float = DEFAULT_HEALTH_INTERVAL_S,
        request_timeout_s: float = DEFAULT_REQUEST_TIMEOUT_S,
        hedge_after_s: Optional[float] = None,
        adaptive_hedge: bool = False,
        hedge_quantile: float = DEFAULT_HEDGE_QUANTILE,
        hedge_budget: Optional[RetryBudget] = None,
        max_rounds: int = 2,
        round_pause_s: float = 0.25,
        retry_budget: Optional[RetryBudget] = None,
        outlier_ratio: Optional[float] = None,
        outlier_restore_ratio: Optional[float] = None,
        outlier_alpha: Optional[float] = None,
        outlier_weight: Optional[float] = None,
        outlier_min_samples: Optional[int] = None,
        outlier_min_ms: Optional[float] = None,
        allow_empty: bool = False,
    ) -> None:
        if not endpoints and not allow_empty:
            raise ValueError("ReplicaPool needs at least one endpoint")
        self.retry_budget = retry_budget or RetryBudget()
        self.client = client or httpx.AsyncClient(
            timeout=httpx.Timeout(request_timeout_s, connect=2.0)
        )
        self._owns_client = client is None
        self.eject_threshold = max(1, eject_threshold)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.health_interval_s = health_interval_s
        # hedging (ISSUE 2 static timer; ISSUE 14 adaptive trigger + budget)
        self.hedge_after_s = hedge_after_s
        self.adaptive_hedge = adaptive_hedge
        self.hedge_quantile = min(max(hedge_quantile, 0.5), 0.999)
        self.hedge_budget = hedge_budget or RetryBudget.for_hedging()
        self._lat_window: deque[float] = deque(maxlen=HEDGE_WINDOW)
        self._lat_samples = 0
        self._hedge_trigger_cache: Optional[float] = None
        self._hedge_trigger_at = 0
        # gray-failure scoring knobs (ISSUE 14); ratio <= 0 disables
        if outlier_ratio is None:
            outlier_ratio = _env_float(OUTLIER_RATIO_ENV, DEFAULT_OUTLIER_RATIO)
        if outlier_restore_ratio is None:
            outlier_restore_ratio = _env_float(
                OUTLIER_RESTORE_RATIO_ENV, DEFAULT_OUTLIER_RESTORE_RATIO
            )
        if outlier_alpha is None:
            outlier_alpha = _env_float(OUTLIER_ALPHA_ENV, DEFAULT_OUTLIER_ALPHA)
        if outlier_weight is None:
            outlier_weight = _env_float(
                OUTLIER_WEIGHT_ENV, DEFAULT_OUTLIER_WEIGHT
            )
        if outlier_min_samples is None:
            outlier_min_samples = _env_int(
                OUTLIER_MIN_SAMPLES_ENV, DEFAULT_OUTLIER_MIN_SAMPLES
            )
        if outlier_min_ms is None:
            outlier_min_ms = _env_float(
                OUTLIER_MIN_MS_ENV, DEFAULT_OUTLIER_MIN_MS
            )
        self.outlier_ratio = float(outlier_ratio)
        self.outlier_restore_ratio = min(
            float(outlier_restore_ratio), max(self.outlier_ratio, 0.0)
        )
        self.outlier_alpha = float(outlier_alpha)
        self.outlier_weight = min(max(float(outlier_weight), 0.001), 1.0)
        self.outlier_min_samples = max(int(outlier_min_samples), 2)
        self.outlier_min_ms = max(float(outlier_min_ms), 0.0)
        self.max_rounds = max(1, max_rounds)
        self.round_pause_s = round_pause_s
        self._rr = itertools.count()
        self._health_task: Optional[asyncio.Task] = None
        self.replicas = [self._new_replica(u.rstrip("/")) for u in endpoints]
        # counters (event-loop only — no lock needed)
        self.requests_total = 0
        self.replays_total = 0
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.hedge_cancels_total = 0
        self.ejections_total = 0
        self.soft_ejections_total = 0
        self.soft_restores_total = 0
        self.invalid_responses_total = 0  # validator rejections (frame CRC)
        self.failures_total = 0  # client-visible (pool exhausted)
        self.suspended_total = 0  # fast-failed: nothing worth trying
        # mixed-version request pinning (ISSUE 15)
        self.version_pinned_replays_total = 0
        self.version_pin_relaxed_total = 0
        # hard quarantine (ISSUE 17)
        self.quarantines_total = 0
        self.quarantines_refused_total = 0

    def _new_replica(self, url: str, healthy: bool = True) -> Replica:
        r = Replica(url=url, healthy=healthy)
        r.req_ewma = Ewma(self.outlier_alpha)
        r.probe_ewma = Ewma(self.outlier_alpha)
        return r

    # ---- membership (fleet controller: spot churn, scale-to-zero) ----

    def add_endpoint(self, url: str, healthy: bool = False) -> Replica:
        """Add a replica at runtime. New members default to `healthy=False`
        ("starting"): the health loop promotes them on the first /healthz 200,
        so live traffic never races a replica that is still binding/compiling."""
        url = url.rstrip("/")
        existing = self.replica_for(url)
        if existing is not None:
            return existing
        r = self._new_replica(url, healthy=healthy)
        self.replicas.append(r)
        return r

    def remove_endpoint(self, url: str) -> Optional[Replica]:
        url = url.rstrip("/")
        r = self.replica_for(url)
        if r is not None:
            self.replicas.remove(r)
        return r

    def replica_for(self, url: str) -> Optional[Replica]:
        url = url.rstrip("/")
        for r in self.replicas:
            if r.url == url:
                return r
        return None

    def set_version(self, url: str, version: str) -> None:
        """Pin a replica's deploy version (ISSUE 15). The rollout
        controller calls this when it adds a canary so version pinning
        works BEFORE the first response teaches the pool; live responses
        keep it fresh afterwards (the X-Spotter-Version header)."""
        r = self.replica_for(url)
        if r is not None:
            r.version = version

    def set_weight(self, url: str, weight: Optional[float]) -> None:
        """Pin (or with None clear) a replica's selection weight — the
        rollout canary hold (ISSUE 15). Composes with the gray-failure
        scorer by taking the min, so a gray canary is thinned even
        further, never boosted."""
        r = self.replica_for(url)
        if r is not None:
            r.pinned_weight = (
                None if weight is None
                else min(max(float(weight), 0.001), 1.0)
            )

    def has_available(self) -> bool:
        now = time.monotonic()
        return any(r.available(now) for r in self.replicas)

    # ---- hard quarantine (ISSUE 17 output-integrity plane) ----

    def quarantine(self, url: str, reason: str = "") -> bool:
        """Hard-quarantine a replica: out of the ring at ZERO weight —
        primaries, replays, hedges, affinity preferences and quorum
        witnessing all stop immediately (`available()` is the single
        gate they share). Refused (False, counted) for an unknown or
        already-quarantined url, and for the LAST available replica:
        quarantining the whole fleet turns "some wrong answers" into
        "no answers at all", which is an operator decision, not an
        automated one."""
        r = self.replica_for(url)
        if r is None or r.quarantined:
            self.quarantines_refused_total += 1
            return False
        now = time.monotonic()
        peers = sum(
            1 for o in self.replicas if o is not r and o.available(now)
        )
        if peers < 1:
            self.quarantines_refused_total += 1
            logger.error(
                "REFUSING to quarantine %s (%s): it is the last available "
                "replica — operator attention required", url, reason,
            )
            return False
        r.quarantined = True
        r.quarantine_reason = reason
        self.quarantines_total += 1
        logger.error(
            "replica %s HARD-QUARANTINED (zero weight, no trickle): %s",
            url, reason,
        )
        return True

    def unquarantine(self, url: str) -> bool:
        """Lift a quarantine (operator path, or a replica readmitted
        after its post-86 restart passed verified readiness)."""
        r = self.replica_for(url)
        if r is None or not r.quarantined:
            return False
        r.quarantined = False
        r.quarantine_reason = ""
        logger.warning("replica %s quarantine lifted", url)
        return True

    def pick_other(self, exclude=()) -> Optional[str]:
        """Public witness selection for the integrity quorum sampler: the
        next ranked AVAILABLE replica outside `exclude`, through the same
        smooth-WRR the primary path uses (so dual-dispatch load spreads
        and a thinned gray replica witnesses proportionally less)."""
        r = self._pick({u.rstrip("/") for u in exclude})
        return r.url if r is not None else None

    # ---- lifecycle ----

    async def start(self) -> None:
        if self._health_task is None:
            self._health_task = asyncio.create_task(self._health_loop())

    async def stop(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None
        if self._owns_client:
            await self.client.aclose()

    # ---- health ----

    async def _probe(self, r: Replica) -> None:
        t0 = time.monotonic()
        try:
            resp = await self.client.get(f"{r.url}/healthz", timeout=2.0)
            ok = resp.status_code == 200
        except Exception as exc:
            ok = False
            r.last_error = f"health: {exc!r}"
        if self.replica_for(r.url) is not r:
            # the member was retired (remove_endpoint) — or removed and
            # re-added as a NEW Replica object — while this probe was in
            # flight (ISSUE 16 satellite): mutating the stale object now
            # would resurrect a retiring member into the ring mid-drain,
            # exactly the adoption/retire race the reconcile loop surfaced
            return
        if not ok:
            r.healthy = False
            return
        # probe latency feeds the gray-failure score (ISSUE 14 satellite:
        # it used to be measured and discarded) — a replica whose event
        # loop is starved answers /healthz slow long before live traffic
        # would show it, so a silent-slow replica is flagged with ZERO
        # /detect traffic
        self._observe_latency(r, (time.monotonic() - t0) * 1e3, probe=True)
        if not r.available(time.monotonic()):
            # only an UNAVAILABLE replica is promoted by a probe success; on
            # an available one the success is a no-op so probes cannot reset
            # the consecutive-failure count live traffic is accumulating
            self._record_success(r)

    async def _health_loop(self) -> None:
        """Probe every replica: an unavailable one so recovery (supervisor
        restart, breaker close, drain replaced by a fresh pod) un-ejects it
        without risking live traffic on a dead endpoint, and an available
        one so a readiness flip (drain, maintenance notice — the preemption
        signature the fleet controller watches) stops routing BEFORE the
        replica starts refusing connections, even on an idle pool."""
        while True:
            probes = [self._probe(r) for r in self.replicas]
            if probes:
                await asyncio.gather(*probes, return_exceptions=True)
            await asyncio.sleep(self.health_interval_s)

    def _record_success(self, r: Replica) -> None:
        r.consecutive_failures = 0
        r.eject_backoff_s = 0.0
        r.ejected_until = 0.0
        r.healthy = True

    def _record_failure(self, r: Replica, err: str) -> None:
        r.failures += 1
        r.last_error = err
        r.consecutive_failures += 1
        if r.consecutive_failures >= self.eject_threshold:
            r.eject_backoff_s = min(
                max(r.eject_backoff_s * 2.0, self.backoff_base_s),
                self.backoff_max_s,
            )
            r.ejected_until = time.monotonic() + r.eject_backoff_s
            r.ejections += 1
            self.ejections_total += 1
            logger.warning(
                "replica %s ejected for %.1f s after %d consecutive failures (%s)",
                r.url, r.eject_backoff_s, r.consecutive_failures, err,
            )

    # ---- gray-failure scoring (ISSUE 14) ----

    def _observe_latency(
        self, r: Replica, ms: float, probe: bool = False, window: bool = True
    ) -> None:
        """One latency observation for `r`: update the kind's EWMA, feed
        the pool-wide hedge-trigger window (request latencies only), count
        canary evidence, and re-run the outlier state machine."""
        if probe:
            r.probe_ewma.update(ms)
        else:
            r.req_ewma.update(ms)
            if window:
                self._lat_window.append(ms)
                self._lat_samples += 1
            if r.outlier_state == OUTLIER_CANARY:
                r.canary_ok += 1
        if self.outlier_ratio > 0:
            self._update_outliers()

    def _outlier_score(
        self,
        r: Replica,
        med_req: Optional[float],
        med_probe: Optional[float],
    ) -> float:
        """`ewma / pool median`, the worse of the request and probe kinds.
        A kind contributes only with enough samples AND an EWMA above the
        absolute floor — a 0.3 ms probe against a 0.1 ms median is noise,
        not a gray failure."""
        score = 0.0
        if (
            med_req
            and r.req_ewma.samples >= self.outlier_min_samples
            and r.req_ewma.value >= self.outlier_min_ms
        ):
            score = r.req_ewma.value / med_req
        if (
            med_probe
            and r.probe_ewma.samples >= self.outlier_min_samples
            and r.probe_ewma.value >= self.outlier_min_ms
        ):
            score = max(score, r.probe_ewma.value / med_probe)
        return score

    def _update_outliers(self) -> None:
        """Recompute every replica's score against the pool medians and run
        the soft-ejection state machine:

            ok ---(score >= ratio, peers exist)--> gray (weight-down)
            gray --(score <= restore ratio)------> canary (quarter weight)
            canary --(CANARY_OK good responses)--> ok (full restore)
            canary --(score >= ratio again)------> gray

        The medians need at least two contributing replicas — with one
        member there is no peer to be slower than."""
        req_vals = [
            r.req_ewma.value
            for r in self.replicas
            if r.req_ewma.samples >= self.outlier_min_samples
        ]
        probe_vals = [
            r.probe_ewma.value
            for r in self.replicas
            if r.probe_ewma.samples >= self.outlier_min_samples
        ]
        med_req = _median(req_vals) if len(req_vals) >= 2 else None
        med_probe = _median(probe_vals) if len(probe_vals) >= 2 else None
        if not med_req and not med_probe:
            return
        now = time.monotonic()
        for r in self.replicas:
            score = self._outlier_score(r, med_req, med_probe)
            r.outlier_score = score
            if r.outlier_state == OUTLIER_OK:
                if score >= self.outlier_ratio:
                    # never soft-eject the last non-gray available replica:
                    # a thinned pool of one is just a slower pool of one
                    peers = sum(
                        1
                        for o in self.replicas
                        if o is not r
                        and o.available(now)
                        and o.outlier_state != OUTLIER_GRAY
                    )
                    if peers >= 1:
                        r.outlier_state = OUTLIER_GRAY
                        r.canary_ok = 0
                        r.soft_ejections += 1
                        self.soft_ejections_total += 1
                        logger.warning(
                            "replica %s soft-ejected (gray): latency score "
                            "%.2fx pool median (req %.1f ms, probe %.1f ms)",
                            r.url, score, r.req_ewma.value, r.probe_ewma.value,
                        )
            elif r.outlier_state == OUTLIER_GRAY:
                if score <= self.outlier_restore_ratio:
                    r.outlier_state = OUTLIER_CANARY
                    r.canary_ok = 0
                    logger.info(
                        "replica %s score recovered (%.2fx): canary re-probe",
                        r.url, score,
                    )
            elif r.outlier_state == OUTLIER_CANARY:
                if score >= self.outlier_ratio:
                    r.outlier_state = OUTLIER_GRAY
                    r.canary_ok = 0
                elif (
                    score <= self.outlier_restore_ratio
                    and r.canary_ok >= CANARY_OK_REQUIRED
                ):
                    r.outlier_state = OUTLIER_OK
                    self.soft_restores_total += 1
                    logger.info(
                        "replica %s restored to full weight after %d good "
                        "canary responses", r.url, r.canary_ok,
                    )

    def _weight(self, r: Replica) -> float:
        w = 1.0
        if r.outlier_state == OUTLIER_GRAY:
            w = self.outlier_weight
        elif r.outlier_state == OUTLIER_CANARY:
            w = CANARY_WEIGHT
        if r.pinned_weight is not None:  # rollout canary hold (ISSUE 15)
            w = min(w, r.pinned_weight)
        return w

    # ---- routing ----

    def _pick(
        self,
        exclude: set[str],
        prefer: Optional[list[str]] = None,
        version: Optional[str] = None,
    ) -> Optional[Replica]:
        """Next replica to try. `prefer` (cache-affinity routing, ISSUE 11)
        is a ranked candidate order — the rendezvous ring's weight ordering
        for this request's key: the first AVAILABLE preferred replica wins,
        so a dead/ejected/draining owner deterministically falls to the
        next-highest-weight holder instead of a random survivor. A
        soft-ejected (gray/canary) preferred holder is THINNED, not
        skipped: a deterministic credit accumulator gives it its weight's
        share of its keyed traffic (the canary trickle that lets its EWMA
        recover) and hands the rest to the next-ranked holder. With the
        preference order exhausted (or absent) selection is round-robin
        while every candidate is at full weight, else smooth weighted
        round-robin over the outlier weights.

        `version` (ISSUE 15) restricts candidates to that deploy version
        during a mixed-version window: a replica of unknown version ("")
        always matches, so pre-version fleets are unaffected. Callers
        decide the fallback policy when nothing matches (request() relaxes
        the pin for replays; hedges stay strict)."""
        now = time.monotonic()

        def version_ok(r: Replica) -> bool:
            return not version or not r.version or r.version == version

        if prefer:
            for url in prefer:
                if url in exclude:
                    continue
                r = self.replica_for(url)
                if r is None or not r.available(now) or not version_ok(r):
                    continue
                w = self._weight(r)
                if w >= 1.0:
                    return r
                r.prefer_credit += w
                if r.prefer_credit >= 1.0:
                    r.prefer_credit -= 1.0
                    return r
                # thinned away this time: fall to the next-ranked holder
        candidates = [
            r for r in self.replicas
            if r.url not in exclude and r.available(now) and version_ok(r)
        ]
        if not candidates:
            return None
        if all(
            r.outlier_state == OUTLIER_OK and r.pinned_weight is None
            for r in candidates
        ):
            # the pre-ISSUE-14 behavior, bit-identical while nothing is
            # gray and no rollout canary holds a pinned weight
            return candidates[next(self._rr) % len(candidates)]
        # smooth weighted round-robin (the nginx algorithm): deterministic,
        # proportional to weight, and maximally spread — no RNG in routing
        total = 0.0
        best: Optional[Replica] = None
        for r in candidates:
            w = self._weight(r)
            total += w
            r.wrr_credit += w
            if best is None or r.wrr_credit > best.wrr_credit:
                best = r
        assert best is not None
        best.wrr_credit -= total
        return best

    def _raise_if_suspended(self) -> None:
        """Fail fast when nothing is worth trying: the pool is empty (scaled
        to zero) or every replica is ejected/down. The Retry-After hint is
        the soonest un-ejection (or one health-probe interval for replicas
        merely marked down), so clients back off just long enough."""
        now = time.monotonic()
        if any(r.available(now) for r in self.replicas):
            return
        waits = [
            r.ejected_until - now
            for r in self.replicas
            if r.ejected_until > now
        ]
        if waits:
            retry_after = min(waits)
        elif self.replicas:  # health-marked down: next probe may revive them
            retry_after = self.health_interval_s
        else:  # empty pool — membership has to change first
            retry_after = 1.0
        retry_after = min(max(retry_after, 0.5), self.backoff_max_s)
        self.suspended_total += 1
        self.failures_total += 1
        raise PoolSuspendedError(
            f"pool suspended: 0 of {len(self.replicas)} replicas available",
            retry_after_s=retry_after,
        )

    async def _attempt(
        self, r: Replica, path: str, payload: dict,
        headers: Optional[dict] = None,
        validator: Optional[Callable] = None,
    ):
        r.requests += 1
        t0 = time.monotonic()
        resp = await self.client.post(
            f"{r.url}{path}", json=payload, headers=headers
        )
        # version learning (ISSUE 15): every direct response names its
        # build, so the pool's per-replica version map stays fresh with no
        # extra round trips (fan-in responses are comma-joined and skipped)
        ver = resp.headers.get(VERSION_HEADER, "")
        if ver and "," not in ver:
            r.version = ver
        if validator is not None and resp.status_code == 200:
            # wire-integrity check (ISSUE 14): a 200 whose body fails the
            # caller's validator (corrupt frame CRC) is a transport-shaped
            # failure — the raise feeds ejection counts and the replay
            # loop, exactly like a connection reset, and the client never
            # sees it
            try:
                validator(resp)
            except Exception:
                self.invalid_responses_total += 1
                raise
        if resp.status_code not in REPLAYABLE_STATUSES:
            self._observe_latency(r, (time.monotonic() - t0) * 1e3)
        return resp

    def _hedge_trigger_s(self) -> Optional[float]:
        """When to fire the hedge: the live pool quantile once the window
        is warm (adaptive mode), else the static timer. None = no hedging.
        The adaptive trigger is floored at HEDGE_MIN_P50_RATIO x the
        observed p50 (see the constant) and cached between recomputes."""
        if self.adaptive_hedge and len(self._lat_window) >= HEDGE_MIN_SAMPLES:
            if (
                self._hedge_trigger_cache is None
                or self._lat_samples - self._hedge_trigger_at
                >= _HEDGE_RECOMPUTE_EVERY
            ):
                lats = sorted(self._lat_window)
                n = len(lats)
                q = lats[min(int(self.hedge_quantile * n), n - 1)]
                p50 = lats[n // 2]
                self._hedge_trigger_cache = max(
                    q, HEDGE_MIN_P50_RATIO * p50, 1.0
                ) / 1000.0
                self._hedge_trigger_at = self._lat_samples
            return self._hedge_trigger_cache
        return self.hedge_after_s

    async def request(
        self,
        path: str,
        payload: dict,
        headers: Optional[dict] = None,
        prefer: Optional[list[str]] = None,
        validator: Optional[Callable] = None,
    ) -> httpx.Response:
        """POST `payload` with failover: try each distinct replica at most
        once per round, replaying on transport errors, replayable statuses,
        and validator rejections (corrupt frames); after a fully-failed
        round, pause briefly and run up to `max_rounds - 1` more (a
        preemption that takes the whole pool down for a beat — e.g. both
        replicas mid-drain — should cost the client milliseconds, not an
        error). Every attempt after the first spends from the retry budget;
        an exhausted budget raises RetryBudgetExhaustedError rather than
        amplifying a correlated failure. A pool with NO available replica
        fails fast with PoolSuspendedError (503 + Retry-After at the
        router) instead of waiting out the request deadline. Raises
        PoolExhaustedError when every round exhausted every replica.

        `validator` (optional) is called on every 200 response body BEFORE
        it is accepted; a raise is treated as a transport failure of that
        replica (counted in `invalid_responses_total`, replayed against the
        next ranked holder) — the wire-integrity hook (ISSUE 14)."""
        self.requests_total += 1
        self.retry_budget.record_request()
        self.hedge_budget.record_request()
        self._raise_if_suspended()
        last_err = ""
        first_attempt = True
        # mixed-version pinning (ISSUE 15): once the first attempt lands on
        # a versioned replica, replays prefer the SAME deploy version —
        # during a rollout window a request must not be re-processed by an
        # incompatible build. A replay relaxes the pin when no same-version
        # candidate remains (the pinned attempt already failed; masking the
        # failure beats skew purity). Hedges stay strict (_hedged_attempt):
        # a hedge DOUBLE-processes by design, which is exactly what must
        # never straddle two versions.
        pinned_version: Optional[str] = None
        for round_idx in range(self.max_rounds):
            if round_idx:
                await asyncio.sleep(self.round_pause_s)
            tried: set[str] = set()
            for attempt in range(len(self.replicas)):
                r = self._pick(tried, prefer, version=pinned_version)
                if r is None and pinned_version is not None:
                    self.version_pin_relaxed_total += 1
                    pinned_version = None
                    r = self._pick(tried, prefer)
                if r is None:
                    if not self.has_available():
                        # everything got ejected mid-request (e.g. a storm
                        # took the last survivor): stop burning the deadline
                        self._raise_if_suspended()
                    break  # all available replicas tried — next round
                if pinned_version is None and r.version:
                    pinned_version = r.version
                elif not first_attempt and pinned_version:
                    self.version_pinned_replays_total += 1
                if not first_attempt:
                    # about to replay: spend budget BEFORE the attempt, so a
                    # correlated failure cannot amplify offered load
                    if not self.retry_budget.try_spend():
                        self.failures_total += 1
                        raise RetryBudgetExhaustedError(
                            f"retry budget exhausted "
                            f"({self.retry_budget.snapshot()['window_retries']}"
                            f" replays in {self.retry_budget.window_s:.0f} s "
                            f"window; last: {last_err})",
                            retry_after_s=1.0,
                        )
                    self.replays_total += 1
                first_attempt = False
                tried.add(r.url)
                try:
                    trigger_s = self._hedge_trigger_s()
                    if trigger_s is not None and attempt == 0:
                        resp = await self._hedged_attempt(
                            r, tried, path, payload, headers, prefer,
                            trigger_s, validator,
                        )
                    else:
                        resp = await self._attempt(
                            r, path, payload, headers, validator
                        )
                except Exception as exc:  # connect/reset/timeout/corrupt
                    self._record_failure(r, repr(exc))
                    last_err = f"{r.url}: {exc!r}"
                    continue
                if resp.status_code in REPLAYABLE_STATUSES:
                    # the replica answered but can't serve (draining,
                    # breaker, queue full, engine fault): not a transport
                    # outlier unless it keeps happening — count a failure,
                    # replay elsewhere
                    self._record_failure(r, f"HTTP {resp.status_code}")
                    last_err = f"{r.url}: HTTP {resp.status_code}"
                    continue
                self._record_success(r)
                return resp
        self.failures_total += 1
        raise PoolExhaustedError(
            f"all {len(self.replicas)} replicas failed over "
            f"{self.max_rounds} rounds (last: {last_err})"
        )

    async def _hedged_attempt(
        self, first: Replica, tried: set[str], path: str, payload: dict,
        headers: Optional[dict] = None, prefer: Optional[list[str]] = None,
        trigger_s: float = 0.0, validator: Optional[Callable] = None,
    ) -> httpx.Response:
        """Fire at `first`; if no answer within the trigger, spend one unit
        of hedge budget and also fire at a second replica, taking whichever
        succeeds first. The loser is CANCELLED — its HTTP request torn down
        and awaited, no failure recorded against its replica (a cancelled
        hedge is the hedge's doing, not the replica's), though the loser's
        elapsed time feeds its latency EWMA so chronic losers converge to
        gray. An exhausted budget degrades to un-hedged waiting. An error
        from every in-flight attempt propagates so request()'s replay logic
        treats it like an unhedged failure."""
        t0 = time.monotonic()
        primary = asyncio.create_task(
            self._attempt(first, path, payload, headers, validator)
        )
        done, _ = await asyncio.wait({primary}, timeout=trigger_s)
        if done:
            return primary.result()  # success or raise-through to replay
        # version-strict backup (ISSUE 15): a hedge runs BOTH attempts to
        # completion-or-cancel — the one shape that genuinely
        # double-processes — so during a mixed-version window the backup
        # must serve the primary's deploy version; with no same-version
        # candidate the hedge is skipped (un-hedged waiting, never an
        # error), exactly like an exhausted hedge budget.
        backup_replica = self._pick(
            tried | {first.url}, prefer, version=first.version or None
        )
        if backup_replica is None:  # nowhere to hedge: wait the primary out
            return await primary
        if not self.hedge_budget.try_spend():
            # budget refused: fall back to un-hedged (never an error) — the
            # counter rides self.hedge_budget.exhausted_total
            return await primary
        self.hedges_total += 1
        backup = asyncio.create_task(
            self._attempt(backup_replica, path, payload, headers, validator)
        )
        pending = {primary, backup}
        last_exc: Optional[BaseException] = None
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                if t.exception() is None:
                    if pending:
                        for p in pending:
                            p.cancel()
                        # actually tear the losing request down (the
                        # cancelled task closes its HTTP stream) before
                        # returning — a hedge must not leak work
                        await asyncio.gather(
                            *pending, return_exceptions=True
                        )
                        self.hedge_cancels_total += len(pending)
                        if t is backup:
                            # the loser ran at least this long: a truthful
                            # lower-bound latency sample for its EWMA (kept
                            # out of the hedge-trigger window — it is not a
                            # completed request latency)
                            self._observe_latency(
                                first,
                                (time.monotonic() - t0) * 1e3,
                                window=False,
                            )
                    if t is backup:
                        self.hedge_wins_total += 1
                        self._record_success(backup_replica)
                    return t.result()
                last_exc = t.exception()
                if t is backup:  # request() only accounts for `first`
                    self._record_failure(backup_replica, repr(last_exc))
        assert last_exc is not None
        raise last_exc

    async def detect(self, payload: dict) -> dict:
        """POST /detect through the pool; returns the decoded JSON body."""
        resp = await self.request("/detect", payload)
        return resp.json()

    # ---- observability ----

    def snapshot(self) -> dict:
        now = time.monotonic()
        trigger_s = self._hedge_trigger_s()
        return {
            "pool_requests_total": self.requests_total,
            "pool_replays_total": self.replays_total,
            "pool_hedges_total": self.hedges_total,
            "pool_hedge_wins_total": self.hedge_wins_total,
            "pool_hedge_cancels_total": self.hedge_cancels_total,
            "pool_hedge_budget_exhausted_total": self.hedge_budget.exhausted_total,
            "pool_ejections_total": self.ejections_total,
            "pool_soft_ejections_total": self.soft_ejections_total,
            "pool_soft_restores_total": self.soft_restores_total,
            "pool_invalid_responses_total": self.invalid_responses_total,
            "pool_failures_total": self.failures_total,
            "pool_suspended_total": self.suspended_total,
            "pool_retry_budget_exhausted_total": self.retry_budget.exhausted_total,
            "pool_version_pinned_replays_total": self.version_pinned_replays_total,
            "pool_version_pin_relaxed_total": self.version_pin_relaxed_total,
            "pool_quarantines_total": self.quarantines_total,
            "pool_quarantines_refused_total": self.quarantines_refused_total,
            "retry_budget": self.retry_budget.snapshot(),
            "hedge": {
                "adaptive": self.adaptive_hedge,
                "trigger_ms": (
                    round(trigger_s * 1e3, 3) if trigger_s is not None else None
                ),
                "quantile": self.hedge_quantile,
                "budget": self.hedge_budget.snapshot(),
            },
            "outlier": {
                "ratio": self.outlier_ratio,
                "restore_ratio": self.outlier_restore_ratio,
                "weight": self.outlier_weight,
                "min_samples": self.outlier_min_samples,
                "min_ms": self.outlier_min_ms,
            },
            "replicas": [
                {
                    "url": r.url,
                    "healthy": r.healthy,
                    "available": r.available(now),
                    "ejected_for_s": max(r.ejected_until - now, 0.0),
                    "consecutive_failures": r.consecutive_failures,
                    "requests": r.requests,
                    "failures": r.failures,
                    "ejections": r.ejections,
                    "outlier_state": r.outlier_state,
                    "outlier_score": round(r.outlier_score, 3),
                    "quarantined": r.quarantined,
                    "quarantine_reason": r.quarantine_reason,
                    "weight": self._weight(r),
                    "version": r.version,
                    "pinned_weight": r.pinned_weight,
                    "req_ewma_ms": round(r.req_ewma.value, 3),
                    "probe_ewma_ms": round(r.probe_ewma.value, 3),
                    "soft_ejections": r.soft_ejections,
                    "last_error": r.last_error,
                }
                for r in self.replicas
            ],
        }
