"""Serving bootstrap: MODEL_NAME env -> engine -> detector (+ Ray adapter).

Mirrors the reference's module-import bootstrap (serve.py:199-205): MODEL_NAME
is required and raises if unset; the built app object is what the RayService
manifest names as import_path (rayservice-template.yaml:8-9).

Ray Serve is optional in this build (it is the production fabric when
installed — reference pyproject.toml:11 — but the framework degrades to the
standalone aiohttp server, and tests never need Ray, matching the reference's
own practice of testing the undecorated class: test_serve.py:32).
"""

import os

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.models import build_detector
from spotter_tpu.serving.detector import AmenitiesDetector

DETECTION_THRESHOLD = 0.5  # serve.py:107


def build_detector_app(
    model_name: str | None = None,
    threshold: float = DETECTION_THRESHOLD,
    batch_buckets: tuple[int, ...] = (1, 2, 4, 8),
    max_delay_ms: float = 5.0,
    warmup: bool = False,
) -> AmenitiesDetector:
    model_name = model_name or os.environ.get("MODEL_NAME")
    if not model_name:
        raise ValueError("MODEL_NAME environment variable not set.")
    built = build_detector(model_name)
    engine = InferenceEngine(built, threshold=threshold, batch_buckets=batch_buckets)
    if warmup:
        engine.warmup()
    batcher = MicroBatcher(engine, max_delay_ms=max_delay_ms)
    return AmenitiesDetector(engine, batcher)


def ray_deployment():
    """Ray Serve deployment graph node (the manifest's import_path target)."""
    from ray import serve
    from starlette.requests import Request

    @serve.deployment
    class RayAmenitiesDetector:
        def __init__(self, model_name: str) -> None:
            self._inner = build_detector_app(model_name, warmup=True)

        async def __call__(self, raw_payload: "Request"):
            return await self._inner.detect(await raw_payload.json())

    model_name = os.environ.get("MODEL_NAME")
    if not model_name:
        raise ValueError("MODEL_NAME environment variable not set.")
    return RayAmenitiesDetector.bind(model_name)


try:  # module-level `deployment` preserved for manifest import_path parity
    import ray  # noqa: F401
except ImportError:  # Ray not installed — standalone mode
    deployment = None
else:
    # With Ray present, real bootstrap errors (missing MODEL_NAME, model load
    # failure) must propagate like the reference's import-time raise
    # (serve.py:199-201), not turn into an opaque import_path=None deploy.
    deployment = ray_deployment()
