"""Serving bootstrap: MODEL_NAME env -> engine -> detector (+ Ray adapter).

Mirrors the reference's module-import bootstrap (serve.py:199-205): MODEL_NAME
is required and raises if unset; the built app object is what the RayService
manifest names as import_path (rayservice-template.yaml:8-9).

Ray Serve is optional in this build (it is the production fabric when
installed — reference pyproject.toml:11 — but the framework degrades to the
standalone aiohttp server, and tests never need Ray, matching the reference's
own practice of testing the undecorated class: test_serve.py:32).
"""

import logging
import os

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine, default_batch_buckets
from spotter_tpu.models import build_detector
from spotter_tpu.models.registry import family_for
from spotter_tpu.serving.detector import AmenitiesDetector

logger = logging.getLogger(__name__)

DETECTION_THRESHOLD = 0.5  # serve.py:107

SERVE_DP_ENV = "SPOTTER_TPU_SERVE_DP"
SERVE_TP_ENV = "SPOTTER_TPU_SERVE_TP"
MESH_ENV = "SPOTTER_TPU_MESH"


def serve_dp_from_env() -> int:
    """SPOTTER_TPU_SERVE_DP: data-parallel serving width (0/1/unset = one
    chip; `all` = every local chip). Malformed values fail loudly."""
    raw = os.environ.get(SERVE_DP_ENV, "").strip()
    if not raw:
        return 1
    if raw.lower() == "all":
        import jax

        return max(1, len(jax.local_devices()))
    if not raw.isdigit():
        raise ValueError(f"{SERVE_DP_ENV} must be a positive int or 'all', got {raw!r}")
    return max(1, int(raw))


def serve_tp_from_env() -> int:
    """SPOTTER_TPU_SERVE_TP: tensor-parallel width (0/1/unset = params whole
    on every chip). Composes with SERVE_DP into a dp×tp mesh; the bucket
    ladder scales by dp ONLY — tp splits weights, not the batch."""
    raw = os.environ.get(SERVE_TP_ENV, "").strip()
    if not raw:
        return 1
    if not raw.isdigit():
        raise ValueError(f"{SERVE_TP_ENV} must be a positive int, got {raw!r}")
    return max(1, int(raw))


def parse_mesh_spec(spec: str) -> dict[str, int]:
    """"dp=4" / "dp=4,tp=2" -> {"dp": 4, "tp": 2} (the SPOTTER_TPU_MESH knob)."""
    out = {"tp": 1}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        if key not in ("dp", "tp") or not value.isdigit() or int(value) < 1:
            raise ValueError(
                f"bad SPOTTER_TPU_MESH entry '{part}' (expected dp=<n>[,tp=<n>])"
            )
        out[key] = int(value)
    if "dp" not in out:
        raise ValueError(f"SPOTTER_TPU_MESH '{spec}' must set dp=<n>")
    return out


def parse_batch_buckets(spec: str) -> tuple[int, ...]:
    """SPOTTER_TPU_BATCH_BUCKETS: comma-separated ascending bucket ladder."""
    try:
        buckets = tuple(int(v) for v in spec.split(","))
    except ValueError:
        buckets = ()
    if not buckets or any(b < 1 for b in buckets) or list(buckets) != sorted(
        set(buckets)
    ):
        raise ValueError(
            f"SPOTTER_TPU_BATCH_BUCKETS must be ascending positive ints, "
            f"got {spec!r}"
        )
    return buckets


def build_detector_app(
    model_name: str | None = None,
    threshold: float = DETECTION_THRESHOLD,
    batch_buckets: tuple[int, ...] | None = None,
    max_delay_ms: float = 5.0,
    warmup: bool = False,
    mesh_spec: str | None = None,
    serve_dp: int | None = None,
    cache_mb: float | None = None,
) -> AmenitiesDetector:
    model_name = model_name or os.environ.get("MODEL_NAME")
    if not model_name:
        raise ValueError("MODEL_NAME environment variable not set.")
    # Warm restart (ISSUE 2): arm JAX's persistent compilation cache
    # (SPOTTER_TPU_COMPILE_CACHE_DIR) before the first jit — a preempted
    # replica restarting on the same model + bucket ladder then loads its
    # compiled programs from disk instead of recompiling them, which is
    # most of time_to_ready_s.
    from spotter_tpu.serving.lifecycle import maybe_enable_compile_cache

    maybe_enable_compile_cache()
    env_buckets = False
    if batch_buckets is None:
        # Per-model ladder tuning is a deployment concern: R18's per-chip
        # peak is batch 16 (485 vs 449 img/s — BASELINE.md round-4 sweep),
        # R101's is batch 8; the default stays the conservative 8-max.
        # `is not None` (not truthiness): an explicitly-set empty value is
        # a malformed spec and must raise, not silently serve the default.
        spec = os.environ.get("SPOTTER_TPU_BATCH_BUCKETS")
        env_buckets = spec is not None
        batch_buckets = (
            parse_batch_buckets(spec)
            if spec is not None
            else default_batch_buckets()
        )

    # Sharded serving (VERDICT r1 weak #5): SPOTTER_TPU_MESH=dp=4[,tp=2]
    # builds a mesh and the engine shards batches over "dp" / params over
    # "tp"; unset means the single-device path (one Serve replica per chip,
    # Ray pinning each replica via TPU_VISIBLE_CHIPS).
    mesh = None
    tp_rules = ()
    mesh_source = None
    mesh_spec = mesh_spec or os.environ.get(MESH_ENV)
    # dp×tp serving as a first-class config (ISSUES 3 + 13):
    # SPOTTER_TPU_SERVE_DP=<n|all> shards the batch over n chip GROUPS and
    # SPOTTER_TPU_SERVE_TP=<m> splits the params m-way inside each group.
    # Unlike the expert SPOTTER_TPU_MESH knob (which keeps the configured
    # ladder and merely rounds it up), the bucket ladder here stays per-
    # group semantics and is scaled by dp ONLY: the batcher fills
    # dp × per_chip_bucket before dispatch — tp splits weights, never the
    # batch, so each tp group keeps the batch the ladder was tuned for.
    serve_dp_set = serve_dp is not None or bool(
        os.environ.get(SERVE_DP_ENV, "").strip()
    )
    serve_tp_set = bool(os.environ.get(SERVE_TP_ENV, "").strip())
    if mesh_spec:
        mesh_source = MESH_ENV
        if serve_dp_set or serve_tp_set:
            # the knob conflict, loud instead of silent (ISSUE 13 satellite:
            # SERVE_DP previously just lost here with no trace)
            logger.warning(
                "%s=%r wins over %s/%s — the SERVE_* knobs are ignored while"
                " an explicit mesh spec is set; the resolved mesh is surfaced"
                " in /healthz",
                MESH_ENV, mesh_spec, SERVE_DP_ENV, SERVE_TP_ENV,
            )
    else:
        dp = serve_dp if serve_dp is not None else serve_dp_from_env()
        tp = serve_tp_from_env()
        if dp > 1 or tp > 1:
            batch_buckets = tuple(b * dp for b in batch_buckets)
            mesh_spec = f"dp={dp},tp={tp}"
            mesh_source = (
                f"{SERVE_DP_ENV} x {SERVE_TP_ENV}" if tp > 1 else SERVE_DP_ENV
            )
    if mesh_spec:
        from spotter_tpu.parallel import initialize_multihost, make_mesh

        # Multi-host bring-up belongs to the SPMD-mesh mode ONLY: exactly one
        # process per host may join jax.distributed, which is true when the
        # replica owns the whole host's chips via a mesh — and false in the
        # per-chip-replica mode, where N replicas per pod would all race to
        # register the same TPU_WORKER_ID. jax.distributed must be
        # initialized before any backend use, hence before make_mesh; the
        # single-host case is a no-op (multihost.py).
        initialize_multihost()

        axes = parse_mesh_spec(mesh_spec)
        if env_buckets and any(b % axes["dp"] for b in batch_buckets):
            # An OPERATOR-configured ladder that doesn't divide the dp axis
            # is a config contradiction: reject up front with both knobs
            # named (ISSUE 13 satellite) instead of silently rounding up.
            # Constructor-arg ladders (library/tests) keep the engine's
            # documented round-up semantics.
            raise ValueError(
                f"SPOTTER_TPU_BATCH_BUCKETS={list(batch_buckets)} not "
                f"divisible by dp={axes['dp']} (from "
                f"{mesh_source or MESH_ENV}): every bucket must split "
                f"evenly across the dp axis"
            )
        mesh = make_mesh(
            dp=axes["dp"], tp=axes["tp"], source=mesh_source or MESH_ENV
        )
        # Per-family TP rule set from the registry (ISSUE 13): tp=2 on an
        # OWL-ViT deployment shards the CLIP towers, RT-DETR its
        # encoder/decoder stacks; non-matching params fall back to
        # replicated, and a rule matching NOTHING fails loud in the engine
        # (sharding.check_rules_cover).
        tp_rules = family_for(model_name).tp_rules if axes["tp"] > 1 else ()

    built = build_detector(model_name)
    engine = InferenceEngine(
        built,
        threshold=threshold,
        batch_buckets=batch_buckets,
        mesh=mesh,
        tp_rules=tp_rules,
    )
    # /healthz surfaces which knob produced the serving mesh (satellite 2)
    engine.mesh_source = mesh_source
    if warmup:
        engine.warmup()
    # Resilience knobs (ISSUE 1) ride the environment into the batcher:
    # SPOTTER_TPU_QUEUE_DEPTH (bounded admission queue),
    # SPOTTER_TPU_BATCH_TIMEOUT_MS (hung-engine watchdog),
    # SPOTTER_TPU_BREAKER_THRESHOLD / _COOLDOWN_S (circuit breaker) are read
    # inside MicroBatcher/CircuitBreaker; SPOTTER_TPU_MAX_IN_FLIGHT is the
    # dispatch-depth knob that already existed as a constructor arg.
    max_in_flight = int(os.environ.get("SPOTTER_TPU_MAX_IN_FLIGHT", "2"))
    batcher = MicroBatcher(engine, max_delay_ms=max_delay_ms, max_in_flight=max_in_flight)
    # Caching tier (ISSUE 5): opt-in result cache + single-flight coalescing
    # in front of the engine. SPOTTER_TPU_CACHE_MAX_MB (or the explicit
    # `cache_mb` arg, i.e. --cache-mb) arms it; unset/0 constructs none of
    # the machinery — SPOTTER_TPU_CACHE_TTL_S / _CACHE_NEGATIVE_TTL_S bound
    # entry lifetimes when it is on.
    if cache_mb is None:
        return AmenitiesDetector(engine, batcher)
    from spotter_tpu.caching.result_cache import ResultCache

    cache = ResultCache.from_env(metrics=engine.metrics, max_mb=cache_mb)
    return AmenitiesDetector(engine, batcher, cache=cache)


def explain_sharding(
    model_name: str | None = None, mesh_spec: str | None = None
) -> str:
    """The `--explain-sharding` dump (ISSUE 13): build the model + the
    resolved serving mesh and report param path -> PartitionSpec ->
    per-device bytes, plus the dead-rule list. Read-only: no engine, no
    warmup, no compile — just the param tree and the rule set.
    """
    from spotter_tpu.parallel import make_mesh
    from spotter_tpu.parallel.sharding import (
        format_sharding_report,
        sharding_report,
    )

    model_name = model_name or os.environ.get("MODEL_NAME")
    if not model_name:
        raise ValueError("MODEL_NAME environment variable not set.")
    mesh_spec = mesh_spec or os.environ.get(MESH_ENV)
    if mesh_spec:
        axes = parse_mesh_spec(mesh_spec)
        source = MESH_ENV
    else:
        dp = serve_dp_from_env()
        tp = serve_tp_from_env()
        axes = {"dp": dp, "tp": tp}
        source = f"{SERVE_DP_ENV} x {SERVE_TP_ENV}"
    mesh = make_mesh(dp=axes["dp"], tp=axes["tp"], source=source)
    family = family_for(model_name)
    rules = family.tp_rules if axes["tp"] > 1 else ()
    built = build_detector(model_name)
    report = sharding_report(built.params, mesh, rules)
    header = (
        f"model {model_name} (family {family.name}), "
        f"{len(rules)} TP rule(s) active"
    )
    return header + "\n" + format_sharding_report(report)


def ray_deployment():
    """Ray Serve deployment graph node (the manifest's import_path target)."""
    from ray import serve
    from starlette.requests import Request

    @serve.deployment
    class RayAmenitiesDetector:
        def __init__(self, model_name: str) -> None:
            self._inner = build_detector_app(model_name, warmup=True)

        async def __call__(self, raw_payload: "Request"):
            return await self._inner.detect(await raw_payload.json())

    model_name = os.environ.get("MODEL_NAME")
    if not model_name:
        raise ValueError("MODEL_NAME environment variable not set.")
    return RayAmenitiesDetector.bind(model_name)


try:  # module-level `deployment` preserved for manifest import_path parity
    import ray  # noqa: F401
except ImportError:  # Ray not installed — standalone mode
    deployment = None
else:
    # With Ray present, real bootstrap errors (missing MODEL_NAME, model load
    # failure) must propagate like the reference's import-time raise
    # (serve.py:199-201), not turn into an opaque import_path=None deploy.
    deployment = ray_deployment()
