"""Serving bootstrap: MODEL_NAME env -> engine -> detector (+ Ray adapter).

Mirrors the reference's module-import bootstrap (serve.py:199-205): MODEL_NAME
is required and raises if unset; the built app object is what the RayService
manifest names as import_path (rayservice-template.yaml:8-9).

Ray Serve is optional in this build (it is the production fabric when
installed — reference pyproject.toml:11 — but the framework degrades to the
standalone aiohttp server, and tests never need Ray, matching the reference's
own practice of testing the undecorated class: test_serve.py:32).
"""

import os

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine, default_batch_buckets
from spotter_tpu.models import build_detector
from spotter_tpu.serving.detector import AmenitiesDetector

DETECTION_THRESHOLD = 0.5  # serve.py:107

SERVE_DP_ENV = "SPOTTER_TPU_SERVE_DP"


def serve_dp_from_env() -> int:
    """SPOTTER_TPU_SERVE_DP: data-parallel serving width (0/1/unset = one
    chip; `all` = every local chip). Malformed values fail loudly."""
    raw = os.environ.get(SERVE_DP_ENV, "").strip()
    if not raw:
        return 1
    if raw.lower() == "all":
        import jax

        return max(1, len(jax.local_devices()))
    if not raw.isdigit():
        raise ValueError(f"{SERVE_DP_ENV} must be a positive int or 'all', got {raw!r}")
    return max(1, int(raw))


def parse_mesh_spec(spec: str) -> dict[str, int]:
    """"dp=4" / "dp=4,tp=2" -> {"dp": 4, "tp": 2} (the SPOTTER_TPU_MESH knob)."""
    out = {"tp": 1}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        if key not in ("dp", "tp") or not value.isdigit() or int(value) < 1:
            raise ValueError(
                f"bad SPOTTER_TPU_MESH entry '{part}' (expected dp=<n>[,tp=<n>])"
            )
        out[key] = int(value)
    if "dp" not in out:
        raise ValueError(f"SPOTTER_TPU_MESH '{spec}' must set dp=<n>")
    return out


def parse_batch_buckets(spec: str) -> tuple[int, ...]:
    """SPOTTER_TPU_BATCH_BUCKETS: comma-separated ascending bucket ladder."""
    try:
        buckets = tuple(int(v) for v in spec.split(","))
    except ValueError:
        buckets = ()
    if not buckets or any(b < 1 for b in buckets) or list(buckets) != sorted(
        set(buckets)
    ):
        raise ValueError(
            f"SPOTTER_TPU_BATCH_BUCKETS must be ascending positive ints, "
            f"got {spec!r}"
        )
    return buckets


def build_detector_app(
    model_name: str | None = None,
    threshold: float = DETECTION_THRESHOLD,
    batch_buckets: tuple[int, ...] | None = None,
    max_delay_ms: float = 5.0,
    warmup: bool = False,
    mesh_spec: str | None = None,
    serve_dp: int | None = None,
    cache_mb: float | None = None,
) -> AmenitiesDetector:
    model_name = model_name or os.environ.get("MODEL_NAME")
    if not model_name:
        raise ValueError("MODEL_NAME environment variable not set.")
    # Warm restart (ISSUE 2): arm JAX's persistent compilation cache
    # (SPOTTER_TPU_COMPILE_CACHE_DIR) before the first jit — a preempted
    # replica restarting on the same model + bucket ladder then loads its
    # compiled programs from disk instead of recompiling them, which is
    # most of time_to_ready_s.
    from spotter_tpu.serving.lifecycle import maybe_enable_compile_cache

    maybe_enable_compile_cache()
    if batch_buckets is None:
        # Per-model ladder tuning is a deployment concern: R18's per-chip
        # peak is batch 16 (485 vs 449 img/s — BASELINE.md round-4 sweep),
        # R101's is batch 8; the default stays the conservative 8-max.
        # `is not None` (not truthiness): an explicitly-set empty value is
        # a malformed spec and must raise, not silently serve the default.
        spec = os.environ.get("SPOTTER_TPU_BATCH_BUCKETS")
        batch_buckets = (
            parse_batch_buckets(spec)
            if spec is not None
            else default_batch_buckets()
        )

    # Sharded serving (VERDICT r1 weak #5): SPOTTER_TPU_MESH=dp=4[,tp=2]
    # builds a mesh and the engine shards batches over "dp" / params over
    # "tp"; unset means the single-device path (one Serve replica per chip,
    # Ray pinning each replica via TPU_VISIBLE_CHIPS).
    mesh = None
    tp_rules = ()
    mesh_spec = mesh_spec or os.environ.get("SPOTTER_TPU_MESH")
    # dp-sharded serving as a first-class config (ISSUE 3):
    # SPOTTER_TPU_SERVE_DP=<n|all> shards the REAL serving path (engine +
    # batcher + HTTP) over n local chips. Unlike the expert SPOTTER_TPU_MESH
    # knob (which keeps the configured ladder and merely rounds it up), the
    # bucket ladder here stays per-chip semantics and is scaled to the
    # AGGREGATE: the batcher fills dp × per_chip_bucket before dispatch, so
    # each chip keeps the per-chip batch the ladder was tuned for. An
    # explicit SPOTTER_TPU_MESH wins when both are set.
    if not mesh_spec:
        dp = serve_dp if serve_dp is not None else serve_dp_from_env()
        if dp > 1:
            batch_buckets = tuple(b * dp for b in batch_buckets)
            mesh_spec = f"dp={dp}"
    if mesh_spec:
        from spotter_tpu.parallel import (
            RTDETR_TP_RULES,
            initialize_multihost,
            make_mesh,
        )

        # Multi-host bring-up belongs to the SPMD-mesh mode ONLY: exactly one
        # process per host may join jax.distributed, which is true when the
        # replica owns the whole host's chips via a mesh — and false in the
        # per-chip-replica mode, where N replicas per pod would all race to
        # register the same TPU_WORKER_ID. jax.distributed must be
        # initialized before any backend use, hence before make_mesh; the
        # single-host case is a no-op (multihost.py).
        initialize_multihost()

        axes = parse_mesh_spec(mesh_spec)
        mesh = make_mesh(dp=axes["dp"], tp=axes["tp"])
        # The TP rule set names the shared transformer projections
        # (models/layers.py: fc1/fc2, q/k/v/out_proj) used by every family;
        # non-matching params fall back to replicated (sharding.py).
        tp_rules = RTDETR_TP_RULES if axes["tp"] > 1 else ()

    built = build_detector(model_name)
    engine = InferenceEngine(
        built,
        threshold=threshold,
        batch_buckets=batch_buckets,
        mesh=mesh,
        tp_rules=tp_rules,
    )
    if warmup:
        engine.warmup()
    # Resilience knobs (ISSUE 1) ride the environment into the batcher:
    # SPOTTER_TPU_QUEUE_DEPTH (bounded admission queue),
    # SPOTTER_TPU_BATCH_TIMEOUT_MS (hung-engine watchdog),
    # SPOTTER_TPU_BREAKER_THRESHOLD / _COOLDOWN_S (circuit breaker) are read
    # inside MicroBatcher/CircuitBreaker; SPOTTER_TPU_MAX_IN_FLIGHT is the
    # dispatch-depth knob that already existed as a constructor arg.
    max_in_flight = int(os.environ.get("SPOTTER_TPU_MAX_IN_FLIGHT", "2"))
    batcher = MicroBatcher(engine, max_delay_ms=max_delay_ms, max_in_flight=max_in_flight)
    # Caching tier (ISSUE 5): opt-in result cache + single-flight coalescing
    # in front of the engine. SPOTTER_TPU_CACHE_MAX_MB (or the explicit
    # `cache_mb` arg, i.e. --cache-mb) arms it; unset/0 constructs none of
    # the machinery — SPOTTER_TPU_CACHE_TTL_S / _CACHE_NEGATIVE_TTL_S bound
    # entry lifetimes when it is on.
    if cache_mb is None:
        return AmenitiesDetector(engine, batcher)
    from spotter_tpu.caching.result_cache import ResultCache

    cache = ResultCache.from_env(metrics=engine.metrics, max_mb=cache_mb)
    return AmenitiesDetector(engine, batcher, cache=cache)


def ray_deployment():
    """Ray Serve deployment graph node (the manifest's import_path target)."""
    from ray import serve
    from starlette.requests import Request

    @serve.deployment
    class RayAmenitiesDetector:
        def __init__(self, model_name: str) -> None:
            self._inner = build_detector_app(model_name, warmup=True)

        async def __call__(self, raw_payload: "Request"):
            return await self._inner.detect(await raw_payload.json())

    model_name = os.environ.get("MODEL_NAME")
    if not model_name:
        raise ValueError("MODEL_NAME environment variable not set.")
    return RayAmenitiesDetector.bind(model_name)


try:  # module-level `deployment` preserved for manifest import_path parity
    import ray  # noqa: F401
except ImportError:  # Ray not installed — standalone mode
    deployment = None
else:
    # With Ray present, real bootstrap errors (missing MODEL_NAME, model load
    # failure) must propagate like the reference's import-time raise
    # (serve.py:199-201), not turn into an opaque import_path=None deploy.
    deployment = ray_deployment()
