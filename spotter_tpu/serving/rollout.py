"""Safe deployment plane (ISSUE 15): versioned canary rollouts with
SLO-burn auto-rollback, shadow traffic, and wave-by-wave member
replacement under live traffic.

Every robustness tier so far hardens the fleet against ENVIRONMENTAL
failure — preemption (ISSUE 2/6), overload (ISSUE 8), gray replicas
(ISSUE 14). The leading cause of real outages at fleet scale is none of
those: it is a BAD DEPLOY, and until now a new build replaced every
replica at once with a human as the only rollback path. DeepServe
(PAPERS.md) treats deployment as a first-class automated fleet-lifecycle
operation; this module is that operation for the spotter fleet:

- **Waves**: `RolloutController.run()` replaces the fleet one member per
  wave. Each wave spawns ONE new-version replica (through the caller's
  spawner — the supervisor + persistent compile cache from ISSUE 2 make
  it a warm bring-up), adds it to the live `ReplicaPool` and HOLDS it at
  `SPOTTER_TPU_ROLLOUT_CANARY_WEIGHT` (default 5%) via the pool's
  pinned-weight machinery (the ISSUE 14 smooth-weighted-RR + affinity
  credit thinning, driven by deployment intent instead of a gray score).
- **Verdict**: after a verdict window of live evidence the canary is
  judged on the ISSUE 12 fleet-telemetry signals — per-replica error
  rate (pool transport/5xx failures + shadow-lane errors), p99 vs the
  BASELINE COHORT's median p99 (the aggregator's per-member snapshots),
  and the canary's fast-window `slo_burn_rate` (ISSUE 10) — plus the
  shadow lane's detection-diff rate. A failing signal rolls back EARLY
  (mid-window, as soon as minimum evidence exists); a clean window
  promotes: the canary goes to full weight and one old-version member is
  drained (`POST /drain {"deadline_ms": ...}` — the ISSUE 15 precise
  drain) and retired. Wave 1 runs the full window; later waves run a
  shorter confirmation window — the canary wave already proved the build.
- **Auto-rollback**: on any failed verdict the canary is removed from the
  pool FIRST (no new traffic), drained, and shut down; remaining members'
  weights are restored; the rollout FREEZES in `rolled_back` (promoted
  waves are not un-done — a frozen mixed fleet is an operator decision,
  not an automated flap). The rollback pins a flight-recorder trace
  (`/debug/traces`, request id `rollout-rollback-*`) and bumps
  `rollouts_total{verdict="rolled_back"}`; zero client-visible failures
  is the contract the deployment chaos drills
  (`testing/chaos_matrix.py::DEPLOY_MATRIX`, `bench.py --rollout-drill`)
  enforce.
- **Shadow lane**: with `SPOTTER_TPU_SHADOW_PCT` > 0 the router mirrors a
  deterministically-sampled share of live requests to the canary
  (fire-and-forget, responses DISCARDED — never client-visible) and
  counts the detection-diff rate against the primary's answer. Shadow
  evidence feeds the verdict without exposing clients to the canary at
  all, so even a 0%-weight canary can be judged.

Version identity threads the whole stack: `SPOTTER_TPU_BUILD_VERSION` and
the weights digest live in the ISSUE 12 identity block (/metrics,
/healthz) and the `X-Spotter-Version` response header; the pool learns
per-replica versions from that header and PINS a request's replays and
hedges within one version during the mixed-version window
(replica_pool.py), so deploy skew can never double-process a request
across incompatible builds.
"""

import asyncio
import inspect
import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Callable, Optional

from spotter_tpu.obs import compare
from spotter_tpu.obs import http as obs_http
from spotter_tpu.serving.replica_pool import ReplicaPool

logger = logging.getLogger(__name__)

# rollout states
IDLE = "idle"
SPAWNING = "spawning"
CANARY = "canary"
PROMOTING = "promoting"
ROLLING_BACK = "rolling_back"
ROLLED_BACK = "rolled_back"  # terminal: frozen, operator owns the next move
DONE = "done"  # terminal: every member serves the new version

CANARY_WEIGHT_ENV = "SPOTTER_TPU_ROLLOUT_CANARY_WEIGHT"
WINDOW_ENV = "SPOTTER_TPU_ROLLOUT_WINDOW_S"
CONFIRM_WINDOW_ENV = "SPOTTER_TPU_ROLLOUT_CONFIRM_S"
MIN_REQUESTS_ENV = "SPOTTER_TPU_ROLLOUT_MIN_REQUESTS"
MAX_ERROR_RATE_ENV = "SPOTTER_TPU_ROLLOUT_MAX_ERROR_RATE"
P99_RATIO_ENV = "SPOTTER_TPU_ROLLOUT_P99_RATIO"
BURN_LIMIT_ENV = "SPOTTER_TPU_ROLLOUT_BURN_LIMIT"
SHADOW_PCT_ENV = "SPOTTER_TPU_SHADOW_PCT"
SHADOW_DIFF_RATE_ENV = "SPOTTER_TPU_ROLLOUT_SHADOW_DIFF_RATE"
DRAIN_MS_ENV = "SPOTTER_TPU_ROLLOUT_DRAIN_MS"
SPAWN_WAIT_ENV = "SPOTTER_TPU_ROLLOUT_SPAWN_WAIT_S"

DEFAULT_CANARY_WEIGHT = 0.05
DEFAULT_WINDOW_S = 30.0
DEFAULT_MIN_REQUESTS = 20
DEFAULT_MAX_ERROR_RATE = 0.02
DEFAULT_P99_RATIO = 2.0
DEFAULT_BURN_LIMIT = 2.0
DEFAULT_SHADOW_PCT = 0.0
DEFAULT_SHADOW_DIFF_RATE = 0.02
DEFAULT_DRAIN_MS = 5000.0
DEFAULT_SPAWN_WAIT_S = 60.0
# the latency signal needs this many canary-served requests before its
# quantiles mean anything (below it, one sample IS the tail)
LATENCY_MIN_SERVED = 8
# a hard cap on waiting for verdict evidence: past this multiple of the
# window an idle fleet simply has no signal, and "no evidence of badness"
# promotes (the canary stays observable at full weight; the alternative —
# rolling back every deploy on a quiet fleet — would make rollouts
# impossible exactly when they are safest)
EVIDENCE_WAIT_FACTOR = 3.0

SHADOW_HEADER = "X-Spotter-Shadow"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


@dataclass
class RolloutMember:
    """One fleet member the rollout knows about: `handle` is whatever the
    spawner returned (must expose `.url`; `shutdown()` may be sync or
    async) or None for members someone else manages (static endpoints —
    retire then only removes them from the pool and drains them)."""

    url: str
    handle: object = None
    version: str = ""


async def _shutdown_handle(handle) -> None:
    """Run a member handle's shutdown, whichever color its function is:
    in-process harness members are async (closing an aiohttp TestServer),
    subprocess members (testing/cluster.py) block on process exit."""
    if handle is None:
        return
    fn = getattr(handle, "shutdown", None)
    if fn is None:
        return
    if inspect.iscoroutinefunction(fn):
        await fn()
        return
    res = await asyncio.get_running_loop().run_in_executor(None, fn)
    if inspect.isawaitable(res):  # defensive: sync fn returning a coroutine
        await res


# The detection-diff definition moved to obs/compare.py (ISSUE 17) so the
# shadow verdict and the router's integrity quorum sampler judge "same
# answer" identically; re-exported under the old name for existing callers.
_norm_detections = compare.norm_detections


class ShadowLane:
    """Mirror a sampled share of live traffic to the canary and count the
    detection-diff rate. Deterministic Bresenham sampling (no RNG — the
    drills assert exact shares), responses discarded, every failure
    contained: nothing on this lane can ever surface to a client."""

    def __init__(self, pct: Optional[float] = None) -> None:
        if pct is None:
            pct = _env_float(SHADOW_PCT_ENV, DEFAULT_SHADOW_PCT)
        self.pct = min(max(float(pct), 0.0), 100.0)
        self._credit = 0.0
        self.requests_total = 0
        self.errors_total = 0
        self.compared_total = 0
        self.diffs_total = 0

    def take(self) -> bool:
        if self.pct <= 0:
            return False
        self._credit += self.pct
        if self._credit >= 100.0:
            self._credit -= 100.0
            return True
        return False

    async def run_one(
        self, client, canary_url: str, payload: dict, primary_body
    ) -> None:
        """One mirrored request: POST the canary, compare detections
        against the primary's already-serialized JSON body."""
        self.requests_total += 1
        try:
            resp = await client.post(
                f"{canary_url}/detect",
                json=payload,
                headers={SHADOW_HEADER: "1"},
            )
            if resp.status_code != 200:
                self.errors_total += 1
                return
            canary = resp.json()
        except Exception:
            self.errors_total += 1
            return
        try:
            primary = (
                json.loads(primary_body)
                if isinstance(primary_body, (bytes, bytearray, str))
                else primary_body
            )
            self.compared_total += 1
            if _norm_detections(primary.get("images")) != _norm_detections(
                canary.get("images")
            ):
                self.diffs_total += 1
        except Exception:
            # an uncomparable primary (frame body, unexpected shape) is a
            # skipped comparison, never an error charged to the canary
            self.compared_total = max(self.compared_total - 1, 0)

    def snapshot(self) -> dict:
        return {
            "pct": self.pct,
            "requests_total": self.requests_total,
            "errors_total": self.errors_total,
            "compared_total": self.compared_total,
            "diffs_total": self.diffs_total,
            "diff_rate": (
                self.diffs_total / self.compared_total
                if self.compared_total
                else 0.0
            ),
        }


def resume_plan(record, now: Optional[float] = None) -> Optional[dict]:
    """What a restarted controller should do about a journaled rollout
    (ISSUE 16): None when there is nothing in flight (no record, or a
    terminal state); otherwise a directive dict:

    - `{"action": "resume", ...}` — the crash landed inside a live canary
      window: re-adopt the canary at `canary_url` and serve out the
      REMAINING `window_s`;
    - `{"action": "rollback", ...}` — the canary window expired while no
      controller was alive to judge it (the canary carried live weight
      unwatched), so the only safe move is rollback;
    - `{"action": "restart_wave", ...}` — the crash landed between waves
      (spawning/promoting): start the wave over; orphan adoption has
      already reclaimed any half-spawned canary via the manifest.

    Wall-clock (`time.time`) on purpose: the journal outlives the process
    whose monotonic clock stamped it."""
    if not isinstance(record, dict):
        return None
    state = record.get("state")
    if state not in (SPAWNING, CANARY, PROMOTING):
        return None
    now = time.time() if now is None else now
    plan = {
        "wave": int(record.get("wave") or 0),
        "version_to": record.get("version_to") or "",
        "version_from": record.get("version_from") or "",
        "canary_url": record.get("canary_url"),
        "old_urls": list(record.get("old_urls") or []),
    }
    if state == CANARY and record.get("canary_url"):
        remaining = float(record.get("window_deadline") or 0.0) - now
        if remaining <= 0:
            plan["action"] = "rollback"
            plan["reason"] = "verdict_window_expired"
        else:
            plan["action"] = "resume"
            plan["window_s"] = remaining
        return plan
    plan["action"] = "restart_wave"
    plan["canary_url"] = None  # not yet serving at weight; respawn/adopt
    return plan


class RolloutController:
    """Wave-by-wave versioned rollout over a live `ReplicaPool`.

    The controller OWNS the deployment lifecycle but not the fleet: the
    pool keeps routing, health-checking, ejecting and replaying exactly as
    before; the controller only adds/weights/retires members and renders
    verdicts. `await run()` drives the whole rollout to a terminal state
    (`done` or `rolled_back`); `start()` wraps it in a background task for
    server wiring. Everything is event-loop-confined."""

    def __init__(
        self,
        pool: ReplicaPool,
        members: list,
        spawner: Callable[[], object],
        version_to: str,
        version_from: str = "",
        aggregator=None,
        canary_weight: Optional[float] = None,
        window_s: Optional[float] = None,
        confirm_window_s: Optional[float] = None,
        min_requests: Optional[int] = None,
        max_error_rate: Optional[float] = None,
        p99_ratio: Optional[float] = None,
        burn_limit: Optional[float] = None,
        shadow_pct: Optional[float] = None,
        shadow_diff_rate: Optional[float] = None,
        drain_deadline_ms: Optional[float] = None,
        spawn_wait_s: Optional[float] = None,
        tick_s: float = 0.1,
        store=None,
        resume: Optional[dict] = None,
        resume_handle=None,
    ) -> None:
        self.pool = pool
        self.old_members = [
            m if isinstance(m, RolloutMember) else (
                RolloutMember(url=m) if isinstance(m, str)
                else RolloutMember(url=m.url, handle=m)
            )
            for m in members
        ]
        self.new_members: list[RolloutMember] = []
        self.spawner = spawner
        self.version_to = version_to
        self.version_from = version_from
        self.aggregator = aggregator
        self.canary_weight = (
            canary_weight
            if canary_weight is not None
            else _env_float(CANARY_WEIGHT_ENV, DEFAULT_CANARY_WEIGHT)
        )
        self.window_s = (
            window_s if window_s is not None
            else _env_float(WINDOW_ENV, DEFAULT_WINDOW_S)
        )
        self.confirm_window_s = (
            confirm_window_s
            if confirm_window_s is not None
            else _env_float(CONFIRM_WINDOW_ENV, self.window_s / 3.0)
        )
        self.min_requests = (
            min_requests
            if min_requests is not None
            else _env_int(MIN_REQUESTS_ENV, DEFAULT_MIN_REQUESTS)
        )
        self.max_error_rate = (
            max_error_rate
            if max_error_rate is not None
            else _env_float(MAX_ERROR_RATE_ENV, DEFAULT_MAX_ERROR_RATE)
        )
        self.p99_ratio = (
            p99_ratio if p99_ratio is not None
            else _env_float(P99_RATIO_ENV, DEFAULT_P99_RATIO)
        )
        self.burn_limit = (
            burn_limit if burn_limit is not None
            else _env_float(BURN_LIMIT_ENV, DEFAULT_BURN_LIMIT)
        )
        self.shadow = ShadowLane(shadow_pct)
        self.shadow_diff_rate = (
            shadow_diff_rate
            if shadow_diff_rate is not None
            else _env_float(SHADOW_DIFF_RATE_ENV, DEFAULT_SHADOW_DIFF_RATE)
        )
        self.drain_deadline_ms = (
            drain_deadline_ms
            if drain_deadline_ms is not None
            else _env_float(DRAIN_MS_ENV, DEFAULT_DRAIN_MS)
        )
        self.spawn_wait_s = (
            spawn_wait_s
            if spawn_wait_s is not None
            else _env_float(SPAWN_WAIT_ENV, DEFAULT_SPAWN_WAIT_S)
        )
        self.tick_s = tick_s
        # durable intent (ISSUE 16): every wave transition is journaled to
        # the statestore BEFORE the fleet mutation it describes, so a
        # controller killed mid-wave leaves enough recorded state for its
        # successor to resume the wave (or roll back an expired one) —
        # `resume` is that successor's directive (see `resume_plan`), and
        # `resume_handle` re-attaches the orphaned canary's member handle
        # (a reconcile.ManifestHandle) so retire/shutdown still work.
        self.store = store
        self._resume = resume
        self._resume_handle = resume_handle
        # state
        self.state = IDLE
        self.wave = int(resume.get("wave") or 0) if resume else 0
        self.canary: Optional[RolloutMember] = None
        self.canary_since: Optional[float] = None
        self.rollback_reason: Optional[str] = None
        self.last_verdict: Optional[dict] = None
        self.rollback_s: Optional[float] = None
        self.verdict_window_s_used: Optional[float] = None
        # counters (the acceptance surface: rollouts_total{verdict})
        self.rollouts_total = {"promoted": 0, "rolled_back": 0}
        self.waves_promoted_total = 0
        self._task: Optional[asyncio.Task] = None
        self._shadow_tasks: set[asyncio.Task] = set()

    # ---- server wiring ----

    def start(self) -> asyncio.Task:
        if self._task is None:
            self._task = asyncio.create_task(self.run())
        return self._task

    async def stop(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await self._drain_shadow_tasks()

    def maybe_shadow(self, payload: dict, primary_body) -> None:
        """Router hook: mirror this (already-served) request to the canary
        on the sampled lane. Synchronous and O(1) on the decline path —
        the idle-rollout hot-path cost is one state check."""
        if self.state != CANARY or self.canary is None:
            return
        if not self.shadow.take():
            return
        task = asyncio.create_task(
            self.shadow.run_one(
                self.pool.client, self.canary.url, payload, primary_body
            )
        )
        self._shadow_tasks.add(task)
        task.add_done_callback(self._shadow_tasks.discard)

    async def _drain_shadow_tasks(self) -> None:
        if self._shadow_tasks:
            await asyncio.gather(
                *list(self._shadow_tasks), return_exceptions=True
            )

    # ---- the rollout ----

    async def run(self) -> str:
        """Drive the rollout to a terminal state; returns it ("done" /
        "rolled_back"). One wave per old member; the first wave is the
        canary wave (full verdict window), later waves confirm on the
        shorter window."""
        if self._resume is not None and self._resume.get("expired"):
            # crashed mid-window and the verdict window expired while no
            # controller was alive to judge it: the canary got live weight
            # with nobody watching, so the ONLY safe resume is rollback
            url = self._resume.get("canary_url")
            if url:
                self.canary = RolloutMember(
                    url=url, handle=self._resume_handle,
                    version=self.version_to,
                )
                if self.pool.replica_for(url) is None:
                    self.pool.add_endpoint(url, healthy=False)
            await self._rollback("verdict_window_expired")
            return self.state
        if not self.old_members and not (
            self._resume and self._resume.get("canary_url")
        ):
            self.state = DONE
            self._journal(DONE)
            return self.state
        logger.info(
            "rollout %s -> %s: %d members, canary weight %.0f%%, "
            "window %.1f s",
            self.version_from or "?", self.version_to,
            len(self.old_members), self.canary_weight * 100, self.window_s,
        )
        try:
            first = True
            while self.old_members or (
                first and self._resume and self._resume.get("canary_url")
            ):
                resume_url = None
                window = (
                    self.window_s if self.wave == 0 else self.confirm_window_s
                )
                if first and self._resume is not None:
                    resume_url = self._resume.get("canary_url")
                    if resume_url and self._resume.get("window_s"):
                        # serve out the REMAINDER of the journaled window,
                        # not a fresh one — the dead controller's clock
                        # still binds its successor
                        window = float(self._resume["window_s"])
                first = False
                ok, reason = await self._one_wave(window, resume_url=resume_url)
                if not ok:
                    await self._rollback(reason)
                    return self.state
                self.wave += 1
                self.waves_promoted_total += 1
            self.state = DONE
            self._journal(DONE)
            self.rollouts_total["promoted"] += 1
            logger.info(
                "rollout to %s complete: %d waves promoted",
                self.version_to, self.wave,
            )
            return self.state
        finally:
            await self._drain_shadow_tasks()

    async def _one_wave(
        self, window_s: float, resume_url: Optional[str] = None
    ) -> tuple[bool, str]:
        self.state = SPAWNING
        if resume_url is None:
            self._journal(SPAWNING)
            handle = self.spawner()
            if inspect.isawaitable(handle):
                handle = await handle
            url = handle.url.rstrip("/")
            version = getattr(handle, "version", "") or self.version_to
        else:
            # resuming a journaled wave (ISSUE 16): the canary is already
            # running (adopted from the endpoints manifest) — re-attach it
            # instead of spawning a sibling
            url = resume_url.rstrip("/")
            handle = self._resume_handle
            version = self.version_to
        self.canary = RolloutMember(url=url, handle=handle, version=version)
        if self.pool.replica_for(url) is None:
            self.pool.add_endpoint(url, healthy=False)
        self.pool.set_version(url, version)
        self.pool.set_weight(url, self.canary_weight)
        # wait for the health loop to promote the new member
        deadline = time.monotonic() + self.spawn_wait_s
        while True:
            r = self.pool.replica_for(url)
            if r is not None and r.available(time.monotonic()):
                break
            if time.monotonic() > deadline:
                return False, "spawn_timeout"
            await asyncio.sleep(self.tick_s)
        self.state = CANARY
        self.canary_since = time.monotonic()
        self.verdict_window_s_used = window_s
        # journal the canary phase with a WALL-CLOCK window deadline: a
        # successor controller (new process, new monotonic epoch) must be
        # able to decide "is this window still live" from the record alone
        self._journal(CANARY, window_s=window_s,
                      window_deadline=time.time() + window_s)
        r = self.pool.replica_for(url)
        base = {
            "requests": r.requests,
            "failures": r.failures,
            "shadow_requests": self.shadow.requests_total,
            "shadow_errors": self.shadow.errors_total,
            "shadow_compared": self.shadow.compared_total,
            "shadow_diffs": self.shadow.diffs_total,
        }
        hard_deadline = (
            self.canary_since + window_s * EVIDENCE_WAIT_FACTOR
        )
        window_end = self.canary_since + window_s
        while True:
            await asyncio.sleep(self.tick_s)
            now = time.monotonic()
            verdict = self._verdict(base)
            self.last_verdict = verdict
            enough = verdict["evidence"] >= self.min_requests
            if enough and not verdict["ok"]:
                # fail fast: a bad deploy must not get the window's full
                # courtesy — rollback starts the moment the evidence bar
                # and a failing signal coincide
                return False, verdict["reason"]
            if (now >= window_end and enough) or now >= hard_deadline:
                # window served (or evidence never arrived on an idle
                # fleet, where no signal of badness promotes — see
                # EVIDENCE_WAIT_FACTOR)
                if verdict["ok"]:
                    await self._promote()
                return verdict["ok"], verdict.get("reason") or ""

    def _member_snapshot(self, url: str) -> Optional[dict]:
        if self.aggregator is None:
            return None
        try:
            return self.aggregator.member_snapshot(url)
        except Exception:
            return None

    def _verdict(self, base: dict) -> dict:
        """Render the canary verdict from the live signals. `ok=False`
        carries the FIRST failing signal as `reason` (error_rate beats
        latency beats burn beats shadow-diff — ordered by how direct the
        client harm is)."""
        assert self.canary is not None
        r = self.pool.replica_for(self.canary.url)
        attempts = (r.requests - base["requests"]) if r is not None else 0
        failures = (r.failures - base["failures"]) if r is not None else 0
        shadow_req = self.shadow.requests_total - base["shadow_requests"]
        shadow_err = self.shadow.errors_total - base["shadow_errors"]
        shadow_cmp = self.shadow.compared_total - base["shadow_compared"]
        shadow_diff = self.shadow.diffs_total - base["shadow_diffs"]
        evidence = attempts + shadow_req
        bad = failures + shadow_err
        error_rate = bad / evidence if evidence else 0.0

        canary_snap = self._member_snapshot(self.canary.url) or {}
        canary_p99 = float(canary_snap.get("latency_ms_p99") or 0.0)
        # the canary SIDE of the latency signal is its p90: early in the
        # window the canary has served tens of requests, where p99 IS the
        # single worst sample — one cold-start hiccup would roll back a
        # healthy build. A genuinely slow deploy moves every percentile
        # (10x service time moves p90 exactly as far as p99), so p90 keeps
        # the detection and drops the single-sample noise.
        canary_p90 = float(
            canary_snap.get("latency_ms_p90") or canary_p99 or 0.0
        )
        baseline_p99s = sorted(
            p
            for m in self.old_members + self.new_members
            for p in [
                float(
                    (self._member_snapshot(m.url) or {}).get(
                        "latency_ms_p99"
                    )
                    or 0.0
                )
            ]
            if p > 0.0
        )
        baseline_p99 = (
            baseline_p99s[len(baseline_p99s) // 2] if baseline_p99s else 0.0
        )
        burn = canary_snap.get("slo_burn_rate") or {}
        burn_fast = float(burn.get("fast") or 0.0)
        diff_rate = shadow_diff / shadow_cmp if shadow_cmp else 0.0

        # requests the canary actually SERVED (pool-routed + shadow): the
        # aggregator's canary quantiles cover both, so a 0%-weight canary
        # judged purely on shadow traffic still has a latency signal
        served = attempts + shadow_cmp
        reason = None
        if bad >= 2 and error_rate >= self.max_error_rate:
            reason = "error_rate"
        elif (
            canary_p90 > 0.0
            and baseline_p99 > 0.0
            and served >= LATENCY_MIN_SERVED
            and canary_p90 >= self.p99_ratio * baseline_p99
        ):
            reason = "p99_vs_baseline"
        elif burn_fast >= self.burn_limit:
            reason = "slo_burn"
        elif shadow_diff >= 2 and diff_rate >= self.shadow_diff_rate:
            reason = "shadow_diff"
        return {
            "ok": reason is None,
            "reason": reason,
            "evidence": evidence,
            "attempts": attempts,
            "failures": failures,
            "error_rate": round(error_rate, 4),
            "canary_p90_ms": round(canary_p90, 3),
            "canary_p99_ms": round(canary_p99, 3),
            "baseline_p99_ms": round(baseline_p99, 3),
            "slo_burn_fast": round(burn_fast, 4),
            "shadow_compared": shadow_cmp,
            "shadow_diffs": shadow_diff,
            "shadow_diff_rate": round(diff_rate, 4),
        }

    async def _drain_member(self, url: str) -> Optional[dict]:
        """POST /drain with the precise deadline (ISSUE 15 satellite);
        best-effort — a member that cannot drain still gets shut down."""
        headers = {}
        token = os.environ.get(obs_http.ADMIN_TOKEN_ENV, "")
        if token:
            headers[obs_http.ADMIN_TOKEN_HEADER] = token
        try:
            resp = await self.pool.client.post(
                f"{url}/drain",
                json={"deadline_ms": self.drain_deadline_ms},
                headers=headers,
            )
            summary = resp.json() if resp.status_code == 200 else None
            if summary is not None and summary.get("in_flight"):
                logger.warning(
                    "drain of %s timed out with %s batches in flight",
                    url, summary["in_flight"],
                )
            return summary
        except Exception:
            logger.warning("draining %s failed", url, exc_info=True)
            return None

    async def _retire(self, member: RolloutMember) -> None:
        """Retire a member under traffic, client-invisibly: out of the
        pool first (no new picks; in-flight replays still mask), drain
        what it holds, then shut the process down."""
        self.pool.remove_endpoint(member.url)
        await self._drain_member(member.url)
        try:
            await _shutdown_handle(member.handle)
        except Exception:
            logger.exception("shutting down %s failed", member.url)

    async def _promote(self) -> None:
        assert self.canary is not None
        self.state = PROMOTING
        self.pool.set_weight(self.canary.url, None)  # full weight
        # a resumed final wave can arrive with the retired cohort already
        # empty (the predecessor promoted it before dying) — promote the
        # canary, nothing left to retire
        old = self.old_members.pop(0) if self.old_members else None
        logger.info(
            "rollout wave %d promoted: %s (%s) in, retiring %s",
            self.wave, self.canary.url, self.canary.version,
            old.url if old else "(nothing)",
        )
        self._journal(PROMOTING, promoted_url=self.canary.url)
        if old is not None:
            await self._retire(old)
        self.new_members.append(self.canary)
        self.canary = None

    async def _rollback(self, reason: str) -> None:
        self.state = ROLLING_BACK
        self.rollback_reason = reason
        t0 = time.monotonic()
        logger.warning(
            "rollout to %s ROLLING BACK at wave %d: %s (verdict %s)",
            self.version_to, self.wave, reason, self.last_verdict,
        )
        if self.canary is not None:
            await self._retire(self.canary)
            self.canary = None
        # restore weights: nothing but the (now removed) canary is pinned,
        # but clear defensively so a frozen fleet routes at full weight
        for r in self.pool.replicas:
            r.pinned_weight = None
        self.rollback_s = time.monotonic() - t0
        self.state = ROLLED_BACK
        self._journal(ROLLED_BACK, reason=reason)
        self.rollouts_total["rolled_back"] += 1
        self._pin_rollback_trace(reason)

    def _journal(self, state: str, **extra) -> None:
        """Record this transition in the durable statestore (ISSUE 16).
        Best-effort by policy: a full state disk must degrade the rollout
        to the pre-journal (memory-only) behavior, not abort a promotion
        mid-flight — the chaos matrix covers the crash/resume paths where
        the journal DID land."""
        if self.store is None:
            return
        record = {
            "state": state,
            "wave": self.wave,
            "version_to": self.version_to,
            "version_from": self.version_from,
            "canary_weight": self.canary_weight,
            "canary_url": self.canary.url if self.canary else None,
            "old_urls": [m.url for m in self.old_members],
        }
        record.update(extra)
        try:
            self.store.set_rollout(record)
        except Exception:
            logger.exception("journaling rollout state %r failed", state)

    def _pin_rollback_trace(self, reason: str) -> None:
        """Pin a synthetic flight-recorder trace (the brownout pattern):
        /debug/traces answers 'when did the deploy roll back, and why'
        without scraping logs. Best effort, never fails the rollback."""
        try:
            from spotter_tpu import obs

            recorder = obs.get_recorder()
            if not recorder.enabled:
                return
            trace = obs.begin_trace(
                request_id=(
                    f"rollout-rollback-wave{self.wave}-{self.version_to}"
                )
            )
            trace.set_error(
                "rollout_rollback",
                f"{self.version_from or '?'} -> {self.version_to} "
                f"wave {self.wave}: {reason} ({self.last_verdict})",
            )
            recorder.record(trace)
        except Exception:
            logger.exception("pinning rollback trace failed")

    # ---- observability ----

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "version_from": self.version_from,
            "version_to": self.version_to,
            "wave": self.wave,
            "members_remaining": len(self.old_members),
            "members_promoted": len(self.new_members),
            "canary_url": self.canary.url if self.canary else None,
            "canary_weight": self.canary_weight,
            "window_s": self.window_s,
            "verdict_window_s": self.verdict_window_s_used,
            "rollouts_total": dict(self.rollouts_total),
            "waves_promoted_total": self.waves_promoted_total,
            "rollback_reason": self.rollback_reason,
            "rollback_s": (
                round(self.rollback_s, 3)
                if self.rollback_s is not None
                else None
            ),
            "last_verdict": self.last_verdict,
            "shadow": self.shadow.snapshot(),
        }
