"""Tenant identity + isolation plane (ISSUE 19).

Every robustness tier so far defends against failures of the SYSTEM; this
module defends against failures of the NEIGHBORS: one flooding or
retry-storming client must not be able to consume the batcher queue, the
AIMD admission limit, or the SLO budget that every other client shares.
DeepServe (arXiv:2501.14417) makes per-tenant fairness a first-class
property of serving at scale; "Answer Fast" grounds the framing — an
in-quota tenant's p99 must be invariant to what other tenants do.

Four pieces, stdlib-only (edges and the supervisor import through here):

- **Identity** (`TenantPlane.resolve`): the `X-Spotter-Tenant` header
  names a tenant but is NEVER trusted bare — any client can type any
  header, and a spoofed id would let an abuser impersonate a high-quota
  tenant, poison a victim's SLO/occupancy accounting, or dodge its own
  bucket by rotating fresh ids. The header is honored only when (a) the
  request carries the edge-attestation token (`X-Spotter-Edge-Token`
  matching `SPOTTER_TPU_TENANT_EDGE_SECRET` — edges stamp it on
  forwarded requests via `stamp()`, so edge->replica propagation is
  attested), (b) it matches the tenant the API-key map resolves
  (`SPOTTER_TPU_TENANT_KEYS`, a JSON file of api-key -> tenant, checked
  against `X-API-Key`), or (c) `SPOTTER_TPU_TENANT_TRUST_HEADER=1`
  explicitly opts a deployment in (header attested upstream: mTLS
  ingress, service mesh). Otherwise identity falls back to the API-key
  map alone, else `"anon"` — every unauthenticated client shares ONE
  bucket, so inventing ids gains nothing. Edges re-stamp the RESOLVED
  id (plus the attestation token) into the forwarded header alongside
  `X-Request-ID` so the replica, its QueueItem, and its traces all
  agree on who a request belongs to.
- **Token-bucket quotas** (`TokenBucket`, `TenantPlane.try_admit`):
  per-tenant rate + burst from `SPOTTER_TPU_TENANT_CONFIG` (a path to —
  or inline — JSON; see below) with `SPOTTER_TPU_TENANT_RPS_DEFAULT` as
  the fallback rate. Over-quota requests shed 429 with a TENANT-scoped
  jittered Retry-After BEFORE any fetch/decode work, strictly before any
  in-quota request is shed. A per-tenant concurrent-inflight cap bounds
  slow-loris occupancy the rate bucket can't see.
- **Fair scheduling** (`TenantPlane.drr_order`): deficit-weighted
  round-robin across active tenants for the scheduler's within-class
  ordering — a flooding tenant queues behind its own backlog, not the
  fleet's. Fairness is PER CALL: each plan() round reorders the whole
  pending backlog it was handed, which is the window that matters.
  With one distinct tenant (or the plane unconfigured) the input order
  is returned UNCHANGED: FIFO semantics stay bit-identical, the same
  opt-out discipline as the RAGGED/ADMIT knobs.
- **Per-tenant accounting** (`record_outcome`, `metrics_view`,
  `snapshot`): admit/shed/occupancy counters + an `SloBurn` per tenant.
  `/metrics` exposure is BOUNDED: top-K tenants by admits
  (`SPOTTER_TPU_TENANT_TOP_K`, default 8) plus an `other` overflow
  bucket, so prom label cardinality can't explode however many tenant
  ids a flood invents. `/debug/tenants` (admin-gated) serves the full
  table.

Config format (`SPOTTER_TPU_TENANT_CONFIG`, path or inline JSON):

    {"default": {"rps": 50, "burst": 100, "weight": 1, "max_inflight": 0},
     "tenants": {"acme": {"rps": 200, "burst": 400, "weight": 4},
                 "hobby": {"rps": 5}}}

Unset fields inherit the default block; an absent default block inherits
`SPOTTER_TPU_TENANT_RPS_DEFAULT` (rate; burst = 2x rate), weight 1, and
no inflight cap. `rps` 0 (or negative) = unlimited for that tenant.

`TenantPlane.from_env()` returns None unless at least one of
`SPOTTER_TPU_TENANT_KEYS` / `SPOTTER_TPU_TENANT_CONFIG` /
`SPOTTER_TPU_TENANT_RPS_DEFAULT` is set: the whole plane is absent — not
merely idle — in an unconfigured deployment, and serving is bit-identical
to a pre-tenancy build (test-asserted).
"""

import hmac
import json
import logging
import os
import random
import threading
import time
from collections import deque
from typing import Callable, Optional

from spotter_tpu.obs.perf import SloBurn
from spotter_tpu.serving.resilience import (
    AdmissionError,
    jittered_retry_after,
)

logger = logging.getLogger(__name__)

TENANT_HEADER = "X-Spotter-Tenant"
API_KEY_HEADER = "X-API-Key"
# edge attestation (REVIEW): carries the shared secret that makes a
# forwarded X-Spotter-Tenant trustworthy on the next hop
EDGE_TOKEN_HEADER = "X-Spotter-Edge-Token"
ANON = "anon"

TENANT_KEYS_ENV = "SPOTTER_TPU_TENANT_KEYS"
TENANT_CONFIG_ENV = "SPOTTER_TPU_TENANT_CONFIG"
TENANT_RPS_DEFAULT_ENV = "SPOTTER_TPU_TENANT_RPS_DEFAULT"
TENANT_TOP_K_ENV = "SPOTTER_TPU_TENANT_TOP_K"
TENANT_EDGE_SECRET_ENV = "SPOTTER_TPU_TENANT_EDGE_SECRET"
TENANT_TRUST_HEADER_ENV = "SPOTTER_TPU_TENANT_TRUST_HEADER"

DEFAULT_TOP_K = 8
# burst defaults to 2x the sustained rate: one second of doubled arrival
# absorbs without a shed, which is what "bursty but in quota" means
DEFAULT_BURST_FACTOR = 2.0
# hard cap on tracked per-tenant state: a flood inventing fresh tenant ids
# must not grow memory without bound — least-recently-admitted evicted
MAX_TRACKED_TENANTS = 1024
# eviction backstop (REVIEW): a tenant whose inflight slot has not been
# touched for this long is a leak (every handler releases in a finally,
# so a live request can't look this stale) — reclaimable under pressure
INFLIGHT_STALE_S = 600.0

SHED_RATE = "rate"
SHED_INFLIGHT = "inflight"


class TenantQuotaError(AdmissionError):
    """Tenant over its rate quota or inflight cap — shed with 429 before
    any fetch/decode work; the hint is tenant-scoped (this tenant's own
    bucket refill time), jittered like every other Retry-After."""

    status = 429

    def __init__(
        self, tenant: str, kind: str, retry_after_s: float = 1.0
    ) -> None:
        what = (
            "rate quota" if kind == SHED_RATE else "concurrent-inflight cap"
        )
        super().__init__(
            f"tenant {tenant!r} over its {what}",
            retry_after_s=retry_after_s,
        )
        self.tenant = tenant
        self.kind = kind


class TokenBucket:
    """Classic token bucket: `burst` capacity, `rate` tokens/s refill.

    The clock is injectable so the property tests drive it
    deterministically. Invariants the tests pin: tokens never exceed
    `burst`, refill is monotone in elapsed time, and arrival at exactly
    the sustained rate never starves (every request finds its token).
    """

    __slots__ = ("rate", "burst", "tokens", "_t_last", "_clock")

    def __init__(
        self,
        rate: float,
        burst: float,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.rate = max(float(rate), 0.0)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst  # a fresh tenant starts with full burst
        self._clock = clock
        self._t_last = clock()

    def _refill(self, now: float) -> None:
        if now > self._t_last:
            self.tokens = min(
                self.burst, self.tokens + (now - self._t_last) * self.rate
            )
        self._t_last = now

    def try_take(self, n: float = 1.0) -> bool:
        self._refill(self._clock())
        # a nanotoken of grace: arrival at EXACTLY the sustained rate
        # accumulates float representation error across refills, and the
        # quota boundary belongs to the tenant — never-starves is pinned
        if self.tokens >= n - 1e-9:
            self.tokens = max(self.tokens - n, 0.0)
            return True
        return False

    def retry_after_s(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available at the current fill
        — THE tenant-scoped hint (a fast bucket says retry soon, a slow
        one says back off properly)."""
        self._refill(self._clock())
        missing = n - self.tokens
        if missing <= 0.0:
            return 0.0
        if self.rate <= 0.0:
            return 1.0
        return missing / self.rate


class _TenantState:
    """Everything tracked for one active tenant."""

    __slots__ = (
        "bucket", "weight", "max_inflight", "inflight",
        "admits_total", "sheds_total", "burn", "last_seen",
    )

    def __init__(
        self,
        bucket: Optional[TokenBucket],
        weight: float,
        max_inflight: int,
    ) -> None:
        self.bucket = bucket
        self.weight = weight
        self.max_inflight = max_inflight
        self.inflight = 0
        self.admits_total = 0
        self.sheds_total = {SHED_RATE: 0, SHED_INFLIGHT: 0}
        self.burn = SloBurn()
        self.last_seen = 0.0


class _Admitted:
    """Release handle for one admitted request: decrements the tenant's
    inflight occupancy exactly once and feeds its per-tenant SLO burn.

    `good=None` releases the slot WITHOUT touching the SLO burn — the
    abandoned-request path (client disconnect mid-await, uncaught handler
    error) where no outcome was served: the leak guard must not let a
    disconnect flood poison (or credit) anyone's budget. Idempotent, so
    the handler's finally can release unconditionally and the normal
    done() path still wins with the real outcome."""

    __slots__ = ("_plane", "tenant", "_released")

    def __init__(self, plane: "TenantPlane", tenant: str) -> None:
        self._plane = plane
        self.tenant = tenant
        self._released = False

    def release(self, good: Optional[bool] = True) -> None:
        if self._released:
            return
        self._released = True
        self._plane._release(self.tenant, good)


class TenantPlane:
    """The shared isolation plane: identity, quotas, DRR state, and
    per-tenant accounting. Thread-safe — edges call from the event loop,
    the batcher's engine worker records outcomes from its thread."""

    def __init__(
        self,
        config: Optional[dict] = None,
        key_map: Optional[dict] = None,
        default_rps: float = 0.0,
        top_k: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        edge_secret: Optional[str] = None,
        trust_header: bool = False,
    ) -> None:
        config = config or {}
        self._key_map = dict(key_map or {})
        defaults = dict(config.get("default") or {})
        tenants = config.get("tenants")
        if tenants is None:
            # flat form: the whole object (minus "default") is the map
            tenants = {
                k: v for k, v in config.items() if k != "default"
            }
        self._tenant_cfg = {
            str(k): dict(v or {}) for k, v in tenants.items()
        }
        self.default_rps = float(defaults.get("rps", default_rps) or 0.0)
        self.default_burst = float(
            defaults.get("burst", self.default_rps * DEFAULT_BURST_FACTOR)
            or 0.0
        )
        self.default_weight = max(float(defaults.get("weight", 1.0)), 1e-6)
        self.default_max_inflight = int(defaults.get("max_inflight", 0) or 0)
        self.top_k = (
            top_k
            if top_k is not None
            else _env_int(TENANT_TOP_K_ENV, DEFAULT_TOP_K)
        )
        self._clock = clock
        self._rng = rng
        self._edge_secret = edge_secret or None
        self.trust_header = bool(trust_header)
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantState] = {}
        # plane-level totals (the admit_sheds_total-style counters the
        # contract test reads without depending on label bounding)
        self.admits_total = 0
        self.sheds_total = {SHED_RATE: 0, SHED_INFLIGHT: 0}
        # spoof visibility: claimed-but-unattested tenant headers that
        # fell back to key/anon identity
        self.header_rejects_total = 0

    # ---- identity ----

    def resolve(self, headers) -> str:
        """Tenant id for a request. `headers` is any mapping with .get
        (aiohttp CIMultiDict works).

        The claimed `X-Spotter-Tenant` header is honored only when it is
        ATTESTED (REVIEW): the edge token matches the shared secret, the
        API-key map resolves the same tenant, or the deployment opted
        into bare-header trust. Everything else resolves through the
        API key alone, else to `anon` — one shared bucket, so a spoofer
        rotating invented ids gains neither a victim's quota nor a fresh
        burst, and cannot skew a victim's burn/occupancy accounting."""
        if headers is None:
            return ANON
        key = str(headers.get(API_KEY_HEADER, "") or "").strip()
        key_tenant = (
            str(self._key_map[key])
            if key and key in self._key_map
            else None
        )
        claimed = str(headers.get(TENANT_HEADER, "") or "").strip()
        if claimed:
            if self.trust_header:
                return claimed
            if self._edge_secret is not None:
                token = str(headers.get(EDGE_TOKEN_HEADER, "") or "")
                if token and hmac.compare_digest(token, self._edge_secret):
                    return claimed
            if key_tenant is not None and claimed == key_tenant:
                return key_tenant
            with self._lock:
                self.header_rejects_total += 1
        if key_tenant is not None:
            return key_tenant
        return ANON

    def stamp(self, headers: dict, tenant: str) -> None:
        """Stamp the RESOLVED identity onto forwarded headers (edge ->
        replica hop), plus the attestation token when a shared secret is
        configured — the next hop's plane then honors the id without
        re-deriving it from client-controlled input."""
        headers[TENANT_HEADER] = tenant
        if self._edge_secret is not None:
            headers[EDGE_TOKEN_HEADER] = self._edge_secret

    # ---- per-tenant config ----

    def _cfg(self, tenant: str, field: str, default):
        cfg = self._tenant_cfg.get(tenant)
        if cfg is not None and field in cfg and cfg[field] is not None:
            return cfg[field]
        return default

    def weight(self, tenant: str) -> float:
        return max(float(self._cfg(tenant, "weight", self.default_weight)),
                   1e-6)

    def _make_state(self, tenant: str) -> _TenantState:
        cfg = self._tenant_cfg.get(tenant) or {}
        rps = float(self._cfg(tenant, "rps", self.default_rps) or 0.0)
        if cfg.get("burst") is not None:
            burst = float(cfg["burst"] or 0.0)
        elif cfg.get("rps") is None:
            # rate fully inherited from the default block: inherit its
            # burst too (which itself defaults to 2x the default rate)
            burst = self.default_burst
        else:
            # per-tenant rate override without an explicit burst: scale
            # the burst to THIS tenant's rate, not the default block's
            burst = rps * DEFAULT_BURST_FACTOR
        bucket = (
            TokenBucket(rps, burst, clock=self._clock) if rps > 0.0 else None
        )
        max_inflight = int(
            self._cfg(tenant, "max_inflight", self.default_max_inflight) or 0
        )
        return _TenantState(bucket, self.weight(tenant), max_inflight)

    def _state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            if len(self._tenants) >= MAX_TRACKED_TENANTS:
                # evict the least-recently-admitted UNOCCUPIED tenant so a
                # tenant-id flood can't grow this map without bound
                idle = [
                    (s.last_seen, t)
                    for t, s in self._tenants.items()
                    if s.inflight == 0
                ]
                if not idle:
                    # backstop (REVIEW): every tracked tenant claims an
                    # inflight slot — slots untouched past the stale
                    # horizon are leaks (handlers release in a finally,
                    # so live requests never look this old) and must not
                    # make their tenants immortal
                    horizon = self._clock() - INFLIGHT_STALE_S
                    idle = [
                        (s.last_seen, t)
                        for t, s in self._tenants.items()
                        if s.last_seen < horizon
                    ]
                if idle:
                    _, victim = min(idle)
                    del self._tenants[victim]
            st = self._make_state(tenant)
            if len(self._tenants) < MAX_TRACKED_TENANTS:
                self._tenants[tenant] = st
            # else: full AND nothing evictable (MAX tenants all holding
            # fresh inflight) — serve off transient untracked state so
            # the memory bound is HARD; accounting for this tenant is
            # degraded until pressure drops, never the map unbounded
        return st

    # ---- admission ----

    def try_admit(self, tenant: str) -> _Admitted:
        """Admit one request for `tenant` or raise TenantQuotaError (429).

        Checked BEFORE any fetch/decode work and strictly before any
        in-quota request would be shed: the inflight cap first (slow-loris
        occupancy), then the rate bucket. Success returns a release handle
        that MUST be released exactly once."""
        with self._lock:
            st = self._state(tenant)
            st.last_seen = self._clock()
            if 0 < st.max_inflight <= st.inflight:
                st.sheds_total[SHED_INFLIGHT] += 1
                self.sheds_total[SHED_INFLIGHT] += 1
                st.burn.bad()
                raise TenantQuotaError(
                    tenant,
                    SHED_INFLIGHT,
                    retry_after_s=max(
                        jittered_retry_after(1.0, rng=self._rng), 0.1
                    ),
                )
            if st.bucket is not None and not st.bucket.try_take():
                st.sheds_total[SHED_RATE] += 1
                self.sheds_total[SHED_RATE] += 1
                st.burn.bad()
                raise TenantQuotaError(
                    tenant,
                    SHED_RATE,
                    retry_after_s=max(
                        jittered_retry_after(
                            max(st.bucket.retry_after_s(), 0.05),
                            rng=self._rng,
                        ),
                        0.05,
                    ),
                )
            st.inflight += 1
            st.admits_total += 1
            self.admits_total += 1
            return _Admitted(self, tenant)

    def _release(self, tenant: str, good: Optional[bool]) -> None:
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                return
            st.inflight = max(st.inflight - 1, 0)
            if good is None:  # abandoned: no outcome served, no burn
                return
            if good:
                st.burn.good()
            else:
                st.burn.bad()

    def record_outcome(self, tenant: Optional[str], good: bool) -> None:
        """Per-tenant SLO accounting for paths that bypass try_admit
        (e.g. the batcher recording a deadline miss for an already
        admitted image)."""
        if not tenant:
            tenant = ANON
        with self._lock:
            st = self._state(tenant)
            if good:
                st.burn.good()
            else:
                st.burn.bad()

    # ---- occupancy / overload scoping ----

    def inflight(self, tenant: str) -> int:
        with self._lock:
            st = self._tenants.get(tenant)
            return st.inflight if st is not None else 0

    def top_occupancy_tenant(self) -> Optional[str]:
        """Tenant holding the most weight-normalized inflight occupancy
        right now (ties broken by name for determinism); None when idle.
        The limiter revokes THIS tenant's bulk first."""
        with self._lock:
            best = None
            best_score = 0.0
            for t, st in sorted(self._tenants.items()):
                score = st.inflight / st.weight
                if st.inflight > 0 and score > best_score:
                    best, best_score = t, score
            return best

    def over_share(self, tenant: Optional[str]) -> bool:
        """Is `tenant` holding more than its weight-fair share of current
        inflight occupancy? Brownout rung 4 browns out ONLY over-share
        tenants; in-quota tenants keep full service. Unknown/idle tenants
        are never over share."""
        if not tenant:
            return False
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None or st.inflight == 0:
                return False
            total_inflight = sum(s.inflight for s in self._tenants.values())
            if total_inflight <= st.inflight:
                return True  # alone on the server: its own backlog
            active_weight = sum(
                s.weight
                for s in self._tenants.values()
                if s.inflight > 0
            )
            fair = st.weight / active_weight if active_weight > 0 else 1.0
            return st.inflight / total_inflight > fair + 1e-9

    # ---- fair scheduling (DRR) ----

    def drr_order(self, items: list, tenant_of: Callable[[object], str]):
        """Deficit-weighted round-robin across the tenants present in
        `items`, preserving each tenant's internal order. With zero or one
        distinct tenant the INPUT LIST is returned unchanged (identity,
        not a copy) — the bit-identity opt-out the scheduler tests pin.

        Fairness is PER CALL (classic DRR: a deficit resets the moment
        its queue empties, and every queue drains within the call, so no
        credit survives to the next one). That is the window that
        matters: each plan() round is handed the whole pending backlog
        and re-interleaves it, so a tenant wronged in one round is
        re-ranked fairly from scratch in the next — nothing banks, for
        anyone."""
        tenants: list[str] = []
        queues: dict[str, deque] = {}
        for it in items:
            t = tenant_of(it) or ANON
            q = queues.get(t)
            if q is None:
                q = queues[t] = deque()
                tenants.append(t)
            q.append(it)
        if len(tenants) <= 1:
            return items
        deficit = {t: 0.0 for t in tenants}
        out: list = []
        while len(out) < len(items):
            for t in tenants:
                q = queues[t]
                if not q:
                    continue
                # quantum = weight: a weight-4 tenant drains 4 items
                # per round for a weight-1 tenant's one
                deficit[t] += self.weight(t)
                while q and deficit[t] >= 1.0:
                    deficit[t] -= 1.0
                    out.append(q.popleft())
                if not q:
                    # emptied: surrender leftover credit (no banking)
                    deficit[t] = 0.0
        return out

    # ---- observability ----

    def _tenant_row(self, st: _TenantState) -> dict:
        return {
            "inflight": st.inflight,
            "admits_total": st.admits_total,
            "sheds_rate_total": st.sheds_total[SHED_RATE],
            "sheds_inflight_total": st.sheds_total[SHED_INFLIGHT],
            "slo_burn": st.burn.burn(60.0),
            "weight": st.weight,
            "rps": st.bucket.rate if st.bucket is not None else 0.0,
            "burst": st.bucket.burst if st.bucket is not None else 0.0,
            "max_inflight": st.max_inflight,
        }

    def metrics_view(self) -> dict:
        """Bounded per-tenant numeric map for /metrics: top-K tenants by
        admits + an `other` overflow row summing the rest. The prom
        renderer labels these {tenant=..., stat=...}; K bounds the label
        cardinality however many tenant ids a flood invents."""
        with self._lock:
            ranked = sorted(
                self._tenants.items(),
                key=lambda kv: (-kv[1].admits_total, kv[0]),
            )
            view: dict[str, dict] = {}
            other = {
                "inflight": 0, "admits_total": 0,
                "sheds_rate_total": 0, "sheds_inflight_total": 0,
            }
            overflow = False
            for i, (t, st) in enumerate(ranked):
                if i < self.top_k:
                    row = self._tenant_row(st)
                    # metrics_view rows stay purely numeric (prom labels)
                    view[t] = {
                        k: round(float(v), 6) for k, v in row.items()
                    }
                else:
                    overflow = True
                    other["inflight"] += st.inflight
                    other["admits_total"] += st.admits_total
                    other["sheds_rate_total"] += st.sheds_total[SHED_RATE]
                    other["sheds_inflight_total"] += (
                        st.sheds_total[SHED_INFLIGHT]
                    )
            if overflow:
                view["other"] = {k: float(v) for k, v in other.items()}
            return view

    def snapshot(self) -> dict:
        """Full (but MAX_TRACKED_TENANTS-bounded) table for the
        admin-gated /debug/tenants view."""
        with self._lock:
            rows = {
                t: self._tenant_row(st)
                for t, st in sorted(self._tenants.items())
            }
        return {
            "tenants": rows,
            "active": sum(1 for r in rows.values() if r["inflight"] > 0),
            "tracked": len(rows),
            "admits_total": self.admits_total,
            "sheds_total": dict(self.sheds_total),
            "header_rejects_total": self.header_rejects_total,
            "trust_header": self.trust_header,
            "edge_attested": self._edge_secret is not None,
            "default_rps": self.default_rps,
            "default_weight": self.default_weight,
            "top_k": self.top_k,
        }


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def _load_key_map(raw: str) -> dict:
    """`SPOTTER_TPU_TENANT_KEYS` is a PATH to a JSON file (api-key ->
    tenant): keys are secrets and don't belong in `ps e` output."""
    try:
        with open(raw) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        logger.warning("tenant key map %r unreadable (%s); ignoring",
                       raw, exc)
        return {}
    if not isinstance(data, dict):
        logger.warning("tenant key map %r is not an object; ignoring", raw)
        return {}
    return {str(k): str(v) for k, v in data.items()}


def _load_edge_secret(raw: str) -> Optional[str]:
    """`SPOTTER_TPU_TENANT_EDGE_SECRET` is preferably a PATH to a file
    holding the shared attestation secret (secrets don't belong in
    `ps e` output); a value that names no file is used literally (the
    test/drill ergonomic case)."""
    if os.path.isfile(raw):
        try:
            with open(raw) as f:
                secret = f.read().strip()
        except OSError as exc:
            logger.warning(
                "tenant edge secret file %r unreadable (%s); ignoring",
                raw, exc,
            )
            return None
        return secret or None
    return raw


def _load_config(raw: str) -> dict:
    """`SPOTTER_TPU_TENANT_CONFIG` is a path OR inline JSON (inline wins
    the ergonomic case for tests and drills)."""
    text = raw
    if not raw.lstrip().startswith("{"):
        try:
            with open(raw) as f:
                text = f.read()
        except OSError as exc:
            logger.warning("tenant config %r unreadable (%s); ignoring",
                           raw, exc)
            return {}
    try:
        data = json.loads(text)
    except json.JSONDecodeError as exc:
        logger.warning("tenant config invalid JSON (%s); ignoring", exc)
        return {}
    return data if isinstance(data, dict) else {}


def from_env(
    clock: Callable[[], float] = time.monotonic,
) -> Optional[TenantPlane]:
    """None unless tenancy is configured — the whole plane is absent in
    an unconfigured deployment (bit-identical serving, the RAGGED/ADMIT
    opt-out discipline)."""
    keys_raw = os.environ.get(TENANT_KEYS_ENV, "").strip()
    cfg_raw = os.environ.get(TENANT_CONFIG_ENV, "").strip()
    rps_raw = os.environ.get(TENANT_RPS_DEFAULT_ENV, "").strip()
    if not keys_raw and not cfg_raw and not rps_raw:
        return None
    try:
        default_rps = float(rps_raw) if rps_raw else 0.0
    except ValueError:
        logger.warning("%s=%r is not a number; using 0 (unlimited)",
                       TENANT_RPS_DEFAULT_ENV, rps_raw)
        default_rps = 0.0
    secret_raw = os.environ.get(TENANT_EDGE_SECRET_ENV, "").strip()
    trust_raw = os.environ.get(TENANT_TRUST_HEADER_ENV, "").strip()
    plane = TenantPlane(
        config=_load_config(cfg_raw) if cfg_raw else None,
        key_map=_load_key_map(keys_raw) if keys_raw else None,
        default_rps=default_rps,
        clock=clock,
        edge_secret=_load_edge_secret(secret_raw) if secret_raw else None,
        trust_header=trust_raw not in ("", "0"),
    )
    logger.warning(
        "TENANT ISOLATION ACTIVE: default_rps=%s weight=%s top_k=%d "
        "(%d configured tenants, %d api keys; header %s)",
        plane.default_rps or "unlimited", plane.default_weight,
        plane.top_k, len(plane._tenant_cfg), len(plane._key_map),
        "TRUSTED BARE" if plane.trust_header
        else ("edge-attested" if plane._edge_secret is not None
              else "untrusted (key/anon identity)"),
    )
    return plane
