"""Replica lifecycle: startup state machine, preemption watcher, warm restart.

PR 1 hardened the request path inside one replica; this module makes the
*replica itself* a managed, restartable unit — the prerequisite for running
the fleet on spot/preemptible TPU capacity (Spotlight, arXiv:2606.19004:
preemption-aware scheduling recovers most on-demand throughput; DeepServe,
arXiv:2501.14417: fast cold start + health-aware routing is what makes
serverless serving viable). Three pieces:

- `StartupTracker`: the `loading -> warming -> ready` state machine behind
  the `/startupz` endpoint, so a k8s startupProbe can distinguish "still
  compiling the bucket ladder" from "dead" and not kill a long warmup.
  `mark_ready()` records `time_to_ready_s` into the engine metrics — the
  number `bench.py --failover` and warm-restart work optimize.
- `PreemptionWatcher`: SIGTERM plus an env-configured maintenance-event
  source (`SPOTTER_TPU_PREEMPTION_FILE`: a path whose appearance signals the
  event — fault-injectable from tests and chaos staging;
  `SPOTTER_TPU_PREEMPTION_URL`: a metadata endpoint polled like GCE's
  maintenance-event URL). On the first signal it flips readiness, drains via
  the detector's existing `drain()`, and exits with a DISTINCT code
  (`PREEMPTED_EXIT_CODE`) so the supervisor can tell preemption from a crash
  and skip the crash-loop backoff.
- `maybe_enable_compile_cache()`: points JAX's persistent compilation cache
  at `SPOTTER_TPU_COMPILE_CACHE_DIR` before any program is compiled, so a
  restarted replica (same model, same bucket ladder) skips recompilation —
  the difference between a minutes-long and a seconds-long `time_to_ready_s`.
"""

import asyncio
import logging
import os
import signal
import time
from typing import Awaitable, Callable, Optional

logger = logging.getLogger(__name__)

COMPILE_CACHE_ENV = "SPOTTER_TPU_COMPILE_CACHE_DIR"
PREEMPTION_FILE_ENV = "SPOTTER_TPU_PREEMPTION_FILE"
PREEMPTION_URL_ENV = "SPOTTER_TPU_PREEMPTION_URL"
PREEMPTION_POLL_ENV = "SPOTTER_TPU_PREEMPTION_POLL_S"
RESTARTS_ENV = "SPOTTER_TPU_RESTARTS"
# Which fleet pool this replica belongs to ("on_demand" / "spot"), set by
# whatever spawned it (testing/cluster.py fleet members, a k8s nodeSelector
# wrapper). Purely a label: it surfaces in /startupz + /healthz so an
# operator — and the fleet controller's logs — can tell capacity classes
# apart without consulting the spawner.
POOL_ENV = "SPOTTER_TPU_POOL"

DEFAULT_PREEMPTION_POLL_S = 5.0

# Distinct from any Python/aiohttp crash code: the supervisor restarts a
# preempted replica immediately (capacity came back or k8s rescheduled us)
# instead of treating it as a crash loop.
PREEMPTED_EXIT_CODE = 83

# Startup states, in order. "ready" is terminal for a healthy bring-up;
# "failed" is terminal for a bring-up that raised — the server exits
# non-zero right after marking it so the supervisor/kubelet restart path
# (with backoff) takes over instead of the replica serving 503s forever.
# "verifying" (ISSUE 17) sits between warming and ready: the golden probe
# and weights attestation must pass before the replica may serve — on cold
# start, warm compile-cache restore, OOM downgrade, and degraded-dp
# rebuild alike. A warmup that compiled fine can still answer WRONG
# (corrupt restore, poisoned compile cache), and readiness is the last
# gate before clients see those answers.
LOADING = "loading"
WARMING = "warming"
VERIFYING = "verifying"
READY = "ready"
FAILED = "failed"

# Exit code for a failed bring-up: distinct from PREEMPTED_EXIT_CODE (83)
# and the supervisor's CRASH_LOOP_EXIT_CODE (84) so logs tell the three
# apart; the supervisor treats it as a plain crash (exponential backoff).
BRINGUP_FAILED_EXIT_CODE = 82

# Exit code for a failed integrity verification (ISSUE 17): the replica's
# golden probe or weights attestation failed — it was about to serve (or
# WAS serving) wrong answers. Distinct from every other rung because the
# supervisor's response is unique: COLD restart with the suspect
# compile-cache dir quarantined, since a warm restart would faithfully
# restore the very state that just failed verification.
INTEGRITY_EXIT_CODE = 86

# Process-start anchor for time_to_ready_s. Module import happens at the top
# of server bootstrap, so this slightly undercounts interpreter start — the
# compile/warmup cost it exists to expose dwarfs that.
_PROCESS_START = time.monotonic()


def maybe_enable_compile_cache() -> Optional[str]:
    """Arm JAX's persistent compilation cache from the env (idempotent).

    Must run before the first jit compilation of the process. Thresholds are
    zeroed so every bucket program is cached — the ladder is a handful of
    programs and a preempted replica wants all of them back.
    """
    cache_dir = os.environ.get(COMPILE_CACHE_ENV, "").strip()
    if not cache_dir:
        return None
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    logger.info("persistent compile cache enabled at %s (warm restart)", cache_dir)
    return cache_dir


def pool_from_env() -> Optional[str]:
    """The fleet pool label this replica was spawned into, or None."""
    return os.environ.get(POOL_ENV, "").strip() or None


def restarts_from_env() -> int:
    """How many times the supervisor has restarted this replica (0 on the
    first launch or outside a supervisor)."""
    raw = os.environ.get(RESTARTS_ENV, "").strip()
    try:
        return max(0, int(raw)) if raw else 0
    except ValueError:
        return 0


class StartupTracker:
    """`loading -> warming -> ready` behind /startupz.

    A k8s startupProbe polls /startupz with a generous failureThreshold;
    readiness/liveness probes only take over once startup has succeeded, so
    a cold compile cache cannot get the pod killed mid-warmup.
    """

    def __init__(self) -> None:
        self._state = LOADING
        self._since = time.monotonic()
        self.time_to_ready_s: Optional[float] = None
        self.error: Optional[str] = None

    @property
    def state(self) -> str:
        return self._state

    @property
    def ready(self) -> bool:
        return self._state == READY

    def mark(self, state: str) -> None:
        if state not in (LOADING, WARMING, VERIFYING, READY):
            raise ValueError(f"unknown startup state {state!r}")
        self._state = state
        self._since = time.monotonic()

    def mark_ready(self, metrics=None) -> float:
        """Transition to ready; record time_to_ready_s (process start ->
        now) into `metrics` when given. Returns the gauge value."""
        self._state = READY
        self._since = time.monotonic()
        self.time_to_ready_s = time.monotonic() - _PROCESS_START
        if metrics is not None:
            metrics.set_time_to_ready(self.time_to_ready_s)
        return self.time_to_ready_s

    def mark_failed(self, error: str) -> None:
        """Terminal: bring-up raised. /startupz keeps answering 503 with the
        error for whatever probe window remains before the process exits."""
        self._state = FAILED
        self._since = time.monotonic()
        self.error = error

    def snapshot(self) -> dict:
        # deploy identity (ISSUE 15): a replica that is still loading
        # already declares WHICH build is coming up — the rollout
        # controller (and an operator watching a canary spawn) reads it
        # from /startupz before the engine exists. Imported lazily so this
        # module stays cheap for the supervisor's import path.
        from spotter_tpu.engine.metrics import default_build_version

        return {
            "state": self._state,
            "ready": self.ready,
            "state_age_s": time.monotonic() - self._since,
            "time_to_ready_s": self.time_to_ready_s,
            "error": self.error,
            "pool": pool_from_env(),
            "version": default_build_version(),
        }


class PreemptionWatcher:
    """Watch for preemption (SIGTERM or a maintenance-event source) and run
    one graceful drain-then-exit sequence.

    `on_preempt` is awaited exactly once (typically `detector.drain()` — it
    already flips readiness so the LB stops routing); then `exit_cb` is
    called with `PREEMPTED_EXIT_CODE`. Tests inject a no-op `exit_cb`; the
    server default is `os._exit`, which is deliberate: after a drain there is
    nothing left worth unwinding, and a preempted host may have seconds.
    """

    def __init__(
        self,
        on_preempt: Callable[[], Awaitable],
        poll_s: Optional[float] = None,
        file_source: Optional[str] = None,
        url_source: Optional[str] = None,
        exit_cb: Callable[[int], None] = os._exit,
        install_sigterm: bool = True,
    ) -> None:
        if poll_s is None:
            raw = os.environ.get(PREEMPTION_POLL_ENV, "").strip()
            poll_s = float(raw) if raw else DEFAULT_PREEMPTION_POLL_S
        self.on_preempt = on_preempt
        self.poll_s = max(poll_s, 0.01)
        self.file_source = (
            file_source
            if file_source is not None
            else os.environ.get(PREEMPTION_FILE_ENV, "").strip() or None
        )
        self.url_source = (
            url_source
            if url_source is not None
            else os.environ.get(PREEMPTION_URL_ENV, "").strip() or None
        )
        self.exit_cb = exit_cb
        self.install_sigterm = install_sigterm
        self.preempted = False
        self.reason: Optional[str] = None
        self._task: Optional[asyncio.Task] = None
        self._triggered = asyncio.Event()

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self.install_sigterm:
            try:
                loop.add_signal_handler(
                    signal.SIGTERM, self.trigger, "SIGTERM (kubelet/preemption)"
                )
            except (NotImplementedError, RuntimeError):  # non-main thread
                logger.warning("could not install SIGTERM handler")
        self._task = asyncio.create_task(self._run())

    def trigger(self, reason: str) -> None:
        """Idempotent: the first trigger wins; later ones are logged only."""
        if self.preempted:
            logger.info("preemption re-signaled (%s); drain already running", reason)
            return
        self.preempted = True
        self.reason = reason
        self._triggered.set()

    async def _check_sources(self) -> Optional[str]:
        if self.file_source and os.path.exists(self.file_source):
            return f"maintenance file {self.file_source}"
        if self.url_source:
            try:
                import httpx

                async with httpx.AsyncClient(timeout=2.0) as client:
                    resp = await client.get(self.url_source)
                body = resp.text.strip().upper()
                if resp.status_code == 200 and body not in ("", "NONE", "FALSE"):
                    return f"maintenance event from {self.url_source}: {body[:80]}"
            except Exception:  # metadata endpoint flaky — never a crash source
                logger.debug("preemption URL poll failed", exc_info=True)
        return None

    async def _run(self) -> None:
        while not self._triggered.is_set():
            reason = await self._check_sources()
            if reason is not None:
                self.trigger(reason)
                break
            try:
                await asyncio.wait_for(self._triggered.wait(), self.poll_s)
            except asyncio.TimeoutError:
                continue
        await self._triggered.wait()
        logger.warning("preemption: %s — draining then exiting %d",
                       self.reason, PREEMPTED_EXIT_CODE)
        try:
            await self.on_preempt()
        except Exception:
            logger.exception("drain during preemption failed; exiting anyway")
        # flight-recorder post-mortem (ISSUE 7): the in-memory trace ring
        # dies with the process — persist it so "what was in flight when
        # the preemption landed" is answerable after the restart
        from spotter_tpu.obs.recorder import dump_for_exit

        dump_for_exit(PREEMPTED_EXIT_CODE)
        self.exit_cb(PREEMPTED_EXIT_CODE)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
