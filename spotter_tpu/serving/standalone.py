"""Standalone aiohttp serving runtime (no Ray required).

Serves the same route the Ray Serve app exposes behind the manager proxy
(route_prefix /detect — rayservice-template.yaml:10; proxy target
handlers.go:298-304), plus /healthz and /metrics (SURVEY.md §5.5 requires
throughput/latency counters that the reference lacks).
"""

import argparse
import json
import logging

import pydantic
from aiohttp import web

from spotter_tpu.serving.app import build_detector_app

logger = logging.getLogger(__name__)


def make_app(detector=None, model_name: str | None = None, warmup: bool = False) -> web.Application:
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["detector"] = detector or build_detector_app(model_name, warmup=warmup)

    async def detect(request: web.Request) -> web.Response:
        try:
            payload = await request.json()
        except json.JSONDecodeError:
            return web.Response(status=400, text="Invalid JSON body")
        try:
            response = await request.app["detector"].detect(payload)
        except pydantic.ValidationError as exc:
            return web.Response(status=400, text=f"Invalid request: {exc}")
        except Exception:
            logger.exception("detect failed")
            return web.Response(status=500, text="Internal server error")
        return web.json_response(response.model_dump())

    async def healthz(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def metrics(request: web.Request) -> web.Response:
        return web.json_response(request.app["detector"].engine.metrics.snapshot())

    async def on_cleanup(app: web.Application) -> None:
        await app["detector"].aclose()

    app.router.add_post("/detect", detect)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    app.on_cleanup.append(on_cleanup)
    return app


def main() -> None:
    parser = argparse.ArgumentParser(description="spotter-tpu standalone detection server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--model", default=None, help="overrides MODEL_NAME env")
    parser.add_argument("--no-warmup", action="store_true")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    web.run_app(
        make_app(model_name=args.model, warmup=not args.no_warmup),
        host=args.host,
        port=args.port,
    )


if __name__ == "__main__":
    main()
