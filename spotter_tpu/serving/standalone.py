"""Standalone aiohttp serving runtime (no Ray required).

Serves the same route the Ray Serve app exposes behind the manager proxy
(route_prefix /detect — rayservice-template.yaml:10; proxy target
handlers.go:298-304), plus /healthz and /metrics (SURVEY.md §5.5 requires
throughput/latency counters that the reference lacks).

Resilience surface (ISSUE 1): /detect answers 429 (queue full) or 503
(breaker open / draining) with a Retry-After hint when the request is shed;
/healthz is READINESS (503 while the breaker is open or a drain is in
progress) while /livez is LIVENESS (200 whenever the process serves HTTP) —
the split k8s needs to stop routing without restarting the pod; /drain is
the preStop hook: stop admitting, flush the queue, wait for in-flight
batches. SPOTTER_TPU_FAULTS arms the fault-injection harness
(spotter_tpu/testing/faults.py) for chaos staging — loud at startup.

Replica lifecycle (ISSUE 2): the HTTP surface binds BEFORE the model loads —
bring-up runs as a background task through the `loading -> warming -> ready`
state machine exposed at /startupz, so a k8s startupProbe can wait out a
long warmup without the pod being killed (readiness stays 503 throughout).
`SPOTTER_TPU_COMPILE_CACHE_DIR` arms JAX's persistent compilation cache
before the engine compiles, making a post-preemption restart warm;
`time_to_ready_s` and `restarts_total` (from `SPOTTER_TPU_RESTARTS`, set by
the supervisor) land in /metrics. A `PreemptionWatcher` (SIGTERM + the
`SPOTTER_TPU_PREEMPTION_FILE`/`_URL` maintenance source) drains and exits
with the distinct preemption code. When `SPOTTER_TPU_ADMIN_TOKEN` is set,
the state-changing admin endpoints (/drain, /profile) require it in the
`X-Admin-Token` header — without the guard any client could drain a replica
out of the fleet or trigger a trace capture.

Ragged scheduling (ISSUE 9): `--ragged` (or `SPOTTER_TPU_RAGGED=1`) swaps
the batcher's per-bucket FIFO for the unified scheduler — deadline-slack
admission ordering and mixed-resolution superbatch packing; /healthz then
reports `ragged: true` and /metrics grows `padding_waste_pct` +
`slack_at_dispatch_ms`. Unset keeps per-bucket semantics bit-identical.

Caching tier (ISSUE 5): `--cache-mb` (or `SPOTTER_TPU_CACHE_MAX_MB`) arms
the content-addressed result cache + single-flight coalescing tier in the
detector/batcher; /healthz then reports the cache's size state and /metrics
the hit/miss/coalesce/eviction counters. Unset/0 leaves serving
bit-identical to a cache-less build.
"""

import argparse
import asyncio
import json
import logging
import math
import os
import tempfile

import pydantic
from aiohttp import web

from spotter_tpu.obs import http as obs_http
from spotter_tpu.obs import logs as obs_logs
from spotter_tpu.ops import preprocess
from spotter_tpu.serving import integrity, lifecycle, tenancy, wire
from spotter_tpu.serving.detector import QueriesUnsupportedError
from spotter_tpu.serving.fleet import classify_request
from spotter_tpu.serving.resilience import AdmissionError
from spotter_tpu.serving.tenancy import TenantQuotaError
from spotter_tpu.testing import faults, stub_engine

logger = logging.getLogger(__name__)

# Back-compat aliases: the admin guard moved to obs/http.py (ISSUE 7) so
# /debug/traces on the router shares it; existing imports keep working.
ADMIN_TOKEN_ENV = obs_http.ADMIN_TOKEN_ENV
ADMIN_TOKEN_HEADER = obs_http.ADMIN_TOKEN_HEADER
_admin_rejection = obs_http.admin_rejection


def _rmdir_quiet(path: str) -> None:
    """Drop a just-created empty trace dir on failed /profile requests."""
    try:
        os.rmdir(path)
    except OSError:  # non-empty (trace partially written) or already gone
        pass


def _shed_response(exc: AdmissionError) -> web.Response:
    # Retry-After never renders 0 (REVIEW): sub-second hints (the tenant
    # rate-shed jitter floors at 0.05 s) ceil to 1 — a "0" header invites
    # the immediate retry the shed exists to push back. The precise float
    # rides in the body for clients that want fast pacing.
    return web.json_response(
        {
            "error": str(exc),
            "status": exc.status,
            "retry_after_s": round(max(exc.retry_after_s, 0.0), 3),
        },
        status=exc.status,
        headers={"Retry-After": f"{max(1, math.ceil(exc.retry_after_s))}"},
    )


def _not_ready_response(tracker: lifecycle.StartupTracker) -> web.Response:
    return web.json_response(
        {"error": f"replica starting up ({tracker.state})", "status": 503},
        status=503,
        headers={"Retry-After": "2"},
    )


def _build_detector_blocking(model_name: str | None):
    """The heavy half of bring-up, run in an executor: compile-cache arming
    must precede the first jit, then the model/engine build."""
    lifecycle.maybe_enable_compile_cache()
    if stub_engine.stub_mode_enabled():
        logger.warning(
            "STUB ENGINE ACTIVE (%s) — canned detections, no device; "
            "never production", stub_engine.STUB_ENGINE_ENV,
        )
        return stub_engine.build_stub_detector()
    from spotter_tpu.serving.app import build_detector_app

    return build_detector_app(model_name, warmup=False)


def make_app(
    detector=None,
    model_name: str | None = None,
    warmup: bool = False,
    preemption: bool = False,
    bringup_exit_cb=os._exit,
    fatal_exit_cb=os._exit,
    integrity_exit_cb=os._exit,
) -> web.Application:
    """Build the serving app.

    With `detector` given (tests), the app is ready immediately. Otherwise
    bring-up runs as a background task after the HTTP surface binds: the
    startupProbe watches /startupz while the model loads and warms.
    `preemption=True` (the `main()` path) installs the PreemptionWatcher.

    A FAILED bring-up (bad MODEL_NAME, OOM, compile error) must not leave
    the process alive serving 503s forever — the supervisor/kubelet only
    react to process exit. It marks the terminal `failed` startup state and
    calls `bringup_exit_cb(BRINGUP_FAILED_EXIT_CODE)` (default `os._exit`,
    overridable in tests) so the crash-loop/backoff machinery takes over.

    Engine fault domain (ISSUE 4): the batcher is wired with the startup
    tracker (a degraded-dp rebuild re-enters `warming` on /startupz) and
    with `fatal_exit_cb` — on a fatal device error at dp=1 the process
    exits `FATAL_ENGINE_EXIT_CODE` (85) for an immediate supervisor warm
    restart instead of serving breaker-open 503s off a dead chip.

    Verified readiness (ISSUE 17): with the integrity plane enabled
    (`SPOTTER_TPU_INTEGRITY`, default on), bring-up passes through the
    `verifying` state — on-device weights attestation plus a golden probe
    through the real batcher must PASS before READY, on cold start and
    warm compile-cache restore alike, and again after every degraded-dp
    rebuild. A failure exits `INTEGRITY_EXIT_CODE` (86) via
    `integrity_exit_cb` so the supervisor cold-restarts with the suspect
    compile cache quarantined. The injected-detector path (tests) skips
    verification, exactly like it skips bring-up.
    """
    app = web.Application(client_max_size=64 * 1024 * 1024)
    tracker = lifecycle.StartupTracker()
    app["startup"] = tracker
    app["detector"] = detector
    # tenant isolation plane (ISSUE 19): None unless configured — every
    # tenant branch below is then absent and serving is bit-identical
    tenant_plane = tenancy.from_env()
    app["tenancy"] = tenant_plane
    if faults.maybe_activate_from_env() is not None:
        logger.warning(
            "FAULT INJECTION ACTIVE (%s) — this server is a chaos target, "
            "never production",
            faults.FAULTS_ENV,
        )

    def _stamp_identity(det) -> None:
        # fleet-mergeable snapshot identity (ISSUE 12): the model name
        # joins replica_id/pid/generation in every /metrics snapshot so
        # the aggregator's per-replica table and restart detection are
        # principled. Generation itself rides set_restarts (below).
        model = (
            model_name
            or os.environ.get("MODEL_NAME")
            or ("stub" if stub_engine.stub_mode_enabled() else None)
        )
        if model is not None:
            det.engine.metrics.set_identity(model=model)
        # weights digest (ISSUE 15): engines that can fingerprint their
        # loaded params expose weights_digest(); an operator-pinned
        # SPOTTER_TPU_WEIGHTS_DIGEST (already stamped at Metrics init)
        # outranks the computed one
        from spotter_tpu.engine.metrics import default_weights_digest

        digest_fn = getattr(det.engine, "weights_digest", None)
        if digest_fn is not None and default_weights_digest() is None:
            try:
                digest = digest_fn() if callable(digest_fn) else digest_fn
            except Exception:
                digest = None
            if digest:
                det.engine.metrics.set_identity(weights_digest=str(digest))

    def _wire_fault_domain(det) -> None:
        det.batcher.attach_lifecycle(tracker)
        if det.batcher.fatal_exit_cb is None:
            det.batcher.fatal_exit_cb = fatal_exit_cb
        # HBM telemetry (ISSUE 10): poll device.memory_stats() into the
        # perf ledger's gauges. Only engines with real devices get a
        # sampler (stub/fake engines have no `.devices`); the thread is a
        # daemon and is stopped on app cleanup. SPOTTER_TPU_HBM_SAMPLE_S=0
        # disables it.
        from spotter_tpu.obs import perf as obs_perf

        devices_fn = getattr(det.engine, "devices", None)
        if devices_fn is not None and app.get("hbm_sampler") is None:
            sampler = obs_perf.HbmSampler(
                devices_fn, det.engine.metrics.perf
            )
            if sampler.start():
                app["hbm_sampler"] = sampler

    if detector is not None:
        detector.engine.metrics.set_restarts(lifecycle.restarts_from_env())
        _stamp_identity(detector)
        _wire_fault_domain(detector)
        detector.attach_tenancy(tenant_plane)
        tracker.mark_ready(detector.engine.metrics)

    def _make_integrity_recheck(plane):
        def recheck(source: str) -> bool:
            if plane.verify_blocking(source):
                return True
            plane.integrity_exit(plane.last_error or source)
            return False

        return recheck

    async def _bring_up(app: web.Application) -> None:
        loop = asyncio.get_running_loop()
        try:
            det = await loop.run_in_executor(
                None, _build_detector_blocking, model_name
            )
            tracker.mark(lifecycle.WARMING)
            if warmup:
                await loop.run_in_executor(None, det.engine.warmup)
            app["detector"] = det
            det.engine.metrics.set_restarts(lifecycle.restarts_from_env())
            _stamp_identity(det)
            _wire_fault_domain(det)
            det.attach_tenancy(tenant_plane)
            # SDC injection seam (ISSUE 17, chaos only): corrupt the live
            # weights AFTER load, BEFORE verification — the flipped-bit-
            # after-restore shape the attestation gate must catch
            n_corrupt = faults.take_corrupt_weights()
            if n_corrupt and hasattr(det.engine, "corrupt_weights"):
                logger.warning(
                    "FAULT: corrupting %d weight leaves before "
                    "verification", n_corrupt,
                )
                det.engine.corrupt_weights(n_corrupt)
            plane = None
            if integrity.integrity_enabled():
                # verified readiness (ISSUE 17): attest + golden probe must
                # pass before READY — a warm compile-cache restore is just
                # as much an SDC ingress as a cold load, so both verify
                tracker.mark(lifecycle.VERIFYING)
                plane = integrity.IntegrityPlane(
                    det.engine, det.batcher, exit_cb=integrity_exit_cb
                )
                app["integrity"] = plane
                source = (
                    "warm-restore"
                    if lifecycle.restarts_from_env() > 0
                    else "cold-start"
                )
                if not await plane.verify(source):
                    tracker.mark_failed(plane.last_error or "integrity")
                    plane.integrity_exit(plane.last_error or source)
                    return
                det.batcher.integrity_recheck_cb = (
                    _make_integrity_recheck(plane)
                )
            ttr = tracker.mark_ready(det.engine.metrics)
            logger.info("replica ready in %.1f s", ttr)
            if plane is not None:
                await plane.start()
        except asyncio.CancelledError:  # server shutdown mid-bring-up
            raise
        except Exception as exc:
            logger.exception("replica bring-up failed; exiting %d",
                             lifecycle.BRINGUP_FAILED_EXIT_CODE)
            tracker.mark_failed(f"{type(exc).__name__}: {exc}")
            bringup_exit_cb(lifecycle.BRINGUP_FAILED_EXIT_CODE)

    async def on_startup(app: web.Application) -> None:
        # profiler server after the loop exists; tasks stored for cleanup
        from spotter_tpu.engine import profiler

        profiler.maybe_start_profiler_server()
        if app["detector"] is None:
            app["bringup_task"] = asyncio.create_task(_bring_up(app))
        if preemption:
            async def drain_on_preempt():
                det = app["detector"]
                if det is not None:
                    await det.drain()

            watcher = lifecycle.PreemptionWatcher(drain_on_preempt)
            app["preemption_watcher"] = watcher
            await watcher.start()

    async def detect(request: web.Request) -> web.Response:
        # Request-scoped trace (ISSUE 7): continue the edge's traceparent or
        # mint ids from/with X-Request-ID; EVERY branch below — sheds
        # included — echoes the request id, and completed traces land in
        # the flight recorder with per-stage Server-Timing on the response.
        trace, request_id = obs_http.begin_http_trace(request)
        tenant = None
        tadm = None

        def done(resp: web.Response) -> web.Response:
            # per-tenant occupancy + SLO accounting (ISSUE 19): every
            # outcome releases the inflight slot exactly once; sheds and
            # server errors burn the tenant's budget, everything else
            # credits it
            if tadm is not None:
                tadm.release(
                    good=resp.status not in (429, 503) and resp.status < 500
                )
            # replica identity header (ISSUE 14 satellite): every /detect
            # outcome — sheds and errors included — names the replica that
            # produced it, so a slow or corrupt response joins /debug/fleet
            # rows and stitched traces by replica id. The deploy version
            # rides along (ISSUE 15) so clients, edges and the rollout
            # controller can attribute every response to a build.
            if det is not None:
                resp.headers[wire.REPLICA_HEADER] = (
                    det.engine.metrics.replica_id
                )
                resp.headers[wire.VERSION_HEADER] = (
                    det.engine.metrics.version
                )
            return obs_http.finish_http_trace(
                trace, request_id, resp, server_timing=True
            )

        det = request.app["detector"]
        if det is None:  # still loading/warming: shed, probe /startupz
            return done(_not_ready_response(tracker))
        if faults.take_flaky(det.engine.metrics.replica_id):
            # injected intermittent failure (ISSUE 14 chaos matrix): the
            # gray-failure shape hard ejection can't see — a 500 rate below
            # the consecutive-failure threshold. 500 is a REPLAYABLE status
            # at the pool, so the edge masks each one
            return done(
                web.json_response(
                    {"error": "injected flaky failure", "status": 500},
                    status=500,
                )
            )
        if tenant_plane is not None:
            # edge quota (ISSUE 19): resolve the tenant and charge its
            # token bucket / inflight cap BEFORE any parse/fetch/decode
            # work — an over-quota tenant sheds 429 here, strictly before
            # any in-quota request could be shed below
            tenant = tenant_plane.resolve(request.headers)
            try:
                tadm = tenant_plane.try_admit(tenant)
            except TenantQuotaError as exc:
                det.engine.metrics.record_shed()
                det.engine.metrics.record_admit_shed(
                    classify_request(request.headers, None)[0]
                )
                return done(_shed_response(exc))
        try:
            shed = det.check_admission()
            if shed is not None:  # draining / breaker open: reject before parsing
                return done(_shed_response(shed))
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                return done(web.Response(status=400, text="Invalid JSON body"))
            # request class (ISSUE 8): X-Request-Class header > request_class
            # payload key (stripped) > deadline tag > env default — the PR 6
            # fleet precedence, honored at the replica too so the brownout
            # ladder's bulk-only rung and the limiter's class-ordered shed work
            # with or without a fleet edge in front
            cls, payload = classify_request(request.headers, payload)
            shed = det.check_admission(cls, tenant)
            if shed is not None:  # brownout bulk shed: reject before fetching
                return done(_shed_response(shed))
            # data-plane observations (ISSUE 11): per-URL cache outcomes for
            # X-Cache and deterministic-failure verdicts for X-Spotter-Negative
            info: dict = {}
            try:
                response = await det.detect(
                    payload, cls=cls, info=info, tenant=tenant
                )
            except pydantic.ValidationError as exc:
                return done(web.Response(status=400, text=f"Invalid request: {exc}"))
            except QueriesUnsupportedError as exc:
                # open-vocab queries on a closed-set model (ISSUE 13): the
                # request can never succeed on this deployment — a client
                # error, not a server one
                return done(web.Response(status=400, text=str(exc)))
            except AdmissionError as exc:  # every image shed -> 429/503
                return done(_shed_response(exc))
            except Exception:
                logger.exception("detect failed")
                return done(web.Response(status=500, text="Internal server error"))
            body = response.model_dump(exclude_none=True)
            # binary wire format (ISSUE 11): `Accept: application/x-spotter-frame`
            # negotiates the length-prefixed frame (raw JPEG segments, deflated
            # header — no base64 tax). NOT negotiated -> the exact pre-existing
            # json_response call, byte-identical on the wire (exclude_none: the
            # `degraded` marker is absent unless a brownout concession shaped
            # this response — schemas.py contract).
            frame = wire.wants_frame(request.headers.get("Accept"))
            if frame:
                # corrupt_frame injection (ISSUE 14): while armed, one byte of
                # the encoded frame is flipped AFTER the checksums were
                # computed — the deterministic way to prove the edge CRC
                # validator catches, counts, and replays corruption
                resp = web.Response(
                    body=faults.corrupt_frame_bytes(
                        wire.encode_frame(body), det.engine.metrics.replica_id
                    ),
                    content_type=wire.FRAME_CONTENT_TYPE,
                )
            else:
                resp = web.json_response(body)
            x_cache = wire.summarize_cache_outcomes(
                (info.get("cache") or {}).values()
            )
            if x_cache is not None:
                resp.headers[wire.X_CACHE_HEADER] = x_cache
            verdicts = wire.encode_negative_header(info.get("negative") or {})
            if verdicts is not None:
                resp.headers[wire.NEGATIVE_HEADER] = verdicts
            out_bytes = resp.body
            det.engine.metrics.record_wire(
                request.content_length or 0,
                len(out_bytes) if isinstance(out_bytes, (bytes, bytearray)) else 0,
                frame,
            )
            return done(resp)
        finally:
            # leak guard (REVIEW): a client disconnect (CancelledError
            # in any await) or an uncaught error below must still free
            # the tenant's inflight slot, or the tenant is permanently
            # 429-locked at its inflight cap and its occupancy skews
            # the limiter/brownout forever. Idempotent: when done()
            # ran, it already released with the real outcome; this
            # no-outcome release never touches the SLO burn.
            if tadm is not None:
                tadm.release(good=None)

    async def startupz(request: web.Request) -> web.Response:
        """Startup probe: 200 only once the replica reached ready. A long
        warmup answers 503 with the state, which a startupProbe tolerates up
        to its failureThreshold — unlike a liveness probe, it won't kill."""
        snap = tracker.snapshot()
        return web.json_response(snap, status=200 if tracker.ready else 503)

    async def healthz(request: web.Request) -> web.Response:
        """Readiness: 503 drops this replica from the LB while starting up,
        while the breaker is open, or while a drain is in progress."""
        det = request.app["detector"]
        if det is None:
            return _not_ready_response(tracker)
        health = det.health()
        health["startup"] = tracker.state
        health["pool"] = lifecycle.pool_from_env()
        return web.json_response(health, status=200 if health["ready"] else 503)

    async def livez(request: web.Request) -> web.Response:
        """Liveness: the process is serving HTTP — restart only on hang."""
        return web.json_response({"status": "alive"})

    async def drain(request: web.Request) -> web.Response:
        """k8s preStop: stop admitting, flush the queue, wait for in-flight
        batches. Idempotent — a second call reports the drained state.
        Guarded by SPOTTER_TPU_ADMIN_TOKEN when set.

        Body (optional JSON, ISSUE 15): {"deadline_ms": N} caps the wait;
        the response reports `in_flight` (batches still running at the
        deadline) and `queued_failed`, so a rollout controller or preStop
        hook waits precisely instead of sleeping a fixed grace period."""
        rejected = _admin_rejection(request)
        if rejected is not None:
            return rejected
        det = request.app["detector"]
        if det is None:
            return _not_ready_response(tracker)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        timeout_s = None
        if isinstance(body, dict) and "deadline_ms" in body:
            try:
                timeout_s = max(float(body["deadline_ms"]), 0.0) / 1000.0
            except (TypeError, ValueError):
                return web.Response(
                    status=400, text="deadline_ms must be a number"
                )
        summary = await det.drain(timeout_s)
        return web.json_response(summary)

    async def metrics(request: web.Request) -> web.Response:
        det = request.app["detector"]
        if det is None:
            return obs_http.metrics_response(
                request, {"startup": tracker.snapshot()}
            )
        # JSON view unchanged for existing consumers; ?format=prometheus or
        # Accept: text/plain selects the text exposition (ISSUE 7)
        snap = det.engine.metrics.snapshot()
        # output-integrity plane (ISSUE 17): verification + probe + attest
        # counters ride the replica snapshot additively
        plane = request.app.get("integrity")
        if plane is not None:
            snap["integrity"] = plane.snapshot()
        # per-tenant accounting (ISSUE 19): bounded top-K view — prom
        # renders it {tenant=..., stat=...}; absent when unconfigured
        if tenant_plane is not None:
            snap["tenants"] = tenant_plane.metrics_view()
        return obs_http.metrics_response(request, snap)

    async def debug_tenants(request: web.Request) -> web.Response:
        """Full per-tenant table (ISSUE 19) — admin-token-gated like
        /profile; the bounded top-K view lives in /metrics."""
        rejected = _admin_rejection(request)
        if rejected is not None:
            return rejected
        if tenant_plane is None:
            return web.json_response({"enabled": False})
        return web.json_response(tenant_plane.snapshot())

    async def profile(request: web.Request) -> web.Response:
        """Capture a jax.profiler trace of in-flight device work.

        Body (optional JSON): {"duration_s": 1.0}. The server picks the
        trace directory (under SPOTTER_TPU_PROFILE_DIR or the system temp
        dir — never a client-supplied path) and returns it; open it with
        TensorBoard/xprof. Guarded by SPOTTER_TPU_ADMIN_TOKEN when set.
        """
        rejected = _admin_rejection(request)
        if rejected is not None:
            return rejected
        from spotter_tpu.engine import profiler

        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        if not isinstance(body, dict):
            return web.Response(status=400, text="body must be a JSON object")
        try:
            duration_s = min(float(body.get("duration_s", 1.0)), 30.0)
        except (TypeError, ValueError):
            return web.Response(status=400, text="duration_s must be a number")
        if not duration_s > 0.0:  # also rejects NaN before any dir is made
            return web.Response(status=400, text="duration_s must be > 0")
        base = os.environ.get("SPOTTER_TPU_PROFILE_DIR")
        log_dir = tempfile.mkdtemp(prefix="spotter-trace-", dir=base or None)
        try:
            summary = await asyncio.get_running_loop().run_in_executor(
                None, profiler.capture, log_dir, duration_s
            )
        except ValueError as exc:  # bad duration (e.g. <= 0, NaN)
            _rmdir_quiet(log_dir)
            return web.Response(status=400, text=str(exc))
        except RuntimeError as exc:  # capture already in progress
            _rmdir_quiet(log_dir)
            return web.Response(status=409, text=str(exc))
        return web.json_response(summary)

    async def on_cleanup(app: web.Application) -> None:
        sampler = app.get("hbm_sampler")
        if sampler is not None:
            sampler.stop()
        plane = app.get("integrity")
        if plane is not None:
            await plane.aclose()
        task = app.get("bringup_task")
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        watcher = app.get("preemption_watcher")
        if watcher is not None:
            await watcher.stop()
        if app["detector"] is not None:
            await app["detector"].aclose()

    app.router.add_post("/detect", detect)
    app.router.add_get("/startupz", startupz)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/livez", livez)
    app.router.add_post("/drain", drain)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/profile", profile)
    # per-tenant isolation table (ISSUE 19): admin-token-gated like /profile
    app.router.add_get("/debug/tenants", debug_tenants)
    # flight-recorder view (ISSUE 7): admin-token-gated like /profile
    app.router.add_get("/debug/traces", obs_http.make_debug_traces_handler())
    # device-efficiency ledger view (ISSUE 10): top-K expensive dispatches
    # (trace ids join /debug/traces), compile-shape table, HBM, burn-rate —
    # admin-token-gated like /profile
    app.router.add_get(
        "/debug/perf",
        obs_http.make_debug_perf_handler(
            lambda: (
                app["detector"].engine.metrics
                if app["detector"] is not None
                else None
            )
        ),
    )
    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def main() -> None:
    parser = argparse.ArgumentParser(description="spotter-tpu standalone detection server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--model", default=None, help="overrides MODEL_NAME env")
    parser.add_argument("--no-warmup", action="store_true")
    parser.add_argument(
        "--serve-dp",
        default=None,
        help="data-parallel serving width: shard batches over this many "
        "local chips with aggregate bucket sizing (SPOTTER_TPU_SERVE_DP; "
        "'all' = every local chip)",
    )
    parser.add_argument(
        "--serve-tp",
        default=None,
        help="tensor-parallel width: split the model's attention/MLP "
        "weights over this many chips per dp group "
        "(SPOTTER_TPU_SERVE_TP; composes with --serve-dp into a dp×tp "
        "mesh — the bucket ladder scales by dp only). Use when one chip's "
        "HBM can't hold (or serve fast enough) the model, e.g. "
        "OWLv2/ViT-L at tp=2/4",
    )
    parser.add_argument(
        "--explain-sharding",
        action="store_true",
        help="print the per-param sharding report for the resolved mesh "
        "(param path -> PartitionSpec -> per-device bytes, dead TP rules "
        "flagged) and exit without serving",
    )
    parser.add_argument(
        "--device-preprocess",
        action="store_true",
        help="uint8 ingest + on-device rescale/normalize "
        "(SPOTTER_TPU_DEVICE_PREPROCESS=1): 4x less H2D traffic, decode-only "
        "host work",
    )
    parser.add_argument(
        "--decode-workers",
        type=int,
        default=None,
        help=f"host decode/resize pool size ({preprocess.DECODE_WORKERS_ENV})",
    )
    parser.add_argument(
        "--ragged",
        action="store_true",
        help="ragged mixed-resolution batching + deadline-slack scheduling "
        "(SPOTTER_TPU_RAGGED=1): mixed-size images pack into one padded "
        "superbatch chosen to minimize padded-pixel waste, slo traffic "
        "fills dispatches before bulk; unset keeps per-bucket FIFO "
        "semantics bit-identical",
    )
    parser.add_argument(
        "--cache-mb",
        type=float,
        default=None,
        help="content-addressed result cache + request coalescing budget in "
        "MB (SPOTTER_TPU_CACHE_MAX_MB; 0 disables the tier — the default)",
    )
    parser.add_argument(
        "--stub-engine",
        action="store_true",
        help=f"canned-detection stub engine ({stub_engine.STUB_ENGINE_ENV}=1); "
        "failover tests/bench only",
    )
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    # SPOTTER_TPU_LOG_JSON=1: structured logs carrying the trace/request id
    # of whatever request was active when the line was emitted (ISSUE 7)
    obs_logs.maybe_setup_json_logging()
    if args.stub_engine:
        os.environ[stub_engine.STUB_ENGINE_ENV] = "1"
    # ingest/topology flags land in the env: bring-up (and any supervisor
    # respawn of it) reads them there, so flag and env behave identically
    if args.serve_dp is not None:
        os.environ["SPOTTER_TPU_SERVE_DP"] = str(args.serve_dp)
    if args.serve_tp is not None:
        os.environ["SPOTTER_TPU_SERVE_TP"] = str(args.serve_tp)
    if args.explain_sharding:
        from spotter_tpu.serving.app import explain_sharding

        print(explain_sharding(args.model))
        return
    if args.device_preprocess:
        os.environ["SPOTTER_TPU_DEVICE_PREPROCESS"] = "1"
    if args.ragged:
        from spotter_tpu.engine.scheduler import RAGGED_ENV

        os.environ[RAGGED_ENV] = "1"
    if args.decode_workers is not None:
        os.environ[preprocess.DECODE_WORKERS_ENV] = str(args.decode_workers)
    if args.cache_mb is not None:
        from spotter_tpu.caching.result_cache import CACHE_MAX_MB_ENV

        os.environ[CACHE_MAX_MB_ENV] = str(args.cache_mb)
    web.run_app(
        make_app(
            model_name=args.model, warmup=not args.no_warmup, preemption=True
        ),
        host=args.host,
        port=args.port,
    )


if __name__ == "__main__":
    main()
