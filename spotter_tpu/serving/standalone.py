"""Standalone aiohttp serving runtime (no Ray required).

Serves the same route the Ray Serve app exposes behind the manager proxy
(route_prefix /detect — rayservice-template.yaml:10; proxy target
handlers.go:298-304), plus /healthz and /metrics (SURVEY.md §5.5 requires
throughput/latency counters that the reference lacks).

Resilience surface (ISSUE 1): /detect answers 429 (queue full) or 503
(breaker open / draining) with a Retry-After hint when the request is shed;
/healthz is READINESS (503 while the breaker is open or a drain is in
progress) while /livez is LIVENESS (200 whenever the process serves HTTP) —
the split k8s needs to stop routing without restarting the pod; /drain is
the preStop hook: stop admitting, flush the queue, wait for in-flight
batches. SPOTTER_TPU_FAULTS arms the fault-injection harness
(spotter_tpu/testing/faults.py) for chaos staging — loud at startup.
"""

import argparse
import asyncio
import json
import logging
import os
import tempfile

import pydantic
from aiohttp import web

from spotter_tpu.engine import profiler
from spotter_tpu.serving.app import build_detector_app
from spotter_tpu.serving.resilience import AdmissionError
from spotter_tpu.testing import faults

logger = logging.getLogger(__name__)


def _rmdir_quiet(path: str) -> None:
    """Drop a just-created empty trace dir on failed /profile requests."""
    try:
        os.rmdir(path)
    except OSError:  # non-empty (trace partially written) or already gone
        pass


def _shed_response(exc: AdmissionError) -> web.Response:
    return web.json_response(
        {"error": str(exc), "status": exc.status},
        status=exc.status,
        headers={"Retry-After": f"{max(exc.retry_after_s, 0.0):.0f}"},
    )


def make_app(detector=None, model_name: str | None = None, warmup: bool = False) -> web.Application:
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["detector"] = detector or build_detector_app(model_name, warmup=warmup)
    profiler.maybe_start_profiler_server()
    if faults.maybe_activate_from_env() is not None:
        logger.warning(
            "FAULT INJECTION ACTIVE (%s) — this server is a chaos target, "
            "never production",
            faults.FAULTS_ENV,
        )

    async def detect(request: web.Request) -> web.Response:
        shed = request.app["detector"].check_admission()
        if shed is not None:  # draining / breaker open: reject before fetching
            return _shed_response(shed)
        try:
            payload = await request.json()
        except json.JSONDecodeError:
            return web.Response(status=400, text="Invalid JSON body")
        try:
            response = await request.app["detector"].detect(payload)
        except pydantic.ValidationError as exc:
            return web.Response(status=400, text=f"Invalid request: {exc}")
        except AdmissionError as exc:  # every image shed -> 429/503
            return _shed_response(exc)
        except Exception:
            logger.exception("detect failed")
            return web.Response(status=500, text="Internal server error")
        return web.json_response(response.model_dump())

    async def healthz(request: web.Request) -> web.Response:
        """Readiness: 503 drops this replica from the LB while the breaker
        is open or a drain is in progress; recovery (successful half-open
        probe) flips it back to 200."""
        health = request.app["detector"].health()
        return web.json_response(health, status=200 if health["ready"] else 503)

    async def livez(request: web.Request) -> web.Response:
        """Liveness: the process is serving HTTP — restart only on hang."""
        return web.json_response({"status": "alive"})

    async def drain(request: web.Request) -> web.Response:
        """k8s preStop: stop admitting, flush the queue, wait for in-flight
        batches. Idempotent — a second call reports the drained state."""
        summary = await request.app["detector"].drain()
        return web.json_response(summary)

    async def metrics(request: web.Request) -> web.Response:
        return web.json_response(request.app["detector"].engine.metrics.snapshot())

    async def profile(request: web.Request) -> web.Response:
        """Capture a jax.profiler trace of in-flight device work.

        Body (optional JSON): {"duration_s": 1.0}. The server picks the
        trace directory (under SPOTTER_TPU_PROFILE_DIR or the system temp
        dir — never a client-supplied path) and returns it; open it with
        TensorBoard/xprof.
        """
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        if not isinstance(body, dict):
            return web.Response(status=400, text="body must be a JSON object")
        try:
            duration_s = min(float(body.get("duration_s", 1.0)), 30.0)
        except (TypeError, ValueError):
            return web.Response(status=400, text="duration_s must be a number")
        if not duration_s > 0.0:  # also rejects NaN before any dir is made
            return web.Response(status=400, text="duration_s must be > 0")
        base = os.environ.get("SPOTTER_TPU_PROFILE_DIR")
        log_dir = tempfile.mkdtemp(prefix="spotter-trace-", dir=base or None)
        try:
            summary = await asyncio.get_running_loop().run_in_executor(
                None, profiler.capture, log_dir, duration_s
            )
        except ValueError as exc:  # bad duration (e.g. <= 0, NaN)
            _rmdir_quiet(log_dir)
            return web.Response(status=400, text=str(exc))
        except RuntimeError as exc:  # capture already in progress
            _rmdir_quiet(log_dir)
            return web.Response(status=409, text=str(exc))
        return web.json_response(summary)

    async def on_cleanup(app: web.Application) -> None:
        await app["detector"].aclose()

    app.router.add_post("/detect", detect)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/livez", livez)
    app.router.add_post("/drain", drain)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/profile", profile)
    app.on_cleanup.append(on_cleanup)
    return app


def main() -> None:
    parser = argparse.ArgumentParser(description="spotter-tpu standalone detection server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--model", default=None, help="overrides MODEL_NAME env")
    parser.add_argument("--no-warmup", action="store_true")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    web.run_app(
        make_app(model_name=args.model, warmup=not args.no_warmup),
        host=args.host,
        port=args.port,
    )


if __name__ == "__main__":
    main()
