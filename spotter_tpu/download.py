"""Weight pre-conversion at image build time.

The reference bakes torch weights into the serving image by running
`spotter_download` during docker build (apps/spotter/Dockerfile:17,
download.py:12-30) so pods start without network. The TPU analog converts
the torch checkpoint to Flax params and writes the versioned Orbax cache
(convert/loader.py); pod start then loads converted params directly and
never imports torch.
"""

import logging
import os
import sys

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(levelname)s %(message)s")
logger = logging.getLogger(__name__)


def download(model_name: str) -> None:
    from spotter_tpu.models import build_detector

    logger.info("Pre-converting weights for %s", model_name)
    built = build_detector(model_name)
    n_params = sum(p.size for p in _leaves(built.params))
    logger.info("Converted %s: %.1fM params cached", model_name, n_params / 1e6)


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def main() -> int:
    model_name = os.environ.get("MODEL_NAME")
    if not model_name:
        logger.error("MODEL_NAME environment variable not set.")
        return 1
    download(model_name)
    return 0


if __name__ == "__main__":
    sys.exit(main())
