"""spotter-tpu: a TPU-native object-detection serving framework.

Capability contract mirrors chilir/spotter (reference at /root/reference):
a control plane that deploys/deletes the serving app as a KubeRay RayService and
proxies `/detect` (apps/spotter-manager), plus a Python serving layer that detects
"amenities" in images fetched from URLs (apps/spotter/src/spotter/serve.py).

The compute path is rebuilt TPU-first: Flax model implementations compiled with
jax.jit/pjit, static-shape input bucketing, fixed-k postprocess, device-mesh
data/model parallelism via jax.sharding, and XLA collectives over ICI/DCN.
"""

__version__ = "0.1.0"

from spotter_tpu.taxonomy import AMENITIES_MAPPING  # noqa: F401
