{
  # Hermetic dev environment for spotter-tpu (the reference pins its
  # toolchain the same way: flake.nix:30-60 — go/python/uv/ruff; this build's
  # toolchain is python/jax + cmake/ninja for the C++ control plane).
  description = "spotter-tpu: TPU-native amenity-detection serving framework";

  inputs = {
    nixpkgs.url = "github:NixOS/nixpkgs/nixos-24.05";
    flake-utils.url = "github:numtide/flake-utils";
  };

  outputs = { self, nixpkgs, flake-utils }:
    flake-utils.lib.eachSystem [ "x86_64-linux" "aarch64-linux" ] (system:
      let
        pkgs = import nixpkgs { inherit system; };
        python = pkgs.python312;
      in {
        devShells.default = pkgs.mkShell {
          packages = [
            python
            pkgs.uv          # resolves pyproject deps (jax/flax wheels are not in nixpkgs at useful versions)
            pkgs.ruff
            pkgs.cmake
            pkgs.ninja
            pkgs.gcc13
            pkgs.openssl     # manager TLS (dlopen'd libssl3)
          ];

          env = {
            # same env contract as the serving bootstrap (serve.py:199 analog)
            MODEL_NAME = "PekingU/rtdetr_v2_r101vd";
            # keep uv on the nix-pinned interpreter
            UV_PYTHON = "${python}/bin/python3.12";
            UV_PYTHON_DOWNLOADS = "never";
          };

          shellHook = ''
            echo "spotter-tpu dev shell"
            echo "  fast suite : uv run --extra test pytest tests/          (-m 'not slow' is the default)"
            echo "  full suite : uv run --all-extras pytest tests/ -m 'not tpu'"
            echo "  manager    : cmake -S manager -B manager/build -G Ninja && cmake --build manager/build && ctest --test-dir manager/build"
          '';
        };
      });
}
