# Serving image for the spotter-tpu Ray Serve app on TPU node pools.
#
# Reference analog: apps/spotter/Dockerfile (ray base image, pip install,
# weight baking via the download script). TPU differences: jax[tpu] instead
# of cpu torch, and the baked artifact is the converted Flax param cache
# (torch is only present at build time for the conversion step).
FROM rayproject/ray:2.44.1-py312-cpu

ARG MODEL_NAME=PekingU/rtdetr_v2_r101vd
ENV MODEL_NAME=${MODEL_NAME}

WORKDIR /app
COPY pyproject.toml ./
COPY spotter_tpu ./spotter_tpu
COPY tools/golden_check.py ./tools/golden_check.py
COPY tests/test_data/test_pic.jpg ./tests/test_data/test_pic.jpg

# Cache path must be pinned BEFORE the bake step so build-time conversion and
# runtime load agree on it (the ray base image runs as user `ray`).
ENV SPOTTER_TPU_CACHE=/home/ray/.cache/spotter_tpu

# The golden_check step is the accuracy gate (reference test_serve.py:246-326
# runs in its CI): it reloads the just-baked Orbax cache, detects on the
# reference fixture, logs every box, and FAILS THE BUILD on >±1 px drift —
# a bad conversion can never ship. Runs on the build host's CPU backend
# (JAX_PLATFORMS=cpu: no TPU at image-build time).
RUN pip install --no-cache-dir "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir .[torch] \
    && spotter-tpu-download \
    && JAX_PLATFORMS=cpu python tools/golden_check.py \
    && pip uninstall -y torch transformers timm accelerate
EXPOSE 8000
