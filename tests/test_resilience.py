"""Unit tests for the resilience primitives (serving/resilience.py):
Deadline budgets, CircuitBreaker state machine (fake clock), env parsing."""

import pytest

from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.serving.resilience import (
    BACKOFF_JITTER_ENV,
    BREAKER_COOLDOWN_ENV,
    BREAKER_THRESHOLD_ENV,
    DEADLINE_ENV,
    CircuitBreaker,
    Deadline,
)


class FakeClock:
    def __init__(self) -> None:
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


def test_deadline_from_env_unset_is_none(monkeypatch):
    monkeypatch.delenv(DEADLINE_ENV, raising=False)
    assert Deadline.from_env() is None
    monkeypatch.setenv(DEADLINE_ENV, "0")
    assert Deadline.from_env() is None


def test_deadline_from_env_budget(monkeypatch):
    monkeypatch.setenv(DEADLINE_ENV, "250")
    dl = Deadline.from_env()
    assert dl is not None
    assert dl.budget_s == pytest.approx(0.25)
    assert not dl.expired()
    assert 0.0 < dl.remaining() <= 0.25


def test_deadline_expiry():
    dl = Deadline.after(-0.001)  # already past
    assert dl.expired()
    err = dl.exceeded("unit test")
    assert isinstance(err, TimeoutError)
    assert "unit test" in str(err)


def test_breaker_trips_after_threshold_and_half_opens():
    clock = FakeClock()
    metrics = Metrics()
    br = CircuitBreaker(threshold=3, cooldown_s=5.0, metrics=metrics, clock=clock)
    assert br.state == CircuitBreaker.CLOSED
    for _ in range(2):
        br.record_failure()
    assert br.state == CircuitBreaker.CLOSED and br.allow()
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()
    assert br.would_reject()

    # cooldown not elapsed: still shedding
    clock.now += 4.0
    assert not br.allow()

    # cooldown elapsed: exactly one probe admitted
    clock.now += 2.0
    assert not br.would_reject()  # pre-check must not block the probe
    assert br.allow()
    assert br.state == CircuitBreaker.HALF_OPEN
    assert not br.allow()  # second concurrent request is shed while probing

    # probe success closes; traffic flows again
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow()

    snap = metrics.snapshot()
    assert snap["breaker_state"] == "closed"
    # closed -> open -> half_open -> closed
    assert snap["breaker_transitions_total"] == 3


def test_breaker_failed_probe_reopens():
    clock = FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=clock)
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    clock.now += 6.0
    assert br.allow()  # probe
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()  # cooldown restarted
    clock.now += 6.0
    assert br.allow()
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(threshold=3, cooldown_s=5.0)
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == CircuitBreaker.CLOSED  # never 3 consecutive


def test_breaker_disabled_never_trips():
    br = CircuitBreaker(threshold=0)
    for _ in range(50):
        br.record_failure()
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow() and not br.would_reject()


def test_breaker_from_env(monkeypatch):
    monkeypatch.setenv(BREAKER_THRESHOLD_ENV, "7")
    monkeypatch.setenv(BREAKER_COOLDOWN_ENV, "2.5")
    br = CircuitBreaker.from_env()
    assert br.threshold == 7
    assert br.cooldown_s == 2.5


def test_breaker_retry_after_tracks_cooldown(monkeypatch):
    # jitter pinned off: this test asserts the exact cooldown arithmetic
    # (the +-25% jitter contract has its own seeded test in test_overload)
    monkeypatch.setenv(BACKOFF_JITTER_ENV, "0")
    clock = FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_s=10.0, clock=clock)
    br.record_failure()
    assert br.retry_after_s() == pytest.approx(10.0)
    clock.now += 6.0
    assert br.retry_after_s() == pytest.approx(4.0)


def test_breaker_retry_after_jitter_stays_in_band(monkeypatch):
    monkeypatch.delenv(BACKOFF_JITTER_ENV, raising=False)  # default: on
    clock = FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_s=10.0, clock=clock)
    br.record_failure()
    for _ in range(50):
        assert 7.5 <= br.retry_after_s() <= 12.5  # 10 s +- 25%
