"""Fleet-tier tests (ISSUE 6): request classing + SLO pinning, preemption
storms draining only the marked member, jittered respawn of dead members,
scale-to-zero + demand restore, the preempt_storm fault hook, and the fleet
HTTP surface. Most cases drive in-process scripted members (aiohttp
TestServer + a fake handle); the cross-process preemption-file propagation
test runs REAL supervised stub replicas via testing/cluster.py."""

import asyncio
import random
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from spotter_tpu.serving.fleet import (
    BULK,
    SLO,
    FleetController,
    PoolSpec,
    classify_request,
    make_fleet_app,
)
from spotter_tpu.testing import faults

PAYLOAD = {"image_urls": ["http://example.com/room.jpg"]}

FAST_POOL_KWARGS = dict(
    eject_threshold=1,
    backoff_base_s=0.1,
    backoff_max_s=0.5,
    health_interval_s=0.05,
)


class FakeMember:
    """In-process scripted replica + fleet member handle: /detect and
    /healthz with mutable behavior, plus the sync handle surface
    (alive/preempt/clear_preemption/shutdown) the controller drives."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.status = 200
        self.health_status = 200
        self.detect_calls = 0
        self._alive = True
        self.preempted = False
        self.clears = 0
        self.shutdowns = 0
        self.on_shutdown = None
        app = web.Application()
        app.router.add_post("/detect", self._detect)
        app.router.add_get("/healthz", self._healthz)
        self.server = TestServer(app)
        self.url = ""

    async def _detect(self, request: web.Request) -> web.Response:
        self.detect_calls += 1
        return web.json_response({"served_by": self.name}, status=self.status)

    async def _healthz(self, request: web.Request) -> web.Response:
        return web.json_response({}, status=self.health_status)

    async def start(self) -> str:
        await self.server.start_server()
        self.url = f"http://{self.server.host}:{self.server.port}"
        return self.url

    async def close(self) -> None:
        await self.server.close()

    # ---- MemberHandle surface ----

    def alive(self) -> bool:
        return self._alive

    def preempt(self) -> None:
        """Drain-like: readiness flips and /detect sheds, the shape a
        maintenance notice produces on a real replica."""
        self.preempted = True
        self.status = 503
        self.health_status = 503

    def revive(self) -> None:
        self.preempted = False
        self._alive = True
        self.status = 200
        self.health_status = 200

    def clear_preemption(self) -> None:
        self.clears += 1

    def shutdown(self, timeout_s: float = 10.0) -> str:
        self.shutdowns += 1
        self._alive = False
        self.status = 503
        self.health_status = 503
        if self.on_shutdown is not None:
            self.on_shutdown()
        return ""


async def _members(*names: str) -> list[FakeMember]:
    ms = [FakeMember(n) for n in names]
    for m in ms:
        await m.start()
    return ms


async def _wait(predicate, timeout_s: float = 5.0, interval_s: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(interval_s)
    raise TimeoutError("condition not met in time")


async def _start_fleet(od: list, spot: list, **kw) -> FleetController:
    specs = [
        PoolSpec("on_demand", handles=od),
        PoolSpec("spot", handles=spot),
    ]
    defaults = dict(tick_s=0.02, pool_kwargs=dict(FAST_POOL_KWARGS))
    defaults.update(kw)
    ctrl = FleetController(specs, **defaults)
    await ctrl.start()
    await _wait(lambda: all(
        fp.pool.has_available() for fp in ctrl.pools.values() if fp.members
    ))
    return ctrl


def test_classify_request_precedence_and_stripping():
    # header wins
    cls, payload = classify_request(
        {"X-Request-Class": "bulk"}, {"image_urls": [], "request_class": "slo"}
    )
    assert cls == "bulk"
    # routing metadata never reaches the detector
    assert "request_class" not in payload
    # payload key next
    assert classify_request(None, {"request_class": "bulk"})[0] == "bulk"
    # a deadline tag means latency-critical
    assert classify_request(None, {"deadline_ms": 50})[0] == "slo"
    # unclassified defaults conservative (slo)
    assert classify_request(None, {"image_urls": []})[0] == "slo"
    # explicit default honored
    assert classify_request(None, {}, default="bulk")[0] == "bulk"
    # garbage falls back to the default
    assert classify_request({"X-Request-Class": "weird"}, {})[0] == "slo"


def test_slo_pins_on_demand_bulk_drains_spot():
    async def run():
        od, s0, s1 = await _members("od0", "s0", "s1")
        ctrl = await _start_fleet([od], [s0, s1])
        for _ in range(6):
            assert (await ctrl.detect(PAYLOAD, SLO))["served_by"] == "od0"
        bulk_served = {
            (await ctrl.detect(PAYLOAD, BULK))["served_by"] for _ in range(6)
        }
        assert bulk_served <= {"s0", "s1"}
        assert od.detect_calls == 6  # bulk never touched the SLO pool
        snap = ctrl.snapshot()
        assert snap["requests_total"] == {SLO: 6, BULK: 6}
        assert snap["failures_total"] == {SLO: 0, BULK: 0}
        await ctrl.stop(shutdown_members=False)
        for m in (od, s0, s1):
            await m.close()

    asyncio.run(run())


def test_storm_drains_only_marked_member_slo_untouched():
    """The storm fault hook preempts ONE spot member; the other spot member
    keeps serving bulk throughout, and SLO traffic neither fails nor ever
    touches the spot pool."""

    async def run():
        od, s0, s1 = await _members("od0", "s0", "s1")
        ctrl = await _start_fleet([od], [s0, s1])
        with faults.inject(preempt_storm=1) as plan:
            await _wait(lambda: plan.preempt_storm == 0)
        victims = [m for m in (s0, s1) if m.preempted]
        assert len(victims) == 1, "storm must mark exactly one member"
        victim = victims[0]
        survivor = s1 if victim is s0 else s0
        # the controller observes the drain, counts the preemption, and
        # clears the maintenance source exactly once
        await _wait(lambda: ctrl.snapshot()["preemptions_total"] >= 1)
        await _wait(lambda: victim.clears == 1)
        # mid-storm: bulk lands on the survivor (replay is invisible), SLO
        # stays pinned and clean
        for _ in range(4):
            assert (await ctrl.detect(PAYLOAD, BULK))["served_by"] == survivor.name
        for _ in range(4):
            assert (await ctrl.detect(PAYLOAD, SLO))["served_by"] == "od0"
        snap = ctrl.snapshot()
        assert snap["failures_total"] == {SLO: 0, BULK: 0}
        assert snap["pool_size"]["spot"]["ready"] >= 1
        assert snap["storms_total"] == 1
        # recovery (the supervisor's job on a real member): spot refills
        victim.revive()
        await _wait(lambda: ctrl.snapshot()["pool_size"]["spot"]["ready"] == 2)
        await ctrl.stop(shutdown_members=False)
        for m in (od, s0, s1):
            await m.close()

    asyncio.run(run())


def test_dead_member_respawned_with_jittered_backoff():
    """A member whose SUPERVISOR process dies (not a preemption — the
    supervisor would absorb that) is retired and replaced by the spawner
    after a jittered backoff."""

    async def run():
        (m0,) = await _members("gen0")
        replacement = FakeMember("gen1")
        await replacement.start()
        stock = [replacement]

        def spawner():
            m = stock.pop(0)
            m.revive()
            return m

        specs = [
            PoolSpec("spot", handles=[m0], spawner=spawner, target_size=1),
        ]
        ctrl = FleetController(
            specs,
            tick_s=0.02,
            respawn_base_s=0.05,
            rng=random.Random(7),
            pool_kwargs=dict(FAST_POOL_KWARGS),
        )
        await ctrl.start()
        await _wait(lambda: ctrl.pools["spot"].pool.has_available())
        m0._alive = False  # the supervisor process is gone
        await _wait(lambda: ctrl.snapshot()["pools"]["spot"]["respawns_total"] == 1)
        await _wait(lambda: ctrl.pools["spot"].pool.has_available())
        assert (await ctrl.detect(PAYLOAD, BULK))["served_by"] == "gen1"
        assert not stock  # the spawner was actually used
        await ctrl.stop(shutdown_members=False)
        for m in (m0, replacement):
            await m.close()

    asyncio.run(run())


def test_scale_to_zero_and_demand_restore():
    async def run():
        (m0,) = await _members("z0")
        m0._alive = False  # not managed yet; spawner revives it
        stock = [m0]
        m0.on_shutdown = lambda: stock.append(m0)

        def spawner():
            m = stock.pop(0)
            m.revive()
            return m

        specs = [
            PoolSpec("spot", spawner=spawner, target_size=1,
                     scale_to_zero_s=0.25),
        ]
        ctrl = FleetController(
            specs,
            tick_s=0.02,
            restore_wait_s=5.0,
            pool_kwargs=dict(FAST_POOL_KWARGS),
        )
        await ctrl.start()
        assert (await ctrl.detect(PAYLOAD, BULK))["served_by"] == "z0"
        first_ttr = ctrl.pools["spot"].time_to_ready_s
        assert first_ttr is not None and first_ttr > 0
        # idle past the threshold: the pool drains to zero members
        await _wait(lambda: ctrl.snapshot()["pools"]["spot"]["scaled_to_zero"])
        assert m0.shutdowns == 1
        snap = ctrl.snapshot()
        assert snap["pools"]["spot"]["size"] == 0
        assert snap["pools"]["spot"]["scale_to_zero_total"] == 1
        # demand restore: the next bulk request wakes the pool and waits
        assert (await ctrl.detect(PAYLOAD, BULK))["served_by"] == "z0"
        snap = ctrl.snapshot()
        assert snap["pools"]["spot"]["restores_total"] == 1
        assert not snap["pools"]["spot"]["scaled_to_zero"]
        assert snap["time_to_ready_s"]["spot"] > 0
        await ctrl.stop(shutdown_members=False)
        await m0.close()

    asyncio.run(run())


def test_take_preempt_storm_consumes_whole_value():
    assert faults.take_preempt_storm() == 0  # no plan active
    with faults.inject(preempt_storm=2):
        assert faults.take_preempt_storm() == 2  # one correlated event
        assert faults.take_preempt_storm() == 0
    assert faults.take_preempt_storm() == 0


def test_fleet_app_routes_and_pool_gauges():
    from aiohttp.test_utils import TestClient

    async def run():
        od, s0 = await _members("od0", "s0")
        specs = [
            PoolSpec("on_demand", endpoints=[od.url]),
            PoolSpec("spot", endpoints=[s0.url]),
        ]
        ctrl = FleetController(
            specs, tick_s=0.02, pool_kwargs=dict(FAST_POOL_KWARGS)
        )
        app = make_fleet_app(ctrl)
        async with TestClient(TestServer(app)) as client:
            # header-classed bulk rides spot
            resp = await client.post(
                "/detect", json=PAYLOAD, headers={"X-Request-Class": "bulk"}
            )
            assert resp.status == 200
            assert (await resp.json())["served_by"] == "s0"
            # payload-classed slo pins on demand (and the key is stripped)
            resp = await client.post(
                "/detect", json={**PAYLOAD, "request_class": "slo"}
            )
            assert resp.status == 200
            assert (await resp.json())["served_by"] == "od0"

            health = await client.get("/healthz")
            assert health.status == 200
            body = await health.json()
            assert body["pools_available"] == {"on_demand": True, "spot": True}

            assert (await client.get("/livez")).status == 200

            metrics = await (await client.get("/metrics")).json()
            for key in (
                "pool_size",
                "preemptions_total",
                "replays_total",
                "retry_budget_exhausted_total",
                "requests_total",
                "time_to_ready_s",
            ):
                assert key in metrics
            assert set(metrics["pool_size"]) == {"on_demand", "spot"}
            assert set(metrics["pool_size"]["spot"]) == {
                "ready", "starting", "down", "dead",
            }
            assert metrics["requests_total"] == {"slo": 1, "bulk": 1}

            bad = await client.post("/detect", data=b"{nope")
            assert bad.status == 400
        for m in (od, s0):
            await m.close()

    asyncio.run(run())


def test_fleet_suspended_pool_answers_503_with_retry_after():
    """An SLO request against a fleet whose on_demand pool is entirely down
    must answer 503 + Retry-After fast — not burn the request deadline."""
    from aiohttp.test_utils import TestClient

    async def run():
        specs = [
            # an endpoint that exists but is health-marked down immediately
            PoolSpec("on_demand", endpoints=["http://127.0.0.1:1"]),
        ]
        ctrl = FleetController(
            specs,
            tick_s=0.02,
            unavailable_wait_s=0.2,
            pool_kwargs=dict(FAST_POOL_KWARGS),
        )
        app = make_fleet_app(ctrl)
        async with TestClient(TestServer(app)) as client:
            # let the health loop mark the dead endpoint down, then the
            # request path must fail fast (suspended), not ride the rounds
            fp = ctrl.pools["on_demand"]
            await _wait(lambda: not fp.pool.replicas[0].healthy, timeout_s=3.0)
            t0 = time.perf_counter()
            resp = await client.post("/detect", json=PAYLOAD)
            elapsed = time.perf_counter() - t0
            assert resp.status == 503
            assert "Retry-After" in resp.headers
            assert int(resp.headers["Retry-After"]) >= 1
            assert elapsed < 1.0

    asyncio.run(run())


# ---- cross-process: the PR 2 maintenance-file machinery through the fleet
# controller (ISSUE 6 satellite) ----


def test_preemption_file_drains_only_marked_member_cross_process(tmp_path):
    """REAL supervised stub replicas: a preemption storm (maintenance file
    via the storm hook) on one spot member drains ONLY that member — the
    other spot member serves bulk throughout, SLO traffic never fails and
    never touches spot, and the supervisor brings the victim back to ready
    so the spot pool refills on its own."""
    from spotter_tpu.testing import cluster

    async def run():
        ctrl = FleetController(
            [
                PoolSpec(
                    "on_demand",
                    spawner=cluster.fleet_spawner(str(tmp_path), "on_demand"),
                    target_size=1,
                ),
                PoolSpec(
                    "spot",
                    spawner=cluster.fleet_spawner(str(tmp_path), "spot"),
                    target_size=2,
                ),
            ],
            tick_s=0.05,
            pool_kwargs=dict(
                eject_threshold=1,
                backoff_base_s=0.2,
                health_interval_s=0.1,
                request_timeout_s=10.0,
            ),
        )
        await ctrl.start()
        await _wait(
            lambda: (
                ctrl.snapshot()["pool_size"]["on_demand"]["ready"] >= 1
                and ctrl.snapshot()["pool_size"]["spot"]["ready"] >= 2
            ),
            timeout_s=90.0,
            interval_s=0.2,
        )

        failures = {SLO: 0, BULK: 0}
        spot_always_had_capacity = {"ok": True}
        done = {"n": 0}

        async def one(cls):
            try:
                await ctrl.detect(PAYLOAD, cls)
            except Exception:
                failures[cls] += 1
            done["n"] += 1

        async def load():
            for _ in range(20):
                await asyncio.gather(one(SLO), one(BULK))

        async def storm():
            # land the storm mid-load
            while done["n"] < 8:
                await asyncio.sleep(0.02)
            with faults.inject(preempt_storm=1) as plan:
                while plan.preempt_storm > 0:
                    await asyncio.sleep(0.02)

        async def watch_spot():
            while done["n"] < 40:
                snap = ctrl.snapshot()
                if snap["pool_size"]["spot"]["ready"] < 1:
                    spot_always_had_capacity["ok"] = False
                await asyncio.sleep(0.05)

        await asyncio.gather(load(), storm(), watch_spot())

        # zero client-visible failures in EITHER class
        assert failures == {SLO: 0, BULK: 0}
        # only the marked member drained: bulk capacity never hit zero
        assert spot_always_had_capacity["ok"]
        snap = ctrl.snapshot()
        assert snap["preemptions_total"] >= 1
        assert snap["requests_total"] == {SLO: 20, BULK: 20}
        # the on_demand member served exactly the 20 SLO requests: no bulk
        # leaked onto it, and no SLO request ever needed a replay
        od_replicas = snap["pools"]["on_demand"]["pool"]["replicas"]
        assert sum(r["requests"] for r in od_replicas) == 20
        spot_requests = sum(
            r["requests"] for r in snap["pools"]["spot"]["pool"]["replicas"]
        )
        assert spot_requests >= 20  # all bulk + its replays stayed on spot
        # the supervisor restarts the drained member: spot refills to 2
        await _wait(
            lambda: ctrl.snapshot()["pool_size"]["spot"]["ready"] >= 2,
            timeout_s=60.0,
            interval_s=0.2,
        )
        await ctrl.stop()

    asyncio.run(run())
