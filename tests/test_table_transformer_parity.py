"""Numerical parity: Flax DetrDetector (pre_norm) vs HF torch
TableTransformerForObjectDetection.

Table-Transformer (microsoft/table-transformer-*) is served through the same
MODEL_NAME boundary as DETR (the reference accepts any
AutoModelForObjectDetection checkpoint, serve.py:199-205); architecturally it
is DETR with pre-norm layers and a closing encoder LayerNorm, which
DetrConfig.pre_norm selects. Tiny random-init config, no network.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import ResNetConfig as HFResNetConfig
from transformers import TableTransformerConfig
from transformers.models.table_transformer.modeling_table_transformer import (
    TableTransformerForObjectDetection,
)

from spotter_tpu.convert.detr_rules import detr_rules
from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.models.configs import DetrConfig
from spotter_tpu.models.detr import DetrDetector
from spotter_tpu.models.registry import MODEL_REGISTRY


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _tiny_hf_config():
    backbone = HFResNetConfig(
        embedding_size=8,
        hidden_sizes=[8, 12, 16, 24],
        depths=[1, 1, 1, 1],
        layer_type="basic",
        out_features=["stage4"],
    )
    return TableTransformerConfig(
        use_timm_backbone=False,
        use_pretrained_backbone=False,
        backbone_config=backbone,
        d_model=32,
        encoder_layers=2,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        num_queries=9,
        num_labels=3,
    )


def test_table_transformer_parity():
    hf_cfg = _tiny_hf_config()
    torch.manual_seed(0)
    model = TableTransformerForObjectDetection(hf_cfg).eval()
    with torch.no_grad():
        for m in model.modules():
            if hasattr(m, "running_mean"):
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.8, 1.2)

    cfg = DetrConfig.from_hf(hf_cfg)
    assert cfg.pre_norm  # model_type discriminates the pre-norm variant
    params = convert_state_dict(model.state_dict(), detr_rules(cfg), strict=True)

    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, size=(2, 3, 64, 96)).astype(np.float32)
    mask = np.zeros((2, 64, 96), dtype=np.int64)
    mask[0, :64, :80] = 1
    mask[1, :48, :96] = 1

    with torch.no_grad():
        tout = model(torch.from_numpy(x), pixel_mask=torch.from_numpy(mask))

    jout = DetrDetector(cfg).apply(
        {"params": params},
        np.transpose(x, (0, 2, 3, 1)),
        mask.astype(np.float32),
    )

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=5e-4, rtol=1e-3
    )


def test_timm_resnet18_backbone_mapping():
    """Real table-transformer checkpoints ship use_timm_backbone=true with
    backbone='resnet18' (basic blocks) — the from_hf mapping must produce
    the basic-block architecture, not the bottleneck default. (Loading the
    torch side needs the timm package, present in the serving image per the
    reference's deps, so only the config mapping is pinned here.)"""
    # published checkpoints' config.json: use_timm_backbone with resnet18
    # (the transformers class default is resnet50)
    hf = TableTransformerConfig(num_labels=3, backbone="resnet18")
    assert hf.use_timm_backbone and hf.backbone == "resnet18"
    cfg = DetrConfig.from_hf(hf)
    assert cfg.pre_norm
    assert cfg.backbone.layer_type == "basic"
    assert cfg.backbone.depths == (2, 2, 2, 2)
    assert cfg.backbone.hidden_sizes == (64, 128, 256, 512)
    assert cfg.backbone.style == "v1"


def test_registry_routes_table_transformer():
    from spotter_tpu.models import zoo  # noqa: F401  (self-registers families)

    fam = next(
        f
        for f in MODEL_REGISTRY.values()
        if any("table-transformer" in m for m in f.matches)
    )
    assert fam.name == "detr"
    assert any(
        m in "microsoft/table-transformer-detection" for m in fam.matches
    )
