"""Model-multiplexed autoscaling tests (ISSUE 20): registry ambiguous-name
resolution, model routing precedence + structured 400s, the AutoscalerBrain
policy loop (deterministic via injectable clock and direct step() calls),
the fleet-app integration (routing + the `autoscale` /metrics block), and
the SCALE_MATRIX chaos rows. The cross-process drills (controller crash
mid-scale, scale-to-zero over real supervised stub replicas) are marked
slow."""

import asyncio
import time
from types import SimpleNamespace

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from spotter_tpu.models.registry import family_for, match_score
from spotter_tpu.obs.aggregate import FleetAggregator
from spotter_tpu.serving.autoscale import (
    MODEL_HEADER,
    AutoscalerBrain,
    ClosedSetQueriesError,
    ModelPool,
    UnknownModelError,
    model_pools_from_registry,
    pool_shape,
)
from spotter_tpu.serving.fleet import (
    FleetController,
    PoolSpec,
    make_fleet_app,
)

PAYLOAD = {"image_urls": ["http://example.com/room.jpg"]}

FAST_POOL_KWARGS = dict(
    eject_threshold=1,
    backoff_base_s=0.1,
    backoff_max_s=0.5,
    health_interval_s=0.05,
)


# ---- satellite: registry ambiguous-name resolution ----


def test_match_score_earliest_start_then_longest():
    # earliest start wins even against a longer match further in
    assert match_score("dab-detr-resnet-50", ("dab-detr",)) == (0, -8)
    assert match_score("dab-detr-resnet-50", ("detr-resnet",)) == (4, -11)
    assert (0, -8) < (4, -11)
    # same start: longer match wins (smaller negated length)
    assert match_score("rtdetr_v2_r50", ("rtdetr", "rt")) == (0, -6)
    # absent pattern scores None
    assert match_score("yolos-small", ("detr",)) is None


def test_family_for_ambiguous_names_deterministic():
    """The PR 20 bugfix: family resolution must not depend on registration
    order. Prefixed DETR variants resolve to THEIR family even though the
    plain detr patterns ("detr-resnet") also appear inside the name."""
    cases = {
        "dab-detr-resnet-50": "dab_detr",
        "conditional-detr-resnet-50": "conditional_detr",
        "SenseTime/deformable-detr": "deformable_detr",
        "detr-resnet-50": "detr",
        "facebook/detr_resnet_101": "detr",
        "table-transformer-detection": "detr",
        "rtdetr_r50vd": "rtdetr",
        "PekingU/rtdetr_v2_r18vd": "rtdetr",
        "owlvit-base-patch32": "owlvit",
        "hustvl/yolos-small": "yolos",
    }
    for name, want in cases.items():
        assert family_for(name).name == want, name
    with pytest.raises(ValueError):
        family_for("segment-anything-vit-h")


# ---- routing (no fleet needed: a stub controller satisfies the brain) ----


def _stub_controller(pool_names):
    return SimpleNamespace(
        pools={
            n: SimpleNamespace(
                spec=SimpleNamespace(spawner=None, target_size=1),
                scaled_to_zero=False,
                members=[object()],
            )
            for n in pool_names
        }
    )


def _routing_brain():
    pools = [
        ModelPool(model="rtdetr", matches=("rtdetr",), default=True),
        ModelPool(model="dab_detr", matches=("dab-detr", "dab_detr")),
        ModelPool(model="detr", matches=("detr-resnet", "detr_resnet")),
        ModelPool(model="owlvit", matches=("owlvit",), open_vocab=True),
    ]
    return AutoscalerBrain(
        _stub_controller([p.name for p in pools]), pools, clock=lambda: 0.0
    )


def test_route_precedence_header_payload_queries_default():
    brain = _routing_brain()
    # no hints -> default pool
    assert brain.route(None, dict(PAYLOAD))[0] == "rtdetr"
    # payload `model` key routes and is STRIPPED before forwarding
    name, fwd = brain.route(None, {**PAYLOAD, "model": "dab-detr-resnet-50"})
    assert name == "dab_detr"
    assert "model" not in fwd and fwd["image_urls"] == PAYLOAD["image_urls"]
    # header beats payload
    name, _ = brain.route(
        {MODEL_HEADER: "owlvit-base-patch32"}, {**PAYLOAD, "model": "rtdetr"}
    )
    assert name == "owlvit"
    # bare `queries` -> the open-vocab pool
    name, fwd = brain.route(None, {**PAYLOAD, "queries": ["a cat"]})
    assert name == "owlvit" and fwd["queries"] == ["a cat"]
    # ambiguous name resolves like the registry (earliest-start-then-longest)
    assert brain.route(None, {"model": "dab-detr-resnet-50"})[0] == "dab_detr"
    assert brain.route(None, {"model": "detr-resnet-50"})[0] == "detr"


def test_route_unknown_model_is_structured_400():
    brain = _routing_brain()
    with pytest.raises(UnknownModelError) as ei:
        brain.route(None, {**PAYLOAD, "model": "segment-anything"})
    exc = ei.value
    assert exc.status == 400 and exc.kind == "unknown_model"
    assert set(exc.families) == {"rtdetr", "dab_detr", "detr", "owlvit"}
    assert brain.routing_rejections_total == 1


def test_route_queries_against_closed_set():
    brain = _routing_brain()
    # a named closed-set model cannot take open-vocab queries
    with pytest.raises(ClosedSetQueriesError):
        brain.route(None, {**PAYLOAD, "model": "rtdetr", "queries": ["cat"]})
    # a named open-vocab model can
    assert (
        brain.route(
            None, {**PAYLOAD, "model": "owlvit-base", "queries": ["cat"]}
        )[0]
        == "owlvit"
    )
    # a fleet with no open-vocab pool rejects bare queries
    closed = AutoscalerBrain(
        _stub_controller(["rtdetr"]),
        [ModelPool(model="rtdetr", default=True)],
        clock=lambda: 0.0,
    )
    with pytest.raises(ClosedSetQueriesError) as ei:
        closed.route(None, {**PAYLOAD, "queries": ["cat"]})
    assert ei.value.kind == "closed_set_queries"


def test_model_pools_from_registry_covers_the_zoo():
    pools = model_pools_from_registry()
    by_name = {p.model: p for p in pools}
    assert set(by_name) == {
        "conditional_detr", "dab_detr", "deformable_detr", "rtdetr",
        "owlvit", "yolos", "detr",
    }
    assert by_name["owlvit"].open_vocab
    # big models shard tp, small models pack dp (ISSUE 20d)
    assert (by_name["owlvit"].tp, by_name["owlvit"].dp) == pool_shape("owlvit")
    assert by_name["owlvit"].tp > 1
    assert by_name["yolos"].dp > 1
    assert by_name["rtdetr"].default
    assert sum(1 for p in pools if p.default) == 1


# ---- the policy loop, deterministically ----


class _Member:
    """Minimal in-process managed member (aiohttp server + sync handle)."""

    def __init__(self, name: str, pool: str) -> None:
        self.name = name
        self.pool = pool
        self.serving = False
        self.last_payload = None
        app = web.Application()
        app.router.add_post("/detect", self._detect)
        app.router.add_get("/healthz", self._healthz)
        self.server = TestServer(app)
        self.url = ""

    async def _detect(self, request: web.Request) -> web.Response:
        self.last_payload = await request.json()
        if not self.serving:
            return web.json_response({}, status=503)
        return web.json_response({"served_by": self.name, "pool": self.pool})

    async def _healthz(self, request: web.Request) -> web.Response:
        return web.json_response({}, status=200 if self.serving else 503)

    async def start(self) -> None:
        await self.server.start_server()
        self.url = f"http://{self.server.host}:{self.server.port}"

    async def close(self) -> None:
        await self.server.close()

    # MemberHandle surface
    def alive(self) -> bool:
        return True

    def preempt(self) -> None:
        self.serving = False

    def clear_preemption(self) -> None:
        pass

    def shutdown(self, timeout_s: float = 10.0) -> str:
        self.serving = False
        return ""


class _RecordingStore:
    def __init__(self) -> None:
        self.pools: dict = {}
        self.calls: list = []

    def set_pool(self, name: str, **spec) -> None:
        self.calls.append((name, dict(spec)))
        self.pools.setdefault(name, {}).update(spec)


async def _brain_fleet(pool_cfgs, **brain_kw):
    """(controller, brain, members): per-model pools of _Member stock."""
    members = []
    specs = []
    model_pools = []
    for cfg in pool_cfgs:
        stock = []
        for i in range(cfg.get("stock", 2)):
            m = _Member(f"{cfg['model']}-m{i}", cfg["model"])
            await m.start()
            stock.append(m)
            members.append(m)

        def spawner(stock=stock):
            for m in stock:
                if not m.serving:
                    m.serving = True
                    return m
            raise RuntimeError("stock exhausted")

        specs.append(
            PoolSpec(
                cfg["model"], spawner=spawner,
                target_size=cfg.get("initial", 1),
                scale_to_zero_s=cfg.get("scale_to_zero_s"),
            )
        )
        model_pools.append(
            ModelPool(
                model=cfg["model"],
                matches=tuple(cfg.get("matches", ())),
                open_vocab=cfg.get("open_vocab", False),
                min_size=cfg.get("min", 0),
                max_size=cfg.get("max", 2),
                default=cfg.get("default", False),
            )
        )
    controller = FleetController(
        [s for s in specs], tick_s=0.02, restore_wait_s=5.0,
        pool_kwargs=dict(FAST_POOL_KWARGS),
    )
    brain = AutoscalerBrain(controller, model_pools, **brain_kw)
    return controller, brain, members


async def _wait(predicate, timeout_s: float = 5.0, interval_s: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(interval_s)
    raise TimeoutError("condition not met in time")


def test_step_scales_up_on_edge_inflight():
    async def run():
        store = _RecordingStore()
        ctrl, brain, members = await _brain_fleet(
            [{"model": "rtdetr", "default": True, "min": 1, "max": 2}],
            store=store, inflight_high=2.0, clock=lambda: 0.0,
        )
        await ctrl.start()
        await _wait(lambda: ctrl.pools["rtdetr"].pool.has_available())
        brain.route(None, dict(PAYLOAD))
        t1, t2 = brain.track("rtdetr"), brain.track("rtdetr")
        applied = await brain.step()
        assert [d.reason for d in applied] == ["up: inflight 2"]
        assert ctrl.pools["rtdetr"].spec.target_size == 2
        assert brain.scale_ups_total == 1
        # journal carries intent + shape BEFORE the spawn landed
        assert store.pools["rtdetr"]["size"] == 2
        assert store.pools["rtdetr"]["tp"] == 1
        # capped at max_size: another overloaded round does not grow past it
        applied = await brain.step()
        assert ctrl.pools["rtdetr"].spec.target_size == 2
        t1.done(200), t2.done(200)
        # done() is idempotent and classifies outcomes
        t1.done(500)
        st = brain._pool_state["rtdetr"]
        assert (st["ok_total"], st["fail_total"], st["inflight"]) == (2, 0, 0)
        await ctrl.stop(shutdown_members=False)
        for m in members:
            await m.close()

    asyncio.run(run())


def test_step_scales_down_after_consecutive_idle_rounds():
    async def run():
        ctrl, brain, members = await _brain_fleet(
            [{"model": "rtdetr", "default": True, "initial": 2, "max": 2}],
            down_steps=2, clock=lambda: 0.0,
        )
        await ctrl.start()
        await _wait(
            lambda: len(ctrl.pools["rtdetr"].members) == 2
            and ctrl.pools["rtdetr"].pool.has_available()
        )
        assert await brain.step() == []  # idle round 1: streak, no action
        applied = await brain.step()    # idle round 2: step down
        assert [d.desired for d in applied] == [1]
        assert brain.scale_downs_total == 1
        await _wait(lambda: len(ctrl.pools["rtdetr"].members) == 1)
        # demand resets the streak: no further step-down
        brain.route(None, dict(PAYLOAD))
        assert await brain.step() == []
        assert await brain.step() == []
        assert ctrl.pools["rtdetr"].spec.target_size == 1
        await ctrl.stop(shutdown_members=False)
        for m in members:
            await m.close()

    asyncio.run(run())


def test_step_holds_during_flood_instead_of_scaling():
    """Rising tenant sheds with zero admitted demand must never scale a
    pool — the brain records an explicit hold."""

    class _RisingSheds:
        def __init__(self) -> None:
            self.total = 0.0

        def metrics_view(self):
            self.total += 100.0
            return {
                "abuser": {
                    "sheds_rate_total": self.total,
                    "sheds_inflight_total": 0.0,
                }
            }

    async def run():
        ctrl, brain, members = await _brain_fleet(
            [{"model": "rtdetr", "default": True, "min": 1, "max": 2}],
            tenancy_plane=_RisingSheds(), clock=lambda: 0.0,
        )
        await ctrl.start()
        await _wait(lambda: ctrl.pools["rtdetr"].pool.has_available())
        await brain.step()  # baseline shed observation
        assert await brain.step() == []
        assert brain.flood_suppressions_total >= 1
        assert brain.scale_ups_total == 0
        assert ctrl.pools["rtdetr"].spec.target_size == 1
        await ctrl.stop(shutdown_members=False)
        for m in members:
            await m.close()

    asyncio.run(run())


def test_route_wakes_scaled_to_zero_pool():
    async def run():
        store = _RecordingStore()
        ctrl, brain, members = await _brain_fleet(
            [
                {"model": "rtdetr", "default": True, "min": 1},
                {"model": "owlvit", "open_vocab": True, "initial": 0},
            ],
            store=store, clock=lambda: 0.0,
        )
        await ctrl.start()
        assert ctrl.pools["owlvit"].spec.target_size == 0
        name, _ = brain.route(None, {**PAYLOAD, "queries": ["cat"]})
        assert name == "owlvit"
        assert brain.wakes_total == 1
        assert ctrl.pools["owlvit"].spec.target_size == 1
        assert store.pools["owlvit"]["size"] == 1  # journaled intent
        await _wait(lambda: ctrl.pools["owlvit"].pool.has_available())
        await ctrl.stop(shutdown_members=False)
        for m in members:
            await m.close()

    asyncio.run(run())


def test_actuation_is_fenced_journal_first():
    """A deposed controller's actuation dies at the fence BEFORE any
    journal write or target change."""

    class _Fence:
        def __init__(self) -> None:
            self.raises = False
            self.calls = 0

        def __call__(self):
            self.calls += 1
            if self.raises:
                raise RuntimeError("stale leader")
            return 1

    async def run():
        store = _RecordingStore()
        fence = _Fence()
        ctrl, brain, members = await _brain_fleet(
            [{"model": "rtdetr", "default": True, "max": 3}],
            store=store, fence=fence, clock=lambda: 0.0,
        )
        await ctrl.start()
        await _wait(lambda: ctrl.pools["rtdetr"].pool.has_available())
        brain.actuate("rtdetr", 2, "drill")
        assert fence.calls == 1
        assert store.pools["rtdetr"]["size"] == 2
        await _wait(lambda: len(ctrl.pools["rtdetr"].members) == 2)
        fence.raises = True
        with pytest.raises(RuntimeError):
            brain.actuate("rtdetr", 3, "drill")
        # fenced out BEFORE journal and target mutation
        assert store.pools["rtdetr"]["size"] == 2
        assert ctrl.pools["rtdetr"].spec.target_size == 2
        await ctrl.stop(shutdown_members=False)
        for m in members:
            await m.close()

    asyncio.run(run())


def test_chips_desired_accounts_pool_shape():
    pools = [
        ModelPool(model="owlvit", tp=2, dp=1, default=True),   # 2 chips/member
        ModelPool(model="yolos", tp=1, dp=2),                  # 2 chips/member
    ]
    ctrl = _stub_controller(["owlvit", "yolos"])
    ctrl.pools["owlvit"].spec.target_size = 2
    ctrl.pools["yolos"].spec.target_size = 1
    brain = AutoscalerBrain(ctrl, pools, clock=lambda: 0.0)
    assert brain.chips_desired() == 2 * 2 + 1 * 2


# ---- the fleet edge end to end (in-process) ----


def test_fleet_app_model_routing_and_metrics_block():
    async def run():
        ctrl, brain, members = await _brain_fleet(
            [
                {"model": "rtdetr", "matches": ("rtdetr",), "default": True,
                 "min": 1},
                {"model": "owlvit", "matches": ("owlvit",),
                 "open_vocab": True, "min": 1},
            ],
            clock=time.monotonic,
        )
        app = make_fleet_app(
            ctrl,
            aggregator=FleetAggregator(lambda: [], interval_s=0.0),
            autoscaler=brain,
        )
        async with TestClient(TestServer(app)) as client:
            await _wait(
                lambda: all(
                    fp.pool.has_available() for fp in ctrl.pools.values()
                )
            )
            # payload model key routes to the named pool and is stripped
            resp = await client.post(
                "/detect", json={**PAYLOAD, "model": "rtdetr_r50vd"}
            )
            assert resp.status == 200
            assert (await resp.json())["pool"] == "rtdetr"
            served = next(m for m in members if m.last_payload is not None)
            assert "model" not in served.last_payload
            # header routing to the open-vocab pool
            resp = await client.post(
                "/detect", json=dict(PAYLOAD),
                headers={MODEL_HEADER: "owlvit-base-patch32"},
            )
            assert resp.status == 200
            assert (await resp.json())["pool"] == "owlvit"
            # queries land open-vocab without naming a model
            resp = await client.post(
                "/detect", json={**PAYLOAD, "queries": ["a cat"]}
            )
            assert (await resp.json())["pool"] == "owlvit"
            # unknown model: structured 400 naming the registry, no
            # Retry-After (client defect, not load)
            resp = await client.post(
                "/detect", json={**PAYLOAD, "model": "segment-anything"}
            )
            assert resp.status == 400
            body = await resp.json()
            assert body["status"] == 400
            assert body["kind"] == "unknown_model"
            assert set(body["families"]) == {"rtdetr", "owlvit"}
            assert "Retry-After" not in resp.headers
            # /metrics grows the autoscale block
            snap = await (await client.get("/metrics")).json()
            auto = snap["autoscale"]
            assert auto["default_pool"] == "rtdetr"
            assert auto["open_vocab_pool"] == "owlvit"
            assert auto["routing_rejections_total"] == 1
            assert auto["pools"]["rtdetr"]["admits_total"] == 1
            assert auto["pools"]["rtdetr"]["desired"] == 1
            assert auto["pools"]["owlvit"]["admits_total"] == 2
        for m in members:
            await m.close()

    asyncio.run(run())


# ---- the chaos rows ----


def _scale_row(name):
    from spotter_tpu.testing.chaos_matrix import SCALE_MATRIX

    return next(sc for sc in SCALE_MATRIX if sc.name == name)


@pytest.mark.parametrize(
    "row", ["burst-to-cold-model", "idle-reclaim", "flood-vs-in-quota-demand"]
)
def test_scale_matrix_fast_rows(row):
    from spotter_tpu.testing.chaos_matrix import run_scale_scenario

    report = asyncio.run(run_scale_scenario(_scale_row(row)))
    assert report["ok"], report["checks"]


def test_evaluate_scale_rejects_unknown_invariant():
    from spotter_tpu.testing.chaos_matrix import ScaleScenario, evaluate_scale

    sc = ScaleScenario(name="x", invariants={"not_a_real_invariant": 1})
    with pytest.raises(ValueError, match="not_a_real_invariant"):
        evaluate_scale(sc, {"client_failures": 0})


@pytest.mark.slow
def test_scale_matrix_controller_crash_mid_scale(tmp_path):
    """kill -9 against a REAL controller mid-scale-up: the successor adopts
    every live supervised member and converges to the JOURNALED size with
    zero double-spawns."""
    from spotter_tpu.testing.chaos_matrix import run_scale_crash_scenario

    report = run_scale_crash_scenario(
        _scale_row("controller-crash-mid-scale"), str(tmp_path)
    )
    assert report["ok"], report


# ---- satellite: scale-to-zero -> cold restore over REAL supervised
# replicas, timed through /metrics ----


@pytest.mark.slow
def test_scale_to_zero_cold_restore_cross_process(tmp_path, monkeypatch):
    """A real supervised stub pool idles past SPOTTER_TPU_SCALE_TO_ZERO_S
    and is reclaimed; the next routed request restores it through the
    persistent compile cache path and /metrics reports time_to_ready_s
    under 15 s with zero client-visible failures."""
    from spotter_tpu.testing import cluster

    monkeypatch.setenv("SPOTTER_TPU_SCALE_TO_ZERO_S", "1.0")

    async def run():
        ctrl = FleetController(
            [
                PoolSpec(
                    "rtdetr",
                    spawner=cluster.fleet_spawner(str(tmp_path), "rtdetr"),
                    target_size=1,
                    # scale_to_zero_s unset: the env knob drives it
                ),
            ],
            tick_s=0.05,
            restore_wait_s=60.0,
            pool_kwargs=dict(
                eject_threshold=1,
                backoff_base_s=0.2,
                health_interval_s=0.1,
                request_timeout_s=10.0,
            ),
        )
        brain = AutoscalerBrain(
            ctrl,
            [ModelPool(model="rtdetr", matches=("rtdetr",), default=True,
                       min_size=1)],
            tick_s=0.1,
        )
        app = make_fleet_app(
            ctrl,
            aggregator=FleetAggregator(lambda: [], interval_s=0.0),
            autoscaler=brain,
        )
        async with TestClient(TestServer(app)) as client:
            fp = ctrl.pools["rtdetr"]
            assert fp.scale_to_zero_s == 1.0  # env knob wired through
            await _wait(
                lambda: fp.pool.has_available(), timeout_s=90.0,
                interval_s=0.2,
            )
            resp = await client.post("/detect", json=dict(PAYLOAD))
            assert resp.status == 200
            # idle past the knob: the supervised member is reclaimed
            await _wait(
                lambda: fp.scaled_to_zero, timeout_s=30.0, interval_s=0.2
            )
            snap = await (await client.get("/metrics")).json()
            assert snap["autoscale"]["pools"]["rtdetr"]["scaled_to_zero"]
            assert snap["autoscale"]["pools"]["rtdetr"]["size"] == 0
            # the next request wakes + restores through the compile cache
            t0 = time.monotonic()
            resp = await client.post("/detect", json=dict(PAYLOAD))
            assert resp.status == 200, await resp.text()
            restore_wall_s = time.monotonic() - t0
            await _wait(
                lambda: not fp.restoring, timeout_s=10.0, interval_s=0.1
            )
            snap = await (await client.get("/metrics")).json()
            auto = snap["autoscale"]["pools"]["rtdetr"]
            assert auto["restores_total"] == 1
            assert not auto["scaled_to_zero"]
            assert auto["time_to_ready_s"] is not None
            assert auto["time_to_ready_s"] < 15.0, auto
            assert restore_wall_s < 60.0
            assert auto["fail_total"] == 0
        await ctrl.stop(shutdown_members=True)

    asyncio.run(run())
