"""Rendezvous-ring + key-normalization contract (ISSUE 11).

The properties that make cache-affinity routing safe to turn on by
default: placement is deterministic across processes, membership churn
moves only ~1/N of the key space, an ejected owner falls to the
deterministic next-highest-weight holder, and the edge's affinity key can
never drift from the replica's cache URL key because both come from
caching/keys.py.
"""

from collections import Counter

from spotter_tpu.caching import keys
from spotter_tpu.caching import result_cache
from spotter_tpu.serving.ring import RendezvousRing

MEMBERS = [f"http://127.0.0.1:80{i:02d}" for i in range(4)]
KEYS = [f"http://cdn.example.com/listing-{i}/photo.jpg" for i in range(1000)]


def test_deterministic_placement_across_instances():
    a = RendezvousRing(MEMBERS)
    b = RendezvousRing(list(reversed(MEMBERS)))  # discovery order must not matter
    for k in KEYS[:100]:
        assert a.owner(k) == b.owner(k)
        assert a.ranked(k) == b.ranked(k)
        # ranked is a permutation of the membership with the owner first
        assert sorted(a.ranked(k)) == sorted(MEMBERS)
        assert a.ranked(k)[0] == a.owner(k)


def test_balanced_distribution():
    ring = RendezvousRing(MEMBERS)
    counts = Counter(ring.owner(k) for k in KEYS)
    assert set(counts) == set(MEMBERS)
    for member, n in counts.items():
        # 1000 keys over 4 members: expect ~250 each; generous slack keeps
        # the test hash-stable while still catching gross imbalance
        assert 150 <= n <= 350, f"{member} owns {n}/1000 keys"


def test_member_join_moves_about_one_in_n_keys():
    before = {k: RendezvousRing(MEMBERS).owner(k) for k in KEYS}
    grown = RendezvousRing(MEMBERS + ["http://127.0.0.1:8099"])
    moved = 0
    for k in KEYS:
        now = grown.owner(k)
        if now != before[k]:
            moved += 1
            # HRW invariant: a key only ever moves TO the new member —
            # every other key keeps its exact placement (warm caches
            # survive the scale-out)
            assert now == "http://127.0.0.1:8099"
    # expected 1/5 = 200 of 1000, with slack for hash variance
    assert 120 <= moved <= 280, f"join moved {moved}/1000 keys"


def test_member_leave_moves_only_its_keys():
    full = RendezvousRing(MEMBERS)
    before = {k: full.owner(k) for k in KEYS}
    shrunk = RendezvousRing(MEMBERS[:-1])
    for k in KEYS:
        if before[k] == MEMBERS[-1]:
            # orphaned keys land on the key's next-ranked survivor
            assert shrunk.owner(k) == full.ranked(k)[1]
        else:
            assert shrunk.owner(k) == before[k]


def test_ejected_owner_falls_to_next_highest_weight():
    ring = RendezvousRing(MEMBERS)
    k = KEYS[0]
    ranked = ring.ranked(k)
    # the failover plan is the weight ordering itself: skipping the dead
    # owner yields the same replica every router instance would pick
    available = [m for m in ranked if m != ranked[0]]
    assert available[0] == ranked[1]
    # draining the top TWO holders still yields a deterministic third
    assert [m for m in ranked if m not in ranked[:2]][0] == ranked[2]


def test_affinity_key_equals_replica_cache_url_key():
    """The drift pin: the edge hashes `affinity_key(url)`, the replica
    stores negative verdicts under `url_key(url)`; both MUST be the same
    normalization with only the namespace prefix differing."""
    for url in (
        "http://cdn.example.com/a.jpg",
        "  http://cdn.example.com/a.jpg \n",
        "https://CDN.example.com/Path%20/x.jpg?w=1",
    ):
        assert keys.url_key(url) == "url|" + keys.affinity_key(url)
    # and the result cache re-exports THE SAME functions, not copies —
    # a future edit cannot fork the derivation
    assert result_cache.url_key is keys.url_key
    assert result_cache.content_key is keys.content_key


def test_empty_and_single_member_rings():
    assert RendezvousRing([]).owner("k") is None
    assert RendezvousRing([]).ranked("k") == []
    solo = RendezvousRing(["http://only"])
    assert solo.owner("k") == "http://only"
    assert solo.ranked("k") == ["http://only"]
